"""Benchmark: CRDT messages merged/sec/chip (BASELINE.json metric).

Measures the device merge pipeline that replaces the reference's
per-message applyMessages loop (SURVEY.md §2.3): batched LWW planning
(sort + segmented scans) + per-(owner, minute) Merkle XOR deltas +
batch digest, on a 1M-message batch spread over 1k owners with cell
contention (the config-3 shape). Inputs are device-resident columnar
arrays — the framework's device cell-version-cache design keeps them
there between batches (SURVEY.md §7, "hard parts" #4).

North star (BASELINE.json): ≥50M msgs/sec on v5e-4 = 12.5M/sec/chip;
`vs_baseline` reports the fraction of that per-chip target.

Prints exactly one JSON line.
"""

import json
import statistics
import sys
import time

import jax

jax.config.update("jax_enable_x64", True)

import numpy as np

N = 1_000_000
OWNERS = 1_000
TARGET_PER_CHIP = 12_500_000.0


def build_columns(n=N, owners=OWNERS, seed=7):
    rng = np.random.default_rng(seed)
    base = 1_700_000_000_000
    # ~4 messages/cell contention, clustered minutes (realistic sync bursts).
    cells = max(n // 4, 1)
    cell_id = rng.integers(0, cells, n).astype(np.int32)
    owner_of_cell = rng.integers(0, owners, cells).astype(np.int64)
    owner_ix = owner_of_cell[cell_id]
    millis = base + rng.integers(0, 86_400_000, n).astype(np.int64)
    counter = rng.integers(0, 256, n).astype(np.int32)
    node = rng.integers(1, 2**63, n).astype(np.uint64)
    k1 = (millis.astype(np.uint64) << np.uint64(16)) | counter.astype(np.uint64)
    return {
        "cell_id": cell_id,
        "k1": k1,
        "k2": node,
        "ex_k1": np.zeros(n, np.uint64),
        "ex_k2": np.zeros(n, np.uint64),
        "millis": millis,
        "counter": counter,
        "node": node,
        "owner_ix": owner_ix,
    }


def main():
    from evolu_tpu.parallel.mesh import create_mesh, sharding
    from evolu_tpu.parallel.reconcile import _compiled_kernel

    mesh = create_mesh()  # all local devices (1 chip under axon)
    n_dev = mesh.devices.size
    cols = build_columns()
    # Owners must not span shards: remap owner→shard-major layout.
    order = np.argsort(cols["owner_ix"] % n_dev, kind="stable")
    cols = {k: v[order] for k, v in cols.items()}

    shd = sharding(mesh)
    names = ("cell_id", "k1", "k2", "ex_k1", "ex_k2", "millis", "counter", "node", "owner_ix")
    args = [jax.device_put(cols[k], shd) for k in names]
    kernel = _compiled_kernel(mesh)

    jax.block_until_ready(kernel(*args))  # compile + warm
    times = []
    for _ in range(10):
        t0 = time.perf_counter()
        jax.block_until_ready(kernel(*args))
        times.append(time.perf_counter() - t0)
    p50 = statistics.median(times)
    per_chip = N / p50 / n_dev
    print(
        json.dumps(
            {
                "metric": "crdt_messages_merged_per_sec_per_chip",
                "value": round(per_chip),
                "unit": "msgs/sec/chip",
                "vs_baseline": round(per_chip / TARGET_PER_CHIP, 4),
                "detail": {
                    "batch": N,
                    "owners": OWNERS,
                    "devices": n_dev,
                    "p50_ms": round(p50 * 1e3, 3),
                    "platform": jax.devices()[0].platform,
                },
            }
        )
    )


if __name__ == "__main__":
    sys.path.insert(0, ".")
    main()
