"""Benchmark: CRDT messages merged/sec/chip (BASELINE.json metric).

Measures the device merge pipeline that replaces the reference's
per-message applyMessages loop (SURVEY.md §2.3): batched LWW planning
(sort + segmented scans) + per-(owner, minute) Merkle XOR deltas +
batch digest, on a 1M-message batch spread over 1k owners with cell
contention (the config-3 shape). Inputs are device-resident columnar
arrays — the framework's device cell-version-cache design keeps them
there between batches (SURVEY.md §7, "hard parts" #4).

North star (BASELINE.json): ≥50M msgs/sec on v5e-4 = 12.5M/sec/chip;
`vs_baseline` reports the fraction of that per-chip target.

Prints exactly one JSON line.
"""

import json
import statistics
import sys
import time

import jax

import numpy as np

N = 1_000_000
OWNERS = 1_000
TARGET_PER_CHIP = 12_500_000.0


def build_columns(n=N, owners=OWNERS, seed=7, stored_winners=False):
    rng = np.random.default_rng(seed)
    base = 1_700_000_000_000
    # ~4 messages/cell contention, clustered minutes (realistic sync bursts).
    cells = max(n // 4, 1)
    cell_id = rng.integers(0, cells, n).astype(np.int32)
    owner_of_cell = rng.integers(0, owners, cells).astype(np.int64)
    owner_ix = owner_of_cell[cell_id]
    millis = base + rng.integers(0, 86_400_000, n).astype(np.int64)
    counter = rng.integers(0, 256, n).astype(np.int32)
    node = rng.integers(1, 2**63, n).astype(np.uint64)
    k1 = (millis.astype(np.uint64) << np.uint64(16)) | counter.astype(np.uint64)
    ex_k1 = np.zeros(n, np.uint64)
    ex_k2 = np.zeros(n, np.uint64)
    if stored_winners:
        # ~60% of cells carry a winner persisted by prior batches, drawn
        # from the same time window — so roughly half the incoming
        # messages LOSE to the stored winner, exercising both arms of
        # the _lex_max(p, e) seed and the `beats` compare (merge.py),
        # which the all-zero sentinel never touches.
        has = rng.random(cells) < 0.6
        w_millis = (base + rng.integers(0, 86_400_000, cells)).astype(np.uint64)
        w_k1 = ((w_millis << np.uint64(16)) | rng.integers(0, 256, cells).astype(np.uint64))
        w_k2 = rng.integers(1, 2**63, cells).astype(np.uint64)
        ex_k1 = np.where(has, w_k1, 0)[cell_id].astype(np.uint64)
        ex_k2 = np.where(has, w_k2, 0)[cell_id].astype(np.uint64)
    return {
        "cell_id": cell_id,
        "k1": k1,
        "k2": node,
        "ex_k1": ex_k1,
        "ex_k2": ex_k2,
        "millis": millis,
        "counter": counter,
        "node": node,
        "owner_ix": owner_ix,
    }


def shard_layout(cols, n_dev):
    """Repack flat columns so every owner's rows are contiguous inside
    exactly one equal-size shard chunk (the kernel's owner-locality
    precondition): owner → shard by owner % n_dev, each chunk padded to
    the max shard load rounded up to a power of two (pad rows carry the
    planner's padding cell and zero keys)."""
    n = len(cols["owner_ix"])
    shard_of = cols["owner_ix"] % n_dev
    order = np.argsort(shard_of, kind="stable")
    loads = np.bincount(shard_of, minlength=n_dev)
    chunk = 64
    while chunk < loads.max():
        chunk *= 2
    total = n_dev * chunk
    out = {}
    pad_cell = np.int32(0x7FFFFFFF)
    for k, v in cols.items():
        dst = np.zeros(total, v.dtype)
        if k == "cell_id":
            dst[:] = pad_cell
        start = 0
        for d in range(n_dev):
            rows = order[start : start + loads[d]]
            dst[d * chunk : d * chunk + loads[d]] = v[rows]
            start += loads[d]
        out[k] = dst
    return out, total


# Two fused-iteration counts per dispatch. The dispatch round-trip
# under the axon tunnel is ~107 ms of pure fixed overhead (measured: a
# fori_loop of trivial body costs the same wall time regardless of
# iteration count) — dividing one wall time by its iteration count
# buries that RTT in the per-iteration figure (r2 did exactly this and
# under-reported the chip by ~2.3×). The SLOPE between two iteration
# counts cancels the fixed term exactly: per_iter = (t_hi - t_lo) /
# (ITERS_HI - ITERS_LO). Inputs are perturbed per iteration so XLA
# cannot CSE, and the checksum carry keeps every iteration live.
ITERS_LO = 8
ITERS_HI = 72


def make_loop(mesh, iters, kernel=None):
    """The timed graph: `iters` fused reconcile iterations whose carry
    folds EVERY kernel output (the DCE fence — see body comments).
    Module-level so tests/test_bench_liveness.py can assert, output by
    output, that the checksum really depends on each pipeline stage;
    `kernel` is injectable for exactly that perturbation test."""
    import jax.numpy as jnp

    from evolu_tpu.ops import shard_map
    from jax.sharding import PartitionSpec as P

    if kernel is None:
        from evolu_tpu.parallel.reconcile import _shard_kernel as kernel

    spec = P("owners")
    pad_cell = jnp.int32(0x7FFFFFFF)

    def shard_loop(cell_id, k1, k2, ex_k1, ex_k2, owner_ix):
        def body(i, acc):
            # Perturb per iteration so XLA cannot CSE iterations:
            # the HLC tie-break key flips low node bits, and the
            # cell ids are bijectively relabeled (cells < 2^18, so
            # XOR-ing bits 18+ keeps groups intact but reshuffles
            # the sort order — each iteration does real, different
            # data movement). Padding rows keep the sentinel cell.
            cid = jnp.where(
                cell_id == pad_cell, cell_id, cell_id ^ (i << 18).astype(jnp.int32)
            )
            outs = kernel(
                cid, k1, k2 ^ i.astype(jnp.uint64), ex_k1, ex_k2, owner_ix,
            )
            # Fold EVERY output into the carry so no stage of the
            # pipeline is dead code — consuming only the masks let
            # XLA DCE the whole Merkle minute-segment stage in
            # r2/r3 early runs (the digest doesn't depend on it),
            # silently flattering the number. psum replicates the
            # carry across shards. tests/test_bench_liveness.py fails
            # if any output stops feeding the checksum.
            local = outs[0].astype(jnp.int64).sum()
            for o in outs[1:-1]:
                local = local + o.astype(jnp.int64).sum()
            masked = jax.lax.psum(local, "owners")
            return acc + masked + outs[-1].astype(jnp.int64)

        return jax.lax.fori_loop(0, iters, body, jnp.int64(0))

    return jax.jit(
        shard_map(
            shard_loop,
            mesh=mesh,
            in_specs=(spec,) * 6,
            out_specs=P(),
            check_vma=False,
        )
    )


def main():
    from evolu_tpu.parallel.mesh import create_mesh, sharding

    mesh = create_mesh()  # all local devices (1 chip under axon)
    n_dev = mesh.devices.size
    shd = sharding(mesh)
    names = ("cell_id", "k1", "k2", "ex_k1", "ex_k2", "owner_ix")

    results = {}
    with jax.enable_x64(True):
        loops = {k: make_loop(mesh, k) for k in (ITERS_LO, ITERS_HI)}
        for label, stored in (("empty_store", False), ("stored_winners", True)):
            cols, _ = shard_layout(build_columns(stored_winners=stored), n_dev)
            args = [jax.device_put(cols[k], shd) for k in names]
            medians = {}
            for iters, looped in loops.items():
                np.asarray(looped(*args))  # compile + warm
                times = []
                for _ in range(8):
                    t0 = time.perf_counter()
                    np.asarray(looped(*args))
                    times.append(time.perf_counter() - t0)
                medians[iters] = statistics.median(times)
            per_iter = (medians[ITERS_HI] - medians[ITERS_LO]) / (ITERS_HI - ITERS_LO)
            fixed = medians[ITERS_LO] - ITERS_LO * per_iter
            results[label] = {
                "per_chip": N / per_iter / n_dev,
                "per_iter_ms": round(per_iter * 1e3, 3),
                "dispatch_overhead_ms": round(fixed * 1e3, 1),
                "p50_ms_hi": round(medians[ITERS_HI] * 1e3, 3),
                "wall_per_chip_hi": round(ITERS_HI * N / medians[ITERS_HI] / n_dev),
            }

    # Headline = the stored-winners config: every kernel branch live
    # (winner-compare against a populated store, cells relabeled per
    # iteration). The empty-store config is reported alongside.
    head = results["stored_winners"]["per_chip"]
    print(
        json.dumps(
            {
                "metric": "crdt_messages_merged_per_sec_per_chip",
                "value": round(head),
                "unit": "msgs/sec/chip",
                "vs_baseline": round(head / TARGET_PER_CHIP, 4),
                "detail": {
                    "batch": N,
                    "owners": OWNERS,
                    "devices": n_dev,
                    "iters": [ITERS_LO, ITERS_HI],
                    "method": "two-point slope (fixed dispatch overhead cancelled)",
                    "stored_winners": True,
                    "rotating_cells": True,
                    "configs": {
                        k: {**v, "per_chip": round(v["per_chip"])}
                        for k, v in results.items()
                    },
                    "platform": jax.devices()[0].platform,
                },
            }
        )
    )


if __name__ == "__main__":
    # Global, not scoped: the whole pipeline is u64-keyed. Set only when
    # run as a script — tests import this module, and flipping the
    # process-wide default there would mask missing scoped
    # `with jax.enable_x64(True)` wraps in runtime code.
    jax.config.update("jax_enable_x64", True)
    sys.path.insert(0, ".")
    main()
