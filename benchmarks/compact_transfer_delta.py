"""Compact-transfer delta encoding: bytes/row before vs after, with a
byte-equal end-state check on the config-3 full-system shape
(ISSUE 4 satellite, VERDICT #9).

Runs the BatchReconciler ingest twice over identical request sets —
EVOLU_COMPACT_DELTA=0 (the r3 20 B/row packed-HLC-key upload) vs =1
(u32 millis-delta + u32 owner|counter + u64 node = 16 B/row) — on
fresh sharded stores, asserts the dumped end state (every row + every
tree) is byte-equal via crc32, and reports the per-variant upload
bytes/row from the `evolu_engine_compact_upload_bytes_total` metric
(the padded-total bytes the device leg actually ships). On the
tunneled-TPU host the upload is leg-cost directly (~12-17 MB/s); on
this CPU mesh the wall-time delta is noise and is reported as such.

Prints one JSON line.
"""

import json
import os
import statistics
import sys
import time
import zlib

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

N = int(os.environ.get("CTD_N", 200_000))
OWNERS = int(os.environ.get("CTD_OWNERS", 500))
SHARDS = 8
TRIALS = int(os.environ.get("CTD_TRIALS", 3))


def main():
    from benchmarks.config3_server_reconcile import _ciphertext_pool, build_requests
    from evolu_tpu.obs import metrics
    from evolu_tpu.server.engine import BatchReconciler
    from evolu_tpu.server.relay import ShardedRelayStore

    pool = _ciphertext_pool(2048)
    requests = build_requests(n=N, owners=OWNERS, pool=pool)
    n_msgs = sum(len(r.messages) for r in requests)

    warm = BatchReconciler(ShardedRelayStore(shards=SHARDS))
    warm.reconcile(requests)

    def dump_crc(store):
        crc = 0
        for sh in store.shards:
            for row in sh.db.exec(
                'SELECT "timestamp","userId","content" FROM "message" '
                'ORDER BY "userId","timestamp"'
            ):
                for v in row:
                    crc = zlib.crc32(v if isinstance(v, bytes) else str(v).encode(), crc)
            for row in sh.db.exec(
                'SELECT "userId","merkleTree" FROM "merkleTree" ORDER BY "userId"'
            ):
                for v in row:
                    crc = zlib.crc32(str(v).encode(), crc)
        return crc

    results, crcs = {}, {}
    for flag, label in (("0", "full_key_20B"), ("1", "delta_16B")):
        os.environ["EVOLU_COMPACT_DELTA"] = flag
        walls = []
        store = engine = None
        for _ in range(TRIALS):
            if store is not None:
                engine.close(); store.close()
            store = ShardedRelayStore(shards=SHARDS)
            engine = BatchReconciler(store, warm.mesh)
            metrics.reset()
            t0 = time.perf_counter()
            engine.reconcile(requests)
            walls.append(time.perf_counter() - t0)
        variant = "delta" if flag == "1" else "full"
        upload = metrics.get_counter(
            "evolu_engine_compact_upload_bytes_total", variant=variant
        )
        results[label] = {
            "wall_s_median": round(statistics.median(walls), 3),
            "msgs_per_sec": round(n_msgs / statistics.median(walls)),
            "upload_bytes": int(upload),
            "upload_bytes_per_row": round(upload / n_msgs, 2),
        }
        crcs[label] = dump_crc(store)
        engine.close(); store.close()
    os.environ.pop("EVOLU_COMPACT_DELTA", None)

    assert crcs["full_key_20B"] == crcs["delta_16B"], crcs
    print(json.dumps({
        "metric": "compact_transfer_delta_encoding",
        "n": n_msgs,
        "owners": OWNERS,
        "end_state_crc32": f"{crcs['delta_16B']:08x}",
        "end_state_byte_equal": True,
        "variants": results,
        "key_column_bytes_per_row": {"before": 8, "after": 4},
    }))


if __name__ == "__main__":
    main()
