"""Bench-baseline drift gate (ISSUE 15 satellite).

Every bench in this repo prints ONE JSON line; until now those lines
lived in ad-hoc BENCH_r*.json artifacts and prose in docs/BENCHMARKS.md
— nothing machine-readable tracked the trajectory, so a silent 2×
regression between PRs would only surface if a human re-read the docs.
This tool normalizes a bench's JSON line into `docs/baselines/
<bench>.<platform>.json` and flags relative drift beyond tolerance on
the next run.

Normalization (`normalize`): the record is flattened to dot-keyed
leaves and split into
- `values`  — plain numerics, compared with RELATIVE tolerance
  (default 25% — bench noise on shared hosts is real; the point is
  catching step changes, not basis points);
- `gates`   — strings, bools, and any numeric whose key smells like a
  correctness artifact (digest/checksum/crc/parity/pass...): these
  must match EXACTLY. Drift in a gate is a correctness failure, never
  noise, so gates stay hard even under `--smoke`.

Usage:
    python benchmarks/compare_baselines.py --update receive_leg < one.json
    python bench.py | python benchmarks/compare_baselines.py --check bench
    ... --check bench --smoke        # CI: drift is advisory (exit 0),
                                     # gate mismatches still exit 1

Exit codes: 0 ok/advisory, 1 gate mismatch (always) or drift
(non-smoke), 2 usage/missing-input errors. A missing baseline for this
(bench, platform) pair is advisory: it prints the `--update` command
and exits 0 — first runs on a new platform must not break CI.
"""

from __future__ import annotations

import argparse
import json
import os
import re
import sys
from typing import Dict, Tuple

BASELINE_DIR = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "docs", "baselines",
)

# Numeric leaves whose names mark correctness artifacts — exact-match
# gates, never tolerance-compared.
GATE_KEY_RE = re.compile(
    r"(digest|checksum|crc|parity|golden|byte_identical|pass)", re.I
)

# Key SEGMENTS that identify the run but should neither gate nor
# drift (free-text method notes, timestamps, artifact paths). Exact
# segment match — a substring test would eat e.g. "detail.*" ("tail")
# or "dispatch_overhead_ms" ("path").
IGNORE_SEGMENTS = frozenset(
    {"method", "written_at", "timestamp", "path", "cmd", "tail", "note"}
)


def _ignored(key: str) -> bool:
    return any(seg.lower() in IGNORE_SEGMENTS for seg in key.split("."))

DEFAULT_TOLERANCE = 0.25


def _flatten(obj, prefix="") -> Dict[str, object]:
    out: Dict[str, object] = {}
    if isinstance(obj, dict):
        for k, v in obj.items():
            out.update(_flatten(v, f"{prefix}{k}."))
    elif isinstance(obj, (list, tuple)):
        for i, v in enumerate(obj):
            out.update(_flatten(v, f"{prefix}{i}."))
    else:
        out[prefix[:-1]] = obj
    return out


def normalize(record: dict, bench: str) -> dict:
    """One bench JSON line → the stored baseline shape: numeric
    `values` (tolerance-compared), exact-match `gates`, and the
    platform key the baseline file is selected by."""
    flat = _flatten(record)
    values: Dict[str, float] = {}
    gates: Dict[str, object] = {}
    platform = "unknown"
    for key, v in flat.items():
        leaf = key.rsplit(".", 1)[-1]
        if leaf == "platform":
            platform = str(v)
            continue
        if _ignored(key):
            continue
        if isinstance(v, bool) or isinstance(v, str) or v is None:
            gates[key] = v
        elif isinstance(v, (int, float)):
            if GATE_KEY_RE.search(key):
                gates[key] = v
            else:
                values[key] = float(v)
    return {"bench": bench, "platform": platform,
            "values": values, "gates": gates}


def baseline_path(bench: str, platform: str) -> str:
    return os.path.join(BASELINE_DIR, f"{bench}.{platform}.json")


def compare(baseline: dict, current: dict,
            tolerance: float = DEFAULT_TOLERANCE
            ) -> Tuple[list, list]:
    """→ (gate_failures, drifts). Gate failures: [(key, base, cur)].
    Drifts: [(key, base, cur, rel)] where rel = |cur-base|/max(|base|,
    tiny). Keys present on only one side are DRIFT (shape changed —
    worth a look, not a hard failure) unless they are gates (a vanished
    checksum field IS a failure)."""
    gate_failures, drifts = [], []
    b_gates, c_gates = baseline.get("gates", {}), current.get("gates", {})
    for key in sorted(set(b_gates) | set(c_gates)):
        b, c = b_gates.get(key, "<absent>"), c_gates.get(key, "<absent>")
        if b != c:
            gate_failures.append((key, b, c))
    b_vals, c_vals = baseline.get("values", {}), current.get("values", {})
    for key in sorted(set(b_vals) | set(c_vals)):
        if key not in b_vals or key not in c_vals:
            drifts.append((key, b_vals.get(key), c_vals.get(key), None))
            continue
        b, c = b_vals[key], c_vals[key]
        rel = abs(c - b) / max(abs(b), 1e-12)
        if rel > tolerance:
            drifts.append((key, b, c, rel))
    return gate_failures, drifts


def _read_record(args) -> dict:
    raw = (open(args.file).read() if args.file else sys.stdin.read())
    # A whole-file JSON document first (the BENCH_r*.json artifact
    # shape); else benches may emit warnings before their JSON line —
    # take the LAST line that parses as a JSON object.
    try:
        rec = json.loads(raw)
        if isinstance(rec, dict):
            return rec.get("parsed", rec) if "parsed" in rec else rec
    except ValueError:
        pass
    last_err = None
    for line in reversed([l for l in raw.splitlines() if l.strip()]):
        try:
            rec = json.loads(line)
        except ValueError as e:
            last_err = e
            continue
        if isinstance(rec, dict):
            # BENCH_r* artifacts wrap the line under "parsed".
            return rec.get("parsed", rec) if "parsed" in rec else rec
    raise SystemExit(f"no JSON object line found in input ({last_err})")


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--update", metavar="BENCH",
                    help="normalize stdin/--file into the baseline store")
    ap.add_argument("--check", metavar="BENCH",
                    help="compare stdin/--file against the stored baseline")
    ap.add_argument("--file", help="read the bench JSON from a file "
                                   "instead of stdin")
    ap.add_argument("--tolerance", type=float, default=DEFAULT_TOLERANCE,
                    help=f"relative drift tolerance (default "
                         f"{DEFAULT_TOLERANCE})")
    ap.add_argument("--smoke", action="store_true",
                    help="advisory mode: drift prints warnings but exits 0 "
                         "(gates stay hard)")
    ap.add_argument("--baseline-dir", default=BASELINE_DIR,
                    help=argparse.SUPPRESS)
    args = ap.parse_args(argv)
    if bool(args.update) == bool(args.check):
        ap.error("exactly one of --update / --check is required")
    bench = args.update or args.check
    current = normalize(_read_record(args), bench)
    path = os.path.join(args.baseline_dir,
                        f"{bench}.{current['platform']}.json")
    if args.update:
        os.makedirs(args.baseline_dir, exist_ok=True)
        with open(path, "w") as f:
            json.dump(current, f, indent=2, sort_keys=True)
            f.write("\n")
        print(f"baseline written: {path} "
              f"({len(current['values'])} values, "
              f"{len(current['gates'])} gates)")
        return 0
    if not os.path.exists(path):
        print(f"no baseline for ({bench}, {current['platform']}) — "
              f"advisory pass; record one with:\n"
              f"  ... | python benchmarks/compare_baselines.py "
              f"--update {bench}")
        return 0
    with open(path) as f:
        baseline = json.load(f)
    gate_failures, drifts = compare(baseline, current, args.tolerance)
    for key, b, c in gate_failures:
        print(f"GATE MISMATCH {key}: baseline={b!r} current={c!r}")
    for key, b, c, rel in drifts:
        if rel is None:
            print(f"DRIFT (shape) {key}: baseline={b} current={c}")
        else:
            print(f"DRIFT {key}: baseline={b:g} current={c:g} "
                  f"({100 * rel:.1f}% > {100 * args.tolerance:.0f}%)")
    if gate_failures:
        return 1
    if drifts and not args.smoke:
        return 1
    if drifts:
        print(f"(smoke: {len(drifts)} drift(s) advisory-only)")
    if not gate_failures and not drifts:
        print(f"ok: within {100 * args.tolerance:.0f}% of {path}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
