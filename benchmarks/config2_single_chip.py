"""BASELINE config 2: 3-table schema, 100k messages, Merkle diff +
applyMessages — full-system single-chip throughput (device planner +
SQLite apply + tree update), not just the kernel.

Prints one JSON line.
"""

import json
import os
import random
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from evolu_tpu.core.merkle import diff_merkle_trees
from evolu_tpu.core.timestamp import Timestamp, timestamp_to_string
from evolu_tpu.core.types import CrdtMessage
from evolu_tpu.storage.apply import apply_messages
from evolu_tpu.storage.native import open_database
from evolu_tpu.storage.schema import init_db_model

N = 100_000


def build_messages(n=N, seed=2):
    rng = random.Random(seed)
    tables = [("todo", ("title", "isCompleted", "categoryId")),
              ("todoCategory", ("name",)),
              ("todoNote", ("text",))]
    nodes = [f"{rng.getrandbits(64):016x}" for _ in range(8)]
    base = 1_700_000_000_000
    out = []
    for i in range(n):
        table, cols = rng.choice(tables)
        out.append(CrdtMessage(
            timestamp_to_string(Timestamp(base + i // 4, i % 4, rng.choice(nodes))),
            table, f"row{rng.randrange(5000)}", rng.choice(cols), f"v{i}",
        ))
    return out


def main():
    messages = build_messages()
    db = open_database(backend="auto")
    init_db_model(db, mnemonic=None)
    for t in ("todo", "todoCategory", "todoNote"):
        db.exec(
            f'CREATE TABLE "{t}" ("id" TEXT PRIMARY KEY, "title" BLOB, '
            '"isCompleted" BLOB, "categoryId" BLOB, "name" BLOB, "text" BLOB)'
        )

    # Warm the jit for this power-of-two bucket (a long-running service
    # compiles once per bucket; the persistent cache keeps it across
    # processes).
    from evolu_tpu.ops.merge import plan_batch_device_full

    plan_batch_device_full(messages[:1], {})
    plan_batch_device_full(messages, {})

    t0 = time.perf_counter()
    tree = apply_messages(db, {}, messages, planner=plan_batch_device_full)
    apply_s = time.perf_counter() - t0

    # Merkle diff latency vs an empty replica (full-history divergence).
    t0 = time.perf_counter()
    diff = diff_merkle_trees(tree, {})
    diff_ms = (time.perf_counter() - t0) * 1e3
    assert diff is not None

    stored = db.exec('SELECT COUNT(*) FROM "__message"')[0][0]
    print(json.dumps({
        "metric": "config2_full_system_msgs_per_sec",
        "value": round(N / apply_s),
        "unit": "msgs/sec",
        "detail": {
            "messages": N, "stored": stored, "apply_s": round(apply_s, 3),
            "merkle_diff_ms": round(diff_ms, 3),
            "backend": type(db).__name__,
        },
    }))
    db.close()


if __name__ == "__main__":
    main()
