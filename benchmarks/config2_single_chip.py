"""BASELINE config 2: 3-table schema, 100k messages — full-system
single-chip client throughput (planner + SQLite apply + tree update),
not just the kernel.

r5 rewrite (VERDICT r4 next #5: the old row predated the winner cache,
the packed reader, and the fused receive). Measures the CURRENT client
paths, fresh store per trial, median of TRIALS:

- `objects`: the production planner (`select_planner` — HBM winner
  cache above `min_device_batch`) applying a CrdtMessage batch: the
  local-mutation (`_send`) shape.
- `packed`: the fused receive leg — response wire bytes →
  `decrypt_response_columns` → PackedReceive → packed plan →
  `eh_apply_planned_cells` (decrypt INCLUDED in the timed region; the
  wire bytes are what a client actually receives).
- `packed_v2`: the SAME timed region over an `aead-batch-v1` response
  (sync/aead.py — session-keyed GCM records instead of per-message
  OpenPGP): what a NEGOTIATED client receives. The delta vs `packed`
  is the full-system share of the ISSUE-8 crypto-ceiling lift.
- `legacy_streamed`: the pre-r3 shape (plan_batch_device_full with
  SQLite-streamed winners) kept for cross-round continuity.

Prints one JSON line.
"""

import json
import os
import random
import statistics
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from evolu_tpu.core.merkle import diff_merkle_trees
from evolu_tpu.core.timestamp import Timestamp, timestamp_to_string
from evolu_tpu.core.types import CrdtMessage
from evolu_tpu.runtime.worker import select_planner
from evolu_tpu.storage.apply import apply_messages
from evolu_tpu.storage.native import open_database
from evolu_tpu.storage.schema import init_db_model
from evolu_tpu.utils.config import Config

N = int(os.environ.get("CONFIG2_N", 100_000))
TRIALS = int(os.environ.get("CONFIG2_TRIALS", 3))
MN = "legal winner thank year wave sausage worth useful legal winner thank yellow"


def build_messages(n=N, seed=2):
    rng = random.Random(seed)
    tables = [("todo", ("title", "isCompleted", "categoryId")),
              ("todoCategory", ("name",)),
              ("todoNote", ("text",))]
    nodes = [f"{rng.getrandbits(64):016x}" for _ in range(8)]
    base = 1_700_000_000_000
    out = []
    for i in range(n):
        table, cols = rng.choice(tables)
        out.append(CrdtMessage(
            timestamp_to_string(Timestamp(base + i // 4, i % 4, rng.choice(nodes))),
            table, f"row{rng.randrange(5000)}", rng.choice(cols), f"v{i}",
        ))
    return out


def mkdb():
    db = open_database(backend="auto")
    init_db_model(db, mnemonic=None)
    for t in ("todo", "todoCategory", "todoNote"):
        db.exec(
            f'CREATE TABLE "{t}" ("id" TEXT PRIMARY KEY, "title" BLOB, '
            '"isCompleted" BLOB, "categoryId" BLOB, "name" BLOB, "text" BLOB)'
        )
    return db


def main():
    from evolu_tpu.ops.merge import plan_batch_device_full
    from evolu_tpu.sync import native_crypto, protocol
    from evolu_tpu.sync.client import encrypt_messages, encrypt_messages_v2

    messages = build_messages()
    resp_bytes = protocol.encode_sync_response(
        protocol.SyncResponse(tuple(encrypt_messages(messages, MN)), "{}")
    )
    resp_bytes_v2 = protocol.encode_sync_response(
        protocol.SyncResponse(tuple(encrypt_messages_v2(messages, MN)), "{}")
    )
    probe = mkdb()
    backend = type(probe).__name__  # Cpp vs Py sqlite matters for the record
    probe.close()

    def trial_objects():
        db = mkdb()
        planner = select_planner(Config(), db)
        t0 = time.perf_counter()
        tree = apply_messages(db, {}, messages, planner=planner)
        dt = time.perf_counter() - t0
        return db, tree, dt

    def _trial_wire(wire_bytes):
        db = mkdb()
        planner = select_planner(Config(), db)
        t0 = time.perf_counter()
        out = native_crypto.decrypt_response_columns(wire_bytes, MN)
        if out is None:  # no native crypto: the client's object fallback
            batch, _tree_str = native_crypto.decrypt_response(wire_bytes, MN) or (
                None, None,
            )
            if batch is None:
                from evolu_tpu.sync.client import decrypt_messages

                resp = protocol.decode_sync_response(wire_bytes)
                batch = decrypt_messages(resp.messages, MN)
        else:
            batch, _tree_str = out
        tree = apply_messages(db, {}, batch, planner=planner)
        dt = time.perf_counter() - t0
        return db, tree, dt

    def trial_packed():
        return _trial_wire(resp_bytes)

    def trial_packed_v2():
        return _trial_wire(resp_bytes_v2)

    def trial_legacy():
        db = mkdb()
        t0 = time.perf_counter()
        tree = apply_messages(db, {}, messages, planner=plan_batch_device_full)
        dt = time.perf_counter() - t0
        return db, tree, dt

    results = {}
    diff_ms = None
    trees = {}
    for label, fn in (("objects", trial_objects), ("packed", trial_packed),
                      ("packed_v2", trial_packed_v2),
                      ("legacy_streamed", trial_legacy)):
        db, tree, _ = fn()  # warm the jit bucket (compile once per bucket)
        stored = db.exec_sql_query('SELECT COUNT(*) FROM "__message"', ())
        assert next(iter(stored[0].values())) == N
        trees[label] = tree
        if diff_ms is None:
            t0 = time.perf_counter()
            assert diff_merkle_trees(tree, {}) is not None
            diff_ms = (time.perf_counter() - t0) * 1e3
        db.close()
        rates = []
        for _ in range(TRIALS):
            db, _tree, dt = fn()
            rates.append(N / dt)
            db.close()
        results[label] = round(statistics.median(rates))

    # The v2 wire must land the exact state the v1 wire lands (the
    # store and Merkle algebra are version-blind — ISSUE 8 contract).
    assert trees["packed_v2"] == trees["packed"] == trees["objects"]

    import jax

    print(json.dumps({
        "metric": "config2_full_system_msgs_per_sec",
        "value": results["packed"],
        "unit": "msgs/sec",
        "detail": {
            "messages": N, "trials": TRIALS,
            "paths": results,
            "merkle_diff_ms": round(diff_ms, 3),
            "backend": backend,
            "platform": jax.devices()[0].platform,
        },
    }))


if __name__ == "__main__":
    main()
