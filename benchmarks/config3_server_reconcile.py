"""BASELINE config 3, full system: batch-reconcile encrypted messages
across many owners through the relay's BatchReconciler — protobuf-shaped
requests in, SQLite + per-owner Merkle trees out, device pass for the
per-(owner, minute) XOR deltas. The end state is identical to running
`store.sync` per request (asserted on a sample).

The kernel-only number for this shape is bench.py; this measures the
whole server path a pod would run.

Prints one JSON line.
"""

import json
import os
import random
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from evolu_tpu.core.timestamp import Timestamp, timestamp_to_string
from evolu_tpu.server.engine import BatchReconciler
from evolu_tpu.server.relay import RelayStore
from evolu_tpu.sync import protocol

N = int(os.environ.get("CONFIG3_N", 200_000))
OWNERS = int(os.environ.get("CONFIG3_OWNERS", 200))


def build_requests(n=N, owners=OWNERS, seed=3):
    rng = random.Random(seed)
    base = 1_700_000_000_000
    per_owner = {}
    for i in range(n):
        o = rng.randrange(owners)
        t = Timestamp(base + i // 16, i % 16, f"{o:015x}{rng.randrange(16):x}")
        per_owner.setdefault(o, []).append(
            protocol.EncryptedCrdtMessage(timestamp_to_string(t), b"\x00" * 64)
        )
    from evolu_tpu.core.merkle import create_initial_merkle_tree, merkle_tree_to_string

    empty = merkle_tree_to_string(create_initial_merkle_tree())
    return [
        protocol.SyncRequest(tuple(msgs), f"owner{o:04d}", "f" * 16, empty)
        for o, msgs in per_owner.items()
    ]


def main():
    requests = build_requests()
    n_msgs = sum(len(r.messages) for r in requests)

    # Warm the jit with the SAME batch shape (jit traces per bucket
    # size) on a throwaway store, so the timed run measures steady state.
    warm = BatchReconciler(RelayStore())
    warm.reconcile(build_requests())

    store = RelayStore()
    engine = BatchReconciler(store, warm.mesh)
    t0 = time.perf_counter()
    responses = engine.reconcile(requests)
    elapsed = time.perf_counter() - t0

    # Spot-check: per-request sync on a fresh store gives the same tree.
    sample = requests[0]
    solo = RelayStore()
    solo_resp = solo.sync(sample)
    assert responses[0].merkle_tree == solo_resp.merkle_tree, "batch != per-request"

    stored = store.db.exec('SELECT COUNT(*) FROM "message"')[0][0]
    print(json.dumps({
        "metric": "config3_server_reconcile_msgs_per_sec",
        "value": round(n_msgs / elapsed),
        "unit": "msgs/sec",
        "detail": {
            "messages": n_msgs, "owners": len(requests), "stored": stored,
            "elapsed_s": round(elapsed, 3),
            "devices": engine.mesh.devices.size,
            "backend": type(store.db).__name__,
        },
    }))
    store.close(), solo.close(), warm.store.close()


if __name__ == "__main__":
    main()
