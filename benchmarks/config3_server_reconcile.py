"""BASELINE config 3, full system: batch-reconcile encrypted messages
across many owners through the relay's BatchReconciler — protobuf-shaped
requests in, SQLite + per-owner Merkle trees out, device pass for the
per-(owner, minute) XOR deltas, storage sharded per owner with parallel
shard writers. The end state is identical to running `store.sync` per
request (asserted on a sample).

Steady-state shape: each client pushes its own new messages with its
post-apply tree (how the reference sync protocol actually behaves), so
responses are empty; a separate cold-sync leg measures full-history
response packing for restored devices with empty trees.

The kernel-only number for this shape is bench.py; this measures the
whole server path a pod would run. Prints one JSON line.
"""

import json
import os
import random
import statistics
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from evolu_tpu.core.merkle import (
    apply_prefix_xors,
    merkle_tree_to_string,
    minute_deltas_host,
)
from evolu_tpu.core.timestamp import Timestamp, timestamp_to_string
from evolu_tpu.server.engine import BatchReconciler
from evolu_tpu.server.relay import RelayStore, ShardedRelayStore
from evolu_tpu.sync import protocol

N = int(os.environ.get("CONFIG3_N", 1_000_000))
OWNERS = int(os.environ.get("CONFIG3_OWNERS", 1000))
SHARDS = int(os.environ.get("CONFIG3_SHARDS", 8))
COLD = int(os.environ.get("CONFIG3_COLD", 25))
BATCHES = int(os.environ.get("CONFIG3_BATCHES", 8))
# Robust protocol for tunnel-noisy end-to-end runs (VERDICT r3 weak
# #2): repeated same-process trials on fresh stores, MEDIAN as the
# statistic, full spread reported. TPU runs use >= 5.
TRIALS = int(os.environ.get("CONFIG3_TRIALS", 1))


def _ciphertext_pool(size=8192):
    """REAL ciphertexts of realistic CrdtMessageContents — the relay is
    E2EE-blind, so content bytes only shape storage/IO, but a zero-byte
    stand-in (r2/r3) under-weighed both; a cycled pool of distinct real
    ciphertexts gives every insert honest size and entropy without
    paying 1M encryptions of setup. CONFIG3_WIRE picks the format:
    `v1` (default) = OpenPGP SKESK‖SEIPD, `v2` = aead-batch-v1 GCM
    records (sync/aead.py, ~43 B/row smaller) — what a fleet whose
    clients all negotiated the ISSUE-8 capability actually stores."""
    from evolu_tpu.core.types import CrdtMessage
    from evolu_tpu.sync.client import encrypt_messages, encrypt_messages_v2

    mnemonic = "legal winner thank year wave sausage worth useful legal winner thank yellow"
    msgs = tuple(
        CrdtMessage("t", "todo", f"Tf9faXx1ryRXmPF6e_{i:04d}", "title", f"item {i} ✓")
        for i in range(size)
    )
    enc = (encrypt_messages_v2 if os.environ.get("CONFIG3_WIRE") == "v2"
           else encrypt_messages)
    return tuple(e.content for e in enc(msgs, mnemonic))


def build_requests(n=N, owners=OWNERS, seed=3, pool=None):
    rng = random.Random(seed)
    base = 1_700_000_000_000
    pool = pool or _ciphertext_pool()
    per_owner = {}
    for i in range(n):
        o = rng.randrange(owners)
        t = Timestamp(base + i // 16, i % 16, f"{o:015x}{rng.randrange(16):x}")
        per_owner.setdefault(o, []).append(
            protocol.EncryptedCrdtMessage(timestamp_to_string(t), pool[i % len(pool)])
        )
    requests = []
    for o, msgs in per_owner.items():
        # Steady state: the client's tree already covers its own pushed
        # messages (send applies locally before syncing), and the server
        # holds nothing else for this owner.
        deltas, _ = minute_deltas_host(m.timestamp for m in msgs)
        tree = merkle_tree_to_string(apply_prefix_xors({}, deltas))
        requests.append(
            protocol.SyncRequest(tuple(msgs), f"owner{o:04d}", "f" * 16, tree)
        )
    return requests


def main():
    pool = _ciphertext_pool()
    requests = build_requests(pool=pool)
    n_msgs = sum(len(r.messages) for r in requests)

    # Warm the jit with the SAME batch shape (jit traces per bucket
    # size) on a throwaway store, so the timed run measures steady state.
    warm = BatchReconciler(ShardedRelayStore(shards=SHARDS))
    warm.reconcile(build_requests(pool=pool))

    one_shot_rates = []
    store = engine = responses = None
    for _ in range(TRIALS):
        if store is not None:
            engine.close()
            store.close()
        store = ShardedRelayStore(shards=SHARDS)
        engine = BatchReconciler(store, warm.mesh)
        t0 = time.perf_counter()
        responses = engine.reconcile(requests)
        one_shot_rates.append(n_msgs / (time.perf_counter() - t0))
    assert all(r.messages == () for r in responses), "steady state must answer empty"

    # Spot-check: per-request sync on a fresh store gives the same tree.
    sample = requests[0]
    solo = RelayStore()
    solo_resp = solo.sync(sample)
    assert responses[0].merkle_tree == solo_resp.merkle_tree, "batch != per-request"

    # Cold-sync leg: restored devices (empty tree, different node) pull
    # their owner's full history.
    cold = [
        protocol.SyncRequest((), r.user_id, "e" * 16, "{}")
        for r in requests[:COLD]
    ]
    t1 = time.perf_counter()
    cold_responses = engine.reconcile(cold)
    cold_elapsed = time.perf_counter() - t1
    cold_msgs = sum(len(r.messages) for r in cold_responses)
    assert cold_msgs == sum(len(r.messages) for r in requests[:COLD])

    stored = sum(
        s.db.exec('SELECT COUNT(*) FROM "message"')[0][0] for s in store.shards
    )
    assert stored == n_msgs

    # Pipelined streaming leg: the SAME 1M messages as a stream of
    # request batches — batch k+1's device hashing rides the
    # tunnel/chip while batch k's SQLite inserts + trees commit
    # (engine.reconcile_stream). End state must equal the one-shot run.
    per = -(-len(requests) // BATCHES)
    batches = [requests[i : i + per] for i in range(0, len(requests), per)]
    warm2 = BatchReconciler(ShardedRelayStore(shards=SHARDS), warm.mesh)
    warm2.reconcile_stream(batches)  # jit-warm the per-batch bucket shapes
    pipe_rates = []
    pipe_store = pipe_engine = None
    for _ in range(TRIALS):
        if pipe_store is not None:
            pipe_engine.close()
            pipe_store.close()
        pipe_store = ShardedRelayStore(shards=SHARDS)
        pipe_engine = BatchReconciler(pipe_store, warm.mesh)
        t2 = time.perf_counter()
        pipe_engine.reconcile_stream(batches)
        pipe_rates.append(n_msgs / (time.perf_counter() - t2))

    def dump(s):
        out = []
        for sh in s.shards:
            out.append(sh.db.exec('SELECT "timestamp","userId","content" FROM "message" ORDER BY "userId","timestamp"'))
            out.append(sh.db.exec('SELECT "userId","merkleTree" FROM "merkleTree" ORDER BY "userId"'))
        return out

    assert dump(pipe_store) == dump(store), "pipelined end state diverged"

    def stats(rates):
        return {
            "median": round(statistics.median(rates)),
            "min": round(min(rates)), "max": round(max(rates)),
            "trials": [round(r) for r in rates],
        }

    print(json.dumps({
        "metric": "config3_server_reconcile_msgs_per_sec",
        # Headline = the better MODE by median-of-trials; the spread
        # rides in detail (never "best observed" — VERDICT r3 weak #2).
        "value": round(max(statistics.median(one_shot_rates),
                           statistics.median(pipe_rates))),
        "unit": "msgs/sec",
        "detail": {
            "messages": n_msgs, "owners": len(requests), "stored": stored,
            "protocol": f"median of {TRIALS} same-process trials, fresh stores",
            "one_shot": stats(one_shot_rates),
            "pipelined": stats(pipe_rates),
            "pipeline_batches": len(batches),
            "devices": engine.mesh.devices.size,
            "storage_shards": SHARDS,
            "cold_sync_msgs_per_sec": round(cold_msgs / cold_elapsed),
            "cold_requests": COLD,
            "backend": type(store.shards[0].db).__name__,
            "wire": os.environ.get("CONFIG3_WIRE", "v1"),
            "ciphertext_bytes_per_row": round(
                sum(map(len, pool)) / len(pool), 1),
        },
    }))
    store.close(), solo.close(), warm.store.close(), warm2.store.close(), pipe_store.close()


if __name__ == "__main__":
    main()
