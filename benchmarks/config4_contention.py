"""BASELINE config 4: 64 replicas editing the same 100 rows — HLC
(counter, node) tie-break correctness under maximal collision, plus
the merge throughput on that adversarial shape.

Prints one JSON line; "correct" asserts byte-level agreement between
the device-planned end state and the sequential TS-semantics oracle.
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from evolu_tpu.storage.apply import apply_messages, apply_messages_sequential
from evolu_tpu.storage.native import open_database
from evolu_tpu.storage.schema import init_db_model

sys.path.insert(0, os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "tests"))
from test_convergence import make_contention_workload  # noqa: E402


def fresh():
    db = open_database(backend="auto")
    init_db_model(db, mnemonic=None)
    db.exec('CREATE TABLE "todo" ("id" TEXT PRIMARY KEY, "title" BLOB, "n" BLOB)')
    return db


def dump(db):
    return (
        db.exec('SELECT * FROM "todo" ORDER BY "id"'),
        db.exec('SELECT * FROM "__message" ORDER BY "timestamp"'),
    )


def main():
    messages = make_contention_workload(n_replicas=64, n_rows=100, writes_per_replica=60)

    from evolu_tpu.ops.merge import plan_batch_device_full

    plan_batch_device_full(messages, {})  # warm the jit bucket

    device_db = fresh()
    t0 = time.perf_counter()
    apply_messages(device_db, {}, messages, planner=plan_batch_device_full)
    device_s = time.perf_counter() - t0

    oracle_db = fresh()
    t0 = time.perf_counter()
    with oracle_db.transaction():
        apply_messages_sequential(oracle_db, {}, messages)
    oracle_s = time.perf_counter() - t0

    correct = dump(device_db) == dump(oracle_db)
    print(json.dumps({
        "metric": "config4_contention_msgs_per_sec",
        "value": round(len(messages) / device_s),
        "unit": "msgs/sec",
        "detail": {
            "messages": len(messages), "replicas": 64, "rows": 100,
            "correct_vs_oracle": correct,
            "device_s": round(device_s, 3), "oracle_s": round(oracle_s, 3),
            "speedup_vs_sequential": round(oracle_s / device_s, 2),
        },
    }))
    assert correct, "device plan diverged from sequential oracle"
    device_db.close(), oracle_db.close()


if __name__ == "__main__":
    main()
