"""BASELINE config 5: 10M messages, owners sharded over the device
mesh, Merkle digests XOR-combined across devices over ICI.

On real TPU hardware this uses every local chip; under the CPU test
env set XLA_FLAGS=--xla_force_host_platform_device_count=8 to exercise
the 8-way mesh semantics.

Prints one JSON line.
"""

import json
import os
import statistics
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

jax.config.update("jax_enable_x64", True)

import numpy as np

N = 10_000_000
OWNERS = 1_000
INNER_ITERS = 2


def main():
    import jax.numpy as jnp
    from evolu_tpu.ops import shard_map
    from jax.sharding import PartitionSpec as P

    sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    import bench

    from evolu_tpu.parallel.mesh import create_mesh, sharding
    from evolu_tpu.parallel.reconcile import _shard_kernel

    mesh = create_mesh()
    n_dev = mesh.devices.size
    cols, total = bench.shard_layout(bench.build_columns(n=N, owners=OWNERS), n_dev)

    shd = sharding(mesh)
    names = ("cell_id", "k1", "k2", "ex_k1", "ex_k2", "owner_ix")
    args = [jax.device_put(cols[k], shd) for k in names]

    spec = P("owners")

    def shard_loop(*xs):
        def body(i, acc):
            outs = _shard_kernel(xs[0], xs[1], xs[2] ^ i.astype(jnp.uint64), *xs[3:])
            masked = jax.lax.psum(outs[0].astype(jnp.int64).sum(), "owners")
            return acc + masked + outs[-1].astype(jnp.int64)

        return jax.lax.fori_loop(0, INNER_ITERS, body, jnp.int64(0))

    with jax.enable_x64(True):
        looped = jax.jit(shard_map(
            shard_loop, mesh=mesh, in_specs=(spec,) * 6, out_specs=P(), check_vma=False,
        ))
        np.asarray(looped(*args))
        times = []
        for _ in range(5):
            t0 = time.perf_counter()
            np.asarray(looped(*args))
            times.append(time.perf_counter() - t0)
    p50 = statistics.median(times)
    total_rate = INNER_ITERS * N / p50
    print(json.dumps({
        "metric": "config5_mesh_msgs_per_sec",
        "value": round(total_rate),
        "unit": "msgs/sec",
        "detail": {
            "batch": N, "owners": OWNERS, "devices": n_dev,
            "per_chip": round(total_rate / n_dev),
            "p50_ms": round(p50 * 1e3, 3),
            "pod_pass": pod_pass(mesh),
            "platform": jax.devices()[0].platform,
        },
    }))


def pod_pass(mesh):
    """r5 (VERDICT r4 next #4): ONE WHOLE-SERVER pod pass — the literal
    BASELINE "one pod pass" shape (reference apps/server/src/index.ts:
    224-248 at pod scale). `reconcile_pod` runs ingest + the SPMD
    Merkle dispatch over this mesh + the wire-mode serve on a fresh
    store per trial; single-process degenerate semantics are byte-equal
    to the plain engine (test-pinned)."""
    from benchmarks.pod_requests import build_pod_requests
    from evolu_tpu.server.engine import reconcile_pod
    from evolu_tpu.server.relay import ShardedRelayStore

    pod_owners = int(os.environ.get("POD_OWNERS", 500))
    per = int(os.environ.get("POD_N", 200_000)) // pod_owners
    pod_n = per * pod_owners  # honest: the rows actually built
    requests, _expect = build_pod_requests(
        owners=pod_owners, per=per, factor=977, stride_ms=1000, payload=b"c" * 64
    )
    times = []
    for _ in range(3):
        store = ShardedRelayStore(shards=min(8, mesh.devices.size))
        t0 = time.perf_counter()
        _resp, _digest = reconcile_pod(mesh, store, tuple(requests), wire=True)
        times.append(time.perf_counter() - t0)
        store.close()
    p50 = statistics.median(times)
    return {
        "msgs_per_sec": round(pod_n / p50),
        "p50_ms": round(p50 * 1e3, 1),
        "rows": pod_n,
        "owners": pod_owners,
        "wire_serve": True,
    }


if __name__ == "__main__":
    main()
