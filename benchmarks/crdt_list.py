"""RGA list linearization kernel, slope-measured (ISSUE 14).

Same protocol as bench.py: the kernel runs inside a fused fori_loop at
two iteration counts; the slope between the two wall times cancels the
fixed dispatch overhead (mandatory under the axon tunnel, where
block_until_ready does not block and RTT is ~101-121 ms), and EVERY
kernel output folds into the checksum carry so XLA cannot DCE a stage
(the r2/r3 lesson). The per-iteration perturbation here must keep the
input a VALID forest, so the loop alternates between two precomputed
random forests on the same cells — the positions genuinely change
every iteration and neither structure can be hoisted.

Measures, at N elements over K cells (tombstone ratio ~50%):
- **linearize**: the full device twin (`rga_order_core`) — one packed
  (cell | parent | rank) sort, Euler-tour predecessor construction,
  log2(2N) pointer-jumping gathers, then the second sort + segmented
  alive-slot scan on the shared `pallas_scan` machinery.
- **host_oracle**: the pure-Python `crdt_list.linearize` replay on the
  same shape — the honest CPU baseline the device path has to beat.

`--smoke` runs a small shape, asserts bit-parity against the host
oracle per cell (positions AND alive slots), and prints the same JSON
line (CI). Prints ONE JSON line.
"""

import argparse
import functools
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np

jax.config.update("jax_enable_x64", True)

ITERS_LO, ITERS_HI = 2, 10


def _slope(run, iters_lo=ITERS_LO, iters_hi=ITERS_HI, reps=3):
    """Per-iteration seconds via the two-count slope, best of reps."""
    run(iters_lo)  # compile both shapes before timing
    run(iters_hi)
    best = None
    for _ in range(reps):
        t0 = time.perf_counter()
        run(iters_lo)
        t_lo = time.perf_counter() - t0
        t0 = time.perf_counter()
        run(iters_hi)
        t_hi = time.perf_counter() - t0
        s = (t_hi - t_lo) / (iters_hi - iters_lo)
        best = s if best is None else min(best, s)
    return best


def _random_forest(n, k, seed):
    """(cell, parent, alive): contiguous cells, every parent an earlier
    element of the same cell or −1 (head) — a valid RGA forest in the
    kernel's sorted layout."""
    rng = np.random.default_rng(seed)
    cell = np.sort(rng.integers(0, k, n)).astype(np.int32)
    starts = np.r_[0, np.flatnonzero(np.diff(cell)) + 1]
    cell_start = np.repeat(starts, np.diff(np.r_[starts, n]))
    local = np.arange(n) - cell_start
    draw = np.floor(rng.random(n) * (local + 1)).astype(np.int64)
    parent = (cell_start - 1 + draw).astype(np.int32)
    parent = np.where(parent < cell_start, -1, parent).astype(np.int32)
    alive = rng.integers(0, 2, n).astype(np.int32)
    return cell, parent, alive


def bench_linearize(n, k):
    from evolu_tpu.ops.crdt_list_merge import rga_order_core

    cell, pa, alive = _random_forest(n, k, 5)
    _c2, pb, _a2 = _random_forest(n, k, 6)
    cell_j = jnp.asarray(cell)
    pa_j, pb_j = jnp.asarray(pa), jnp.asarray(pb)
    alive_j = jnp.asarray(alive)

    @functools.partial(jax.jit, static_argnums=0)
    def loop(iters):
        def body(i, acc):
            # Alternate between two valid forests: the tree structure —
            # and therefore every position — really changes each
            # iteration, so neither sort nor the ranking can be cached
            # out of the timed graph.
            par = jnp.where(i % 2 == 0, pa_j, pb_j)
            pos, slot = rga_order_core(cell_j, par, alive_j)
            # Consume EVERY output (slot is −1 for tombstones; +1 keeps
            # the sum sensitive to each one).
            return acc + pos.astype(jnp.uint64).sum() \
                + (slot + 1).astype(jnp.uint64).sum()

        return jax.lax.fori_loop(0, iters, body, jnp.zeros((), jnp.uint64))

    checks = {}

    def run(iters):
        checks[iters] = int(jax.block_until_ready(loop(iters)))

    s = _slope(run)
    # Liveness: different iteration counts must yield different carries.
    assert checks[ITERS_LO] != checks[ITERS_HI], "checksum carry is dead"
    return {"slope_ms": s * 1e3, "elems_per_s": n / s, "checksum": checks[ITERS_HI]}


def bench_host_oracle(n, k):
    from evolu_tpu.core import crdt_list as cl

    cell, parent, _alive = _random_forest(n, k, 5)
    best = None
    for _ in range(3):
        t0 = time.perf_counter()
        for c in range(k):
            lo, hi = np.searchsorted(cell, c), np.searchsorted(cell, c + 1)
            if lo == hi:
                continue
            tags = [f"{i:08d}" for i in range(lo, hi)]
            origins = ["" if parent[i] < 0 else f"{parent[i]:08d}"
                       for i in range(lo, hi)]
            cl.linearize(tags, origins)
        dt = time.perf_counter() - t0
        best = dt if best is None else min(best, dt)
    return {"wall_ms": best * 1e3, "elems_per_s": n / best}


def parity_check(n=20_000, k=64):
    """Device/host bit-parity on random forests (the smoke gate):
    positions AND alive slots, per cell."""
    from evolu_tpu.core import crdt_list as cl
    from evolu_tpu.ops.crdt_list_merge import rga_order

    cell, parent, alive = _random_forest(n, k, 3)
    pos, slot = rga_order(cell, parent, alive)
    for c in range(k):
        lo, hi = np.searchsorted(cell, c), np.searchsorted(cell, c + 1)
        if lo == hi:
            continue
        tags = [f"{i:08d}" for i in range(lo, hi)]
        origins = ["" if parent[i] < 0 else f"{parent[i]:08d}"
                   for i in range(lo, hi)]
        expect = cl.linearize(tags, origins)
        assert list(pos[lo:hi]) == expect, f"pos parity broke in cell {c}"
        by_pos = sorted(range(lo, hi), key=lambda i: pos[i])
        s = 0
        for i in by_pos:
            if alive[i]:
                assert slot[i] == s, f"slot parity broke in cell {c}"
                s += 1
            else:
                assert slot[i] == -1


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--smoke", action="store_true",
                    help="small shape + host-oracle parity gate (CI)")
    ap.add_argument("--n", type=int, default=None)
    args = ap.parse_args()
    n = args.n or (1 << 14 if args.smoke else 1 << 20)
    k = 1 << 6 if args.smoke else 1 << 12
    parity_check()
    out = {
        "bench": "crdt_list",
        "backend": jax.default_backend(),
        "device": str(jax.devices()[0]),
        "n_elems": n,
        "cells": k,
        "smoke": bool(args.smoke),
        "linearize": bench_linearize(n, k),
        "host_oracle": bench_host_oracle(min(n, 1 << 17), min(k, 1 << 9)),
        "parity": "ok",
    }
    print(json.dumps(out))


if __name__ == "__main__":
    main()
