"""CRDT typed-column merge kernels, slope-measured (ISSUE 7).

Same protocol as bench.py: each kernel runs inside a fused fori_loop at
two iteration counts; the slope between the two wall times cancels the
fixed dispatch overhead (mandatory under the axon tunnel, where
block_until_ready does not block and RTT is ~101-121 ms), and EVERY
kernel output folds into the checksum carry so XLA cannot DCE a stage
(the r2/r3 lesson, fenced by tests/test_bench_liveness.py for the LWW
kernels; the same per-iteration perturbation discipline applies here).

Measures, at N ops over K cells:
- **counter**: the PN-counter fold (`pn_counter_sums_core`) — packed
  cell|idx sort + two segmented sums + dense scatter of per-cell
  totals. The sort-based shape, comparable row-for-row to the LWW sort
  plan's numbers in docs/BENCHMARKS.md.
- **awset**: the AW-set membership fold (`_killed_table_core` +
  `awset_pair_alive_core`) — pure scatter-OR, the shape where scatter
  has NO LWW duplicate-screen caveat. On CPU this is the plan that won
  PR 4; on TPU the recorded v5e law prices serialized scatters above a
  sort — whatever the chip says is recorded honestly.

`--smoke` runs a small shape, asserts bit-parity against the host
oracle (core/crdt_types.py), and prints the same JSON line (CI).
Prints ONE JSON line.
"""

import argparse
import functools
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np

jax.config.update("jax_enable_x64", True)

ITERS_LO, ITERS_HI = 2, 10


def _slope(run, iters_lo=ITERS_LO, iters_hi=ITERS_HI, reps=3):
    """Per-iteration seconds via the two-count slope, best of reps."""
    run(iters_lo)  # compile both shapes before timing
    run(iters_hi)
    best = None
    for _ in range(reps):
        t0 = time.perf_counter()
        run(iters_lo)
        t_lo = time.perf_counter() - t0
        t0 = time.perf_counter()
        run(iters_hi)
        t_hi = time.perf_counter() - t0
        s = (t_hi - t_lo) / (iters_hi - iters_lo)
        best = s if best is None else min(best, s)
    return best


def bench_counter(n, k):
    from evolu_tpu.ops.crdt_merge import pn_counter_sums_core

    rng = np.random.default_rng(7)
    cell = jnp.asarray(rng.integers(0, k, n).astype(np.int32))
    delta = jnp.asarray(rng.integers(-1000, 1000, n).astype(np.int64))
    low_mask = jnp.int32(k - 1)  # k is a power of two

    @functools.partial(jax.jit, static_argnums=0)
    def loop(iters):
        def body(i, acc):
            # Bijective in-range relabel + delta twiddle: the fold's
            # input really changes every iteration, so no stage can be
            # hoisted or cached out of the timed graph.
            cid = cell ^ (i * jnp.int32(0x2B) & low_mask)
            d = delta + (i & jnp.int64(7))
            pos, neg = pn_counter_sums_core(cid, d, table_size=k)
            return acc + pos.sum() + neg.sum()  # consume EVERY output

        return jax.lax.fori_loop(0, iters, body, jnp.zeros((), jnp.uint64))

    checks = {}

    def run(iters):
        checks[iters] = int(jax.block_until_ready(loop(iters)))

    s = _slope(run)
    # Liveness: different iteration counts must yield different carries.
    assert checks[ITERS_LO] != checks[ITERS_HI], "checksum carry is dead"
    return {"slope_ms": s * 1e3, "ops_per_s": n / s, "checksum": checks[ITERS_HI]}


def bench_awset(n, k):
    from evolu_tpu.ops.crdt_merge import _killed_table_core, awset_pair_alive_core

    rng = np.random.default_rng(11)
    n_kills = n // 5
    pair = jnp.asarray(rng.integers(0, k, n).astype(np.int32))
    tag = jnp.asarray(np.arange(n, dtype=np.int32))
    kills = jnp.asarray(rng.integers(0, n, n_kills).astype(np.int32))
    mask = jnp.int32(n - 1)  # n is a power of two

    @functools.partial(jax.jit, static_argnums=0)
    def loop(iters):
        def body(i, acc):
            k_ids = kills ^ (i * jnp.int32(0x5D) & mask)
            killed = _killed_table_core(k_ids, num_tags=n)
            alive = jnp.int32(1) - killed[tag]
            member = awset_pair_alive_core(pair, alive, num_pairs=k)
            local = killed.sum() + alive.sum() + member.sum()
            return acc + local.astype(jnp.int64)

        return jax.lax.fori_loop(0, iters, body, jnp.zeros((), jnp.int64))

    checks = {}

    def run(iters):
        checks[iters] = int(jax.block_until_ready(loop(iters)))

    s = _slope(run)
    assert checks[ITERS_LO] != checks[ITERS_HI], "checksum carry is dead"
    return {"slope_ms": s * 1e3, "ops_per_s": n / s, "checksum": checks[ITERS_HI]}


def parity_check(n=20_000, k=128):
    """Host-oracle bit-parity on a random log (the smoke gate)."""
    from evolu_tpu.core import crdt_types as ct
    from evolu_tpu.ops import crdt_merge as cm

    rng = np.random.default_rng(3)
    cell = rng.integers(0, k, n).astype(np.int32)
    delta = rng.integers(-1000, 1000, n).astype(np.int64)
    pos, neg = cm.pn_counter_sums(cell, delta, k)
    hp = np.zeros(k, np.int64)
    hn = np.zeros(k, np.int64)
    np.add.at(hp, cell, np.where(delta > 0, delta, 0))
    np.add.at(hn, cell, np.where(delta < 0, -delta, 0))
    assert np.array_equal(pos, hp) and np.array_equal(neg, hn), "counter parity"
    tags = [f"t{i}" for i in range(2000)]
    kills = {t for i, t in enumerate(tags) if i % 3 == 0}
    state = {t for i, t in enumerate(tags) if i % 7 == 0}
    assert ct.alive_add_flags(tags, kills, state) == cm.awset_alive_flags(
        tags, kills, state
    ), "awset parity"


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--smoke", action="store_true",
                    help="small shape + host-oracle parity gate (CI)")
    ap.add_argument("--n", type=int, default=None)
    args = ap.parse_args()
    n = args.n or (1 << 14 if args.smoke else 1 << 20)
    k = 1 << 10 if args.smoke else 1 << 18
    parity_check()
    out = {
        "bench": "crdt_types",
        "backend": jax.default_backend(),
        "device": str(jax.devices()[0]),
        "n_ops": n,
        "cells": k,
        "smoke": bool(args.smoke),
        "counter": bench_counter(n, k),
        "awset": bench_awset(n, k),
        "parity": "ok",
    }
    print(json.dumps(out))


if __name__ == "__main__":
    main()
