"""Hot loop #3: per-message OpenPGP encrypt/decrypt (SURVEY.md;
reference packages/evolu/src/sync.worker.ts:50-91,135-173).

Measures the full client sync leg — CrdtMessage → protobuf content →
SKESK‖SEIPD ciphertext and back — through the public entry points
(`encrypt_messages`/`decrypt_messages`), for both the batched C++ path
(native/evolu_crypto.cpp, production default) and the pure Python
oracle (sync/crypto.py, forced via monkeypatched unavailability).
Host-side by design: values never touch the device. Prints one JSON
line; numbers live in docs/BENCHMARKS.md.
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from evolu_tpu.core.types import CrdtMessage
from evolu_tpu.sync import native_crypto
from evolu_tpu.sync.client import decrypt_messages, encrypt_messages

N = int(os.environ.get("CRYPTO_N", 100_000))
MNEMONIC = "legal winner thank year wave sausage worth useful legal winner thank yellow"


def build_messages(n=N):
    # The config-3 value mix: short strings (titles), ints (flags/ids),
    # None (deletes) — what a todo-style client actually syncs.
    vals = [lambda i: f"todo item {i} ✓", lambda i: i % 2, lambda i: None,
            lambda i: f"note {i}: café", lambda i: i * 977]
    return tuple(
        CrdtMessage(
            f"2024-01-01T00:00:00.{i % 1000:03d}Z-{i % 16:04X}-a1b2c3d4e5f6{i % 256:02x}18",
            "todo", f"Tf9faXx1ryRXmPF6e_{i:06d}", "title", vals[i % 5](i),
        )
        for i in range(n)
    )


def timed(fn, *args):
    t0 = time.perf_counter()
    out = fn(*args)
    return out, time.perf_counter() - t0


def main():
    msgs = build_messages()
    results = {}

    from evolu_tpu.utils import native_loader

    for label in ("native", "pure"):
        if label == "pure":
            # Force the oracle: a None cache entry marks the library
            # unavailable, routing both legs pure.
            native_loader._cache["libevolu_crypto.so"] = None
        elif not native_crypto.native_available():
            continue
        enc, t_enc = timed(encrypt_messages, msgs, MNEMONIC)
        dec, t_dec = timed(decrypt_messages, enc, MNEMONIC)
        assert dec == msgs, f"{label} roundtrip diverged"
        results[label] = {
            "encrypt_msgs_per_sec": round(N / t_enc),
            "decrypt_msgs_per_sec": round(N / t_dec),
            "encrypt_us_per_msg": round(t_enc * 1e6 / N, 2),
            "decrypt_us_per_msg": round(t_dec * 1e6 / N, 2),
        }
    native_loader._cache.pop("libevolu_crypto.so", None)  # restore

    head = results.get("native", results.get("pure"))
    speedup = (
        round(results["native"]["encrypt_msgs_per_sec"]
              / results["pure"]["encrypt_msgs_per_sec"], 2)
        if "native" in results and "pure" in results else None
    )
    print(json.dumps({
        "metric": "crypto_encrypt_msgs_per_sec",
        "value": head["encrypt_msgs_per_sec"],
        "unit": "msgs/sec",
        "detail": {"n": N, "paths": results, "encrypt_speedup": speedup},
    }))


if __name__ == "__main__":
    main()
