"""Hot loop #3: the per-message sync crypto (SURVEY.md; reference
packages/evolu/src/sync.worker.ts:50-91,135-173) — v1 OpenPGP vs the
negotiated aead-batch-v1 wire (ISSUE 8, sync/aead.py).

Measures the full client sync legs through the public entry points:

- v1: CrdtMessage → protobuf content → SKESK‖SEIPD per message
  (`encrypt_messages`/`decrypt_messages`), batched C++ path and pure
  oracle. Per-message S2K (a fresh-salt 1KB SHA-256) is ~3µs/msg of
  irreducible format cost — the ceiling this bench exists to document.
- v2 (`aead-batch-v1`): ONE HKDF session key per owner, one small
  AES-256-GCM record per message — push leg via
  `encode_push_request_aead` (the fused C wire encoder the negotiated
  client uses), receive leg via `decrypt_response_columns` (fused
  wire→columns, production) and `decrypt_response` (object path).

Timing uses the SLOPE between two batch sizes (CLAUDE.md): per-message
marginal cost with every fixed cost — session HKDF, key schedule, call
overhead — cancelled, reported separately in the anatomy. Same
config-3 value mix as every crypto number in docs/BENCHMARKS.md.

`--smoke` runs a small-N oracle-parity gate for CI: native v2 bytes
must decrypt through the PURE oracle (and vice versa) to the exact
messages, and the v1 roundtrip must stay intact. Host-side by design:
values never touch the device. Prints one JSON line; numbers live in
docs/BENCHMARKS.md.
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from evolu_tpu.core.types import CrdtMessage
from evolu_tpu.sync import aead, native_crypto, protocol
from evolu_tpu.sync.client import (
    decrypt_messages,
    encrypt_messages,
    encrypt_messages_v2,
)

N = int(os.environ.get("CRYPTO_N", 100_000))
N_LO_FRAC = 0.2  # slope anchor: 20% of N
MNEMONIC = "legal winner thank year wave sausage worth useful legal winner thank yellow"
# r4 batched-C v1 reference points (config-3 mix, this 1-core host
# class) — the ISSUE-8 acceptance compares against these constants.
R4_V1 = {"enc": 195_000, "dec": 179_000}


def build_messages(n=N):
    # The config-3 value mix: short strings (titles), ints (flags/ids),
    # None (deletes) — what a todo-style client actually syncs.
    vals = [lambda i: f"todo item {i} ✓", lambda i: i % 2, lambda i: None,
            lambda i: f"note {i}: café", lambda i: i * 977]
    return tuple(
        CrdtMessage(
            f"2024-01-01T00:00:00.{i % 1000:03d}Z-{i % 16:04X}-a1b2c3d4e5f6{i % 256:02x}18",
            "todo", f"Tf9faXx1ryRXmPF6e_{i:06d}", "title", vals[i % 5](i),
        )
        for i in range(n)
    )


def timed(fn, *args):
    t0 = time.perf_counter()
    out = fn(*args)
    return out, time.perf_counter() - t0


def timed_best(fn, *args, reps=3):
    """min wall time over `reps` calls — the single-core host shares
    its hypervisor with noisy neighbors, and the minimum is the least-
    contended (most honest) observation of the code's own cost."""
    best = float("inf")
    for _ in range(reps):
        _, t = timed(fn, *args)
        best = min(best, t)
    return best


def slope_rate(fn, msgs, n_lo):
    """msgs/s from the SLOPE between a small and a full batch — fixed
    per-call costs (session key derivation, AES key schedule, ctypes
    dispatch) cancel; returns (msgs_per_sec, us_per_msg, fixed_ms)."""
    lo = msgs[:n_lo]
    t_lo = timed_best(fn, lo)
    t_hi = timed_best(fn, msgs)
    slope = (t_hi - t_lo) / (len(msgs) - n_lo)
    fixed = max(t_lo - slope * n_lo, 0.0)
    return round(1.0 / slope), round(slope * 1e6, 3), round(fixed * 1e3, 3)


def v2_encode_fn(session, uid="user-1", node="a1b2c3d4e5f60718", tree="{}"):
    def enc(msgs):
        body = native_crypto.encode_push_request_aead(
            msgs, session.key, session.salt, uid, node, tree)
        if body is None:  # no native leg: the pure loop is the product path
            return protocol.encode_sync_request(protocol.SyncRequest(
                encrypt_messages_v2(msgs, MNEMONIC), uid, node, tree))
        return body
    return enc


def response_bytes_for(enc_messages, tree='{"t":1}'):
    return protocol.encode_sync_response(
        protocol.SyncResponse(enc_messages, tree))


def check_parity(n=2000):
    """The oracle-parity gate (--smoke and every full run): cross
    decrypt between the C leg and the pure oracle, both formats."""
    msgs = build_messages(n)
    canon = msgs  # config-3 mix has no bools — decode is identity
    # v1 roundtrip (both paths produce interoperable OpenPGP).
    assert decrypt_messages(encrypt_messages(msgs, MNEMONIC), MNEMONIC) == canon
    # v2 pure encrypt → pure + fused decode.
    enc2 = encrypt_messages_v2(msgs, MNEMONIC)
    assert all(aead.is_v2_record(m.content) for m in enc2)
    assert decrypt_messages(enc2, MNEMONIC) == canon
    if native_crypto.native_available():
        fused = native_crypto.decrypt_response(
            response_bytes_for(enc2), MNEMONIC)
        assert fused is not None and fused[0] == canon, "fused v2 decode diverged"
        # native v2 encode → pure oracle decrypt (HKDF/GCM bit-parity).
        session = aead.get_session(MNEMONIC)
        body = v2_encode_fn(session)(msgs)
        req = protocol.decode_sync_request(body)
        assert len(req.messages) == n
        got = tuple(
            CrdtMessage(e.timestamp,
                        *protocol.decode_content(aead.decrypt_content(e.content, MNEMONIC)))
            for e in req.messages
        )
        assert got == canon, "native v2 encode diverged from the pure oracle"
        # fused columns path accepts the same wire (liveness; its full
        # parity gate lives in tests/test_wire_v2.py + test_packed_receive).
        packed = native_crypto.decrypt_response_columns(
            response_bytes_for(tuple(req.messages)), MNEMONIC)
        assert packed is not None
    return True


def main():
    smoke = "--smoke" in sys.argv
    check_parity()
    if smoke:
        print(json.dumps({
            "metric": "crypto_parity_smoke", "value": 1, "unit": "ok",
            "detail": {"n": 2000,
                       "native": native_crypto.native_available(),
                       "aead_native": native_crypto._AEAD_NATIVE},
        }))
        return

    msgs = build_messages()
    n_lo = int(N * N_LO_FRAC)
    results = {}

    from evolu_tpu.utils import native_loader

    for label in ("native", "pure"):
        if label == "pure":
            # Force the oracle: a None cache entry marks the library
            # unavailable, routing both legs pure.
            native_loader._cache["libevolu_crypto.so"] = None
        elif not native_crypto.native_available():
            continue
        enc, t_enc = timed(encrypt_messages, msgs, MNEMONIC)
        dec, t_dec = timed(decrypt_messages, enc, MNEMONIC)
        assert dec == msgs, f"{label} v1 roundtrip diverged"
        results[label] = {
            "encrypt_msgs_per_sec": round(N / t_enc),
            "decrypt_msgs_per_sec": round(N / t_dec),
            "encrypt_us_per_msg": round(t_enc * 1e6 / N, 2),
            "decrypt_us_per_msg": round(t_dec * 1e6 / N, 2),
        }
    native_loader._cache.pop("libevolu_crypto.so", None)  # restore

    # ---- aead-batch-v1 legs (the negotiated wire) ----
    aead.reset_sessions()
    t0 = time.perf_counter()
    session = aead.get_session(MNEMONIC)
    hkdf_ms = (time.perf_counter() - t0) * 1e3  # once per owner session

    enc_fn = v2_encode_fn(session)
    e_rate, e_us, e_fixed_ms = slope_rate(enc_fn, msgs, n_lo)
    # The columnar blob lane's Python-side cost, measured alone. When
    # the CPython-ABI lane (`ehc_aead_encrypt_push_py`) is live it
    # BYPASSES this pack entirely — then pack_us exceeds the whole
    # encode slope and is reported as the avoided fallback-lane cost,
    # not a share of the production leg.
    pack_rate, pack_us, _ = slope_rate(native_crypto._pack_columns, msgs, n_lo)
    py_lane = native_crypto._py_push_fn() is not None

    enc2 = protocol.decode_sync_request(enc_fn(msgs)).messages
    resp_full = response_bytes_for(enc2)
    resp_lo = response_bytes_for(enc2[:n_lo])

    def dec_cols(resp):
        out = native_crypto.decrypt_response_columns(resp, MNEMONIC)
        assert out is not None
        return out

    def dec_obj(resp):
        out = native_crypto.decrypt_response(resp, MNEMONIC)
        if out is None:  # pure path (no native leg)
            r = protocol.decode_sync_response(resp)
            return decrypt_messages(r.messages, MNEMONIC), r.merkle_tree
        return out

    def rate_over_responses(fn):
        t_lo = timed_best(fn, resp_lo)
        t_hi = timed_best(fn, resp_full)
        slope = (t_hi - t_lo) / (N - n_lo)
        return round(1.0 / slope), round(slope * 1e6, 3)

    have_native = native_crypto.native_available()
    d_obj_rate, d_obj_us = rate_over_responses(dec_obj)
    if have_native:
        d_cols_rate, d_cols_us = rate_over_responses(dec_cols)
    else:
        d_cols_rate, d_cols_us = d_obj_rate, d_obj_us

    # Pure v2 legs (fallback product path when the .so is absent).
    pure_n = min(N, 20_000)
    pure_msgs = msgs[:pure_n]
    _, t_p_enc = timed(encrypt_messages_v2, pure_msgs, MNEMONIC)
    p_enc2 = encrypt_messages_v2(pure_msgs, MNEMONIC)
    _, t_p_dec = timed(decrypt_messages, p_enc2, MNEMONIC) if not have_native else (
        None, None)
    if have_native:
        native_loader._cache["libevolu_crypto.so"] = None
        _, t_p_dec = timed(decrypt_messages, p_enc2, MNEMONIC)
        native_loader._cache.pop("libevolu_crypto.so", None)

    results["aead_v2"] = {
        "encrypt_msgs_per_sec": e_rate,
        "decrypt_msgs_per_sec": d_cols_rate,  # fused columns = production receive
        "decrypt_object_msgs_per_sec": d_obj_rate,
        "encrypt_us_per_msg": e_us,
        "decrypt_us_per_msg": d_cols_us,
        "decrypt_object_us_per_msg": d_obj_us,
        "speedup_vs_r4_v1": {
            "encrypt": round(e_rate / R4_V1["enc"], 2),
            "decrypt": round(d_cols_rate / R4_V1["dec"], 2),
            "decrypt_object": round(d_obj_rate / R4_V1["dec"], 2),
        },
        "anatomy": {
            # The whole point of the capability: the S2K → one HKDF.
            "hkdf_session_ms_once_per_owner": round(hkdf_ms, 4),
            "encode_fixed_ms_per_leg": e_fixed_ms,
            "cpython_abi_lane": py_lane,
            "blob_pack_us_per_msg": pack_us,
            "blob_pack_share_of_encode": (
                None if py_lane or not e_us else round(pack_us / e_us, 2)),
            "pure_fallback_encrypt_msgs_per_sec": round(pure_n / t_p_enc),
            "pure_fallback_decrypt_msgs_per_sec": round(pure_n / t_p_dec),
        },
    }

    head = results["aead_v2"]
    print(json.dumps({
        "metric": "crypto_v2_encrypt_msgs_per_sec",
        "value": head["encrypt_msgs_per_sec"],
        "unit": "msgs/sec",
        "detail": {"n": N, "slope_anchor": n_lo, "paths": results,
                   "r4_v1_reference": R4_V1},
    }))


if __name__ == "__main__":
    main()
