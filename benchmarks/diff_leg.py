"""The diff/response leg, isolated (VERDICT r3 missing #3 / next #6).

SURVEY §7's build plan floated a device-side Merkle diff
("argmin over diverging prefixes"); `core.merkle.diff_merkle_trees` is
a host Python walk (reference packages/evolu/src/merkleTree.ts:63-91).
This measures whether that walk — and the whole per-request response
leg at 1k DIVERGENT owners (the cold-ish worst case: every client is
missing the second half of its history) — is worth device work.

Components timed separately over the same store and requests:
  tree_read    — merkleTree row fetch + JSON parse per owner
  diff         — diff_merkle_trees(server, client) per owner
  fetch        — the `timestamp > since` SQL read + message decode
  respond_full — the real engine._respond (all of the above + protobuf)

Prints one JSON line; conclusions live in docs/BENCHMARKS.md.
"""

import json
import os
import statistics
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from evolu_tpu.core.merkle import (
    apply_prefix_xors,
    diff_merkle_trees,
    merkle_tree_from_string,
    merkle_tree_to_string,
    minute_deltas_host,
)
from evolu_tpu.server.engine import BatchReconciler
from evolu_tpu.server.relay import ShardedRelayStore
from evolu_tpu.sync import protocol
from benchmarks.config3_server_reconcile import build_requests, _ciphertext_pool

N = int(os.environ.get("DIFF_N", 1_000_000))
OWNERS = int(os.environ.get("DIFF_OWNERS", 1000))
SHARDS = int(os.environ.get("DIFF_SHARDS", 8))
TRIALS = int(os.environ.get("DIFF_TRIALS", 3))


def timed(fn):
    rates = []
    for _ in range(TRIALS):
        t0 = time.perf_counter()
        out = fn()
        rates.append(time.perf_counter() - t0)
    return out, statistics.median(rates)


def main():
    pool = _ciphertext_pool()
    requests = build_requests(n=N, owners=OWNERS, pool=pool)
    store = ShardedRelayStore(shards=SHARDS)
    engine = BatchReconciler(store)
    engine.reconcile(requests)  # populate: 1M rows, 1k owner trees

    # Divergent clients: each knows only the first half of its history.
    divergent = []
    for r in requests:
        half = sorted(m.timestamp for m in r.messages)[: len(r.messages) // 2]
        deltas, _ = minute_deltas_host(iter(half))
        client_tree = merkle_tree_to_string(apply_prefix_xors({}, deltas))
        divergent.append(protocol.SyncRequest((), r.user_id, "e" * 16, client_tree))

    server_trees = {}
    client_trees = {}

    def tree_read():
        for r in divergent:
            server_trees[r.user_id] = store.get_merkle_tree(r.user_id)
            client_trees[r.user_id] = merkle_tree_from_string(r.merkle_tree)
        return None

    _, t_tree = timed(tree_read)

    def diff_only():
        return [
            diff_merkle_trees(server_trees[r.user_id], client_trees[r.user_id])
            for r in divergent
        ]

    diffs, t_diff = timed(diff_only)
    assert all(d is not None for d in diffs), "every owner must diverge"

    def fetch_only():
        total = 0
        for r in divergent:
            total += len(
                store.get_messages(
                    r.user_id, r.node_id,
                    server_trees[r.user_id], client_trees[r.user_id],
                )
            )
        return total

    n_fetched, t_fetch = timed(fetch_only)

    def respond_full():
        # Empty trees dict = the cold-sync shape: _respond reads the
        # STORED tree strings (r4 path — no parse→re-dump round-trip).
        return engine._respond(divergent, {})

    responses, t_full = timed(respond_full)
    n_resp = sum(len(r.messages) for r in responses)
    assert n_resp == n_fetched

    def respond_wire():
        # r5 bytes-mode twin: the messages stream comes straight from C.
        return engine._respond_wire(divergent, {})

    wire, t_wire = timed(respond_wire)
    # Honesty check inside the bench: the fast path must be serving the
    # exact same bytes the object path would encode.
    assert wire[0] == protocol.encode_sync_response(responses[0])
    assert wire[-1] == protocol.encode_sync_response(responses[-1])

    # The server-pass yardstick: one full reconcile of the same 1M-push
    # batch on a fresh store (the thing the VERDICT's >=5% is against).
    fresh = ShardedRelayStore(shards=SHARDS)
    eng2 = BatchReconciler(fresh, engine.mesh)
    t0 = time.perf_counter()
    eng2.reconcile(requests)
    t_pass = time.perf_counter() - t0

    print(json.dumps({
        "metric": "diff_response_leg_ms_per_1k_divergent_owners",
        "value": round(t_full * 1e3, 1),
        "unit": "ms",
        "detail": {
            "owners": len(divergent), "rows": N,
            "messages_served": n_resp,
            "tree_read_ms": round(t_tree * 1e3, 1),
            "diff_ms": round(t_diff * 1e3, 1),
            "fetch_ms": round(t_fetch * 1e3, 1),
            "respond_full_ms": round(t_full * 1e3, 1),
            "respond_wire_ms": round(t_wire * 1e3, 1),
            "respond_full_msgs_per_sec": round(n_resp / t_full),
            "respond_wire_msgs_per_sec": round(n_resp / t_wire),
            "respond_wire_speedup": round(t_full / t_wire, 2),
            "diff_us_per_owner": round(t_diff * 1e6 / len(divergent), 1),
            "server_pass_ms": round(t_pass * 1e3, 1),
            "diff_pct_of_pass": round(100 * t_diff / t_pass, 2),
            "respond_pct_of_pass": round(100 * t_full / t_pass, 2),
            "trials": TRIALS,
        },
    }))
    store.close(), fresh.close(), engine.close(), eng2.close()


if __name__ == "__main__":
    main()
