"""Owner-sharded relay fleet: process-count ingest scaling + byte
identity + live rebalance (server/fleet.py).

The claim behind the fleet tier: the full-system wall is HOST-bound
(SQLite btree ~0.72M rows/s/core, one Python process ≈ one core), so
partitioning owners across N relay PROCESSES should scale aggregate
ingest with process count while keeping every owner's end state
byte-identical to a single relay — and a ring change should move
owners between relays with zero lost ACKed writes, cut over at the
per-owner Merkle watermark.

Measured here directly, with REAL processes (each leg spawns
`python -m evolu_tpu.server.fleet` workers — plain subprocesses, one
store each, scoped gossip between them) and M client threads pushing a
Zipf-skewed owner workload through the real routing path (random
first relay, learned 307 routes, 503-backoff retries):

* leg `single`: 1 relay ingests the whole workload → the oracle state
  (per-owner tree text + row crc) and the baseline msgs/s.
* leg `fleet`: N relays, same workload → aggregate msgs/s, then every
  owner's PRIMARY state — and each of its R replicas after gossip —
  must be byte-identical to the oracle.
* leg `rebalance`: relay N+1 joins via `POST /fleet/reload` WHILE a
  writer keeps pushing; moved owners snapshot-install on the gainer
  and cut over at the watermark (counter-asserted via /stats), and
  every ACKed write must exist in the final fleet state.

HONESTY (docs/BENCHMARKS.md): thread overlap inside one Python
process is serial — the scaling assertion (aggregate >= 2x single for
3 processes) is only asserted when `os.cpu_count()` actually offers a
core per relay; on a 1-core container the measured ratio is reported
as-is (expect ~1x — the point of the bench is that the LIMIT moves
from "one process" to "core count"). Correctness assertions
(byte-identity, zero lost ACKs, watermark cutover) always run.

Prints ONE JSON line. `--smoke` runs a tiny 2-relay CI pass.
"""

import argparse
import json
import os
import random
import socket
import subprocess
import sys
import threading
import time
import urllib.error
import urllib.request
import zlib

os.environ["JAX_PLATFORMS"] = "cpu"
for _v in ("PALLAS_AXON_POOL_IPS", "PALLAS_AXON_REMOTE_COMPILE"):
    os.environ.pop(_v, None)

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)

from evolu_tpu.core.timestamp import Timestamp, timestamp_to_string  # noqa: E402
from evolu_tpu.server.fleet import HashRing  # noqa: E402
from evolu_tpu.sync import protocol  # noqa: E402
from evolu_tpu.sync.client import _http_post  # noqa: E402
from evolu_tpu.utils.config import FleetConfig  # noqa: E402

BASE = 1_700_000_000_000
NODE = "00000000000000bb"


# -- fleet-of-processes harness --


def _free_ports(n):
    socks = []
    for _ in range(n):
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        socks.append(s)
    ports = [s.getsockname()[1] for s in socks]
    for s in socks:
        s.close()
    return ports


class FleetProcs:
    """N `python -m evolu_tpu.server.fleet` worker processes sharing
    one FleetConfig."""

    def __init__(self, n, version=1, seed=0, repl_interval=0.25):
        self.ports = _free_ports(n)
        self.urls = [f"http://127.0.0.1:{p}" for p in self.ports]
        self.seed = seed
        self.repl_interval = repl_interval
        self.config = FleetConfig(relays=tuple(self.urls), version=version,
                                  replication_factor=min(2, n), seed=seed)
        self.procs = []
        for port, url in zip(self.ports, self.urls):
            self.procs.append(self._spawn(port, url, self.config))
        self._await_ready(self.procs)

    def _spawn(self, port, url, config):
        env = dict(os.environ)
        env["PYTHONPATH"] = (
            _REPO + (os.pathsep + env["PYTHONPATH"]
                     if env.get("PYTHONPATH") else "")
        )
        return subprocess.Popen(
            [sys.executable, "-m", "evolu_tpu.server.fleet",
             "--port", str(port), "--self-url", url,
             "--config-json", json.dumps(config.to_json()),
             "--replication-interval-s", str(self.repl_interval)],
            env=env, stdout=subprocess.PIPE, text=True,
        )

    def _await_ready(self, procs, timeout=60):
        import select

        waiting = {p.stdout.fileno(): p for p in procs}
        deadline = time.time() + timeout
        while waiting and time.time() < deadline:
            dead = [p for p in procs if p.poll() is not None]
            if dead:
                raise RuntimeError(
                    f"{len(dead)} fleet workers exited at startup "
                    f"(rc={[p.returncode for p in dead]})"
                )
            ready, _, _ = select.select(list(waiting), [], [], 0.2)
            for fd in ready:
                if "READY" in waiting[fd].stdout.readline():
                    del waiting[fd]
        if waiting:
            raise RuntimeError(f"{len(waiting)} fleet workers did not come up")

    def join(self, version):
        """Spawn one MORE relay and push the grown ring to EVERY
        member (the static config reload) → the new member's url.
        Order matters: the SURVIVORS reload first (their scoped
        summaries must know the new ring before the joiner asks), the
        joiner's reload comes last — a reconcile push that kicks its
        snapshot rebalance sweep."""
        (port,) = _free_ports(1)
        url = f"http://127.0.0.1:{port}"
        old_urls = list(self.urls)
        self.urls.append(url)
        self.ports.append(port)
        new_cfg = FleetConfig(
            relays=tuple(self.urls), version=version,
            replication_factor=min(2, len(self.urls)), seed=self.seed,
        )
        proc = self._spawn(port, url, new_cfg)
        self.procs.append(proc)
        self._await_ready([proc])
        body = json.dumps(new_cfg.to_json()).encode()
        for u in old_urls + [url]:
            req = urllib.request.Request(u + "/fleet/reload", data=body,
                                         method="POST")
            with urllib.request.urlopen(req, timeout=10) as r:
                r.read()
        self.config = new_cfg
        return url

    def all_serving(self):
        for u in self.urls:
            try:
                with urllib.request.urlopen(u + "/health", timeout=5) as r:
                    if r.status != 200:
                        return False
            except urllib.error.HTTPError:
                return False
            except OSError:
                return False
        return True

    def stats(self, url):
        with urllib.request.urlopen(url + "/stats", timeout=10) as r:
            return json.loads(r.read())

    def stop(self):
        for p in self.procs:
            if p.poll() is None:
                p.terminate()
        for p in self.procs:
            try:
                p.wait(timeout=10)
            except Exception:  # noqa: BLE001 - wedged: escalate AND reap
                p.kill()
                p.wait(timeout=10)
        self.procs = []


# -- workload --


def _zipf_counts(owners, total, s, rng):
    w = [1.0 / (i + 1) ** s for i in range(owners)]
    z = sum(w)
    counts = [max(1, int(total * wi / z)) for wi in w]
    while sum(counts) > total:
        counts[counts.index(max(counts))] -= 1
    i = 0
    while sum(counts) < total:
        counts[i % owners] += 1
        i += 1
    rng.shuffle(counts)
    return counts


def _build_workload(owners, total, batch, zipf_s, seed, t0=0):
    """→ (requests, per_owner_timestamps): requests are
    (owner_id, encoded SyncRequest body, n_messages), shuffled."""
    rng = random.Random(seed)
    counts = _zipf_counts(owners, total, zipf_s, rng)
    requests = []
    per_owner = {}
    for k in range(owners):
        uid = f"owner{k:04d}"
        ts = [
            timestamp_to_string(Timestamp(BASE + (t0 + j) * 500, 0,
                                          f"{k + 1:016x}"))
            for j in range(counts[k])
        ]
        per_owner[uid] = ts
        for i in range(0, len(ts), batch):
            chunk = ts[i : i + batch]
            msgs = tuple(
                protocol.EncryptedCrdtMessage(t, b"ct-%d-%s" % (k, t[:29].encode()))
                for t in chunk
            )
            requests.append((uid, protocol.encode_sync_request(
                protocol.SyncRequest(msgs, uid, NODE, "{}")), len(chunk)))
    rng.shuffle(requests)
    return requests, per_owner


def _ingest(requests, relay_urls, threads, deadline_s=600):
    """Push every request through the real routing path: random first
    relay, follow 307s (cache the learned route), ride _http_post's
    429/503/connection backoff, retry rounds until ACKed. → (wall_s,
    acked dict owner→msgs)."""
    routes = {}
    acked = {}
    lock = threading.Lock()
    idx = {"i": 0}
    errors = []

    def worker(tid):
        rng = random.Random(1000 + tid)
        while True:
            with lock:
                i = idx["i"]
                if i >= len(requests):
                    return
                idx["i"] = i + 1
            uid, body, n = requests[i]
            stop_at = time.time() + deadline_s
            while True:
                url = routes.get(uid) or rng.choice(relay_urls) + "/"
                try:
                    _http_post(url, body)
                    with lock:
                        acked[uid] = acked.get(uid, 0) + n
                    break
                except urllib.error.HTTPError as e:
                    loc = e.headers.get("Location") if e.headers else None
                    if e.code == 307 and loc:
                        routes[uid] = loc
                        continue
                    routes.pop(uid, None)
                    if time.time() > stop_at:
                        errors.append((uid, repr(e)))
                        return
                    time.sleep(0.05)
                except OSError as e:
                    routes.pop(uid, None)
                    if time.time() > stop_at:
                        errors.append((uid, repr(e)))
                        return
                    time.sleep(0.05)

    t0 = time.perf_counter()
    ts = [threading.Thread(target=worker, args=(t,)) for t in range(threads)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    wall = time.perf_counter() - t0
    if errors:
        raise RuntimeError(f"{len(errors)} requests never ACKed: {errors[:3]}")
    return wall, acked


# -- state readback (the oracle comparison surface) --


def _owner_state(url, uid):
    """(tree text, rows crc32, row count) for one owner as served by
    `url`, read through the replication pull (empty peer_url = the
    unscoped oracle read), looping past the per-owner response cap."""
    crc = 0
    count = 0
    since = ""
    tree = ""
    while True:
        body = protocol.encode_replica_pull(
            protocol.ReplicaPull(((uid, since),), "bench-read"))
        resp = protocol.decode_replica_pull_response(
            _http_post(url + "/replicate/pull", body))
        if not resp.chunks:
            break
        om = resp.chunks[0]
        tree = om.merkle_tree
        if not om.messages:
            break
        for m in om.messages:
            crc = zlib.crc32(m.timestamp.encode(), crc)
            crc = zlib.crc32(m.content, crc)
            count += 1
        since = om.messages[-1].timestamp
    return tree, crc, count


def _await(predicate, deadline_s, what):
    deadline = time.time() + deadline_s
    while time.time() < deadline:
        if predicate():
            return
        time.sleep(0.2)
    raise RuntimeError(f"timed out waiting for {what}")


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("--smoke", action="store_true",
                    help="tiny 2-relay CI pass (correctness only)")
    ap.add_argument("--relays", type=int, default=3)
    ap.add_argument("--owners", type=int, default=32)
    ap.add_argument("--messages", type=int, default=24_000)
    ap.add_argument("--batch", type=int, default=64)
    ap.add_argument("--threads", type=int, default=8)
    ap.add_argument("--zipf", type=float, default=1.1)
    args = ap.parse_args()

    if args.smoke:
        args.relays, args.owners, args.messages = 2, 12, 1_500
        args.batch, args.threads = 32, 4
    cpus = os.cpu_count() or 1
    assert_scaling = (not args.smoke) and cpus >= args.relays

    requests, per_owner = _build_workload(
        args.owners, args.messages, args.batch, args.zipf, seed=42)
    owners = sorted(per_owner)
    total = sum(len(v) for v in per_owner.values())

    # -- leg 1: single relay (the oracle) --
    single = FleetProcs(1)
    try:
        wall, acked = _ingest(requests, single.urls, args.threads)
        assert sum(acked.values()) == total
        oracle = {uid: _owner_state(single.urls[0], uid) for uid in owners}
        single_rate = total / wall
        leg_single = {"relays": 1, "wall_s": round(wall, 3),
                      "msgs_per_s": round(single_rate)}
    finally:
        single.stop()
    for uid in owners:
        assert oracle[uid][2] == len(per_owner[uid]), uid

    # -- leg 2: N-relay fleet, same workload --
    fleet = FleetProcs(args.relays)
    try:
        ring = HashRing(fleet.config)
        wall, acked = _ingest(requests, fleet.urls, args.threads)
        assert sum(acked.values()) == total
        fleet_rate = total / wall
        # Byte-identity at EVERY placed relay. Any of an owner's R
        # placed relays accepts its writes locally (multi-master
        # within the replica set — a client's random first relay may
        # be the replica, not the primary), so identity is asserted at
        # the scoped-gossip fixpoint, primary and replica alike.
        def replicas_converged():
            for uid in owners:
                for url in ring.placement(uid):
                    if _owner_state(url, uid) != oracle[uid]:
                        return False
            return True

        _await(replicas_converged, 120, "replica gossip convergence")
        # Scoped replication: a non-placed relay must NOT hold a copy.
        strays = 0
        for uid in owners:
            for url in fleet.urls:
                if url not in ring.placement(uid):
                    if _owner_state(url, uid)[2] != 0:
                        strays += 1
        assert strays == 0, f"{strays} owner copies outside placement"

        # -- leg 3: ring change under live writes. The live writer
        # covers only the FIRST HALF of the owner ids: live writes
        # landing on the joiner before its sweep legitimately divert
        # those owners to the gossip-drain path, so keeping half the
        # owners quiet guarantees (whenever any quiet owner moves)
        # that the snapshot-install path is exercised too. --
        extra_reqs, extra_owner = _build_workload(
            max(2, args.owners // 2), max(args.owners * 8, total // 10),
            args.batch, args.zipf, seed=43, t0=10**6)
        writer_out = {}

        def writer():
            try:
                writer_out["result"] = _ingest(extra_reqs, fleet.urls,
                                               max(2, args.threads // 2))
            except BaseException as e:  # noqa: BLE001 - re-raised after
                # join: a thread-swallowed failure here would otherwise
                # surface as an unrelated KeyError masking the real
                # "requests never ACKed" diagnosis.
                writer_out["error"] = e

        wt = threading.Thread(target=writer)
        wt.start()
        new_url = fleet.join(version=2)
        new_ring = HashRing(fleet.config)
        moved = [uid for uid in owners
                 if new_ring.primary(uid) != ring.primary(uid)]
        _await(fleet.all_serving, 120, "post-reload readiness")
        wt.join()
        if "error" in writer_out:
            raise writer_out["error"]
        _wall2, acked2 = writer_out["result"]
        assert sum(acked2.values()) == sum(len(v) for v in extra_owner.values())
        expected = {
            uid: sorted(per_owner[uid] + extra_owner.get(uid, []))
            for uid in owners
        }

        def rebalance_converged():
            for uid in owners:
                tree, crc, count = _owner_state(new_ring.primary(uid), uid)
                if count != len(expected[uid]):
                    return False
            return True

        _await(rebalance_converged, 180, "rebalance + gossip convergence")
        # Zero lost ACKed writes: every ACKed message is present at
        # the owner's (new) primary — exact count per owner, crc per
        # owner recomputable from the known message set.
        for uid in owners:
            want_crc = 0
            k = int(uid[5:])
            for t in expected[uid]:
                want_crc = zlib.crc32(t.encode(), want_crc)
                want_crc = zlib.crc32(b"ct-%d-%s" % (k, t[:29].encode()),
                                      want_crc)
            _tree, crc, count = _owner_state(new_ring.primary(uid), uid)
            assert count == len(expected[uid]), uid
            assert crc == want_crc, f"{uid}: rows diverged after rebalance"
        # Counter-asserted snapshot cutover at the Merkle watermark:
        # every snapshot-installed owner passed the cutover gate
        # (verified = byte-equal to the donor watermark; superset =
        # concurrent gossip rows on top — both safe-to-serve states).
        # An owner a live write reached FIRST drains via gossip
        # instead — designed degradation, not loss — so the >=1
        # install assertion is gated on a QUIET owner having moved
        # (the port-derived ring makes placement run-dependent; a
        # moved-nothing draw is reported, not failed).
        moved_to_new = [uid for uid in owners
                        if new_ring.primary(uid) == new_url]
        # Any placement on the joiner (primary OR replica) installs.
        quiet_moved = [uid for uid in owners
                       if uid not in extra_owner
                       and new_url in new_ring.placement(uid)]
        gain_stats = fleet.stats(new_url)["fleet"]
        if quiet_moved:
            assert gain_stats["rebalanced_owners"] >= 1, gain_stats
        assert (gain_stats["cutovers_verified"]
                + gain_stats["cutovers_superset"]) \
            >= gain_stats["rebalanced_owners"], gain_stats
        leg_rebalance = {
            "joined": new_url,
            "owners_moved": len(moved),
            "moved_to_new_relay": len(moved_to_new),
            "rebalanced_owners": gain_stats["rebalanced_owners"],
            "rebalanced_messages": gain_stats["rebalanced_messages"],
            "cutovers_verified": gain_stats["cutovers_verified"],
            "cutovers_superset": gain_stats["cutovers_superset"],
            "live_writes_acked": sum(acked2.values()),
            "lost_acked_writes": 0,
        }
    finally:
        fleet.stop()

    ratio = fleet_rate / single_rate
    if assert_scaling:
        assert ratio >= 2.0, (
            f"aggregate fleet ingest only {ratio:.2f}x the single relay "
            f"with {args.relays} processes on {cpus} cores"
        )
    print(json.dumps({
        "metric": "fleet_scaling_ratio",
        "value": round(ratio, 2),
        "unit": f"x single-relay ingest ({args.relays} relay processes)",
        "detail": {
            "messages": total,
            "owners": args.owners,
            "zipf_s": args.zipf,
            "batch": args.batch,
            "client_threads": args.threads,
            "cpus": cpus,
            "scaling_asserted": assert_scaling,
            "smoke": bool(args.smoke),
            "single": leg_single,
            "fleet": {"relays": args.relays, "wall_s": round(wall, 3),
                      "msgs_per_s": round(fleet_rate),
                      "byte_identical_to_oracle": True,
                      "strays_outside_placement": 0},
            "rebalance": leg_rebalance,
        },
    }))


if __name__ == "__main__":
    main()
