"""Capture a jax.profiler trace of the merge kernel pipeline (ISSUE 4,
VERDICT #7): one warm + N profiled reconcile dispatches through BOTH
plan formulations (sort and scatter shard kernels), with the span
trace-annotations enabled so host-side phases appear in the timeline
under the same `kernel:*` target names the log/metrics surfaces use.

Usage: python benchmarks/kernel_trace.py [outdir]
Default outdir: docs/traces/kernel_pipeline (the checked-in evidence
base for the BENCHMARKS.md anatomy claims — the device rows show the
sort/scan vs scatter/gather op mix directly).

Prints one JSON line: {outdir, platform, iters, per-variant wall ms}.
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import numpy as np

jax.config.update("jax_enable_x64", True)

from evolu_tpu.utils.log import enable_trace_annotations, span

N = int(os.environ.get("TRACE_N", 1 << 14))
ITERS = int(os.environ.get("TRACE_ITERS", 2))
# Checked-in evidence: keep the perfetto .trace.json.gz (human-viewable
# at ui.perfetto.dev) and DROP the raw .xplane.pb, which is 20-25× the
# size (TensorBoard's source form; regenerate locally when needed).
KEEP_XPLANE = os.environ.get("TRACE_KEEP_XPLANE") == "1"


def main():
    import bench
    from evolu_tpu.ops import to_host_many
    from evolu_tpu.ops.merge import _PAD_CELL
    from evolu_tpu.ops.scatter_merge import table_size_for
    from evolu_tpu.parallel.mesh import create_mesh, sharding
    from evolu_tpu.parallel.reconcile import (
        _compiled_kernel,
        _shard_kernel,
        scatter_shard_kernel,
    )

    outdir = sys.argv[1] if len(sys.argv) > 1 else os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "docs", "traces", "kernel_pipeline",
    )
    enable_trace_annotations(True)
    mesh = create_mesh()
    n_dev = mesh.devices.size
    cols, _ = bench.shard_layout(
        bench.build_columns(n=N, owners=256, stored_winners=True), n_dev
    )
    real = cols["cell_id"] != int(_PAD_CELL)
    table = table_size_for(int(cols["cell_id"].max(initial=0, where=real)))
    shd = sharding(mesh)
    names = ("cell_id", "k1", "k2", "ex_k1", "ex_k2", "owner_ix")
    variants = {
        "sort": _compiled_kernel(mesh, _shard_kernel),
        "scatter": _compiled_kernel(mesh, scatter_shard_kernel(table)),
    }
    walls = {}
    with jax.enable_x64(True):
        args = [jax.device_put(cols[k], shd) for k in names]
        for label, fn in variants.items():
            to_host_many(*fn(*args))  # compile + warm outside the trace
        with jax.profiler.trace(outdir):
            for label, fn in variants.items():
                t0 = time.perf_counter()
                for _ in range(ITERS):
                    with span("kernel:reconcile", f"trace:{label}", n=N):
                        to_host_many(*fn(*args))
                walls[label] = round((time.perf_counter() - t0) / ITERS * 1e3, 2)
    if not KEEP_XPLANE:
        for root, _dirs, files in os.walk(outdir):
            for f in files:
                if f.endswith(".xplane.pb"):
                    os.unlink(os.path.join(root, f))
    print(json.dumps({
        "outdir": outdir,
        "platform": jax.devices()[0].platform,
        "n": N,
        "iters": ITERS,
        # NOTE: walls here are measured UNDER the profiler and are
        # heavily inflated for op-dense graphs (the CPU scatter
        # lowering emits orders of magnitude more trace events than
        # the sort) — the honest wall numbers are the slope method in
        # benchmarks/scatter_vs_sort.py; this tool is for ANATOMY.
        "wall_ms_per_dispatch_under_profiler": walls,
    }))


if __name__ == "__main__":
    main()
