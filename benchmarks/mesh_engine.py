"""Mesh-sharded engine scaling: msgs/s vs device count (ISSUE 12).

One child process per device count (1 → 2 → 4 → 8 virtual CPU
devices; each child re-enters this file with
`--xla_force_host_platform_device_count=N` and the axon tunnel vars
stripped, so this never claims the real chip): the child drives
`BatchReconciler.run_batch_wire` with a `MeshContext` (stable
owner→device placement — the sharded engine path) over deterministic
multi-owner push+pull rounds.

Method (CLAUDE.md timing discipline): per child, the SLOPE between a
low and a high round count on fresh stores after a jit warmup —
msgs/s = Δmsgs/Δwall, so compile/setup cancels. EVERY response byte
folds into a crc32 checksum that is printed (liveness: no serving leg
can be skipped unnoticed), and the child asserts the PARITY GATE —
responses + SQLite end state byte-identical to a SINGLE-DEVICE plain
engine — before any number is reported. The parent additionally pins
the final-store checksum identical across all device counts.

HONESTY: this container is 1-core. The virtual CPU mesh shares that
core, so the msgs/s-vs-devices slope here measures sharding OVERHEAD
(layout, padding, collective emulation), not ICI speedup — flat-to-
slightly-down is the expected CPU shape. The TPU slope is the claim
this bench exists to measure and is QUEUED BEHIND TUNNEL ACCESS
(docs/BENCHMARKS.md r12).

Prints ONE JSON line. `--smoke` runs devices (1, 2) with a tiny
workload — the CI parity gate.
"""

import json
import os
import re
import subprocess
import sys
import time
import zlib

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

DEVICES = (1, 2, 4, 8)
SMOKE = "--smoke" in sys.argv

OWNERS = 16 if SMOKE else 48
BATCH_OWNERS = 8 if SMOKE else 16
MSGS = 6 if SMOKE else 20
ROUNDS_LO, ROUNDS_HI = (1, 2) if SMOKE else (2, 6)
BASE = 1_700_000_000_000


def _child_env(n_devices: int) -> dict:
    env = dict(os.environ)
    for v in ("PALLAS_AXON_POOL_IPS", "PALLAS_AXON_REMOTE_COMPILE"):
        env.pop(v, None)
    env["JAX_PLATFORMS"] = "cpu"
    flags = re.sub(r"--xla_force_host_platform_device_count=\d+", "",
                   env.get("XLA_FLAGS", ""))
    env["XLA_FLAGS"] = (
        flags + f" --xla_force_host_platform_device_count={n_devices}"
    ).strip()
    env["_MESH_BENCH_CHILD"] = str(n_devices)
    return env


def _rounds():
    """Deterministic traffic: every round, BATCH_OWNERS-owner batches
    pushing fresh windows (with one overlapping duplicate row per
    owner after round 0) and pulling against an empty client tree."""
    from evolu_tpu.core.timestamp import Timestamp, timestamp_to_string
    from evolu_tpu.sync import protocol

    def msgs(node, start, n):
        return tuple(
            protocol.EncryptedCrdtMessage(
                timestamp_to_string(Timestamp(BASE + (start + i) * 1000, 0, node)),
                b"ct%d" % (start + i),
            )
            for i in range(n)
        )

    out = []
    for rnd in range(ROUNDS_HI):
        batches = []
        for b0 in range(0, OWNERS, BATCH_OWNERS):
            reqs = []
            for i in range(b0, min(b0 + BATCH_OWNERS, OWNERS)):
                node = f"{i + 1:016x}"
                start = max(rnd * (MSGS - 1), 0)  # 1-row overlap per round
                reqs.append(protocol.SyncRequest(
                    msgs(node, start, MSGS), f"bench-u{i:03d}", node, "{}"
                ))
            batches.append(tuple(reqs))
        out.append(batches)
    return out


def _store_crc(store) -> int:
    crc = 0
    for s in store.shards:
        for row in s.db.exec(
            'SELECT "timestamp", "userId", "content" FROM "message" '
            'ORDER BY "timestamp", "userId"'
        ):
            crc = zlib.crc32(repr(row).encode(), crc)
        for row in s.db.exec(
            'SELECT "userId", "merkleTree" FROM "merkleTree" ORDER BY "userId"'
        ):
            crc = zlib.crc32(repr(row).encode(), crc)
    return crc


def _drive(engine_factory, rounds_n, traffic):
    """Serve `rounds_n` rounds on a FRESH store; → (wall_s, msgs, crc,
    store_crc)."""
    from evolu_tpu.server.relay import ShardedRelayStore

    store = ShardedRelayStore(shards=4)
    eng = engine_factory(store)
    crc = 0
    n_msgs = 0
    t0 = time.perf_counter()
    try:
        for rnd in range(rounds_n):
            for reqs in traffic[rnd]:
                for w in eng.run_batch_wire(reqs):
                    crc = zlib.crc32(w, crc)
                n_msgs += sum(len(r.messages) for r in reqs)
        wall = time.perf_counter() - t0
        return wall, n_msgs, crc, _store_crc(store)
    finally:
        eng.close()
        store.close()


def child(n_devices: int) -> None:
    import jax

    assert len(jax.devices()) == n_devices, (jax.devices(), n_devices)
    from evolu_tpu.parallel.mesh import MeshContext, create_mesh
    from evolu_tpu.server.engine import BatchReconciler

    ctx = MeshContext()
    assert ctx.n_shards == n_devices
    traffic = _rounds()

    def mesh_engine(store):
        return BatchReconciler(store, mesh_ctx=ctx)

    def single_engine(store):
        return BatchReconciler(store, mesh=create_mesh(1))

    # Parity gate FIRST (fresh stores, full traffic): sharded responses
    # and end state byte-identical to the single-device plain engine.
    from evolu_tpu.server.relay import ShardedRelayStore

    ms, ss = ShardedRelayStore(shards=4), ShardedRelayStore(shards=4)
    me, se = mesh_engine(ms), single_engine(ss)
    try:
        for rnd in range(ROUNDS_HI):
            for reqs in traffic[rnd]:
                assert me.run_batch_wire(reqs) == se.run_batch_wire(reqs), (
                    "PARITY GATE FAILED: sharded responses != single-device"
                )
        assert _store_crc(ms) == _store_crc(ss), (
            "PARITY GATE FAILED: sharded end state != single-device"
        )
    finally:
        me.close()
        se.close()
        ms.close()
        ss.close()

    # Slope: warmup (compiles every bucket), then lo and hi rounds.
    _drive(mesh_engine, 1, traffic)
    wall_lo, msgs_lo, _crc_lo, _ = _drive(mesh_engine, ROUNDS_LO, traffic)
    wall_hi, msgs_hi, crc_hi, store_crc = _drive(mesh_engine, ROUNDS_HI, traffic)
    slope = (msgs_hi - msgs_lo) / max(wall_hi - wall_lo, 1e-9)
    print(json.dumps({
        "devices": n_devices,
        "msgs_per_s_slope": round(slope, 1),
        "wall_lo_s": round(wall_lo, 4), "wall_hi_s": round(wall_hi, 4),
        "msgs_hi": msgs_hi,
        "response_crc": crc_hi,
        "store_crc": store_crc,
        "parity": "ok",
    }))


def main() -> None:
    devices = DEVICES[:2] if SMOKE else DEVICES
    results = []
    for n in devices:
        proc = subprocess.run(
            [sys.executable, os.path.abspath(__file__)]
            + (["--smoke"] if SMOKE else []),
            env=_child_env(n), stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT, text=True, timeout=1800,
        )
        if proc.returncode != 0:
            sys.stdout.write(proc.stdout)
            raise SystemExit(f"mesh bench child ({n} devices) failed")
        results.append(json.loads(proc.stdout.strip().splitlines()[-1]))
    # End state must be IDENTICAL across device counts (the cross-
    # device-count half of the parity claim).
    crcs = {r["store_crc"] for r in results}
    assert len(crcs) == 1, f"end state diverged across device counts: {results}"
    print(json.dumps({
        "bench": "mesh_engine",
        "smoke": SMOKE,
        "platform": "cpu-1core-virtual-mesh (TPU slope queued behind tunnel)",
        "rounds": [ROUNDS_LO, ROUNDS_HI],
        "owners": OWNERS,
        "per_request_msgs": MSGS,
        "store_crc": results[0]["store_crc"],
        "slope_msgs_per_s_by_devices": {
            str(r["devices"]): r["msgs_per_s_slope"] for r in results
        },
        "parity": "ok",
    }))


if __name__ == "__main__":
    if os.environ.get("_MESH_BENCH_CHILD"):
        child(int(os.environ["_MESH_BENCH_CHILD"]))
    else:
        main()
