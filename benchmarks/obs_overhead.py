"""Instrumentation overhead on the 1M-row reconcile path (slope method).

Acceptance gate for the observability PR: metrics must cost <=1% of the
1M-row reconcile. Two measurements, both per CLAUDE.md's slope rule
(never divide one wall time by its count — fixed overhead buries the
result):

1. The DEVICE leg is untouched by construction (obs never imports jax,
   tests/test_bench_liveness.py pins checksum + jit-cache equality), so
   the only possible cost is the HOST-side instrumentation sequence per
   batch. Measure exactly that sequence — the per-batch counter incs,
   histogram observes, span bookkeeping and flight append that
   `reconcile_owner_batches` + `plan_batch`-level code execute — via
   the slope between two repetition counts.

2. Anchor it against the measured per-batch reconcile wall time on this
   platform (the same two-point slope over fused iterations bench.py
   uses), and report the ratio.

Prints one JSON line.
"""

import json
import os
import statistics
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import numpy as np

import bench
from evolu_tpu.obs import anatomy, flight, ledger, metrics
from evolu_tpu.utils.log import logger

REPS_LO, REPS_HI = 200, 2000
ITERS_LO, ITERS_HI = 2, 10

# Conservation-ledger + sentinel gate (ISSUE 15): their combined
# per-batch cost must stay <= 0.1% of the config-2 reconcile leg.
LEDGER_GATE_FRACTION = 0.001


def instrumentation_sequence():
    """The host-side metric work ONE 1M-row reconcile batch performs:
    reconcile batch/owner observes + 8 shard-size observes + kernel
    routing counter (reconcile.py), one span close (histogram observe +
    flight append + duration aggregate, utils/log.py + obs), and the
    apply-route counter (apply.py). Deliberately a superset of the
    steady-state count."""
    metrics.observe("evolu_reconcile_batch_rows", 1_000_000,
                    buckets=metrics.COUNT_BUCKETS)
    metrics.observe("evolu_reconcile_batch_owners", 1_000,
                    buckets=metrics.COUNT_BUCKETS)
    for _ in range(8):
        metrics.observe("evolu_reconcile_shard_rows", 125_000,
                        buckets=metrics.COUNT_BUCKETS)
    metrics.inc("evolu_reconcile_kernel_total", variant="packed")
    metrics.inc("evolu_apply_batches_total", route="object")
    metrics.observe("evolu_kernel_span_ms", 12.5, target="kernel:reconcile")
    flight.record("kernel:reconcile", "batch", n=1_000_000)
    metrics.inc("evolu_winner_cache_hits_total", 250_000)
    metrics.inc("evolu_winner_cache_misses_total", 0)
    metrics.set_gauge("evolu_winner_cache_streaming", 0)


_OWNERS = [f"owner{i:04d}" for i in range(32)]


def ledger_sentinel_sequence():
    """The ledger + sentinel work ONE config-2 engine pass performs
    (32 requests / 32 owners / 1M rows): per-request relay ingress
    counts, the pass's pending-entry terminal classification, the
    recompile-sentinel gauge refresh, and the tunnel-pull wave
    instrumentation. Deliberately a superset (real passes skip
    zero-count stations for free)."""
    for o in _OWNERS:
        ledger.count(ledger.INGRESS_SYNC, 31250, owner=o)
    entry = ledger.pending()
    for o in _OWNERS:
        entry.count(ledger.STORE_INSERTED, 31250, owner=o)
        entry.count(ledger.STORE_DUPLICATE, 0, owner=o)
    entry.commit()
    # Recompile sentinel: two cache gauges + the flat-diff bookkeeping.
    metrics.set_gauge("evolu_jit_cache_size", 7, cache="merkle")
    metrics.set_gauge("evolu_jit_cache_size", 0, cache="mesh")
    # Tunnel-bandwidth plane: one output wave of the merkle kernel.
    metrics.inc("evolu_pull_bytes_total", 48_000_000)
    metrics.inc("evolu_pull_seconds_total", 3.0)
    metrics.observe("evolu_pull_wave_bytes", 48_000_000,
                    buckets=metrics.SIZE_BUCKETS)


def anatomy_sequence():
    """The stage-anatomy accounting ONE config-2 engine pass performs
    (ISSUE 16): the three runtime seam records (device dispatch / pull
    wave / host apply, each pricing a floor + feeding the decayed fit +
    share gauges) plus the two kernel-span folds the pass's spans
    trigger. Deliberately a superset of the steady state."""
    anatomy.record_stage("device_dispatch", 0.115, rows=1_000_000)
    anatomy.record_stage("pull_wave", 2.8, nbytes=48_000_000)
    anatomy.record_stage("host_apply", 1.4, rows=1_000_000)
    anatomy.record_span("kernel:reconcile", 115.0, rows=1_000_000)
    anatomy.record_span("kernel:merkle", 9.5, rows=1_000_000)


def _slope_ms(fn):
    """Slope between two repetition counts of a per-batch sequence."""
    def timed(reps):
        runs = []
        for _ in range(7):
            t0 = time.perf_counter()
            for _ in range(reps):
                fn()
            runs.append(time.perf_counter() - t0)
        return statistics.median(runs)

    t_lo, t_hi = timed(REPS_LO), timed(REPS_HI)
    return (t_hi - t_lo) / (REPS_HI - REPS_LO) * 1e3  # ms per batch


def measure_instrumentation_ms():
    return _slope_ms(instrumentation_sequence)


def measure_ledger_sentinel_ms():
    return _slope_ms(ledger_sentinel_sequence)


def measure_anatomy_ms():
    return _slope_ms(anatomy_sequence)


def measure_reconcile_batch_ms():
    """Per-iteration wall time of the 1M-row reconcile pipeline on this
    platform, two-point slope over fused iterations (bench.py method,
    smaller iteration counts — this anchors a ratio, it is not the
    scored bench)."""
    from evolu_tpu.parallel.mesh import create_mesh, sharding

    mesh = create_mesh()
    n_dev = mesh.devices.size
    shd = sharding(mesh)
    names = ("cell_id", "k1", "k2", "ex_k1", "ex_k2", "owner_ix")
    with jax.enable_x64(True):
        cols, _ = bench.shard_layout(bench.build_columns(stored_winners=True), n_dev)
        args = [jax.device_put(cols[k], shd) for k in names]
        medians = {}
        for iters in (ITERS_LO, ITERS_HI):
            loop = bench.make_loop(mesh, iters)
            np.asarray(loop(*args))  # compile + warm
            runs = []
            for _ in range(5):
                t0 = time.perf_counter()
                np.asarray(loop(*args))
                runs.append(time.perf_counter() - t0)
            medians[iters] = statistics.median(runs)
    return (medians[ITERS_HI] - medians[ITERS_LO]) / (ITERS_HI - ITERS_LO) * 1e3


def main():
    logger.clear()
    instr_ms = measure_instrumentation_ms()
    ledger_ms = measure_ledger_sentinel_ms()
    anatomy.set_platform("tpu")  # priced floors = the expensive path
    anatomy_ms = measure_anatomy_ms()
    batch_ms = measure_reconcile_batch_ms()
    print(json.dumps({
        "metric": "obs_instrumentation_overhead_on_1m_reconcile",
        "instrumentation_ms_per_batch": round(instr_ms, 5),
        "ledger_sentinel_ms_per_batch": round(ledger_ms, 5),
        "reconcile_ms_per_batch": round(batch_ms, 3),
        "overhead_fraction": round(instr_ms / batch_ms, 6),
        "overhead_pct": round(100 * instr_ms / batch_ms, 4),
        "pass_1pct_gate": instr_ms / batch_ms <= 0.01,
        "ledger_overhead_fraction": round(ledger_ms / batch_ms, 6),
        "ledger_overhead_pct": round(100 * ledger_ms / batch_ms, 4),
        "pass_ledger_0p1pct_gate": ledger_ms / batch_ms <= LEDGER_GATE_FRACTION,
        "anatomy_ms_per_batch": round(anatomy_ms, 5),
        "anatomy_overhead_fraction": round(anatomy_ms / batch_ms, 6),
        "anatomy_overhead_pct": round(100 * anatomy_ms / batch_ms, 4),
        "pass_anatomy_0p1pct_gate": anatomy_ms / batch_ms <= LEDGER_GATE_FRACTION,
        "device_graph_untouched": "pinned by tests/test_bench_liveness.py",
        "platform": jax.devices()[0].platform,
        "method": "two-point slope on both legs (fixed overhead cancelled)",
    }))


if __name__ == "__main__":
    jax.config.update("jax_enable_x64", True)
    main()
