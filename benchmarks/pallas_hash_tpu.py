"""Pallas-vs-XLA timestamp-hash benchmark on REAL TPU silicon.

Runs `ops.pallas_hash._hash_blocks` NON-interpreted on the chip,
asserts bit-exactness against the XLA path (`encode.timestamp_hashes`)
at 1M hashes, and times both with K iterations fused into one jit so
the measurement-tunnel RTT amortizes out (same protocol as bench.py).

Requires a TPU backend (exits with a skip note otherwise). Round-2
result on v5e-1: XLA 6.24 ms / 1M (168M hashes/sec), Pallas 6.47 ms
(162M hashes/sec) — a tie; the XLA path stays production (see
docs/BENCHMARKS.md).

Prints one JSON line.
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np

from evolu_tpu.ops import pallas_hash as ph
from evolu_tpu.ops.encode import timestamp_hashes

N = 1 << 20
K = 16


def main():
    if jax.devices()[0].platform != "tpu":
        print(json.dumps({"metric": "pallas_hash_tpu", "skipped": True,
                          "reason": f"needs TPU, got {jax.devices()[0].platform}"}))
        return
    rng = np.random.default_rng(0)
    with jax.enable_x64(True):
        millis = jax.device_put(jnp.asarray(
            (1_700_000_000_000 + rng.integers(0, 3_600_000, N)).astype(np.int64)))
        counter = jax.device_put(jnp.asarray(rng.integers(0, 65536, N).astype(np.int32)))
        node = jax.device_put(jnp.asarray(rng.integers(1, 2**63, N).astype(np.uint64)))

        @jax.jit
        def xla_k(millis, counter, node):
            acc = jnp.uint32(0)
            for i in range(K):
                h = timestamp_hashes(millis, counter ^ jnp.int32(i), node)
                acc = acc ^ jax.lax.reduce(h, jnp.uint32(0), jnp.bitwise_xor, (0,))
            return acc

        @jax.jit
        def split(millis, counter, node):
            ms = (millis % 1000).astype(jnp.uint32)
            secs = millis // 1000
            return ((secs // 86400).astype(jnp.int32).reshape(N // 128, 128),
                    (secs % 86400).astype(jnp.int32).reshape(N // 128, 128),
                    ms.reshape(N // 128, 128),
                    counter.reshape(N // 128, 128),
                    (node >> jnp.uint64(32)).astype(jnp.uint32).reshape(N // 128, 128),
                    node.astype(jnp.uint32).reshape(N // 128, 128))

        comps = jax.block_until_ready(split(millis, counter, node))
        expect = int(jax.block_until_ready(xla_k(millis, counter, node)))

    # The Pallas kernel is pure 32-bit: trace OUTSIDE the x64 scope or
    # Mosaic rejects the i64 grid index map (verified on the chip).
    with jax.enable_x64(False):
        days, sod, msr, c32, nh, nl = comps

        @jax.jit
        def pl_k(days, sod, msr, c32, nh, nl):
            acc = jnp.uint32(0)
            for i in range(K):
                c = (c32 ^ jnp.int32(i)).astype(jnp.uint32)
                h = ph._hash_blocks(days, sod, msr, c, nh, nl, interpret=False)
                acc = acc ^ jax.lax.reduce(h, jnp.uint32(0), jnp.bitwise_xor, (0, 1))
            return acc

        got = int(jax.block_until_ready(pl_k(days, sod, msr, c32, nh, nl)))
        assert got == expect, (hex(got), hex(expect))

        def median_iter_ms(fn, *args):
            ts = []
            for _ in range(10):
                t0 = time.perf_counter()
                jax.block_until_ready(fn(*args))
                ts.append(time.perf_counter() - t0)
            ts.sort()
            return ts[5] / K * 1000

        with jax.enable_x64(True):
            xla_ms = median_iter_ms(xla_k, millis, counter, node)
        pl_ms = median_iter_ms(pl_k, days, sod, msr, c32, nh, nl)

    print(json.dumps({
        "metric": "timestamp_hash_ms_per_1M_on_tpu",
        "value": round(min(xla_ms, pl_ms), 3),
        "unit": "ms",
        "detail": {
            "bit_exact": True, "n": N, "fused_iters": K,
            "xla_ms": round(xla_ms, 3), "pallas_ms": round(pl_ms, 3),
            "xla_mhashes_per_sec": round(N / xla_ms / 1000),
            "pallas_mhashes_per_sec": round(N / pl_ms / 1000),
            "winner": "xla" if xla_ms <= pl_ms else "pallas",
            "device": str(jax.devices()[0]),
        },
    }))


if __name__ == "__main__":
    main()
