"""Partial replication: scoped-slice sync bytes vs full sync.

The claim behind sync/scope.py + server/scope.py (ISSUE 18): a thin
client that declares a slice — here 1 of 10 equal HMAC lanes — should
pay wire bytes proportional to the SLICE, not the owner's history.
Measured directly at the HTTP transport against a live relay: a fresh
scoped puller and a fresh full puller each converge from empty via the
real codec (`encode_sync_request` with the capability-gated scope
clause / plain v1 request), counting request+response bytes per leg.

Method: the SLOPE between two history sizes (CLAUDE.md: never divide
one wall/byte total by its count) — each sync round also ships both
sides' Merkle tree summaries, a per-round overhead that does not scale
with served rows; the byte slope between N1 and N2 cancels it. The
gate is on the slope ratio: a 10% slice must cost <= 15% of full-sync
bytes per row (the 5-point slack covers the scoped leg's extra clause
bytes and the shared summary overhead that the slope cannot fully
cancel when round counts differ).

Liveness fence (the r2/r3 lesson, transposed to the wire): every
served row feeds a crc32 carry (timestamp + ciphertext), and each
leg's carry must equal the donor-side crc of exactly the rows that leg
was OWED — full = the whole history, scoped = the lane's rows. A leg
that silently dropped or skipped rows cannot pass; the crcs are
deterministic (fixed BASE, seeded content) and double as exact-match
baseline gates for compare_baselines.py.

Host-side only (HTTP + SQLite + Merkle walks; the scoped minute-fold
routes host at these sizes — SCOPE_DEVICE_FOLD_MIN); env pinned to
CPU. Prints ONE JSON line; numbers live in docs/BENCHMARKS.md.
`--smoke` runs a tiny pass for CI: crc gates hard, the slope-ratio
gate enforced at a loosened bound (tiny histories leave the per-round
summary overhead a visible share of the slope).
"""

import argparse
import json
import os
import sys
import time
import zlib

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()
for _v in ("PALLAS_AXON_POOL_IPS", "PALLAS_AXON_REMOTE_COMPILE"):
    os.environ.pop(_v, None)

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from evolu_tpu.core.merkle import (
    apply_prefix_xors,
    merkle_tree_to_string,
    minute_deltas_host,
)
from evolu_tpu.core.timestamp import Timestamp, timestamp_to_string
from evolu_tpu.server import scope as server_scope
from evolu_tpu.server.relay import RelayServer, RelayStore
from evolu_tpu.sync import protocol
from evolu_tpu.sync.client import _http_post
from evolu_tpu.sync.scope import derive_scope_tag

BASE = 1_700_000_000_000
MINUTE = 60_000
OWNER = "bench-owner"
FEED_NODE = "feed00000000feed"
PULL_NODE = "9999aaaabbbbcccc"
MNEMONIC = "bench partial sync mnemonic"
TABLES = 10
MAX_ROUNDS = 200


def _seed(store, minutes, per_min):
    """`minutes` x `per_min` rows for each of TABLES lanes, all
    authored by the feed node, lane-tagged exactly as an author's
    capability-gated push would have (author-only rule included)."""
    tags = [derive_scope_tag(MNEMONIC, f"table{t}") for t in range(TABLES)]
    all_ts, all_tags = [], []
    msgs = []
    for m in range(minutes):
        for j in range(per_min):
            for t in range(TABLES):
                ts = timestamp_to_string(Timestamp(
                    BASE + m * MINUTE + (j * TABLES + t) * 40, 0, FEED_NODE))
                msgs.append(protocol.EncryptedCrdtMessage(
                    ts, b"ct%02d" % t + b"x" * 96 + b"%06d" % (m * per_min + j)))
                all_ts.append(ts)
                all_tags.append(tags[t])
    store.add_messages(OWNER, tuple(msgs))
    server_scope.record_push_lanes(store.db, OWNER, all_ts, all_tags,
                                   node_id=FEED_NODE)
    return msgs


def _crc_of(msgs):
    crc = 0
    for m in sorted(msgs, key=lambda m: m.timestamp):
        crc = zlib.crc32(m.timestamp.encode(), crc)
        crc = zlib.crc32(m.content, crc)
    return crc


def _pull(url, scope_clause):
    """Converge a fresh puller from empty; → (bytes, rows, rounds,
    crc_carry, wall_s). The carry consumes EVERY served row — the
    liveness fence."""
    tree = {}
    caps = (protocol.CAP_SYNC_SCOPE,) if scope_clause is not None else ()
    n_bytes = rows = rounds = crc = 0
    t0 = time.perf_counter()
    for _ in range(MAX_ROUNDS):
        body = protocol.encode_sync_request(protocol.SyncRequest(
            (), OWNER, PULL_NODE, merkle_tree_to_string(tree),
            caps, scope_clause))
        out = _http_post(url, body, retries=0)
        n_bytes += len(body) + len(out)
        rounds += 1
        resp = protocol.decode_sync_response(out)
        if not resp.messages:
            break
        for m in resp.messages:
            crc = zlib.crc32(m.timestamp.encode(), crc)
            crc = zlib.crc32(m.content, crc)
        rows += len(resp.messages)
        deltas, _ = minute_deltas_host(m.timestamp for m in resp.messages)
        tree = apply_prefix_xors(tree, deltas)
    else:
        raise AssertionError("puller did not converge in MAX_ROUNDS")
    return n_bytes, rows, rounds, crc, time.perf_counter() - t0


def _leg(minutes, per_min):
    store = RelayStore()
    server = RelayServer(store).start()
    try:
        msgs = _seed(store, minutes, per_min)
        slice_tag = derive_scope_tag(MNEMONIC, "table0")
        full_b, full_rows, full_rounds, full_crc, full_wall = _pull(
            server.url, None)
        sc_b, sc_rows, sc_rounds, sc_crc, sc_wall = _pull(
            server.url, protocol.ScopeClause(0, (slice_tag,), ()))
        owed_full = _crc_of(msgs)
        owed_scoped = _crc_of([m for m in msgs
                               if m.content.startswith(b"ct00")])
        assert full_rows == len(msgs)
        return {
            "rows_total": len(msgs),
            "full": {"wire_bytes": full_b, "rows": full_rows,
                     "rounds": full_rounds, "wall_s": round(full_wall, 4),
                     "served_crc": f"{full_crc:08x}",
                     "pass_crc": full_crc == owed_full},
            "scoped": {"wire_bytes": sc_b, "rows": sc_rows,
                       "rounds": sc_rounds, "wall_s": round(sc_wall, 4),
                       "served_crc": f"{sc_crc:08x}",
                       "pass_crc": sc_crc == owed_scoped},
        }
    finally:
        server.stop()
        store.close()


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("--smoke", action="store_true",
                    help="tiny CI pass: crc gates hard, ratio gate loosened")
    args = ap.parse_args()

    if args.smoke:
        sizes = [(2, 4), (6, 4)]  # (minutes, per_min): 80 / 240 rows
        gate = 0.30  # summary overhead is a real share at tiny sizes
    else:
        sizes = [(8, 25), (32, 25)]  # 2 000 / 8 000 rows
        gate = 0.15

    legs = [_leg(m, p) for m, p in sizes]
    n1, n2 = legs[0]["rows_total"], legs[1]["rows_total"]
    slope_full = (legs[1]["full"]["wire_bytes"]
                  - legs[0]["full"]["wire_bytes"]) / (n2 - n1)
    slope_scoped = (legs[1]["scoped"]["wire_bytes"]
                    - legs[0]["scoped"]["wire_bytes"]) / (n2 - n1)
    ratio = slope_scoped / slope_full
    rec = {
        "bench": "partial_sync",
        "platform": "cpu",
        "smoke": bool(args.smoke),
        "tables": TABLES,
        "slice_share": 1 / TABLES,
        "sizes_rows": [n1, n2],
        "legs": legs,
        "slope_bytes_per_row_full": round(slope_full, 2),
        "slope_bytes_per_row_scoped": round(slope_scoped, 2),
        "slope_ratio": round(ratio, 4),
        "byte_ratio_at_n2": round(
            legs[1]["scoped"]["wire_bytes"] / legs[1]["full"]["wire_bytes"],
            4),
        "gate": gate,
        "pass_slice_byte_gate": ratio <= gate,
        "method": ("byte slope between two history sizes (cancels "
                   "per-round tree-summary overhead); crc carry over "
                   "every served row == donor-side crc of the owed set"),
    }
    print(json.dumps(rec, separators=(",", ":")))
    assert rec["pass_slice_byte_gate"], \
        f"slice byte gate failed: slope ratio {ratio:.4f} > {gate}"
    for leg in legs:
        assert leg["full"]["pass_crc"], "full leg dropped served rows"
        assert leg["scoped"]["pass_crc"], "scoped leg crc != owed slice"


if __name__ == "__main__":
    main()
