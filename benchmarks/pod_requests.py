"""Synthetic pod-server request batches — ONE copy of the SyncRequest
synthesis shared by the multichip dryrun (`__graft_entry__`) and
`benchmarks/config5_mesh.py`, so the artifact cross-check and the
bench can never drift apart on the request shape."""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from evolu_tpu.core.merkle import (
    apply_prefix_xors,
    merkle_tree_to_string,
    minute_deltas_host,
)
from evolu_tpu.core.timestamp import Timestamp, timestamp_to_string
from evolu_tpu.sync import protocol

_BASE = 1_700_000_000_000


def build_pod_requests(owners: int, per: int, factor: int, stride_ms: int,
                       payload: bytes = b"ct"):
    """→ (requests, expected_digest): `owners` owners each pushing
    `per` canonical messages (millis = base + (o*factor + i)*stride_ms)
    with their post-apply trees — the steady-state push shape.
    `expected_digest` is the XOR of the host minute-fold digests, which
    a clean pod pass (no duplicates) must reproduce on device."""
    requests = []
    expect = 0
    for o in range(owners):
        ts = [
            timestamp_to_string(
                Timestamp(_BASE + (o * factor + i) * stride_ms, i % 4,
                          f"{o + 1:016x}")
            )
            for i in range(per)
        ]
        msgs = tuple(
            protocol.EncryptedCrdtMessage(t, payload + b"-%d" % o) for t in ts
        )
        deltas, owner_digest = minute_deltas_host(iter(ts))
        expect ^= owner_digest
        requests.append(protocol.SyncRequest(
            msgs, f"owner{o}", "f" * 16,
            merkle_tree_to_string(apply_prefix_xors({}, deltas)),
        ))
    return requests, expect & 0xFFFFFFFF
