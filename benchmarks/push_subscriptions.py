"""Push subscriptions + event-loop connection tier bench (ISSUE 13).

Three measurements, one child-process relay (the 20k-FD container
limit means 10^4 connections must split their endpoints across two
processes; the split also lets us read the RELAY's /proc accounting
untainted by the driver):

1. Idle-connection scaling — parked long-polls vs the relay process's
   thread count and RSS. THE acceptance gate: threads must NOT grow
   with connections (10^4 idle subscriptions on the event tier cost
   file descriptors, not threads).

2. Mutation→client-visible latency, push vs poll, with the idle fleet
   parked: K probe subscribers on the hot owner measure
   wake→sync-round-complete; the polling baseline measures
   mutation→first-interval-poll-that-sees-it at POLL_INTERVAL_S
   (1.0 s — generous to polling: the reference's headless analog
   syncs on a timer of seconds; halve it and push's factor halves,
   recorded honestly in docs/BENCHMARKS.md). Acceptance: push p50
   ≥ 5× better at 10^3+ subscribers.

3. Byte-identity gate — the same mutation stream driven at an
   event-tier relay and a threaded oracle relay: every response and
   both SQLite end states must match (modulo the Date header).

`--smoke` (CI): 2k idle connections, fewer rounds, same asserts.
Output: ONE JSON line, like every bench here.
"""

import json
import os
import selectors
import socket
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

POLL_INTERVAL_S = 1.0
NODE_W = "a" * 16  # writer node
NODE_S = "5" * 16  # subscriber node


def _serve():
    """Child mode: run one event-tier relay, print its URL, serve
    until stdin closes."""
    from evolu_tpu.server.relay import RelayServer, RelayStore

    srv = RelayServer(RelayStore(), connection_tier="eventloop").start()
    print("READY " + srv.url, flush=True)
    try:
        sys.stdin.read()  # parent closes stdin to stop us
    finally:
        srv.stop()


def _proc_status(pid):
    threads = rss_kb = None
    with open(f"/proc/{pid}/status") as f:
        for line in f:
            if line.startswith("Threads:"):
                threads = int(line.split()[1])
            elif line.startswith("VmRSS:"):
                rss_kb = int(line.split()[1])
    return threads, rss_kb


def _raw_poll(owner, node, cursor=0, timeout=50.0):
    path = (f"/push/poll?owner={owner}&node={node}"
            f"&cursor={cursor}&timeout={timeout}")
    return (f"GET {path} HTTP/1.0\r\nContent-Length: 0\r\n\r\n").encode()


def _park(addr, owner, node, timeout=50.0):
    s = socket.create_connection(addr, timeout=30)
    s.sendall(_raw_poll(owner, node, timeout=timeout))
    s.setblocking(False)
    return s


def _msgs(node, start, n):
    from evolu_tpu.core.timestamp import Timestamp, timestamp_to_string
    from evolu_tpu.sync import protocol

    base = 1_740_000_000_000
    return tuple(
        protocol.EncryptedCrdtMessage(
            timestamp_to_string(Timestamp(base + (start + i) * 1000, 0, node)),
            b"ct-%d" % (start + i))
        for i in range(n)
    )


def _sync_body(owner, node, messages, tree="{}"):
    from evolu_tpu.sync import protocol

    return protocol.encode_sync_request(
        protocol.SyncRequest(messages, owner, node, tree))


def _post(url, body):
    import urllib.request

    with urllib.request.urlopen(
            urllib.request.Request(url, data=body), timeout=60) as r:
        return r.read()


def _recv_all(sock, deadline):
    sock.setblocking(True)
    sock.settimeout(max(0.05, deadline - time.monotonic()))
    out = bytearray()
    while True:
        chunk = sock.recv(65536)
        if not chunk:
            return bytes(out)
        out += chunk


def _percentile(xs, q):
    xs = sorted(xs)
    return xs[min(len(xs) - 1, int(q * len(xs)))]


def run(smoke: bool):
    n_idle = 2000 if smoke else 10_000
    # Probes are measurement taps; the parked idle fleet provides the
    # subscriber scale. Too many SIMULTANEOUS probes would measure the
    # 1-core thundering-herd of their own confirmation pulls, not the
    # push path (32 concurrent pulls serialized behind one core added
    # ~3x to p50 — recorded in docs/BENCHMARKS.md).
    n_probes = 8
    rounds = 6 if smoke else 12
    checkpoints = [0, n_idle // 2, n_idle]

    child = subprocess.Popen(
        [sys.executable, os.path.abspath(__file__), "--serve"],
        stdin=subprocess.PIPE, stdout=subprocess.PIPE, text=True,
        env={**os.environ, "PYTHONPATH": REPO, "JAX_PLATFORMS": "cpu"},
    )
    out = {"bench": "push_subscriptions", "smoke": smoke, "n_idle": n_idle}
    idle_socks = []
    try:
        line = child.stdout.readline()
        assert line.startswith("READY "), line
        url = line.split()[1]
        host, port = url.split("//")[1].split(":")
        addr = (host, int(port))

        # -- 1: idle-connection scaling --
        scaling = []
        k = 0
        for target in checkpoints:
            while k < target:
                idle_socks.append(_park(addr, f"idle-{k}", NODE_S))
                k += 1
            import urllib.request

            deadline = time.monotonic() + 60
            while True:
                with urllib.request.urlopen(url + "/stats", timeout=30) as r:
                    st = json.loads(r.read())
                if st["push"]["subscriptions"] >= target:
                    break
                assert time.monotonic() < deadline, \
                    (target, st["push"]["subscriptions"])
                time.sleep(0.05)
            threads, rss_kb = _proc_status(child.pid)
            scaling.append({"connections": target, "threads": threads,
                            "rss_kb": rss_kb,
                            "parked": st["push"]["subscriptions"]})
        out["idle_scaling"] = scaling
        # THE gate: threads flat across 0 → n_idle parked connections
        # (the first checkpoint may still be warming the handler pool,
        # so compare the two loaded checkpoints AND bound absolutely).
        t_half, t_full = scaling[1]["threads"], scaling[2]["threads"]
        assert t_full <= t_half, \
            f"threads grew with connections: {t_half} -> {t_full}"
        assert t_full < 64, f"unbounded thread count: {t_full}"

        # -- 2: push vs poll latency, idle fleet still parked --
        hot = "hot-owner"
        push_lat = []
        seq = 0
        for rnd in range(rounds):
            probes = [_park(addr, hot, NODE_S, timeout=30.0)
                      for _ in range(n_probes)]
            time.sleep(0.3)  # let them park
            t0 = time.monotonic()
            _post(url + "/", _sync_body(hot, NODE_W, _msgs(NODE_W, seq, 1)))
            seq += 1
            sel = selectors.DefaultSelector()
            for s in probes:
                sel.register(s, selectors.EVENT_READ)
            deadline = t0 + 30
            done = 0
            while done < len(probes) and time.monotonic() < deadline:
                for key, _ in sel.select(timeout=1.0):
                    s = key.fileobj
                    sel.unregister(s)
                    resp = _recv_all(s, deadline)
                    assert b'"wake": true' in resp.replace(b'"wake":true', b'"wake": true'), resp[-200:]
                    # client-visible = wake + the sync round it triggers
                    _post(url + "/", _sync_body(hot, NODE_S, ()))
                    push_lat.append(time.monotonic() - t0)
                    s.close()
                    done += 1
            assert done == len(probes), f"round {rnd}: {done}/{len(probes)}"
        # Polling baseline: same relay, same owner, interval pollers.
        poll_lat = []
        for rnd in range(rounds):
            # Pollers offset uniformly across the interval (the honest
            # steady-state phase distribution, not worst- or best-case).
            offsets = [(i + 0.5) / n_probes * POLL_INTERVAL_S
                       for i in range(n_probes)]
            t0 = time.monotonic()
            _post(url + "/", _sync_body(hot, NODE_W, _msgs(NODE_W, seq, 1)))
            seq += 1
            target_n = seq  # rows the hot owner now has
            for off in offsets:
                now = time.monotonic() - t0
                wait = (off - now) % POLL_INTERVAL_S
                time.sleep(max(0.0, wait))
                while True:
                    resp = _post(url + "/", _sync_body(hot, NODE_S, ()))
                    from evolu_tpu.sync import protocol

                    got = protocol.decode_sync_response(resp)
                    if len(got.messages) >= target_n:
                        poll_lat.append(time.monotonic() - t0)
                        break
                    time.sleep(POLL_INTERVAL_S)
        out["push_ms"] = {"p50": round(_percentile(push_lat, 0.5) * 1e3, 2),
                          "p99": round(_percentile(push_lat, 0.99) * 1e3, 2)}
        out["poll_ms"] = {"p50": round(_percentile(poll_lat, 0.5) * 1e3, 2),
                          "p99": round(_percentile(poll_lat, 0.99) * 1e3, 2),
                          "interval_s": POLL_INTERVAL_S}
        factor = out["poll_ms"]["p50"] / max(out["push_ms"]["p50"], 1e-9)
        out["push_vs_poll_p50_factor"] = round(factor, 1)
        assert factor >= 5.0, \
            f"push p50 only {factor:.1f}x better than {POLL_INTERVAL_S}s polling"
    finally:
        for s in idle_socks:
            try:
                s.close()
            except OSError:
                pass
        child.stdin.close()
        try:
            child.wait(timeout=15)
        except subprocess.TimeoutExpired:
            child.kill()

    # -- 3: byte-identity gate vs the threaded oracle --
    from evolu_tpu.server.relay import RelayServer, RelayStore

    def _dump(store):
        msgs = store.db.exec_sql_query(
            'SELECT "timestamp", "userId", "content" FROM "message" '
            'ORDER BY "userId", "timestamp"', ())
        trees = store.db.exec_sql_query(
            'SELECT "userId", "merkleTree" FROM "merkleTree" '
            'ORDER BY "userId"', ())
        return ([(r["timestamp"], r["userId"], bytes(r["content"]))
                 for r in msgs],
                [(r["userId"], r["merkleTree"]) for r in trees])

    twins = [RelayServer(RelayStore(), connection_tier=t).start()
             for t in ("threaded", "eventloop")]
    try:
        n_div = 0
        for i in range(12):
            owner = f"ow-{i % 3}"
            body = _sync_body(owner, NODE_W, _msgs(NODE_W, i * 10, 3))
            got = [_post(s.url + "/", body) for s in twins]
            if got[0] != got[1]:
                n_div += 1
        pull = _sync_body("ow-0", NODE_S, ())
        got = [_post(s.url + "/", pull) for s in twins]
        assert got[0] == got[1], "cold pull diverged between tiers"
        assert n_div == 0, f"{n_div} responses diverged between tiers"
        assert _dump(twins[0].store) == _dump(twins[1].store), \
            "SQLite end state diverged between tiers"
        out["byte_identity"] = "ok"
    finally:
        for s in twins:
            s.stop()
    print(json.dumps(out))


if __name__ == "__main__":
    if "--serve" in sys.argv:
        _serve()
    else:
        run(smoke="--smoke" in sys.argv)
