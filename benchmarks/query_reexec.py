"""Hot loop #4: reactive query re-execution (VERDICT r3 next #7;
reference packages/evolu/src/query.ts:31-76 re-runs every subscribed
query after each mutation and diffs rows with rfc6902 createPatch).

Measures a 10k-row subscribed query's per-cycle cost in three shapes:
  per_cell   — the pre-r4 path (per-cell ctypes column reads + diff)
  unchanged  — r4 production steady state: packed raw read + byte
               compare, no dict materialization, no diff
  changed    — r4 production when the result set changed: packed raw
               read + FULL unpack + rfc6902 diff
  changed_1row_granular — r5 production: packed read with offsets +
               row-aligned partial unpack (unchanged rows reuse prev
               dicts) + identity-shortcut diff

Prints one JSON line; conclusions live in docs/BENCHMARKS.md.
"""

import json
import os
import statistics
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from evolu_tpu.api.query import table
from evolu_tpu.runtime.client import create_evolu
from evolu_tpu.runtime.jsonpatch import create_patch
import evolu_tpu.runtime.messages as msg_mod

ROWS = int(os.environ.get("QUERY_ROWS", 10_000))
REPS = int(os.environ.get("QUERY_REPS", 20))


def med(fn):
    ts = []
    for _ in range(REPS):
        t0 = time.perf_counter()
        fn()
        ts.append(time.perf_counter() - t0)
    return statistics.median(ts) * 1e3


def main():
    e = create_evolu({"todo": ("title", "done")}, db_path=":memory:")
    with e.batching():
        for i in range(ROWS):
            e.create("todo", {"title": f"item {i:06d}", "done": 0})
    e.worker.flush()
    q = table("todo").select("id", "title", "done").order_by("title").serialize()
    sql, params = msg_mod.deserialize_query(q)
    w = e.worker
    rows = e.query_once(q)
    raw_capable = hasattr(w.db, "exec_sql_query_packed_raw")

    out = {"rows": ROWS, "raw_capable": raw_capable}
    if raw_capable:
        from evolu_tpu.storage.native import unpack_packed_rows

        raw = w.db.exec_sql_query_packed_raw(sql, params)
        out["raw_read_ms"] = round(med(
            lambda: w.db.exec_sql_query_packed_raw(sql, params)), 2)
        out["unchanged_cycle_ms"] = round(med(
            lambda: w.db.exec_sql_query_packed_raw(sql, params) == raw), 2)
        fresh = unpack_packed_rows(raw)
        out["unpack_ms"] = round(med(lambda: unpack_packed_rows(raw)), 2)
        out["diff_ms"] = round(med(lambda: create_patch(rows, fresh)), 2)
        out["changed_cycle_ms"] = round(
            out["raw_read_ms"] + out["unpack_ms"] + out["diff_ms"], 2)

        # r5: the row-granular changed path — mutate ONE row of 10k,
        # then the full production cycle: packed read with offsets →
        # row-aligned partial unpack (unchanged rows reuse prev dicts)
        # → identity-shortcut diff.
        from evolu_tpu.storage.native import unpack_changed_rows

        prev_raw, prev_offs = w.db.exec_sql_query_packed_raw(
            sql, params, with_offsets=True
        )
        prev_rows = unpack_packed_rows(prev_raw)
        # Toggle `done` on one mid-result row: the canonical reactive
        # mutation — sort position and row count unchanged.
        row_id = prev_rows[ROWS // 2]["id"]
        w.db.run('UPDATE "todo" SET "done" = 1 WHERE "id" = ?', (row_id,))

        def changed_row_cycle():
            raw2, offs2 = w.db.exec_sql_query_packed_raw(
                sql, params, with_offsets=True
            )
            rows2 = unpack_changed_rows(raw2, offs2, prev_raw, prev_offs, prev_rows)
            return create_patch(prev_rows, rows2)

        ops = changed_row_cycle()
        assert ops, "the mutation must produce a patch"
        out["changed_1row_granular_cycle_ms"] = round(med(changed_row_cycle), 2)

    def per_cell():
        with w.db._lock:
            r, c = w.db._execute(sql, params)
            return [dict(zip(c, row)) for row in r]

    if hasattr(w.db, "_execute"):
        prev = per_cell()
        out["per_cell_cycle_ms"] = round(
            med(per_cell) + med(lambda: create_patch(prev, prev)), 2)

    print(json.dumps({
        "metric": "query_reexec_unchanged_cycle_ms",
        "value": out.get("unchanged_cycle_ms"),
        "unit": "ms",
        "detail": out,
    }))
    e.dispose()


if __name__ == "__main__":
    main()
