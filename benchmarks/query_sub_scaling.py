"""Mutation→notify latency vs live-subscription count (ISSUE 9).

The reference re-runs every subscribed query after every mutation
(query.ts:31-76); r9 gates that sweep on the merge planner's
changed-set (runtime/worker.py::_query × storage/deps.py). This bench
sweeps 10/100/1k/10k subscriptions over three write shapes and
measures the worker's mutation→notify wall time (one Send carrying the
mutation plus the full subscribed-query sweep, handled synchronously):

  table_disjoint — subscriptions read "todo", the write lands in
                   "other": every query skips without a read.
  row_disjoint   — per-row detail subscriptions (`WHERE "id" = ?`),
                   the write lands in an unsubscribed row: every query
                   skips on the static id constraint.
  overlap        — unconstrained list subscriptions over the written
                   table: nothing can skip; measures pure gate
                   overhead (must stay within 1.1× of ungated).

`--smoke` is the CI oracle-parity gate: twin workers (gated vs
re-run-everything) with pinned HLC nodes run one mixed schedule —
disjoint/overlapping Sends, a canonical and a NON-CANONICAL Receive
(host-oracle bounce), a rollback, eviction churn — and every output
(patch streams, pushes) plus the SQLite end state must be identical,
with the skip counters proven engaged.

Prints ONE JSON line; numbers live in docs/BENCHMARKS.md (r9).
"""

import itertools
import json
import os
import statistics
import sys
import time
from dataclasses import replace

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from evolu_tpu.core.merkle import create_initial_merkle_tree, merkle_tree_to_string
from evolu_tpu.core.timestamp import Timestamp, timestamp_to_string
from evolu_tpu.core.types import CrdtClock, CrdtMessage, NewCrdtMessage, TableDefinition
from evolu_tpu.obs import metrics
from evolu_tpu.runtime import messages as msg
from evolu_tpu.runtime.worker import DbWorker
from evolu_tpu.storage.clock import read_clock, update_clock
from evolu_tpu.storage.native import open_database
from evolu_tpu.utils.config import Config

MNEMONIC = ("abandon abandon abandon abandon abandon abandon "
            "abandon abandon abandon abandon abandon about")
EMPTY_TREE = merkle_tree_to_string(create_initial_merkle_tree())
TDS = (
    TableDefinition.of("todo", ("title", "done")),
    TableDefinition.of("other", ("name",)),
)
SEED_ROWS = int(os.environ.get("QSS_SEED_ROWS", 512))


def counting_now(base=1_700_000_000_000, step=7):
    c = itertools.count()
    return lambda: base + step * next(c)


def make_worker(gated: bool):
    db = open_database(":memory:")
    outputs, pushes = [], []
    cfg = Config(backend="cpu", winner_cache=False, query_invalidation=gated)
    w = DbWorker(db, config=cfg, on_output=outputs.append,
                 post_sync=pushes.append, now=counting_now())
    w.start(MNEMONIC)
    w.stop()  # drive handle() synchronously: no queue/thread noise
    clock = read_clock(db)
    with db.transaction():  # pin the node id → twin-run determinism
        update_clock(db, CrdtClock(
            replace(clock.timestamp, node="00c0ffee00c0ffee"), clock.merkle_tree))
    w.handle(msg.UpdateDbSchema(TDS))
    seed = tuple(NewCrdtMessage("todo", f"seed{i:05d}", "title", f"t{i:05d}")
                 for i in range(SEED_ROWS))
    w.handle(msg.Send(seed, (), ()))
    w.handle(msg.Send((NewCrdtMessage("other", "o0", "name", "n0"),), (), ()))
    outputs.clear()
    pushes.clear()
    return w, outputs, pushes


def q_detail(i):
    return msg.serialize_query(
        'SELECT "id", "title", "done" FROM "todo" WHERE "id" = ?',
        (f"seed{i:05d}",))


def q_list(i):
    # Distinct strings, unconstrained rows: the un-gateable shape.
    return msg.serialize_query(
        'SELECT "id", "title" FROM "todo" WHERE "done" = ? ORDER BY "title"',
        (i,))


def subscriptions(scenario: str, n: int):
    if scenario == "row_disjoint":
        return tuple(q_detail(i) for i in range(n))
    if scenario == "overlap":
        return tuple(q_list(i) for i in range(n))
    # table_disjoint: realistic mix of detail + list, all reading todo.
    return tuple(q_detail(i) if i % 2 else q_list(i) for i in range(n))


def mutation(scenario: str, rep: int):
    if scenario == "table_disjoint":
        return NewCrdtMessage("other", "o0", "name", f"v{rep}")
    if scenario == "row_disjoint":
        # seed00000..: every detail sub targets its own row; write one
        # PAST the subscribed range.
        return NewCrdtMessage("todo", "unsubscribed-row", "title", f"v{rep}")
    return NewCrdtMessage("todo", "seed00007", "done", rep)


def run_scenario(scenario: str, n: int, gated: bool, reps: int):
    w, outputs, _ = make_worker(gated)
    qs = subscriptions(scenario, n)
    w.handle(msg.Query(qs))  # establish baselines (+ dependency index)
    outputs.clear()
    lat = []
    for rep in range(reps):
        cmd = msg.Send((mutation(scenario, rep),), (), qs)
        t0 = time.perf_counter()
        w.handle(cmd)
        lat.append((time.perf_counter() - t0) * 1e3)
    for o in outputs:
        if isinstance(o, msg.OnError):
            raise AssertionError(f"bench schedule errored: {o.error!r}")
    w.db.close()
    lat.sort()
    return {
        "p50_ms": round(statistics.median(lat), 4),
        "p99_ms": round(lat[min(len(lat) - 1, int(len(lat) * 0.99))], 4),
    }


def full_bench():
    sweep = [int(x) for x in os.environ.get(
        "QSS_SWEEP", "10,100,1000,10000").split(",")]
    detail = {"seed_rows": SEED_ROWS, "sweep": sweep, "scenarios": {}}
    for scenario in ("table_disjoint", "row_disjoint", "overlap"):
        per_n = {}
        for n in sweep:
            reps = int(os.environ.get("QSS_REPS", 10 if n >= 10_000 else 30))
            gated = run_scenario(scenario, n, True, reps)
            naive = run_scenario(scenario, n, False, reps)
            per_n[n] = {
                "gated": gated, "ungated": naive,
                "speedup_p50": round(naive["p50_ms"] / max(gated["p50_ms"], 1e-9), 2),
            }
        detail["scenarios"][scenario] = per_n
    top = max(n for n in detail["scenarios"]["row_disjoint"])
    print(json.dumps({
        "metric": "query_sub_scaling_speedup_p50_at_max_subs",
        "value": detail["scenarios"]["row_disjoint"][top]["speedup_p50"],
        "unit": "x",
        "detail": detail,
    }))


# -- smoke: the oracle-parity gate -------------------------------------


def remote_ts(i, counter=0, upper=False):
    s = timestamp_to_string(
        Timestamp(1_700_000_000_000 + i, counter, "00000000000000ab"))
    return s[:30] + s[30:].upper() if upper else s


def smoke_schedule():
    qs = tuple([q_detail(i) for i in range(16)]
               + [q_list(i) for i in range(16)]
               + [msg.serialize_query('SELECT "id", "name" FROM "other" ORDER BY "id"')])
    canonical = tuple(
        CrdtMessage(remote_ts(i, counter=i), "todo", f"rem{i % 3}", "title", f"m{i}")
        for i in range(6))
    non_canonical = tuple(
        CrdtMessage(remote_ts(50 + i, counter=i, upper=True),
                    "todo", "seed00003", "done", i)
        for i in range(3))
    steps = [msg.Query(qs)]
    for rep in range(4):
        steps += [
            msg.Send((mutation("table_disjoint", rep),), (), qs),
            msg.Send((mutation("row_disjoint", rep),), (), qs),
            msg.Send((mutation("overlap", rep),), (f"cb{rep}",), qs),
            msg.Query(qs),
        ]
    steps += [
        msg.Receive(canonical, EMPTY_TREE), msg.Query(qs),
        msg.Receive(non_canonical, EMPTY_TREE), msg.Query(qs),
        # rollback: un-encodable value refuses before any write
        msg.Send((NewCrdtMessage("todo", "seed00001", "title", b"\x00"),), (), qs),
        msg.Query(qs),
        msg.EvictQueries(qs[:4]),
        msg.Query(qs),
        msg.Sync(qs),
    ]
    return steps


def smoke():
    before = {k: metrics.get_counter(k) for k in (
        "evolu_query_skipped_by_table_total",
        "evolu_query_skipped_by_rows_total",
        "evolu_query_skipped_clean_total")}
    w_gated, out_g, push_g = make_worker(True)
    w_naive, out_n, push_n = make_worker(False)
    for cmd in smoke_schedule():
        w_gated.handle(cmd)
        w_naive.handle(cmd)
    errs_g = [o for o in out_g if isinstance(o, msg.OnError)]
    errs_n = [o for o in out_n if isinstance(o, msg.OnError)]
    assert [type(e.error).__name__ for e in errs_g] == \
        [type(e.error).__name__ for e in errs_n], "error streams diverged"
    stream_g = [o for o in out_g if not isinstance(o, msg.OnError)]
    stream_n = [o for o in out_n if not isinstance(o, msg.OnError)]
    assert stream_g == stream_n, "gated patch stream != re-run-everything oracle"
    assert push_g == push_n, "sync pushes diverged"
    for sql in ('SELECT * FROM "__message" ORDER BY "timestamp"',
                'SELECT * FROM "todo" ORDER BY "id"',
                'SELECT * FROM "other" ORDER BY "id"'):
        assert w_gated.db.exec(sql) == w_naive.db.exec(sql), "end state diverged"
    for name, b in before.items():
        assert metrics.get_counter(name) > b, f"{name} never engaged"
    n_onquery = sum(1 for o in stream_g if isinstance(o, msg.OnQuery))
    print(json.dumps({
        "metric": "query_sub_scaling_smoke",
        "value": 1,
        "unit": "ok",
        "detail": {"outputs": len(stream_g), "onquery": n_onquery,
                   "parity": "byte-identical"},
    }))


if __name__ == "__main__":
    if "--smoke" in sys.argv:
        smoke()
    else:
        full_bench()
