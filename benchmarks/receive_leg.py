"""The client receive leg (VERDICT r4 missing #1 / next #1): response
wire bytes → decrypted batch → applied SQLite state.

r4 measured the decode stage at 154k msgs/s with the floor attributed
to per-message CrdtMessage construction (~4 µs/msg of pure object
layer, docs/BENCHMARKS.md). The r5 fused path
(`ehc_decrypt_response_columns` → PackedReceive →
`eh_apply_planned_cells`) removes the object layer end to end. This
script measures both stages both ways on the same response bytes:

- decode: wire → batch (object path `decrypt_response` vs columns
  path `decrypt_response_columns`);
- full leg: wire → planned → committed SQLite rows + Merkle tree
  (object apply vs packed apply), fresh database per trial.

Median-of-trials protocol (within-run trials correlate; the median is
the per-run statistic, docs/BENCHMARKS.md r4). Prints one JSON line.
"""

import json
import os
import random
import statistics
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from evolu_tpu.core.timestamp import Timestamp, timestamp_to_string
from evolu_tpu.core.types import CrdtMessage
from evolu_tpu.runtime.worker import select_planner
from evolu_tpu.storage.apply import apply_messages
from evolu_tpu.storage.native import open_database
from evolu_tpu.storage.schema import init_db_model
from evolu_tpu.sync import native_crypto, protocol
from evolu_tpu.sync.client import encrypt_messages
from evolu_tpu.utils.config import Config

N = int(os.environ.get("RECEIVE_N", 50_000))
TRIALS = int(os.environ.get("RECEIVE_TRIALS", 5))
MN = "legal winner thank year wave sausage worth useful legal winner thank yellow"


def build_messages(n=N, seed=4):
    # The config-3 value mix: short strings, ints, None deletes.
    rng = random.Random(seed)
    vals = [lambda i: f"todo item {i} ✓", lambda i: i % 2, lambda i: None,
            lambda i: f"note {i}: café", lambda i: i * 977]
    nodes = [f"{rng.getrandbits(64):016x}" for _ in range(8)]
    out = []
    for i in range(n):
        out.append(CrdtMessage(
            timestamp_to_string(Timestamp(1_700_000_000_000 + i // 4, i % 4,
                                          rng.choice(nodes))),
            "todo", f"row{rng.randrange(5000)}", "title", vals[i % 5](i),
        ))
    return out


def mkdb():
    db = open_database(backend="auto")
    init_db_model(db, mnemonic=None)
    db.exec('CREATE TABLE "todo" ("id" TEXT PRIMARY KEY, "title" BLOB)')
    return db


def median_rate(fn, trials=TRIALS):
    rates = []
    for _ in range(trials):
        dt = fn()
        rates.append(N / dt)
    return statistics.median(rates)


def main():
    msgs = build_messages()
    resp = protocol.encode_sync_response(
        protocol.SyncResponse(tuple(encrypt_messages(msgs, MN)), "{}")
    )

    # -- decode stage --
    def decode_objects():
        t0 = time.perf_counter()
        out = native_crypto.decrypt_response(resp, MN)
        dt = time.perf_counter() - t0
        assert out is not None and len(out[0]) == N
        return dt

    def decode_columns():
        t0 = time.perf_counter()
        out = native_crypto.decrypt_response_columns(resp, MN)
        dt = time.perf_counter() - t0
        assert out is not None and len(out[0]) == N
        return dt

    dec_obj = median_rate(decode_objects)
    dec_col = median_rate(decode_columns)

    # -- full leg: decode + plan + apply on a fresh DB --
    def full(mode):
        def trial():
            db = mkdb()
            planner = select_planner(Config(), db)
            # Warm the jit bucket outside the timed region (a
            # long-running client compiles once per bucket).
            t0 = time.perf_counter()
            if mode == "packed":
                pb, _tree = native_crypto.decrypt_response_columns(resp, MN)
                apply_messages(db, {}, pb, planner=planner)
            else:
                batch, _tree = native_crypto.decrypt_response(resp, MN)
                apply_messages(db, {}, batch, planner=planner)
            dt = time.perf_counter() - t0
            n_rows = db.exec_sql_query('SELECT COUNT(*) FROM "__message"', ())
            assert next(iter(n_rows[0].values())) == N
            db.close()
            return dt

        # one unmeasured warm trial per mode (jit compile for the bucket)
        trial()
        return median_rate(trial)

    full_obj = full("objects")
    full_pk = full("packed")

    print(json.dumps({
        "metric": "receive_leg_full_msgs_per_sec",
        "value": round(full_pk),
        "unit": "msgs/sec",
        "detail": {
            "n": N, "trials": TRIALS,
            "decode_objects_msgs_per_sec": round(dec_obj),
            "decode_columns_msgs_per_sec": round(dec_col),
            "decode_speedup": round(dec_col / dec_obj, 2),
            "full_objects_msgs_per_sec": round(full_obj),
            "full_packed_msgs_per_sec": round(full_pk),
            "full_speedup": round(full_pk / full_obj, 2),
        },
    }))


if __name__ == "__main__":
    main()
