"""Relay latency under 25 concurrent clients (the reference deploy's
fly.io concurrency allowance, examples/server-nodejs/fly.toml) — p50/p99
per-request, single-store vs owner-sharded store.

Each client = one owner posting rounds of 100 encrypted messages over
HTTP (protobuf SyncRequest), like the reference hot loop
apps/server/src/index.ts:148-159 sees from many devices.

Prints one JSON line.
"""

import json
import os
import statistics
import sys
import threading
import time
import urllib.request

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from evolu_tpu.core.timestamp import Timestamp, timestamp_to_string
from evolu_tpu.server.relay import RelayServer, RelayStore, ShardedRelayStore
from evolu_tpu.sync import protocol

CLIENTS = 25
ROUNDS = 8
MSGS_PER_ROUND = 100
BASE = 1_700_000_000_000


def _msgs(node: str, start: int, n: int):
    return tuple(
        protocol.EncryptedCrdtMessage(
            timestamp_to_string(Timestamp(BASE + (start + i) * 1000, 0, node)),
            b"x" * 64,
        )
        for i in range(n)
    )


def _post(url: str, req: protocol.SyncRequest) -> protocol.SyncResponse:
    body = protocol.encode_sync_request(req)
    r = urllib.request.urlopen(
        urllib.request.Request(
            url, data=body, headers={"Content-Type": "application/octet-stream"}
        ),
        timeout=60,
    )
    return protocol.decode_sync_response(r.read())


def run(store=None, server=None) -> dict:
    """Drive 25 concurrent clients against `server` (or an in-process
    RelayServer over `store`)."""
    own_server = server is None
    if own_server:
        server = RelayServer(store).start()
    latencies: list = []
    lock = threading.Lock()
    barrier = threading.Barrier(CLIENTS)
    errors = []

    def client(i: int):
        user, node = f"user{i:03d}", f"{i + 1:016x}"
        mine = []
        try:
            barrier.wait(timeout=30)
            for rnd in range(ROUNDS):
                req = protocol.SyncRequest(
                    _msgs(node, rnd * MSGS_PER_ROUND, MSGS_PER_ROUND), user, node, "{}"
                )
                t0 = time.perf_counter()
                _post(server.url, req)
                mine.append(time.perf_counter() - t0)
        except Exception as e:  # noqa: BLE001
            errors.append(e)
        with lock:
            latencies.extend(mine)

    try:
        threads = [threading.Thread(target=client, args=(i,)) for i in range(CLIENTS)]
        t0 = time.perf_counter()
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        wall = time.perf_counter() - t0
    finally:
        if own_server:
            server.stop()
    if errors:
        raise errors[0]
    latencies.sort()
    total_msgs = CLIENTS * ROUNDS * MSGS_PER_ROUND
    return {
        "p50_ms": round(statistics.median(latencies) * 1e3, 2),
        "p99_ms": round(latencies[int(len(latencies) * 0.99) - 1] * 1e3, 2),
        "max_ms": round(latencies[-1] * 1e3, 2),
        "requests": len(latencies),
        "msgs_per_sec": round(total_msgs / wall),
    }


def main() -> None:
    import tempfile

    from evolu_tpu.server.relay import MultiprocessRelay

    results = {
        "single_store": run(RelayStore()),
        "sharded_store": run(ShardedRelayStore(shards=8)),
    }
    # Pre-forked multiprocess relay (VERDICT r2 #8): N worker processes
    # on one SO_REUSEPORT port over a shared file-backed WAL store.
    # On a 1-core host this validates the deployment shape and its
    # overheads, not scaling — documented as such.
    for workers in (1, 2, 4):
        with tempfile.TemporaryDirectory() as tmp:
            relay = MultiprocessRelay(
                f"{tmp}/relay.db", workers=workers, shards=8
            ).start()
            try:
                results[f"multiprocess_{workers}w"] = run(server=relay)
            finally:
                relay.stop()
    head = results["sharded_store"]
    print(
        json.dumps(
            {
                "metric": "relay_concurrent_sync_p99_ms",
                "value": head["p99_ms"],
                "unit": "ms @ 25 clients",
                "detail": {
                    "clients": CLIENTS,
                    "rounds": ROUNDS,
                    "msgs_per_round": MSGS_PER_ROUND,
                    "configs": results,
                    "cpus": os.cpu_count(),
                },
            }
        )
    )


if __name__ == "__main__":
    main()
