"""Relay replication anti-entropy: transfer scales with DIVERGENCE.

The claim behind Merkle anti-entropy (server/replicate.py): syncing a
peer costs bandwidth proportional to what DIVERGED, not to database
size. Measured here directly: a source relay holds OWNERS×MINUTES×
PER_MIN messages; destination relays that are (a) fresh (full pull),
(b) 1 minute behind, (c) 8 minutes behind each run one gossip sweep,
and the messages-transferred counter (the same counter the
partition-heal acceptance test asserts on) plus wall time are
recorded.

Throughput uses the SLOPE method (CLAUDE.md timing discipline): the
msgs/s figure is Δtransferred/Δwall between the 1-minute and 8-minute
divergence legs (per-leg medians of 3 runs), so summary/diff overhead
that both legs share cancels out instead of polluting the number.
Liveness: every destination's full end state (tree strings + every
row) folds into a printed crc32 per leg — a sweep that skipped data
changes the checksum, and the per-leg checksums must MATCH the
source's own state checksum (asserted).

Runs host-side only (HTTP + SQLite + Merkle walks — no device leg);
the env is pinned to CPU so importing anything jax-adjacent can never
claim the real chip. Prints ONE JSON line; numbers live in
docs/BENCHMARKS.md.
"""

import json
import os
import statistics
import sys
import time
import zlib

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()
for _v in ("PALLAS_AXON_POOL_IPS", "PALLAS_AXON_REMOTE_COMPILE"):
    os.environ.pop(_v, None)

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from evolu_tpu.core.timestamp import Timestamp, timestamp_to_string
from evolu_tpu.obs import metrics
from evolu_tpu.server.relay import RelayServer, RelayStore
from evolu_tpu.server.replicate import ReplicationManager
from evolu_tpu.sync import protocol
from evolu_tpu.sync.client import _http_post

OWNERS = 8
MINUTES = 60
PER_MIN = 50
BASE = 1_700_000_000_000
TRIALS = 3
DIV_LO, DIV_HI = 1, 8  # minutes of divergence for the slope legs


def _owner_messages(node: str, minutes: int):
    return tuple(
        protocol.EncryptedCrdtMessage(
            timestamp_to_string(
                Timestamp(BASE + m * 60_000 + i * 500, 0, node)
            ),
            b"ct-%d-%d" % (m, i),
        )
        for m in range(minutes)
        for i in range(PER_MIN)
    )


def _owners():
    return [(f"owner{i:02d}", f"{i + 1:016x}") for i in range(OWNERS)]


def _state_crc(store) -> int:
    crc = 0
    for u in sorted(store.user_ids()):
        crc = zlib.crc32(store.get_merkle_tree_string(u).encode(), crc)
        for m in store.replica_messages(u, ""):
            crc = zlib.crc32(m.timestamp.encode(), crc)
            crc = zlib.crc32(m.content, crc)
    return crc


def _sweep(src_url: str, behind_minutes: int, tag: str):
    """One gossip sweep by a destination that is `behind_minutes`
    behind the source (MINUTES = fresh peer). Returns
    (wall_s, messages_pulled, end_state_crc)."""
    dest = RelayStore()
    try:
        if behind_minutes < MINUTES:
            for u, node in _owners():
                dest.add_messages(u, _owner_messages(node, MINUTES - behind_minutes))
        mgr = ReplicationManager(
            dest, [src_url], replica_id=tag,
            http_post=lambda u, d: _http_post(u, d, retries=0),
        )
        t0 = time.perf_counter()
        mgr.run_once()
        wall = time.perf_counter() - t0
        mgr.stop()
        pulled = metrics.get_counter(
            "evolu_repl_messages_pulled_total", replica=tag, peer=src_url.rstrip("/")
        )
        return wall, int(pulled), _state_crc(dest)
    finally:
        dest.close()


def main() -> None:
    src_store = RelayStore()
    for u, node in _owners():
        src_store.add_messages(u, _owner_messages(node, MINUTES))
    src = RelayServer(src_store, peers=[]).start()  # listener-only source
    try:
        src_crc = _state_crc(src_store)
        legs = {}
        for name, behind in (("full", MINUTES), ("lo", DIV_LO), ("hi", DIV_HI)):
            walls, pulls, crcs = [], set(), set()
            for t in range(TRIALS):
                wall, pulled, crc = _sweep(src.url, behind, f"bench-{name}-{t}")
                walls.append(wall)
                pulls.add(pulled)
                crcs.add(crc)
            (pulled,) = pulls  # transfer count must be deterministic
            (crc,) = crcs
            assert crc == src_crc, f"{name}: end state != source ({crc:08x})"
            legs[name] = {
                "behind_minutes": behind,
                "messages_pulled": pulled,
                "wall_median_s": round(statistics.median(walls), 4),
                "end_state_crc": f"{crc:08x}",
            }
    finally:
        src.stop()

    d_msgs = legs["hi"]["messages_pulled"] - legs["lo"]["messages_pulled"]
    d_wall = legs["hi"]["wall_median_s"] - legs["lo"]["wall_median_s"]
    total = OWNERS * MINUTES * PER_MIN
    print(
        json.dumps(
            {
                "metric": "replication_antientropy_transfer_ratio",
                "value": round(
                    legs["full"]["messages_pulled"]
                    / max(1, legs["lo"]["messages_pulled"]),
                    1,
                ),
                "unit": "x fresh-peer transfer vs 1-minute divergence",
                "detail": {
                    "db_messages": total,
                    "owners": OWNERS,
                    "minutes": MINUTES,
                    "legs": legs,
                    "pull_msgs_per_sec_slope": (
                        round(d_msgs / d_wall) if d_wall > 0 else None
                    ),
                    "cpus": os.cpu_count(),
                },
            }
        )
    )


if __name__ == "__main__":
    main()
