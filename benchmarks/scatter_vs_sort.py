"""Sort vs scatter-argmax LWW plan, slope-measured (ISSUE 4).

Same protocol as bench.py (two fused iteration counts per dispatch;
the slope cancels fixed dispatch overhead; every kernel output folds
into the checksum carry so XLA cannot DCE a stage), over the config-3
shard layout on all local devices. The per-iteration perturbation
relabels cells BIJECTIVELY WITHIN the cell-id range (XOR of low bits)
instead of bench.py's high-bit XOR — the scatter kernel's winner table
is sized to the cell-id range, and letting the relabel escape it would
compare a 2^18-cell sort against a 2^25-slot table. Checksum parity
between the two kernels is asserted on the XOR digest (order-free);
the full mask/delta parity is test-pinned in
tests/test_scatter_merge.py.

Prints one JSON line.
"""

import json
import os
import statistics
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np

jax.config.update("jax_enable_x64", True)

N = int(os.environ.get("SVS_N", 1_000_000))
OWNERS = 1_000
ITERS_LO, ITERS_HI = 2, 8


def make_loop(mesh, iters, kernel, cell_bits):
    from evolu_tpu.ops import shard_map
    from jax.sharding import PartitionSpec as P

    spec = P("owners")
    pad_cell = jnp.int32(0x7FFFFFFF)
    low_mask = (1 << (cell_bits - 6)) - 1

    def shard_loop(cell_id, k1, k2, ex_k1, ex_k2, owner_ix):
        def body(i, acc):
            # Bijective in-range relabel: XOR the low cell bits with a
            # per-iteration pattern (cells stay < 2^cell_bits, so both
            # kernels see the same table/key bounds every iteration)
            # and flip HLC node bits so the compare order really moves.
            cid = jnp.where(
                cell_id == pad_cell,
                cell_id,
                cell_id ^ (i * jnp.int32(0x2B)) & jnp.int32(low_mask),
            )
            outs = kernel(cid, k1, k2 ^ i.astype(jnp.uint64), ex_k1, ex_k2, owner_ix)
            local = outs[0].astype(jnp.int64).sum()
            for o in outs[1:-1]:
                local = local + o.astype(jnp.int64).sum()
            masked = jax.lax.psum(local, "owners")
            return acc + masked + outs[-1].astype(jnp.int64)

        return jax.lax.fori_loop(0, iters, body, jnp.int64(0))

    return jax.jit(
        shard_map(shard_loop, mesh=mesh, in_specs=(spec,) * 6, out_specs=P(),
                  check_vma=False)
    )


def main():
    import bench
    from evolu_tpu.ops.merge import _PAD_CELL
    from evolu_tpu.ops.scatter_merge import table_size_for
    from evolu_tpu.parallel.mesh import create_mesh, sharding
    from evolu_tpu.parallel.reconcile import _shard_kernel, scatter_shard_kernel

    mesh = create_mesh()
    n_dev = mesh.devices.size
    shd = sharding(mesh)
    names = ("cell_id", "k1", "k2", "ex_k1", "ex_k2", "owner_ix")
    cols, _ = bench.shard_layout(
        bench.build_columns(n=N, owners=OWNERS, stored_winners=True), n_dev
    )
    real = cols["cell_id"] != int(_PAD_CELL)
    cell_max = int(cols["cell_id"].max(initial=0, where=real))
    table = table_size_for(cell_max)
    cell_bits = table.bit_length() - 1
    variants = {
        "sort": _shard_kernel,
        "scatter": scatter_shard_kernel(table),
    }
    results = {}
    digests = {}
    with jax.enable_x64(True):
        args = [jax.device_put(cols[k], shd) for k in names]
        for label, kernel in variants.items():
            medians = {}
            for iters in (ITERS_LO, ITERS_HI):
                loop = make_loop(mesh, iters, kernel, cell_bits)
                np.asarray(loop(*args))  # compile + warm
                times = []
                for _ in range(5):
                    t0 = time.perf_counter()
                    np.asarray(loop(*args))
                    times.append(time.perf_counter() - t0)
                medians[iters] = statistics.median(times)
            per_iter = (medians[ITERS_HI] - medians[ITERS_LO]) / (ITERS_HI - ITERS_LO)
            results[label] = {
                "per_iter_ms": round(per_iter * 1e3, 2),
                "per_chip": round(N / per_iter / n_dev),
            }
            # Order-free parity probe: the XOR digest of one plain
            # dispatch (the loop checksum itself is order-SENSITIVE in
            # the segment columns — tile-local grouping sees different
            # row orders per kernel — so cross-kernel equality is
            # asserted on the digest; full mask/delta parity is pinned
            # in tests/test_scatter_merge.py).
            from evolu_tpu.ops import shard_map
            from jax.sharding import PartitionSpec as P

            dig = jax.jit(shard_map(
                lambda *a: kernel(*a)[-1], mesh=mesh,
                in_specs=(P("owners"),) * 6, out_specs=P(), check_vma=False,
            ))
            digests[label] = int(np.asarray(dig(*args)))
    print(json.dumps({
        "metric": "scatter_vs_sort_plan",
        "n": N,
        "owners": OWNERS,
        "devices": n_dev,
        "platform": jax.devices()[0].platform,
        "cell_max": cell_max,
        "table_slots": table,
        "variants": results,
        "checksums_equal": digests["sort"] == digests["scatter"],
        "speedup_scatter_over_sort": round(
            results["sort"]["per_iter_ms"] / results["scatter"]["per_iter_ms"], 3
        ),
    }))


if __name__ == "__main__":
    main()
