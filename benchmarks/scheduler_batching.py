"""Continuous-batching scheduler vs per-request relay dispatch.

32 concurrent clients (one owner each, 20 encrypted messages per
round) hammer the HTTP relay twice: once with the per-request
`sync_wire` path (the reference relay's shape) and once through the
`SyncScheduler` → one fused `BatchReconciler` pass per micro-batch.

Throughput uses the SLOPE method (CLAUDE.md timing discipline): each
config is driven at TWO round counts after a warmup leg, and the
msgs/s figure is Δmessages/Δwall between them — server start, jit
warmup, and connection setup cancel out instead of burying the result.
Every response byte feeds a crc32 checksum that is printed, so no
serving leg can be skipped unnoticed.

Runs on the 8-device virtual CPU mesh by default (the env is forced
below, axon tunnel vars stripped, so this never claims the real chip);
set EVOLU_SCHED_BENCH_TPU=1 to inherit the ambient platform instead.

Prints ONE JSON line; numbers live in docs/BENCHMARKS.md.
"""

import json
import os
import statistics
import sys
import threading
import time
import urllib.request
import zlib

if not os.environ.get("EVOLU_SCHED_BENCH_TPU"):
    os.environ["JAX_PLATFORMS"] = "cpu"
    _flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in _flags:
        os.environ["XLA_FLAGS"] = (
            _flags + " --xla_force_host_platform_device_count=8"
        ).strip()
    for _v in ("PALLAS_AXON_POOL_IPS", "PALLAS_AXON_REMOTE_COMPILE"):
        os.environ.pop(_v, None)

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from evolu_tpu.core.timestamp import Timestamp, timestamp_to_string
from evolu_tpu.obs import metrics
from evolu_tpu.server.relay import RelayServer, ShardedRelayStore
from evolu_tpu.sync import protocol

CLIENTS = 32
MSGS_PER_ROUND = 20
ROUNDS_LO, ROUNDS_HI = 2, 8
BASE = 1_700_000_000_000


def _msgs(node: str, start: int, n: int):
    return tuple(
        protocol.EncryptedCrdtMessage(
            timestamp_to_string(Timestamp(BASE + (start + i) * 1000, 0, node)),
            b"x" * 64,
        )
        for i in range(n)
    )


def _drive(url: str, namespace: str, rounds: int):
    """32 concurrent clients × `rounds` push rounds against `url`.
    Returns (wall_s, sorted per-request latencies, response checksum).
    The checksum folds EVERY response's bytes — the liveness guard."""
    latencies: list = []
    checksums = [0] * CLIENTS
    lock = threading.Lock()
    barrier = threading.Barrier(CLIENTS)
    errors: list = []

    def client(i: int):
        user = f"{namespace}-u{i:03d}"
        node = f"{i + 1:016x}"
        mine = []
        crc = 0
        try:
            barrier.wait(timeout=60)
            for rnd in range(rounds):
                req = protocol.SyncRequest(
                    _msgs(node, rnd * MSGS_PER_ROUND, MSGS_PER_ROUND),
                    user, node, "{}",
                )
                body = protocol.encode_sync_request(req)
                t0 = time.perf_counter()
                with urllib.request.urlopen(
                    urllib.request.Request(
                        url, data=body,
                        headers={"Content-Type": "application/octet-stream"},
                    ),
                    timeout=120,
                ) as r:
                    crc = zlib.crc32(r.read(), crc)
                mine.append(time.perf_counter() - t0)
        except Exception as e:  # noqa: BLE001
            errors.append(e)
        checksums[i] = crc
        with lock:
            latencies.extend(mine)

    threads = [threading.Thread(target=client, args=(i,)) for i in range(CLIENTS)]
    t0 = time.perf_counter()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    wall = time.perf_counter() - t0
    if errors:
        raise errors[0]
    latencies.sort()
    checksum = 0
    for c in checksums:
        checksum = zlib.crc32(c.to_bytes(4, "little"), checksum)
    return wall, latencies, checksum


def measure(batching: bool) -> dict:
    store = ShardedRelayStore(shards=4)
    server = RelayServer(store, batching=batching).start()
    passes0 = metrics.get_counter("evolu_sched_batches_total")
    try:
        _drive(server.url, "warm", 1)  # jit + btree warmup, uncounted
        wall_lo, _lats, crc_lo = _drive(server.url, "lo", ROUNDS_LO)
        wall_hi, lats, crc_hi = _drive(server.url, "hi", ROUNDS_HI)
        passes = metrics.get_counter("evolu_sched_batches_total") - passes0
    finally:
        server.stop()
    d_msgs = CLIENTS * MSGS_PER_ROUND * (ROUNDS_HI - ROUNDS_LO)
    d_reqs = CLIENTS * (ROUNDS_HI - ROUNDS_LO)
    n_reqs_counted = CLIENTS * (1 + ROUNDS_LO + ROUNDS_HI)
    return {
        "msgs_per_sec_slope": round(d_msgs / (wall_hi - wall_lo)),
        "reqs_per_sec_slope": round(d_reqs / (wall_hi - wall_lo), 1),
        "p50_ms": round(statistics.median(lats) * 1e3, 2),
        "p99_ms": round(lats[int(len(lats) * 0.99) - 1] * 1e3, 2),
        "wall_lo_s": round(wall_lo, 3),
        "wall_hi_s": round(wall_hi, 3),
        "engine_passes": int(passes) if batching else n_reqs_counted,
        "requests": n_reqs_counted,
        "checksum": f"{crc_lo:08x}/{crc_hi:08x}",
    }


def main() -> None:
    baseline = measure(batching=False)
    batched = measure(batching=True)
    speedup = (
        batched["msgs_per_sec_slope"] / baseline["msgs_per_sec_slope"]
        if baseline["msgs_per_sec_slope"]
        else float("nan")
    )
    print(
        json.dumps(
            {
                "metric": "scheduler_batching_throughput_ratio",
                "value": round(speedup, 2),
                "unit": "x vs per-request dispatch @ 32 clients (slope)",
                "detail": {
                    "clients": CLIENTS,
                    "msgs_per_round": MSGS_PER_ROUND,
                    "rounds": [ROUNDS_LO, ROUNDS_HI],
                    "per_request": baseline,
                    "scheduler": batched,
                    "pass_reduction": round(
                        batched["requests"] / max(1, batched["engine_passes"]), 1
                    ),
                    "cpus": os.cpu_count(),
                },
            }
        )
    )


if __name__ == "__main__":
    main()
