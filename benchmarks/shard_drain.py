"""Parallel owner-sharded drain (PR-19) vs the single drain worker.

The PR-11 write-behind queue moved the SQLite btree off the latency
path but drained it with ONE thread under ONE lock — the host-apply
wall stayed serial no matter how many storage shards the store had.
PR-19 gives every shard its own drain worker, lock, and watermark;
this bench measures what that buys on the DRAIN leg, with process-
level walls (the only honest wall on a shared host: each leg is a
fresh file-backed store + queue, timed from lock release to the
composed drain barrier).

Method (CLAUDE.md timing discipline): per (mode, workers) leg, park
every drain worker by holding the composed `db_lock`, serve the whole
seeded stream (the backlog accumulates in the shard deques), then
release and time `flush()` — the drain wall for that backlog. The
reported slope is Δrows/Δwall between a small and a large backlog, so
store open, replay, and child spawn cancel out. `ratio` =
slope(2 workers)/slope(1 worker) per mode.

Modes:
- `thread`: workers apply in-process (the native path's shape — there
  the C inserts drop the GIL; on the pure-Python backend used here
  sqlite3 still releases the GIL around its C calls).
- `process`: workers feed per-shard child processes over pipes (the
  pure-Python escape hatch from the GIL); the leg asserts the queue
  actually resolved `drain_mode == "process"`.

Gates (hard-fail, run in --smoke too):
- byte-identity: EVERY leg's drained state crc equals the ground-truth
  oracle (direct `add_messages`, no engine, no queue — an independent
  computation, so a serving leg that drops rows cannot go unnoticed).
- audit: the episode-end conservation audit is clean — every queued
  row reached exactly one ledger terminal across all legs.

HONESTY (docs/BENCHMARKS.md): parallel drain needs parallel hardware.
The `ratio >= 1.8` scaling assertion fires whenever `os.cpu_count()`
>= 2 — --smoke included (an armed smoke run widens the backlog to the
full depths so the drain wall dwarfs scheduler jitter; rows stay
small). On a 1-core container the skip is EXPLICIT: the reason is
printed to stderr and recorded in the JSON note, and the measured ~1x
flat line is reported as-is — the point of PR-19 is that the drain
LIMIT moves from "one thread" to "core count". Correctness gates
always run. Prints ONE JSON line; numbers live in docs/BENCHMARKS.md.
"""

import json
import os
import sys
import tempfile
import time
import zlib

os.environ["JAX_PLATFORMS"] = "cpu"
for _v in ("PALLAS_AXON_POOL_IPS", "PALLAS_AXON_REMOTE_COMPILE"):
    os.environ.pop(_v, None)

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from evolu_tpu.core.merkle import merkle_tree_to_string
from evolu_tpu.core.timestamp import Timestamp, timestamp_to_string
from evolu_tpu.obs import ledger
from evolu_tpu.server.engine import BatchReconciler
from evolu_tpu.server.relay import RelayStore, ShardedRelayStore
from evolu_tpu.storage.write_behind import WriteBehindQueue
from evolu_tpu.sync import protocol

BASE = 1_700_000_000_000
OWNERS = 8
SHARDS = 2


def _stream(n_batches: int, rows_per_owner: int, payload: bytes):
    """Seeded batches of distinct-owner in-sync FRESH pushes (the
    steady-state hot shape). All-fresh matters beyond realism: a
    duplicate-redelivery shape bounces the response to the exact path,
    whose serve-side `flush_owner` would deadlock against this bench's
    parked drain (dup correction is pinned by tests/test_write_behind
    and the SIGKILL torture instead). Client trees come from a
    deterministic tree oracle — a reference computation, quarantined
    from the conservation ledger."""
    with ledger.quarantine():
        oracle = RelayStore()
        batches = []
        for b in range(n_batches):
            reqs = []
            for o in range(OWNERS):
                owner = f"owner{o:02d}"
                node = f"{o + 1:016x}"
                msgs = [
                    protocol.EncryptedCrdtMessage(
                        timestamp_to_string(Timestamp(
                            BASE + (b * rows_per_owner + i) * 1000, 0, node
                        )),
                        payload,
                    )
                    for i in range(rows_per_owner)
                ]
                tree = oracle.add_messages(owner, msgs)
                reqs.append(protocol.SyncRequest(
                    tuple(msgs), owner, node, merkle_tree_to_string(tree)
                ))
            batches.append(reqs)
        oracle.close()
    return batches


def _state_crc(store) -> int:
    crc = 0
    for s in (getattr(store, "shards", None) or [store]):
        for u in sorted(s.user_ids()):
            crc = zlib.crc32(s.get_merkle_tree_string(u).encode(), crc)
            for m in s.replica_messages(u, ""):
                crc = zlib.crc32(m.timestamp.encode(), crc)
                crc = zlib.crc32(m.content, crc)
    return crc


def _ground_truth_crc(batches) -> int:
    """Direct add_messages — no engine, no queue: the independent
    oracle every drained leg must match byte-for-byte."""
    with ledger.quarantine():
        store = ShardedRelayStore(shards=SHARDS)
        for reqs in batches:
            for r in reqs:
                store.add_messages(r.user_id, r.messages)
        crc = _state_crc(store)
        store.close()
    return crc


def _drain_leg(tmp, tag, warmup, batches, workers, process):
    """Drain `warmup` end-to-end first (spawns the per-shard children
    in process mode, warms the btree files), then serve `batches` with
    every drain worker parked behind db_lock, and time the released
    flush. → (drain_wall_s, rows, crc, mode)."""
    path = os.path.join(tmp, f"{tag}.db")
    store = ShardedRelayStore(path, backend="python", shards=SHARDS)
    wb = WriteBehindQueue(store, log_path=path + ".wblog",
                          drain_workers=workers, drain_process=process)
    eng = BatchReconciler(store, write_behind=wb)

    def serve(reqs):
        # The bench IS the delivery boundary (no HTTP front): ingress
        # posts here, where relay.py posts it at decode.
        for r in reqs:
            ledger.count(ledger.INGRESS_SYNC, len(r.messages),
                         owner=r.user_id)
        eng.run_batch_wire(reqs)
        return sum(len(r.messages) for r in reqs)

    serve(warmup)
    wb.flush()
    rows = 0
    wb.db_lock.acquire()
    try:
        for reqs in batches:
            rows += serve(reqs)
    finally:
        wb.db_lock.release()
    t0 = time.perf_counter()
    wb.flush()
    wall = time.perf_counter() - t0
    mode = wb.drain_mode
    crc = _state_crc(store)
    wb.close()
    eng.close()
    store.close()
    return wall, rows, crc, mode


def main() -> None:
    smoke = "--smoke" in sys.argv
    cpus = os.cpu_count() or 1
    assert_scaling = cpus >= 2  # armed under --smoke too (ISSUE 20)
    rows_per_owner = 16 if smoke else 96
    # An ARMED smoke run uses the full backlog depths (rows stay
    # small): the ratio needs per-leg drain walls that dwarf
    # scheduler jitter, or a passing 1.8x would be luck, not scaling.
    lo, hi = (2, 5) if (smoke and not assert_scaling) else (4, 16)
    skip_reason = None
    if not assert_scaling:
        skip_reason = (
            f"scaling assertion skipped: os.cpu_count()={cpus} < 2 — "
            "parallel drain cannot beat one worker without a second "
            "core; correctness gates (byte-identity, audit) still ran"
        )
        print(f"shard_drain: {skip_reason}", file=sys.stderr)

    batches = _stream(hi + 1, rows_per_owner, b"x" * 64)
    # Batch 0 is the (drained, untimed) warmup; a count-n leg ends
    # with batches[:1+n] applied.
    want_crc = {n: _ground_truth_crc(batches[:1 + n]) for n in (lo, hi)}
    # Both shards must actually carry load or the ratio is vacuous.
    covered = {zlib.crc32(f"owner{o:02d}".encode()) % SHARDS
               for o in range(OWNERS)}
    assert covered == set(range(SHARDS)), covered

    legs = {}
    with tempfile.TemporaryDirectory() as tmp:
        for mode_name, process in (("thread", False), ("process", True)):
            for workers in (1, 2):
                walls = {}
                for count in (lo, hi):
                    tag = f"{mode_name}-w{workers}-n{count}"
                    wall, rows, crc, got_mode = _drain_leg(
                        tmp, tag, batches[0], batches[1:1 + count],
                        workers, process)
                    assert crc == want_crc[count], (
                        f"{tag}: drained state != ground-truth oracle "
                        f"({crc:08x} != {want_crc[count]:08x})"
                    )
                    assert got_mode == mode_name, (tag, got_mode)
                    walls[count] = (wall, rows)
                d_wall = walls[hi][0] - walls[lo][0]
                d_rows = walls[hi][1] - walls[lo][1]
                legs[f"{mode_name}_w{workers}"] = {
                    "drain_rows_per_s": round(d_rows / max(d_wall, 1e-9)),
                    "wall_lo_s": round(walls[lo][0], 4),
                    "wall_hi_s": round(walls[hi][0], 4),
                }

    ratios = {
        m: round(legs[f"{m}_w2"]["drain_rows_per_s"]
                 / max(legs[f"{m}_w1"]["drain_rows_per_s"], 1), 2)
        for m in ("thread", "process")
    }
    if assert_scaling:
        best = max(ratios.values())
        assert best >= 1.8, (
            f"2-worker drain only {best:.2f}x the single worker on "
            f"{cpus} cores (ratios={ratios})"
        )

    violations = ledger.audit(at_barrier=True)
    assert not violations, violations

    print(json.dumps({
        "bench": "shard_drain",
        "smoke": smoke,
        "platform": "cpu",
        "shards": SHARDS,
        "owners": OWNERS,
        "rows_hi": hi * OWNERS * rows_per_owner,
        "legs": legs,
        "ratio_thread": ratios["thread"],
        "ratio_process": ratios["process"],
        "state_crc": f"{want_crc[hi]:08x}",
        "byte_identical": True,
        "audit_clean": True,
        "note": {"cpus": cpus, "scaling_asserted": assert_scaling,
                 "skip_reason": skip_reason},
    }))


if __name__ == "__main__":
    main()
