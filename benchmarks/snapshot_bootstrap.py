"""Fresh-peer cold start: snapshot bootstrap vs pure anti-entropy.

The claim behind server/snapshot.py: a fresh relay joining a fleet (or
restoring after disk loss) should cold-start in O(state) — one
manifest plus a handful of big crc-checked chunks — instead of
crawling the whole history through `serve_pull`'s capped, minute-
ranged rounds, each of which also re-ships BOTH sides' full per-owner
tree summaries. Measured here directly: one donor holding
OWNERS×MINUTES×PER_MIN messages; fresh destination relays converge by
(a) pure PR-3 anti-entropy under the donor's serve_pull caps —
swept honestly across the default caps AND production-latency-bounded
tight caps (the satellite made them constructor args) — and
(b) snapshot bootstrap. Per leg: HTTP round-trips (the same
`evolu_repl_round_trips_total` counter the acceptance test asserts
on), total wire bytes (request+response, counted at the transport),
wall, and the end-state crc32 (trees + every row), which must MATCH
the donor's own state crc (asserted — a leg that skipped data cannot
pass).

Round-trip accounting is the honest story here: at small histories
the default pull caps are generous enough that anti-entropy needs few
rounds too (reported as-is, including when the ratio is ~1); the
snapshot win scales with history ÷ caps, which the tight-caps leg and
the bytes column make visible without extrapolation.

Runs host-side only (HTTP + SQLite + Merkle walks — no device leg);
env pinned to CPU. Prints ONE JSON line; numbers live in
docs/BENCHMARKS.md. `--smoke` runs a tiny end-to-end pass for CI
(path exercise + crc identity, no ratio claims).
"""

import argparse
import json
import os
import sys
import time
import zlib

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()
for _v in ("PALLAS_AXON_POOL_IPS", "PALLAS_AXON_REMOTE_COMPILE"):
    os.environ.pop(_v, None)

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from evolu_tpu.core.timestamp import Timestamp, timestamp_to_string
from evolu_tpu.obs import metrics
from evolu_tpu.server.relay import RelayServer, RelayStore
from evolu_tpu.server.replicate import ReplicationManager
from evolu_tpu.sync import protocol
from evolu_tpu.sync.client import _http_post

BASE = 1_700_000_000_000


class _CountingPost:
    """Transport wrapper: every call is one HTTP round-trip; bytes are
    request + response payloads (the honest wire cost, incl. the
    per-round summary overhead anti-entropy pays)."""

    def __init__(self):
        self.calls = 0
        self.bytes = 0

    def __call__(self, url, body):
        out = _http_post(url, body, retries=0)
        self.calls += 1
        self.bytes += len(body) + len(out)
        return out


def _seed(store, owners, minutes, per_min):
    for i in range(owners):
        node = f"{i + 1:016x}"
        msgs = tuple(
            protocol.EncryptedCrdtMessage(
                timestamp_to_string(
                    Timestamp(BASE + m * 60_000 + j * 500, 0, node)
                ),
                b"ct-%d-%d" % (m, j),
            )
            for m in range(minutes)
            for j in range(per_min)
        )
        store.add_messages(f"owner{i:03d}", msgs)


def _state_crc(store) -> int:
    crc = 0
    for u in sorted(store.user_ids()):
        crc = zlib.crc32(store.get_merkle_tree_string(u).encode(), crc)
        for m in store.replica_messages(u, ""):
            crc = zlib.crc32(m.timestamp.encode(), crc)
            crc = zlib.crc32(m.content, crc)
    return crc


def _converge_anti(donor_url, src_crc, tag, max_rounds=500):
    dest = RelayStore()
    post = _CountingPost()
    mgr = ReplicationManager(dest, [donor_url], replica_id=tag, http_post=post)
    try:
        t0 = time.perf_counter()
        rounds = 0
        while rounds < max_rounds:
            mgr.run_once()
            rounds += 1
            if _state_crc(dest) == src_crc:
                break
        wall = time.perf_counter() - t0
        crc = _state_crc(dest)
        pulled = metrics.get_counter(
            "evolu_repl_messages_pulled_total", replica=tag,
            peer=donor_url.rstrip("/"),
        )
        return {
            "round_trips": post.calls,
            "wire_bytes": post.bytes,
            "gossip_rounds": rounds,
            "messages_pulled": int(pulled),
            "wall_s": round(wall, 4),
            "end_state_crc": f"{crc:08x}",
            "converged": crc == src_crc,
        }
    finally:
        mgr.stop()
        dest.close()


def _converge_snapshot(donor_url, src_crc, tag, chunk_bytes):
    dest = RelayStore()
    post = _CountingPost()
    mgr = ReplicationManager(
        dest, [donor_url], replica_id=tag, http_post=post,
        bootstrap_lag_owners=1, snapshot_chunk_bytes=chunk_bytes,
    )
    try:
        t0 = time.perf_counter()
        mgr.run_once()  # bootstrap round
        mgr.run_once()  # watermark gossip round (confirms convergence)
        wall = time.perf_counter() - t0
        crc = _state_crc(dest)
        return {
            "round_trips": post.calls,
            "wire_bytes": post.bytes,
            "chunks": int(metrics.get_counter(
                "evolu_snap_chunks_fetched_total", replica=tag,
                peer=donor_url.rstrip("/"),
            )),
            "messages_pulled": int(metrics.get_counter(
                "evolu_repl_messages_pulled_total", replica=tag,
                peer=donor_url.rstrip("/"),
            )),
            "wall_s": round(wall, 4),
            "end_state_crc": f"{crc:08x}",
            "converged": crc == src_crc,
        }
    finally:
        mgr.stop()
        dest.close()


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("--smoke", action="store_true",
                    help="tiny CI pass: exercise the path, assert crc identity")
    args = ap.parse_args()

    if args.smoke:
        owners, minutes, per_min = 12, 2, 5
        cap_sweep = [("tight", 16, 64)]
        chunk_bytes = 64 << 10
    else:
        owners, minutes, per_min = 100, 10, 100  # 100k messages
        cap_sweep = [("default", None, None), ("tight", 1024, 8192)]
        chunk_bytes = 4 << 20

    donor_store = RelayStore()
    _seed(donor_store, owners, minutes, per_min)
    donor_mgr = ReplicationManager(donor_store, [], replica_id="bench-donor")
    donor = RelayServer(donor_store, replication=donor_mgr).start()
    try:
        src_crc = _state_crc(donor_store)
        legs = {}
        for cap_name, per_owner, per_resp in cap_sweep:
            donor_mgr.pull_messages_per_owner = per_owner
            donor_mgr.pull_messages_per_response = per_resp
            legs[f"anti_{cap_name}"] = {
                "pull_caps": [per_owner, per_resp],
                **_converge_anti(donor.url, src_crc, f"bench-anti-{cap_name}"),
            }
        donor_mgr.pull_messages_per_owner = None
        donor_mgr.pull_messages_per_response = None
        legs["snapshot"] = {
            "chunk_bytes": chunk_bytes,
            **_converge_snapshot(donor.url, src_crc, "bench-snap", chunk_bytes),
        }
    finally:
        donor.stop()

    for name, leg in legs.items():
        assert leg["converged"], f"{name}: end state != donor ({leg})"
        assert leg["end_state_crc"] == f"{src_crc:08x}"

    anti_key = "anti_tight" if "anti_tight" in legs else next(iter(legs))
    ratio = legs[anti_key]["round_trips"] / max(1, legs["snapshot"]["round_trips"])
    print(
        json.dumps(
            {
                "metric": "snapshot_bootstrap_round_trip_ratio",
                "value": round(ratio, 1),
                "unit": f"x fewer HTTP round-trips vs anti-entropy ({anti_key})",
                "detail": {
                    "db_messages": owners * minutes * per_min,
                    "owners": owners,
                    "smoke": bool(args.smoke),
                    "legs": legs,
                    "cpus": os.cpu_count(),
                },
            }
        )
    )


if __name__ == "__main__":
    main()
