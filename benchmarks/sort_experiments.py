"""Slope-measured sort-stage experiments on the real chip (VERDICT r3
next #1). Each candidate is timed with bench.py's two-point fused-loop
slope method (RTT cancelled, checksum consumes every output so XLA
cannot DCE a stage). Prints one JSON line per experiment; conclusions
live in docs/BENCHMARKS.md.

Candidates:
  merge_stable   — r3 production: 1×i32 key, stable, 5 payloads
  merge_packed   — r4 production: (cell<<24|idx) 1×i64 key, UNSTABLE,
                   4 payloads (idx recovered from the key's low bits)
  minute_i64     — the r3 minute sort alone (packed i64 key, 1 payload)
  minute_scan    — the full r3 minute stage (global sort + XOR scan),
                   inlined for comparison
  minute_rowsort — r4 production: tile-local grouping via a row-wise
                   sort of a (N/8192, 8192) view (segment_xor2_core)

Measured r4 negative result (kernel deleted; git history has it): a
Pallas block-local bitonic group-by (91-stage XOR-partner network via
pltpu.roll) ran 1.75 ms vs minute_rowsort's 0.33 — VPU-compute-bound;
see docs/BENCHMARKS.md.
"""

import json
import os
import statistics
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np

N = int(os.environ.get("SORT_N", 1 << 20))
ITERS_LO, ITERS_HI = 4, 36
REPS = 8


def build(seed=7):
    rng = np.random.default_rng(seed)
    cells = max(N // 4, 1)
    cell_id = rng.integers(0, cells, N).astype(np.int32)
    k1 = ((1_700_000_000_000 + rng.integers(0, 86_400_000, N).astype(np.int64))
          .astype(np.uint64) << np.uint64(16)) | rng.integers(0, 256, N).astype(np.uint64)
    k2 = rng.integers(1, 2**63, N).astype(np.uint64)
    ex1 = rng.integers(0, 2**63, N).astype(np.uint64)
    ex2 = rng.integers(0, 2**63, N).astype(np.uint64)
    owner = rng.integers(0, 1000, N).astype(np.int32)
    minute = ((1_700_000_000_000 + rng.integers(0, 86_400_000, N)) // 60000).astype(np.int32)
    hashes = rng.integers(0, 2**32, N).astype(np.uint32)
    return dict(cell_id=cell_id, k1=k1, k2=k2, ex1=ex1, ex2=ex2,
                owner=owner, minute=minute, hashes=hashes)


def slope_time(make_loop, args):
    """Wall at two fused iteration counts → per-iteration slope."""
    medians = {}
    for iters in (ITERS_LO, ITERS_HI):
        fn = jax.jit(make_loop(iters))
        np.asarray(fn(*args))  # compile + warm
        times = []
        for _ in range(REPS):
            t0 = time.perf_counter()
            np.asarray(fn(*args))
            times.append(time.perf_counter() - t0)
        medians[iters] = statistics.median(times)
    return (medians[ITERS_HI] - medians[ITERS_LO]) / (ITERS_HI - ITERS_LO)


def _fold(acc, outs):
    local = outs[0].astype(jnp.int64).sum()
    for o in outs[1:]:
        local = local + o.astype(jnp.int64).sum()
    return acc + local


def merge_stable(cols):
    cell, k1, k2, e1, e2 = (jnp.asarray(cols[k]) for k in ("cell_id", "k1", "k2", "ex1", "ex2"))

    def make(iters):
        def loop(cell, k1, k2, e1, e2):
            def body(i, acc):
                c = cell ^ (i << 20).astype(jnp.int32)
                idx = jnp.arange(N, dtype=jnp.int32)
                outs = jax.lax.sort((c, idx, k1 ^ i.astype(jnp.uint64), k2, e1, e2),
                                    num_keys=1, is_stable=True)
                return _fold(acc, outs)
            return jax.lax.fori_loop(0, iters, body, jnp.int64(0))
        return loop

    return make, (cell, k1, k2, e1, e2)


def merge_packed(cols):
    cell, k1, k2, e1, e2 = (jnp.asarray(cols[k]) for k in ("cell_id", "k1", "k2", "ex1", "ex2"))

    def make(iters):
        def loop(cell, k1, k2, e1, e2):
            def body(i, acc):
                c = cell ^ (i << 20).astype(jnp.int32)
                idx = jnp.arange(N, dtype=jnp.int32)
                key = (c.astype(jnp.int64) << jnp.int64(24)) | idx.astype(jnp.int64)
                outs = jax.lax.sort((key, k1 ^ i.astype(jnp.uint64), k2, e1, e2),
                                    num_keys=1, is_stable=False)
                i_s = (outs[0] & jnp.int64((1 << 24) - 1)).astype(jnp.int32)
                return _fold(acc, outs[1:] + (i_s,))
            return jax.lax.fori_loop(0, iters, body, jnp.int64(0))
        return loop

    return make, (cell, k1, k2, e1, e2)


def minute_i64(cols):
    owner, minute, hashes = (jnp.asarray(cols[k]) for k in ("owner", "minute", "hashes"))

    def make(iters):
        def loop(owner, minute, hashes):
            def body(i, acc):
                key = (owner.astype(jnp.int64) << jnp.int64(32)) | (
                    (minute ^ i).astype(jnp.uint32).astype(jnp.int64))
                outs = jax.lax.sort((key, hashes), num_keys=1, is_stable=False)
                return _fold(acc, outs)
            return jax.lax.fori_loop(0, iters, body, jnp.int64(0))
        return loop

    return make, (owner, minute, hashes)


def minute_scan(cols):
    """The r3 GLOBAL formulation (full packed-i64 sort + scan),
    inlined so it stays comparable after segment_xor2_core moved to
    tile-local sorting."""
    from evolu_tpu.ops.merkle_ops import _SENTINEL_HI, segmented_xor_scan

    owner, minute, hashes = (jnp.asarray(cols[k]) for k in ("owner", "minute", "hashes"))

    def make(iters):
        def loop(owner, minute, hashes):
            def body(i, acc):
                key = (owner.astype(jnp.int64) << jnp.int64(32)) | (
                    (minute ^ i).astype(jnp.uint32).astype(jnp.int64))
                k_s, h_s = jax.lax.sort(
                    (key, hashes ^ i.astype(jnp.uint32)), num_keys=1, is_stable=False)
                hi_s = (k_s >> jnp.int64(32)).astype(jnp.int32)
                valid = hi_s != jnp.int32(_SENTINEL_HI)
                change = k_s[1:] != k_s[:-1]
                seg_start = jnp.concatenate([jnp.ones((1,), bool), change])
                seg_end = jnp.concatenate([change, jnp.ones((1,), bool)])
                seg_xor = segmented_xor_scan(seg_start, h_s)
                return _fold(acc, (hi_s, k_s.astype(jnp.int32), seg_end, seg_xor, valid))
            return jax.lax.fori_loop(0, iters, body, jnp.int64(0))
        return loop

    return make, (owner, minute, hashes)


def minute_rowsort(cols):
    """Tile-local grouping: sort a (N/8192, 8192) view row-wise — the
    r4 production formulation inside segment_xor2_core."""
    owner, minute, hashes = (jnp.asarray(cols[k]) for k in ("owner", "minute", "hashes"))

    def make(iters):
        def loop(owner, minute, hashes):
            def body(i, acc):
                from evolu_tpu.ops.merkle_ops import segment_xor2_core

                outs = segment_xor2_core(owner, minute ^ i, hashes ^ i.astype(jnp.uint32))
                return _fold(acc, outs)
            return jax.lax.fori_loop(0, iters, body, jnp.int64(0))
        return loop

    return make, (owner, minute, hashes)


EXPERIMENTS = {
    "merge_stable": merge_stable,
    "merge_packed": merge_packed,
    "minute_i64": minute_i64,
    "minute_scan": minute_scan,
    "minute_rowsort": minute_rowsort,
}


def main():
    names = sys.argv[1:] or list(EXPERIMENTS)
    cols = build()
    out = {}
    with jax.enable_x64(True):
        for name in names:
            try:
                make, args = EXPERIMENTS[name](cols)
                per_iter = slope_time(make, args)
                out[name] = round(per_iter * 1e3, 3)
            except Exception as e:  # noqa: BLE001 - record and continue
                out[name] = f"error: {e}"[:200]
    print(json.dumps({"metric": "sort_experiments_ms_per_iter", "n": N,
                      "platform": jax.devices()[0].platform, "results": out}))


if __name__ == "__main__":
    main()
