"""Self-ablating stage anatomy of the fused reconcile pipeline (ISSUE 16).

"Re-ablate stages after every restructure" (CLAUDE.md) as an artifact
instead of a ritual: this harness builds one stage-TRUNCATED timed
variant per device stage of the registry in `evolu_tpu/obs/anatomy.py`
(key_sort → plan_compare → hash_render → minute_fold → delta_encode —
each variant keeps every output produced so far), verifies per variant
that EVERY retained output feeds the checksum carry (the r2/r3 DCE
lesson: a dead output means XLA silently times a smaller pipeline),
slope-measures each variant between two fused iteration counts (never
wall/count — the fixed dispatch RTT buries the figure), and reports
per-stage marginal costs, shares of the full pipeline, and the priced
roofline floors from the registry's cost laws. The pull wave is
measured separately (per-wave slope of `to_host_many` on the real
9-output kernel) since it lives outside the fused loop.

The JSON line is the `anatomy` baseline artifact
(`docs/baselines/anatomy.<platform>.json` via
benchmarks/compare_baselines.py). Hard gates even under --smoke:
`liveness_pass` (bool), `registry_digest` (registry/cost-law
fingerprint from obs.anatomy), and `pipeline_digest` (jaxpr primitive
multiset of the full variant at a fixed probe shape) — so restructuring
the pipeline or re-pricing a law without re-recording the baseline
from a real run fails CI. Stage shares/slopes are tolerance-compared
(25%) on non-smoke checks.

Usage:
    python benchmarks/stage_anatomy.py            # full (seeds laws)
    python benchmarks/stage_anatomy.py --smoke    # CI: tiny N, gates hard
    python benchmarks/stage_anatomy.py | \
        python benchmarks/compare_baselines.py --update anatomy

Prints exactly one JSON line.
"""

import argparse
import json
import os
import statistics
import sys
import time
from collections import Counter

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

import bench
from evolu_tpu.obs import anatomy
from evolu_tpu.ops import shard_map, to_host_many
from evolu_tpu.ops.encode import timestamp_hashes, unpack_ts_keys
from evolu_tpu.ops.merge import masks_from_sorted_flags, winner_flags
from evolu_tpu.ops.merkle_ops import owner_minute_segments
from evolu_tpu.parallel.mesh import create_mesh, sharding
from evolu_tpu.parallel.reconcile import (
    _CELL_BITS,
    _PAD_OWNER,
    pack_owner_cell_key,
    xor_allreduce,
)

# The ablation order IS the registry order; the import-time assert
# below fails the harness (and its smoke CI step) the moment the
# registry and this builder drift apart.
DEVICE_STAGES = tuple(s.name for s in anatomy.STAGES if s.kind == "device")
_EXPECTED_ORDER = ("key_sort", "plan_compare", "hash_render",
                   "minute_fold", "delta_encode")
assert DEVICE_STAGES == _EXPECTED_ORDER, (
    f"registry device stages {DEVICE_STAGES} no longer match the "
    f"variant builder {_EXPECTED_ORDER} — update build_variant AND "
    f"re-record docs/baselines/anatomy.*.json"
)

# Outputs added by each stage (must mirror the registry declaration —
# asserted below) and the cumulative variant arity.
_STAGE_OUTPUTS = {s.name: s.outputs for s in anatomy.STAGES
                  if s.kind == "device"}


def variant_arity(upto: str) -> int:
    k = DEVICE_STAGES.index(upto) + 1
    return sum(len(_STAGE_OUTPUTS[s]) for s in DEVICE_STAGES[:k])


def stage_output_indices(stage: str):
    """Indices (into the variant output tuple) of the outputs ADDED by
    `stage` — the per-stage liveness fence perturbs exactly these."""
    lo = variant_arity(stage) - len(_STAGE_OUTPUTS[stage])
    return range(lo, variant_arity(stage))


def build_variant(upto: str):
    """The reconcile shard kernel truncated after `upto`, retaining
    EVERY output produced so far (liveness discipline: the timed loop
    folds all of them, so no earlier stage is ever dead code in a
    later variant). Stage bodies are verbatim the production pipeline:
    reconcile._shard_kernel for the first four stages,
    engine._compact_segments_tail's encode tail for the fifth. Must be
    traced under enable_x64(True)."""
    k = DEVICE_STAGES.index(upto) + 1
    active = frozenset(DEVICE_STAGES[:k])

    def kernel(cell_id, k1, k2, ex_k1, ex_k2, owner_ix):
        n = cell_id.shape[0]
        idx = jnp.arange(n, dtype=jnp.int32)
        a, b = winner_flags(k1, k2, ex_k1, ex_k2)
        key = pack_owner_cell_key(
            owner_ix, cell_id, idx, lo_bits=2,
            lo=(b.astype(jnp.int64) << jnp.int64(1)) | a.astype(jnp.int64),
        )
        key_s, s1, s2 = jax.lax.sort((key, k1, k2), num_keys=1, is_stable=False)
        outs = [key_s, s1, s2]
        if "plan_compare" in active:
            owner_s = (key_s >> jnp.int64(_CELL_BITS + 26)).astype(jnp.int32)
            i_s = ((key_s >> jnp.int64(2)) & jnp.int64((1 << 24) - 1)).astype(jnp.int32)
            a_s = (key_s & jnp.int64(1)) != 0
            b_s = (key_s & jnp.int64(2)) != 0
            real = owner_s != jnp.int32(_PAD_OWNER)
            xor_s, upsert_s = masks_from_sorted_flags(
                key_s >> jnp.int64(26), s1, s2, a_s, b_s, real
            )
            outs += [xor_s, upsert_s, i_s]
        if "hash_render" in active:
            millis_s, counter_s = unpack_ts_keys(s1)
            hashes = jnp.where(
                xor_s, timestamp_hashes(millis_s, counter_s, s2), jnp.uint32(0)
            )
            digest = xor_allreduce(
                jax.lax.reduce(hashes, jnp.uint32(0), jnp.bitwise_xor, (0,))
            )
            outs += [hashes, digest]
        if "minute_fold" in active:
            owner_sorted, minute_sorted, seg_end, seg_xor, valid_sorted = (
                owner_minute_segments(owner_s, millis_s, hashes, xor_s)
            )
            outs += [owner_sorted, minute_sorted, seg_end, seg_xor, valid_sorted]
        if "delta_encode" in active:
            # engine._compact_segments_tail's encode tail (the compact
            # delta 16B/row wire form): pack owner<<32|minute, stable
            # float-segments-to-front sort, distinct-segment count.
            is_seg = seg_end & valid_sorted
            packed = (
                owner_sorted.astype(jnp.uint64) << jnp.uint64(32)
            ) | minute_sorted.astype(jnp.uint32).astype(jnp.uint64)
            _, packed_c, xor_c = jax.lax.sort(
                (~is_seg, packed, seg_xor), num_keys=1, is_stable=True
            )
            seg_count = jnp.sum(is_seg.astype(jnp.int32))
            outs += [packed_c, xor_c, seg_count]
        assert len(outs) == variant_arity(upto), (
            f"variant {upto}: {len(outs)} outputs vs registry "
            f"{variant_arity(upto)} — registry and builder drifted"
        )
        return tuple(outs)

    return kernel


def make_variant_loop(mesh, iters, kernel):
    """bench.make_loop generalized to variable arity: `iters` fused
    iterations whose carry folds EVERY variant output (inputs
    perturbed per iteration so XLA cannot CSE, exactly the bench's
    discipline)."""
    spec = P("owners")
    pad_cell = jnp.int32(0x7FFFFFFF)

    def shard_loop(cell_id, k1, k2, ex_k1, ex_k2, owner_ix):
        def body(i, acc):
            cid = jnp.where(
                cell_id == pad_cell, cell_id, cell_id ^ (i << 18).astype(jnp.int32)
            )
            outs = kernel(cid, k1, k2 ^ i.astype(jnp.uint64), ex_k1, ex_k2,
                          owner_ix)
            local = outs[0].astype(jnp.int64).sum()
            for o in outs[1:]:
                local = local + o.astype(jnp.int64).sum()
            return acc + jax.lax.psum(local, "owners")

        return jax.lax.fori_loop(0, iters, body, jnp.int64(0))

    return jax.jit(
        shard_map(shard_loop, mesh=mesh, in_specs=(spec,) * 6,
                  out_specs=P(), check_vma=False)
    )


def perturbing_kernel(base_kernel, j, arity):
    """The variant kernel with output j nudged by one unit/flip — the
    minimal observable change a live fold must propagate (the
    tests/test_bench_liveness.py pattern, arity-generic)."""

    def kernel(*args):
        outs = list(base_kernel(*args))
        assert len(outs) == arity, f"variant grew to {len(outs)} outputs"
        o = outs[j]
        if o.ndim == 0:
            outs[j] = ~o if o.dtype == jnp.bool_ else o + jnp.ones((), o.dtype)
        elif o.dtype == jnp.bool_:
            outs[j] = o.at[0].set(~o[0])
        else:
            outs[j] = o.at[0].add(jnp.ones((), o.dtype))
        return tuple(outs)

    return kernel


def liveness_check(mesh, args, upto: str):
    """Per-variant DCE fence: returns the list of output indices whose
    perturbation does NOT move the checksum (must be empty). iters=1 so
    a bool-flip delta cannot cancel across iterations."""
    kernel = build_variant(upto)
    arity = variant_arity(upto)
    base = int(make_variant_loop(mesh, 1, kernel)(*args))
    dead = []
    for j in range(arity):
        loop = make_variant_loop(mesh, 1, perturbing_kernel(kernel, j, arity))
        if int(loop(*args)) == base:
            dead.append(j)
    return dead


def _interleaved_samples(mesh, args, kernels, iters_pair, reps):
    """Wall-time samples for every (variant, iteration-count) pair,
    taken round-robin: compile everything first, then each rep round
    times all pairs back-to-back. Marginals are differences of slopes
    — on a shared 1-core box, minutes-apart slopes carry enough load
    drift to swamp any stage under ~300 ms/iter (three early runs put
    hash_render's marginal at 3, 105 and 119 ms). Interleaving puts
    the subtracted measurements seconds apart inside one rep round, so
    drift hits both sides of every difference."""
    loops = {}
    for name, kernel in kernels.items():
        for iters in iters_pair:
            loop = make_variant_loop(mesh, iters, kernel)
            np.asarray(loop(*args))  # compile + warm
            loops[(name, iters)] = loop
    samples = {key: [] for key in loops}
    for _ in range(reps):
        for key, loop in loops.items():
            t0 = time.perf_counter()
            np.asarray(loop(*args))
            samples[key].append(time.perf_counter() - t0)
    return samples


def _per_rep_slopes(samples, names, iters_pair, reps):
    """Per-rep two-point slopes (seconds/iter) per variant — the
    CLAUDE.md slope rule applied within each rep round."""
    lo, hi = iters_pair
    return {
        name: [
            (samples[(name, hi)][r] - samples[(name, lo)][r]) / (hi - lo)
            for r in range(reps)
        ]
        for name in names
    }


def measure_pull_wave(mesh, cols, wave_pair, reps):
    """Per-wave slope of `to_host_many` over the real 9-output kernel's
    device results (the wave lives OUTSIDE the fused loop, so it gets
    its own two-point measurement over wave counts)."""
    from evolu_tpu.parallel.reconcile import reconcile_columns_sharded

    outs = reconcile_columns_sharded(mesh, cols)
    wave_bytes = sum(int(a.nbytes) for a in to_host_many(*outs))  # warm
    lo, hi = wave_pair
    medians = {}
    for waves in wave_pair:
        runs = []
        for _ in range(reps):
            t0 = time.perf_counter()
            for _ in range(waves):
                to_host_many(*outs)
            runs.append(time.perf_counter() - t0)
        medians[waves] = statistics.median(runs)
    per_wave_ms = (medians[hi] - medians[lo]) / (hi - lo) * 1e3
    mb = wave_bytes / 1e6
    return {
        "ms_per_wave": round(per_wave_ms, 4),
        "wave_mb": round(mb, 3),
        "mb_per_s": round(mb / (per_wave_ms / 1e3), 2) if per_wave_ms > 0 else 0.0,
    }


def _sub_jaxprs(v):
    vs = v if isinstance(v, (list, tuple)) else (v,)
    out = []
    for x in vs:
        if hasattr(x, "eqns"):
            out.append(x)
        elif hasattr(getattr(x, "jaxpr", None), "eqns"):
            out.append(x.jaxpr)
    return out


def _collect_prims(jaxpr, acc):
    for eqn in jaxpr.eqns:
        acc.append(eqn.primitive.name)
        for v in eqn.params.values():
            for sub in _sub_jaxprs(v):
                _collect_prims(sub, acc)


def pipeline_fingerprint(mesh) -> str:
    """crc32 of the full variant's jaxpr primitive multiset at a FIXED
    probe shape (independent of run size, so smoke and full runs agree).
    A perf restructure of any stage changes the traced program →
    changes this digest → the baseline gate fails until the anatomy is
    re-recorded. Falls back to the string form if jaxpr internals move
    between jax versions."""
    import zlib

    n = mesh.devices.size * 64
    probe = (
        np.full(n, 0x7FFFFFFF, np.int32),      # cell_id: all padding
        np.zeros(n, np.uint64), np.zeros(n, np.uint64),
        np.zeros(n, np.uint64), np.zeros(n, np.uint64),
        np.zeros(n, np.int64),
    )
    loop = make_variant_loop(mesh, 1, build_variant(DEVICE_STAGES[-1]))
    with jax.enable_x64(True):
        jaxpr = jax.make_jaxpr(loop)(*probe)
    try:
        prims: list = []
        _collect_prims(jaxpr.jaxpr, prims)
        canon = ",".join(f"{k}:{v}" for k, v in sorted(Counter(prims).items()))
    except Exception:  # noqa: BLE001 - fingerprint, not correctness
        canon = str(jaxpr)
    return f"{zlib.crc32(canon.encode()) & 0xFFFFFFFF:08x}"


def run(n, owners, iters_pair, reps, wave_pair, liveness_n=512):
    mesh = create_mesh()
    n_dev = mesh.devices.size
    shd = sharding(mesh)
    names = ("cell_id", "k1", "k2", "ex_k1", "ex_k2", "owner_ix")

    with jax.enable_x64(True):
        # 1. Per-variant liveness fence at a tiny shape (the timing
        # would be a lie for any variant with a dead output).
        tiny_cols, _ = bench.shard_layout(
            bench.build_columns(n=liveness_n, owners=16, stored_winners=True),
            n_dev,
        )
        tiny_args = [jax.device_put(tiny_cols[k], shd) for k in names]
        dead_by_variant = {}
        for name in DEVICE_STAGES:
            dead = liveness_check(mesh, tiny_args, name)
            if dead:
                dead_by_variant[name] = dead
        liveness_pass = not dead_by_variant

        # 2. Slope-measure every truncated variant at the real shape.
        cols, _ = bench.shard_layout(
            bench.build_columns(n=n, owners=owners, stored_winners=True), n_dev
        )
        args = [jax.device_put(cols[k], shd) for k in names]
        samples = _interleaved_samples(
            mesh, args, {n_: build_variant(n_) for n_ in DEVICE_STAGES},
            iters_pair, reps,
        )
        rep_slopes = _per_rep_slopes(samples, DEVICE_STAGES, iters_pair, reps)
        slopes = {n_: statistics.median(s) * 1e3
                  for n_, s in rep_slopes.items()}
        # Marginal = median over reps of the WITHIN-REP slope
        # difference (drift-robust), not the difference of medians.
        marginals = {}
        prev_name = None
        for name in DEVICE_STAGES:
            if prev_name is None:
                diffs = rep_slopes[name]
            else:
                diffs = [a - b for a, b in
                         zip(rep_slopes[name], rep_slopes[prev_name])]
            marginals[name] = statistics.median(diffs) * 1e3
            prev_name = name
        lo = iters_pair[0]
        full_name = DEVICE_STAGES[-1]
        fixed_full = statistics.median(
            samples[(full_name, lo)][r] - lo * rep_slopes[full_name][r]
            for r in range(reps)
        ) * 1e3

        # 3. Pull wave (outside the fused loop).
        pull = measure_pull_wave(mesh, cols, wave_pair, reps)

    platform = jax.devices()[0].platform
    full = slopes[DEVICE_STAGES[-1]]
    stages = {}
    for name in DEVICE_STAGES:
        marginal = marginals[name]
        floor = anatomy.floor_ms(name, rows=n, platform=platform)
        stages[name] = {
            "slope_ms": round(slopes[name], 4),
            "marginal_ms": round(marginal, 4),
            "share": round(max(marginal, 0.0) / full, 4) if full > 0 else 0.0,
            "floor_ms": round(floor, 4),
            "floor_ratio": (
                round(max(marginal, 0.0) / floor, 3) if floor > 0 else None
            ),
        }
    pull["floor_ms"] = round(
        anatomy.floor_ms("pull_wave", nbytes=int(pull["wave_mb"] * 1e6),
                         platform=platform), 4)

    return {
        "metric": "stage_anatomy",
        "platform": platform,
        "batch": n,
        "owners": owners,
        "devices": n_dev,
        "iters": list(iters_pair),
        "reps": reps,
        "liveness_pass": liveness_pass,
        "dead_outputs": dead_by_variant,
        "registry_digest": anatomy.registry_digest(),
        "pipeline_digest": pipeline_fingerprint(mesh),
        "full_pipeline_ms_per_iter": round(full, 4),
        "dispatch_fixed_ms": round(fixed_full, 3),
        "stages": stages,
        "pull_wave": pull,
        "method": "per-variant checksum-carry liveness fence, then "
                  "interleaved two-point slopes (all variants timed "
                  "round-robin per rep; fixed dispatch overhead "
                  "cancelled); marginal = median over reps of the "
                  "within-rep slope_k - slope_{k-1}; pull wave "
                  "slope-measured over wave counts",
    }


def main():
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--smoke", action="store_true",
                    help="tiny N for CI: gates (liveness/digests) are "
                         "exercised for real, timings are advisory")
    ap.add_argument("--n", type=int, default=None,
                    help="batch rows (default: 2^19 full, 2^14 smoke)")
    args = ap.parse_args()
    if args.smoke:
        n = args.n or (1 << 14)
        rec = run(n, owners=64, iters_pair=(2, 6), reps=3, wave_pair=(1, 3))
    else:
        n = args.n or (1 << 19)
        rec = run(n, owners=512, iters_pair=(2, 10), reps=5, wave_pair=(2, 8))
    print(json.dumps(rec))
    return 0 if rec["liveness_pass"] else 1


if __name__ == "__main__":
    jax.config.update("jax_enable_x64", True)
    sys.exit(main())
