"""Tensor-CRDT merge kernels, slope-measured (ISSUE 20).

Same protocol as bench.py / crdt_types.py: each kernel runs inside a
fused fori_loop at two iteration counts; the slope between the two
wall times cancels the fixed dispatch overhead (mandatory under the
axon tunnel, where block_until_ready does not block and RTT is
~101-121 ms), and EVERY kernel output folds into the checksum carry so
XLA cannot DCE a stage (the r2/r3 lesson). A per-output drop probe
additionally proves each declared output actually moves the carry.

Measures, at N contributing ops over K cells of `width` elements:
- **cell_fold sum/max**: `tensor_cell_fold_core` — ONE packed
  cell|idx i64 sort + a single row-gather recovering the (n, width)
  matrix + ONE flattened segmented scan over all width element
  columns + dense scatter. The design bet this bench prices: the
  recorded v5e law charges ~0.75 ms per extra u64 sort payload at 1M,
  so a width-8 cell carried as payloads would pay O(width) sorts —
  the gather layout pays one sort + one gather regardless of width.
- **shard packed/wide**: `tensor_shard_sums_core` (owner|cell|idx
  packed key, the reconcile drain shape) and the wide-id fallback
  (owner as a gathered payload) — tensor widths exercise the wide
  path at production shapes, so both variants are priced.

Gates (hard-fail, run in --smoke too): device twins bit-identical to
the pure-numpy host oracle (`core/crdt_tensor.py`) for sum, mean and
max monoids, and both shard variants vs a numpy group-by — the same
parity the goldens pin in tests/test_crdt_tensor.py.

HONESTY (docs/BENCHMARKS.md): CPU numbers from the CI container are
recorded as CPU numbers; the v5e projection in the docs is labeled a
projection until bench.py runs this shape on the tunneled chip.
Prints ONE JSON line; numbers live in docs/BENCHMARKS.md.
"""

import argparse
import functools
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np

jax.config.update("jax_enable_x64", True)

ITERS_LO, ITERS_HI = 2, 10
WIDTH = 8


def _slope(run, iters_lo=ITERS_LO, iters_hi=ITERS_HI, reps=3):
    """Per-iteration seconds via the two-count slope, best of reps."""
    run(iters_lo)  # compile both shapes before timing
    run(iters_hi)
    best = None
    for _ in range(reps):
        t0 = time.perf_counter()
        run(iters_lo)
        t_lo = time.perf_counter() - t0
        t0 = time.perf_counter()
        run(iters_hi)
        t_hi = time.perf_counter() - t0
        s = (t_hi - t_lo) / (iters_hi - iters_lo)
        best = s if best is None else min(best, s)
    return best


def bench_cell_fold(n, k, monoid):
    from evolu_tpu.ops.crdt_tensor_merge import tensor_cell_fold_core

    rng = np.random.default_rng(7)
    cell = jnp.asarray(rng.integers(0, k, n).astype(np.int32))
    contrib = jnp.asarray(
        rng.integers(0, 1 << 48, (n, WIDTH)).astype(np.uint64))
    low_mask = jnp.int32(k - 1)  # k is a power of two

    @functools.partial(jax.jit, static_argnums=0)
    def loop(iters):
        def body(i, acc):
            # Bijective in-range relabel + value twiddle: the fold's
            # input really changes every iteration, so no stage can be
            # hoisted or cached out of the timed graph.
            cid = cell ^ (i.astype(jnp.int32) * jnp.int32(0x2B) & low_mask)
            v = contrib + (i & jnp.int64(7)).astype(jnp.uint64)
            table = tensor_cell_fold_core(cid, v, table_size=k,
                                          width=WIDTH, monoid=monoid)
            return acc + table.sum()  # consume the ONLY output

        return jax.lax.fori_loop(0, iters, body, jnp.zeros((), jnp.uint64))

    checks = {}

    def run(iters):
        checks[iters] = int(jax.block_until_ready(loop(iters)))

    s = _slope(run)
    # Liveness: different iteration counts must yield different carries.
    assert checks[ITERS_LO] != checks[ITERS_HI], "checksum carry is dead"
    return {"slope_ms": s * 1e3, "elems_per_s": n * WIDTH / s,
            "checksum": checks[ITERS_HI]}


def bench_shard(n, k, variant):
    from evolu_tpu.ops.crdt_tensor_merge import (
        tensor_shard_sums_core, tensor_shard_sums_wide_core)

    rng = np.random.default_rng(11)
    owner_np = rng.integers(0, 64, n).astype(np.int32)
    # Globally interned cell ids (unique per owner — the wide
    # contract); the wide leg pushes them past the packed 2^25 budget.
    cell_np = (rng.integers(0, k, n) * 64 + owner_np).astype(np.int32)
    if variant == "wide":
        cell_np = cell_np + (1 << 26)
    core = tensor_shard_sums_core if variant == "packed" \
        else tensor_shard_sums_wide_core
    owner = jnp.asarray(owner_np)
    cell = jnp.asarray(cell_np)
    contrib = jnp.asarray(
        rng.integers(0, 1 << 48, (n, WIDTH)).astype(np.uint64))

    # Per-output drop probe: each declared core output must move the
    # carry — a checksum formula that ignored an output would let XLA
    # DCE that stage out of the timed graph (the r2/r3 bug class).
    outs = [np.asarray(o) for o in jax.jit(core)(owner, cell, contrib)]
    parts = [np.uint64(o.astype(np.uint64).sum()) for o in outs]
    full = np.uint64(0)
    for p in parts:
        full = full + p
    for i, p in enumerate(parts):
        assert full != full - p, f"{variant} output {i} is checksum-dead"

    @functools.partial(jax.jit, static_argnums=0)
    def loop(iters):
        def body(i, acc):
            v = contrib + (i & jnp.int64(7)).astype(jnp.uint64)
            res = core(owner, cell, v)
            local = jnp.zeros((), jnp.uint64)
            for o in res:  # consume EVERY output
                local = local + o.astype(jnp.uint64).sum()
            return acc + local

        return jax.lax.fori_loop(0, iters, body, jnp.zeros((), jnp.uint64))

    checks = {}

    def run(iters):
        checks[iters] = int(jax.block_until_ready(loop(iters)))

    s = _slope(run)
    assert checks[ITERS_LO] != checks[ITERS_HI], "checksum carry is dead"
    return {"slope_ms": s * 1e3, "elems_per_s": n * WIDTH / s,
            "checksum": checks[ITERS_HI]}


def parity_check(n=6_000, k=64):
    """Device twins bit-identical to the pure-numpy host oracle — the
    HARD gate (runs under --smoke too): a fast kernel that drifts by
    one bit would fork replicas forever."""
    from evolu_tpu.core import crdt_tensor as tz
    from evolu_tpu.ops.crdt_tensor_merge import (
        tensor_cell_folds, tensor_shard_sums)

    rng = np.random.default_rng(3)
    for type_string in ("tensor:sum:f32:8", "tensor:mean:f32:8",
                        "tensor:max:bf16:8"):
        cfg = tz.parse_tensor_type(type_string)
        cell = rng.integers(0, k, n).astype(np.int32)
        contrib = np.empty((n, cfg.size), np.uint64)
        counts = rng.integers(1, 9, n)
        for i in range(n):
            vals = (rng.random(cfg.size) * 60 - 30).astype(np.float32)
            payload = vals.astype(tz._np_dtype(cfg)).tobytes()
            if cfg.monoid == "max":
                contrib[i] = tz.monotone_key(cfg, payload).astype(np.uint64)
            else:
                c = counts[i] if cfg.monoid == "mean" else 1
                contrib[i] = tz.quantize(cfg, payload).view(np.uint64) \
                    * np.uint64(c)
        table = tensor_cell_folds(cell, contrib, k, cfg.monoid)
        host = np.zeros((k, cfg.size), np.uint64)
        if cfg.monoid == "max":
            np.maximum.at(host, cell, contrib)
        else:
            np.add.at(host, cell, contrib)
        assert np.array_equal(table, host), f"{type_string} parity"
    owner = rng.integers(0, 8, n).astype(np.int64)
    for variant, bump in (("packed", 0), ("wide", 1 << 26)):
        cell = (rng.integers(0, k, n) * 8 + owner + bump).astype(np.int64)
        contrib = rng.integers(0, 1 << 40, (n, 4)).astype(np.uint64)
        got = tensor_shard_sums(owner, cell, contrib)
        expect = {}
        for o, c, v in zip(owner, cell, contrib):
            key = (int(o), int(c))
            expect[key] = expect.get(key, np.zeros(4, np.uint64)) + v
        assert set(got) == set(expect), f"{variant} shard keys"
        for key in expect:
            assert np.array_equal(got[key], expect[key].view(np.int64)), \
                f"{variant} shard parity"


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--smoke", action="store_true",
                    help="small shape + host-oracle parity gate (CI)")
    ap.add_argument("--n", type=int, default=None)
    args = ap.parse_args()
    n = args.n or (1 << 13 if args.smoke else 1 << 20)
    k = 1 << 8 if args.smoke else 1 << 15
    parity_check()
    out = {
        "bench": "tensor_merge",
        "platform": jax.default_backend(),
        "device": str(jax.devices()[0]),
        "n_ops": n,
        "cells": k,
        "width": WIDTH,
        "smoke": bool(args.smoke),
        "cell_fold_sum": bench_cell_fold(n, k, "sum"),
        "cell_fold_max": bench_cell_fold(n, k, "max"),
        "shard_packed": bench_shard(n, k, "packed"),
        "shard_wide": bench_shard(n, k, "wide"),
        "parity": "ok",
    }
    print(json.dumps(out))


if __name__ == "__main__":
    main()
