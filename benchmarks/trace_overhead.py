"""Distributed-tracing overhead at 100% sampling (slope method).

Acceptance gate for ISSUE 10: tracing must cost <=1% of the config-2
leg (the 1M-row client reconcile pipeline — the same anchor the PR-1
metrics gate used) AND leave wire bytes + SQLite end state
byte-identical. Three measurements:

1. The DEVICE leg is untouched by construction (obs.trace never
   imports jax; tests/test_bench_liveness.py pins checksum + jit-cache
   equality with tracing enabled), so the only possible cost is the
   HOST-side span sequence per traced round. Measure exactly that —
   header parse, server span start/activate/end, queue-wait record,
   batch span with fan-in link, respond span, exemplar observe; a
   SUPERSET of what one sync round executes — via the slope between
   two repetition counts (fixed overhead cancels, CLAUDE.md rule).

2. Anchor against the measured config-2 reconcile wall per batch on
   this platform (two-point slope over fused iteration counts,
   bench.py method) and assert sequence/batch <= 1%.

3. Byte-identity: drive an identical fixed request set — v1 OpenPGP-
   shaped AND v2 aead-magic records — through a TRACED relay (100%
   sampling, traceparent headers on every POST) and an UNTRACED one;
   response bytes and full store state (tree strings + message rows)
   must match exactly.

Also reported (not gated): the per-request ratio against the relay's
~1.2 ms HTTP serve wall — the worst-case anchor, since a batched
relay amortizes the batch span and the engine pass dominates.

`--smoke` shrinks the anchor shape for CI. Prints one JSON line.
"""

import json
import os
import statistics
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

REPS_LO, REPS_HI = 200, 2000
ITERS_LO, ITERS_HI = 2, 10

HDR = "00-" + "ab" * 16 + "-" + "cd" * 8 + "-01"


def tracing_sequence():
    """The host-side tracing work ONE fully-traced sync round performs,
    deliberately a superset (it charges a whole batch span + link to a
    single request — a real micro-batch amortizes it across N)."""
    from evolu_tpu.obs import metrics, trace

    ctx = trace.parse_traceparent(HDR)
    srv = trace.start_span("relay.sync", parent=ctx, attrs={"endpoint": "/"})
    tok = trace.activate(srv.context)
    srv.set_attr("owner", "o123")
    trace.record_span("sched.queue", srv.context, time.time(), 0.1)
    batch = trace.start_span(
        "engine.batch", links=[srv.context], force_sample=True,
        attrs={"requests": 1, "owners": 1},
    )
    batch.end()
    trace.start_span("relay.respond", parent=srv.context).end()
    trace.deactivate(tok)
    srv.end()
    metrics.observe("evolu_relay_request_ms", 1.2, exemplar=srv.trace_id)


def measure_tracing_ms():
    """Slope between two repetition counts of the per-round sequence."""
    def timed(reps):
        runs = []
        for _ in range(7):
            t0 = time.perf_counter()
            for _ in range(reps):
                tracing_sequence()
            runs.append(time.perf_counter() - t0)
        return statistics.median(runs)

    t_lo, t_hi = timed(REPS_LO), timed(REPS_HI)
    return (t_hi - t_lo) / (REPS_HI - REPS_LO) * 1e3  # ms per round


def measure_config2_batch_ms(n_rows):
    """Per-iteration wall of the config-2 reconcile pipeline, two-point
    slope over fused iterations (bench.py method — this anchors a
    ratio, it is not the scored bench)."""
    import jax
    import numpy as np

    import bench
    from evolu_tpu.parallel.mesh import create_mesh, sharding

    mesh = create_mesh()
    n_dev = mesh.devices.size
    shd = sharding(mesh)
    names = ("cell_id", "k1", "k2", "ex_k1", "ex_k2", "owner_ix")
    with jax.enable_x64(True):
        cols, _ = bench.shard_layout(
            bench.build_columns(n=n_rows, stored_winners=True), n_dev
        )
        args = [jax.device_put(cols[k], shd) for k in names]
        medians = {}
        for iters in (ITERS_LO, ITERS_HI):
            loop = bench.make_loop(mesh, iters)
            np.asarray(loop(*args))  # compile + warm
            runs = []
            for _ in range(5):
                t0 = time.perf_counter()
                np.asarray(loop(*args))
                runs.append(time.perf_counter() - t0)
            medians[iters] = statistics.median(runs)
    return (medians[ITERS_HI] - medians[ITERS_LO]) / (ITERS_HI - ITERS_LO) * 1e3


def measure_relay_leg_ms(n_lo=50, n_hi=200):
    """Diagnostic anchor: marginal per-request wall of the relay's
    HTTP serve path (slope between two request counts on one store)."""
    from evolu_tpu.core.timestamp import Timestamp, timestamp_to_string
    from evolu_tpu.server.relay import RelayServer, RelayStore
    from evolu_tpu.sync import protocol
    from evolu_tpu.sync.client import _http_post

    base = 1_700_000_000_000

    def body(owner, k):
        node = f"{k + 1:016x}"
        msg = protocol.EncryptedCrdtMessage(
            timestamp_to_string(Timestamp(base + k * 1000, 0, node)), b"ct"
        )
        return protocol.encode_sync_request(
            protocol.SyncRequest((msg,), owner, "00000000000000bb", "{}")
        )

    server = RelayServer(RelayStore()).start()
    try:
        def serve(n, tag):
            t0 = time.perf_counter()
            for i in range(n):
                _http_post(server.url + "/", body(f"{tag}{i:05d}", i))
            return time.perf_counter() - t0

        serve(30, "warm")
        t_lo, t_hi = serve(n_lo, "lo"), serve(n_hi, "hi")
        return (t_hi - t_lo) / (n_hi - n_lo) * 1e3
    finally:
        server.stop()


def assert_byte_identity():
    """Identical fixed requests through a traced relay (100% sampling,
    traceparent on every POST) and an untraced one: responses and
    store end state must be byte-identical — for v2 aead records
    exactly like v1."""
    from evolu_tpu.core.timestamp import Timestamp, timestamp_to_string
    from evolu_tpu.obs import trace
    from evolu_tpu.server.relay import RelayServer, RelayStore
    from evolu_tpu.sync import aead, protocol
    from evolu_tpu.sync.client import _http_post

    base = 1_700_000_000_000

    def requests():
        out = []
        for k in range(8):
            node = f"{k + 1:016x}"
            content = (aead.MAGIC + b"\x00" * 44) if k % 2 else b"ct-%d" % k
            msgs = tuple(
                protocol.EncryptedCrdtMessage(
                    timestamp_to_string(Timestamp(base + (k * 4 + j) * 1000, 0, node)),
                    content,
                )
                for j in range(3)
            )
            out.append(protocol.SyncRequest(
                msgs, f"owner{k:02d}", "00000000000000bb", "{}"))
        return out

    def drive(traced):
        trace.set_enabled(traced)
        server = RelayServer(RelayStore()).start()
        try:
            responses = []
            for r in requests():
                hdrs = None
                if traced:
                    root = trace.start_span("client.mutate")
                    hdrs = {trace.TRACEPARENT_HEADER:
                            trace.format_traceparent(root.context)}
                responses.append(_http_post(
                    server.url + "/", protocol.encode_sync_request(r),
                    headers=hdrs))
                if traced:
                    root.end()
            state = {
                uid: (server.store.get_merkle_tree_string(uid),
                      server.store.replica_messages(uid, ""))
                for uid in sorted(server.store.user_ids())
            }
            return responses, state
        finally:
            server.stop()
            trace.set_enabled(True)

    traced_resp, traced_state = drive(True)
    plain_resp, plain_state = drive(False)
    assert traced_resp == plain_resp, "tracing changed response bytes"
    assert traced_state == plain_state, "tracing changed SQLite end state"
    return len(traced_resp)


def main(smoke: bool):
    from evolu_tpu.utils.log import logger

    logger.clear()
    requests_checked = assert_byte_identity()
    tracing_ms = measure_tracing_ms()
    # Smoke shrinks the device anchor shape (CI runs on a small CPU
    # mesh); the full run uses the config-2 1M-row shape.
    n_rows = 1 << 16 if smoke else 1 << 20
    batch_ms = measure_config2_batch_ms(n_rows)
    relay_ms = measure_relay_leg_ms(
        n_lo=20 if smoke else 50, n_hi=80 if smoke else 200)
    overhead = tracing_ms / batch_ms
    import jax

    out = {
        "metric": "trace_overhead_on_config2_leg",
        "sampling": 1.0,
        "tracing_ms_per_round": round(tracing_ms, 5),
        "config2_rows": n_rows,
        "config2_batch_ms": round(batch_ms, 3),
        "overhead_fraction": round(overhead, 6),
        "overhead_pct": round(100 * overhead, 4),
        "pass_1pct_gate": bool(overhead <= 0.01),
        "byte_identical_end_state": True,
        "byte_identity_requests": requests_checked,
        "relay_http_ms_per_request": round(relay_ms, 4),
        "relay_leg_overhead_pct": round(100 * tracing_ms / relay_ms, 3),
        "device_graph_untouched": "pinned by tests/test_bench_liveness.py",
        "platform": jax.devices()[0].platform,
        "method": "two-point slope on both legs (fixed overhead cancelled)",
    }
    print(json.dumps(out))
    assert out["pass_1pct_gate"], (
        f"tracing overhead {out['overhead_pct']}% exceeds the 1% gate"
    )


if __name__ == "__main__":
    smoke = "--smoke" in sys.argv
    import jax

    jax.config.update("jax_enable_x64", True)
    main(smoke)
