"""Winner-source experiment (SURVEY.md §7 hard part 4): stream stored
winners from SQLite per batch vs keep them HBM-resident across batches
(`ops/winner_cache.py`), on the config-2 full-system shape — steady
state: several successive 100k-message batches over a persistent cell
population, SQLite end states asserted equal.

Prints one JSON line.
"""

import json
import os
import random
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from evolu_tpu.core.merkle import merkle_tree_to_string
from evolu_tpu.core.timestamp import Timestamp, timestamp_to_string
from evolu_tpu.core.types import CrdtMessage
from evolu_tpu.ops.merge import plan_batch_device_full
from evolu_tpu.ops.winner_cache import DeviceWinnerCache
from evolu_tpu.storage.apply import apply_messages
from evolu_tpu.storage.native import open_database
from evolu_tpu.storage.schema import init_db_model

N = 100_000
BATCHES = 4


def build_batch(batch_no, n=N, seed=2):
    rng = random.Random(seed + batch_no)
    tables = [("todo", ("title", "isCompleted", "categoryId")),
              ("todoCategory", ("name",)),
              ("todoNote", ("text",))]
    nodes = [f"{rng.getrandbits(64):016x}" for _ in range(8)]
    base = 1_700_000_000_000 + batch_no * 40_000_000
    out = []
    for i in range(n):
        table, cols = rng.choice(tables)
        out.append(CrdtMessage(
            timestamp_to_string(Timestamp(base + i // 4, i % 4, rng.choice(nodes))),
            table, f"row{rng.randrange(5000)}", rng.choice(cols), f"v{i}",
        ))
    return out


def fresh_db():
    db = open_database(backend="auto")
    init_db_model(db, mnemonic=None)
    for t in ("todo", "todoCategory", "todoNote"):
        db.exec(
            f'CREATE TABLE "{t}" ("id" TEXT PRIMARY KEY, "title" BLOB, '
            '"isCompleted" BLOB, "categoryId" BLOB, "name" BLOB, "text" BLOB)'
        )
    return db


def run(planner_for):
    db = fresh_db()
    planner = planner_for(db)
    tree = {}
    # Warm compiles outside the timed region (both planners share
    # bucket-size-keyed jits; the cache also compiles its seed kernel).
    warm = build_batch(99, n=1024)
    tree_w = apply_messages(db, {}, warm, planner=planner)
    per_batch = []
    for b in range(BATCHES):
        batch = build_batch(b)
        t0 = time.perf_counter()
        tree = apply_messages(db, tree, batch, planner=planner)
        per_batch.append(time.perf_counter() - t0)
    dump = (
        db.exec('SELECT COUNT(*), MIN("timestamp"), MAX("timestamp") FROM "__message"'),
        db.exec('SELECT COUNT(*) FROM "todo"'),
    )
    db.close()
    steady = per_batch[1:]  # batch 0 populates the store / cache
    return {
        "per_batch_s": [round(t, 3) for t in per_batch],
        "steady_msgs_per_sec": round(N * len(steady) / sum(steady)),
        "tree": merkle_tree_to_string(tree),
        "dump": repr(dump),
    }


def main():
    streamed = run(lambda db: plan_batch_device_full)
    cached = run(lambda db: DeviceWinnerCache(db, capacity=1 << 15).plan_batch)
    assert streamed["tree"] == cached["tree"], "digest divergence"
    assert streamed["dump"] == cached["dump"], "end-state divergence"
    import jax

    print(json.dumps({
        "metric": "winner_source_steady_msgs_per_sec",
        "value": cached["steady_msgs_per_sec"],
        "unit": "msgs/sec",
        "detail": {
            "batches": BATCHES, "batch_size": N,
            "streamed_sqlite": {k: streamed[k] for k in ("per_batch_s", "steady_msgs_per_sec")},
            "hbm_cache": {k: cached[k] for k in ("per_batch_s", "steady_msgs_per_sec")},
            "end_state_equal": True,
            "platform": jax.devices()[0].platform,
        },
    }))


if __name__ == "__main__":
    main()
