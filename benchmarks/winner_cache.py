"""Winner-source experiment (SURVEY.md §7 hard part 4): stream stored
winners from SQLite per batch vs keep them HBM-resident across batches
(`ops/winner_cache.py`), on the config-2 full-system shape — steady
state: several successive 100k-message batches over a persistent cell
population, SQLite end states asserted equal.

Prints one JSON line.
"""

import json
import os
import random
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from evolu_tpu.core.merkle import merkle_tree_to_string
from evolu_tpu.core.timestamp import Timestamp, timestamp_to_string
from evolu_tpu.core.types import CrdtMessage
from evolu_tpu.ops.merge import plan_batch_device_full
from evolu_tpu.ops.winner_cache import DeviceWinnerCache
from evolu_tpu.storage.apply import apply_messages
from evolu_tpu.storage.native import open_database
from evolu_tpu.storage.schema import init_db_model

N = 100_000
BATCHES = 8


def build_batch(batch_no, n=N, seed=2, rotate=False):
    """`rotate=False`: one persistent 5k-row population (steady state —
    the cache's home turf). `rotate=True`: every batch introduces a
    fresh row namespace (~22k new cells/batch — the seed-heavy shape
    where streaming wins)."""
    rng = random.Random(seed + batch_no)
    tables = [("todo", ("title", "isCompleted", "categoryId")),
              ("todoCategory", ("name",)),
              ("todoNote", ("text",))]
    nodes = [f"{rng.getrandbits(64):016x}" for _ in range(8)]
    base = 1_700_000_000_000 + batch_no * 40_000_000
    prefix = f"b{batch_no}_" if rotate else ""
    out = []
    for i in range(n):
        table, cols = rng.choice(tables)
        out.append(CrdtMessage(
            timestamp_to_string(Timestamp(base + i // 4, i % 4, rng.choice(nodes))),
            table, f"{prefix}row{rng.randrange(5000)}", rng.choice(cols), f"v{i}",
        ))
    return out


def fresh_db():
    db = open_database(backend="auto")
    init_db_model(db, mnemonic=None)
    for t in ("todo", "todoCategory", "todoNote"):
        db.exec(
            f'CREATE TABLE "{t}" ("id" TEXT PRIMARY KEY, "title" BLOB, '
            '"isCompleted" BLOB, "categoryId" BLOB, "name" BLOB, "text" BLOB)'
        )
    return db


def run(planner_for, rotate=False):
    db = fresh_db()
    planner = planner_for(db)
    tree = {}
    # Warm compiles outside the timed region (both planners share
    # bucket-size-keyed jits; the cache also compiles its seed kernel).
    warm = build_batch(99, n=1024)
    tree_w = apply_messages(db, {}, warm, planner=planner)
    per_batch = []
    for b in range(BATCHES):
        batch = build_batch(b, rotate=rotate)
        t0 = time.perf_counter()
        tree = apply_messages(db, tree, batch, planner=planner)
        per_batch.append(time.perf_counter() - t0)
    dump = (
        db.exec('SELECT COUNT(*), MIN("timestamp"), MAX("timestamp") FROM "__message"'),
        db.exec('SELECT COUNT(*) FROM "todo"'),
    )
    db.close()
    steady = per_batch[1:]  # batch 0 populates the store / cache
    tail = per_batch[-4:]  # converged: past the adaptive gate's ~2-batch transition
    return {
        "per_batch_s": [round(t, 3) for t in per_batch],
        "steady_msgs_per_sec": round(N * len(steady) / sum(steady)),
        "tail_msgs_per_sec": round(N * len(tail) / sum(tail)),
        "tree": merkle_tree_to_string(tree),
        "dump": repr(dump),
    }


PLANNERS = {
    "streamed_sqlite": lambda db: plan_batch_device_full,
    "hbm_cache_static": lambda db: DeviceWinnerCache(
        db, capacity=1 << 15, adaptive=False
    ).plan_batch,
    "adaptive": lambda db: DeviceWinnerCache(db, capacity=1 << 15).plan_batch,
}


def main():
    import jax

    detail = {"batches": BATCHES, "batch_size": N,
              "platform": jax.devices()[0].platform}
    summary = {}
    for shape, rotate in (("steady", False), ("rotating", True)):
        results = {name: run(p, rotate=rotate) for name, p in PLANNERS.items()}
        first = next(iter(results.values()))
        for name, r in results.items():
            assert r["tree"] == first["tree"], f"{shape}/{name}: digest divergence"
            assert r["dump"] == first["dump"], f"{shape}/{name}: end-state divergence"
        detail[shape] = {
            name: {k: r[k] for k in ("per_batch_s", "steady_msgs_per_sec", "tail_msgs_per_sec")}
            for name, r in results.items()
        }
        detail[shape]["end_state_equal"] = True
        summary[shape] = {
            n: {"steady": r["steady_msgs_per_sec"], "tail": r["tail_msgs_per_sec"]}
            for n, r in results.items()
        }

    # The adaptive gate's promise: >= max(static paths) on BOTH shapes.
    print(json.dumps({
        "metric": "winner_source_adaptive_msgs_per_sec",
        "value": summary["steady"]["adaptive"]["tail"],
        "unit": "msgs/sec",
        "detail": {**detail, "summary": summary},
    }))


if __name__ == "__main__":
    main()
