"""Write-behind storage inversion (PR-11) vs the synchronous engine.

Drives the SAME seeded request stream through two `BatchReconciler`s:
the synchronous path (insert + tree upsert inside the serving pass —
the PR-8..10 shape) and the write-behind path (serve from in-memory
trees, ACK into the durable record log, SQLite materialized by the
background drain). Three figures:

- `serve` slope: Δmessages/Δwall of the SERVING path alone between two
  batch counts (CLAUDE.md timing discipline — setup, jit warmup, and
  store open cancel out). This is the number the 503/Retry-After
  admission bound protects: what a client observes while the btree
  lags behind.
- `end_to_end` slope: the same but including the final drain — the
  sustained-throughput bound (the btree still has to swallow every
  row; write-behind moves it off the latency path, it does not make
  it free).
- `sync` slope: the synchronous engine on the identical stream.

Gates (hard-fail, run in --smoke too):
- byte-identity: after the drain, both stores' rows + trees are
  identical (the oracle-twin contract the SIGKILL torture extends).
- checksum-carry liveness: the state crc is printed and must MOVE when
  the payload is perturbed — a serving leg that drops rows cannot go
  unnoticed (the r2/r3 DCE lesson applied to the host path).

Runs on the 8-device virtual CPU mesh by default (axon vars stripped —
never claims the real chip); EVOLU_WB_BENCH_TPU=1 inherits the ambient
platform. Prints ONE JSON line; numbers live in docs/BENCHMARKS.md.
"""

import json
import os
import sys
import time
import zlib

if not os.environ.get("EVOLU_WB_BENCH_TPU"):
    os.environ["JAX_PLATFORMS"] = "cpu"
    _flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in _flags:
        os.environ["XLA_FLAGS"] = (
            _flags + " --xla_force_host_platform_device_count=8"
        ).strip()
    for _v in ("PALLAS_AXON_POOL_IPS", "PALLAS_AXON_REMOTE_COMPILE"):
        os.environ.pop(_v, None)

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from evolu_tpu.core.timestamp import Timestamp, timestamp_to_string
from evolu_tpu.server.engine import BatchReconciler
from evolu_tpu.server.relay import RelayStore, ShardedRelayStore
from evolu_tpu.storage.write_behind import WriteBehindQueue
from evolu_tpu.sync import protocol

BASE = 1_700_000_000_000
OWNERS = 16
SHARDS = 4


def _stream(n_batches: int, rows_per_owner: int, payload: bytes):
    """Seeded batches of distinct-owner in-sync pushes (the steady-
    state hot shape: response diff empty, no serve-side flush). Client
    trees come from a deterministic tree oracle."""
    from evolu_tpu.core.merkle import merkle_tree_to_string

    oracle = RelayStore()
    batches = []
    for b in range(n_batches):
        reqs = []
        for o in range(OWNERS):
            owner = f"owner{o:02d}"
            node = f"{o + 1:016x}"
            msgs = tuple(
                protocol.EncryptedCrdtMessage(
                    timestamp_to_string(Timestamp(
                        BASE + (b * rows_per_owner + i) * 1000, 0, node
                    )),
                    payload,
                )
                for i in range(rows_per_owner)
            )
            tree = oracle.add_messages(owner, msgs)
            reqs.append(protocol.SyncRequest(
                msgs, owner, node, merkle_tree_to_string(tree)
            ))
        batches.append(reqs)
    oracle.close()
    return batches


def _state_crc(store) -> int:
    crc = 0
    shards = getattr(store, "shards", None) or [store]
    for s in shards:
        for u in sorted(s.user_ids()):
            crc = zlib.crc32(s.get_merkle_tree_string(u).encode(), crc)
            for m in s.replica_messages(u, ""):
                crc = zlib.crc32(m.timestamp.encode(), crc)
                crc = zlib.crc32(m.content, crc)
    return crc


def _dump(store):
    rows, trees = [], []
    for s in (getattr(store, "shards", None) or [store]):
        rows += [(r["userId"], r["timestamp"], r["content"])
                 for r in s.db.exec_sql_query(
                     'SELECT "timestamp", "userId", "content" FROM "message"')]
        trees += [(r["userId"], r["merkleTree"])
                  for r in s.db.exec_sql_query(
                      'SELECT "userId", "merkleTree" FROM "merkleTree"')]
    return sorted(rows), sorted(trees)


def _drive(batches, write_behind: bool, hold_drain: bool = False):
    """Serve `batches`; → (serve_wall, drain_wall, store, crc).

    `hold_drain` parks the drain behind `db_lock` for the SERVE
    measurement (after one warmup batch seeds the tree cache, the
    steady-state serve path takes no locks): on the 1-core container
    thread interleaving is serial, so this is the only way to measure
    the serving path and the btree drain as separate walls — the
    roadmap's recorded limit for core-count claims. drain_wall is then
    the timed flush of the full backlog (the btree's bulk cost)."""
    store = ShardedRelayStore(shards=SHARDS)
    wb = WriteBehindQueue(store) if write_behind else None
    eng = BatchReconciler(store, write_behind=wb)
    crc = 0
    if hold_drain and wb is not None:
        for out in eng.run_batch_wire(batches[0]):  # warmup: seed caches
            crc = zlib.crc32(out, crc)
        wb.flush()
        wb.db_lock.acquire()
        batches = batches[1:]
    t0 = time.perf_counter()
    for reqs in batches:
        for out in eng.run_batch_wire(reqs):
            crc = zlib.crc32(out, crc)
    t_serve = time.perf_counter() - t0
    t1 = time.perf_counter()
    if wb is not None:
        if hold_drain:
            wb.db_lock.release()
        wb.flush()
    t_drain = time.perf_counter() - t1
    if wb is not None:
        wb.close()
    eng.close()
    return t_serve, t_drain, store, crc


def _slope(lo_batches, hi_batches, rows_per_batch, write_behind,
           hold_drain: bool = False):
    s_lo, d_lo, st_lo, _ = _drive(lo_batches, write_behind, hold_drain)
    s_hi, d_hi, st_hi, crc = _drive(hi_batches, write_behind, hold_drain)
    n = (len(hi_batches) - len(lo_batches)) * rows_per_batch
    serve = n / max(s_hi - s_lo, 1e-9)
    drain = n / max(d_hi - d_lo, 1e-9)
    st_lo.close()
    return serve, drain, st_hi, crc


def main() -> None:
    smoke = "--smoke" in sys.argv
    rows_per_owner = 32 if smoke else 256
    lo, hi = (2, 5) if smoke else (4, 16)
    payload = b"x" * 64
    rows_per_batch = OWNERS * rows_per_owner

    batches = _stream(hi, rows_per_owner, payload)

    # -- byte-identity + liveness gates (always) --
    _s, _e, store_wb, crc_wb = _drive(batches[:lo], True)
    _s, _e, store_sync, crc_sync = _drive(batches[:lo], False)
    assert _dump(store_wb) == _dump(store_sync), (
        "write-behind drained state != synchronous oracle"
    )
    state_crc = _state_crc(store_wb)
    store_wb.close()
    store_sync.close()
    # Liveness: perturb the payload — the state crc MUST move.
    perturbed = _stream(lo, rows_per_owner, b"y" * 64)
    _s, _e, store_p, _c = _drive(perturbed, True)
    assert _state_crc(store_p) != state_crc, (
        "checksum did not move under payload perturbation — dead serving leg"
    )
    store_p.close()

    # -- slopes --
    # Serving path with the drain held: the latency-path number (what
    # a client sees while the btree lags). Drain slope: the btree's
    # bulk cost, timed separately (1-core limit — see _drive).
    wb_serve, wb_drain, st1, _ = _slope(
        batches[:lo], batches, rows_per_batch, True, hold_drain=True
    )
    # Interleaved (drain competing for the core): the sustained bound.
    wb_inter, _d, st3, _ = _slope(batches[:lo], batches, rows_per_batch, True)
    sync_serve, _d2, st2, _ = _slope(batches[:lo], batches, rows_per_batch, False)
    st1.close()
    st2.close()
    st3.close()

    print(json.dumps({
        "bench": "write_behind",
        "smoke": smoke,
        "platform": os.environ.get("JAX_PLATFORMS", "ambient"),
        "owners": OWNERS,
        "shards": SHARDS,
        "rows_per_batch": rows_per_batch,
        "serve_msgs_per_s_drain_held": round(wb_serve),
        "drain_msgs_per_s_bulk": round(wb_drain),
        "serve_msgs_per_s_interleaved": round(wb_inter),
        "serve_msgs_per_s_sync": round(sync_serve),
        "serve_path_speedup": round(wb_serve / max(sync_serve, 1e-9), 2),
        "interleaved_vs_sync": round(wb_inter / max(sync_serve, 1e-9), 2),
        "byte_identity": "ok",
        "liveness": "ok",
        "state_crc": f"{state_crc:08x}",
    }))


if __name__ == "__main__":
    main()
