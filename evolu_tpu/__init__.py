"""evolu_tpu — a TPU-native local-first data framework.

A brand-new framework with the capabilities of Evolu (the TypeScript
reference surveyed in SURVEY.md): reactive SQLite storage, a
last-write-wins CRDT over (table, row, column) cells, hybrid logical
clocks, Merkle-tree anti-entropy sync, end-to-end encryption,
mnemonic-derived identity, and a blind relay server.

The design is TPU-first: CRDT message batches are columnar arrays; the
merge hot path (LWW resolution, HLC comparison, Merkle insert/diff) runs
as batched JAX/XLA kernels (`evolu_tpu.ops`), owners shard over a device
mesh (`evolu_tpu.parallel`), and SQLite remains the durable store with
byte-identical end state to the reference semantics
(`evolu_tpu.storage`).

Public API mirrors the reference's surface (reference:
packages/evolu/src/index.ts):
- `create_evolu(schema, config)` — the client runtime (useQuery /
  mutate analogs live on the returned handle).
- `model` — branded column types and casting helpers.
- errors, Owner, mnemonic restore, etc.
"""

from evolu_tpu.core.timestamp import (
    Timestamp,
    timestamp_to_string,
    timestamp_from_string,
    timestamp_to_hash,
    send_timestamp,
    receive_timestamp,
    create_initial_timestamp,
    create_sync_timestamp,
)
from evolu_tpu.core.merkle import (
    create_initial_merkle_tree,
    insert_into_merkle_tree,
    diff_merkle_trees,
    merkle_tree_to_string,
    merkle_tree_from_string,
)
from evolu_tpu.core.types import (
    CrdtMessage,
    NewCrdtMessage,
    CrdtClock,
    TimestampDriftError,
    TimestampCounterOverflowError,
    TimestampDuplicateNodeError,
    SyncError,
    EvoluError,
)
from evolu_tpu.core.ids import create_id, create_node_id, mnemonic_to_owner_id
from evolu_tpu.utils.config import Config


def __getattr__(name):
    # Runtime/API surface re-exported lazily: importing the bare core
    # package must not pull in jax (the kernels) or start threads.
    lazy = {
        "Evolu": ("evolu_tpu.runtime.client", "Evolu"),
        "create_evolu": ("evolu_tpu.runtime.client", "create_evolu"),
        "create_hooks": ("evolu_tpu.api.hooks", "create_hooks"),
        "Hooks": ("evolu_tpu.api.hooks", "Hooks"),
        "QueryView": ("evolu_tpu.api.hooks", "QueryView"),
        "QueryBuilder": ("evolu_tpu.api.query", "QueryBuilder"),
        "table": ("evolu_tpu.api.query", "table"),
        "fn": ("evolu_tpu.api.query", "fn"),
        "model": ("evolu_tpu.api", "model"),
        "connect": ("evolu_tpu.sync.client", "connect"),
        "RelayServer": ("evolu_tpu.server.relay", "RelayServer"),
        "RelayStore": ("evolu_tpu.server.relay", "RelayStore"),
        "generate_mnemonic": ("evolu_tpu.core.mnemonic", "generate_mnemonic"),
        "validate_mnemonic": ("evolu_tpu.core.mnemonic", "validate_mnemonic"),
    }
    if name in lazy:
        import importlib

        module, attr = lazy[name]
        return getattr(importlib.import_module(module), attr)
    raise AttributeError(f"module 'evolu_tpu' has no attribute {name!r}")

__version__ = "0.1.0"

__all__ = [
    "Timestamp",
    "timestamp_to_string",
    "timestamp_from_string",
    "timestamp_to_hash",
    "send_timestamp",
    "receive_timestamp",
    "create_initial_timestamp",
    "create_sync_timestamp",
    "create_initial_merkle_tree",
    "insert_into_merkle_tree",
    "diff_merkle_trees",
    "merkle_tree_to_string",
    "merkle_tree_from_string",
    "CrdtMessage",
    "NewCrdtMessage",
    "CrdtClock",
    "TimestampDriftError",
    "TimestampCounterOverflowError",
    "TimestampDuplicateNodeError",
    "SyncError",
    "EvoluError",
    "create_id",
    "create_node_id",
    "mnemonic_to_owner_id",
    "Config",
    "__version__",
    "Evolu",
    "create_evolu",
    "create_hooks",
    "Hooks",
    "QueryView",
    "QueryBuilder",
    "table",
    "fn",
    "model",
    "connect",
    "RelayServer",
    "RelayStore",
    "generate_mnemonic",
    "validate_mnemonic",
]
