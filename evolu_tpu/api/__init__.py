"""Public bindings: data model validation, the compile-only query
builder, and reactive subscription helpers.

Reference: packages/evolu/src/model.ts (branded column types + casts),
kysely.ts (compile-only query builder), createHooks.ts / useOwner.ts
(React bindings). Python has no React; the binding analog is the
subscription API on `evolu_tpu.runtime.client.Evolu` plus this
package's query builder and model validators.
"""

from evolu_tpu.api import model
from evolu_tpu.api.query import (
    Cond,
    Fn,
    QueryBuilder,
    and_,
    c,
    exists,
    fn,
    not_,
    not_exists,
    or_,
    ref,
    table,
)

__all__ = [
    "model", "QueryBuilder", "table", "fn", "Fn",
    "Cond", "c", "and_", "or_", "not_", "exists", "not_exists", "ref",
    "Hooks", "QueryView", "create_hooks",
]


def __getattr__(name):
    # hooks imports the runtime, which imports api.model — loading hooks
    # lazily keeps `import evolu_tpu.runtime` acyclic.
    if name in ("Hooks", "QueryView", "create_hooks"):
        from evolu_tpu.api import hooks

        return getattr(hooks, name)
    raise AttributeError(f"module 'evolu_tpu.api' has no attribute {name!r}")
