"""createHooks analog — the binding layer apps consume.

Reference: packages/evolu/src/createHooks.ts (useQuery/useMutation),
useOwner.ts, db.ts:89-94 (useEvoluFirstDataAreLoaded). React hooks
become plain objects: `create_hooks(schema)` boots a client for the
schema and returns a `Hooks` handle whose `use_query` gives a live
`QueryView` (subscribed rows + change listeners — the
useSyncExternalStore analog) and whose `use_mutation` returns the
stable mutate function.
"""

from __future__ import annotations

from typing import TYPE_CHECKING, Callable, List, Optional

from evolu_tpu.api.query import QueryBuilder, table

if TYPE_CHECKING:  # runtime imports api.model; keep the cycle lazy
    from evolu_tpu.runtime.client import Evolu


class QueryView:
    """A subscribed query: `.rows` is always current; `subscribe(fn)`
    registers a change listener (createHooks.ts:28-49)."""

    def __init__(self, evolu: "Evolu", query):
        self._evolu = evolu
        self._query = query
        self._unsub = evolu.subscribe_query(query)
        self._listeners: List[Callable[[], None]] = []
        self._unlisten = evolu.listen(self._notify)
        self._disposed = False

    def _notify(self) -> None:
        for fn in list(self._listeners):
            fn()

    @property
    def rows(self) -> List[dict]:
        return self._evolu.get_query_rows(self._query)

    @property
    def first_row(self) -> Optional[dict]:
        rows = self.rows
        return rows[0] if rows else None

    def subscribe(self, listener: Callable[[], None]) -> Callable[[], None]:
        self._listeners.append(listener)

        def unsubscribe() -> None:
            if listener in self._listeners:
                self._listeners.remove(listener)

        return unsubscribe

    def dispose(self) -> None:
        if self._disposed:
            return
        self._disposed = True
        self._unlisten()
        self._unsub()


class Hooks:
    """What `create_hooks(schema)` returns (createHooks.ts:20-60)."""

    def __init__(self, evolu: "Evolu"):
        self.evolu = evolu

    def use_query(self, query) -> QueryView:
        """`query` is a QueryBuilder, raw SQL, a serialized query, or a
        callable receiving the `table` factory (the reference's
        `(db) => db.selectFrom(...)` lambda form)."""
        if callable(query) and not isinstance(query, QueryBuilder):
            query = query(table)
        return QueryView(self.evolu, query)

    def use_mutation(self):
        """The stable mutate function (createHooks.ts:51-54)."""
        return self.evolu.mutate

    def use_owner(self):
        """useOwner.ts:5."""
        return self.evolu.owner

    def use_evolu_first_data_are_loaded(self) -> bool:
        """db.ts:89-94 — True once the first query results arrived."""
        return self.evolu.first_data_loaded.is_set()


def create_hooks(schema, **evolu_kwargs) -> Hooks:
    """createHooks(schema) analog: boot a client, register the schema,
    return the hooks handle. Extra kwargs go to `Evolu(...)`
    (db_path, config, mnemonic, backend)."""
    from evolu_tpu.runtime.client import Evolu

    evolu = Evolu(**evolu_kwargs)
    try:
        evolu.update_db_schema(schema)
        return Hooks(evolu)
    except BaseException:
        evolu.dispose()
        raise
