"""Column value model: branded types, validation, and SQLite casts.

Reference: packages/evolu/src/model.ts. The reference brands values
with zod (`String1000`, `NonEmptyString1000`, `SqliteBoolean`,
`SqliteDate`, `Id`, `Mnemonic`); here the same constraints are
validator functions plus `cast` helpers mapping Python-native values
to their SQLite encodings (model.ts:100-112): bool ⇔ 0/1, datetime ⇔
fixed-width ISO-8601 string.
"""

from __future__ import annotations

import datetime
import re
from urllib.parse import urlparse
from typing import Union

from evolu_tpu.core.ids import create_id, is_valid_id
from evolu_tpu.core.mnemonic import validate_mnemonic
from evolu_tpu.core.types import StringMaxLengthError, ValidationError

SqliteBoolean = int  # 0 | 1 (model.ts:57-63)
SqliteDate = str  # ISO-8601 string (model.ts:65-74)


def validate_string_1000(value: str) -> str:
    """String1000 (model.ts:78-84): max length 1000."""
    if not isinstance(value, str) or len(value) > 1000:
        raise StringMaxLengthError("String1000: max length is 1000")
    return value


def validate_non_empty_string_1000(value: str) -> str:
    """NonEmptyString1000 (model.ts:86-94): 1..1000 chars, trimmed not empty."""
    validate_string_1000(value)
    if len(value.strip()) == 0:
        raise StringMaxLengthError("NonEmptyString1000: must not be empty")
    return value


_EMAIL_RE = re.compile(r"^[^\s@]+@[^\s@]+\.[^\s@]+$")


def validate_email(value: str) -> str:
    """Email brand (model.ts:65-66). Like the reference's zod
    `.email()`, no length cap — sync never validates, so local
    strictness is a UX concern only."""
    if not isinstance(value, str) or not _EMAIL_RE.fullmatch(value):
        raise ValidationError(f"invalid email: {value!r}")
    return value


def validate_url(value: str) -> str:
    """Url brand (model.ts:69-70). Rejects whitespace anywhere (JS
    `new URL` / zod `.url()` semantics) and malformed hosts."""
    if not isinstance(value, str) or re.search(r"\s", value):
        raise ValidationError(f"invalid url: {value!r}")
    try:
        p = urlparse(value)
    except ValueError:
        raise ValidationError(f"invalid url: {value!r}") from None
    if not (p.scheme and p.netloc):
        raise ValidationError(f"invalid url: {value!r}")
    return value


def is_sqlite_boolean(value: object) -> bool:
    return value in (0, 1)


_ISO_RE = re.compile(r"^\d{4}-\d{2}-\d{2}T\d{2}:\d{2}:\d{2}\.\d{3}Z$")


def is_sqlite_date(value: object) -> bool:
    return isinstance(value, str) and _ISO_RE.match(value) is not None


def cast(value: Union[bool, datetime.datetime, int, str]) -> Union[int, str, bool, datetime.datetime]:
    """model.ts:100-112 — the two-way boolean/date cast.

    bool → 0/1, datetime → ISO string; 0/1 → bool and ISO string →
    datetime on the way back (the reference overloads one `cast`).
    """
    if isinstance(value, bool):
        return 1 if value else 0
    if isinstance(value, datetime.datetime):
        utc = value.astimezone(datetime.timezone.utc)
        millis = int(utc.timestamp() * 1000)
        from evolu_tpu.core.timestamp import millis_to_iso

        return millis_to_iso(millis)
    if isinstance(value, int) and value in (0, 1):
        return value == 1
    if isinstance(value, str) and _ISO_RE.match(value):
        from evolu_tpu.core.timestamp import iso_to_millis

        return datetime.datetime.fromtimestamp(
            iso_to_millis(value) / 1000, tz=datetime.timezone.utc
        )
    raise TypeError(f"cast: unsupported value {value!r}")


def sqlite_value(value: object) -> object:
    """Normalize a mutation value to its storable form: bools and
    datetimes cast (db.ts:281-283), everything else passes through."""
    if isinstance(value, (bool, datetime.datetime)):
        return cast(value)
    return value


# Common columns present on every row (types.ts:194-201).
COMMON_COLUMNS = ("createdAt", "createdBy", "updatedAt", "isDeleted")

__all__ = [
    "SqliteBoolean",
    "SqliteDate",
    "COMMON_COLUMNS",
    "cast",
    "sqlite_value",
    "create_id",
    "is_valid_id",
    "validate_mnemonic",
    "validate_string_1000",
    "validate_non_empty_string_1000",
    "validate_email",
    "validate_url",
    "is_sqlite_boolean",
    "is_sqlite_date",
]
