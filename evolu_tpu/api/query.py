"""Compile-only SQL query builder — the Kysely analog.

Reference: packages/evolu/src/kysely.ts builds a Kysely instance with a
DummyDriver: queries are *compiled* to `{sql, parameters}` but never
executed by the builder; execution belongs to the DbWorker
(createHooks.ts:28-37). This module is the same idea natively: a small
immutable fluent builder whose `.serialize()` yields the
`SqlQueryString` the runtime subscribes with.

Identifiers are always double-quoted; values always travel as bound
parameters — the builder never interpolates values into SQL.
"""

from __future__ import annotations

from dataclasses import dataclass, field, replace
from typing import List, Optional, Sequence, Tuple, Union

from evolu_tpu.runtime.messages import serialize_query

_OPS = ("=", "!=", "<>", "<", "<=", ">", ">=", "like", "not like", "is", "is not", "in")


def _quote(identifier: str) -> str:
    if "\x00" in identifier:
        raise ValueError("identifier contains NUL")
    return '"' + identifier.replace('"', '""') + '"'


@dataclass(frozen=True)
class QueryBuilder:
    """An immutable SELECT builder; every method returns a new builder."""

    _table: str
    _columns: Tuple[str, ...] = ()
    _wheres: Tuple[Tuple[str, str, object], ...] = ()
    _order_by: Tuple[Tuple[str, str], ...] = ()
    _limit: Optional[int] = None
    _offset: Optional[int] = None

    def select(self, *columns: str) -> "QueryBuilder":
        return replace(self, _columns=self._columns + columns)

    def select_all(self) -> "QueryBuilder":
        return replace(self, _columns=())

    def where(self, column: str, op: str, value: object) -> "QueryBuilder":
        if op.lower() not in _OPS:
            raise ValueError(f"unsupported operator: {op}")
        return replace(self, _wheres=self._wheres + ((column, op.lower(), value),))

    def where_is_deleted(self, deleted: bool = False) -> "QueryBuilder":
        """The common soft-delete filter (examples/nextjs/pages/index.tsx
        queries filter `isDeleted is not 1`)."""
        op, v = ("is", 1) if deleted else ("is not", 1)
        return self.where("isDeleted", op, v)

    def order_by(self, column: str, direction: str = "asc") -> "QueryBuilder":
        if direction.lower() not in ("asc", "desc"):
            raise ValueError(f"bad direction: {direction}")
        return replace(self, _order_by=self._order_by + ((column, direction.lower()),))

    def limit(self, n: int) -> "QueryBuilder":
        return replace(self, _limit=int(n))

    def offset(self, n: int) -> "QueryBuilder":
        return replace(self, _offset=int(n))

    def compile(self) -> Tuple[str, List[object]]:
        """→ (sql, parameters), like Kysely's `.compile()`."""
        cols = ", ".join(_quote(c) for c in self._columns) if self._columns else "*"
        sql = f"SELECT {cols} FROM {_quote(self._table)}"
        parameters: List[object] = []
        if self._wheres:
            terms = []
            for column, op, value in self._wheres:
                if op == "in":
                    values = list(value)  # type: ignore[arg-type]
                    marks = ", ".join("?" for _ in values)
                    terms.append(f"{_quote(column)} in ({marks})")
                    parameters.extend(values)
                elif op in ("is", "is not") and value is None:
                    terms.append(f"{_quote(column)} {op} null")
                else:
                    terms.append(f"{_quote(column)} {op} ?")
                    parameters.append(value)
            sql += " WHERE " + " AND ".join(terms)
        if self._order_by:
            sql += " ORDER BY " + ", ".join(f"{_quote(c)} {d}" for c, d in self._order_by)
        if self._limit is not None:
            sql += " LIMIT ?"
            parameters.append(self._limit)
        elif self._offset is not None:
            sql += " LIMIT -1"  # SQLite requires LIMIT before OFFSET
        if self._offset is not None:
            sql += " OFFSET ?"
            parameters.append(self._offset)
        return sql, parameters

    def serialize(self) -> str:
        """→ SqlQueryString, the runtime's canonical query key."""
        sql, parameters = self.compile()
        return serialize_query(sql, parameters)


def table(name: str) -> QueryBuilder:
    """Entry point: `table("todo").select("id", "title").where(...)`."""
    return QueryBuilder(name)
