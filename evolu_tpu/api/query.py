"""Compile-only SQL query builder — the Kysely analog.

Reference: packages/evolu/src/kysely.ts builds a Kysely instance with a
DummyDriver: queries are *compiled* to `{sql, parameters}` but never
executed by the builder; execution belongs to the DbWorker
(createHooks.ts:28-37). This module is the same idea natively: a small
immutable fluent builder whose `.serialize()` yields the
`SqlQueryString` the runtime subscribes with. The surface mirrors what
the reference's Kysely instance exposes to apps: selects with aliases,
inner/left joins (`innerJoin("todoCategory", "todoCategory.id",
"todo.categoryId")`), aggregate functions (`fn.count`), group by,
having, order/limit/offset.

Identifiers are always double-quoted; values always travel as bound
parameters — the builder never interpolates values into SQL.
"""

from __future__ import annotations

from dataclasses import dataclass, field, replace
from typing import List, Optional, Sequence, Tuple, Union

from evolu_tpu.runtime.messages import serialize_query

_OPS = ("=", "!=", "<>", "<", "<=", ">", ">=", "like", "not like", "is", "is not", "in")
_FNS = ("count", "sum", "avg", "min", "max", "total", "group_concat")


def _quote(identifier: str) -> str:
    if "\x00" in identifier:
        raise ValueError("identifier contains NUL")
    return '"' + identifier.replace('"', '""') + '"'


def _quote_ref(ref: str) -> str:
    """Quote a possibly table-qualified reference: `todo.title` →
    `"todo"."title"`, `title` → `"title"`."""
    return ".".join(_quote(part) for part in ref.split("."))


@dataclass(frozen=True)
class Fn:
    """An aggregate select expression, e.g. `fn.count("id").as_("n")`.
    `ref=None` means `*` (COUNT only)."""

    name: str
    ref: Optional[str]
    alias: Optional[str] = None
    distinct: bool = False

    def as_(self, alias: str) -> "Fn":
        return replace(self, alias=alias)

    def sql(self) -> str:
        inner = "*" if self.ref is None else _quote_ref(self.ref)
        if self.distinct:
            inner = "distinct " + inner
        out = f"{self.name}({inner})"
        if self.alias is not None:
            out += f" as {_quote(self.alias)}"
        return out


class fn:
    """Aggregate helpers, the Kysely `fn` namespace analog."""

    @staticmethod
    def _make(name: str, ref: Optional[str], distinct: bool = False) -> Fn:
        if name not in _FNS:
            raise ValueError(f"unsupported function: {name}")
        if ref is None and name != "count":
            raise ValueError(f"{name} requires a column")
        if ref is None and distinct:
            # count(distinct *) is invalid SQLite; failing here beats
            # failing later when the subscribed query first executes.
            raise ValueError("count(distinct) requires a column")
        return Fn(name, ref, None, distinct)

    @staticmethod
    def count(ref: Optional[str] = None, distinct: bool = False) -> Fn:
        return fn._make("count", ref, distinct)

    @staticmethod
    def sum(ref: str) -> Fn:
        return fn._make("sum", ref)

    @staticmethod
    def avg(ref: str) -> Fn:
        return fn._make("avg", ref)

    @staticmethod
    def min(ref: str) -> Fn:
        return fn._make("min", ref)

    @staticmethod
    def max(ref: str) -> Fn:
        return fn._make("max", ref)

    @staticmethod
    def total(ref: str) -> Fn:
        return fn._make("total", ref)

    @staticmethod
    def group_concat(ref: str, distinct: bool = False) -> Fn:
        return fn._make("group_concat", ref, distinct)


# A select item: a (possibly qualified) column ref, a (ref, alias)
# pair, or an aggregate Fn.
SelectItem = Union[str, Tuple[str, str], Fn]


# -- predicate expression trees --
#
# The reference exposes the full Kysely read-only expression surface to
# apps (types.ts:188-280; kysely.ts:12-27): `eb.or([...])`,
# `eb.and([...])`, `eb.not(...)`, `eb.exists(selectFrom(...))`, and
# `in`-subqueries. These nodes are the native analog: an immutable tree
# that `compile()` walks left-to-right so bound-parameter order always
# matches placeholder order.


class Cond:
    """A predicate node. Combine with `&`, `|`, `~` or the `and_` /
    `or_` / `not_` helpers."""

    def sql(self, parameters: List[object]) -> str:
        raise NotImplementedError

    def __and__(self, other: "Cond") -> "Cond":
        return and_(self, other)

    def __or__(self, other: "Cond") -> "Cond":
        return or_(self, other)

    def __invert__(self) -> "Cond":
        return not_(self)


@dataclass(frozen=True)
class Ref:
    """A column reference used as a comparison RHS — compiles to the
    quoted identifier, never a bound parameter. The Kysely `whereRef`
    analog; what makes `exists` subqueries correlated."""

    name: str


def ref(name: str) -> Ref:
    return Ref(name)


@dataclass(frozen=True)
class Comparison(Cond):
    """Leaf: `target op value`. For `in`, value may be a sequence of
    bindables or a QueryBuilder (compiled as a subquery); for any op,
    a `ref(...)` value compares against another column."""

    target: Union[str, Fn]
    op: str
    value: object

    def sql(self, parameters: List[object]) -> str:
        if isinstance(self.target, Fn):
            # Reusing a selected-and-aliased Fn in having() is the
            # natural flow; the alias belongs to the select list only.
            lhs = replace(self.target, alias=None).sql()
        else:
            lhs = _quote_ref(self.target)
        if isinstance(self.value, Ref):
            return f"{lhs} {self.op} {_quote_ref(self.value.name)}"
        if self.op == "in":
            if isinstance(self.value, QueryBuilder):
                sub_sql, sub_params = self.value.compile()
                parameters.extend(sub_params)
                return f"{lhs} in ({sub_sql})"
            values = list(self.value)  # type: ignore[arg-type]
            if not values:
                # SQLite rejects `x in ()` at parse time; an empty set
                # matches nothing, so compile the constant instead of
                # deferring a syntax error to first execution.
                return "1 = 0"
            marks = ", ".join("?" for _ in values)
            parameters.extend(values)
            return f"{lhs} in ({marks})"
        if self.op in ("is", "is not") and self.value is None:
            return f"{lhs} {self.op} null"
        parameters.append(self.value)
        return f"{lhs} {self.op} ?"


@dataclass(frozen=True)
class Group(Cond):
    """`(a AND b AND ...)` / `(a OR b OR ...)` — always parenthesized,
    so nesting needs no precedence bookkeeping."""

    kind: str  # "and" | "or"
    terms: Tuple[Cond, ...]

    def sql(self, parameters: List[object]) -> str:
        inner = f" {self.kind} ".join(t.sql(parameters) for t in self.terms)
        return f"({inner})"


@dataclass(frozen=True)
class Not(Cond):
    term: Cond

    def sql(self, parameters: List[object]) -> str:
        return f"not ({self.term.sql(parameters)})"


@dataclass(frozen=True)
class Exists(Cond):
    """`exists (SELECT ...)`. The subquery may reference outer-table
    columns (correlated); refs compile identically either way."""

    query: "QueryBuilder"
    negate: bool = False

    def sql(self, parameters: List[object]) -> str:
        sub_sql, sub_params = self.query.compile()
        parameters.extend(sub_params)
        keyword = "not exists" if self.negate else "exists"
        return f"{keyword} ({sub_sql})"


# Distinguishes "argument omitted" from an explicit None (NULL bind):
# a forgotten value must fail at build time, not compile to `x = NULL`
# (never true in SQLite — a silently empty subscribed query).
_MISSING = object()


def c(target: Union[str, Fn], op: str, value: object = _MISSING) -> Comparison:
    """Leaf constructor: `c("todo.title", "like", "a%")`."""
    if op.lower() not in _OPS:
        raise ValueError(f"unsupported operator: {op}")
    if value is _MISSING:
        raise ValueError(f"comparison {target!r} {op!r} is missing its value")
    return Comparison(target, op.lower(), value)


def _as_cond(term: object) -> Cond:
    if isinstance(term, Cond):
        return term
    if isinstance(term, tuple) and len(term) == 3:
        return c(*term)
    raise ValueError(f"not a condition: {term!r}")


def and_(*terms: object) -> Cond:
    """`and_(c(...), or_(...), ("col", "=", v))` — tuples are accepted
    as comparison shorthand."""
    if not terms:
        raise ValueError("and_ requires at least one term")
    return Group("and", tuple(_as_cond(t) for t in terms))


def or_(*terms: object) -> Cond:
    if not terms:
        raise ValueError("or_ requires at least one term")
    return Group("or", tuple(_as_cond(t) for t in terms))


def not_(term: object) -> Cond:
    return Not(_as_cond(term))


def exists(query: "QueryBuilder") -> Cond:
    return Exists(query)


def not_exists(query: "QueryBuilder") -> Cond:
    return Exists(query, negate=True)


def _select_sql(item: SelectItem) -> str:
    if isinstance(item, Fn):
        return item.sql()
    if isinstance(item, tuple):
        ref, alias = item
        return f"{_quote_ref(ref)} as {_quote(alias)}"
    return _quote_ref(item)


@dataclass(frozen=True)
class QueryBuilder:
    """An immutable SELECT builder; every method returns a new builder."""

    _table: str
    _columns: Tuple[SelectItem, ...] = ()
    _joins: Tuple[Tuple[str, str, str, str], ...] = ()  # (kind, table, left, right)
    _wheres: Tuple[Cond, ...] = ()
    _group_by: Tuple[str, ...] = ()
    _havings: Tuple[Cond, ...] = ()
    _order_by: Tuple[Tuple[str, str], ...] = ()
    _limit: Optional[int] = None
    _offset: Optional[int] = None

    def select(self, *columns: SelectItem) -> "QueryBuilder":
        return replace(self, _columns=self._columns + columns)

    def select_all(self) -> "QueryBuilder":
        return replace(self, _columns=())

    def inner_join(self, other: str, left_ref: str, right_ref: str) -> "QueryBuilder":
        """`inner_join("todoCategory", "todoCategory.id",
        "todo.categoryId")` — the Kysely innerJoin signature."""
        return replace(
            self, _joins=self._joins + (("inner", other, left_ref, right_ref),)
        )

    def left_join(self, other: str, left_ref: str, right_ref: str) -> "QueryBuilder":
        return replace(
            self, _joins=self._joins + (("left", other, left_ref, right_ref),)
        )

    def where(self, column, op: Optional[str] = None, value: object = _MISSING) -> "QueryBuilder":
        """Either the 3-arg comparison form `where("title", "=", x)` or
        a single expression tree `where(or_(c(...), and_(c(...), ...)))`
        — the Kysely `where(eb => eb.or([...]))` analog. Multiple
        `where()` calls AND together, like Kysely."""
        if op is None:
            term = _as_cond(column)
        else:
            term = c(column, op, value)
        return replace(self, _wheres=self._wheres + (term,))

    def where_is_deleted(self, deleted: bool = False) -> "QueryBuilder":
        """The common soft-delete filter (examples/nextjs/pages/index.tsx
        queries filter `isDeleted is not 1`)."""
        op, v = ("is", 1) if deleted else ("is not", 1)
        return self.where("isDeleted", op, v)

    def group_by(self, *refs: str) -> "QueryBuilder":
        return replace(self, _group_by=self._group_by + refs)

    def having(self, target, op: Optional[str] = None, value: object = _MISSING) -> "QueryBuilder":
        if op is None:
            term = _as_cond(target)
        else:
            term = c(target, op, value)
        return replace(self, _havings=self._havings + (term,))

    def order_by(self, column: str, direction: str = "asc") -> "QueryBuilder":
        if direction.lower() not in ("asc", "desc"):
            raise ValueError(f"bad direction: {direction}")
        return replace(self, _order_by=self._order_by + ((column, direction.lower()),))

    def limit(self, n: int) -> "QueryBuilder":
        return replace(self, _limit=int(n))

    def offset(self, n: int) -> "QueryBuilder":
        return replace(self, _offset=int(n))

    def compile(self) -> Tuple[str, List[object]]:
        """→ (sql, parameters), like Kysely's `.compile()`."""
        cols = ", ".join(_select_sql(c) for c in self._columns) if self._columns else "*"
        sql = f"SELECT {cols} FROM {_quote(self._table)}"
        for kind, other, left_ref, right_ref in self._joins:
            sql += (
                f" {kind} join {_quote(other)}"
                f" on {_quote_ref(left_ref)} = {_quote_ref(right_ref)}"
            )
        parameters: List[object] = []
        if self._wheres:
            sql += " WHERE " + " AND ".join(t.sql(parameters) for t in self._wheres)
        if self._group_by:
            sql += " GROUP BY " + ", ".join(_quote_ref(r) for r in self._group_by)
        if self._havings:
            if not self._group_by:
                raise ValueError("having requires group_by")
            sql += " HAVING " + " AND ".join(t.sql(parameters) for t in self._havings)
        if self._order_by:
            sql += " ORDER BY " + ", ".join(
                f"{_quote_ref(c)} {d}" for c, d in self._order_by
            )
        if self._limit is not None:
            sql += " LIMIT ?"
            parameters.append(self._limit)
        elif self._offset is not None:
            sql += " LIMIT -1"  # SQLite requires LIMIT before OFFSET
        if self._offset is not None:
            sql += " OFFSET ?"
            parameters.append(self._offset)
        return sql, parameters

    def serialize(self) -> str:
        """→ SqlQueryString, the runtime's canonical query key."""
        sql, parameters = self.compile()
        return serialize_query(sql, parameters)


def table(name: str) -> QueryBuilder:
    """Entry point: `table("todo").select("id", "title").where(...)`."""
    return QueryBuilder(name)
