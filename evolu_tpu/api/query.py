"""Compile-only SQL query builder — the Kysely analog.

Reference: packages/evolu/src/kysely.ts builds a Kysely instance with a
DummyDriver: queries are *compiled* to `{sql, parameters}` but never
executed by the builder; execution belongs to the DbWorker
(createHooks.ts:28-37). This module is the same idea natively: a small
immutable fluent builder whose `.serialize()` yields the
`SqlQueryString` the runtime subscribes with. The surface mirrors what
the reference's Kysely instance exposes to apps: selects with aliases,
inner/left joins (`innerJoin("todoCategory", "todoCategory.id",
"todo.categoryId")`), aggregate functions (`fn.count`), group by,
having, order/limit/offset.

Identifiers are always double-quoted; values always travel as bound
parameters — the builder never interpolates values into SQL.
"""

from __future__ import annotations

from dataclasses import dataclass, field, replace
from typing import List, Optional, Sequence, Tuple, Union

from evolu_tpu.runtime.messages import serialize_query

_OPS = ("=", "!=", "<>", "<", "<=", ">", ">=", "like", "not like", "is", "is not", "in")
_FNS = ("count", "sum", "avg", "min", "max", "total", "group_concat")


def _quote(identifier: str) -> str:
    if "\x00" in identifier:
        raise ValueError("identifier contains NUL")
    return '"' + identifier.replace('"', '""') + '"'


def _quote_ref(ref: str) -> str:
    """Quote a possibly table-qualified reference: `todo.title` →
    `"todo"."title"`, `title` → `"title"`."""
    return ".".join(_quote(part) for part in ref.split("."))


@dataclass(frozen=True)
class Fn:
    """An aggregate select expression, e.g. `fn.count("id").as_("n")`.
    `ref=None` means `*` (COUNT only)."""

    name: str
    ref: Optional[str]
    alias: Optional[str] = None
    distinct: bool = False

    def as_(self, alias: str) -> "Fn":
        return replace(self, alias=alias)

    def sql(self) -> str:
        inner = "*" if self.ref is None else _quote_ref(self.ref)
        if self.distinct:
            inner = "distinct " + inner
        out = f"{self.name}({inner})"
        if self.alias is not None:
            out += f" as {_quote(self.alias)}"
        return out


class fn:
    """Aggregate helpers, the Kysely `fn` namespace analog."""

    @staticmethod
    def _make(name: str, ref: Optional[str], distinct: bool = False) -> Fn:
        if name not in _FNS:
            raise ValueError(f"unsupported function: {name}")
        if ref is None and name != "count":
            raise ValueError(f"{name} requires a column")
        return Fn(name, ref, None, distinct)

    @staticmethod
    def count(ref: Optional[str] = None, distinct: bool = False) -> Fn:
        return fn._make("count", ref, distinct)

    @staticmethod
    def sum(ref: str) -> Fn:
        return fn._make("sum", ref)

    @staticmethod
    def avg(ref: str) -> Fn:
        return fn._make("avg", ref)

    @staticmethod
    def min(ref: str) -> Fn:
        return fn._make("min", ref)

    @staticmethod
    def max(ref: str) -> Fn:
        return fn._make("max", ref)

    @staticmethod
    def total(ref: str) -> Fn:
        return fn._make("total", ref)

    @staticmethod
    def group_concat(ref: str, distinct: bool = False) -> Fn:
        return fn._make("group_concat", ref, distinct)


# A select item: a (possibly qualified) column ref, a (ref, alias)
# pair, or an aggregate Fn.
SelectItem = Union[str, Tuple[str, str], Fn]


def _select_sql(item: SelectItem) -> str:
    if isinstance(item, Fn):
        return item.sql()
    if isinstance(item, tuple):
        ref, alias = item
        return f"{_quote_ref(ref)} as {_quote(alias)}"
    return _quote_ref(item)


@dataclass(frozen=True)
class QueryBuilder:
    """An immutable SELECT builder; every method returns a new builder."""

    _table: str
    _columns: Tuple[SelectItem, ...] = ()
    _joins: Tuple[Tuple[str, str, str, str], ...] = ()  # (kind, table, left, right)
    _wheres: Tuple[Tuple[str, str, object], ...] = ()
    _group_by: Tuple[str, ...] = ()
    _havings: Tuple[Tuple[Union[str, Fn], str, object], ...] = ()
    _order_by: Tuple[Tuple[str, str], ...] = ()
    _limit: Optional[int] = None
    _offset: Optional[int] = None

    def select(self, *columns: SelectItem) -> "QueryBuilder":
        return replace(self, _columns=self._columns + columns)

    def select_all(self) -> "QueryBuilder":
        return replace(self, _columns=())

    def inner_join(self, other: str, left_ref: str, right_ref: str) -> "QueryBuilder":
        """`inner_join("todoCategory", "todoCategory.id",
        "todo.categoryId")` — the Kysely innerJoin signature."""
        return replace(
            self, _joins=self._joins + (("inner", other, left_ref, right_ref),)
        )

    def left_join(self, other: str, left_ref: str, right_ref: str) -> "QueryBuilder":
        return replace(
            self, _joins=self._joins + (("left", other, left_ref, right_ref),)
        )

    def where(self, column: str, op: str, value: object) -> "QueryBuilder":
        if op.lower() not in _OPS:
            raise ValueError(f"unsupported operator: {op}")
        return replace(self, _wheres=self._wheres + ((column, op.lower(), value),))

    def where_is_deleted(self, deleted: bool = False) -> "QueryBuilder":
        """The common soft-delete filter (examples/nextjs/pages/index.tsx
        queries filter `isDeleted is not 1`)."""
        op, v = ("is", 1) if deleted else ("is not", 1)
        return self.where("isDeleted", op, v)

    def group_by(self, *refs: str) -> "QueryBuilder":
        return replace(self, _group_by=self._group_by + refs)

    def having(self, target: Union[str, Fn], op: str, value: object) -> "QueryBuilder":
        if op.lower() not in _OPS:
            raise ValueError(f"unsupported operator: {op}")
        return replace(self, _havings=self._havings + ((target, op.lower(), value),))

    def order_by(self, column: str, direction: str = "asc") -> "QueryBuilder":
        if direction.lower() not in ("asc", "desc"):
            raise ValueError(f"bad direction: {direction}")
        return replace(self, _order_by=self._order_by + ((column, direction.lower()),))

    def limit(self, n: int) -> "QueryBuilder":
        return replace(self, _limit=int(n))

    def offset(self, n: int) -> "QueryBuilder":
        return replace(self, _offset=int(n))

    @staticmethod
    def _condition(target: Union[str, Fn], op: str, value: object, parameters: List[object]) -> str:
        if isinstance(target, Fn):
            # Reusing a selected-and-aliased Fn in having() is the
            # natural flow; the alias belongs to the select list only.
            lhs = replace(target, alias=None).sql()
        else:
            lhs = _quote_ref(target)
        if op == "in":
            values = list(value)  # type: ignore[arg-type]
            marks = ", ".join("?" for _ in values)
            parameters.extend(values)
            return f"{lhs} in ({marks})"
        if op in ("is", "is not") and value is None:
            return f"{lhs} {op} null"
        parameters.append(value)
        return f"{lhs} {op} ?"

    def compile(self) -> Tuple[str, List[object]]:
        """→ (sql, parameters), like Kysely's `.compile()`."""
        cols = ", ".join(_select_sql(c) for c in self._columns) if self._columns else "*"
        sql = f"SELECT {cols} FROM {_quote(self._table)}"
        for kind, other, left_ref, right_ref in self._joins:
            sql += (
                f" {kind} join {_quote(other)}"
                f" on {_quote_ref(left_ref)} = {_quote_ref(right_ref)}"
            )
        parameters: List[object] = []
        if self._wheres:
            terms = [
                self._condition(column, op, value, parameters)
                for column, op, value in self._wheres
            ]
            sql += " WHERE " + " AND ".join(terms)
        if self._group_by:
            sql += " GROUP BY " + ", ".join(_quote_ref(r) for r in self._group_by)
        if self._havings:
            if not self._group_by:
                raise ValueError("having requires group_by")
            terms = [
                self._condition(target, op, value, parameters)
                for target, op, value in self._havings
            ]
            sql += " HAVING " + " AND ".join(terms)
        if self._order_by:
            sql += " ORDER BY " + ", ".join(
                f"{_quote_ref(c)} {d}" for c, d in self._order_by
            )
        if self._limit is not None:
            sql += " LIMIT ?"
            parameters.append(self._limit)
        elif self._offset is not None:
            sql += " LIMIT -1"  # SQLite requires LIMIT before OFFSET
        if self._offset is not None:
            sql += " OFFSET ?"
            parameters.append(self._offset)
        return sql, parameters

    def serialize(self) -> str:
        """→ SqlQueryString, the runtime's canonical query key."""
        sql, parameters = self.compile()
        return serialize_query(sql, parameters)


def table(name: str) -> QueryBuilder:
    """Entry point: `table("todo").select("id", "title").where(...)`."""
    return QueryBuilder(name)
