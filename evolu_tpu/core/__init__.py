"""Pure CPU reference core ("the oracle").

Exact reference semantics for HLC timestamps, murmur3 hashing, the
Merkle trie, and LWW message application. Every JAX/TPU kernel in
`evolu_tpu.ops` is property-tested against this module.
"""
