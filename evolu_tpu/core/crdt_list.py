"""RGA sequence CRDT — the `"col:list"` column type (ISSUE 14).

Collaborative list/text editing on the PR-7 typed-op substrate: inserts
and deletes are ordinary `CrdtMessage`s (the Merkle/anti-entropy
algebra stays TIMESTAMP-ONLY and byte-for-byte unchanged — the
Merkle-CRDT argument, arXiv:2004.00107), and only the app-table
materialization differs. Semantics follow the RGA family from the
op-based composition framework (arXiv:2004.04303):

- **insert op** `["i", origin, value]`: places a new element AFTER the
  element identified by `origin` (an element's identity is its insert
  op's own HLC timestamp — globally unique for free, exactly like the
  AW-set add tag); `origin == ""` inserts at the head.
- **delete op** `["d", tag]`: tombstones element `tag`. Tombstones are
  permanent (GC is an explicit non-goal — see docs/LIST_CRDT.md): a
  dead element keeps its position so concurrent inserts anchored on it
  still land deterministically, and a delete arriving BEFORE its
  insert (anti-entropy has no causal delivery) is tombstoned in
  `__crdt_list_kill` so the insert is dead on arrival.

**The one ordering rule** (the whole merge): replay the DISTINCT
insert-op set in ascending raw-string timestamp order, placing each
element immediately after its origin (head for `""`). Because HLC
timestamps of causally-later ops compare greater, every element a
replica could have observed is already placed when its insert replays
— so this is exactly the reference-semantics RGA: siblings anchored on
the same origin end up in DESCENDING raw-string timestamp order (a
later concurrent insert at the same anchor lands closer to the
anchor). A dangling origin (hostile bytes, or an op whose origin is
not in the delivered set / not smaller than the op's own timestamp)
deterministically roots at the head group — materialization is a pure
function of the delivered op SET, so any permutation / partition /
redelivery schedule converges.

Layer map (the PR-7 playbook):
- this module: codecs (ValueError-only), the pure host-oracle
  linearization (the semantics ground truth), `__crdt_list` /
  `__crdt_list_kill` SQL merge state, and materialization;
- `ops/crdt_list_merge.py`: the device twin (Euler-tour list ranking
  over one global sort + the `pallas_scan` segmented machinery),
  bit-identical to the oracle and routed only for in-bounds batches;
- `storage/apply.py` → `crdt_types.apply_typed_ops`: folds new list
  ops inside the apply transaction (dedup = `__message` timestamp-PK
  screen), before the batch's `__message` insert;
- `runtime/client.py`: `list_insert` / `list_append` / `list_delete` /
  `list_elements` (drain-before-observe, the `set_remove` lesson);
- `sync/protocol.py`: the advisory `crdt-list-v1` capability.
"""

from __future__ import annotations

import json
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

from evolu_tpu.core.types import CrdtMessage
from evolu_tpu.obs import metrics

ROOT_ORIGIN = ""  # the type tag itself lives in crdt_types.LIST

# An origin/target tag is an HLC timestamp string (46 chars canonical).
# Anything longer is hostile framing; rejecting it at the codec keeps
# the state tables bounded and is convergence-safe (malformed ops drop
# identically on every replica).
_MAX_TAG_LEN = 256

# The device linearization packs (cell, parent, rank) into one i64 sort
# key (ops/crdt_list_merge.py); batches beyond these bounds route to
# the host oracle BEFORE any side effect (the r5 oversized contract).
DEVICE_MAX_ELEMS = (1 << 20) - 2
DEVICE_MAX_CELLS = (1 << 22) - 2

LIST_STATE_TABLES_SQL = (
    # One row per insert op; "tag" is the element identity (the insert
    # op's timestamp), "origin" the anchor tag ("" = head), "value" the
    # canonical JSON element encoding. alive=0 marks a tombstoned
    # element — the row STAYS (position anchor; GC non-goal).
    'CREATE TABLE IF NOT EXISTS "__crdt_list" ('
    '"tag" BLOB PRIMARY KEY, "table" BLOB, "row" BLOB, "column" BLOB, '
    '"origin" BLOB, "value" BLOB, "alive" INTEGER NOT NULL)',
    'CREATE INDEX IF NOT EXISTS "index__crdt_list_cell" ON "__crdt_list" '
    '("table", "row", "column")',
    # Delete tombstones for elements not (yet) inserted — same shape as
    # the AW-set `__crdt_kill` (a delete may arrive before its insert).
    'CREATE TABLE IF NOT EXISTS "__crdt_list_kill" ("tag" BLOB PRIMARY KEY)',
)

Cell = Tuple[str, str, str]


# --- op codecs (ValueError-only, like every wire decoder) ---


def _check_tag(tag, what: str) -> str:
    if not isinstance(tag, str):
        raise ValueError(f"list op {what} must be a timestamp string: {tag!r}")
    if len(tag) > _MAX_TAG_LEN:
        raise ValueError(f"list op {what} exceeds {_MAX_TAG_LEN} chars")
    return tag


def list_insert_value(value, after: Optional[str] = None) -> str:
    """Encode an insert op value. `after` is the origin element's tag
    (None/"" = head). The op's OWN timestamp becomes the element tag."""
    from evolu_tpu.core.crdt_types import elem_key

    origin = _check_tag(after if after is not None else ROOT_ORIGIN, "origin")
    return json.dumps(["i", origin, json.loads(elem_key(value))],
                      separators=(",", ":"))


def list_delete_value(tag: str) -> str:
    """Encode a delete op tombstoning element `tag`."""
    return json.dumps(["d", _check_tag(tag, "target")], separators=(",", ":"))


def decode_list_op(value) -> Tuple[str, str, str]:
    """Decode a list op value → ("i", origin, elem_json) or
    ("d", target, ""). ValueError only — the fold layer catches, counts
    and drops malformed ops so a hostile peer can never wedge sync."""
    from evolu_tpu.core.crdt_types import elem_key

    if not isinstance(value, str):
        raise ValueError(f"list op value must be a JSON string: {value!r}")
    try:
        op = json.loads(value)
    except json.JSONDecodeError as e:
        raise ValueError(f"malformed list op JSON: {e}") from e
    if not isinstance(op, list) or not op or op[0] not in ("i", "d"):
        raise ValueError(f"malformed list op shape: {value!r}")
    if op[0] == "i":
        if len(op) != 3:
            raise ValueError(f"insert op must be ['i', origin, value]: {value!r}")
        return "i", _check_tag(op[1], "origin"), elem_key(op[2])
    if len(op) != 2:
        raise ValueError(f"delete op must be ['d', tag]: {value!r}")
    return "d", _check_tag(op[1], "target"), ""


def decode_list_batch(
    msgs: Sequence[CrdtMessage],
) -> Tuple[List[Tuple[CrdtMessage, str, str]], List[Tuple[CrdtMessage, str]], int]:
    """→ (inserts [(msg, origin, elem_json)] tagged by msg.timestamp,
    deletes [(msg, target_tag)], malformed_count). Malformed ops drop
    HERE so they can never touch a cell — whether a cell materializes
    must be a function of the delivered VALID op set only (the same
    batching-independence argument as `decode_set_batch`)."""
    inserts: List[Tuple[CrdtMessage, str, str]] = []
    deletes: List[Tuple[CrdtMessage, str]] = []
    bad = 0
    for m in msgs:
        try:
            kind, a, b = decode_list_op(m.value)
        except ValueError:
            bad += 1
            continue
        if kind == "i":
            inserts.append((m, a, b))
        else:
            deletes.append((m, a))
    return inserts, deletes, bad


# --- the host-oracle linearization (the semantics ground truth) ---


def linearize(tags: Sequence[str], origins: Sequence[str]) -> List[int]:
    """Document position (0-based, tombstones INCLUDED — they anchor)
    per element, for one cell. `tags` must be distinct (the state
    table's PK guarantees it); order of the input arrays is irrelevant.

    Equivalent to replaying inserts in ascending raw-string timestamp
    order, each placed immediately after its origin: build the sibling
    tree (parent = origin iff origin is a delivered element AND
    compares smaller than the tag, else the head group), then DFS with
    children in DESCENDING tag order. O(n log n)."""
    n = len(tags)
    order = sorted(range(n), key=lambda i: tags[i])
    present = set(tags)
    if len(present) != n:
        raise ValueError("duplicate element tags in linearize input")
    children: Dict[str, List[int]] = {}
    for i in order:
        o = origins[i]
        parent = o if (o != ROOT_ORIGIN and o in present and o < tags[i]) \
            else ROOT_ORIGIN
        children.setdefault(parent, []).append(i)  # ascending append
    pos = [0] * n
    stack = list(children.get(ROOT_ORIGIN, ()))  # pop() → highest tag first
    c = 0
    while stack:
        i = stack.pop()
        pos[i] = c
        c += 1
        stack.extend(children.get(tags[i], ()))
    return pos


def materialize_list_value(values_in_doc_order: Iterable[str]) -> str:
    """Canonical JSON array over ALIVE element values in document order
    — NOT sorted, NOT deduped (it is a sequence, not a set)."""
    return "[" + ",".join(values_in_doc_order) + "]"


def fold_cell(
    elems: Sequence[Tuple[str, str, str, bool]],
) -> Tuple[List[int], str]:
    """Pure per-cell fold: [(tag, origin, elem_json, alive)] →
    (positions, materialized value). The one-call oracle the device
    twin and the model-check replay are pinned against."""
    tags = [e[0] for e in elems]
    pos = linearize(tags, [e[1] for e in elems])
    by_pos = sorted(range(len(elems)), key=lambda i: pos[i])
    return pos, materialize_list_value(
        elems[i][2] for i in by_pos if elems[i][3]
    )


def replay_log(msgs: Sequence[CrdtMessage]) -> Dict[Cell, str]:
    """Host-oracle replay of a FULL op log (any order, duplicates
    fine): → {cell: materialized value}. Ground truth for model-check
    episodes — must equal whatever the incremental apply materialized."""
    seen: Set[str] = set()
    per_cell: Dict[Cell, List[Tuple[CrdtMessage, str, str]]] = {}
    kills: Set[str] = set()
    for m in msgs:
        if m.timestamp in seen:
            continue
        seen.add(m.timestamp)
        try:
            kind, a, b = decode_list_op(m.value)
        except ValueError:
            continue
        if kind == "d":
            kills.add(a)
            per_cell.setdefault((m.table, m.row, m.column), [])
        else:
            per_cell.setdefault((m.table, m.row, m.column), []).append((m, a, b))
    out: Dict[Cell, str] = {}
    for cell, inserts in per_cell.items():
        elems = [(m.timestamp, origin, val, m.timestamp not in kills)
                 for m, origin, val in inserts]
        out[cell] = fold_cell(elems)[1] if elems else "[]"
    return out


# --- SQL state fold (runs INSIDE the caller's apply transaction) ---


def apply_list_ops(db, new_msgs: Sequence[CrdtMessage]) -> Set[Cell]:
    """Fold NEW list ops (already screened against __message) into
    `__crdt_list` / `__crdt_list_kill`. Returns touched cells; the
    caller (`crdt_types.apply_typed_ops`) materializes them."""
    from evolu_tpu.core.crdt_types import LIST as _LT, _chunked_in, alive_add_flags

    inserts, deletes, bad = decode_list_batch(new_msgs)
    if bad:
        metrics.inc("evolu_crdt_malformed_ops_total", bad, type=_LT)
    if not inserts and not deletes:
        return set()
    metrics.inc("evolu_crdt_ops_total", len(inserts) + len(deletes), type=_LT)
    if inserts:
        metrics.inc("evolu_crdt_list_ops_total", len(inserts), kind="insert")
    if deletes:
        metrics.inc("evolu_crdt_list_ops_total", len(deletes), kind="delete")

    kills: Set[str] = {t for _m, t in deletes}
    insert_tags = [m.timestamp for m, _o, _v in inserts]
    state_killed: Set[str] = set()
    if insert_tags:
        state_killed = {
            r["tag"]
            for r in _chunked_in(
                db, 'SELECT "tag" FROM "__crdt_list_kill" WHERE "tag" IN ({})',
                insert_tags,
            )
        }
    alive = alive_add_flags(insert_tags, kills, state_killed)

    touched: Set[Cell] = set()
    if kills:
        # Tombstone first, then kill matching EXISTING alive elements
        # (their rows stay — position anchors; only `alive` flips).
        db.run_many(
            'INSERT OR IGNORE INTO "__crdt_list_kill" ("tag") VALUES (?)',
            [(t,) for t in sorted(kills)],
        )
        killed_rows = _chunked_in(
            db,
            'SELECT "tag", "table", "row", "column" FROM "__crdt_list" '
            'WHERE "alive" = 1 AND "tag" IN ({})',
            sorted(kills),
        )
        if killed_rows:
            db.run_many(
                'UPDATE "__crdt_list" SET "alive" = 0 WHERE "tag" = ?',
                [(r["tag"],) for r in killed_rows],
            )
            touched.update((r["table"], r["row"], r["column"]) for r in killed_rows)
    if inserts:
        db.run_many(
            'INSERT OR IGNORE INTO "__crdt_list" '
            '("tag", "table", "row", "column", "origin", "value", "alive") '
            "VALUES (?, ?, ?, ?, ?, ?, ?)",
            [
                (m.timestamp, m.table, m.row, m.column, origin, val, int(a))
                for (m, origin, val), a in zip(inserts, alive)
            ],
        )
        touched.update((m.table, m.row, m.column) for m, _o, _v in inserts)
    # Every VALID op touches its cell — a delete targeting a cell with
    # no stored elements still materializes it (possibly as "[]"),
    # identically on every replica regardless of batching.
    touched.update((m.table, m.row, m.column) for m, _t in deletes)
    return touched


def _cell_rows(db, table: str, column: str, rows: Sequence[str]) -> Dict[str, list]:
    """ALL stored elements (alive AND dead — positions need both) of
    the touched cells, grouped per row."""
    out: Dict[str, list] = {}
    for i in range(0, len(rows), 500):
        part = rows[i : i + 500]
        q = (
            'SELECT "row", "tag", "origin", "value", "alive" FROM "__crdt_list" '
            'WHERE "table" = ? AND "column" = ? AND "row" IN ({})'
        ).format(",".join("?" * len(part)))
        for r in db.exec_sql_query(q, (table, column, *part)):
            out.setdefault(r["row"], []).append(
                (r["tag"], r["origin"], r["value"], bool(r["alive"]))
            )
    return out


def materialize_list_values(
    db, table: str, column: str, rows: Sequence[str]
) -> Dict[str, str]:
    """→ {row: canonical JSON array} for the touched cells of one
    (table, column). Linearization routes to the device twin
    (`ops.crdt_list_merge.rga_order`) when the combined element count
    clears `DEVICE_FOLD_MIN` and fits the packed-key bounds; anything
    oversized stays on the host oracle (routed BEFORE any side effect
    — this function only reads)."""
    from evolu_tpu.core.crdt_types import DEVICE_FOLD_MIN

    per_row = _cell_rows(db, table, column, rows)
    total = sum(len(v) for v in per_row.values())
    oversized = total > DEVICE_MAX_ELEMS or len(per_row) > DEVICE_MAX_CELLS
    use_device = DEVICE_FOLD_MIN <= total and not oversized
    if oversized:
        metrics.inc("evolu_crdt_list_oversized_host_routes_total")
    metrics.inc("evolu_crdt_list_linearize_total",
                path="device" if use_device else "host")
    metrics.inc("evolu_crdt_list_linearized_elements_total", total)
    if use_device:
        return _materialize_device(per_row)
    return {
        row: fold_cell(elems)[1] for row, elems in per_row.items()
    }


def _materialize_device(per_row: Dict[str, list]) -> Dict[str, str]:
    """Batch every touched cell into ONE device linearization dispatch
    (`rga_order`), then place alive values by the kernel's segmented
    alive-slot output — bit-identical to `fold_cell` (test-pinned)."""
    import numpy as np

    from evolu_tpu.ops.crdt_list_merge import rga_order

    cell_id: List[int] = []
    parent_ix: List[int] = []
    alive: List[int] = []
    vals: List[str] = []
    spans: List[Tuple[str, int, int]] = []  # (row, start, count)
    orphans = 0
    for ci, row in enumerate(sorted(per_row)):
        elems = sorted(per_row[row])  # ascending tag — the rank order
        base = len(cell_id)
        ix = {tag: j for j, (tag, _o, _v, _a) in enumerate(elems)}
        for j, (tag, origin, val, a) in enumerate(elems):
            if origin != ROOT_ORIGIN and origin in ix and origin < tag:
                p = ix[origin]
            else:
                p = -1
                if origin != ROOT_ORIGIN:
                    orphans += 1
            cell_id.append(ci)
            parent_ix.append(base + p if p >= 0 else -1)
            alive.append(int(a))
            vals.append(val)
        spans.append((row, base, len(elems)))
    if orphans:
        metrics.inc("evolu_crdt_list_orphan_inserts_total", orphans)
    pos, slot = rga_order(
        np.asarray(cell_id, np.int32),
        np.asarray(parent_ix, np.int32),
        np.asarray(alive, np.int32),
    )
    out: Dict[str, str] = {}
    for row, base, count in spans:
        n_alive = int(np.sum(np.asarray(alive[base : base + count])))
        parts: List[str] = [""] * n_alive
        for j in range(base, base + count):
            if alive[j]:
                parts[int(slot[j])] = vals[j]
        out[row] = materialize_list_value(parts)
    return out


# --- reads for the client API (drain-before-observe callers) ---


def list_state(db, table: str, row: str, column: str) -> List[Tuple[str, str]]:
    """Alive (tag, elem_json) pairs of one cell in document order —
    what `Evolu.list_elements` returns (after draining the worker) and
    what `list_append` / index-addressed deletes observe."""
    rows = db.exec_sql_query(
        'SELECT "tag", "origin", "value", "alive" FROM "__crdt_list" '
        'WHERE "table" = ? AND "row" = ? AND "column" = ?',
        (table, row, column),
    )
    if not rows:
        return []
    elems = [(r["tag"], r["origin"], r["value"], bool(r["alive"])) for r in rows]
    pos = linearize([e[0] for e in elems], [e[1] for e in elems])
    by_pos = sorted(range(len(elems)), key=lambda i: pos[i])
    return [(elems[i][0], elems[i][2]) for i in by_pos if elems[i][3]]
