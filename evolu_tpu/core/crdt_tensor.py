"""Tensor-valued CRDT columns — the `"col:tensor:…"` type (ISSUE 20).

CRDT-compliant model merging as a first-class workload (the two-layer
CRDT model-merging architecture, arXiv:2605.19373): replicas
collaboratively edit fixed-shape numeric state, converged by a merge
monoid DECLARED per column. Ops are ordinary `CrdtMessage`s on the
PR-7 typed-op substrate — the Merkle/anti-entropy algebra stays
TIMESTAMP-ONLY and byte-for-byte unchanged; only the app-table
materialization differs (materialization-only divergence, exactly like
counters, sets and lists before it).

Column spec: `"weights:tensor:<monoid>:<dtype>:<shape>"`, e.g.
`"weights:tensor:sum:f32:4x8"` — monoid ∈ {sum, mean, max}, dtype ∈
{f32, bf16}, shape `x`-separated. The FULL type string is stored in
`__crdt_schema`, so the generic conflict check ("cannot re-declare
with a different type") covers monoid/dtype/shape changes for free.

**Exactness model (the whole design):** float addition is not
associative, so a float fold could never be bit-identical under
arbitrary permutation/partition/redelivery (the acceptance bar every
CRDT type here clears). Sum and mean therefore quantize at decode —
`q = rint(v * 2^16)` — and accumulate in MODULAR uint64 (two's
complement), which IS exactly commutative and associative: device and
host agree unconditionally, in any order, on any backend. Codec caps
(`|v| ≤ 2^15`, `count ≤ 2^15`) make the sum exact (no wrap) up to
~2^31 ops/cell and the count-weighted mean up to ~2^16 ops/cell;
beyond that the accumulator wraps mod 2^64 — still CONVERGENT on
every replica, just wrapped (documented in docs/TENSOR_CRDT.md; the
same bound shape as the PN-counter's int32-delta argument). Values
live on the 2^-16 lattice: an overwrite's payload is quantized too, so
base and deltas compose in one integer algebra. Element-wise max maps
f32 bits through the standard monotone u32 key transform (nonneg →
bits|0x8000_0000, neg → ~bits): integer max is exact and idempotent,
and the total order puts -0.0 below +0.0.

**Merge monoids** (op kinds: `["d", b64]` delta, `["s", b64]` set):
- `sum`: cell value = Σ quantized deltas (mod 2^64 per element).
- `mean`: deltas carry a count (`["d", b64, count]`, the mean-by-count
  weight); cell value = Σ(q·count) / Σ count.
- `max`: element-wise max over delta payloads (exact float bits, no
  quantization).
- LWW-overwrite fallback, composed with each delta monoid by the
  SEMIDIRECT-PRODUCT rule (arXiv:2004.04303): the latest `set` op (by
  raw-string timestamp, the LWW order) resets the fold base; deltas
  timestamped AFTER it reapply on top; deltas before it are shadowed.
  The fold is a pure function of the delivered op SET — every
  schedule converges. The base itself enters the fold as one ordinary
  contribution (quantized / key-mapped), so "reset + reapply" is a
  single segmented reduction.

Layer map (the PR-7/PR-14 playbook):
- this module: specs + ValueError-only codecs with declared
  shape/dtype validation and the `TENSOR_MAX_BYTES` payload cap, the
  `__crdt_tensor` op-log SQL state, the pure-numpy host oracle
  (`fold_cell` / `replay_log` — the semantics ground truth), and
  materialization with device routing;
- `ops/crdt_tensor_merge.py`: the device twin — the merge IS a
  batched segmented reduction over the `pallas_scan` machinery
  (blocked XLA on CPU, single-pass Pallas on TPU), with the
  reconcile-shaped shard cores (`pack_owner_cell_key` packed layout +
  the wide fallback these payload widths finally exercise);
- `storage/apply.py` → `crdt_types.apply_typed_ops`: folds new tensor
  ops inside the apply transaction (dedup = `__message` screen);
- `runtime/client.py`: `tensor_delta` / `tensor_set` / `tensor_value`
  (drain-before-observe, the `set_remove` lesson);
- `sync/protocol.py`: the advisory `crdt-tensor-v1` capability.

The HOST does all raw-string timestamp ordering (base selection +
delta masking), exactly like the list twin — device kernels see only
integers, so the canonical-timestamp routing contract never applies
to the tensor leg. GC is an explicit non-goal: `__crdt_tensor` keeps
one row per op (the log IS the state; a snapshot bootstrap ships it
like any other state table).
"""

from __future__ import annotations

import base64
import functools
import json
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

import numpy as np

from evolu_tpu.core.types import CrdtMessage
from evolu_tpu.obs import metrics

TENSOR = "tensor"
MONOIDS = ("sum", "mean", "max")
DTYPES = ("f32", "bf16")

# Payload cap, enforced at DECLARATION (a schema whose cells cannot be
# shipped must fail loudly) and re-checked at decode (hostile framing).
TENSOR_MAX_BYTES = 1 << 16
_MAX_DIMS = 8

# Fixed-point lattice: q = rint(v * 2^16). The codec magnitude cap
# |v| ≤ 2^15 bounds |q| ≤ 2^31, so an unwrapped sum survives ~2^31
# ops/cell (the PN-counter bound); count ≤ 2^15 bounds the weighted
# mean's unwrapped range at ~2^16 ops/cell. Beyond: modular wrap,
# convergent on every replica.
_FRAC_BITS = 16
_SCALE = float(1 << _FRAC_BITS)
_MAG_MAX = float(1 << 15)
_COUNT_MAX = 1 << 15

# Flat-element ceiling for ONE device dispatch (ops × elements after
# flattening); materialization chunks row groups under it, and a
# single cell exceeding it folds on the host oracle.
DEVICE_MAX_FLAT = 1 << 24

TENSOR_STATE_TABLES_SQL = (
    # One row per op — the log IS the merge state (the semidirect fold
    # needs every delta's timestamp relative to the winning base, so
    # nothing can be pre-reduced without re-deriving LWW order). "tag"
    # is the op's own HLC timestamp (PK = the redelivery screen),
    # "kind" is "d"/"s", "count" the mean weight (1 elsewhere),
    # "payload" the raw little-endian element bytes.
    'CREATE TABLE IF NOT EXISTS "__crdt_tensor" ('
    '"tag" BLOB PRIMARY KEY, "table" BLOB, "row" BLOB, "column" BLOB, '
    '"kind" BLOB, "count" INTEGER NOT NULL, "payload" BLOB)',
    'CREATE INDEX IF NOT EXISTS "index__crdt_tensor_cell" ON "__crdt_tensor" '
    '("table", "row", "column")',
)

Cell = Tuple[str, str, str]


class TensorConfig:
    """Parsed, validated column config — the unit every codec, fold and
    kernel wrapper takes. Hashable/immutable; `type_string` round-trips
    to the `__crdt_schema` entry."""

    __slots__ = ("monoid", "dtype", "shape", "size", "nbytes", "type_string")

    def __init__(self, monoid: str, dtype: str, shape: Tuple[int, ...]):
        self.monoid = monoid
        self.dtype = dtype
        self.shape = shape
        self.size = 1
        for d in shape:
            self.size *= d
        self.nbytes = self.size * (4 if dtype == "f32" else 2)
        self.type_string = (
            f"{TENSOR}:{monoid}:{dtype}:" + "x".join(str(d) for d in shape)
        )

    def __repr__(self) -> str:  # pragma: no cover - debug aid
        return f"TensorConfig({self.type_string!r})"


@functools.lru_cache(maxsize=None)
def parse_tensor_type(ct: str) -> TensorConfig:
    """`"tensor:sum:f32:4x8"` → TensorConfig. ValueError only — a
    typo'd declaration must fail loudly, never become an LWW column."""
    parts = ct.split(":")
    if len(parts) != 4 or parts[0] != TENSOR:
        raise ValueError(
            f"tensor column type must be 'tensor:<monoid>:<dtype>:<shape>': {ct!r}"
        )
    _tag, monoid, dtype, shape_s = parts
    if monoid not in MONOIDS:
        raise ValueError(f"unknown tensor merge monoid {monoid!r} in {ct!r}")
    if dtype not in DTYPES:
        raise ValueError(f"unknown tensor dtype {dtype!r} in {ct!r}")
    dims = shape_s.split("x")
    if not dims or len(dims) > _MAX_DIMS:
        raise ValueError(f"tensor shape must have 1..{_MAX_DIMS} dims: {ct!r}")
    shape: List[int] = []
    for d in dims:
        if not d.isdigit() or (len(d) > 1 and d[0] == "0") or int(d) < 1:
            raise ValueError(f"bad tensor dim {d!r} in {ct!r}")
        shape.append(int(d))
    cfg = TensorConfig(monoid, dtype, tuple(shape))
    if cfg.nbytes > TENSOR_MAX_BYTES:
        raise ValueError(
            f"tensor payload {cfg.nbytes}B exceeds the {TENSOR_MAX_BYTES}B cap: {ct!r}"
        )
    return cfg


def is_tensor_type(ct: str) -> bool:
    return isinstance(ct, str) and ct.startswith(TENSOR + ":")


def tensor_type(monoid: str, dtype: str, shape: Sequence[int]) -> str:
    """Spec-suffix builder (validates): `tensor_type("sum","f32",(4,8))`
    → `"tensor:sum:f32:4x8"` — append to a column name with `:`."""
    ct = f"{TENSOR}:{monoid}:{dtype}:" + "x".join(str(int(d)) for d in shape)
    parse_tensor_type(ct)
    return ct


def _np_dtype(cfg: TensorConfig):
    if cfg.dtype == "f32":
        return np.dtype(np.float32)
    import ml_dtypes  # jax hard dependency; no backend touch

    return np.dtype(ml_dtypes.bfloat16)


# --- op codecs (ValueError-only, like every wire decoder) ---


def _encode(cfg: TensorConfig, kind: str, array, count: int = 1) -> str:
    arr = np.asarray(array, dtype=np.float32)
    if arr.shape != cfg.shape:
        raise ValueError(
            f"tensor op shape {arr.shape} != declared {cfg.shape}"
        )
    if not np.all(np.isfinite(arr)):
        raise ValueError("tensor op values must be finite")
    if cfg.monoid != "max" and bool(np.any(np.abs(arr) > _MAG_MAX)):
        raise ValueError(f"tensor op magnitude exceeds {_MAG_MAX}")
    payload = arr.reshape(-1).astype(_np_dtype(cfg)).tobytes()
    b64 = base64.b64encode(payload).decode("ascii")
    if cfg.monoid == "mean":
        if isinstance(count, bool) or not isinstance(count, int) \
                or not 1 <= count <= _COUNT_MAX:
            raise ValueError(f"tensor op count must be 1..{_COUNT_MAX}: {count!r}")
        return json.dumps([kind, b64, count], separators=(",", ":"))
    if count != 1:
        raise ValueError(f"count is the mean monoid's weight, not {cfg.monoid}'s")
    return json.dumps([kind, b64], separators=(",", ":"))


def tensor_delta_value(cfg: TensorConfig, array, count: int = 1) -> str:
    """Encode a delta op value for `cfg`'s monoid."""
    return _encode(cfg, "d", array, count)


def tensor_set_value(cfg: TensorConfig, array, count: int = 1) -> str:
    """Encode an overwrite (the semidirect LWW fallback): resets the
    fold base; later-timestamped deltas reapply on top."""
    return _encode(cfg, "s", array, count)


def decode_tensor_op(cfg: TensorConfig, value) -> Tuple[str, bytes, int]:
    """Decode an op value against the DECLARED config → (kind, payload
    bytes, count). ValueError only — the fold layer catches, counts and
    drops malformed ops so a hostile peer can never wedge sync. Every
    accepted payload is exactly `cfg.nbytes` of finite, magnitude-
    bounded elements (bound skipped for max, which never accumulates)."""
    if not isinstance(value, str):
        raise ValueError(f"tensor op value must be a JSON string: {value!r}")
    if len(value) > 2 * TENSOR_MAX_BYTES:
        raise ValueError("tensor op value exceeds the payload cap")
    try:
        op = json.loads(value)
    except json.JSONDecodeError as e:
        raise ValueError(f"malformed tensor op JSON: {e}") from e
    if not isinstance(op, list) or not op or op[0] not in ("d", "s"):
        raise ValueError(f"malformed tensor op shape: {value!r}")
    count = 1
    if cfg.monoid == "mean":
        if len(op) != 3:
            raise ValueError(f"mean op must be [kind, b64, count]: {value!r}")
        count = op[2]
        if isinstance(count, bool) or not isinstance(count, int) \
                or not 1 <= count <= _COUNT_MAX:
            raise ValueError(f"tensor op count must be 1..{_COUNT_MAX}: {count!r}")
    elif len(op) != 2:
        raise ValueError(f"{cfg.monoid} op must be [kind, b64]: {value!r}")
    if not isinstance(op[1], str):
        raise ValueError(f"tensor op payload must be base64: {value!r}")
    try:
        payload = base64.b64decode(op[1], validate=True)
    except Exception as e:  # binascii.Error
        raise ValueError(f"tensor op payload is not base64: {e}") from e
    if len(payload) != cfg.nbytes:
        raise ValueError(
            f"tensor op payload {len(payload)}B != declared {cfg.nbytes}B"
        )
    arr = _payload_f32(cfg, payload)
    if not np.all(np.isfinite(arr)):
        raise ValueError("tensor op payload must be finite")
    if cfg.monoid != "max" and bool(np.any(np.abs(arr) > _MAG_MAX)):
        raise ValueError(f"tensor op magnitude exceeds {_MAG_MAX}")
    return op[0], payload, count


def decode_tensor_batch(
    cfg: TensorConfig, msgs: Sequence[CrdtMessage]
) -> Tuple[List[Tuple[CrdtMessage, str, bytes, int]], int]:
    """→ ([(msg, kind, payload, count)], malformed_count). Malformed
    ops drop HERE so they can never touch a cell (the decode-layer
    batching-independence argument shared by every typed decoder)."""
    out: List[Tuple[CrdtMessage, str, bytes, int]] = []
    bad = 0
    for m in msgs:
        try:
            kind, payload, count = decode_tensor_op(cfg, m.value)
        except ValueError:
            bad += 1
            continue
        out.append((m, kind, payload, count))
    return out, bad


# --- the fixed-point / key algebra (shared by oracle AND device prep) ---


def _payload_f32(cfg: TensorConfig, payload: bytes) -> np.ndarray:
    """Payload bytes → (size,) float32 (bf16 widens EXACTLY)."""
    if cfg.dtype == "f32":
        return np.frombuffer(payload, dtype=np.float32)
    import ml_dtypes

    return np.frombuffer(payload, dtype=ml_dtypes.bfloat16).astype(np.float32)


def quantize(cfg: TensorConfig, payload: bytes) -> np.ndarray:
    """Payload → (size,) int64 on the 2^-16 lattice. f32→f64 widening
    and the f64 multiply are exact; rint is IEEE round-half-even —
    fully deterministic across platforms."""
    v = _payload_f32(cfg, payload).astype(np.float64)
    return np.rint(v * _SCALE).astype(np.int64)


def monotone_key(cfg: TensorConfig, payload: bytes) -> np.ndarray:
    """f32 bits → (size,) uint32 keys with unsigned-integer order ==
    float total order (nonneg → bits|0x8000_0000, neg → ~bits; -0.0
    sorts below +0.0). Codec-rejected non-finite values never reach
    here, so NaN ordering is moot."""
    b = _payload_f32(cfg, payload).view(np.uint32)
    return np.where(b >> 31 != 0, ~b, b | np.uint32(0x80000000)).astype(np.uint32)


def monotone_key_invert(keys: np.ndarray) -> np.ndarray:
    """Inverse of `monotone_key` → float32."""
    k = keys.astype(np.uint32)
    b = np.where(k >> 31 != 0, k ^ np.uint32(0x80000000), ~k)
    return b.astype(np.uint32).view(np.float32)


def zeros_value(cfg: TensorConfig) -> bytes:
    """The app-table default for a never-touched cell: all-zero element
    bytes (identical for f32 and bf16 — 0.0 encodes as zero bytes)."""
    return bytes(cfg.nbytes)


def _finalize(cfg: TensorConfig, acc: np.ndarray, den: int) -> bytes:
    """Accumulator → canonical app-table bytes. ONE copy shared by the
    host oracle and the device unpack, so finalization can never drift:
    - sum/mean: u64 acc viewed two's-complement int64, divided on the
      exact f64 lattice (den includes the 2^16 scale), then ONE rounding
      into the declared dtype;
    - max: u32 keys inverted to f32, then narrowed."""
    if cfg.monoid == "max":
        vec = monotone_key_invert(acc.astype(np.uint32))
    else:
        vec = acc.astype(np.uint64).view(np.int64).astype(np.float64) / (
            float(den) * _SCALE
        )
    return np.asarray(vec, dtype=_np_dtype(cfg)).tobytes()


# --- host-oracle fold (the semantics ground truth) ---


def contributing_ops(
    ops: Sequence[Tuple[str, str, int, bytes]],
) -> List[Tuple[str, int, bytes]]:
    """The semidirect mask: [(tag, kind, count, payload)] in ANY order
    (duplicate tags collapse keep-first, mirroring the PK / keep-first
    screen) → the ordered contributing list [(kind, count, payload)]:
    the latest `set` op (raw-string tag order — the LWW rule), then
    every delta tagged strictly after it; with no set op, all deltas.
    Deltas shadowed by the base drop here, which is exactly what makes
    the fold a pure function of the op set."""
    by_tag: Dict[str, Tuple[str, int, bytes]] = {}
    for tag, kind, count, payload in ops:
        if tag not in by_tag:
            by_tag[tag] = (kind, count, payload)
    tags = sorted(by_tag)
    base_i = -1
    for i, t in enumerate(tags):
        if by_tag[t][0] == "s":
            base_i = i
    contrib: List[Tuple[str, int, bytes]] = []
    if base_i >= 0:
        contrib.append(by_tag[tags[base_i]])
    for t in tags[base_i + 1:] if base_i >= 0 else tags:
        kind, count, payload = by_tag[t]
        if kind == "d":
            contrib.append((kind, count, payload))
    return contrib


def _fold_contributions(
    cfg: TensorConfig, contrib: Sequence[Tuple[str, int, bytes]]
) -> bytes:
    """Pure-numpy reduction over an already-masked contributing list —
    modular u64 for sum/mean (commutative AND associative bit-exactly,
    the device-parity keystone), integer max over monotone keys."""
    if not contrib:
        return zeros_value(cfg)
    if cfg.monoid == "max":
        acc: Optional[np.ndarray] = None
        for _kind, _count, payload in contrib:
            keys = monotone_key(cfg, payload)
            acc = keys if acc is None else np.maximum(acc, keys)
        return _finalize(cfg, acc, 1)
    acc64 = np.zeros(cfg.size, np.uint64)
    den = 0
    for _kind, count, payload in contrib:
        c = count if cfg.monoid == "mean" else 1
        acc64 += quantize(cfg, payload).view(np.uint64) * np.uint64(c)
        den += c
    return _finalize(cfg, acc64, den if cfg.monoid == "mean" else 1)


def fold_cell(cfg: TensorConfig, ops: Sequence[Tuple[str, str, int, bytes]]) -> bytes:
    """Pure per-cell fold: [(tag, kind, count, payload)] in any order →
    canonical materialized bytes. The one-call oracle the device twin,
    the goldens and the model-check replay are pinned against."""
    return _fold_contributions(cfg, contributing_ops(ops))


def replay_log(
    types: Dict[Tuple[str, str], str], msgs: Sequence[CrdtMessage]
) -> Dict[Cell, bytes]:
    """Host-oracle replay of a FULL op log (any order, duplicates
    fine): → {cell: materialized bytes} for every tensor column in
    `types`. Ground truth for the model-check episodes."""
    seen: Set[str] = set()
    per_cell: Dict[Cell, List[Tuple[str, str, int, bytes]]] = {}
    for m in msgs:
        if m.timestamp in seen:
            continue
        seen.add(m.timestamp)
        ct = types.get((m.table, m.column))
        if ct is None or not is_tensor_type(ct):
            continue
        try:
            kind, payload, count = decode_tensor_op(parse_tensor_type(ct), m.value)
        except ValueError:
            continue
        per_cell.setdefault((m.table, m.row, m.column), []).append(
            (m.timestamp, kind, count, payload)
        )
    return {
        cell: fold_cell(parse_tensor_type(types[(cell[0], cell[2])]), ops)
        for cell, ops in per_cell.items()
    }


# --- SQL state fold (runs INSIDE the caller's apply transaction) ---


def apply_tensor_ops(db, ct: str, new_msgs: Sequence[CrdtMessage]) -> Set[Cell]:
    """Fold NEW tensor ops of ONE declared type (already screened
    against __message) into the `__crdt_tensor` op log. Returns touched
    cells; the caller (`crdt_types.apply_typed_ops`) materializes them."""
    if not new_msgs:
        return set()
    cfg = parse_tensor_type(ct)
    valid, bad = decode_tensor_batch(cfg, new_msgs)
    if bad:
        metrics.inc("evolu_crdt_malformed_ops_total", bad, type=TENSOR)
    if not valid:
        return set()
    metrics.inc("evolu_crdt_ops_total", len(valid), type=TENSOR)
    n_sets = sum(1 for _m, kind, _p, _c in valid if kind == "s")
    if n_sets:
        metrics.inc("evolu_crdt_tensor_ops_total", n_sets, kind="set")
    if len(valid) - n_sets:
        metrics.inc("evolu_crdt_tensor_ops_total", len(valid) - n_sets,
                    kind="delta")
    metrics.inc("evolu_crdt_tensor_bytes_total",
                sum(len(p) for _m, _k, p, _c in valid))
    db.run_many(
        'INSERT OR IGNORE INTO "__crdt_tensor" '
        '("tag", "table", "row", "column", "kind", "count", "payload") '
        "VALUES (?, ?, ?, ?, ?, ?, ?)",
        [
            (m.timestamp, m.table, m.row, m.column, kind, count, payload)
            for m, kind, payload, count in valid
        ],
    )
    # Every VALID op touches its cell — identically on every replica
    # regardless of batching (the decode-layer screen above).
    return {(m.table, m.row, m.column) for m, _k, _p, _c in valid}


def _cell_rows(
    db, table: str, column: str, rows: Sequence[str]
) -> Dict[str, List[Tuple[str, str, int, bytes]]]:
    """ALL stored ops of the touched cells, grouped per row."""
    out: Dict[str, List[Tuple[str, str, int, bytes]]] = {}
    for i in range(0, len(rows), 500):
        part = rows[i : i + 500]
        q = (
            'SELECT "row", "tag", "kind", "count", "payload" FROM "__crdt_tensor" '
            'WHERE "table" = ? AND "column" = ? AND "row" IN ({})'
        ).format(",".join("?" * len(part)))
        for r in db.exec_sql_query(q, (table, column, *part)):
            out.setdefault(r["row"], []).append(
                (r["tag"], r["kind"], r["count"], r["payload"])
            )
    return out


def materialize_tensor_values(
    db, ct: str, table: str, column: str, rows: Sequence[str]
) -> Dict[str, bytes]:
    """→ {row: canonical element bytes} for the touched cells of one
    (table, column). The HOST applies the semidirect mask (raw-string
    tag ordering — timestamps never reach the device); the masked
    contributions route to the device twin when the flattened element
    count clears `DEVICE_FOLD_MIN`, chunked under `DEVICE_MAX_FLAT`
    per dispatch (routing happens BEFORE any side effect — this
    function only reads)."""
    from evolu_tpu.core.crdt_types import DEVICE_FOLD_MIN

    cfg = parse_tensor_type(ct)
    per_row = _cell_rows(db, table, column, rows)
    plans = {row: contributing_ops(ops) for row, ops in per_row.items()}
    total_elems = sum(len(c) for c in plans.values()) * cfg.size
    use_device = DEVICE_FOLD_MIN <= total_elems
    metrics.inc("evolu_crdt_tensor_fold_total",
                path="device" if use_device else "host", monoid=cfg.monoid)
    metrics.inc("evolu_crdt_tensor_folded_elements_total", total_elems)
    if use_device:
        return _materialize_device(cfg, plans)
    return {row: _fold_contributions(cfg, c) for row, c in plans.items()}


def _materialize_device(
    cfg: TensorConfig, plans: Dict[str, List[Tuple[str, int, bytes]]]
) -> Dict[str, bytes]:
    """Batch every touched cell's masked contributions into segmented-
    reduction dispatches (`ops.crdt_tensor_merge.tensor_cell_folds`) —
    bit-identical to `_fold_contributions` (test-pinned) because both
    sides reduce the SAME u64 lattice / u32 keys with an exactly
    associative-commutative combine. Row groups chunk under
    `DEVICE_MAX_FLAT` flat elements; a single cell too big for one
    dispatch folds on the host oracle (counted)."""
    from evolu_tpu.ops.crdt_tensor_merge import tensor_cell_folds

    out: Dict[str, bytes] = {}
    max_ops = DEVICE_MAX_FLAT // cfg.size
    chunk_rows: List[Tuple[str, List[Tuple[str, int, bytes]]]] = []
    chunk_ops = 0

    def _flush():
        nonlocal chunk_rows, chunk_ops
        if not chunk_rows:
            return
        cell_id = np.empty(chunk_ops, np.int32)
        contrib = np.empty((chunk_ops, cfg.size), np.uint64)
        dens: List[int] = []
        at = 0
        for ci, (_row, contribs) in enumerate(chunk_rows):
            den = 0
            for _kind, count, payload in contribs:
                if cfg.monoid == "max":
                    contrib[at] = monotone_key(cfg, payload).astype(np.uint64)
                else:
                    c = count if cfg.monoid == "mean" else 1
                    contrib[at] = (
                        quantize(cfg, payload).view(np.uint64) * np.uint64(c)
                    )
                    den += c
                cell_id[at] = ci
                at += 1
            dens.append(den if cfg.monoid == "mean" else 1)
        table = tensor_cell_folds(cell_id, contrib, len(chunk_rows), cfg.monoid)
        for ci, (row, _contribs) in enumerate(chunk_rows):
            out[row] = _finalize(cfg, table[ci], dens[ci])
        chunk_rows = []
        chunk_ops = 0

    for row in sorted(plans):
        contribs = plans[row]
        if not contribs:
            out[row] = zeros_value(cfg)
            continue
        if len(contribs) > max_ops:  # one cell exceeds a dispatch
            metrics.inc("evolu_crdt_tensor_oversized_host_folds_total")
            out[row] = _fold_contributions(cfg, contribs)
            continue
        if chunk_ops + len(contribs) > max_ops:
            _flush()
        chunk_rows.append((row, contribs))
        chunk_ops += len(contribs)
    _flush()
    return out


# --- reads for the client API (drain-before-observe callers) ---


def tensor_config(db, table: str, column: str) -> TensorConfig:
    """The declared config of (table, column) — raises ValueError when
    the column is not a declared tensor column (writing tensor ops into
    an undeclared column would LWW them; fail loudly instead)."""
    from evolu_tpu.core.crdt_types import load_schema

    ct = load_schema(db).column_type(table, column)
    if not is_tensor_type(ct):
        raise ValueError(f"{table}.{column} is not a declared tensor column: {ct!r}")
    return parse_tensor_type(ct)


def tensor_state(db, table: str, row: str, column: str) -> Optional[np.ndarray]:
    """The materialized cell value as a shaped numpy array (declared
    dtype), or None when the app row does not exist. Callers drain the
    worker first (`Evolu.tensor_value`)."""
    from evolu_tpu.storage.sqlite import quote_ident

    cfg = tensor_config(db, table, column)
    rows = db.exec_sql_query(
        f'SELECT {quote_ident(column)} AS "v" FROM {quote_ident(table)} '
        'WHERE "id" = ?',
        (row,),
    )
    if not rows:
        return None
    raw = rows[0]["v"]
    if raw is None:
        raw = zeros_value(cfg)
    if isinstance(raw, str):
        raw = raw.encode("latin-1")
    return np.frombuffer(bytes(raw), dtype=_np_dtype(cfg)).reshape(cfg.shape).copy()
