"""CRDT column types beyond the LWW register (ROADMAP #4, ISSUE 7).

The reference (Evolu v0.5.1) expresses exactly one merge semantic:
last-writer-wins per (table, row, column) cell. This module adds two
op-based column types on the SAME substrate — ops are ordinary
`CrdtMessage`s whose timestamps feed the unchanged Merkle/anti-entropy
/snapshot machinery; only the APP-TABLE materialization differs:

- **PN-counter** (`"counter"`): each op's value is a signed int delta
  (the (replica, pos, neg) decomposition: replica = the op timestamp's
  node, pos/neg = the delta's sign). Cell value = Σ deltas over the
  distinct op set — permutation- and partition-invariant, so any
  delivery schedule converges (arXiv:2004.04303's op-based composition
  view: the increment monoid needs no resolver at all).
- **Add-wins set** (`"awset"`, observed-remove): an add op carries a
  JSON `["a", elem]` and is tagged by its own (globally unique) op
  timestamp; a remove carries `["r", elem, [observed add tags...]]`
  and kills exactly the adds it OBSERVED. An add whose tag no remove
  ever lists survives — concurrent add beats remove (true AW-set, not
  the timestamp-biased LWW-element approximation), and the fold is
  order-free: alive(tag) = added(tag) ∧ tag ∉ kills, regardless of
  arrival order (a kill arriving before its add still wins — kills are
  tombstoned in `__crdt_kill`).

A third type, the **RGA sequence** (`"list"`, ISSUE 14), lives in its
own module `core/crdt_list.py` (insert-after ordering with tombstoned
deletes — the genuinely order-SENSITIVE merge); this module dispatches
its fold and materialization through the same typed-apply leg. So does
the **tensor family** (`"tensor:<monoid>:<dtype>:<shape>"`, ISSUE 20,
`core/crdt_tensor.py`): fixed-shape numeric cells with a declared
merge monoid — there the TYPE STRING itself is parameterized, so
`partition_typed`'s full-string keys carry each column's config to the
fold dispatch for free.

Design invariants (see docs/CRDT_TYPES.md):
- The LWW xor/Merkle algebra is TIMESTAMP-ONLY and stays byte-for-byte
  unchanged for typed cells: replication, snapshot bootstrap, and the
  winner cache's MAX(timestamp) slots need no new wire format — typed
  ops ride the existing E2EE-opaque message stream, which is exactly
  why a v1 peer relays them byte-identically (capability negotiation
  in sync/protocol.py is an announcement, not a format fork).
- Typed cells NEVER take the LWW app-table upsert: `storage.apply`
  strips them from every planner's upsert set (one copy:
  `ops.merge.strip_typed_upserts`) and folds newly-inserted ops into
  the `__crdt_*` state tables inside the same transaction, then
  materializes the cell value (counter: pos−neg int; set: canonical
  sorted JSON array) into the app table.
- Op decoding raises ValueError ONLY; the fold layer catches, counts
  (`evolu_crdt_malformed_ops_total`) and ignores malformed ops — a
  hostile peer must not be able to wedge an owner's sync by writing
  garbage to a typed column.
- Host oracle first: every fold here is the semantics reference; the
  device kernels (`ops/crdt_merge.py`) are pinned bit-identical to it
  on property-sampled op logs (tests/test_crdt_types.py + golden
  fixtures that are never updated).
"""

from __future__ import annotations

import json
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

from evolu_tpu.core.types import CrdtMessage
from evolu_tpu.obs import metrics

LWW = "lww"
COUNTER = "counter"
AWSET = "awset"
LIST = "list"  # RGA sequence CRDT (ISSUE 14) — semantics in core/crdt_list.py
COLUMN_TYPES = (LWW, COUNTER, AWSET, LIST)

# Counter deltas are bounded to int32 so 2^31 ops can never overflow
# the int64 pos/neg accumulators (SQLite INTEGER and the device's i64
# segmented sums share the bound).
_DELTA_MIN, _DELTA_MAX = -(2**31) + 1, 2**31 - 1

# Batches of at least this many new typed ops fold on device
# (ops/crdt_merge.py); below it the host oracle is faster than a
# dispatch (same shape of cutoff as Config.min_device_batch).
DEVICE_FOLD_MIN = 4096

Cell = Tuple[str, str, str]

_SCHEMA_TABLE_SQL = (
    'CREATE TABLE IF NOT EXISTS "__crdt_schema" ('
    '"table" BLOB, "column" BLOB, "type" BLOB, '
    'PRIMARY KEY ("table", "column"))'
)
_STATE_TABLES_SQL = (
    'CREATE TABLE IF NOT EXISTS "__crdt_counter" ('
    '"table" BLOB, "row" BLOB, "column" BLOB, '
    '"pos" INTEGER NOT NULL, "neg" INTEGER NOT NULL, '
    'PRIMARY KEY ("table", "row", "column"))',
    # One row per add op; "tag" is the add's op timestamp (globally
    # unique), "elem" the canonical JSON element key. alive=0 marks an
    # observed-removed add (kept for `observed_tags` idempotence; the
    # row is the tombstone's evidence).
    'CREATE TABLE IF NOT EXISTS "__crdt_set" ('
    '"tag" BLOB PRIMARY KEY, "table" BLOB, "row" BLOB, "column" BLOB, '
    '"elem" BLOB, "alive" INTEGER NOT NULL)',
    'CREATE INDEX IF NOT EXISTS "index__crdt_set_cell" ON "__crdt_set" '
    '("table", "row", "column", "alive")',
    # Kill tombstones: a remove may arrive BEFORE the add it observed
    # (anti-entropy has no causal delivery); the tag must stay dead.
    'CREATE TABLE IF NOT EXISTS "__crdt_kill" ("tag" BLOB PRIMARY KEY)',
)


# --- column specs & schema registry ---


def parse_column_spec(spec: str) -> Tuple[str, str]:
    """`"votes:counter"` → ("votes", "counter"); a bare name is LWW.
    Unknown type suffixes raise ValueError (a typo'd schema must fail
    loudly at declaration, not silently become an LWW column)."""
    if ":" not in spec:
        return spec, LWW
    name, _, ctype = spec.partition(":")
    if not name:
        raise ValueError(f"empty column name in spec {spec!r}")
    if ctype.startswith("tensor"):
        # Parameterized family: the FULL "tensor:monoid:dtype:shape"
        # string is the column type (validated here, stored verbatim in
        # __crdt_schema — the generic re-declaration conflict check
        # then covers monoid/dtype/shape changes for free).
        from evolu_tpu.core.crdt_tensor import parse_tensor_type

        parse_tensor_type(ctype)
        return name, ctype
    if ctype not in COLUMN_TYPES:
        raise ValueError(f"unknown CRDT column type {ctype!r} in {spec!r}")
    return name, ctype


class CrdtSchema:
    """Per-database column-type registry. Empty (the common case and
    every pre-existing database) means pure-LWW and costs one dict
    probe per apply."""

    __slots__ = ("types",)

    def __init__(self, types: Optional[Dict[Tuple[str, str], str]] = None):
        self.types: Dict[Tuple[str, str], str] = dict(types or {})

    def column_type(self, table: str, column: str) -> str:
        return self.types.get((table, column), LWW)

    def is_typed(self, table: str, column: str) -> bool:
        return (table, column) in self.types

    def has_typed(self, cells: Iterable[Cell]) -> bool:
        if not self.types:
            return False
        return any((t, c) in self.types for t, _r, c in cells)

    def __bool__(self) -> bool:
        return bool(self.types)


def ensure_schema_table(db) -> None:
    db.exec(_SCHEMA_TABLE_SQL)


def ensure_state_tables(db) -> None:
    from evolu_tpu.core.crdt_list import LIST_STATE_TABLES_SQL
    from evolu_tpu.core.crdt_tensor import TENSOR_STATE_TABLES_SQL

    for sql in _STATE_TABLES_SQL + LIST_STATE_TABLES_SQL + TENSOR_STATE_TABLES_SQL:
        db.exec(sql)


def declare_column_types(db, declarations: Iterable[Tuple[str, str, str]]) -> None:
    """Persist (table, column, type) declarations (add-only, idempotent;
    re-declaring a column with a DIFFERENT type raises — changing merge
    semantics under committed ops has no sane meaning)."""
    decls = [(t, c, ct) for t, c, ct in declarations if ct != LWW]
    if not decls:
        return
    ensure_schema_table(db)
    ensure_state_tables(db)
    existing = {
        (r["table"], r["column"]): r["type"]
        for r in db.exec_sql_query('SELECT "table", "column", "type" FROM "__crdt_schema"')
    }
    for t, c, ct in decls:
        have = existing.get((t, c))
        if have is not None and have != ct:
            raise ValueError(
                f"column {t}.{c} already declared {have!r}, cannot become {ct!r}"
            )
    new_decls = [d for d in decls if (d[0], d[1]) not in existing]
    db.run_many(
        'INSERT OR IGNORE INTO "__crdt_schema" ("table", "column", "type") '
        "VALUES (?, ?, ?)",
        decls,
    )
    invalidate_schema_cache(db)
    if new_decls:
        _fold_predeclaration_ops(db, new_decls)


def _fold_predeclaration_ops(db, decls: Sequence[Tuple[str, str, str]]) -> None:
    """Ops that reached __message BEFORE a column was declared typed
    (rolling upgrade: a peer authored typed ops while this replica
    still ran the undeclared schema) were applied as LWW and would
    otherwise NEVER be folded — `screen_new_ops` screens everything
    already in __message, so this replica's materialized value would
    silently diverge from a replica that declared before syncing, and
    anti-entropy (timestamp-only) could never heal it. Folding the
    column's full log at declaration time makes materialization a
    function of the op set alone, independent of declaration timing.
    State for a newly-declared column is necessarily empty (only
    declared columns ever fold), so this is exact, and it runs inside
    the caller's transaction (UpdateDbSchema is one command = one txn)."""
    schema = CrdtSchema({(t, c): ct for t, c, ct in decls})
    msgs: List[CrdtMessage] = []
    for t, c, _ct in decls:
        try:
            rows = db.exec_sql_query(
                'SELECT "timestamp", "table", "row", "column", "value" '
                'FROM "__message" WHERE "table" = ? AND "column" = ? '
                'ORDER BY "timestamp"',
                (t, c),
            )
        except Exception as e:  # noqa: BLE001
            if _is_missing_table(e):  # declared before init_db_model: no log yet
                return
            raise
        msgs.extend(
            CrdtMessage(r["timestamp"], r["table"], r["row"], r["column"], r["value"])
            for r in rows
        )
    if not msgs:
        return
    metrics.inc("evolu_crdt_predeclaration_folds_total", len(msgs))
    touched = _fold_by_type(db, partition_typed(schema, msgs))
    if touched:
        materialize_cells(db, schema, touched)


def invalidate_schema_cache(db) -> None:
    try:
        db._crdt_schema_cache = None
    except AttributeError:  # a backend with __slots__: reload per apply
        pass


def _is_missing_table(e: BaseException) -> bool:
    return "no such table" in str(e)


def load_schema(db) -> CrdtSchema:
    """The per-connection schema cache. Declarations happen on the same
    worker connection (single-writer discipline, like the winner
    cache), so a cached load stays valid until `declare_column_types`
    or an owner reset invalidates it.

    Error discipline: a MISSING `__crdt_schema` table means a
    pure-LWW database and caches the empty schema (relays and
    pre-typed apps pay one probe, ever). Any OTHER load error
    re-raises — swallowing e.g. a cross-process 'database is locked'
    into an empty cached schema would silently route typed cells
    through the LWW path forever, permanent divergence; failing the
    apply transaction instead is safe (rollback + redelivery)."""
    cached = getattr(db, "_crdt_schema_cache", None)
    if cached is not None:
        return cached
    try:
        rows = db.exec_sql_query(
            'SELECT "table", "column", "type" FROM "__crdt_schema"'
        )
        types = {(r["table"], r["column"]): r["type"] for r in rows}
    except Exception as e:  # noqa: BLE001
        if not _is_missing_table(e):
            raise
        types = {}
    schema = CrdtSchema(types)
    try:
        db._crdt_schema_cache = schema
    except AttributeError:
        pass
    return schema


# --- op codecs (ValueError-only, like the wire decoders) ---


def counter_delta(value) -> int:
    """Decode a counter op value → signed int delta. ValueError only."""
    if isinstance(value, bool) or not isinstance(value, int):
        raise ValueError(f"counter op value must be an int delta: {value!r}")
    if not _DELTA_MIN <= value <= _DELTA_MAX:
        raise ValueError(f"counter delta exceeds int32: {value!r}")
    return value


def elem_key(elem) -> str:
    """Canonical JSON encoding of a set element — the ONE encoding used
    for kill matching, state storage, and materialization sort order."""
    if isinstance(elem, bool) or not isinstance(elem, (str, int)):
        raise ValueError(f"set element must be str or int: {elem!r}")
    return json.dumps(elem, separators=(",", ":"))


def set_add_value(elem) -> str:
    """Encode an add op value. The op's OWN timestamp becomes its tag."""
    return f'["a",{elem_key(elem)}]'


def set_remove_value(elem, observed: Iterable[str]) -> str:
    """Encode a remove op value killing the `observed` add tags."""
    tags = sorted(set(observed))
    for t in tags:
        if not isinstance(t, str):
            raise ValueError(f"observed tag must be a timestamp string: {t!r}")
    return json.dumps(["r", json.loads(elem_key(elem)), tags],
                      separators=(",", ":"))


def decode_set_op(value) -> Tuple[str, str, Tuple[str, ...]]:
    """Decode a set op value → (kind, elem_key, kill_tags). ValueError
    only (the fold layer catches and counts malformed ops)."""
    if not isinstance(value, str):
        raise ValueError(f"set op value must be a JSON string: {value!r}")
    try:
        op = json.loads(value)
    except json.JSONDecodeError as e:
        raise ValueError(f"malformed set op JSON: {e}") from e
    if not isinstance(op, list) or not op or op[0] not in ("a", "r"):
        raise ValueError(f"malformed set op shape: {value!r}")
    if op[0] == "a":
        if len(op) != 2:
            raise ValueError(f"add op must be ['a', elem]: {value!r}")
        return "a", elem_key(op[1]), ()
    if len(op) != 3 or not isinstance(op[2], list):
        raise ValueError(f"remove op must be ['r', elem, [tags]]: {value!r}")
    tags = []
    for t in op[2]:
        if not isinstance(t, str):
            raise ValueError(f"remove op tag must be a string: {t!r}")
        tags.append(t)
    return "r", elem_key(op[1]), tuple(tags)


def materialize_set_value(alive_elem_keys: Iterable[str]) -> str:
    """Canonical sorted JSON array over DISTINCT alive element keys —
    deterministic across replicas for any op delivery order."""
    return "[" + ",".join(sorted(set(alive_elem_keys))) + "]"


# --- host-oracle folds (the semantics reference for the device twins) ---


def fold_counter_ops(deltas: Iterable[int]) -> Tuple[int, int]:
    """Σ over a batch → (pos, neg) non-negative partial sums."""
    pos = neg = 0
    for d in deltas:
        if d > 0:
            pos += d
        else:
            neg -= d
    return pos, neg


def decode_counter_batch(msgs: Sequence[CrdtMessage]) -> Tuple[List[Tuple[CrdtMessage, int]], int]:
    """→ ([(msg, delta)], malformed_count); malformed ops are dropped."""
    out, bad = [], 0
    for m in msgs:
        try:
            out.append((m, counter_delta(m.value)))
        except ValueError:
            bad += 1
    return out, bad


def decode_set_batch(
    msgs: Sequence[CrdtMessage],
) -> Tuple[List[Tuple[CrdtMessage, str]], List[Tuple[CrdtMessage, Tuple[str, ...]]], int]:
    """→ (adds [(msg, elem_key)] tagged by msg.timestamp,
    removes [(msg, kill_tags)], malformed_count). Malformed ops are
    dropped HERE so they can never touch a cell — whether a cell
    materializes must be a function of the delivered VALID op set only,
    never of how ops happened to be batched (a malformed op that
    co-arrives with a valid one must not create an app row that a
    replica receiving it alone would lack)."""
    adds: List[Tuple[CrdtMessage, str]] = []
    removes: List[Tuple[CrdtMessage, Tuple[str, ...]]] = []
    bad = 0
    for m in msgs:
        try:
            kind, ek, tags = decode_set_op(m.value)
        except ValueError:
            bad += 1
            continue
        if kind == "a":
            adds.append((m, ek))
        else:
            removes.append((m, tags))
    return adds, removes, bad


def alive_add_flags(
    add_tags: Sequence[str], kills: Set[str], state_killed: Set[str]
) -> List[bool]:
    """The AW-set fold's heart: an add survives iff its tag is in
    neither the batch kills nor the tombstoned state kills. Order-free
    and idempotent — the one line both backends must agree on."""
    return [t not in kills and t not in state_killed for t in add_tags]


# --- SQL state integration (runs INSIDE the caller's transaction) ---


def _chunked_in(db, sql_prefix: str, keys: Sequence, chunk: int = 500) -> List[dict]:
    rows: List[dict] = []
    for i in range(0, len(keys), chunk):
        part = keys[i : i + chunk]
        placeholders = ",".join("?" * len(part))
        rows.extend(db.exec_sql_query(sql_prefix.format(placeholders), tuple(part)))
    return rows


def screen_new_ops(db, msgs: Sequence[CrdtMessage]) -> List[CrdtMessage]:
    """Ops whose timestamps are NOT yet in __message, first occurrence
    per timestamp (matching INSERT OR NOTHING's keep-first) — the dedup
    gate that makes the state fold redelivery-safe."""
    seen: Set[str] = set()
    candidates: List[CrdtMessage] = []
    for m in msgs:
        if m.timestamp not in seen:
            seen.add(m.timestamp)
            candidates.append(m)
    if not candidates:
        return []
    existing = {
        r["timestamp"]
        for r in _chunked_in(
            db,
            'SELECT "timestamp" FROM "__message" WHERE "timestamp" IN ({})',
            [m.timestamp for m in candidates],
        )
    }
    return [m for m in candidates if m.timestamp not in existing]


def partition_typed(
    schema: CrdtSchema, msgs: Sequence[CrdtMessage]
) -> Dict[str, List[CrdtMessage]]:
    """{"counter": [...], "awset": [...]} for the typed messages of a
    batch (order preserved). Callers fast-path on empty schema."""
    out: Dict[str, List[CrdtMessage]] = {}
    for m in msgs:
        ct = schema.column_type(m.table, m.column)
        if ct != LWW:
            out.setdefault(ct, []).append(m)
    return out


def _fold_counters_device(pairs: Sequence[Tuple[CrdtMessage, int]]):
    """Per-cell (pos, neg) via the device segmented-sum kernel —
    bit-identical to the host fold (test-pinned)."""
    import numpy as np

    from evolu_tpu.ops.crdt_merge import pn_counter_sums
    from evolu_tpu.ops.host_parse import intern_cells

    msgs = [m for m, _ in pairs]
    cell_id, cells = intern_cells(
        [m.table for m in msgs], [m.row for m in msgs], [m.column for m in msgs]
    )
    deltas = np.fromiter((d for _, d in pairs), np.int64, len(pairs))
    pos, neg = pn_counter_sums(cell_id, deltas, len(cells))
    return {cells[i]: (int(pos[i]), int(neg[i])) for i in range(len(cells))}


def _fold_counters_host(pairs: Sequence[Tuple[CrdtMessage, int]]):
    per_cell: Dict[Cell, List[int]] = {}
    for m, d in pairs:
        per_cell.setdefault((m.table, m.row, m.column), []).append(d)
    return {cell: fold_counter_ops(ds) for cell, ds in per_cell.items()}


def apply_counter_ops(db, new_msgs: Sequence[CrdtMessage]) -> Set[Cell]:
    """Fold NEW counter ops into __crdt_counter. Returns touched cells."""
    pairs, bad = decode_counter_batch(new_msgs)
    if bad:
        metrics.inc("evolu_crdt_malformed_ops_total", bad, type=COUNTER)
    if not pairs:
        return set()
    metrics.inc("evolu_crdt_ops_total", len(pairs), type=COUNTER)
    if len(pairs) >= DEVICE_FOLD_MIN:
        metrics.inc("evolu_crdt_plan_total", type=COUNTER, path="device")
        sums = _fold_counters_device(pairs)
    else:
        metrics.inc("evolu_crdt_plan_total", type=COUNTER, path="host")
        sums = _fold_counters_host(pairs)
    db.run_many(
        'INSERT INTO "__crdt_counter" ("table", "row", "column", "pos", "neg") '
        "VALUES (?, ?, ?, ?, ?) "
        'ON CONFLICT ("table", "row", "column") DO UPDATE SET '
        '"pos" = "pos" + excluded."pos", "neg" = "neg" + excluded."neg"',
        [(t, r, c, p, n) for (t, r, c), (p, n) in sums.items()],
    )
    return set(sums)


def apply_set_ops(db, new_msgs: Sequence[CrdtMessage]) -> Set[Cell]:
    """Fold NEW set ops into __crdt_set/__crdt_kill. Returns touched
    cells (adds AND removes: a remove changes materialization too)."""
    adds, removes, bad = decode_set_batch(new_msgs)
    if bad:
        metrics.inc("evolu_crdt_malformed_ops_total", bad, type=AWSET)
    if not adds and not removes:
        return set()
    metrics.inc("evolu_crdt_ops_total", len(adds) + len(removes), type=AWSET)
    kills: Set[str] = set()
    for _m, tags in removes:
        kills.update(tags)

    # Tombstoned kills relevant to this batch's adds (a kill that
    # arrived in an EARLIER batch must still dead-on-arrival this add).
    add_tags = [m.timestamp for m, _ in adds]
    state_killed: Set[str] = set()
    if add_tags:
        state_killed = {
            r["tag"]
            for r in _chunked_in(
                db, 'SELECT "tag" FROM "__crdt_kill" WHERE "tag" IN ({})', add_tags
            )
        }
    if len(adds) + len(kills) >= DEVICE_FOLD_MIN:
        metrics.inc("evolu_crdt_plan_total", type=AWSET, path="device")
        from evolu_tpu.ops.crdt_merge import awset_alive_flags

        alive = awset_alive_flags(add_tags, kills, state_killed)
    else:
        metrics.inc("evolu_crdt_plan_total", type=AWSET, path="host")
        alive = alive_add_flags(add_tags, kills, state_killed)

    touched: Set[Cell] = set()
    if kills:
        # Tombstone first, then kill matching EXISTING alive adds.
        db.run_many(
            'INSERT OR IGNORE INTO "__crdt_kill" ("tag") VALUES (?)',
            [(t,) for t in sorted(kills)],
        )
        killed_rows = _chunked_in(
            db,
            'SELECT "tag", "table", "row", "column" FROM "__crdt_set" '
            'WHERE "alive" = 1 AND "tag" IN ({})',
            sorted(kills),
        )
        if killed_rows:
            db.run_many(
                'UPDATE "__crdt_set" SET "alive" = 0 WHERE "tag" = ?',
                [(r["tag"],) for r in killed_rows],
            )
            touched.update((r["table"], r["row"], r["column"]) for r in killed_rows)
    if adds:
        db.run_many(
            'INSERT OR IGNORE INTO "__crdt_set" '
            '("tag", "table", "row", "column", "elem", "alive") '
            "VALUES (?, ?, ?, ?, ?, ?)",
            [
                (m.timestamp, m.table, m.row, m.column, ek, int(a))
                for (m, ek), a in zip(adds, alive)
            ],
        )
        touched.update((m.table, m.row, m.column) for m, _ in adds)
    # Every VALID op touches its cell — a remove targeting a cell with
    # no stored adds still materializes it (possibly as "[]"), and does
    # so identically on every replica regardless of batching.
    touched.update((m.table, m.row, m.column) for m, _ in removes)
    return touched


def materialize_cells(db, schema: CrdtSchema, cells: Iterable[Cell]) -> None:
    """Upsert the merged value of each touched typed cell into its app
    table row — the typed replacement for the LWW winner upsert. Runs
    inside the apply transaction; identical fold state ⇒ identical app
    bytes on every replica.

    Batched per (table, column): one chunked IN-list read + one
    run_many upsert per group — per-cell statements would undo the
    vectorization the device fold just paid for on DEVICE_FOLD_MIN+
    batches spread over thousands of cells."""
    from evolu_tpu.storage.apply import _upsert_sql  # one-copy SQL builder

    groups: Dict[Tuple[str, str], Set[str]] = {}
    for table, row, column in cells:
        groups.setdefault((table, column), set()).add(row)
    for (table, column), row_set in sorted(groups.items()):
        ct = schema.column_type(table, column)
        rows = sorted(row_set)
        values: Dict[str, object] = {}
        if ct == COUNTER:
            default: object = 0
            for i in range(0, len(rows), 500):
                part = rows[i : i + 500]
                q = (
                    'SELECT "row", "pos", "neg" FROM "__crdt_counter" '
                    'WHERE "table" = ? AND "column" = ? AND "row" IN ({})'
                ).format(",".join("?" * len(part)))
                for r in db.exec_sql_query(q, (table, column, *part)):
                    values[r["row"]] = r["pos"] - r["neg"]
        elif ct == AWSET:
            default = materialize_set_value(())
            elems: Dict[str, Set[str]] = {}
            for i in range(0, len(rows), 500):
                part = rows[i : i + 500]
                q = (
                    'SELECT "row", "elem" FROM "__crdt_set" '
                    'WHERE "table" = ? AND "column" = ? AND "alive" = 1 '
                    'AND "row" IN ({})'
                ).format(",".join("?" * len(part)))
                for r in db.exec_sql_query(q, (table, column, *part)):
                    elems.setdefault(r["row"], set()).add(r["elem"])
            values = {row: materialize_set_value(e) for row, e in elems.items()}
        elif ct == LIST:
            from evolu_tpu.core.crdt_list import materialize_list_values

            default = "[]"
            values = materialize_list_values(db, table, column, rows)
        else:
            from evolu_tpu.core.crdt_tensor import (
                is_tensor_type, materialize_tensor_values, parse_tensor_type,
                zeros_value,
            )

            if not is_tensor_type(ct):  # pragma: no cover - never routed here
                continue
            default = zeros_value(parse_tensor_type(ct))
            values = materialize_tensor_values(db, ct, table, column, rows)
        db.run_many(
            _upsert_sql(table, column),
            [(row, values.get(row, default), values.get(row, default))
             for row in rows],
        )
        metrics.inc("evolu_crdt_materialized_cells_total", len(rows), type=ct)


def _fold_by_type(db, by_type: Dict[str, List[CrdtMessage]]) -> Set[Cell]:
    """ONE copy of the per-type fold dispatch (incremental apply,
    pre-declaration fold, and full rebuild all route through it)."""
    touched: Set[Cell] = set()
    touched |= apply_counter_ops(db, by_type.get(COUNTER, ()))
    touched |= apply_set_ops(db, by_type.get(AWSET, ()))
    list_msgs = by_type.get(LIST)
    if list_msgs:
        from evolu_tpu.core.crdt_list import apply_list_ops

        touched |= apply_list_ops(db, list_msgs)
    for ct, tensor_msgs in by_type.items():
        # Parameterized tensor family: one bucket PER full type string
        # (the dict key carries the column config to the fold).
        if tensor_msgs and ct.startswith("tensor:"):
            from evolu_tpu.core.crdt_tensor import apply_tensor_ops

            touched |= apply_tensor_ops(db, ct, tensor_msgs)
    return touched


def apply_typed_ops(db, schema: CrdtSchema, typed_msgs: Sequence[CrdtMessage]) -> None:
    """The whole typed apply leg: dedup against __message, fold per
    type, materialize touched cells. MUST run inside the apply
    transaction BEFORE the batch's __message insert (the dedup screen
    reads pre-batch state)."""
    new_ops = screen_new_ops(db, typed_msgs)
    touched = _fold_by_type(db, partition_typed(schema, new_ops))
    # Redelivered-only batches still touch no state; nothing to write.
    if touched:
        materialize_cells(db, schema, touched)


def observed_tags(db, table: str, row: str, column: str, elem) -> List[str]:
    """Alive add tags for (cell, elem) — what a remove op must observe.
    Read on the author's own replica (same connection discipline as
    mutations)."""
    ek = elem_key(elem)
    rows = db.exec_sql_query(
        'SELECT "tag" FROM "__crdt_set" WHERE "table" = ? AND "row" = ? '
        'AND "column" = ? AND "elem" = ? AND "alive" = 1 ORDER BY "tag"',
        (table, row, column, ek),
    )
    return [r["tag"] for r in rows]


def rebuild_state(db, schema: CrdtSchema) -> None:
    """Maintenance: recompute __crdt_* state and every typed app value
    from the full __message log (the fold is order-free, so one pass in
    timestamp order is exact). Used by integrity checks and tests; the
    incremental path never needs it."""
    if not schema:
        return
    ensure_state_tables(db)
    for t in ("__crdt_counter", "__crdt_set", "__crdt_kill",
              "__crdt_list", "__crdt_list_kill", "__crdt_tensor"):
        db.run(f'DELETE FROM "{t}"')
    rows = db.exec_sql_query(
        'SELECT "timestamp", "table", "row", "column", "value" FROM "__message" '
        'ORDER BY "timestamp"'
    )
    msgs = [
        CrdtMessage(r["timestamp"], r["table"], r["row"], r["column"], r["value"])
        for r in rows
        if schema.is_typed(r["table"], r["column"])
    ]
    touched = _fold_by_type(db, partition_typed(schema, msgs))
    if touched:
        materialize_cells(db, schema, touched)
