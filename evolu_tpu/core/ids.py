"""Identity helpers: row ids, node ids, owner id derivation.

Reference: packages/evolu/src/model.ts:44 (nanoid row ids),
types.ts:42-49 (16-hex node ids), initDbModel.ts:21-22 (owner id =
first 21 hex chars of SHA-256(mnemonic) — 1/3 of the hash; the
mnemonic cannot be recovered from it).
"""

from __future__ import annotations

import hashlib
import secrets

# The standard nanoid URL alphabet (64 chars).
_NANOID_ALPHABET = "ABCDEFGHIJKLMNOPQRSTUVWXYZabcdefghijklmnopqrstuvwxyz0123456789_-"
_HEX_ALPHABET = "0123456789abcdef"


def create_id() -> str:
    """A 21-char nanoid row id (model.ts:44)."""
    return "".join(secrets.choice(_NANOID_ALPHABET) for _ in range(21))


def create_node_id() -> str:
    """A 16-lowercase-hex-char HLC node id (types.ts:48-49)."""
    return "".join(secrets.choice(_HEX_ALPHABET) for _ in range(16))


_ID_CHARS = set(_NANOID_ALPHABET)


def is_valid_id(s: str) -> bool:
    """model.ts:35 — /^[\\w-]{21}$/ (ASCII word chars only, like the zod regex)."""
    return len(s) == 21 and all(c in _ID_CHARS for c in s)


def is_valid_node_id(s: str) -> bool:
    """types.ts:42 — /^[0-9a-f]{16}$/i."""
    return len(s) == 16 and all(c in "0123456789abcdefABCDEF" for c in s)


def mnemonic_to_owner_id(mnemonic: str) -> str:
    """initDbModel.ts:21-22."""
    return hashlib.sha256(mnemonic.encode("utf-8")).hexdigest()[:21]
