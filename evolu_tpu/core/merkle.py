"""Merkle trie anti-entropy digest, bit-exact with the reference.

Reference: packages/evolu/src/merkleTree.ts. A ternary trie keyed by
base-3-encoded minutes-since-epoch (truncated to int32 via JS `| 0`,
merkleTree.ts:39). Each node's hash is the XOR of murmur3 hashes of all
timestamps under that prefix; **hash values are JS signed int32** —
`undefined ^ h` and `a ^ b` in JS coerce to int32 — which this module
reproduces so serialized trees interoperate byte-for-byte with
reference replicas.

Tree representation: a dict with optional keys "hash" (signed int32)
and "0"/"1"/"2" (child dicts). Matches the reference JSON wire shape
(types.ts:80-84) directly.
"""

from __future__ import annotations

import json
from typing import Optional

from evolu_tpu.core.murmur import to_int32
from evolu_tpu.core.timestamp import timestamp_to_hash
from evolu_tpu.core.types import Timestamp

MERKLE_KEY_LENGTH = 16  # base-3 digits of int32 minutes (merkleTree.ts:55-61)


def create_initial_merkle_tree() -> dict:
    return {}


def minutes_base3(millis: int) -> str:
    """merkleTree.ts:39 — `((millis/1000/60) | 0).toString(3)` (no padding)."""
    minutes = int(millis / 1000 / 60) & 0xFFFFFFFF
    if minutes >= 0x80000000:  # JS |0 is signed; millis >= 0 keeps this positive until ~year 6053
        minutes -= 0x100000000
    sign = "-" if minutes < 0 else ""  # JS Number.toString(3) keeps the sign prefix
    m = abs(minutes)
    if m == 0:
        return "0"
    digits = []
    while m:
        digits.append(str(m % 3))
        m //= 3
    return sign + "".join(reversed(digits))


def key_to_timestamp_millis(key: str) -> int:
    """merkleTree.ts:55-61 — right-pad the prefix to 16 digits, parse base 3, to millis."""
    fullkey = key + "0" * (MERKLE_KEY_LENGTH - len(key))
    return int(fullkey, 3) * 1000 * 60


def _xor(a: Optional[int], b: int) -> int:
    """JS `a ^ b` with `undefined ^ b === b | 0` (merkleTree.ts:26,45)."""
    return to_int32((a or 0) ^ b)


def insert_into_merkle_tree(t: Timestamp, tree: dict) -> dict:
    """merkleTree.ts:31-50. Returns a new tree; input is not mutated."""
    key = minutes_base3(t.millis)
    h = timestamp_to_hash(t)
    new_tree = dict(tree)
    new_tree["hash"] = _xor(tree.get("hash"), h)
    node = new_tree
    for c in key:
        child = dict(node.get(c) or {})
        child["hash"] = _xor(child.get("hash"), h)
        node[c] = child
        node = child
    return new_tree


def minute_deltas_host(timestamp_strings) -> tuple:
    """Oracle-exact host fold over timestamp STRINGS already flagged for
    insertion: → ({minute-key: int32 XOR delta}, uint32 digest). Parses
    each string and hashes its canonical re-render with the node case
    preserved VERBATIM (timestampToHash semantics) — the single shared
    implementation behind every host fallback, so client, reconcile and
    relay digests can never drift apart."""
    from evolu_tpu.core.timestamp import timestamp_from_string

    deltas: dict = {}
    digest = 0
    for s in timestamp_strings:
        t = timestamp_from_string(s)
        h = timestamp_to_hash(t)
        k = minutes_base3(t.millis)
        deltas[k] = to_int32(deltas.get(k, 0) ^ h)
        digest ^= h & 0xFFFFFFFF
    return deltas, digest


def insert_many_into_merkle_tree(timestamps, tree: dict) -> dict:
    """Batch insert (order-independent since XOR commutes). In-place on a copy."""
    for t in timestamps:
        tree = insert_into_merkle_tree(t, tree)
    return tree


def apply_prefix_xors(tree: dict, prefix_xors: dict) -> dict:
    """Apply precomputed {base3-minute-key: xor-of-hashes} deltas to a tree.

    This is the host-side half of the TPU batch insert: the device
    reduces a message batch to one XOR delta per distinct minute
    (evolu_tpu.ops.merkle_ops); applying those deltas here touches only
    O(distinct minutes * 16) nodes. Equivalent to folding
    insert_into_merkle_tree over the batch.
    """
    new_tree = dict(tree)
    for key, h in prefix_xors.items():
        # A zero delta (even number of identical hashes in the batch) must
        # still materialize the path nodes, exactly as individual inserts
        # would — so no skip here.
        new_tree["hash"] = _xor(new_tree.get("hash"), h)
        node = new_tree
        for c in key:
            child = dict(node.get(c) or {})
            child["hash"] = _xor(child.get("hash"), h)
            node[c] = child
            node = child
    return new_tree


def _child_keys(tree: dict):
    # getKeys (merkleTree.ts:52-53) filters only "hash" — any other key
    # (e.g. a "-" from a negative-minutes key) participates in the walk.
    return [k for k in tree if k != "hash"]


def diff_merkle_trees(tree1: dict, tree2: dict) -> Optional[int]:
    """merkleTree.ts:63-91 — earliest minute (as millis) where trees diverge, else None.

    Walk both trees from the root; at each level take the sorted union
    of child keys and descend into the first child whose hashes differ.
    `None` (JS undefined) hash is distinct from hash 0.
    """
    if tree1.get("hash") == tree2.get("hash"):
        return None
    node1, node2 = tree1, tree2
    k = ""
    while True:
        keys = sorted(set(_child_keys(node1)) | set(_child_keys(node2)))
        diffkey = None
        for key in keys:
            next1 = node1.get(key) or {}
            next2 = node2.get(key) or {}
            if next1.get("hash") != next2.get("hash"):
                diffkey = key
                break
        if diffkey is None:
            return key_to_timestamp_millis(k)
        k += diffkey
        node1 = node1.get(diffkey) or {}
        node2 = node2.get(diffkey) or {}


def _ordered(tree: dict) -> dict:
    """Recursively order keys the way JS object property order does:

    integer-like keys ("0","1","2") ascending first, then "hash" —
    matching JSON.stringify output of the reference so serialized trees
    are byte-identical.
    """
    out = {}
    for k in ("0", "1", "2"):
        if k in tree:
            out[k] = _ordered(tree[k])
    if "hash" in tree:
        out["hash"] = tree["hash"]
    return out


def merkle_tree_to_string(tree: dict) -> str:
    """types.ts:80-81 — JSON with JS property order and no whitespace."""
    return json.dumps(_ordered(tree), separators=(",", ":"))


def merkle_tree_from_string(s: str) -> dict:
    """types.ts:83-84."""
    return json.loads(s)
