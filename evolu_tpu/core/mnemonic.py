"""BIP-39 mnemonic generation/validation (12 words, 128-bit entropy).

Reference: packages/evolu/src/generateMnemonic.ts (extracted from
bitcoinjs/bip39) and validateMnemonic.ts. The mnemonic is the owner's
identity and the E2EE password; owner id = sha256(mnemonic)[:21 hex]
(initDbModel.ts:21-22).
"""

from __future__ import annotations

import hashlib
import secrets

from evolu_tpu.core._bip39_words import WORDS

_WORD_INDEX = {w: i for i, w in enumerate(WORDS)}


def _entropy_to_mnemonic(entropy: bytes) -> str:
    """generateMnemonic.ts:43-72 — entropy bits + sha256-checksum bits, 11-bit word indices."""
    if not (16 <= len(entropy) <= 32) or len(entropy) % 4:
        raise ValueError("INVALID_ENTROPY")
    ent_bits = len(entropy) * 8
    cs_bits = ent_bits // 32
    checksum = hashlib.sha256(entropy).digest()
    bits = int.from_bytes(entropy, "big") << cs_bits
    bits |= checksum[0] >> (8 - cs_bits) if cs_bits <= 8 else int.from_bytes(checksum, "big") >> (256 - cs_bits)
    n_words = (ent_bits + cs_bits) // 11
    words = []
    for i in range(n_words):
        shift = (n_words - 1 - i) * 11
        words.append(WORDS[(bits >> shift) & 0x7FF])
    return " ".join(words)


def generate_mnemonic(strength: int = 128) -> str:
    """generateMnemonic.ts:76-79 — default 12 words."""
    return _entropy_to_mnemonic(secrets.token_bytes(strength // 8))


def validate_mnemonic(mnemonic: str) -> bool:
    """Word-list membership + checksum check (BIP-39)."""
    words = mnemonic.split(" ")
    if len(words) not in (12, 15, 18, 21, 24):
        return False
    try:
        indices = [_WORD_INDEX[w] for w in words]
    except KeyError:
        return False
    bits = 0
    for idx in indices:
        bits = (bits << 11) | idx
    total_bits = len(words) * 11
    cs_bits = total_bits // 33
    ent_bits = total_bits - cs_bits
    entropy = (bits >> cs_bits).to_bytes(ent_bits // 8, "big")
    checksum = bits & ((1 << cs_bits) - 1)
    expected = int.from_bytes(hashlib.sha256(entropy).digest(), "big") >> (256 - cs_bits)
    return checksum == expected
