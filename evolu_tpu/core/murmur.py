"""MurmurHash3 x86 32-bit, bit-exact with the reference's hash.

The reference hashes timestamp strings with the npm `murmurhash@2.0.1`
package's default export (MurmurHash v3, 32-bit, seed 0, operating on
`charCodeAt(i) & 0xff` — i.e. the low byte of each UTF-16 code unit,
which for the ASCII timestamp strings is just the ASCII bytes), see
reference packages/evolu/src/timestamp.ts:87-88. The return value is
`h >>> 0`, an unsigned uint32.

Golden value (reference test snapshot timestamp.test.ts.snap):
murmur3_32(b"1970-01-01T00:00:00.000Z-0000-0000000000000000") == 4179357717
"""

C1 = 0xCC9E2D51
C2 = 0x1B873593
MASK = 0xFFFFFFFF


def murmur3_32(data: bytes, seed: int = 0) -> int:
    """MurmurHash3 32-bit of `data`. Returns unsigned uint32."""
    h = seed & MASK
    n = len(data) & ~3
    for i in range(0, n, 4):
        k = data[i] | (data[i + 1] << 8) | (data[i + 2] << 16) | (data[i + 3] << 24)
        k = (k * C1) & MASK
        k = ((k << 15) | (k >> 17)) & MASK
        k = (k * C2) & MASK
        h ^= k
        h = ((h << 13) | (h >> 19)) & MASK
        h = (h * 5 + 0xE6546B64) & MASK
    tail = data[n:]
    k = 0
    if len(tail) >= 3:
        k ^= tail[2] << 16
    if len(tail) >= 2:
        k ^= tail[1] << 8
    if len(tail) >= 1:
        k ^= tail[0]
        k = (k * C1) & MASK
        k = ((k << 15) | (k >> 17)) & MASK
        k = (k * C2) & MASK
        h ^= k
    h ^= len(data)
    h ^= h >> 16
    h = (h * 0x85EBCA6B) & MASK
    h ^= h >> 13
    h = (h * 0xC2B2AE35) & MASK
    h ^= h >> 16
    return h


def to_int32(x: int) -> int:
    """Coerce a uint32/arbitrary int to JS `| 0` signed int32 semantics."""
    x &= MASK
    return x - 0x100000000 if x >= 0x80000000 else x
