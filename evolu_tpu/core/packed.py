"""PackedReceive — a sync-response batch as columns, not objects.

The receive leg's measured floor (r4, docs/BENCHMARKS.md) was ~4 µs of
per-message Python: `CrdtMessage` construction plus string decodes in
`native_crypto.decrypt_response`, then re-parsing and re-packing the
same strings in `worker._receive` → planner → `db.apply_planned`. This
type carries the batch exactly as the C decrypt emitted it — a
fixed-width timestamp slab, interned cells (only the k unique
(table,row,column) triples become Python strings), and bind-ready
value columns — so the whole client receive path
(reference sync.worker.ts:135-173 → receive.ts:144 →
applyMessages.ts:78) runs with zero per-row Python objects.

Fallback contract: every consumer that cannot take the columnar path
(pure-Python SQLite backend, non-canonical hex case, host-oracle plans,
sequential HLC error reproduction) calls `to_messages()` and continues
on the object path — the materialization is exact, so behavior and
error surfaces are identical to a response decoded the object way.
"""

from __future__ import annotations

from typing import List, Tuple

import numpy as np

from evolu_tpu.core.types import CrdtMessage

TS_WIDTH = 46


class PackedReceive:
    """Columnar CrdtMessage batch (the C decrypt's blob, parsed).

    Arrays are per-row: `cell_id` indexes `cells` (unique
    (table,row,column) tuples in first-appearance order, matching
    `host_parse.intern_cells`); `vkinds` uses the SQLite bind encoding
    (0 null, 1 int, 2 double, 3 text) with text payloads in `vblob`
    spanned by `voffs[i]:voffs[i]+vlens[i]`. `ts_slab` is n×46 ASCII
    bytes. Supports len()/slicing (chunked receive) and exact
    materialization via `to_messages()`.
    """

    __slots__ = (
        "n", "ts_slab", "cells", "cell_id", "vkinds", "ivals", "dvals",
        "vlens", "voffs", "vblob", "cell_blob", "cell_lens", "_parsed",
    )

    def __init__(self, n, ts_slab, cells, cell_id, vkinds, ivals, dvals,
                 vlens, voffs, vblob, cell_blob, cell_lens):
        self.n = n
        self.ts_slab = ts_slab
        self.cells = cells
        self.cell_id = cell_id
        self.vkinds = vkinds
        self.ivals = ivals
        self.dvals = dvals
        self.vlens = vlens
        self.voffs = voffs
        self.vblob = vblob
        # The raw interned-cell buffers ride along so the packed SQLite
        # apply can bind identifiers without re-encoding the `cells`
        # strings (same UTF-8 bytes by construction).
        self.cell_blob = cell_blob
        self.cell_lens = cell_lens
        self._parsed = None

    # -- construction --

    @classmethod
    def from_blob(cls, blob: bytes) -> Tuple["PackedReceive", str]:
        """Parse the `ehc_decrypt_response_columns` output blob →
        (batch, merkle_tree string). Layout documented at the C entry
        point (native/evolu_crypto.cpp)."""
        n, k, tree_len, vblob_len, cell_blob_len = np.frombuffer(
            blob, np.int64, 5
        )
        n, k = int(n), int(k)
        o = 40
        ivals = np.frombuffer(blob, np.int64, n, o); o += 8 * n
        dvals = np.frombuffer(blob, np.float64, n, o); o += 8 * n
        cell_id = np.frombuffer(blob, np.int32, n, o); o += 4 * n
        vlens = np.frombuffer(blob, np.int32, n, o); o += 4 * n
        cell_lens = np.frombuffer(blob, np.int32, 3 * k, o); o += 12 * k
        vkinds = np.frombuffer(blob, np.uint8, n, o); o += n
        ts_slab = blob[o : o + TS_WIDTH * n]; o += TS_WIDTH * n
        vblob = blob[o : o + int(vblob_len)]; o += int(vblob_len)
        cell_blob = blob[o : o + int(cell_blob_len)]; o += int(cell_blob_len)
        tree = blob[o : o + int(tree_len)].decode("utf-8")

        cells: List[Tuple[str, str, str]] = []
        if k:
            # The unique-cell count k approaches n on cold syncs, so
            # this materialization is per-ROW cost at its worst: one
            # whole-blob decode + offset slicing instead of 3k
            # bytes-slice+decode round-trips (measured ~4× cheaper on
            # an all-unique 100k batch). When the blob is pure ASCII —
            # identifiers almost always are — byte offsets ARE char
            # offsets and the slices never re-decode.
            bounds = np.empty(3 * k + 1, np.int64)
            bounds[0] = 0
            np.cumsum(cell_lens, out=bounds[1:])
            bl = bounds.tolist()
            text = cell_blob.decode("utf-8")
            if len(text) == len(cell_blob):
                parts = [text[a:b] for a, b in zip(bl, bl[1:])]
            else:
                parts = [cell_blob[a:b].decode("utf-8")
                         for a, b in zip(bl, bl[1:])]
            it = iter(parts)
            cells = list(zip(it, it, it))

        voffs = np.zeros(n, np.int64)
        if n:
            np.cumsum(vlens[:-1], out=voffs[1:])
        return cls(n, ts_slab, cells, cell_id, vkinds, ivals, dvals,
                   vlens, voffs, vblob, cell_blob, cell_lens), tree

    # -- sequence protocol (chunked receive slices in row ranges) --

    def __len__(self) -> int:
        return self.n

    def __getitem__(self, item):
        if not isinstance(item, slice):
            raise TypeError("PackedReceive supports slice access only")
        a, b, step = item.indices(self.n)
        if step != 1:
            raise ValueError("PackedReceive slices must be contiguous")
        if a == 0 and b == self.n:
            return self
        child = PackedReceive(
            b - a, self.ts_slab[a * TS_WIDTH : b * TS_WIDTH], self.cells,
            self.cell_id[a:b], self.vkinds[a:b], self.ivals[a:b],
            self.dvals[a:b], self.vlens[a:b], self.voffs[a:b], self.vblob,
            self.cell_blob, self.cell_lens,
        )
        if self._parsed is not None:
            # All four parse outputs are per-row arrays: slicing them is
            # exact, and saves chunked receive a native re-parse per
            # chunk (the worker already parsed the full slab for HLC).
            child._parsed = tuple(arr[a:b] for arr in self._parsed)
        return child

    # -- columns --

    def parse_timestamps(self):
        """→ (millis i64, counter i32, node u64, case_ok bool) for the
        whole batch — one native call over the slab (numpy fallback via
        the string path). Raises TimestampParseError exactly like the
        scalar parser. Cached (the HLC fold and the planner both need
        it)."""
        if self._parsed is None:
            from evolu_tpu.ops.host_parse import (
                parse_packed_timestamps,
                parse_timestamp_strings,
            )

            out = parse_packed_timestamps(
                self.ts_slab, self.n, with_case=True, strict=False
            )
            if out is None:  # no host library: go through strings
                out = parse_timestamp_strings(
                    self.timestamp_strings(), with_case=True
                )
            self._parsed = out
        return self._parsed

    def timestamp_strings(self) -> List[str]:
        s = self.ts_slab.decode("ascii")
        return [s[i * TS_WIDTH : (i + 1) * TS_WIDTH] for i in range(self.n)]

    def value(self, i: int):
        kind = int(self.vkinds[i])
        if kind == 1:
            return int(self.ivals[i])
        if kind == 2:
            return float(self.dvals[i])
        if kind == 3:
            off = int(self.voffs[i])
            return self.vblob[off : off + int(self.vlens[i])].decode("utf-8")
        return None

    def touched_cells(self):
        """→ (touched_ids, cells): the unique cell ids this batch
        actually references (a slice may touch only part of `cells`)
        and their (table,row,column) tuples, aligned."""
        touched_ids = np.unique(self.cell_id)
        return touched_ids, [self.cells[int(i)] for i in touched_ids]

    # -- exact materialization (fallback paths) --

    def to_messages(self) -> Tuple[CrdtMessage, ...]:
        ts = self.timestamp_strings()
        cells = self.cells
        cid = self.cell_id
        return tuple(
            CrdtMessage(ts[i], *cells[int(cid[i])], self.value(i))
            for i in range(self.n)
        )
