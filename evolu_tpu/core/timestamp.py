"""Hybrid logical clocks with the reference's exact semantics.

Reference: packages/evolu/src/timestamp.ts. The critical invariant
(timestamp.ts:43-48): the string encoding
`ISO8601(millis) + "-" + HEX4(counter) + "-" + node` is fixed-width, so
lexicographic order of timestamp strings equals the (millis, counter,
node) tuple order. All LWW comparisons — Python, SQL `ORDER BY`, and
the TPU kernels' packed u64 keys — rely on this.
"""

from __future__ import annotations

import datetime
from typing import Optional

from evolu_tpu.core.murmur import murmur3_32
from evolu_tpu.core.types import (
    MAX_COUNTER,
    Timestamp,
    TimestampCounterOverflowError,
    TimestampDriftError,
    TimestampDuplicateNodeError,
    TimestampParseError,
)
from evolu_tpu.core.ids import create_node_id

SYNC_NODE_ID = "0000000000000000"
TIMESTAMP_STRING_LENGTH = 46  # 24 (ISO) + 1 + 4 (hex counter) + 1 + 16 (node)


def create_initial_timestamp(node: Optional[str] = None) -> Timestamp:
    """timestamp.ts:27-31 — millis 0, counter 0, fresh random node id."""
    return Timestamp(0, 0, node if node is not None else create_node_id())


def create_sync_timestamp(millis: int = 0) -> Timestamp:
    """timestamp.ts:35-41 — node id all zeros; used for 'everything after minute X' range queries."""
    return Timestamp(millis, 0, SYNC_NODE_ID)


def millis_to_iso(millis: int) -> str:
    """JS `new Date(millis).toISOString()` for 0 <= millis (years 1970-9999).

    Always `YYYY-MM-DDTHH:mm:ss.sssZ` (24 chars, 3-digit millis) —
    the fixed width is what makes string order == numeric order.
    """
    dt = datetime.datetime.fromtimestamp(millis // 1000, tz=datetime.timezone.utc)
    return f"{dt.year:04d}-{dt.month:02d}-{dt.day:02d}T{dt.hour:02d}:{dt.minute:02d}:{dt.second:02d}.{millis % 1000:03d}Z"


def iso_to_millis(iso: str) -> int:
    """Inverse of millis_to_iso (JS Date.parse on the ISO string)."""
    if (
        len(iso) != 24
        or iso[4] != "-" or iso[7] != "-" or iso[10] != "T"
        or iso[13] != ":" or iso[16] != ":" or iso[19] != "."
        or iso[23] != "Z"
    ):
        raise TimestampParseError(f"bad ISO timestamp: {iso!r}")
    digits = iso[0:4] + iso[5:7] + iso[8:10] + iso[11:13] + iso[14:16] + iso[17:19] + iso[20:23]
    if not digits.isascii() or not digits.isdigit():
        raise TimestampParseError(f"bad ISO timestamp: {iso!r}")
    try:
        dt = datetime.datetime(
            int(iso[0:4]), int(iso[5:7]), int(iso[8:10]),
            int(iso[11:13]), int(iso[14:16]), int(iso[17:19]),
            tzinfo=datetime.timezone.utc,
        )
    except ValueError as e:
        raise TimestampParseError(f"bad ISO timestamp: {iso!r}") from e
    return int(dt.timestamp()) * 1000 + int(iso[20:23])


def timestamp_to_string(t: Timestamp) -> str:
    """timestamp.ts:43-48 — counter is 4 UPPERCASE hex digits; node is 16 lowercase hex."""
    return f"{millis_to_iso(t.millis)}-{t.counter:04X}-{t.node}"


_HEX = set("0123456789abcdefABCDEF")


def timestamp_from_string(s: str) -> Timestamp:
    """timestamp.ts:50-55, with strict field validation (counter is 4 hex
    digits, node is 16 lowercase-hex; separators checked)."""
    if len(s) != TIMESTAMP_STRING_LENGTH or s[24] != "-" or s[29] != "-":
        raise TimestampParseError(f"bad timestamp string: {s!r}")
    counter_s, node = s[25:29], s[30:46]
    if not all(c in _HEX for c in counter_s) or not all(c in _HEX for c in node):
        raise TimestampParseError(f"bad timestamp string: {s!r}")
    return Timestamp(iso_to_millis(s[0:24]), int(counter_s, 16), node)


def timestamp_to_hash(t: Timestamp) -> int:
    """timestamp.ts:87-88 — murmur3-32 (unsigned) of the canonical string."""
    return murmur3_32(timestamp_to_string(t).encode("ascii"))


def _increment_counter(counter: int) -> int:
    """timestamp.ts:90-95."""
    if counter < MAX_COUNTER:
        return counter + 1
    raise TimestampCounterOverflowError()


def send_timestamp(t: Timestamp, now: int, max_drift: int = 60000) -> Timestamp:
    """Stamp a local event (timestamp.ts:97-123).

    millis' = max(local.millis, now); same millis keeps the node's
    counter incrementing, a newer wall clock resets it to 0. Drift
    guard: next - now <= max_drift.
    """
    next_millis = max(t.millis, now)
    if next_millis - now > max_drift:
        raise TimestampDriftError(next_millis, now)
    counter = _increment_counter(t.counter) if next_millis == t.millis else 0
    return Timestamp(next_millis, counter, t.node)


def receive_timestamp(
    local: Timestamp, remote: Timestamp, now: int, max_drift: int = 60000
) -> Timestamp:
    """Merge a remote timestamp into the local clock (timestamp.ts:125-165).

    Order of checks matches the reference exactly: drift first, then
    duplicate-node, then the counter rules.
    """
    next_millis = max(local.millis, remote.millis, now)
    if next_millis - now > max_drift:
        raise TimestampDriftError(next_millis, now)
    if local.node == remote.node:
        raise TimestampDuplicateNodeError(local.node)
    if next_millis == local.millis and next_millis == remote.millis:
        counter = _increment_counter(max(local.counter, remote.counter))
    elif next_millis == local.millis:
        counter = _increment_counter(local.counter)
    elif next_millis == remote.millis:
        counter = _increment_counter(remote.counter)
    else:
        counter = 0
    return Timestamp(next_millis, counter, local.node)


def receive_timestamps_batch(
    local: Timestamp,
    millis,
    counter,
    node_hex,
    now: int = 0,
    max_drift: int = 60000,
) -> Timestamp:
    """Fold `receive_timestamp` over a whole batch in O(n) numpy — the
    "HLC receive is a fold, but reducible" item of SURVEY.md §7.

    With the reference's per-command TimeEnv (`now` is ONE value for the
    whole command, types.ts:303-309), the sequential fold reduces:

    - the clock's millis after step i is the prefix max of
      (local.millis, now, remote millis so far), so the final millis is
      the batch max;
    - the counter follows a max-plus recurrence
      `c_i = max(a_i, c_{i-1} + 1)` inside runs where the prefix max is
      flat (ties with the local clock), resetting when it rises — so the
      final counter is a window max of `a_j + (n - j)` over the last
      run, where `a_j` is `remote.counter + 1` on remote ties.

    Error parity: if any step could error (drift, duplicate node, or a
    counter that might overflow mid-run), fall back to the sequential
    fold so the error type, payload, and position match the reference
    exactly (errors abort the batch, so the slow path costs nothing in
    steady state).

    `millis`/`counter` are numpy arrays; `node_hex` is the RAW wire
    node strings — the duplicate-node check is an exact string compare
    (a u64 compare would be case-insensitive for non-canonical
    uppercase wire hex, diverging from the sequential fold).
    """
    return _receive_batch(
        local, millis, counter, now, max_drift,
        dup_screen=lambda: any(h == local.node for h in node_hex),
        nodes=lambda: node_hex,
    )


def receive_timestamps_batch_packed(
    local: Timestamp,
    millis,
    counter,
    node_u64,
    nodes,
    now: int = 0,
    max_drift: int = 60000,
) -> Timestamp:
    """`receive_timestamps_batch` for the fused receive path: node ids
    arrive as the parsed uint64 column, and `nodes` is a zero-arg
    callable materializing the raw node STRINGS — invoked only when a
    screen fires and the exact sequential fold must run. The
    duplicate-node screen compares u64 values, which is
    case-insensitive and therefore a SUPERSET of the sequential fold's
    exact string equality: a false positive only costs the slow path
    (which then applies the exact rule), never a wrong outcome."""
    import numpy as np

    try:
        local_u64 = np.uint64(int(local.node, 16))
    except (ValueError, OverflowError):
        # Non-hex or out-of-u64-range local node: conservatively
        # sequential (unreachable via the worker — strict parse pins 16
        # hex chars — but direct API callers get the safe path).
        return _receive_batch(
            local, millis, counter, now, max_drift,
            dup_screen=lambda: True, nodes=nodes,
        )
    return _receive_batch(
        local, millis, counter, now, max_drift,
        dup_screen=lambda: bool(
            (np.asarray(node_u64, np.uint64) == local_u64).any()
        ),
        nodes=nodes,
    )


def _receive_batch(
    local: Timestamp, millis, counter, now: int, max_drift: int,
    dup_screen, nodes,
) -> Timestamp:
    """Shared closed-form fold. `dup_screen()` must be True whenever
    ANY remote node string-equals the local node (supersets allowed —
    they only force the sequential path); `nodes()` materializes the
    raw node strings for that exact path."""
    import numpy as np

    n = len(millis)
    if n == 0:
        return local
    millis = np.asarray(millis, np.int64)
    counter_arr = np.asarray(counter, np.int64)

    seed = max(local.millis, now)
    pm = np.maximum.accumulate(np.maximum(millis, seed))
    prev_pm = np.empty_like(pm)
    prev_pm[0] = local.millis
    prev_pm[1:] = pm[:-1]
    tie_local = pm == prev_pm
    tie_remote = pm == millis

    # Conservative screens: any possible error → exact sequential path.
    # The counter only grows inside a flat-millis run (resets between
    # runs), so the tight bound uses the LONGEST tie_local run — a
    # whole-batch `+ n` bound would push every large batch onto the
    # sequential path for no reason.
    reset_pos = np.flatnonzero(~tie_local)
    run_lengths = np.diff(np.concatenate(([-1], reset_pos, [n]))) - 1
    longest_run = int(run_lengths.max(initial=0))
    counter_bound = (
        max(local.counter, int(counter_arr.max(initial=0)) + 1) + longest_run
    )
    if (
        int(pm[-1]) - now > max_drift
        or dup_screen()
        or counter_bound > MAX_COUNTER
    ):
        node_hex = nodes()
        t = local
        for i in range(n):
            t = receive_timestamp(
                t,
                Timestamp(int(millis[i]), int(counter_arr[i]), node_hex[i]),
                now,
                max_drift,
            )
        return t

    resets = ~tie_local
    neg = np.int64(-(1 << 40))
    a = np.where(tie_remote, counter_arr + 1, np.where(resets, 0, neg))
    idx = np.arange(1, n + 1, dtype=np.int64)
    reset_positions = np.nonzero(resets)[0]
    if len(reset_positions) == 0:
        k = 0
        base = local.counter  # virtual step 0 carries the seed counter
    else:
        k = int(reset_positions[-1]) + 1  # 1-based step index of last reset
        base = neg
    window = a[k - 1 :] - idx[k - 1 :] if k >= 1 else a - idx
    best = int(window.max(initial=neg))
    final_counter = max(best, base) + n
    return Timestamp(int(pm[-1]), int(final_counter), local.node)
