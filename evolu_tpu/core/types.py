"""Core CRDT data types and errors.

Mirrors the reference's type surface (reference:
packages/evolu/src/types.ts) with Python dataclasses. A `CrdtValue` is
`None | str | int | float` (types.ts:88). Messages address a single
(table, row, column) cell and carry an HLC timestamp that totally
orders all writes (types.ts:90-99).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional, Union

CrdtValue = Union[None, str, int, float]

MAX_COUNTER = 65535  # types.ts:54
MAX_DRIFT_DEFAULT = 60000  # config.ts:9


@dataclass(frozen=True)
class Timestamp:
    """Hybrid logical clock timestamp (types.ts:60-64).

    `millis` is wall-clock ms since epoch, `counter` in [0, 65535],
    `node` a 16-lowercase-hex-char node id. The string encoding is
    fixed-width so lexicographic string order equals (millis, counter,
    node) order — LWW comparisons are plain string `<`.
    """

    millis: int
    counter: int
    node: str


@dataclass(frozen=True)
class NewCrdtMessage:
    """A cell write not yet stamped with a timestamp (types.ts:90-95)."""

    table: str
    row: str
    column: str
    value: CrdtValue


@dataclass(frozen=True)
class CrdtMessage:
    """A stamped cell write (types.ts:97-99). `timestamp` is the string encoding."""

    timestamp: str
    table: str
    row: str
    column: str
    value: CrdtValue


@dataclass(frozen=True)
class CrdtClock:
    """Per-replica clock state persisted in __clock (types.ts:101-104)."""

    timestamp: Timestamp
    merkle_tree: dict


# --- Errors (types.ts:315-399). Raised as exceptions; the runtime
# converts them into onError outputs like the reference's Either channel.


class EvoluError(Exception):
    """Base class for all framework errors."""

    type: str = "EvoluError"

    def to_dict(self) -> dict:
        return {"type": self.type}


class TimestampDriftError(EvoluError):
    type = "TimestampDriftError"

    def __init__(self, next_millis: int, now: int):
        super().__init__(f"clock drift: next={next_millis} now={now}")
        self.next = next_millis
        self.now = now

    def to_dict(self) -> dict:
        return {"type": self.type, "next": self.next, "now": self.now}


class TimestampCounterOverflowError(EvoluError):
    type = "TimestampCounterOverflowError"

    def __init__(self) -> None:
        super().__init__("HLC counter overflow (> 65535)")


class TimestampDuplicateNodeError(EvoluError):
    type = "TimestampDuplicateNodeError"

    def __init__(self, node: str):
        super().__init__(f"duplicate node id: {node}")
        self.node = node

    def to_dict(self) -> dict:
        return {"type": self.type, "node": self.node}


class TimestampParseError(EvoluError):
    type = "TimestampParseError"


class SyncError(EvoluError):
    """Replica can't converge: repeated identical Merkle diff (types.ts:371-378)."""

    type = "SyncError"

    def __init__(self) -> None:
        super().__init__("sync livelock: repeated identical merkle diff")


class SQLiteError(EvoluError):
    type = "SQLiteError"


class ValidationError(EvoluError):
    """A model brand rejected a value (format or length)."""

    type = "ValidationError"


class StringMaxLengthError(ValidationError):
    type = "StringMaxLengthError"


class UnknownError(EvoluError):
    type = "UnknownError"

    def __init__(self, error: object):
        super().__init__(str(error))
        self.error = error

    def to_dict(self) -> dict:
        return {"type": self.type, "error": {"message": str(self.error)}}


class NonCanonicalStoreError(UnknownError):
    """A stored relay timestamp is not the canonical 46-byte width, so
    the packed C fetch paths (which assume fixed-width rows) cannot
    serve it. Callers fall back to the generic SQL path — a single
    malformed stored row must degrade that owner's sync to the slow
    path, not wedge it (advisor r4)."""

    type = "UnknownError"  # wire-visible type is unchanged


@dataclass(frozen=True)
class Owner:
    """A database owner: identity derived from a BIP39 mnemonic (types.ts:149-153)."""

    id: str
    mnemonic: str


@dataclass(frozen=True)
class TableDefinition:
    name: str
    columns: tuple

    @staticmethod
    def of(name: str, columns) -> "TableDefinition":
        return TableDefinition(name, tuple(columns))
