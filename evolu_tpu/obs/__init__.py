"""Observability: metrics registry + flight recorder + distributed
tracing (host-side only).

`obs.metrics` — thread-safe counters/gauges/histograms with Prometheus
v0.0.4 text exposition, a JSON snapshot, span-derived exemplars, and a
label-cardinality bound, served by the relay at GET /metrics and
GET /stats (server/relay.py).

`obs.flight` — bounded structured-event ring whose dump is attached to
exceptions crossing the worker/relay boundary.

`obs.trace` — W3C-traceparent-style distributed tracing: a bounded
per-process span ring, deterministic hash-based sampling, fan-in span
links, `GET /trace/<id>` span trees, and a Chrome-trace export
(ISSUE 10 — one mutation followed client → relay → batch → engine →
replica).

This package MUST NOT import jax (directly or transitively): metrics
and spans record host-side Python values the hot paths already hold —
never an extra device pull, never an op inside the fused jit pipeline.
The constraint is load-bearing (instrumentation overhead budget is
<=1% — metrics measured at 0.0015%, tracing gated by
benchmarks/trace_overhead.py) and mechanically enforced by
tests/test_import_hygiene.py and tests/test_bench_liveness.py.
"""

from evolu_tpu.obs import flight, metrics, trace
from evolu_tpu.obs.flight import recorder
from evolu_tpu.obs.metrics import registry, set_enabled

__all__ = ["flight", "metrics", "trace", "recorder", "registry", "set_enabled"]
