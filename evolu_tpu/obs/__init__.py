"""Observability: metrics registry + flight recorder + distributed
tracing (host-side only).

`obs.metrics` — thread-safe counters/gauges/histograms with Prometheus
v0.0.4 text exposition, a JSON snapshot, span-derived exemplars, and a
label-cardinality bound, served by the relay at GET /metrics and
GET /stats (server/relay.py).

`obs.flight` — bounded structured-event ring whose dump is attached to
exceptions crossing the worker/relay boundary.

`obs.ledger` — the conservation-ledger accounting plane (ISSUE 15):
typed flow stations with registered conservation equations, an
`audit()` that returns violated equations with per-station deltas
(empty == conserved), owner-scoped sub-ledgers behind the cardinality
cap, served by the relay at GET /ledger and asserted at the end of
every model-check episode.

`obs.trace` — W3C-traceparent-style distributed tracing: a bounded
per-process span ring, deterministic hash-based sampling, fan-in span
links, `GET /trace/<id>` span trees, and a Chrome-trace export
(ISSUE 10 — one mutation followed client → relay → batch → engine →
replica).

`obs.anatomy` — the stage-anatomy plane (ISSUE 16): a declarative
registry over the fused reconcile pipeline with machine-readable cost
laws and roofline-priced floors, the `evolu_stage_*` metrics family
(per-batch dispatch/pull/apply shares, fixed-RTT-vs-slope fits,
over-floor flags), and the registry `benchmarks/stage_anatomy.py`
builds its self-ablating timed variants from.

This package MUST NOT import jax (directly or transitively): metrics
and spans record host-side Python values the hot paths already hold —
never an extra device pull, never an op inside the fused jit pipeline.
The constraint is load-bearing (instrumentation overhead budget is
<=1% — metrics measured at 0.0015%, tracing gated by
benchmarks/trace_overhead.py) and mechanically enforced by
tests/test_import_hygiene.py and tests/test_bench_liveness.py.
"""

from evolu_tpu.obs import flight, ledger, metrics, trace
from evolu_tpu.obs import anatomy
from evolu_tpu.obs.flight import recorder
from evolu_tpu.obs.metrics import registry, set_enabled

__all__ = ["anatomy", "flight", "ledger", "metrics", "trace", "recorder",
           "registry", "set_enabled"]
