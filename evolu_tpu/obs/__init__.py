"""Observability: metrics registry + flight recorder (host-side only).

`obs.metrics` — thread-safe counters/gauges/histograms with Prometheus
v0.0.4 text exposition and a JSON snapshot, served by the relay at
GET /metrics and GET /stats (server/relay.py).

`obs.flight` — bounded structured-event ring whose dump is attached to
exceptions crossing the worker/relay boundary.

This package MUST NOT import jax (directly or transitively): metrics
record host-side Python values the hot paths already hold — never an
extra device pull, never an op inside the fused jit pipeline. The
constraint is load-bearing (instrumentation overhead budget is <=1% of
the 1M-row reconcile) and mechanically enforced by
tests/test_import_hygiene.py and tests/test_bench_liveness.py.
"""

from evolu_tpu.obs import flight, metrics
from evolu_tpu.obs.flight import recorder
from evolu_tpu.obs.metrics import registry, set_enabled

__all__ = ["flight", "metrics", "recorder", "registry", "set_enabled"]
