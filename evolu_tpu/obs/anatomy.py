"""Stage-anatomy plane (ISSUE 16): the fused reconcile pipeline as a
declarative stage registry with roofline-priced floors.

CLAUDE.md's hardest-won rule is "re-ablate stages after every
restructure" (the r4→r5 share shift: hash read 0.885 → 1.29 ms after
the sort shrank) — yet until this module the v5e/CPU cost model lived
as prose in docs/BENCHMARKS.md and ablation was a hand-run ritual.
Here the model becomes data:

- `STAGES` — the ordered registry over the fused reconcile pipeline
  (packed-key sort → plan/compare → hash render → Merkle minute fold →
  compact-delta encode → pull wave) plus the runtime seams the engine
  times per batch (device dispatch / pull wave / host apply). Each
  stage declares its inputs, outputs, and a priced floor as cost-law
  terms; `benchmarks/stage_anatomy.py` builds its stage-truncated
  timed variants from exactly these names and asserts the output
  arity against `outputs` (registry drift fails loudly, not quietly).
- `COST_LAWS` — the machine-readable encoding of the recorded cost
  laws (docs/BENCHMARKS.md r3-r5 for v5e; the CPU row is transcribed
  from this container's seeding run of stage_anatomy.py). `floor_ms`
  prices a stage from them. Floors are the RECORDED BEST for the
  platform, not an ideal roofline: "over floor" means "regressed
  ≥ FLOOR_FACTOR× from what this repo has measured", which is
  actionable, where "above DRAM bandwidth ideal" never is.
- `record_stage` / `record_span` — the runtime accountant feeding the
  `evolu_stage_*` metrics family: per-stage histograms + totals, an
  online (decayed) least-squares fit per stage separating the tunnel
  fixed-RTT intercept from the per-row slope, per-batch
  device-dispatch / pull-wave / host-apply share gauges (EWMA over
  recent batches), and `evolu_stage_over_floor_total` flags when a
  stage runs above FLOOR_FACTOR× its priced floor.

This module is part of `evolu_tpu.obs` and therefore MUST NOT import
jax (tests/test_import_hygiene.py): platform is pushed in via
`set_platform` from the jax side (parallel/mesh.py), and every value
recorded here is a host-side Python float the hot paths already hold.
The accountant follows `metrics.registry.enabled` — disabled, a
record call is one attribute read.
"""

from __future__ import annotations

import json
import os
import threading
import zlib
from dataclasses import dataclass
from typing import Dict, Optional, Tuple

from evolu_tpu.obs import metrics

# --------------------------------------------------------------------
# Cost laws: ms per 1M rows (per_1m_rows), MB/s (bandwidth), or plain
# ms (fixed). v5e numbers are the recorded measurements behind
# docs/BENCHMARKS.md r3-r5 and CLAUDE.md; cpu numbers are this
# container's 8-device-virtual-mesh seeding run of
# benchmarks/stage_anatomy.py (docs/baselines/anatomy.cpu.json) — the
# laws and the baseline artifact are the same measurement, so the
# runtime flags only genuine regressions from it.
# --------------------------------------------------------------------

COST_LAWS: Dict[str, Dict[str, float]] = {
    "tpu": {
        # lax.sort, 1M rows: packed-i64 single key ~1.5 ms + ~0.75 ms
        # per u64 payload carried through it (r3, re-measured r5).
        "sort_key_ms_per_1m": 1.5,
        "sort_payload_ms_per_1m": 0.75,
        # The two segmented max scans + flag algebra of the planner
        # tail (r5 in-pipeline ablation: "scans 0.54").
        "plan_scan_pair_ms_per_1m": 0.54,
        # u32 hi/lo divmod render + murmur fold (r5: "hash 0.24" after
        # the batch-lax.cond exact-division rework).
        "hash_render_ms_per_1m": 0.24,
        # Tile-local (owner, minute) grouping + segmented XOR (r5:
        # "minute 0.36").
        "minute_fold_ms_per_1m": 0.36,
        # Compact-delta encode tail: one more stable packed sort with
        # two payloads (engine._compact_segments_tail) = key + 2
        # payloads by the sort law.
        "delta_encode_ms_per_1m": 3.0,
        # Axon tunnel: fixed dispatch round-trip and the effective
        # device-leg bandwidth floor (CLAUDE.md: 101-121 ms, 12-17
        # MB/s — price with the favorable edge so the floor stays a
        # floor).
        "fixed_rtt_ms": 101.0,
        "pull_mb_per_s": 17.0,
        # Host apply: packed C ingest measured ~0.72M rows/s/core
        # (docs/BENCHMARKS.md r7/r12 btree-bound ingest).
        "host_apply_rows_per_s": 720_000.0,
    },
    "cpu": {
        # Seeded from benchmarks/stage_anatomy.py on this container
        # (8-device virtual CPU mesh, N=2^19, INTERLEAVED per-rep
        # marginals scaled to 1M rows; docs/baselines/anatomy.cpu.json
        # is the adjacent reproducibility run — big-stage marginals
        # agree within ~3%). The key_sort marginal
        # (446 ms/1M for key + 2 payloads) is split key/payload by
        # the v5e 2:1 ratio; the generic-scan-heavy plan/minute
        # stages dominate on CPU exactly as docs/BENCHMARKS.md r7
        # recorded (sort share collapses, scans blow up ~4000× vs
        # the TPU law).
        "sort_key_ms_per_1m": 223.0,
        "sort_payload_ms_per_1m": 111.5,
        "plan_scan_pair_ms_per_1m": 2220.0,
        "hash_render_ms_per_1m": 250.0,
        "minute_fold_ms_per_1m": 411.0,
        "delta_encode_ms_per_1m": 658.0,
        # Dispatch intercept of the timed loop at N=2^19 (jit-call +
        # arg handling; no tunnel on CPU) and the best measured
        # host-copy bandwidth of a kernel-output wave (host-local
        # memcpy — run-to-run spread 2.1-7.6 GB/s, the floor uses
        # the best).
        "fixed_rtt_ms": 261.0,
        "pull_mb_per_s": 7650.0,
        "host_apply_rows_per_s": 720_000.0,
    },
}


@dataclass(frozen=True)
class Stage:
    """One pipeline stage: identity for the ablation harness (inputs /
    outputs name the dataflow; the harness asserts variant arity from
    `outputs`) plus the priced floor as (law_key, unit) terms, where
    unit ∈ {per_1m_rows, bandwidth, fixed, device_pipeline}."""

    name: str
    kind: str  # "device" (ablatable kernel stage) | "host" | "runtime"
    description: str
    inputs: Tuple[str, ...]
    outputs: Tuple[str, ...]
    price: Tuple[Tuple[str, str], ...] = ()


STAGES: Tuple[Stage, ...] = (
    Stage(
        "key_sort", "device",
        "winner flags + packed owner|cell|idx|flags i64 key + lax.sort "
        "with the two u64 HLC payloads (reconcile._shard_kernel head)",
        inputs=("cell_id", "k1", "k2", "ex_k1", "ex_k2", "owner_ix"),
        outputs=("key_sorted", "k1_sorted", "k2_sorted"),
        price=(("sort_key_ms_per_1m", "per_1m_rows"),
               ("sort_payload_ms_per_1m", "per_1m_rows"),
               ("sort_payload_ms_per_1m", "per_1m_rows")),
    ),
    Stage(
        "plan_compare", "device",
        "sorted-key field unpack + segmented max scans + LWW flag "
        "algebra (ops.merge.masks_from_sorted_flags)",
        inputs=("key_sorted", "k1_sorted", "k2_sorted"),
        outputs=("xor_sorted", "upsert_sorted", "idx_sorted"),
        price=(("plan_scan_pair_ms_per_1m", "per_1m_rows"),),
    ),
    Stage(
        "hash_render", "device",
        "HLC key unpack + canonical timestamp render + murmur3 hash, "
        "masked by the xor plan, + XOR-allreduced batch digest",
        inputs=("k1_sorted", "k2_sorted", "xor_sorted"),
        outputs=("hashes", "digest"),
        price=(("hash_render_ms_per_1m", "per_1m_rows"),),
    ),
    Stage(
        "minute_fold", "device",
        "tile-local (owner, minute) grouping + segmented XOR of the "
        "row hashes (ops.merkle_ops.owner_minute_segments)",
        inputs=("owner_ix", "k1_sorted", "hashes", "xor_sorted"),
        outputs=("owner_sorted", "minute_sorted", "seg_end", "seg_xor",
                 "valid_sorted"),
        price=(("minute_fold_ms_per_1m", "per_1m_rows"),),
    ),
    Stage(
        "delta_encode", "device",
        "compact-delta wire encode: pack owner<<32|minute, stable "
        "float-segments-to-front sort, segment count (the "
        "engine._compact_segments_tail shape, 16B/row upload form)",
        inputs=("owner_sorted", "minute_sorted", "seg_end", "seg_xor",
                "valid_sorted"),
        outputs=("delta_packed", "delta_xor", "seg_count"),
        price=(("delta_encode_ms_per_1m", "per_1m_rows"),),
    ),
    Stage(
        "pull_wave", "host",
        "one to_host_many transfer wave of the kernel outputs — "
        "bandwidth-bound under the tunnel (bytes ARE the cost)",
        inputs=("device_outputs",),
        outputs=("host_arrays",),
        price=(("pull_mb_per_s", "bandwidth"),),
    ),
    Stage(
        "device_dispatch", "runtime",
        "engine.start_batch: pack + native parse + device dispatch + "
        "async transfer start (no database access) — one tunnel RTT "
        "plus the whole device pipeline at batch size",
        inputs=("sync_requests",),
        outputs=("staged_batch",),
        price=(("fixed_rtt_ms", "fixed"), ("device_pipeline", "device_pipeline")),
    ),
    Stage(
        "host_apply", "runtime",
        "the btree+tree materialization leg: per-shard C inserts + "
        "delta decode + Merkle tree folds + one atomic commit per "
        "shard — engine.finish_batch synchronously, or the per-shard "
        "write-behind drain workers in deferred mode (each worker "
        "records its shard's batches with a shard= label)",
        inputs=("staged_batch",),
        outputs=("responses",),
        price=(("host_apply_rows_per_s", "rows_per_s"),),
    ),
)

_STAGE_BY_NAME: Dict[str, Stage] = {s.name: s for s in STAGES}

# Runtime stages whose EWMA durations form the per-batch share gauges.
RUNTIME_SHARE_STAGES = ("device_dispatch", "pull_wave", "host_apply")

# kernel:* span targets folded into the family get a priced floor when
# their work maps onto registry stages; everything else records
# unpriced (floor 0 → never flagged).
_SPAN_FLOOR_STAGES: Dict[str, Tuple[str, ...]] = {
    # reconcile_owner_batches wraps dispatch + device pipeline + pull.
    "kernel:reconcile": ("device_dispatch",),
    # The server Merkle kernels run hash + minute fold + delta encode.
    "kernel:merkle": ("hash_render", "minute_fold", "delta_encode"),
}

FLOOR_FACTOR = float(os.environ.get("EVOLU_STAGE_FLOOR_FACTOR", "4.0"))
_WARMUP_RECORDS = 2  # first records include compile; never flag them
_DECAY = 0.98  # sliding exponential window for the per-stage fit
_EWMA_ALPHA = 0.2


def registry_digest() -> str:
    """crc32 fingerprint of the registry + cost laws. A hard gate in
    docs/baselines/anatomy.<platform>.json (compare_baselines treats
    *digest* keys as exact-match): restructuring the registry or
    re-pricing a law without re-ablating fails CI until the baseline
    is re-recorded from a real run."""
    doc = {
        "stages": [
            (s.name, s.kind, s.inputs, s.outputs, s.price) for s in STAGES
        ],
        "laws": COST_LAWS,
    }
    return f"{zlib.crc32(json.dumps(doc, sort_keys=True).encode()) & 0xFFFFFFFF:08x}"


def floor_ms(stage: str, rows: int = 0, nbytes: int = 0,
             platform: Optional[str] = None) -> float:
    """Priced floor for `stage` at this batch shape, in ms; 0.0 when
    the platform has no recorded laws (unknown platform = unpriced =
    never flagged) or the stage is unregistered."""
    p = platform if platform is not None else _acct.platform
    laws = COST_LAWS.get(p)
    st = _STAGE_BY_NAME.get(stage)
    if laws is None or st is None:
        if laws is not None and stage in _SPAN_FLOOR_STAGES:
            return sum(
                floor_ms(s, rows=rows, nbytes=nbytes, platform=p)
                for s in _SPAN_FLOOR_STAGES[stage]
            )
        return 0.0
    total = 0.0
    for law_key, unit in st.price:
        if unit == "per_1m_rows":
            total += laws[law_key] * (rows / 1e6)
        elif unit == "fixed":
            total += laws[law_key]
        elif unit == "bandwidth":
            total += nbytes / (laws[law_key] * 1e6) * 1e3
        elif unit == "rows_per_s":
            total += rows / laws[law_key] * 1e3
        elif unit == "device_pipeline":
            total += sum(
                floor_ms(s.name, rows=rows, platform=p)
                for s in STAGES if s.kind == "device"
            )
    return total


class _StageAccountant:
    """Per-stage running state behind the evolu_stage_* family. All
    host-side dict/float arithmetic under one lock (engine pull thread
    + relay handler threads record concurrently)."""

    def __init__(self):
        self._lock = threading.Lock()
        self.platform = "unknown"
        self._stats: Dict[str, dict] = {}

    def _stage_state(self, stage: str) -> dict:
        st = self._stats.get(stage)
        if st is None:
            st = self._stats[stage] = {
                "count": 0, "total_ms": 0.0, "ewma_ms": None,
                # Decayed least-squares accumulators over (rows, ms).
                "n": 0.0, "sx": 0.0, "sy": 0.0, "sxx": 0.0, "sxy": 0.0,
                "slope_ns_per_row": None, "fixed_ms": None,
                "floor_ms": 0.0, "over_floor": 0,
            }
        return st

    def record(self, stage: str, seconds: float, rows: int = 0,
               nbytes: int = 0, shard: Optional[int] = None) -> None:
        if not metrics.registry.enabled:
            return
        ms = seconds * 1e3
        metrics.observe("evolu_stage_ms", ms, stage=stage)
        if shard is not None:
            # Per-shard split of a stage that runs concurrently per
            # shard (the write-behind drain): shard labels are bounded
            # by store topology, far inside the 512-per-family cap.
            metrics.observe("evolu_stage_shard_ms", ms, stage=stage,
                            shard=str(shard))
        metrics.inc("evolu_stage_seconds_total", seconds, stage=stage)
        if rows:
            metrics.inc("evolu_stage_rows_total", rows, stage=stage)
        if nbytes:
            metrics.inc("evolu_stage_bytes_total", nbytes, stage=stage)
        floor = floor_ms(stage, rows=rows, nbytes=nbytes)
        with self._lock:
            st = self._stage_state(stage)
            st["count"] += 1
            st["total_ms"] += ms
            st["ewma_ms"] = (
                ms if st["ewma_ms"] is None
                else (1 - _EWMA_ALPHA) * st["ewma_ms"] + _EWMA_ALPHA * ms
            )
            st["floor_ms"] = floor
            flagged = (
                floor > 0.0
                and st["count"] > _WARMUP_RECORDS
                and ms > FLOOR_FACTOR * floor
            )
            if flagged:
                st["over_floor"] += 1
            slope_fixed = None
            if rows > 0:
                # Decayed accumulators: the fit tracks the recent
                # regime, so a restructure shows up within ~50 batches
                # instead of being averaged against history forever.
                for k in ("n", "sx", "sy", "sxx", "sxy"):
                    st[k] *= _DECAY
                st["n"] += 1.0
                st["sx"] += rows
                st["sy"] += ms
                st["sxx"] += float(rows) * rows
                st["sxy"] += rows * ms
                n, sx, sy, sxx, sxy = (
                    st["n"], st["sx"], st["sy"], st["sxx"], st["sxy"]
                )
                var = n * sxx - sx * sx
                if n >= 2.0 and var > 1e-9:
                    slope_ms_per_row = (n * sxy - sx * sy) / var
                    fixed = (sy - slope_ms_per_row * sx) / n
                    st["slope_ns_per_row"] = max(slope_ms_per_row, 0.0) * 1e6
                    st["fixed_ms"] = max(fixed, 0.0)
                    slope_fixed = (st["slope_ns_per_row"], st["fixed_ms"])
            shares = None
            if stage in RUNTIME_SHARE_STAGES:
                ewmas = {
                    s: self._stats[s]["ewma_ms"]
                    for s in RUNTIME_SHARE_STAGES
                    if s in self._stats and self._stats[s]["ewma_ms"] is not None
                }
                total = sum(ewmas.values())
                if total > 0:
                    shares = {s: v / total for s, v in ewmas.items()}
        # Gauges outside the lock: metrics has its own.
        if floor > 0.0:
            metrics.set_gauge("evolu_stage_floor_ms", floor, stage=stage)
            metrics.set_gauge("evolu_stage_over_floor_ratio", ms / floor,
                              stage=stage)
            if flagged:
                metrics.inc("evolu_stage_over_floor_total", stage=stage)
        if slope_fixed is not None:
            # The tunnel fixed-RTT intercept separated from the
            # per-row slope — the CLAUDE.md wall/count trap, live.
            metrics.set_gauge("evolu_stage_slope_ns_per_row",
                              slope_fixed[0], stage=stage)
            metrics.set_gauge("evolu_stage_fixed_ms", slope_fixed[1],
                              stage=stage)
        if shares is not None:
            for s, v in shares.items():
                metrics.set_gauge("evolu_stage_share", v, stage=s)

    def payload(self) -> dict:
        with self._lock:
            stages = {
                name: {
                    k: st[k]
                    for k in ("count", "total_ms", "ewma_ms",
                              "slope_ns_per_row", "fixed_ms", "floor_ms",
                              "over_floor")
                }
                for name, st in sorted(self._stats.items())
            }
        ewmas = {
            s: stages[s]["ewma_ms"]
            for s in RUNTIME_SHARE_STAGES
            if s in stages and stages[s]["ewma_ms"] is not None
        }
        total = sum(ewmas.values())
        for s, v in ewmas.items():
            stages[s]["share"] = v / total if total > 0 else None
        return {
            "platform": self.platform,
            "floor_factor": FLOOR_FACTOR,
            "registry_digest": registry_digest(),
            "stages": stages,
        }

    def reset(self) -> None:
        with self._lock:
            self._stats.clear()


_acct = _StageAccountant()


def set_platform(platform: str) -> None:
    """Push the device platform in from the jax side (parallel/mesh.py
    at mesh creation) — this module must never ask jax itself. Unknown
    platforms price every floor at 0 (recorded, never flagged)."""
    _acct.platform = str(platform)


def get_platform() -> str:
    return _acct.platform


def record_stage(stage: str, seconds: float, rows: int = 0,
                 nbytes: int = 0, shard: Optional[int] = None) -> None:
    """Record one execution of a stage (runtime seams call this
    directly: engine.start_batch/finish_batch, ops.to_host_many, the
    write-behind drain workers with their shard index)."""
    _acct.record(stage, seconds, rows=rows, nbytes=nbytes, shard=shard)


def record_span(target: str, ms: float, rows: object = 0) -> None:
    """Fold a kernel:* log span into the family (utils/log.py span
    close). Stage label = the span target; rows from the span's n=
    field when present, so the per-target fit separates fixed RTT from
    slope exactly like the explicit seams."""
    n = rows if isinstance(rows, int) and rows > 0 else 0
    _acct.record(target, ms / 1e3, rows=n)


def stages_payload() -> dict:
    """The GET /stats "stages" section: per-stage counts, EWMA, fit,
    floor, over-floor tally, and runtime shares."""
    return _acct.payload()


def reset() -> None:
    """Clear accumulators (test isolation via logger.clear()); the
    platform survives — it is a process property, not a statistic."""
    _acct.reset()
