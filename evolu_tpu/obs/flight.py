"""Flight recorder: a bounded structured-event ring for post-mortems.

Fault-injection failures (tests/test_faults.py) used to surface as a
bare traceback; this ring keeps the last N structured events (reusing
`utils.log.LogEvent`) from EVERY target — independent of the logger's
console gating — and `attach` hangs the dump off any exception crossing
the worker/relay boundary, so an OnError arrives with the runtime's
recent history instead of just a stack.

Fed from two directions:
- `utils.log.Logger` mirrors every `log()`/`span()` event here even
  when the target's console output is disabled (the recorder exists
  precisely for events nobody was watching);
- hot paths may `record()` directly for events that are not log lines.

Host-side only (no jax — same constraint as obs.metrics); every write
is one deque append under a lock.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Deque, List, Optional

from evolu_tpu.utils.log import LogEvent


class FlightRecorder:
    def __init__(self, capacity: int = 256):
        self._lock = threading.Lock()
        self._ring: Deque[LogEvent] = deque(maxlen=capacity)
        self.enabled = True

    def record(self, target: str, message: str = "", *,
               duration_ms: Optional[float] = None, **fields) -> None:
        if not self.enabled:
            return
        ev = LogEvent(target=target, message=message, t=time.time(),
                      duration_ms=duration_ms, fields=fields)
        with self._lock:
            self._ring.append(ev)

    def record_event(self, ev: LogEvent) -> None:
        """Append an already-built LogEvent (the Logger mirror path)."""
        if not self.enabled:
            return
        with self._lock:
            self._ring.append(ev)

    def dump(self) -> List[LogEvent]:
        with self._lock:
            return list(self._ring)

    def format_dump(self, limit: Optional[int] = None) -> str:
        evs = self.dump()
        if limit is not None:
            evs = evs[-limit:]
        lines = []
        for e in evs:
            dur = f" {e.duration_ms:.3f}ms" if e.duration_ms is not None else ""
            extra = (
                " " + " ".join(f"{k}={v}" for k, v in e.fields.items())
            ) if e.fields else ""
            lines.append(f"{e.t:.3f} [{e.target}] {e.message}{dur}{extra}")
        return "\n".join(lines)

    def clear(self) -> None:
        with self._lock:
            self._ring.clear()

    def attach(self, exc: BaseException, limit: int = 64) -> BaseException:
        """Attach the dump to an exception about to cross a boundary:
        `exc.flight_records` gets the event list (idempotent — a nested
        boundary keeps the innermost, most complete dump) and, where
        supported, an `add_note` makes the tail visible in the printed
        traceback. Must never raise — this runs inside error paths."""
        try:
            if getattr(exc, "flight_records", None) is not None:
                return exc
            exc.flight_records = self.dump()
            if exc.flight_records and hasattr(exc, "add_note"):
                tail = self.format_dump(limit=limit)
                exc.add_note(
                    f"flight recorder (last {min(limit, len(exc.flight_records))} "
                    f"of {len(exc.flight_records)} events):\n{tail}"
                )
        except Exception:  # noqa: BLE001,S110 - never mask the original error
            pass
        return exc


# Module-level default, like utils.log.logger.
recorder = FlightRecorder()

record = recorder.record
dump = recorder.dump
attach = recorder.attach
clear = recorder.clear
