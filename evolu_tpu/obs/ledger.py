"""Conservation-ledger accounting plane: prove where every message went.

The reference's correctness story is "applyMessages eventually
converges"; ours spreads one message across six apply routes and four
ingress paths, and the independent `evolu_*` counters cannot say
whether a message that entered the system ever reached a terminal —
nothing relates them. This module is the relation: a thread-safe
per-process **message-flow ledger** of typed stations with REGISTERED
CONSERVATION EQUATIONS over them, so the obs plane stops being a
dashboard and becomes a correctness oracle (the "verify the merge
bookkeeping" direction of Certified Mergeable Replicated Data Types,
arXiv 2203.14518; Merkle-CRDTs get convergence *detection* from the
DAG — the analogous move for a batched substrate is explicit flow
accounting whose balance is machine-checkable).

Two independent planes share one station namespace:

SERVER plane (relay/engine/store — counts are MESSAGE deliveries, one
event per message per delivery attempt, *not* unique messages):

    ingress.sync         sync POST decoded at the relay (per message)
    ingress.forward      /fleet/forward envelope decoded at the target
    ingress.replication  messages pulled from a peer and served locally
    ingress.snapshot     snapshot rows swapped into the live store
    ingress.replay       write-behind log records replayed at restart
    egress.forward       handed to the placed peer (forward mode)
    egress.redirect      bounced with 307 (redirect mode)
    shed.backpressure    shed with 503 + Retry-After (flow control)
    reject.invalid       serve errored after decode (500/502 answers)
    store.inserted       row was new (changes==1 / was-new flag)
    store.duplicate      row already stored (incl. in-batch dedup)
    wb.queued            rows ACKed into the write-behind log
    wb.drained           rows materialized to SQLite by the drain
    wb.dropped           rows dropped by an explicit queue reset

APPLY plane (the local LWW apply — client/worker storage/apply.py):

    apply.ingress        messages entering apply_messages[_sequential]
    route.packed         applied via the packed columnar cell plan
    route.object         applied via the standard object path
    route.sequential     applied via the reference per-message oracle
    route.typed          (tally) messages folded by typed materializers
    route.host_fallback  (tally) messages planned by merge._host_fallback
    bounce.non_canonical (tally) packed rows bounced for canonicality
    apply.inserted       XORed into the tree AND won its cell
    apply.losing         XORed into the tree but lost LWW
    apply.duplicate      exact duplicate (no XOR)
    apply.rejected       batch rolled back (counted instead of a route)

Default equations (every message entering a station must exit through
exactly one successor; ingress totals == terminal totals at
quiescence):

    server-flow*         Σ ingress.* == store.inserted + store.duplicate
                           + shed.backpressure + reject.invalid
                           + egress.forward + egress.redirect + wb.dropped
    write-behind-balance* wb.queued == wb.drained + wb.dropped
    apply-routing        apply.ingress == route.packed + route.object
                           + route.sequential + apply.rejected
    apply-outcomes       route.packed + route.object + route.sequential
                           == apply.inserted + apply.losing + apply.duplicate

(*) barrier-only: meaningful at quiescence — after write-behind drain
barriers (PR-19: the composed per-shard barrier; each shard's drain
transaction posts its OWN pending entry, committed iff that shard's
SQLite transaction committed, so a kill between shard commits leaves
every row at exactly one terminal), with no requests in flight. `audit(at_barrier=False)` skips
them; `audit()` (the default) checks everything and returns the
violated equations with per-station deltas — an empty list IS the
conservation proof, and tests/test_model_check.py asserts it at the
end of every episode.

Transactional posting: hot paths that classify inside a transaction
accumulate into a `pending()` entry and `commit()` it only after the
SQL transaction committed (`abort()` on rollback) — a poisoned batch
or rolled-back apply must post NOTHING, or the scheduler's singleton
retry would double-count (the retry posts once through the per-request
path instead).

Hard constraints, same as obs.metrics: HOST-SIDE ONLY (this module
never imports jax — mechanically enforced by
tests/test_import_hygiene.py), O(1)-ish per event (one lock + a few
dict adds on ints the call site already holds; never a device pull),
zero graph impact (tests/test_bench_liveness.py runs the fence with
the ledger hot). Owner-scoped sub-ledgers sit behind the PR-10
cardinality cap: past `owner_cardinality_cap` distinct owners, new
owners fold into the "__overflow__" aggregate, so hostile or merely
numerous owner ids can never grow the ledger unboundedly.
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional, Sequence, Tuple

# -- station names (typed constants; count() accepts any string so
# embedders can extend the graph, but equations only see registered
# stations) --

INGRESS_SYNC = "ingress.sync"
INGRESS_FORWARD = "ingress.forward"
INGRESS_REPLICATION = "ingress.replication"
INGRESS_SNAPSHOT = "ingress.snapshot"
INGRESS_REPLAY = "ingress.replay"
EGRESS_FORWARD = "egress.forward"
EGRESS_REDIRECT = "egress.redirect"
SHED_BACKPRESSURE = "shed.backpressure"
REJECT_INVALID = "reject.invalid"
STORE_INSERTED = "store.inserted"
STORE_DUPLICATE = "store.duplicate"
WB_QUEUED = "wb.queued"
WB_DRAINED = "wb.drained"
WB_DROPPED = "wb.dropped"

APPLY_INGRESS = "apply.ingress"
ROUTE_PACKED = "route.packed"
ROUTE_OBJECT = "route.object"
ROUTE_SEQUENTIAL = "route.sequential"
ROUTE_TYPED = "route.typed"
ROUTE_HOST_FALLBACK = "route.host_fallback"
BOUNCE_NON_CANONICAL = "bounce.non_canonical"
APPLY_INSERTED = "apply.inserted"
APPLY_LOSING = "apply.losing"
APPLY_DUPLICATE = "apply.duplicate"
APPLY_REJECTED = "apply.rejected"

# Partial-replication tallies (ISSUE 18 — outside the flow equations
# on purpose: a scoped serve classifies response EGRESS rows, it never
# changes where an ingressed message terminates, so `server-flow`
# stays balanced and `audit() == []` holds with scoping on).
#   serve.scoped_rows      response rows served under a scope clause
#   serve.scope_filtered   response rows withheld by the scope filter
#                          (deferred — still fully stored and in the
#                          full tree; the client's deferred frontier is
#                          the mirror count, runtime/worker.py)
#   apply.deferred_mat     (client plane tally) messages whose
#                          app-table materialization the sync scope
#                          deferred — log+tree applied, upsert skipped
SERVE_SCOPED = "serve.scoped_rows"
SERVE_SCOPE_FILTERED = "serve.scope_filtered"
APPLY_DEFERRED_MAT = "apply.deferred_mat"

# The ISSUE-10 cardinality bound, applied to owner sub-ledgers: past
# the cap, new owners aggregate under this key.
OWNER_OVERFLOW = "__overflow__"
OWNER_CARDINALITY_CAP = 512

def flag_sum(mask) -> int:
    """Count truthy entries of a was-new/plan mask without caring
    whether it is a numpy bool array or a plain list — `.sum()` first
    (builtin sum over a 1M-element ndarray iterates per element in
    Python). ONE copy shared by storage/apply.py and server/relay.py."""
    s = getattr(mask, "sum", None)
    return int(s()) if s is not None else int(sum(1 for f in mask if f))


_SERVER_INGRESS = (INGRESS_SYNC, INGRESS_FORWARD, INGRESS_REPLICATION,
                   INGRESS_SNAPSHOT, INGRESS_REPLAY)
_SERVER_TERMINALS = (STORE_INSERTED, STORE_DUPLICATE, SHED_BACKPRESSURE,
                     REJECT_INVALID, EGRESS_FORWARD, EGRESS_REDIRECT,
                     WB_DROPPED)
_APPLY_ROUTES = (ROUTE_PACKED, ROUTE_OBJECT, ROUTE_SEQUENTIAL)


class PendingEntry:
    """A local, lock-free accumulator for one transaction's worth of
    flow events. `commit()` posts everything to the ledger atomically;
    `abort()` (or garbage collection) discards. Single-shot: a second
    commit is a no-op, so `finally: entry.abort()` patterns are safe."""

    __slots__ = ("_ledger", "_counts", "_done")

    def __init__(self, ledger: "Ledger"):
        self._ledger = ledger
        self._counts: Dict[Tuple[str, Optional[str]], int] = {}
        self._done = False

    def count(self, station: str, n: int = 1, owner: Optional[str] = None) -> None:
        if n:
            key = (station, owner)
            self._counts[key] = self._counts.get(key, 0) + int(n)

    def commit(self) -> None:
        if self._done:
            return
        self._done = True
        if self._counts:
            self._ledger._post(self._counts)

    def abort(self) -> None:
        self._done = True
        self._counts.clear()


class Ledger:
    """Typed stations + registered conservation equations + owner
    sub-ledgers. All counts are plain ints under one lock."""

    def __init__(self, owner_cardinality_cap: int = OWNER_CARDINALITY_CAP):
        self._lock = threading.Lock()
        self.enabled = True
        self.owner_cardinality_cap = owner_cardinality_cap
        self._counts: Dict[str, int] = {}
        self._owners: Dict[str, Dict[str, int]] = {}
        # (name, lhs stations, rhs stations, barrier_only)
        self._equations: List[Tuple[str, Tuple[str, ...], Tuple[str, ...], bool]] = []
        self._register_defaults()

    # -- configuration --

    def _register_defaults(self) -> None:
        self.register_equation(
            "server-flow", _SERVER_INGRESS, _SERVER_TERMINALS,
            barrier_only=True,
        )
        self.register_equation(
            "write-behind-balance", (WB_QUEUED,), (WB_DRAINED, WB_DROPPED),
            barrier_only=True,
        )
        self.register_equation(
            "apply-routing", (APPLY_INGRESS,),
            _APPLY_ROUTES + (APPLY_REJECTED,),
        )
        self.register_equation(
            "apply-outcomes", _APPLY_ROUTES,
            (APPLY_INSERTED, APPLY_LOSING, APPLY_DUPLICATE),
        )

    def register_equation(
        self, name: str, lhs: Sequence[str], rhs: Sequence[str],
        barrier_only: bool = False,
    ) -> None:
        """Register `sum(lhs) == sum(rhs)` as an invariant. Barrier-only
        equations are checked only by `audit(at_barrier=True)` — they
        hold at quiescence (drained write-behind, no in-flight
        requests), not mid-stream."""
        with self._lock:
            self._equations = [e for e in self._equations if e[0] != name]
            self._equations.append((name, tuple(lhs), tuple(rhs), barrier_only))

    # -- write side (hot paths) --

    def count(self, station: str, n: int = 1, owner: Optional[str] = None) -> None:
        """Record `n` messages passing `station`. Cheap by contract:
        one lock, two dict adds on ints the call site already holds."""
        if not self.enabled or not n:
            return
        self._post({(station, owner): int(n)})

    def pending(self) -> PendingEntry:
        """A transactional accumulator — see PendingEntry. Disabled
        ledgers still hand one out (its commit posts nothing)."""
        return PendingEntry(self)

    def _post(self, counts: Dict[Tuple[str, Optional[str]], int]) -> None:
        if not self.enabled:
            return
        with self._lock:
            for (station, owner), n in counts.items():
                self._counts[station] = self._counts.get(station, 0) + n
                if owner is None:
                    continue
                sub = self._owners.get(owner)
                if sub is None:
                    if len(self._owners) >= self.owner_cardinality_cap:
                        owner = OWNER_OVERFLOW
                    sub = self._owners.setdefault(owner, {})
                sub[station] = sub.get(station, 0) + n

    # -- read side --

    def total(self, station: str) -> int:
        with self._lock:
            return self._counts.get(station, 0)

    def totals(self) -> Dict[str, int]:
        with self._lock:
            return dict(self._counts)

    def owner_totals(self, owner: str) -> Dict[str, int]:
        with self._lock:
            return dict(self._owners.get(owner, {}))

    def owners(self) -> List[str]:
        with self._lock:
            return list(self._owners)

    def audit(self, at_barrier: bool = True) -> List[dict]:
        """Check every registered equation; return the VIOLATED ones as
        [{equation, lhs: {station: count}, rhs: {...}, delta}] with
        delta = sum(lhs) - sum(rhs). Empty list == conserved. With
        `at_barrier=False`, barrier-only equations (write-behind
        balance, the server flow) are skipped — they only hold at
        quiescence."""
        with self._lock:
            counts = dict(self._counts)
            equations = list(self._equations)
        out: List[dict] = []
        for name, lhs, rhs, barrier_only in equations:
            if barrier_only and not at_barrier:
                continue
            lhs_m = {s: counts.get(s, 0) for s in lhs}
            rhs_m = {s: counts.get(s, 0) for s in rhs}
            delta = sum(lhs_m.values()) - sum(rhs_m.values())
            if delta != 0:
                out.append({
                    "equation": name,
                    "lhs": lhs_m,
                    "rhs": rhs_m,
                    "delta": delta,
                })
        return out

    def snapshot(self, at_barrier: bool = False) -> dict:
        """JSON-ready dump: station totals, per-owner sub-ledgers, the
        registered equations, and the current audit (run at the given
        barrier level — the default False never claims a quiescence
        violation from a merely in-flight message)."""
        with self._lock:
            payload = {
                "stations": dict(self._counts),
                "owners": {o: dict(sub) for o, sub in self._owners.items()},
                "equations": [
                    {"name": n, "lhs": list(l), "rhs": list(r),
                     "barrier_only": b}
                    for n, l, r, b in self._equations
                ],
                "owner_cardinality_cap": self.owner_cardinality_cap,
            }
        payload["violations"] = self.audit(at_barrier=at_barrier)
        return payload

    def reset(self) -> None:
        """Zero every station and owner sub-ledger (equations persist —
        like metrics bucket shapes, the flow graph is configuration,
        not data). Episode tests reset at start so earlier traffic in
        the process cannot leak into their conservation proof."""
        with self._lock:
            self._counts.clear()
            self._owners.clear()


# Module-level default ledger (the process's accounting plane — the
# relay's GET /ledger and the evidence dump both serve this instance).
ledger = Ledger()

count = ledger.count
pending = ledger.pending
audit = ledger.audit
totals = ledger.totals
snapshot = ledger.snapshot
reset = ledger.reset


def set_enabled(flag: bool) -> None:
    """Ledger kill switch (bench guard / overhead measurement)."""
    ledger.enabled = bool(flag)


def quarantine():
    """Context manager that disables the default ledger for its body:
    for ORACLE TWINS — tests re-running system paths (engine passes,
    store applies) as reference computations whose flows are not part
    of the system under audit. Process-global like the ledger itself:
    only use where no real traffic runs concurrently (the episodes'
    oracle phases run after teardown/quiescence)."""
    from contextlib import contextmanager

    @contextmanager
    def _ctx():
        prev = ledger.enabled
        ledger.enabled = False
        try:
            yield
        finally:
            ledger.enabled = prev

    return _ctx()
