"""Thread-safe metrics registry: counters, gauges, log-bucket histograms.

The only runtime signal used to be the print-gated logger; this module
gives every hot-path decision point (winner-cache hit/miss, host-oracle
routing, packed-vs-object bounces, shard sizes, sync wire volume, relay
latency) a numeric home that the relay can serve as Prometheus v0.0.4
text exposition (`render_prometheus`) or a JSON snapshot (`snapshot`).

Design constraints (the device-path invariant from the issue):
- HOST-SIDE ONLY. This package must never import jax: instrumentation
  records Python ints/floats the hot paths already hold. Nothing here
  may force a device pull or insert ops into the fused jit pipeline —
  mechanically enforced by tests/test_import_hygiene.py (no jax import
  in `evolu_tpu.obs`) and tests/test_bench_liveness.py (bench checksum
  and jit cache unchanged with metrics on).
- O(1) and cheap per event: one lock + one dict update. A disabled
  registry (`set_enabled(False)`) short-circuits before the lock so
  the bench guard can prove zero interaction with the timed graph.
- NO module-level jnp anything (trivially: no jax at all) — the
  "breaks `jax.distributed.initialize`" invariant applies to this
  package like any other.

Histograms use FIXED log-spaced buckets chosen per family at first
observe (defaults below) so exposition shape is batch-independent and
two snapshots always subtract cleanly.

Two ISSUE-10 extensions:
- **Label-cardinality bound.** Per-owner/per-peer trace labels (the
  convergence-plane freshness gauges) mean label VALUES can now come
  from data, not just code. Each family admits at most
  `label_cardinality_cap` distinct label sets; past the cap, new sets
  fold into one `"__overflow__"` value per label (the aggregate stays
  countable) and `evolu_obs_label_overflow_total{family=...}` counts
  the folds — the registry can never grow unboundedly from hostile or
  merely numerous label values.
- **Exemplars.** `observe(..., exemplar=trace_id)` attaches the most
  recent trace id to a histogram series (OpenMetrics exemplar
  semantics: one per series, latest wins — enough to jump from a
  latency histogram to `GET /trace/<id>`). Exposed via `snapshot()`
  and `get_exemplar`; the text exposition stays Prometheus 0.0.4
  unless `render_prometheus(exemplars=True)` opts into the
  OpenMetrics-style `# {trace_id="..."}` suffix.
"""

from __future__ import annotations

import json
import threading
import time
from typing import Dict, List, Optional, Sequence, Tuple


def log_buckets(lo: float, hi: float, ratio: float = 2.0) -> Tuple[float, ...]:
    """Fixed log-spaced bucket upper bounds: lo, lo*ratio, ... >= hi."""
    edges: List[float] = []
    b = float(lo)
    while b < hi:
        edges.append(b)
        b *= ratio
    edges.append(b)
    return tuple(edges)


# Default bucket families (upper bounds; +Inf is implicit).
# Durations in ms: 62.5us .. ~65.5s, x2.
LATENCY_MS_BUCKETS = log_buckets(0.0625, 1 << 16)
# Wire/byte sizes: 64B .. 64MB, x4 (the relay caps bodies at 20MB).
SIZE_BUCKETS = log_buckets(64, 1 << 26, 4.0)
# Row/message counts: 1 .. 16M, x4 (batches cap at 2^24 rows).
COUNT_BUCKETS = log_buckets(1, 1 << 24, 4.0)

_LabelItems = Tuple[Tuple[str, str], ...]


def _label_key(labels: Dict[str, object]) -> _LabelItems:
    return tuple(sorted((k, str(v)) for k, v in labels.items()))


def _escape(v: str) -> str:
    return v.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


def _fmt_labels(items: _LabelItems, extra: str = "") -> str:
    parts = [f'{k}="{_escape(v)}"' for k, v in items]
    if extra:
        parts.append(extra)
    return "{" + ",".join(parts) + "}" if parts else ""


def _fmt_num(v: float) -> str:
    """Prometheus sample value / le bound: trim floats that are ints."""
    f = float(v)
    if f.is_integer() and abs(f) < 1e15:
        return str(int(f))
    return repr(f)


class _Hist:
    __slots__ = ("counts", "sum", "count", "exemplar")

    def __init__(self, n_buckets: int):
        self.counts = [0] * (n_buckets + 1)  # last = +Inf overflow
        self.sum = 0.0
        self.count = 0
        # (trace_id, value, unix_ts) of the latest exemplar-bearing
        # observe, or None — OpenMetrics semantics, latest wins.
        self.exemplar: Optional[Tuple[str, float, float]] = None


# Distinct label sets a family admits before new sets fold into the
# "__overflow__" aggregate. Generous: code-controlled label sets
# (shards, endpoints, peers) sit far below it; only data-driven
# labels (per-owner gauges) ever approach it.
LABEL_CARDINALITY_CAP = 512


class MetricsRegistry:
    """Counters, gauges, histograms keyed by (name, sorted labels).

    The flat imperative API (`inc`/`set_gauge`/`observe`) keeps call
    sites one line and the per-event cost one lock + one dict op —
    families (help text, histogram buckets) register implicitly on
    first use, or explicitly via `describe`.
    """

    def __init__(self):
        self._lock = threading.Lock()
        self.enabled = True
        self.label_cardinality_cap = LABEL_CARDINALITY_CAP
        self._counters: Dict[str, Dict[_LabelItems, float]] = {}
        self._gauges: Dict[str, Dict[_LabelItems, float]] = {}
        self._hists: Dict[str, Dict[_LabelItems, _Hist]] = {}
        self._buckets: Dict[str, Tuple[float, ...]] = {}
        self._help: Dict[str, str] = {}

    # -- write side (hot paths) --

    def _admit(self, fam: dict, name: str, key: _LabelItems) -> _LabelItems:
        """Cardinality gate, called under the lock: an already-known
        key (or the unlabeled key) passes untouched; a NEW key past
        the per-family cap folds every label value into "__overflow__"
        and counts the fold. Direct dict write for the fold counter —
        re-entering inc() under the held lock would deadlock."""
        if key in fam or not key or len(fam) < self.label_cardinality_cap:
            return key
        ofam = self._counters.setdefault("evolu_obs_label_overflow_total", {})
        okey: _LabelItems = (("family", name),)
        ofam[okey] = ofam.get(okey, 0) + 1
        return tuple((k, "__overflow__") for k, _v in key)

    def inc(self, name: str, value: float = 1, **labels) -> None:
        if not self.enabled or value == 0:
            return
        key = _label_key(labels)
        with self._lock:
            fam = self._counters.setdefault(name, {})
            key = self._admit(fam, name, key)
            fam[key] = fam.get(key, 0) + value

    def set_gauge(self, name: str, value: float, **labels) -> None:
        if not self.enabled:
            return
        key = _label_key(labels)
        with self._lock:
            fam = self._gauges.setdefault(name, {})
            fam[self._admit(fam, name, key)] = float(value)

    def observe(
        self, name: str, value: float,
        buckets: Optional[Sequence[float]] = None,
        exemplar: Optional[str] = None, **labels,
    ) -> None:
        """Record into a histogram; `buckets` fixes the family's edges
        on first observe (LATENCY_MS_BUCKETS otherwise) and is ignored
        afterwards — exposition shape must not drift per call.
        `exemplar` (a trace id) replaces the series' stored exemplar
        (latest wins)."""
        if not self.enabled:
            return
        key = _label_key(labels)
        with self._lock:
            edges = self._buckets.get(name)
            if edges is None:
                edges = self._buckets[name] = tuple(
                    buckets if buckets is not None else LATENCY_MS_BUCKETS
                )
            fam = self._hists.setdefault(name, {})
            key = self._admit(fam, name, key)
            h = fam.get(key)
            if h is None:
                h = fam[key] = _Hist(len(edges))
            i = _bisect(edges, value)
            h.counts[i] += 1
            h.sum += value
            h.count += 1
            if exemplar is not None:
                h.exemplar = (exemplar, float(value), time.time())

    def describe(self, name: str, help_: str) -> None:
        with self._lock:
            self._help[name] = help_

    # -- read side --

    def get_counter(self, name: str, **labels) -> float:
        with self._lock:
            return self._counters.get(name, {}).get(_label_key(labels), 0)

    def get_gauge(self, name: str, **labels) -> Optional[float]:
        with self._lock:
            return self._gauges.get(name, {}).get(_label_key(labels))

    def get_histogram(self, name: str, **labels):
        """(bucket_edges, cumulative_counts_incl_inf, sum, count) or None."""
        with self._lock:
            h = self._hists.get(name, {}).get(_label_key(labels))
            if h is None:
                return None
            edges = self._buckets[name]
            cum, acc = [], 0
            for c in h.counts:
                acc += c
                cum.append(acc)
            return edges, cum, h.sum, h.count

    def get_exemplar(self, name: str, **labels):
        """(trace_id, value, unix_ts) of a histogram series' latest
        exemplar, or None."""
        with self._lock:
            h = self._hists.get(name, {}).get(_label_key(labels))
            return h.exemplar if h is not None else None

    def quantile(self, name: str, q: float, **labels) -> Optional[float]:
        """Estimate the q-quantile (0..1) from a histogram's log-spaced
        buckets by linear interpolation inside the bucket. Mass in the
        overflow bucket clamps to the TOP FINITE bucket edge — never
        +Inf, even when a caller registered an explicit inf edge or all
        mass sits past the last finite bound (an estimate, not exact;
        dashboards need a plottable number)."""
        got = self.get_histogram(name, **labels)
        if got is None:
            return None
        edges, cum, _s, count = got
        if count == 0:
            return None
        import math

        finite = [e for e in edges if math.isfinite(e)]
        top = float(finite[-1]) if finite else 0.0
        target = q * count
        lo_edge = 0.0
        for i, hi_cum in enumerate(cum):
            if hi_cum >= target:
                if i >= len(edges) or not math.isfinite(edges[i]):
                    return top  # overflow mass (implicit or explicit inf)
                lo_cum = cum[i - 1] if i else 0
                width = hi_cum - lo_cum
                frac = (target - lo_cum) / width if width else 1.0
                return lo_edge + frac * (edges[i] - lo_edge)
            if i < len(edges) and math.isfinite(edges[i]):
                lo_edge = edges[i]
        return top

    def reset(self) -> None:
        with self._lock:
            self._clear_locked()

    def _clear_locked(self) -> None:
        """Clear every family. Caller holds the lock — reset must be
        atomic against concurrent inc/observe, or a racing writer could
        see one family cleared and another not (half-cleared snapshots;
        hammer-tested in tests/test_obs.py)."""
        self._counters.clear()
        self._gauges.clear()
        self._hists.clear()
        # _buckets/_help persist: family shape is configuration,
        # not data — a post-reset observe keeps identical buckets.

    # -- exposition --

    def render_prometheus(self, exemplars: bool = False) -> str:
        """Prometheus text exposition format version 0.0.4. With
        `exemplars=True` the +Inf bucket line of a series carrying an
        exemplar gets the OpenMetrics-style `# {trace_id="..."} v ts`
        suffix — opt-in because 0.0.4 scrapers do not expect it (the
        relay's /metrics default stays plain 0.0.4)."""
        with self._lock:
            lines: List[str] = []
            for name in sorted(self._counters):
                self._head(lines, name, "counter")
                for key, v in sorted(self._counters[name].items()):
                    lines.append(f"{name}{_fmt_labels(key)} {_fmt_num(v)}")
            for name in sorted(self._gauges):
                self._head(lines, name, "gauge")
                for key, v in sorted(self._gauges[name].items()):
                    lines.append(f"{name}{_fmt_labels(key)} {_fmt_num(v)}")
            for name in sorted(self._hists):
                self._head(lines, name, "histogram")
                edges = self._buckets[name]
                for key, h in sorted(self._hists[name].items()):
                    acc = 0
                    for edge, c in zip(edges, h.counts):
                        acc += c
                        le = _fmt_labels(key, f'le="{_fmt_num(edge)}"')
                        lines.append(f"{name}_bucket{le} {acc}")
                    acc += h.counts[-1]
                    le = _fmt_labels(key, 'le="+Inf"')
                    ex = ""
                    if exemplars and h.exemplar is not None:
                        tid, v, ts = h.exemplar
                        ex = (f' # {{trace_id="{_escape(str(tid))}"}} '
                              f"{_fmt_num(v)} {ts:.3f}")
                    lines.append(f"{name}_bucket{le} {acc}{ex}")
                    lines.append(f"{name}_sum{_fmt_labels(key)} {_fmt_num(h.sum)}")
                    lines.append(f"{name}_count{_fmt_labels(key)} {h.count}")
            return "\n".join(lines) + ("\n" if lines else "")

    def _head(self, lines: List[str], name: str, typ: str) -> None:
        help_ = self._help.get(name)
        if help_:
            lines.append(f"# HELP {name} {_escape(help_)}")
        lines.append(f"# TYPE {name} {typ}")

    def snapshot(self, reset: bool = False) -> dict:
        """JSON-ready snapshot of every metric (same data as the text
        exposition, structured). With `reset=True`, the snapshot and
        the clear happen under ONE lock acquisition: an event can land
        either wholly before (in the snapshot) or wholly after (in the
        next window) — never be lost between a separate snapshot() and
        reset() pair (the drain-window contract the baseline-drift and
        ledger tooling rely on; hammer-tested)."""
        with self._lock:
            out: dict = {"counters": {}, "gauges": {}, "histograms": {}}
            for name, fam in self._counters.items():
                out["counters"][name] = [
                    {"labels": dict(k), "value": v} for k, v in sorted(fam.items())
                ]
            for name, fam in self._gauges.items():
                out["gauges"][name] = [
                    {"labels": dict(k), "value": v} for k, v in sorted(fam.items())
                ]
            for name, fam in self._hists.items():
                edges = self._buckets[name]
                out["histograms"][name] = [
                    {
                        "labels": dict(k),
                        "buckets": list(edges),
                        "counts": list(h.counts),
                        "sum": h.sum,
                        "count": h.count,
                        **({"exemplar": list(h.exemplar)}
                           if h.exemplar is not None else {}),
                    }
                    for k, h in sorted(fam.items())
                ]
            if reset:
                self._clear_locked()
            return out

    def snapshot_json(self) -> str:
        return json.dumps(self.snapshot())


# -- build info + process gauges (ISSUE 15 satellite) --

_PROCESS_START = time.time()


def set_build_info(**labels) -> None:
    """Publish `evolu_build_info` — the constant-1 gauge whose LABELS
    carry the facts (version, backend, mesh device count, the
    write-behind/mesh/conn-tier flags): fleet dashboards tell a
    mesh-sharded event-loop relay from a default one by scraping, not
    SSH. Call once per process at server start; last call wins (one
    series — the relay re-publishes on reconfigure)."""
    registry.describe(
        "evolu_build_info",
        "constant 1; labels identify this process's build and topology",
    )
    registry.set_gauge(
        "evolu_build_info", 1, **{k: str(v) for k, v in labels.items()}
    )


def _read_rss_bytes() -> Optional[float]:
    """Current RSS. /proc (exact, Linux) with a getrusage fallback
    (ru_maxrss = peak, close enough where /proc is absent). Never
    raises — a gauge is not worth a failed scrape."""
    try:
        with open("/proc/self/statm", "r") as f:
            fields = f.read().split()
        import os as _os

        return float(fields[1]) * _os.sysconf("SC_PAGE_SIZE")
    except Exception:  # noqa: BLE001 - non-Linux / masked procfs
        try:
            import resource
            import sys as _sys

            peak = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss
            # ru_maxrss units differ by platform: KiB on Linux, BYTES
            # on macOS/BSD — exactly where this fallback actually runs.
            if _sys.platform != "darwin":
                peak *= 1024.0
            return float(peak)
        except Exception:  # noqa: BLE001
            return None


def update_process_gauges() -> None:
    """Refresh `evolu_process_uptime_seconds` / `evolu_process_rss_bytes`
    — called by the relay right before rendering /metrics or /stats so
    scrapes always carry current values without a background thread."""
    registry.set_gauge("evolu_process_uptime_seconds",
                       time.time() - _PROCESS_START)
    rss = _read_rss_bytes()
    if rss is not None:
        registry.set_gauge("evolu_process_rss_bytes", rss)


def _bisect(edges: Sequence[float], value: float) -> int:
    """Index of the first bucket whose upper bound >= value (len(edges)
    = the +Inf bucket). Buckets are short tuples (<= ~24): a linear
    scan beats bisect's call overhead at this size."""
    for i, e in enumerate(edges):
        if value <= e:
            return i
    return len(edges)


# Module-level default registry (the process's metric store — the relay
# endpoint and the JSON snapshot both serve this instance).
registry = MetricsRegistry()

inc = registry.inc
observe = registry.observe
set_gauge = registry.set_gauge
get_counter = registry.get_counter
get_gauge = registry.get_gauge
get_exemplar = registry.get_exemplar
render_prometheus = registry.render_prometheus
snapshot = registry.snapshot
reset = registry.reset
quantile = registry.quantile

# Content-Type for the text exposition endpoint.
PROMETHEUS_CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"


def set_enabled(flag: bool) -> None:
    """Global instrumentation kill switch (bench guard / overhead
    measurement). Disabled = every write is a single attribute check."""
    registry.enabled = bool(flag)
