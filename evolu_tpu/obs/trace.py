"""End-to-end distributed tracing: follow one mutation across
client → relay → batch → engine → replica.

PR 1's metrics/flight-recorder are process-local; PRs 2-9 made a
single mutation cross client backoff/redirect, fleet forwarding,
scheduler micro-batch coalescing, one fused engine pass shared with
strangers, and Merkle gossip to replicas — with no signal tying the
legs together. This module is that signal: a W3C-traceparent-style
context (trace id, span id, deterministic hash-based sampling)
carried on every HTTP hop the system already makes, with spans
recorded in a bounded per-process ring (the flight-recorder shape,
obs/flight.py) and exported three ways:

- `GET /trace/<id>` per relay (server/relay.py): the JSON span tree
  for one trace, including FAN-IN spans that *link* to it (the
  scheduler's one `engine.batch` span serves N request spans from N
  different traces — it links them, it does not parent them);
- a Chrome-trace/perfetto export (`export_chrome`) interleaving host
  spans with the PR-4 `kernel:*` names (utils/log.py `span()` mirrors
  into the active trace, so the fused pass's kernel spans land inside
  the batch span that dispatched them);
- span-derived exemplars on the existing latency histograms
  (obs/metrics.py `observe(..., exemplar=trace_id)`).

Propagation rules (no wire-format change — context rides HTTP headers
only; ciphertext stays opaque; v1/v2 wire bytes untouched):
- the client's sync POST carries the mutation's context
  (runtime/worker.py mints it, sync/client.py sends it);
- `POST /fleet/forward` carries the forwarding relay's server span;
- `POST /replicate/{summary,pull,snapshot*}` carry the gossip round's
  span, whose trace id is the ORIGIN trace id from the write's hint —
  so a fleet-wide "convergence trace" exists: the replica's ingest
  span lands in the same trace the client's mutation started.
- A malformed or oversized incoming `traceparent` is IGNORED (the
  request proceeds untraced) — never a 4xx/5xx (header-fuzz-pinned).

Hard constraints (the PR-1 contract, unchanged): HOST-SIDE ONLY —
this module never imports jax (tests/test_import_hygiene.py), adds
zero ops/pulls to the fused jit graph (tests/test_bench_liveness.py),
and costs ≤1% at 100% sampling (benchmarks/trace_overhead.py).
Sampling is DETERMINISTIC from the trace id alone, so every process
in the fleet makes the same decision with no flag coordination.
"""

from __future__ import annotations

import json
import os
import random as _random
import threading
import time
from collections import deque
from contextlib import contextmanager
from contextvars import ContextVar
from dataclasses import dataclass, field
from typing import Deque, Dict, List, NamedTuple, Optional, Sequence, Tuple

# Span/trace ids come from a private Mersenne generator seeded once
# from the OS — ids need uniqueness and uniformity (the deterministic
# sampler hashes them), not cryptographic strength, and getrandbits is
# ~5× cheaper per span than os.urandom. Instance methods are C-level
# atomic under the GIL, so no per-call lock.
_rng = _random.Random(int.from_bytes(os.urandom(16), "big"))

TRACEPARENT_HEADER = "traceparent"
# Anything longer is ignored outright (header smuggling / fuzz): the
# longest valid version-00 value is 55 chars; future versions may
# append members, so allow modest slack but never unbounded parsing.
TRACEPARENT_MAX_LEN = 128

# One compiled pass over the header (this runs per relay request; the
# split+set-scan form was a third of the whole tracing sequence):
# version, trace id, span id, flags — lowercase hex only, per W3C.
# Version 00 must end exactly at the flags; later versions may append
# "-member" suffixes.
import re as _re

_TRACEPARENT_RE = _re.compile(
    r"\s*([0-9a-f]{2})-([0-9a-f]{32})-([0-9a-f]{16})-([0-9a-f]{2})(-\S*)?\s*\Z"
)


class SpanContext(NamedTuple):
    """What crosses a process boundary: ids + the (deterministic)
    sampling decision. Immutable and cheap to copy between threads
    (the scheduler hands it handler→dispatcher; replication hands it
    handler→gossip loop). A NamedTuple, not a dataclass: one is built
    per span on the request hot path and tuple construction is ~4×
    cheaper than a frozen dataclass's __init__."""

    trace_id: str  # 32 lowercase hex chars, never all-zero
    span_id: str  # 16 lowercase hex chars, never all-zero
    sampled: bool = True


@dataclass
class Span:
    """One finished span in the ring. `links` are (trace_id, span_id)
    pairs for fan-in edges (batch ← requests, gossip round ← extra
    write origins); `tid` is the recording thread (chrome export
    lanes)."""

    trace_id: str
    span_id: str
    parent_id: Optional[str]
    name: str
    t_start: float  # wall-clock epoch seconds
    duration_ms: float
    attrs: Dict[str, object] = field(default_factory=dict)
    links: Tuple[Tuple[str, str], ...] = ()
    tid: int = 0

    def to_json(self) -> dict:
        return {
            "trace_id": self.trace_id,
            "span_id": self.span_id,
            "parent_id": self.parent_id,
            "name": self.name,
            "t_start": self.t_start,
            "duration_ms": self.duration_ms,
            "attrs": self.attrs,
            "links": [list(l) for l in self.links],
        }


def format_traceparent(ctx: SpanContext) -> str:
    """W3C version-00 header value for an outgoing hop."""
    return f"00-{ctx.trace_id}-{ctx.span_id}-{'01' if ctx.sampled else '00'}"


def parse_traceparent(value: Optional[str]) -> Optional[SpanContext]:
    """Strict parse of an INCOMING traceparent. Returns None for
    anything malformed, oversized, all-zero, or absent — the caller
    proceeds untraced; by contract this function never raises (pinned
    by the header-fuzz test: a hostile header must never turn into a
    4xx/5xx or a handler traceback)."""
    if not value or not isinstance(value, str) or len(value) > TRACEPARENT_MAX_LEN:
        return None
    m = _TRACEPARENT_RE.match(value)
    if m is None:
        return None
    version, trace_id, span_id, _flags, extra = m.groups()
    if version == "ff" or (version == "00" and extra is not None):
        return None
    if trace_id == "0" * 32 or span_id == "0" * 16:
        return None
    # The flag records the upstream's decision, but OUR decision is
    # re-derived deterministically from the trace id (same rate ⇒ the
    # whole fleet agrees without trusting the bit).
    return SpanContext(trace_id, span_id, recorder.sampled(trace_id))


class _NoopSpan:
    """Returned when tracing is disabled or the trace is unsampled
    with no context to propagate: every method is a no-op, `context`
    is None (callers emit no header). Singleton — zero per-call
    allocation on the disabled path."""

    __slots__ = ()
    context = None
    trace_id = None

    def set_attr(self, _k, _v) -> None:
        pass

    def add_link(self, _ctx) -> None:
        pass

    def end(self) -> None:
        pass

    def __enter__(self):
        return self

    def __exit__(self, *_exc):
        self.end()
        return False


NOOP_SPAN = _NoopSpan()


class ActiveSpan:
    """A started span. `context` is ready immediately (headers go out
    before the span ends); `end()` records into the ring exactly once.
    Usable as a context manager."""

    __slots__ = ("_rec", "name", "context", "parent_id", "t_start",
                 "_t0", "attrs", "links", "_done")

    def __init__(self, rec: "TraceRecorder", name: str, context: SpanContext,
                 parent_id: Optional[str], links: Tuple[Tuple[str, str], ...],
                 attrs: Optional[dict]):
        self._rec = rec
        self.name = name
        self.context = context
        self.parent_id = parent_id
        self.t_start = time.time()
        self._t0 = time.perf_counter()
        self.attrs = dict(attrs) if attrs else {}
        self.links = list(links)
        self._done = False

    @property
    def trace_id(self) -> str:
        return self.context.trace_id

    def set_attr(self, key: str, value) -> None:
        self.attrs[key] = value

    def add_link(self, ctx: Optional[SpanContext]) -> None:
        if ctx is not None:
            self.links.append((ctx.trace_id, ctx.span_id))

    def end(self) -> None:
        if self._done:
            return
        self._done = True
        self._rec.record(Span(
            trace_id=self.context.trace_id,
            span_id=self.context.span_id,
            parent_id=self.parent_id,
            name=self.name,
            t_start=self.t_start,
            duration_ms=(time.perf_counter() - self._t0) * 1e3,
            attrs=self.attrs,
            links=tuple(self.links),
            tid=threading.get_ident(),
        ))

    def __enter__(self) -> "ActiveSpan":
        return self

    def __exit__(self, exc_type, exc, _tb):
        if exc is not None:
            self.attrs.setdefault("error", repr(exc))
        self.end()
        return False


class TraceRecorder:
    """Bounded per-process span ring + id minting + sampling.

    The ring is the flight-recorder shape (obs/flight.py): a deque
    under a lock, one append per finished span, post-mortem reads scan
    it. Default capacity 4096 spans ≈ a few hundred recent requests —
    `GET /trace/<id>` is a debugging surface for RECENT traffic, not
    long-term storage (ship the chrome export for that)."""

    def __init__(self, capacity: int = 4096):
        self._lock = threading.Lock()
        self._ring: Deque[Span] = deque(maxlen=capacity)
        self.enabled = True
        # 1.0 = trace everything (the measured-≤1% default); the
        # decision is pure in (trace_id, rate): same rate fleet-wide
        # ⇒ same decision fleet-wide. A malformed env value falls back
        # to the default — config must never crash the import (this
        # constructor runs at module import via the singleton below).
        try:
            self.sample_rate = float(os.environ.get("EVOLU_TRACE_SAMPLE", "1.0"))
        except ValueError:
            self.sample_rate = 1.0

    # -- ids / sampling --

    def new_trace_id(self) -> str:
        return f"{_rng.getrandbits(128) or 1:032x}"  # never all-zero

    def new_span_id(self) -> str:
        return f"{_rng.getrandbits(64) or 1:016x}"

    def sampled(self, trace_id: str) -> bool:
        """Deterministic hash-based decision: the top 32 bits of the
        (already uniformly random) trace id against the rate. Every
        process holding the same rate agrees — no flag coordination,
        no per-hop re-rolls."""
        rate = self.sample_rate
        if rate >= 1.0:
            return True
        if rate <= 0.0:
            return False
        try:
            return int(trace_id[:8], 16) < rate * 0x100000000
        except ValueError:
            return False

    # -- span lifecycle --

    def start_span(
        self,
        name: str,
        parent: Optional[SpanContext] = None,
        links: Sequence[Optional[SpanContext]] = (),
        attrs: Optional[dict] = None,
        force_sample: bool = False,
    ):
        """Start a span. With a parent, the span joins the parent's
        trace (and inherits its sampling decision); without one it
        roots a fresh trace. `links` are fan-in edges to OTHER traces
        (None entries are dropped). `force_sample=True` records even
        when the own-trace decision says no — the batch span must
        exist whenever any request span it links is sampled.
        Unsampled spans still carry a real context so downstream hops
        keep making the same deterministic decision."""
        if not self.enabled:
            return NOOP_SPAN
        if parent is not None:
            ctx = SpanContext(parent.trace_id, self.new_span_id(), parent.sampled)
            parent_id = parent.span_id
        else:
            trace_id = self.new_trace_id()
            ctx = SpanContext(trace_id, self.new_span_id(), self.sampled(trace_id))
            parent_id = None
        link_pairs = tuple((l.trace_id, l.span_id) for l in links if l is not None)
        if not ctx.sampled:
            if not force_sample and not any(
                True for l in links if l is not None and l.sampled
            ):
                # Propagate-only: context flows on, nothing lands in
                # the ring (the unsampled fast path is one branch +
                # one tuple).
                return _PropagateOnlySpan(ctx)
            # Recorded despite the own-trace decision (a sampled link
            # or an explicit force): PROMOTE the context, so children
            # opened under it — the engine pass's kernel:* spans —
            # record too, instead of silently vanishing whenever the
            # fan-in span's own fresh trace rolled unsampled.
            ctx = SpanContext(ctx.trace_id, ctx.span_id, True)
        return ActiveSpan(self, name, ctx, parent_id, link_pairs, attrs)

    def record_span(
        self,
        name: str,
        parent: Optional[SpanContext],
        t_start: float,
        duration_ms: float,
        attrs: Optional[dict] = None,
        links: Sequence[Optional[SpanContext]] = (),
    ) -> None:
        """Record an already-measured interval (the scheduler's
        queue-wait is only known at dispatch time). No-op when
        disabled or the parent trace is unsampled."""
        if not self.enabled or parent is None or not parent.sampled:
            return
        self.record(Span(
            trace_id=parent.trace_id,
            span_id=self.new_span_id(),
            parent_id=parent.span_id,
            name=name,
            t_start=t_start,
            duration_ms=duration_ms,
            attrs=dict(attrs) if attrs else {},
            links=tuple((l.trace_id, l.span_id) for l in links if l is not None),
            tid=threading.get_ident(),
        ))

    def record(self, span: Span) -> None:
        if not self.enabled:
            return
        with self._lock:
            self._ring.append(span)

    # -- read side --

    def dump(self) -> List[Span]:
        with self._lock:
            return list(self._ring)

    def size(self) -> int:
        """Spans currently in the ring — O(1), no copy."""
        with self._lock:
            return len(self._ring)

    def spans_for(self, trace_id: str) -> List[Span]:
        """Every span OF the trace plus every span LINKING to it (the
        fan-in engine.batch span lives in its own trace but must show
        up when you ask about the request's)."""
        out = []
        for s in self.dump():
            if s.trace_id == trace_id or any(t == trace_id for t, _ in s.links):
                out.append(s)
        return out

    def recent_trace_ids(self, limit: int = 64) -> List[str]:
        """Most-recent-first distinct trace ids in the ring."""
        seen: List[str] = []
        for s in reversed(self.dump()):
            if s.trace_id not in seen:
                seen.append(s.trace_id)
                if len(seen) >= limit:
                    break
        return seen

    def clear(self) -> None:
        with self._lock:
            self._ring.clear()


class _PropagateOnlySpan:
    """Unsampled but context-carrying: downstream hops still see the
    trace id (and re-derive the same negative decision); nothing is
    recorded. `trace_id` is None like NOOP_SPAN's: exemplars minted
    from `<span>.trace_id` must skip unsampled spans — an exemplar
    pointing at a trace `GET /trace/<id>` can never show (and
    latest-wins overwriting the rare sampled one) would dead-end the
    histogram→trace jump the feature exists for."""

    __slots__ = ("context",)

    def __init__(self, ctx: SpanContext):
        self.context = ctx

    trace_id = None

    def set_attr(self, _k, _v) -> None:
        pass

    def add_link(self, _ctx) -> None:
        pass

    def end(self) -> None:
        pass

    def __enter__(self):
        return self

    def __exit__(self, *_exc):
        return False


# -- ambient context (thread/task-local) --

_current: ContextVar[Optional[SpanContext]] = ContextVar(
    "evolu_trace_ctx", default=None
)


def current() -> Optional[SpanContext]:
    """The ambient span context on this thread (None = untraced)."""
    return _current.get()


@contextmanager
def use(ctx: Optional[SpanContext]):
    """Make `ctx` ambient for the block — what `utils.log.span()`
    mirrors kernel spans under, and what outgoing HTTP hops read."""
    token = _current.set(ctx)
    try:
        yield
    finally:
        _current.reset(token)


def activate(ctx: Optional[SpanContext]):
    """Token form of `use` for call sites whose scope does not nest as
    a `with` block (the relay handler's try/finally). Pair with
    `deactivate(token)`."""
    return _current.set(ctx)


def deactivate(token) -> None:
    _current.reset(token)


def inject_headers(headers: Optional[dict] = None,
                   ctx: Optional[SpanContext] = None) -> Optional[dict]:
    """Add the traceparent header for `ctx` (default: the ambient
    context) to `headers`. Returns the dict unchanged (possibly None)
    when there is nothing to propagate — callers pass the result
    straight to the transport."""
    ctx = ctx if ctx is not None else current()
    if ctx is None or not recorder.enabled:
        return headers
    headers = dict(headers) if headers else {}
    headers[TRACEPARENT_HEADER] = format_traceparent(ctx)
    return headers


# -- exports --


def _build_tree(spans: List[Span], trace_id: str) -> List[dict]:
    """Parent-nest the trace's own spans; linked (fan-in) spans ride
    at top level with `"linked": true` — they belong to another trace
    and have no parent here."""
    own = [s for s in spans if s.trace_id == trace_id]
    linked = [s for s in spans if s.trace_id != trace_id]
    nodes = {s.span_id: {**s.to_json(), "children": []} for s in own}
    roots: List[dict] = []
    for s in sorted(own, key=lambda s: s.t_start):
        node = nodes[s.span_id]
        parent = nodes.get(s.parent_id) if s.parent_id else None
        if parent is not None:
            parent["children"].append(node)
        else:
            roots.append(node)
    for s in sorted(linked, key=lambda s: s.t_start):
        roots.append({**s.to_json(), "linked": True, "children": []})
    return roots


def serve_trace(trace_id: str) -> dict:
    """The GET /trace/<id> payload: flat spans + the nested tree."""
    spans = recorder.spans_for(trace_id)
    return {
        "trace_id": trace_id,
        "span_count": len(spans),
        "spans": [s.to_json() for s in spans],
        "tree": _build_tree(spans, trace_id),
    }


def export_chrome(spans: Optional[List[Span]] = None) -> dict:
    """Chrome-trace ("traceEvents") export of the ring (or a given
    span list): complete ("X") events in microseconds, one lane per
    recording thread. Host spans and the `kernel:*` spans mirrored by
    utils.log.span() interleave on the same timebase, so loading this
    next to a jax.profiler capture lines the names up."""
    spans = recorder.dump() if spans is None else spans
    events = []
    pid = os.getpid()
    for s in spans:
        events.append({
            "name": s.name,
            "cat": "evolu",
            "ph": "X",
            "ts": s.t_start * 1e6,
            "dur": max(s.duration_ms, 0.0) * 1e3,
            "pid": pid,
            "tid": s.tid,
            "args": {
                "trace_id": s.trace_id,
                "span_id": s.span_id,
                **({"parent_id": s.parent_id} if s.parent_id else {}),
                **({"links": [list(l) for l in s.links]} if s.links else {}),
                **s.attrs,
            },
        })
    return {"traceEvents": events, "displayTimeUnit": "ms"}


def write_evidence(label: str, seed=None, extra: Optional[dict] = None) -> str:
    """Seed-replay evidence dump (ROADMAP #5's smallest useful dose):
    write the seed + flight-recorder ring + span export + metrics
    snapshot to a tmp artifact and return its path — the model-check
    episodes print it in the failure message so a failed seed arrives
    with its causal history, not just a stack. NEVER raises: a failing
    dump (full/read-only tmp, unserializable field) must not mask the
    assertion it documents — it returns a `<evidence dump failed…>`
    marker string instead of a path."""
    try:
        import tempfile

        from evolu_tpu.obs import flight, ledger, metrics

        payload = {
            "label": label,
            "seed": seed,
            "written_at": time.time(),
            "flight": [
                {"target": e.target, "message": e.message, "t": e.t,
                 "duration_ms": e.duration_ms,
                 "fields": {k: repr(v) for k, v in e.fields.items()}}
                for e in flight.recorder.dump()
            ],
            "trace": export_chrome(),
            "metrics": metrics.snapshot(),
            # The conservation proof state at failure time: station
            # totals + per-owner sub-ledgers + the audit verdict — a
            # failed episode arrives knowing where every message went.
            "ledger": ledger.snapshot(),
        }
        if extra:
            payload["extra"] = extra
        fd, path = tempfile.mkstemp(
            prefix=f"evolu-evidence-{label}-", suffix=".json"
        )
        with os.fdopen(fd, "w") as f:
            json.dump(payload, f, default=repr)
        return path
    except Exception as e:  # noqa: BLE001 - see docstring
        return f"<evidence dump failed: {e!r}>"


# Module-level default recorder, like obs.metrics.registry and
# obs.flight.recorder — the process's one span store; the relay's
# /trace endpoint serves this instance.
recorder = TraceRecorder()

start_span = recorder.start_span
record_span = recorder.record_span
spans_for = recorder.spans_for
clear = recorder.clear


def set_enabled(flag: bool) -> None:
    """Tracing kill switch (bench guard / overhead measurement): when
    off, start_span returns the no-op singleton and parse/inject
    short-circuit."""
    recorder.enabled = bool(flag)


def set_sample_rate(rate: float) -> None:
    recorder.sample_rate = float(rate)
