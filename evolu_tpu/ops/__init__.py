"""Device (JAX/TPU) kernels for the CRDT hot paths.

These kernels replace the reference's per-message loops (reference:
packages/evolu/src/applyMessages.ts:78, merkleTree.ts:31-50) with
columnar batch pipelines:

- `hash`    — vmapped murmur3-32 over fixed-width timestamp strings.
- `encode`  — on-device canonical timestamp rendering + packed sort keys.
- `merge`   — radix-style sort + segmented prefix-max LWW planner.
- `merkle_ops` — batched minute-key XOR deltas.

HLC millis are 48-bit, so the kernels need 64-bit integer types. Public
entry points enter `jax.experimental.enable_x64` per call (see
`with_x64`) instead of flipping the process-global x64 flag — importing
this package must not change dtype semantics for the host application's
own JAX code. Pass numpy arrays across the host↔device boundary; the
wrappers convert inside the x64 scope so 64-bit dtypes survive.
"""

import functools
import os

import jax

# jax-version compat: the x64 config context manager is spelled
# `jax.enable_x64` on newer jax but still lives at
# `jax.experimental.enable_x64` on the 0.4.x line this container
# ships. Every kernel entry point (and the test suite) uses the
# `jax.enable_x64` spelling; alias it once here — this package is the
# first evolu_tpu import on every device-side path.
if not hasattr(jax, "enable_x64"):
    from jax.experimental import enable_x64 as _compat_enable_x64

    jax.enable_x64 = _compat_enable_x64

# Same story for shard_map: newer jax exports it at the top level and
# names the replication-check kwarg `check_vma`; the 0.4.x line has it
# under jax.experimental with the kwarg named `check_rep`. Callers
# import THIS symbol and always write `check_vma=`.
try:
    from jax import shard_map as _jax_shard_map

    _SHARD_MAP_CHECK_KW = "check_vma"
except ImportError:  # jax 0.4.x
    from jax.experimental.shard_map import shard_map as _jax_shard_map

    _SHARD_MAP_CHECK_KW = "check_rep"


def shard_map(f, **kwargs):
    """Version-portable `jax.shard_map` (see the compat block above)."""
    if "check_vma" in kwargs and _SHARD_MAP_CHECK_KW != "check_vma":
        kwargs[_SHARD_MAP_CHECK_KW] = kwargs.pop("check_vma")
    return _jax_shard_map(f, **kwargs)

# Cold-start relief: kernels compile once per power-of-two bucket; a
# persistent compilation cache makes that a per-machine (not
# per-process) cost. Only set when the embedder hasn't configured one,
# and never under a remote-compile tunnel — artifacts built by the
# remote helper carry its machine features, and loading them on this
# host risks SIGILL (XLA warns "machine type ... doesn't match").
if (
    jax.config.jax_compilation_cache_dir is None
    and "JAX_COMPILATION_CACHE_DIR" not in os.environ
    and os.environ.get("PALLAS_AXON_REMOTE_COMPILE") != "1"
):
    _cache = os.path.join(os.path.expanduser("~"), ".cache", "evolu_tpu", "jax")
    try:
        os.makedirs(_cache, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", _cache)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
    except OSError:
        pass  # read-only home: stay with in-memory compilation only


def with_x64(fn):
    """Run `fn` under the `jax.enable_x64(True)` config scope.

    Applied to every public kernel entry point: tracing (and jit cache
    keying) happens under the x64 config, so 64-bit HLC keys keep their
    width regardless of the embedding application's global setting.
    """

    @functools.wraps(fn)
    def wrapper(*args, **kwargs):
        with jax.enable_x64(True):
            return fn(*args, **kwargs)

    return wrapper


def to_host(x):
    """Device array → numpy for 1-D kernel outputs.

    On a multi-device sharded array, `np.asarray` builds and runs an
    XLA gather program per call (~10× slower than the raw copies); this
    instead copies each addressable shard and concatenates in index
    order. Falls back to `np.asarray` for anything that isn't a plain
    axis-0-sharded 1-D array (replicated outputs, numpy inputs)."""
    import numpy as np

    shards = getattr(x, "addressable_shards", None)
    if not shards or len(shards) <= 1 or getattr(x, "ndim", 0) != 1:
        return np.asarray(x)
    pairs = [(s.index[0].start or 0, s.data) for s in shards]
    if len({p[0] for p in pairs}) != len(pairs):  # replicated, not sharded
        return np.asarray(x)
    pairs.sort(key=lambda p: p[0])
    return np.concatenate([np.asarray(d) for _, d in pairs])


def to_host_many(*xs):
    """Batched device→host pull: start EVERY copy asynchronously first,
    then materialize — one transfer wave instead of one blocking
    round-trip per array. Under a tunneled device (axon) each blocking
    pull pays full RTT, so fetching a kernel's 7-9 outputs one by one
    costs ~7-9× RTT; this brings it down to ~1×. Per-array conversion
    still goes through `to_host` (sharded-aware). Returns a tuple in
    input order; numpy inputs pass through.

    Instrumented (ISSUE 15 satellite): pull bytes / wave-size histogram
    / pull-seconds counter turn the ~12-17MB/s tunnel device-leg wall
    (CLAUDE.md) into a live gauge-derived MB/s. The instrumentation is
    HOST-side, after the pull materialized — it reads `.nbytes` off the
    returned numpy arrays, never touches the traced graph, and costs a
    few dict ops per WAVE (checksum + jit-cache invariance pinned by
    tests/test_bench_liveness.py)."""
    import time as _time

    from evolu_tpu.obs import anatomy as _anatomy
    from evolu_tpu.obs import metrics as _metrics

    t0 = _time.perf_counter()
    out = tuple(to_host(x) for x in start_host_transfer(*xs))
    if _metrics.registry.enabled:
        dt = _time.perf_counter() - t0
        wave_bytes = sum(int(getattr(a, "nbytes", 0)) for a in out)
        _metrics.inc("evolu_pull_bytes_total", wave_bytes)
        _metrics.inc("evolu_pull_seconds_total", dt)
        _metrics.observe("evolu_pull_wave_bytes", wave_bytes,
                         buckets=_metrics.SIZE_BUCKETS)
        # Stage-anatomy fold (ISSUE 16): every wave is one pull_wave
        # stage record, priced against the tunnel bandwidth law — the
        # over-floor flag fires when a wave runs slower than
        # FLOOR_FACTOR× the recorded MB/s for this platform.
        _anatomy.record_stage("pull_wave", dt, nbytes=wave_bytes)
    return out


def start_host_transfer(*xs):
    """The async-start half of `to_host_many`, for pipelining: begin
    every device→host copy NOW and return the arrays untouched; a later
    `to_host_many` on them materializes mostly-finished copies. (Under
    a tunneled device copy_to_host_async can be a no-op; callers that
    need REAL overlap there park the blocking pull on a thread.)"""
    for x in xs:
        shards = getattr(x, "addressable_shards", None)
        if shards:
            for s in shards:
                try:
                    s.data.copy_to_host_async()
                except AttributeError:
                    break
        else:
            try:
                x.copy_to_host_async()
            except AttributeError:
                pass
    return xs


def bucket_size(n: int, multiple: int = 64) -> int:
    """Power-of-two batch bucket ≥ max(n, multiple). One policy for
    every host→device batch (SURVEY.md §7 "dynamic shapes": pad to
    pow2 buckets so jit compiles once per bucket, not per batch)."""
    size = multiple
    while size < n:
        size *= 2
    return size
