"""Device kernels for the RGA list linearization (ISSUE 14).

Host oracle: `core/crdt_list.py::linearize` / `fold_cell` — everything
here is pinned bit-identical to it (tests/test_crdt_list.py, incl.
Pallas interpret mode).

RGA linearization is a parent-pointer ordering problem: each element
names the element it was inserted after, siblings order by DESCENDING
timestamp rank, and the document order is the DFS of that forest. Done
naively that is a sequential replay; here it is the classic
**Euler-tour list-ranking** factorization, built entirely from the
recorded-cost-model primitives:

1. **One global `lax.sort`** on a packed i64 key — group(cell) |
   parent | descending-rank (the r5 spare-key-bits trick; same layout
   discipline as `merge.plan_merge_sorted_core`) — groups every
   (cell, parent) sibling run; first/last/prev-sibling pointers fall
   out of segment adjacency with three scatters.
2. Each element contributes a **down** edge (enter) and an **up** edge
   (leave); the tour PREDECESSOR of every edge is a local function of
   (prev-sibling, parent, last-child) — no walk. Pointer-jumping over
   the predecessor chain (log2(2N) gathers — gathers are ~4× a sort
   per the v5e law, but there are only ~21 of them and each is i32)
   accumulates the count of down-edges strictly before each element's
   down edge = its document position, tombstones included.
3. A second sort by (cell, position) + the **segmented sum scan** from
   the shared machinery (`crdt_merge.segmented_sum_scan`: blocked
   two-level XLA on CPU, single-pass Pallas
   `pallas_scan.segmented_sum_scan_pallas` on TPU silicon) turns alive
   flags into per-cell output slots, so the host materializer places
   values without re-sorting anything.

Bounds: the batch core packs cell(22) | parent+1(20) | rank(20) into
one positive i64, so N ≤ 2^20-2 elements and ≤ 2^22-2 cells per
dispatch; the reconcile-shaped shard core reuses the SHARED
`reconcile.pack_owner_cell_key` owner|cell layout (37 group bits) and
therefore bounds its per-shard batch at 2^13-2. The host wrapper and
`crdt_list.materialize_list_values` route anything beyond the bounds
to the host oracle BEFORE any side effect (the r5 contract).

Everything traces under enable_x64(True) (i64 packed keys) and pads to
power-of-two buckets (no per-batch recompiles).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from evolu_tpu.ops import bucket_size, to_host_many, with_x64
from evolu_tpu.utils.log import span

_B = 20  # parent / rank field width in the batch core's packed key
_PAD_LIST_CELL = (1 << 22) - 1  # pad sentinel: sorts after every real cell
_SHARD_B = 13  # per-field width under the 37-bit owner|cell group


def _rga_positions(group, parent_ix, b_bits: int):
    """Shared core: document position (0-based, within each group's
    tree, tombstones included) per element.

    `group` int64 (cell id, or the packed owner|cell composite),
    `parent_ix` int32 index into these same arrays (-1 = head/root —
    the wrapper resolved dangling origins already), `b_bits` the
    packed-key field width (elements and parent+1 must fit it).
    PRECONDITION (wrapper-enforced): elements arrive sorted ascending
    by (group, tag), so the array index IS the timestamp rank and
    parent_ix < own index for every non-root element."""
    n = group.shape[0]
    idx = jnp.arange(n, dtype=jnp.int32)
    key = (
        (group << jnp.int64(2 * b_bits))
        | ((parent_ix + 1).astype(jnp.int64) << jnp.int64(b_bits))
        | (jnp.int64(n - 1) - idx.astype(jnp.int64))
    )
    if key.dtype != jnp.dtype("int64"):  # x64 disabled: would mis-order
        raise TypeError(
            "rga linearization must be traced under enable_x64(True): "
            f"packed key degraded to {key.dtype}"
        )
    # Sort → (group, parent) sibling runs in DESCENDING rank order.
    key_s, e_s = jax.lax.sort((key, idx), num_keys=1, is_stable=False)
    seg = key_s >> jnp.int64(b_bits)  # group|parent bits
    parent_s = (
        (key_s >> jnp.int64(b_bits)) & jnp.int64((1 << b_bits) - 1)
    ).astype(jnp.int32) - 1
    seg_start = jnp.concatenate([jnp.ones((1,), bool), seg[1:] != seg[:-1]])
    seg_end = jnp.concatenate([seg_start[1:], jnp.ones((1,), bool)])

    # Sibling pointers from segment adjacency (scatters; pad and root
    # segments carry parent −1 and dump on the out-of-range slot).
    last_child = jnp.full(n, -1, jnp.int32).at[
        jnp.where(seg_end & (parent_s >= 0), parent_s, jnp.int32(n))
    ].set(e_s, mode="drop")
    prev_sib = jnp.full(n, -1, jnp.int32).at[e_s[1:]].set(
        jnp.where(seg[1:] == seg[:-1], e_s[:-1], jnp.int32(-1))
    )

    # Euler-tour PREDECESSOR per edge (down = 2x enters x, up = 2x+1
    # leaves x); the chain of each tree ends (TERM) at the down edge of
    # the head element (first root child, no previous sibling).
    m = 2 * n  # TERM sentinel index
    pred_down = jnp.where(
        prev_sib >= 0,
        2 * prev_sib + 1,
        jnp.where(parent_ix >= 0, 2 * parent_ix, jnp.int32(m)),
    )
    pred_up = jnp.where(last_child >= 0, 2 * last_child + 1, 2 * idx)
    pred = jnp.concatenate(
        [jnp.stack([pred_down, pred_up], axis=1).reshape(m), jnp.full((1,), m, jnp.int32)]
    )  # index m = TERM self-loop
    weight = jnp.concatenate(
        [
            jnp.tile(jnp.array([1, 0], jnp.int32), n),  # down edges count
            jnp.zeros((1,), jnp.int32),
        ]
    )

    # Pointer jumping: wdist[i] = Σ weight over edges STRICTLY before i
    # on its chain → at a down edge, the element's document position.
    def body(_i, carry):
        p, w = carry
        return p[p], w + w[p]

    jumps = max(1, int(m).bit_length() + 1)
    pred, wdist = jax.lax.fori_loop(0, jumps, body, (pred, weight[pred]))
    return wdist[2 * idx]


def _alive_slots(group, pos, alive, b_bits: int, scan, interpret: bool):
    """Second stage: per-group output slot for every ALIVE element
    (dead elements get −1) via sort-by-(group, pos) + the segmented
    sum scan — the machinery the host materializer consumes directly."""
    n = group.shape[0]
    idx = jnp.arange(n, dtype=jnp.int32)
    key2 = (group << jnp.int64(b_bits)) | pos.astype(jnp.int64)
    key2_s, e2_s, alive_s = jax.lax.sort(
        (key2, idx, alive.astype(jnp.int32)), num_keys=1, is_stable=False
    )
    g2 = key2_s >> jnp.int64(b_bits)
    cstart = jnp.concatenate([jnp.ones((1,), bool), g2[1:] != g2[:-1]])
    if interpret:
        from evolu_tpu.ops.pallas_scan import segmented_sum_scan_pallas

        incl = segmented_sum_scan_pallas(
            cstart, alive_s.astype(jnp.uint64), interpret=True
        )
    else:
        incl = scan(cstart, alive_s.astype(jnp.uint64))
    slot_s = jnp.where(alive_s > 0, incl.astype(jnp.int32) - 1, jnp.int32(-1))
    return jnp.zeros(n, jnp.int32).at[e2_s].set(slot_s)


@functools.partial(jax.jit, static_argnames=("interpret_pallas",))
def rga_order_core(cell_id, parent_ix, alive, interpret_pallas: bool = False):
    """Traceable batch core: → (pos, slot) int32 arrays. `cell_id`
    int32 (< 2^22-1; pad rows use _PAD_LIST_CELL), `parent_ix` int32
    (-1 = head; pad rows -1), `alive` int32 0/1. Pad rows form their
    own sibling chain under the sentinel cell and never collide with a
    real group. Must trace under enable_x64(True).

    `interpret_pallas=True` forces the alive-slot scan through the
    Pallas kernel in interpret mode — the bit-identity test hook (the
    production path routes via `crdt_merge.segmented_sum_scan`)."""
    from evolu_tpu.ops.crdt_merge import segmented_sum_scan

    group = cell_id.astype(jnp.int64)
    pos = _rga_positions(group, parent_ix, _B)
    slot = _alive_slots(group, pos, alive, _B, segmented_sum_scan,
                        interpret_pallas)
    return pos, slot


@with_x64
def rga_order(cell_id: np.ndarray, parent_ix: np.ndarray, alive: np.ndarray,
              interpret_pallas: bool = False):
    """Host entry: → (pos, slot) numpy int32 arrays, bit-identical to
    the host oracle (`crdt_list.fold_cell`) per cell. Elements MUST be
    sorted ascending by (cell, tag) with parent indices resolved
    against that order (`crdt_list._materialize_device` builds exactly
    this layout). Batches beyond the packed-key bounds raise — callers
    route those to the host oracle instead."""
    from evolu_tpu.core.crdt_list import DEVICE_MAX_CELLS, DEVICE_MAX_ELEMS

    n = len(cell_id)
    if n == 0:
        z = np.zeros(0, np.int32)
        return z, z.copy()
    if n > DEVICE_MAX_ELEMS:
        raise ValueError(f"batch of {n} elements exceeds the packed-key bound")
    if int(np.max(cell_id)) > DEVICE_MAX_CELLS:
        raise ValueError("cell id exceeds the packed-key bound")
    with span("kernel:crdt_list", "rga_order", n=n):
        size = bucket_size(n)
        c_p = np.concatenate(
            [cell_id.astype(np.int32),
             np.full(size - n, _PAD_LIST_CELL, np.int32)]
        )
        p_p = np.concatenate(
            [parent_ix.astype(np.int32), np.full(size - n, -1, np.int32)]
        )
        a_p = np.concatenate(
            [alive.astype(np.int32), np.zeros(size - n, np.int32)]
        )
        pos, slot = to_host_many(*rga_order_core(
            jnp.asarray(c_p), jnp.asarray(p_p), jnp.asarray(a_p),
            interpret_pallas=interpret_pallas,
        ))
        return pos[:n], slot[:n]


# --- sharded (owner, cell) linearization — the reconcile-shaped form ---


def list_shard_order_core(owner_ix, cell_id, parent_ix, alive):
    """Per-shard RGA linearization for the multi-owner reconcile shape
    (`parallel.reconcile`): elements group by the SAME packed
    owner|cell i64 layout as the LWW shard kernel and the counter fold
    (`pack_owner_cell_key`, idx/lo zeroed — only the 37 group bits are
    used), so the (owner, cell) grouping contract can never drift
    between the planners and this kernel. The remaining 26 key bits
    split 13/13 between parent and rank, bounding a shard dispatch at
    2^13-2 elements — wider batches route to the host oracle. Returns
    (pos, slot) in shard-local order; owners are never split across
    shards, so local trees are globally complete. Must trace under
    enable_x64(True); callers wrap in shard_map over the owners axis."""
    from evolu_tpu.ops.crdt_merge import segmented_sum_scan
    from evolu_tpu.parallel.reconcile import pack_owner_cell_key

    n = cell_id.shape[0]
    zeros = jnp.zeros(n, jnp.int32)
    group = pack_owner_cell_key(owner_ix, cell_id, zeros, lo_bits=0) >> jnp.int64(24)
    pos = _rga_positions(group, parent_ix, _SHARD_B)
    slot = _alive_slots(group, pos, alive, _SHARD_B, segmented_sum_scan, False)
    return pos, slot
