"""Device kernels for the typed CRDT column folds (ISSUE 7).

Host oracle: `core/crdt_types.py` — everything here is pinned
bit-identical to it on property-sampled op logs (tests/test_crdt_types.py).

**PN-counter** — a segmented SUM over cell-grouped ops, the add-monoid
twin of the LWW planner's segmented lex-max: ONE packed i64 sort key
(cell << 24 | idx, same layout and 2^24 bound as
`merge.plan_merge_sorted_core`), then an inclusive segmented sum scan
whose per-segment total lands at the segment-end row and scatters into
a dense per-cell table. The scan uses the same blocked two-level XLA
formulation as `merge._segmented_max_scan` (the recorded cost model:
generic `associative_scan` ~5 ms/scan at 1M) and hands off to the
single-pass Pallas kernel (`pallas_scan.segmented_sum_scan_pallas`,
u32 hi/lo limb carry) on TPU silicon — exact because pos/neg partial
sums are non-negative and bounded by 2^24 ops × 2^31 < 2^55 per cell.

**AW-set** — the order-free membership fold the PR-4 scatter plan
serves WITHOUT the LWW duplicate-screen caveat: `killed[tag] |= 1` per
kill op and `pair_alive[pair] |= alive[add]` per add op are idempotent
OR-folds, so arbitrary duplicates and arbitrary order produce the same
table — no sorted-hash admission screen, no host-side exactness
boundary. Adopted on both backends; on TPU the recorded v5e law still
prices XLA's serialized scatter above a sort for 1M-row batches, which
`benchmarks/crdt_types.py` records honestly.

Everything traces under enable_x64(True) (i64 packed keys / u64 sums)
and pads to power-of-two buckets (no per-batch recompiles).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from evolu_tpu.ops import bucket_size, to_host_many, with_x64
from evolu_tpu.ops.merge import _PAD_CELL, _SCAN_BLOCK, _use_pallas_scan
from evolu_tpu.utils.log import span


# --- segmented sum scan (the add-monoid twin of _segmented_max_scan) ---


def _seg_sum_combine(left, right):
    """Segmented-sum monoid on (flag, value): the operand nearest the
    scan head wins outright when flagged, else values add."""
    lf, lv = left
    rf, rv = right
    return lf | rf, jnp.where(rf, rv, lv + rv)


def _segmented_sum_scan_reference(flags, vals):
    """Inclusive segmented sum via jax.lax.associative_scan — the
    semantics reference and the fallback for lengths the blocked
    variant cannot tile."""
    _, out = jax.lax.associative_scan(_seg_sum_combine, (flags, vals))
    return out


def segmented_sum_scan(flags, vals):
    """Inclusive segmented sum, blocked two-level formulation (mirrors
    `merge._segmented_max_scan`: log2(L) shifted elementwise passes over
    an (N/L, L) view + one tiny cross-block scan + a carry broadcast).
    `vals` is uint64; flags[i] marks a segment start. On TPU silicon
    with a big-enough batch the single-pass Pallas kernel takes over
    (same routing rule as the lex-max scan)."""
    n = flags.shape[0]
    if n >= (1 << 15) and _use_pallas_scan():
        from evolu_tpu.ops.pallas_scan import segmented_sum_scan_pallas

        return segmented_sum_scan_pallas(flags, vals)
    L = min(_SCAN_BLOCK, n)
    if n == 0 or n % L:
        return _segmented_sum_scan_reference(flags, vals)
    s_f = flags.reshape(-1, L)
    s_v = vals.reshape(-1, L)
    shift = 1
    while shift < L:
        pf = jnp.pad(s_f[:, :-shift], ((0, 0), (shift, 0)), constant_values=False)
        pv = jnp.pad(s_v[:, :-shift], ((0, 0), (shift, 0)))
        s_v = jnp.where(s_f, s_v, pv + s_v)
        s_f = s_f | pf
        shift *= 2
    _, carry = jax.lax.associative_scan(_seg_sum_combine, (s_f[:, -1], s_v[:, -1]))
    zero = jnp.zeros((), vals.dtype)
    excl = jnp.concatenate([zero[None], carry[:-1]])
    out = jnp.where(s_f, s_v, excl[:, None] + s_v)
    return out.reshape(n)


# --- PN-counter: per-cell (pos, neg) sums ---


@functools.partial(jax.jit, static_argnames=("table_size",))
def pn_counter_sums_core(cell_id, delta, table_size):
    """Traceable core: cell-grouped segmented sums of the positive and
    negative delta parts, scattered into a (table_size,) dense pair of
    u64 tables (slot = cell id; pad rows park on the out-of-range dump
    slot). `cell_id` int32 with _PAD_CELL padding, `delta` int64,
    n ≤ 2^24 (the packed-key bound — the host wrapper chunks above it).
    Must trace under enable_x64(True) (guarded like the merge cores)."""
    n = cell_id.shape[0]
    idx = jnp.arange(n, dtype=jnp.int32)
    key = (cell_id.astype(jnp.int64) << jnp.int64(24)) | idx.astype(jnp.int64)
    if key.dtype != jnp.dtype("int64"):  # x64 disabled: would mis-group
        raise TypeError(
            "pn_counter_sums_core must be traced under enable_x64(True): "
            f"packed key degraded to {key.dtype}"
        )
    key_s, d_s = jax.lax.sort((key, delta), num_keys=1, is_stable=False)
    c_s = (key_s >> jnp.int64(24)).astype(jnp.int32)
    seg_start = jnp.concatenate([jnp.ones((1,), bool), c_s[1:] != c_s[:-1]])
    pos = jnp.where(d_s > 0, d_s, 0).astype(jnp.uint64)
    neg = jnp.where(d_s < 0, -d_s, 0).astype(jnp.uint64)
    pos_sum = segmented_sum_scan(seg_start, pos)
    neg_sum = segmented_sum_scan(seg_start, neg)
    seg_end = jnp.concatenate([seg_start[1:], jnp.ones((1,), bool)])
    real = c_s != _PAD_CELL
    tgt = jnp.where(seg_end & real, c_s, jnp.int32(table_size))
    pos_t = jnp.zeros(table_size, jnp.uint64).at[tgt].set(pos_sum, mode="drop")
    neg_t = jnp.zeros(table_size, jnp.uint64).at[tgt].set(neg_sum, mode="drop")
    return pos_t, neg_t


@with_x64
def pn_counter_sums(cell_id: np.ndarray, delta: np.ndarray, num_cells: int):
    """Host entry: → (pos, neg) int64 numpy arrays of length num_cells,
    bit-identical to `crdt_types.fold_counter_ops` per cell. Batches
    beyond the 2^24 packed-key bound fold in chunks — the sum monoid is
    associative/commutative, so chunked accumulation is exact."""
    n = len(cell_id)
    if n == 0:
        z = np.zeros(num_cells, np.int64)
        return z, z.copy()
    with span("kernel:crdt", "pn_counter_sums", n=n, cells=num_cells):
        table = bucket_size(max(num_cells, 1))
        pos = np.zeros(table, np.uint64)
        neg = np.zeros(table, np.uint64)
        chunk = 1 << 24
        for i in range(0, n, chunk):
            c = cell_id[i : i + chunk]
            d = delta[i : i + chunk]
            size = bucket_size(len(c))
            c_p = np.concatenate(
                [c.astype(np.int32), np.full(size - len(c), int(_PAD_CELL), np.int32)]
            )
            d_p = np.concatenate([d.astype(np.int64), np.zeros(size - len(d), np.int64)])
            p_t, n_t = to_host_many(*pn_counter_sums_core(
                jnp.asarray(c_p), jnp.asarray(d_p), table_size=table
            ))
            pos += p_t
            neg += n_t
        return pos[:num_cells].astype(np.int64), neg[:num_cells].astype(np.int64)


# --- AW-set: the order-free membership fold ---


@functools.partial(jax.jit, static_argnames=("num_tags",))
def _killed_table_core(kill_ids, num_tags):
    """Idempotent scatter-OR: killed[tag] = any kill op names it. Pad
    rows target the dump slot."""
    return (
        jnp.zeros(num_tags + 1, jnp.int32).at[kill_ids].max(1, mode="drop")[:num_tags]
    )


@functools.partial(jax.jit, static_argnames=("num_pairs",))
def awset_pair_alive_core(pair_id, alive, num_pairs):
    """Per-(cell, elem) membership: pair_alive[p] = OR over its adds'
    alive flags — order-free, duplicate-safe (the scatter shape with no
    LWW caveat). Pad rows use pair_id = num_pairs (dump)."""
    return (
        jnp.zeros(num_pairs + 1, jnp.int32)
        .at[pair_id]
        .max(alive.astype(jnp.int32), mode="drop")[:num_pairs]
    )


def awset_alive_flags(add_tags, kills, state_killed):
    """Device twin of `crdt_types.alive_add_flags`: membership via a
    dense killed-tag table (host interning + one scatter + one gather)
    instead of Python set probes. → list[bool], bit-identical."""
    n = len(add_tags)
    if n == 0:
        return []
    with span("kernel:crdt", "awset_alive_flags", n=n):
        kill_list = [t for t in kills if t is not None]
        kill_list.extend(state_killed)
        universe, inverse = np.unique(
            np.array(list(add_tags) + kill_list, dtype=object), return_inverse=True
        )
        num_tags = len(universe)
        add_ids = inverse[:n].astype(np.int32)
        kill_ids = inverse[n:].astype(np.int32)
        size = bucket_size(max(len(kill_ids), 1), multiple=16)
        kill_p = np.concatenate(
            [kill_ids, np.full(size - len(kill_ids), num_tags, np.int32)]
        )
        killed = np.asarray(_killed_table_core(jnp.asarray(kill_p), num_tags=num_tags))
        return [not bool(killed[i]) for i in add_ids]


def awset_membership(pair_id: np.ndarray, alive: np.ndarray, num_pairs: int):
    """Host entry for the per-(cell, elem) fold: → int32 numpy 0/1 of
    length num_pairs. Used by the bench and the rebuild path; the
    incremental apply stores per-add alive rows and lets SQL DISTINCT
    do the membership."""
    n = len(pair_id)
    if n == 0:
        return np.zeros(num_pairs, np.int32)
    size = bucket_size(n)
    p_p = np.concatenate(
        [pair_id.astype(np.int32), np.full(size - n, num_pairs, np.int32)]
    )
    a_p = np.concatenate([alive.astype(np.int32), np.zeros(size - n, np.int32)])
    out = awset_pair_alive_core(jnp.asarray(p_p), jnp.asarray(a_p), num_pairs=num_pairs)
    return np.asarray(out)


# --- sharded (owner, cell) counter sums — the reconcile-shaped fold ---


def counter_shard_sums_core(owner_ix, cell_id, delta):
    """Per-shard typed fold for the multi-owner reconcile shape
    (`parallel.reconcile`): ops group by the SAME packed owner|cell|idx
    i64 sort key as the LWW shard kernel (`pack_owner_cell_key`,
    lo_bits=0 — the sum monoid needs no stored-winner flag bits), then
    the segmented sums run per (owner, cell) segment. Returns the
    sorted group keys, segment-end mask, and inclusive pos/neg sums —
    the per-cell totals sit at seg-end rows, and every output feeds the
    bench's checksum carry (tests/test_bench_liveness.py discipline).
    Must trace under enable_x64(True); callers wrap in shard_map over
    the owners axis (owners are never split across shards, so local
    segments are globally complete)."""
    from evolu_tpu.parallel.reconcile import pack_owner_cell_key

    n = cell_id.shape[0]
    idx = jnp.arange(n, dtype=jnp.int32)
    key = pack_owner_cell_key(owner_ix, cell_id, idx, lo_bits=0)
    key_s, d_s = jax.lax.sort((key, delta), num_keys=1, is_stable=False)
    grp = key_s >> jnp.int64(24)  # owner|cell bits above idx
    seg_start = jnp.concatenate([jnp.ones((1,), bool), grp[1:] != grp[:-1]])
    pos = jnp.where(d_s > 0, d_s, 0).astype(jnp.uint64)
    neg = jnp.where(d_s < 0, -d_s, 0).astype(jnp.uint64)
    pos_sum = segmented_sum_scan(seg_start, pos)
    neg_sum = segmented_sum_scan(seg_start, neg)
    seg_end = jnp.concatenate([seg_start[1:], jnp.ones((1,), bool)])
    return grp, seg_end, pos_sum, neg_sum
