"""Device kernels for the tensor-CRDT fold (ISSUE 20).

Host oracle: `core/crdt_tensor.py` — everything here is pinned
bit-identical to it (tests/test_crdt_tensor.py + goldens that are
never updated). The merge IS a batched segmented reduction over the
machinery `pallas_scan` already has: blocked two-level XLA on CPU,
single-pass Pallas on TPU silicon, interpret-mode parity pinned.

**Why bit-identity is unconditional:** the host pre-masks the
semidirect composition (base selection + delta shadowing are
raw-string timestamp work — the device never sees a timestamp) and
hands the kernel MODULAR uint64 contributions (sum/mean: fixed-point
q·count on the 2^-16 lattice; max: monotone u32 keys zero-extended).
Modular add and integer max are exactly associative AND commutative,
so scan order, blocking, chunk boundaries and Pallas-vs-XLA routing
cannot move a single bit — unlike a float fold, which could never
clear the any-permutation acceptance bar.

**Layout (the per-payload-width cost call):** the recorded v5e law
prices `lax.sort` ~0.75 ms per extra u64 payload at 1M — carrying a
`width`-element cell through the sort as payloads would cost
O(width) sorts. Instead the ONE packed i64 key (cell << 24 | idx,
the `plan_merge_sorted_core` layout) sorts alone, a single row-gather
`contrib[i_s]` recovers the (n, width) matrix (one gather ≈ 4 sorts,
amortized over the whole width), and the scan runs over the d-major
FLATTENED (width·n,) view with tiled segment flags — every element
column auto-starts a segment at its own offset, so per-cell-per-
element totals fall out of ONE scan pass regardless of width.

Shard shape: `tensor_shard_sums_core` groups by the SAME
`reconcile.pack_owner_cell_key` packed layout as the LWW and counter
shard kernels (lo_bits=0), and payloads this wide finally exercise
the WIDE fallback (`tensor_shard_sums_wide_core`: owner rides as a
sort payload, cells < 2^31) at production shapes — routing is static
host-maxima, mirrored from `reconcile.shard_kernel_for` and counted
under `evolu_crdt_tensor_kernel_total{variant=packed|wide}`.

Everything traces under enable_x64(True) (i64 keys / u64 lattice)
and pads to power-of-two buckets; `width` and `monoid` are static
per COLUMN (schema constants), so the jit cache stays flat within
batch buckets with tensor traffic hot (fenced by the sentinel test).
"""

from __future__ import annotations

import functools
from typing import Dict, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from evolu_tpu.obs import metrics
from evolu_tpu.ops import bucket_size, to_host, with_x64
from evolu_tpu.ops.crdt_merge import segmented_sum_scan
from evolu_tpu.ops.merge import _PAD_CELL, _segmented_max_scan
from evolu_tpu.utils.log import span


def _flat_segmented_fold(c_s, v_s, monoid: str):
    """(n,) sorted cell ids + (n, width) gathered contributions → the
    inclusive segmented fold over the d-major flattened view. Returns
    (agg_flat (width·n,), seg_start, seg_end)."""
    n = c_s.shape[0]
    width = v_s.shape[1]
    seg_start = jnp.concatenate([jnp.ones((1,), bool), c_s[1:] != c_s[:-1]])
    flags = jnp.tile(seg_start, width)
    flat = v_s.T.reshape(-1)
    if monoid == "max":
        agg, _ = _segmented_max_scan(flags, flat, jnp.zeros_like(flat))
    else:
        agg = segmented_sum_scan(flags, flat)
    seg_end = jnp.concatenate([seg_start[1:], jnp.ones((1,), bool)])
    return agg, seg_start, seg_end


@functools.partial(jax.jit, static_argnames=("table_size", "width", "monoid"))
def tensor_cell_fold_core(cell_id, contrib, table_size, width, monoid):
    """Traceable core: cell-grouped segmented u64 fold of (n, width)
    contributions, scattered into a dense (table_size, width) table
    (slot = cell id; pad rows park on the out-of-range dump slot).
    `cell_id` int32 with _PAD_CELL padding, `contrib` uint64,
    n ≤ 2^24 (the packed-key idx bound — the host wrapper chunks).
    Must trace under enable_x64(True) (guarded like the merge cores)."""
    n = cell_id.shape[0]
    idx = jnp.arange(n, dtype=jnp.int32)
    key = (cell_id.astype(jnp.int64) << jnp.int64(24)) | idx.astype(jnp.int64)
    if key.dtype != jnp.dtype("int64"):  # x64 disabled: would mis-group
        raise TypeError(
            "tensor_cell_fold_core must be traced under enable_x64(True): "
            f"packed key degraded to {key.dtype}"
        )
    key_s = jax.lax.sort(key)
    i_s = (key_s & jnp.int64((1 << 24) - 1)).astype(jnp.int32)
    c_s = (key_s >> jnp.int64(24)).astype(jnp.int32)
    v_s = contrib[i_s]  # ONE row gather instead of width sort payloads
    agg, _seg_start, seg_end = _flat_segmented_fold(c_s, v_s, monoid)
    real = c_s != _PAD_CELL
    live = jnp.tile(seg_end & real, width)
    d_ix = jnp.repeat(jnp.arange(width, dtype=jnp.int64), n)
    tgt = jnp.where(
        live,
        jnp.tile(c_s.astype(jnp.int64), width) * jnp.int64(width) + d_ix,
        jnp.int64(table_size * width),
    )
    table = (
        jnp.zeros(table_size * width, jnp.uint64).at[tgt].set(agg, mode="drop")
    )
    return table.reshape(table_size, width)


@with_x64
def tensor_cell_folds(
    cell_id: np.ndarray, contrib: np.ndarray, num_cells: int, monoid: str
) -> np.ndarray:
    """Host entry: → (num_cells, width) uint64 numpy — per-cell modular
    sums (sum/mean) or max keys (max), bit-identical to the host
    oracle's accumulator per cell. Batches beyond the 2^24 idx bound
    fold in chunks — both monoids are associative/commutative on the
    integer lattice, so chunked accumulation is exact."""
    n = len(cell_id)
    width = contrib.shape[1]
    if n == 0:
        return np.zeros((num_cells, width), np.uint64)
    with span("kernel:crdt", "tensor_cell_folds", n=n, cells=num_cells,
              width=width, monoid=monoid):
        table_size = bucket_size(max(num_cells, 1))
        acc = np.zeros((table_size, width), np.uint64)
        chunk = 1 << 24
        for i in range(0, n, chunk):
            c = cell_id[i : i + chunk]
            v = contrib[i : i + chunk]
            size = bucket_size(len(c))
            c_p = np.concatenate(
                [c.astype(np.int32),
                 np.full(size - len(c), int(_PAD_CELL), np.int32)]
            )
            v_p = np.concatenate(
                [v.astype(np.uint64),
                 np.zeros((size - len(v), width), np.uint64)]
            )
            t = to_host(tensor_cell_fold_core(
                jnp.asarray(c_p), jnp.asarray(v_p),
                table_size=table_size, width=width, monoid=monoid,
            ))
            if monoid == "max":
                np.maximum(acc, t, out=acc)
            else:
                acc += t
        return acc[:num_cells]


# --- reconcile-shaped shard cores (packed layout + the wide fallback) ---


def tensor_shard_sums_core(owner_ix, cell_id, contrib):
    """Per-shard tensor fold for the multi-owner reconcile shape: ops
    group by the SAME packed owner|cell|idx i64 sort key as the LWW and
    counter shard kernels (`pack_owner_cell_key`, lo_bits=0 — the sum
    monoid needs no flag bits), then ONE flattened segmented scan sums
    all `width` element columns per (owner, cell) segment. Returns
    (grp, seg_end, sums (width·n,) d-major) — per-cell totals sit at
    seg-end rows, and every output feeds the bench's checksum carry.
    Preconditions: owner < 4095, cell < 2^25, n ≤ 2^24 (the host
    router sends anything beyond to the wide variant)."""
    from evolu_tpu.parallel.reconcile import pack_owner_cell_key

    n = cell_id.shape[0]
    idx = jnp.arange(n, dtype=jnp.int32)
    key = pack_owner_cell_key(owner_ix, cell_id, idx, lo_bits=0)
    key_s = jax.lax.sort(key)
    i_s = (key_s & jnp.int64((1 << 24) - 1)).astype(jnp.int32)
    grp = key_s >> jnp.int64(24)  # owner|cell bits above idx
    v_s = contrib[i_s]
    sums, _seg_start, seg_end = _flat_segmented_fold(grp, v_s, "sum")
    return grp, seg_end, sums


def tensor_shard_sums_wide_core(owner_ix, cell_id, contrib):
    """The wide-id fallback (cell ≥ 2^25 or owner ≥ 4095) — the path
    tensor payload widths finally exercise at production shapes: the
    sort key is cell << 24 | idx (cells < 2^31, i32 interning bound),
    the owner rides as a GATHERED payload instead of key bits, and
    segmentation is by cell alone — same contract as
    `reconcile._shard_kernel_wide` (cell ids are globally interned,
    unique per owner). Returns (own_s, c_s, seg_end, sums) — per-cell
    totals at seg-end rows, bit-identical to the packed variant
    wherever its preconditions hold (parity-pinned)."""
    n = cell_id.shape[0]
    idx = jnp.arange(n, dtype=jnp.int32)
    key = (cell_id.astype(jnp.int64) << jnp.int64(24)) | idx.astype(jnp.int64)
    if key.dtype != jnp.dtype("int64"):  # x64 disabled: would mis-group
        raise TypeError(
            "tensor_shard_sums_wide_core must be traced under "
            f"enable_x64(True): packed key degraded to {key.dtype}"
        )
    key_s, own_s = jax.lax.sort((key, owner_ix.astype(jnp.int32)), num_keys=1)
    i_s = (key_s & jnp.int64((1 << 24) - 1)).astype(jnp.int32)
    c_s = (key_s >> jnp.int64(24)).astype(jnp.int32)
    v_s = contrib[i_s]
    sums, _seg_start, seg_end = _flat_segmented_fold(c_s, v_s, "sum")
    return own_s, c_s, seg_end, sums


_shard_packed_jit = with_x64(jax.jit(tensor_shard_sums_core))
_shard_wide_jit = with_x64(jax.jit(tensor_shard_sums_wide_core))

_OWNER_LIMIT = 4095  # reconcile._PAD_OWNER — the padding sentinel
_CELL_LIMIT = 1 << 25


@with_x64
def tensor_shard_sums(
    owner_ix: np.ndarray, cell_id: np.ndarray, contrib: np.ndarray
) -> Dict[Tuple[int, int], np.ndarray]:
    """Host entry with the static variant routing (mirrors
    `reconcile.shard_kernel_for`): packed when every owner < 4095 and
    every cell < 2^25, else the wide fallback. → {(owner, cell):
    int64 (width,) modular sums} — the parity surface the bench and
    tests pin against the numpy oracle. Routing is decided on HOST
    maxima before tracing; both variants are separately compiled."""
    n = len(cell_id)
    width = contrib.shape[1]
    if n == 0:
        return {}
    real = cell_id != int(_PAD_CELL)
    cell_max = int(cell_id.max(initial=0, where=real))
    owner_max = int(owner_ix.max(initial=0))
    packed = cell_max < _CELL_LIMIT and owner_max < _OWNER_LIMIT and n <= 1 << 24
    metrics.inc("evolu_crdt_tensor_kernel_total",
                variant="packed" if packed else "wide")
    size = bucket_size(n)
    o_p = np.concatenate([owner_ix.astype(np.int32),
                          np.zeros(size - n, np.int32)])
    c_p = np.concatenate([cell_id.astype(np.int32),
                          np.full(size - n, int(_PAD_CELL), np.int32)])
    v_p = np.concatenate([contrib.astype(np.uint64),
                          np.zeros((size - n, width), np.uint64)])
    with span("kernel:crdt", "tensor_shard_sums", n=n, width=width,
              variant="packed" if packed else "wide"):
        out: Dict[Tuple[int, int], np.ndarray] = {}
        if packed:
            grp, seg_end, sums = (np.asarray(x) for x in _shard_packed_jit(
                jnp.asarray(o_p), jnp.asarray(c_p), jnp.asarray(v_p)))
            mat = sums.reshape(width, size)
            for i in np.nonzero(seg_end)[0]:
                g = int(grp[i])
                owner, cell = g >> 25, g & (_CELL_LIMIT - 1)
                if owner == _OWNER_LIMIT:  # padding segment
                    continue
                out[(owner, cell)] = mat[:, i].copy().view(np.int64)
        else:
            own_s, c_s, seg_end, sums = (np.asarray(x) for x in _shard_wide_jit(
                jnp.asarray(o_p), jnp.asarray(c_p), jnp.asarray(v_p)))
            mat = sums.reshape(width, size)
            for i in np.nonzero(seg_end)[0]:
                if int(c_s[i]) == int(_PAD_CELL):
                    continue
                out[(int(own_s[i]), int(c_s[i]))] = \
                    mat[:, i].copy().view(np.int64)
        return out
