"""On-device canonical timestamp encoding and packed sort keys.

The reference orders all CRDT writes by the lexicographic order of the
46-char timestamp string `ISO8601(millis)-HEX4(counter)-node16`
(reference packages/evolu/src/timestamp.ts:43-48). On device we keep
timestamps columnar — `millis:int64, counter:int32, node:uint64` — and

- `render_timestamp_strings` materializes the canonical ASCII bytes
  (N, 46) entirely on device (civil-calendar arithmetic, no host
  round-trip) so `hash.murmur3_32_batch` can hash them in one pass;
- `pack_ts_keys` packs (millis, counter) into one uint64 whose numeric
  order equals the string order (node is a second uint64 tiebreak).

millis < 2**48 for any representable date (year 9999 ≈ 2**47.8), so
`millis << 16 | counter` is exact in uint64.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from evolu_tpu.ops import with_x64
from evolu_tpu.ops.hash import murmur3_32_batch, murmur3_32_bytes

TIMESTAMP_STRING_LENGTH = 46


def _civil_from_days(days):
    """days-since-1970-01-01 → (year, month, day). Howard Hinnant's
    `civil_from_days`, pure int64 arithmetic (valid for all our dates)."""
    z = days + 719468
    era = z // 146097
    doe = z - era * 146097
    yoe = (doe - doe // 1460 + doe // 36524 - doe // 146096) // 365
    y = yoe + era * 400
    doy = doe - (365 * yoe + yoe // 4 - yoe // 100)
    mp = (5 * doy + 2) // 153
    d = doy - (153 * mp + 2) // 5 + 1
    m = mp + jnp.where(mp < 10, 3, -9)
    y = y + (m <= 2)
    return y, m, d


# np scalars, NOT jnp: module-level jnp constants would initialize the
# XLA backend at import time (breaks jax.distributed.initialize).
_ZERO = np.uint32(ord("0"))
_UPPER_A = np.uint32(ord("A") - 10)
_LOWER_A = np.uint32(ord("a") - 10)


def _digits(x, n: int):
    """x (uint32) → list of n ASCII decimal digit uint32 arrays, most
    significant first."""
    out = []
    for i in range(n - 1, -1, -1):
        out.append((x // jnp.uint32(10**i)) % jnp.uint32(10) + _ZERO)
    return out


def _hex_nibble(x, upper: bool):
    x = x.astype(jnp.uint32)
    return jnp.where(x < 10, x + _ZERO, x + (_UPPER_A if upper else _LOWER_A))


# First millis value the u32 fast paths cannot represent: seconds no
# longer fit uint32 (the classic 2106-02-07 rollover).
U32_MILLIS_BOUND = 1000 << 32


def u32_divmod_hi_lo(m_i64, divisor: int):
    """Floor-divmod of millis = hi·2³² + lo by a compile-time constant,
    entirely in uint32 — the 64-bit divide is EMULATED on the 32-bit
    v5e VPU and was the dominant cost of the hash render (r5 ablation:
    1.06 ms/1M for four of them). With q32, r32 = divmod(2³², divisor):
    m ≡ hi·r32 + lo (mod divisor) and
    m // divisor = hi·q32 + (hi·r32 + lo) // divisor.
    Exact for 0 ≤ m < U32_MILLIS_BOUND (hi ≤ 999) PROVIDED the
    intermediate t = hi·r32 + (divisor-1) fits u32 — checked below at
    trace time (ValueError, `python -O`-proof), since it depends on the divisor's REMAINDER, not its
    size (86_400_000 would overflow: r32 = 61_367_296). ONE copy of
    this overflow-sensitive chain, shared by the hash render and the
    minute stage. → (quotient u32, remainder u32)."""
    q32, r32 = divmod(1 << 32, divisor)
    if 999 * r32 + (divisor - 1) >= (1 << 32):
        # A hard error, not an assert: the guard must survive
        # `python -O` — a divisor that overflows the intermediate would
        # silently corrupt every quotient in range.
        raise ValueError(
            f"u32_divmod_hi_lo: divisor {divisor} overflows the u32 chain "
            f"(999*{r32} + {divisor - 1} >= 2**32)"
        )
    mu = m_i64.astype(jnp.uint64)
    hi = (mu >> jnp.uint64(32)).astype(jnp.uint32)  # < 1000 in range
    lo = mu.astype(jnp.uint32)
    lo_q = lo // jnp.uint32(divisor)
    lo_r = lo - lo_q * jnp.uint32(divisor)
    t = hi * jnp.uint32(r32) + lo_r
    return hi * jnp.uint32(q32) + lo_q + t // jnp.uint32(divisor), t % jnp.uint32(divisor)


def millis_range_cond(millis, fast, slow):
    """Batch-level `lax.cond` routing between a u32 fast branch (exact
    for 0 ≤ millis < U32_MILLIS_BOUND) and the exact int64 branch —
    ONE copy of the guard shared by `_millis_clock_parts` and
    `merkle_ops.js_minutes`. Non-1-D or empty inputs take the exact
    branch unconditionally (scalars have no batch to reduce over)."""
    millis = jnp.asarray(millis, jnp.int64)
    if millis.ndim != 1 or millis.shape[0] == 0:
        return slow(millis)
    in_range = (jnp.min(millis) >= 0) & (
        jnp.max(millis) < jnp.int64(U32_MILLIS_BOUND)
    )
    return jax.lax.cond(in_range, fast, slow, millis)


def _millis_clock_parts(millis):
    """millis → (ms uint32, seconds-of-day uint32, days int32).

    The u32 hi/lo divmod chain replaces four EMULATED 64-bit divisions
    (measured **1.06 ms off the 1M merge pipeline on v5e**; the render
    was 1.18 of the 1.29 ms hash stage, r5 ablation); out-of-range
    batches (pre-1970 / beyond 2106-02-07) keep the exact int64 path.
    Bit-identical either way (property-pinned incl. the boundary)."""

    def fast(m):
        secs, ms = u32_divmod_hi_lo(m, 1000)
        days = secs // jnp.uint32(86400)
        sod = secs - days * jnp.uint32(86400)
        return ms, sod, days.astype(jnp.int32)

    def slow(m):
        ms = (m % 1000).astype(jnp.uint32)
        secs = m // 1000
        days = (secs // 86400).astype(jnp.int32)
        sod = (secs % 86400).astype(jnp.uint32)
        return ms, sod, days

    return millis_range_cond(millis, fast, slow)


def _timestamp_bytes_u32(millis, counter, node):
    """The 46 canonical-string bytes as a list of 46 uint32 arrays
    (`YYYY-MM-DDTHH:mm:ss.sssZ-CCCC-n*16`, timestamp.ts:43-48).

    Only the initial millis divmods touch 64-bit (u32 fast path under
    a range cond — `_millis_clock_parts`); everything after is uint32
    so XLA keeps the whole computation in one fused elementwise pass
    (no 64-bit emulation in the digit/hex extraction).
    """
    counter = jnp.asarray(counter, jnp.int32)
    node = jnp.asarray(node, jnp.uint64)
    ms, sod, days = _millis_clock_parts(millis)
    hh, mm, ss = sod // 3600, (sod // 60) % 60, sod % 60
    y, mo, d = _civil_from_days(days)
    y, mo, d = y.astype(jnp.uint32), mo.astype(jnp.uint32), d.astype(jnp.uint32)

    cols = []
    cols += _digits(y, 4)
    dash = jnp.full_like(cols[0], ord("-"))
    cols.append(dash)
    cols += _digits(mo, 2)
    cols.append(dash)
    cols += _digits(d, 2)
    cols.append(jnp.full_like(cols[0], ord("T")))
    cols += _digits(hh, 2)
    colon = jnp.full_like(cols[0], ord(":"))
    cols.append(colon)
    cols += _digits(mm, 2)
    cols.append(colon)
    cols += _digits(ss, 2)
    cols.append(jnp.full_like(cols[0], ord(".")))
    cols += _digits(ms, 3)
    cols.append(jnp.full_like(cols[0], ord("Z")))
    cols.append(dash)
    c32 = counter.astype(jnp.uint32)
    for shift in (12, 8, 4, 0):
        cols.append(_hex_nibble((c32 >> shift) & 0xF, upper=True))
    cols.append(dash)
    n_hi = (node >> jnp.uint64(32)).astype(jnp.uint32)
    n_lo = node.astype(jnp.uint32)
    for half in (n_hi, n_lo):
        for shift in (28, 24, 20, 16, 12, 8, 4, 0):
            cols.append(_hex_nibble((half >> shift) & 0xF, upper=False))
    return cols


@with_x64
def render_timestamp_strings(millis, counter, node) -> jnp.ndarray:
    """(N,) int64 millis, (N,) int32 counter, (N,) uint64 node →
    (N, 46) uint8 canonical strings `YYYY-MM-DDTHH:mm:ss.sssZ-CCCC-n*16`.

    Counter hex is UPPERCASE, node hex lowercase — exactly the
    reference encoding (timestamp.ts:43-48) whose byte order the LWW
    comparisons rely on.
    """
    cols = [c.astype(jnp.uint8) for c in _timestamp_bytes_u32(millis, counter, node)]
    return jnp.stack(cols, axis=1)


@with_x64
def timestamp_hashes(millis, counter, node) -> jnp.ndarray:
    """Batched `timestampToHash` (timestamp.ts:87-88): murmur3-32 of the
    canonical string, computed fully on device — the string bytes stay
    as fused register columns, never materialized as an (N, 46) matrix.
    → (N,) uint32."""
    return murmur3_32_bytes(
        _timestamp_bytes_u32(millis, counter, node), TIMESTAMP_STRING_LENGTH
    )


@with_x64
def pack_ts_keys(millis, counter) -> jnp.ndarray:
    """(millis, counter) → uint64 key; numeric order == string order.

    Key 0 is reserved as the "no existing winner" sentinel in the merge
    planner: a real stored message never has millis == 0 and counter == 0
    with the all-zero sync node (createSyncTimestamp timestamps are
    range-query bounds, never stored — timestamp.ts:33-41).
    """
    millis = jnp.asarray(millis)
    counter = jnp.asarray(counter)
    return (millis.astype(jnp.uint64) << jnp.uint64(16)) | counter.astype(jnp.uint64)


@with_x64
def unpack_ts_keys(k1):
    """Inverse of `pack_ts_keys`: uint64 key → (millis int64,
    counter int32). Owns the bit layout together with pack_ts_keys —
    kernels recovering sorted timestamp columns from sort keys use
    this instead of inlining shifts."""
    k1 = jnp.asarray(k1, jnp.uint64)
    millis = (k1 >> jnp.uint64(16)).astype(jnp.int64)
    counter = (k1 & jnp.uint64(0xFFFF)).astype(jnp.int32)
    return millis, counter


def pack_ts_key_host(millis, counter):
    """Host twin of `pack_ts_keys` — same bit layout, numpy or Python ints.

    One definition of the layout on each side of the boundary; the
    order-equivalence test (tests/test_ops.py) pins them together.
    """
    if isinstance(millis, np.ndarray):
        return (millis.astype(np.uint64) << np.uint64(16)) | counter.astype(np.uint64)
    return (int(millis) << 16) | int(counter)


def node_hex_to_u64(node: str) -> int:
    """Host helper: 16-lowercase-hex node id → uint64 (big-endian nibbles,
    matching render_timestamp_strings)."""
    return int(node, 16)


def u64_to_node_hex(v: int) -> str:
    return f"{v:016x}"
