"""Batched MurmurHash3 x86/32 on device.

Bit-exact with the host oracle `evolu_tpu.core.murmur.murmur3_32`
(which itself matches the npm `murmurhash` package used by the
reference at packages/evolu/src/timestamp.ts:87-88). Operates on a
batch of fixed-width byte strings as a (N, L) uint8 array; the block
loop is unrolled at trace time since L is static (46 for canonical
timestamp strings).

All arithmetic is uint32 with explicit wrapping — XLA integer ops wrap
by construction, so the JS `Math.imul`/`>>>` semantics come for free.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

# np scalars, NOT jnp: module-level jnp constants would initialize the
# XLA backend at import time (breaks jax.distributed.initialize).
_C1 = np.uint32(0xCC9E2D51)
_C2 = np.uint32(0x1B873593)


def _rotl(x, r: int):
    return (x << jnp.uint32(r)) | (x >> jnp.uint32(32 - r))


def _mix_k(k):
    k = k * _C1
    k = _rotl(k, 15)
    return k * _C2


def murmur3_32_bytes(bytes_u32, length: int, seed: int = 0) -> jnp.ndarray:
    """murmur3-32 over `length` bytes given as a list of `length` uint32
    arrays (one array per byte position, values 0..255) → (N,) uint32.

    Keeping the bytes as separate register-resident columns (instead of
    a materialized (N, L) uint8 matrix) lets XLA fuse the whole hash
    into one elementwise kernel — no lane-padded byte matrix in HBM,
    no strided column gathers.
    """
    assert len(bytes_u32) == length
    h = jnp.full_like(bytes_u32[0], seed)

    n_blocks = length // 4
    for i in range(n_blocks):
        b = i * 4
        k = (
            bytes_u32[b]
            | (bytes_u32[b + 1] << jnp.uint32(8))
            | (bytes_u32[b + 2] << jnp.uint32(16))
            | (bytes_u32[b + 3] << jnp.uint32(24))
        )
        h = h ^ _mix_k(k)
        h = _rotl(h, 13)
        h = h * jnp.uint32(5) + jnp.uint32(0xE6546B64)

    tail = length & 3
    if tail:
        k = jnp.zeros_like(h)
        base = n_blocks * 4
        if tail >= 3:
            k = k ^ (bytes_u32[base + 2] << jnp.uint32(16))
        if tail >= 2:
            k = k ^ (bytes_u32[base + 1] << jnp.uint32(8))
        k = k ^ bytes_u32[base]
        h = h ^ _mix_k(k)

    h = h ^ jnp.uint32(length)
    h = h ^ (h >> jnp.uint32(16))
    h = h * jnp.uint32(0x85EBCA6B)
    h = h ^ (h >> jnp.uint32(13))
    h = h * jnp.uint32(0xC2B2AE35)
    h = h ^ (h >> jnp.uint32(16))
    return h


def murmur3_32_batch(data: jnp.ndarray, seed: int = 0) -> jnp.ndarray:
    """murmur3-32 of each row of a (N, L) uint8 array → (N,) uint32.

    L is static; rows are full strings (no per-row lengths — the CRDT
    only hashes canonical 46-char timestamp strings).
    """
    _, length = data.shape
    data = data.astype(jnp.uint32)
    return murmur3_32_bytes([data[:, i] for i in range(length)], length, seed)
