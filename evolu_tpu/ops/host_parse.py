"""Vectorized host-side batch parsing (numpy).

The end-to-end system path was dominated by per-message Python work —
`timestamp_from_string` + pure-Python murmur per message while
columnarizing (the reference's hot loop #4 reborn on the host). These
helpers parse a whole batch of canonical 46-char timestamp strings and
intern cells with numpy, leaving no per-message Python in the batched
apply path.

Strictness: timestamps must be exactly the reference's fixed-width
encoding `YYYY-MM-DDTHH:mm:ss.sssZ-CCCC-node16` (timestamp.ts:43-48);
any malformed row raises TimestampParseError, aborting the enclosing
transaction exactly like the scalar parser would.
"""

from __future__ import annotations

from typing import Dict, List, Sequence, Tuple

import numpy as np

from evolu_tpu.core.types import TimestampParseError

_LEN = 46


def _days_from_civil(y, m, d):
    """Inverse of Howard Hinnant's civil_from_days, vectorized int64."""
    y = y - (m <= 2)
    era = np.floor_divide(y, 400)
    yoe = y - era * 400
    mp = m + np.where(m > 2, -3, 9)
    doy = (153 * mp + 2) // 5 + d - 1
    doe = yoe * 365 + yoe // 4 - yoe // 100 + doy
    return era * 146097 + doe - 719468


def _days_in_month(y, m):
    """Vectorized month lengths with Gregorian leap rules."""
    lengths = np.array([0, 31, 28, 31, 30, 31, 30, 31, 31, 30, 31, 30, 31])
    days = lengths[m]
    leap = ((y % 4 == 0) & (y % 100 != 0)) | (y % 400 == 0)
    return np.where((m == 2) & leap, 29, days)


def parse_timestamp_strings(
    timestamps: Sequence[str], with_case: bool = False
):
    """Batch `timestampFromString`: → (millis int64, counter int32,
    node uint64). Validates the full fixed-width layout.

    With `with_case=True`, appends a per-row bool array: True where the
    row uses the canonical encoder's hex case (UPPERCASE counter,
    lowercase node — timestamp.ts:43-48). Computed from the
    already-built byte buffer, so the screen costs two slice compares,
    not a second join+scan. Callers quarantine non-canonical rows to
    host paths: the device kernels order by numeric keys and hash a
    canonical re-render, which matches the reference's raw-string
    order / verbatim-node hash only for canonical strings."""
    n = len(timestamps)
    if n == 0:
        empty = (np.empty(0, np.int64), np.empty(0, np.int32), np.empty(0, np.uint64))
        return (*empty, np.ones(0, bool)) if with_case else empty
    # Per-string length check FIRST: a joined-length check alone would
    # accept e.g. ["", "<two valid stamps concatenated>"] after reshape.
    if any(len(t) != _LEN for t in timestamps):
        raise TimestampParseError("malformed timestamp in batch")
    joined = "".join(timestamps)
    if not joined.isascii():
        raise TimestampParseError("malformed timestamp in batch")
    packed = joined.encode("ascii")
    native = parse_packed_timestamps(packed, n, with_case=with_case, strict=False)
    if native is not None:
        return native
    buf = np.frombuffer(packed, np.uint8).reshape(n, _LEN)

    # Fixed separators.
    seps = {4: ord("-"), 7: ord("-"), 10: ord("T"), 13: ord(":"), 16: ord(":"),
            19: ord("."), 23: ord("Z"), 24: ord("-"), 29: ord("-")}
    for pos, ch in seps.items():
        if not (buf[:, pos] == ch).all():
            raise TimestampParseError("malformed timestamp in batch")

    def dec(a, b):
        cols = buf[:, a:b]
        if ((cols < ord("0")) | (cols > ord("9"))).any():
            raise TimestampParseError("malformed timestamp in batch")
        v = np.zeros(n, np.int64)
        for i in range(a, b):
            v = v * 10 + (buf[:, i].astype(np.int64) - ord("0"))
        return v

    y, mo, d = dec(0, 4), dec(5, 7), dec(8, 10)
    hh, mi, ss, ms = dec(11, 13), dec(14, 16), dec(17, 19), dec(20, 23)
    # Field-range validation, matching the scalar parser's datetime
    # constructor (a month 13 or hour 25 must abort, not wrap).
    if (
        (y < 1).any()  # datetime's MINYEAR — year 0000 must abort
        or (mo < 1).any() or (mo > 12).any()
        or (d < 1).any() or (d > _days_in_month(y, mo)).any()
        or (hh > 23).any() or (mi > 59).any() or (ss > 59).any()
    ):
        raise TimestampParseError("malformed timestamp in batch")
    days = _days_from_civil(y, mo, d)
    millis = ((days * 86400 + hh * 3600 + mi * 60 + ss) * 1000) + ms

    def hexv(a, b):
        # Both hex cases accepted, like the scalar parser (the canonical
        # encoder emits uppercase counter / lowercase node, but wire
        # strings may be non-canonical and must parse identically on
        # every backend).
        v = np.zeros(n, np.uint64)
        for i in range(a, b):
            c = buf[:, i]
            digit = (c >= ord("0")) & (c <= ord("9"))
            lower = (c >= ord("a")) & (c <= ord("f"))
            upper = (c >= ord("A")) & (c <= ord("F"))
            if ((~digit) & (~lower) & (~upper)).any():
                raise TimestampParseError("malformed timestamp in batch")
            nib = np.where(
                digit, c - ord("0"),
                np.where(lower, c - ord("a") + 10, c - ord("A") + 10),
            ).astype(np.uint64)
            v = (v << np.uint64(4)) | nib
        return v

    counter = hexv(25, 29).astype(np.int32)
    node = hexv(30, 46)
    if with_case:
        cb, nb = buf[:, 25:29], buf[:, 30:46]
        case_ok = ~(
            ((cb >= ord("a")) & (cb <= ord("f"))).any(axis=1)
            | ((nb >= ord("A")) & (nb <= ord("F"))).any(axis=1)
        )
        return millis, counter, node, case_ok
    return millis, counter, node


def parse_packed_timestamps(
    packed: bytes, n: int, with_case: bool = False, strict: bool = True
):
    """Native (C) batch parse over an already-packed buffer of n
    46-byte records — one pass instead of ~40 vectorized numpy passes,
    and no join when the caller already built the buffer (the packed
    relay ingest reuses its insert buffer here).

    Returns the same tuple as `parse_timestamp_strings`. With
    `strict=False`, returns None when the native library is
    unavailable so the caller can fall back to numpy."""
    from evolu_tpu.storage.native import load_library

    lib = load_library()
    if lib is None:
        if strict:
            raise RuntimeError("native host library unavailable")
        return None
    if len(packed) != n * _LEN:
        raise TimestampParseError("malformed timestamp in batch")
    import ctypes

    millis = np.empty(n, np.int64)
    counter = np.empty(n, np.int32)
    node = np.empty(n, np.uint64)
    case_ok = np.empty(n, np.uint8)
    rc = lib.eh_parse_timestamps(
        packed, n,
        millis.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        counter.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        node.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)),
        case_ok.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
    )
    if rc != 0:
        raise TimestampParseError("malformed timestamp in batch")
    if with_case:
        return millis, counter, node, case_ok.astype(bool)
    return millis, counter, node


def intern_cells(
    tables: Sequence[str], rows: Sequence[str], columns: Sequence[str]
) -> Tuple[np.ndarray, List[Tuple[str, str, str]]]:
    """→ (cell_id int32 per message, unique cell tuples indexed by id).

    First-appearance interning like the dict-based scalar path (ids are
    dense 0..k-1 in order of first occurrence)."""
    # Length-prefixed keys: a separator byte inside a field can never
    # collide two distinct cells (fields arrive from untrusted peers).
    keys = np.array(
        [f"{len(t)}.{len(r)}.{t}{r}{c}" for t, r, c in zip(tables, rows, columns)],
        dtype=object,
    )
    _, first_idx, inverse = np.unique(keys, return_index=True, return_inverse=True)
    # np.unique sorts; remap to first-appearance order for parity with
    # the scalar intern (and deterministic cell ids).
    order = np.argsort(first_idx, kind="stable")
    rank = np.empty_like(order)
    rank[order] = np.arange(len(order))
    cell_id = rank[inverse].astype(np.int32)
    uniq_positions = first_idx[order]
    cells = [
        (tables[i], rows[i], columns[i]) for i in uniq_positions
    ]
    return cell_id, cells
