"""Batched LWW merge planner on device.

Replaces the reference's per-message SQL loop (reference
packages/evolu/src/applyMessages.ts:78-124) with one columnar pass:

    stable sort by (cell, batch order)
      → segmented exclusive prefix-max of HLC keys (running winner)
      → xor mask   (message's hash goes into the Merkle tree)
      → segmented total max (final winner per cell)
      → upsert mask (final winner beats the stored winner)

Semantics are *exactly* the sequential loop's, including its quirks:
the Merkle XOR is gated on "running winner != message timestamp", not
on the __message insert actually inserting, so a re-received
non-winning duplicate XORs again (applyMessages.ts:104-122) — the
running winner is the max of the stored winner and all *earlier batch
messages* for the same cell, in batch order.

HLC keys are (k1, k2) uint64 pairs from `encode.pack_ts_keys` — k1 =
millis<<16|counter, k2 = node — compared lexicographically; (0, 0) is
the "no stored winner" sentinel (see encode.pack_ts_keys docstring).

Everything here is shape-static and jit-compiled once per bucket size;
`plan_batch_device` pads to power-of-two buckets to avoid recompiles
(SURVEY.md §7 "dynamic shapes").
"""

from __future__ import annotations

import functools
from typing import Dict, List, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

import operator

from evolu_tpu.core.timestamp import timestamp_from_string
from evolu_tpu.core.types import CrdtMessage
from evolu_tpu.obs import metrics
from evolu_tpu.ops import bucket_size, to_host_many, with_x64
from evolu_tpu.ops.encode import node_hex_to_u64, pack_ts_key_host
from evolu_tpu.utils.log import span

# np scalar, NOT jnp: a module-level jnp constant would initialize
# the XLA backend at import time, breaking jax.distributed.initialize
# (multi-host join must run before any backend touch).
_PAD_CELL = np.int32(0x7FFFFFFF)


def _lex_max(a1, a2, b1, b2):
    """Elementwise max of (a1,a2) vs (b1,b2) under lexicographic order."""
    a_wins = (a1 > b1) | ((a1 == b1) & (a2 >= b2))
    return jnp.where(a_wins, a1, b1), jnp.where(a_wins, a2, b2)


def _seg_combine(left, right):
    """The segmented lex-max monoid on (flag, k1, k2): the operand
    nearest the scan head wins outright when flagged."""
    lf, l1, l2 = left
    rf, r1, r2 = right
    m1, m2 = _lex_max(l1, l2, r1, r2)
    return lf | rf, jnp.where(rf, r1, m1), jnp.where(rf, r2, m2)


def _segmented_max_scan_reference(flags, k1, k2, reverse: bool = False):
    """Inclusive segmented lexicographic max scan via
    jax.lax.associative_scan — the semantics reference (and the
    fallback for lengths the blocked variant cannot tile).

    flags[i] marks a segment start (segment END when reverse=True).
    `reverse=True` flips, scans forward with the same combine, and
    flips back (that is how jax implements it), which realizes the
    right-to-left recurrence
    `out[i] = x[i] if flags[i] else max(x[i], out[i+1])`.
    """
    _, m1, m2 = jax.lax.associative_scan(_seg_combine, (flags, k1, k2), reverse=reverse)
    return m1, m2


_SCAN_BLOCK = 256
_PALLAS_SCAN_MIN = 1 << 15  # one pallas grid tile; below this, padding waste wins


def _use_pallas_scan() -> bool:
    """Trace-time routing: the single-pass Pallas scan wins on TPU
    silicon (0.42 vs 0.60 ms per fwd+rev pair at 1M, slope-measured);
    everywhere else (CPU tests, exotic builds) the blocked XLA form
    runs. Overridable via EVOLU_PALLAS_SCAN=0/1."""
    import os

    override = os.environ.get("EVOLU_PALLAS_SCAN", "").lower()
    if override in ("0", "false", "off"):
        return False
    try:
        from evolu_tpu.ops.pallas_scan import PALLAS_AVAILABLE
    except Exception:  # pragma: no cover
        return False
    # "1" only FORCES where the kernel can actually run — the
    # availability and TPU-backend guards always hold (a CPU build
    # would crash mid-jit in non-interpret mode).
    return PALLAS_AVAILABLE and jax.default_backend() == "tpu"


def _segmented_max_scan(flags, k1, k2, reverse: bool = False):
    """Inclusive segmented lexicographic max scan — blocked two-level
    formulation, ~2.6× faster than `associative_scan` on TPU at N=1M
    (measured 17.9 → 6.8 ms for the planner's two scans; the generic
    lowering materializes log-depth concat/slice passes, this does
    log2(L) unrolled elementwise passes over an (N/L, L) view + one
    tiny cross-block scan + a carry broadcast).

    On TPU with a big-enough batch the single-pass Pallas kernel
    (ops/pallas_scan.py) takes over — one HBM pass with the carry in
    SMEM across the sequential grid, measured another ~30% off the
    scan pair on v5e silicon, bit-identical (tests/test_pallas.py).

    Identical results to `_segmented_max_scan_reference` (property
    pinned in tests/test_ops.py). Production batches are padded to
    power-of-two buckets so L always tiles; other lengths fall back.
    """
    n = flags.shape[0]
    if n >= _PALLAS_SCAN_MIN and _use_pallas_scan():
        from evolu_tpu.ops.pallas_scan import segmented_max_scan_pallas

        return segmented_max_scan_pallas(flags, k1, k2, reverse=reverse)
    L = min(_SCAN_BLOCK, n)
    if n == 0 or n % L:
        return _segmented_max_scan_reference(flags, k1, k2, reverse)
    if reverse:
        o1, o2 = _segmented_max_scan(flags[::-1], k1[::-1], k2[::-1])
        return o1[::-1], o2[::-1]

    s_f = flags.reshape(-1, L)
    s1 = k1.reshape(-1, L)
    s2 = k2.reshape(-1, L)
    # In-block inclusive scan (Hillis–Steele): combine each row with
    # the row `shift` to its left; out-of-range pads with the monoid
    # identity (flag=False, keys 0).
    shift = 1
    while shift < L:
        pf = jnp.pad(s_f[:, :-shift], ((0, 0), (shift, 0)), constant_values=False)
        p1 = jnp.pad(s1[:, :-shift], ((0, 0), (shift, 0)))
        p2 = jnp.pad(s2[:, :-shift], ((0, 0), (shift, 0)))
        m1, m2 = _lex_max(p1, p2, s1, s2)
        n1 = jnp.where(s_f, s1, m1)
        n2 = jnp.where(s_f, s2, m2)
        s_f = s_f | pf
        s1, s2 = n1, n2
        shift *= 2
    # Cross-block exclusive carry over the block summaries (tiny:
    # N/L elements), then broadcast into rows whose block prefix holds
    # no segment start (final s_f is exactly that mask).
    _, c1, c2 = jax.lax.associative_scan(
        _seg_combine, (s_f[:, -1], s1[:, -1], s2[:, -1])
    )
    zero = jnp.zeros((), k1.dtype)
    e1 = jnp.concatenate([zero[None], c1[:-1]])
    e2 = jnp.concatenate([zero[None], c2[:-1]])
    carried1, carried2 = _lex_max(e1[:, None], e2[:, None], s1, s2)
    o1 = jnp.where(s_f, s1, carried1)
    o2 = jnp.where(s_f, s2, carried2)
    return o1.reshape(n), o2.reshape(n)


def plan_merge_sorted_core(cell_id, k1, k2, ex_k1, ex_k2, extras=(), return_winners=False):
    """The device LWW planner in SORTED order (traceable core).

    Sorts by (cell, batch order) and returns the masks in that sorted
    order together with the permutation `i_s` (original index of each
    sorted row), skipping the restoring sort — downstream device work
    (hashing, minute segments) runs directly on the sorted rows and the
    host unpermutes the two bool masks with one vectorized numpy
    scatter. `extras` are additional per-row arrays carried through the
    sort as payload operands and returned sorted.

    Args (all shape (N,), padding rows use cell_id=_PAD_CELL, keys 0):
      cell_id: int32 interned (table,row,column) id per message.
      k1, k2: uint64 HLC sort keys per message.
      ex_k1, ex_k2: uint64 stored-winner keys for the message's cell
        ((0,0) = no stored winner).

    Returns (xor_sorted, upsert_sorted, i_s, s1, s2, extras_sorted);
    s1/s2 are the sorted HLC keys, from which callers recover the
    sorted timestamp columns without extra payloads: millis = s1 >> 16,
    counter = s1 & 0xFFFF, node = s2.

    TPU notes: one 32-bit-key sort + two segmented scans. No scatters
    and no segment_max/min (XLA lowers those to serialized scatter
    updates on TPU — ~100ms+ per call at N=1M vs ~15ms for a sort),
    and no post-sort gathers (all per-row data rides through the sort
    as payload operands, ~8x cheaper than u64 gathers at N=1M).

    MUST be traced inside an enable_x64(True) scope (like
    segment_xor2_core): the packed merge key is a real i64 — under
    x64-disabled tracing it would silently degrade to int32 and the
    `cell << 24` shift would scramble the plan for any cell_id >= 128.
    Guarded at trace time below.
    """
    n = cell_id.shape[0]
    idx = jnp.arange(n, dtype=jnp.int32)

    if n <= 1 << 24:
        # ONE packed i64 key (cell << 24 | idx), UNSTABLE: the key
        # total-orders (cell, idx) exactly — idx is unique, so this is
        # bit-identical to the stable-by-cell sort — and drops both
        # the stability requirement and the idx payload (recovered
        # from the key's low bits). Measured r4 on v5e: 0.54 ms/1M
        # faster than the r3 stable-i32 formulation (itself 28% faster
        # than the 2-key sort). Cell ids are non-negative (interned,
        # pad = int32 max), so the packed key sorts pads last.
        key = (cell_id.astype(jnp.int64) << jnp.int64(24)) | idx.astype(jnp.int64)
        if key.dtype != jnp.dtype("int64"):  # x64 disabled: would mis-plan
            raise TypeError(
                "plan_merge_sorted_core must be traced under enable_x64(True): "
                f"packed merge key degraded to {key.dtype}"
            )
        sorted_ops = jax.lax.sort(
            (key, k1, k2, ex_k1, ex_k2) + tuple(extras),
            num_keys=1, is_stable=False,
        )
        key_s = sorted_ops[0]
        c = (key_s >> jnp.int64(24)).astype(jnp.int32)
        i_s = (key_s & jnp.int64((1 << 24) - 1)).astype(jnp.int32)
        s1, s2, e1, e2 = sorted_ops[1:5]
        extras_sorted = sorted_ops[5:]
    else:  # > 16M rows: idx no longer fits the key's low bits
        sorted_ops = jax.lax.sort(
            (cell_id, idx, k1, k2, ex_k1, ex_k2) + tuple(extras),
            num_keys=1, is_stable=True,
        )
        c, i_s, s1, s2, e1, e2 = sorted_ops[:6]
        extras_sorted = sorted_ops[6:]

    seg_start = jnp.concatenate([jnp.ones((1,), bool), c[1:] != c[:-1]])

    # Inclusive segmented max m, then exclusive p (running batch winner
    # BEFORE each message), then seed with the stored winner e.
    m1, m2 = _segmented_max_scan(seg_start, s1, s2)
    zero = jnp.zeros((), jnp.uint64)
    p1 = jnp.where(seg_start, zero, jnp.roll(m1, 1))
    p2 = jnp.where(seg_start, zero, jnp.roll(m2, 1))
    r1, r2 = _lex_max(p1, p2, e1, e2)
    xor_sorted = (r1 != s1) | (r2 != s2)

    # Segment-wide max t: m is nondecreasing within a segment, so a
    # backward segmented max over m broadcasts each segment's final m
    # (= its total max) to every row of the segment.
    seg_end = jnp.concatenate([seg_start[1:], jnp.ones((1,), bool)])
    t1, t2 = _segmented_max_scan(seg_end, m1, m2, reverse=True)

    # First row achieving the max in batch order: s == t and no earlier
    # batch row reached t (the exclusive batch max p is still < t).
    eligible = (s1 == t1) & (s2 == t2)
    first_eligible = eligible & ~((p1 == t1) & (p2 == t2))
    # Winner strictly beats the stored winner iff lex_max(t, e) != e.
    beats1, beats2 = _lex_max(t1, t2, e1, e2)
    beats = (beats1 != e1) | (beats2 != e2)
    real = c != _PAD_CELL
    upsert_sorted = first_eligible & beats & real
    xor_sorted = xor_sorted & real
    if return_winners:
        # (beats1, beats2) IS lex_max(segment total max, stored winner)
        # — the cell's updated winner; meaningful at seg_end rows. The
        # HBM winner cache scatters these back over its slots.
        return xor_sorted, upsert_sorted, i_s, s1, s2, extras_sorted, (
            beats1, beats2, seg_end, real,
        )
    return xor_sorted, upsert_sorted, i_s, s1, s2, extras_sorted


def winner_flags(k1, k2, ex_k1, ex_k2):
    """Per-row stored-winner relation bits, computed elementwise BEFORE
    the sort: a = e >lex s, b = e ==lex s. ONE copy shared by
    `plan_merge_sorted_flags` and the packed-owner shard kernel."""
    a = (ex_k1 > k1) | ((ex_k1 == k1) & (ex_k2 > k2))
    b = (ex_k1 == k1) & (ex_k2 == k2)
    return a, b


def masks_from_sorted_flags(grp, s1, s2, a_s, b_s, real):
    """The post-sort planner tail shared by `plan_merge_sorted_flags`
    and the packed-owner shard kernel (`parallel.reconcile`): segment
    boundaries from the sorted GROUP key (the sort-key bits above the
    idx/flag fields — cell, or owner|cell), the two segmented max
    scans, and the flag-bit xor/upsert algebra — ONE copy of the
    correctness-critical mask logic, so the two kernels can never
    drift. → (xor_sorted, upsert_sorted), both already masked by
    `real`."""
    seg_start = jnp.concatenate([jnp.ones((1,), bool), grp[1:] != grp[:-1]])
    m1, m2 = _segmented_max_scan(seg_start, s1, s2)
    zero = jnp.zeros((), jnp.uint64)
    p1 = jnp.where(seg_start, zero, jnp.roll(m1, 1))
    p2 = jnp.where(seg_start, zero, jnp.roll(m2, 1))
    p_eq_s = (p1 == s1) & (p2 == s2)
    p_gt_s = (p1 > s1) | ((p1 == s1) & (p2 > s2))
    # lex_max(p, e) == s ⟺ (p==s ∨ e==s) ∧ p≤s ∧ e≤s; xor is its negation.
    xor_sorted = ~((p_eq_s | b_s) & ~p_gt_s & ~a_s)
    seg_end = jnp.concatenate([seg_start[1:], jnp.ones((1,), bool)])
    t1, t2 = _segmented_max_scan(seg_end, m1, m2, reverse=True)
    eligible = (s1 == t1) & (s2 == t2)
    first_eligible = eligible & ~((p1 == t1) & (p2 == t2))
    # beats (t >lex e) read only where s == t: there it is ¬(a ∨ b).
    upsert_sorted = first_eligible & ~(a_s | b_s) & real
    return xor_sorted & real, upsert_sorted


def plan_merge_sorted_flags(cell_id, k1, k2, ex_k1, ex_k2, extras=()):
    """`plan_merge_sorted_core` with the stored-winner payloads REPLACED
    by two flag bits riding in the sort key (r5 kernel restructure).

    The insight: the planner never needs the stored winner's VALUE —
    only its relation to each row's own key. Both e-dependent
    expressions reduce to per-row comparisons computable BEFORE the
    sort:

      xor:    lex_max(p, e) == s  ⟺  (p==s ∨ e==s) ∧ p≤s ∧ e≤s
              — e only enters via (e>s) and (e==s);
      upsert: `beats = t >lex e` is only consumed at rows where s == t
              (first_eligible ⟹ eligible ⟹ s == t), where it equals
              s >lex e ⟺ ¬(e>s) ∧ ¬(e==s).

    So a = (e >lex s) and b = (e ==lex s) are computed elementwise on
    the unsorted columns and packed into the key's two lowest bits:
    key = cell<<26 | idx<<2 | b<<1 | a. The key still total-orders by
    (cell, idx) — idx is unique, the flag bits are never reached — so
    the sort order, masks, and every downstream stage are BIT-IDENTICAL
    to the payload form (property-pinned), but the sort carries 2 u64
    payloads instead of 4 (r4 pricing: ~0.75 ms/payload at 1M).

    Capacity: idx needs 24 bits and cell 36 (n ≤ 2^24 — same guard as
    the packed-key form; larger batches fall back to the payload
    core). The winner-cache kernel keeps `plan_merge_sorted_core`: its
    `return_winners` scatter needs the stored-winner VALUES.

    MUST be traced inside an enable_x64(True) scope (guarded below).
    """
    n = cell_id.shape[0]
    if n > 1 << 24:
        return plan_merge_sorted_core(cell_id, k1, k2, ex_k1, ex_k2, extras)
    idx = jnp.arange(n, dtype=jnp.int32)
    a, b = winner_flags(k1, k2, ex_k1, ex_k2)
    key = (
        (cell_id.astype(jnp.int64) << jnp.int64(26))
        | (idx.astype(jnp.int64) << jnp.int64(2))
        | (b.astype(jnp.int64) << jnp.int64(1))
        | a.astype(jnp.int64)
    )
    if key.dtype != jnp.dtype("int64"):  # x64 disabled: would mis-plan
        raise TypeError(
            "plan_merge_sorted_flags must be traced under enable_x64(True): "
            f"packed merge key degraded to {key.dtype}"
        )
    sorted_ops = jax.lax.sort((key, k1, k2) + tuple(extras), num_keys=1, is_stable=False)
    key_s = sorted_ops[0]
    c = (key_s >> jnp.int64(26)).astype(jnp.int32)
    i_s = ((key_s >> jnp.int64(2)) & jnp.int64((1 << 24) - 1)).astype(jnp.int32)
    a_s = (key_s & jnp.int64(1)) != 0
    b_s = (key_s & jnp.int64(2)) != 0
    s1, s2 = sorted_ops[1:3]
    extras_sorted = sorted_ops[3:]
    xor_sorted, upsert_sorted = masks_from_sorted_flags(
        key_s >> jnp.int64(26), s1, s2, a_s, b_s, c != _PAD_CELL
    )
    return xor_sorted, upsert_sorted, i_s, s1, s2, extras_sorted


def unpermute_masks(xor_sorted, upsert_sorted, i_s, block_size: int = 0):
    """Host side: sorted-order masks + permutation → original batch
    order. With `block_size` > 0 the arrays are concatenated per-shard
    blocks whose `i_s` values are shard-local (the shard_map layout);
    each block unpermutes within its own span. Callers on the hot path
    pre-pull device outputs with `to_host_many` (one transfer wave);
    `to_host` below then no-ops on the numpy arrays."""
    from evolu_tpu.ops import to_host

    xor_sorted = to_host(xor_sorted)
    upsert_sorted = to_host(upsert_sorted)
    i_s = to_host(i_s).astype(np.int64)
    if block_size:
        base = (np.arange(len(i_s), dtype=np.int64) // block_size) * block_size
        i_s = i_s + base
    xor_mask = np.empty_like(xor_sorted)
    upsert_mask = np.empty_like(upsert_sorted)
    xor_mask[i_s] = xor_sorted
    upsert_mask[i_s] = upsert_sorted
    return xor_mask, upsert_mask


def plan_merge_core(cell_id, k1, k2, ex_k1, ex_k2, num_segments: int):
    """Original-order planner: `plan_merge_sorted_core` plus a device
    restoring sort. Kept for callers that need device-resident masks in
    batch order; the shard kernels use the sorted variant and let the
    host unpermute (saves a 1M-row sort per batch).

    Returns (xor_mask, upsert_mask) bools in original batch order.
    """
    del num_segments
    xor_sorted, upsert_sorted, i_s, _, _, _ = plan_merge_sorted_core(
        cell_id, k1, k2, ex_k1, ex_k2
    )
    # A bitonic sort beats a 1M-element scatter on TPU.
    _, xor_mask, upsert_mask = jax.lax.sort(
        (i_s, xor_sorted, upsert_sorted), num_keys=1
    )
    return xor_mask, upsert_mask


plan_merge = jax.jit(plan_merge_core, static_argnames=("num_segments",))


class PlannedBatch(tuple):
    """A planner result that unpacks as the usual (xor_mask, upserts,
    deltas) 3-tuple but also carries the positional bool `upsert_mask`,
    so `storage.apply.apply_messages` can hand the mask straight to the
    C++ `apply_planned` instead of rebuilding it from `upserts` with a
    per-message Python pass."""

    def __new__(cls, xor_mask, upserts, deltas, upsert_mask=None):
        self = super().__new__(cls, (xor_mask, upserts, deltas))
        self.upsert_mask = upsert_mask
        return self


def strip_typed_upserts(plan, messages, schema):
    """Typed-cell plan selection (ISSUE 7), ONE copy for every planner
    (host oracle, device full plan, HBM winner cache, hot-owner shard):
    typed cells NEVER take the LWW app-table upsert — their app value
    is the merge-state materialization (`core.crdt_types`), not the
    winning op's raw value. The xor mask and Merkle deltas are
    TIMESTAMP-ONLY and stay untouched: replication and the winner
    cache's MAX(timestamp) slots are type-agnostic by construction.

    Accepts the 2-tuple, 3-tuple, or PlannedBatch plan shapes and
    returns the same shape with typed upserts removed."""
    typed_idx = [
        i for i, m in enumerate(messages) if schema.is_typed(m.table, m.column)
    ]
    if not typed_idx:
        return plan
    metrics.inc("evolu_crdt_upserts_stripped_total", len(typed_idx))

    def keep(m):
        return not schema.is_typed(m.table, m.column)

    if isinstance(plan, PlannedBatch):
        xor_mask, upserts, deltas = plan
        mask = plan.upsert_mask
        if mask is not None:
            mask = np.array(mask, copy=True)
            mask[typed_idx] = False
        return PlannedBatch(
            xor_mask, [m for m in upserts if keep(m)], deltas, mask
        )
    if len(plan) == 3:
        xor_mask, upserts, deltas = plan
        return xor_mask, [m for m in upserts if keep(m)], deltas
    xor_mask, upserts = plan
    return xor_mask, [m for m in upserts if keep(m)]


def select_messages(messages: Sequence[CrdtMessage], mask: np.ndarray) -> List[CrdtMessage]:
    """messages[i] for mask[i], without a per-message Python loop."""
    ix = np.nonzero(mask)[0]
    if len(ix) == 0:
        return []
    if len(ix) == 1:
        return [messages[int(ix[0])]]
    return list(operator.itemgetter(*ix)(messages))


def winner_key_columns(cells, winners: Dict[Tuple[str, str, str], str]):
    """Per-unique-cell stored-winner key columns: → (ex1_u, ex2_u,
    canonical), zeros where a cell has no stored winner. The ONE
    implementation of winner parse/pack/canonical-check — shared by
    `messages_to_columns`, the HBM cache's lazy seeding, and its
    streamed mode, so the canonical-case rule (a golden-parity
    invariant) can never drift between them."""
    from evolu_tpu.ops.host_parse import parse_timestamp_strings

    ex1_u = np.zeros(len(cells), np.uint64)
    ex2_u = np.zeros(len(cells), np.uint64)
    winner_cids = [i for i, cell in enumerate(cells) if cell in winners]
    canonical = True
    if winner_cids:
        w_millis, w_counter, w_node, w_case_ok = parse_timestamp_strings(
            [winners[cells[i]] for i in winner_cids], with_case=True
        )
        canonical = bool(w_case_ok.all())
        ex1_u[winner_cids] = pack_ts_key_host(w_millis, w_counter)
        ex2_u[winner_cids] = w_node
    return ex1_u, ex2_u, canonical


def messages_to_columns(
    messages: Sequence[CrdtMessage],
    existing_winners: Dict[Tuple[str, str, str], str],
):
    """Host-side columnarization: intern cells, parse timestamps, pack
    keys — fully vectorized (numpy); no per-message Python. A malformed
    timestamp raises TimestampParseError for the whole batch (matching
    the scalar parser's abort-the-transaction behavior).

    Returns numpy arrays (cell_id, k1, k2, ex_k1, ex_k2) plus the parsed
    (millis, counter, node_u64) columns for the Merkle kernel, plus a
    trailing `canonical` bool: False when any message or stored winner
    uses non-canonical hex case — the device kernels order by numeric
    keys and hash a canonical re-render, which matches the reference's
    raw-string order / verbatim-node hash ONLY for canonical strings,
    so such batches must take the host oracle path.
    """
    from evolu_tpu.ops.host_parse import intern_cells, parse_timestamp_strings

    millis, counter, node, case_ok = parse_timestamp_strings(
        [m.timestamp for m in messages], with_case=True
    )
    canonical = bool(case_ok.all())
    cell_ids, cells = intern_cells(
        [m.table for m in messages], [m.row for m in messages],
        [m.column for m in messages],
    )

    # Stored winners per unique cell (parsed as one vectorized batch).
    ex1_u, ex2_u, winners_canonical = winner_key_columns(cells, existing_winners)
    canonical = canonical and winners_canonical
    ex_k1 = ex1_u[cell_ids]
    ex_k2 = ex2_u[cell_ids]

    k1 = pack_ts_key_host(millis, counter)
    k2 = node
    return cell_ids, k1, k2, ex_k1, ex_k2, millis, counter, node, canonical


def pad_columns(arrays, n: int, pad_cell: bool = True):
    """Pad 1-D columns to the power-of-two bucket ≥ n. First array is
    cell_id (padded with _PAD_CELL); the rest pad with 0."""
    size = bucket_size(n)
    out = []
    for j, a in enumerate(arrays):
        pad_val = int(_PAD_CELL) if (j == 0 and pad_cell) else 0
        p = np.full(size - n, pad_val, dtype=a.dtype)
        out.append(np.concatenate([a, p]))
    return out, size


@with_x64
def plan_batch_device(
    messages: Sequence[CrdtMessage],
    existing_winners: Dict[Tuple[str, str, str], str],
):
    """Drop-in replacement for the host `storage.apply.plan_batch` with
    the decision masks computed on device. Same return contract:
    (xor_mask: list[bool], upserts: list[CrdtMessage])."""
    n = len(messages)
    if n == 0:
        return [], []
    with span("kernel:merge", "plan_batch_device", n=n):
        plan = _plan_batch_device_timed(messages, existing_winners)
    if plan is None:
        return _host_fallback(messages, existing_winners, n)
    return plan


def _host_fallback(messages, existing_winners, n, with_deltas=False):
    """Non-canonical hex case in the batch (or its stored winners):
    device numeric order / canonical-render hash would diverge from the
    reference's raw-string semantics, so route to the host oracle —
    loudly, so a throughput collapse (e.g. an adversarial client
    persisting a non-canonical winner into a hot cell) is visible in
    the kernel logs. `with_deltas` keeps plan_batch_device_full's
    3-tuple contract (host fold with verbatim node case)."""
    from evolu_tpu.obs import ledger, metrics
    from evolu_tpu.storage.apply import plan_batch
    from evolu_tpu.utils.log import log

    metrics.inc("evolu_merge_host_fallbacks_total")
    metrics.inc("evolu_merge_host_fallback_messages_total", n)
    # Ledger TALLY stations (outside the flow equations — the batch's
    # flow still terminates through whichever apply route consumes this
    # plan): how many messages were planned by the host oracle, and the
    # canonicality bounce that sent them here.
    ledger.count(ledger.ROUTE_HOST_FALLBACK, n)
    ledger.count(ledger.BOUNCE_NON_CANONICAL, n)
    log("kernel:merge", "non-canonical hex case: host-planner fallback", n=n)
    xor_mask, upserts = plan_batch(messages, existing_winners)
    if not with_deltas:
        return xor_mask, upserts
    from evolu_tpu.core.merkle import minute_deltas_host

    deltas, _ = minute_deltas_host(
        m.timestamp for flag, m in zip(xor_mask, messages) if flag
    )
    return xor_mask, upserts, deltas


def _plan_batch_device_timed(messages, existing_winners):
    from evolu_tpu.ops.scatter_merge import plan_masks_scatter, scatter_table_for

    n = len(messages)
    cell_ids, k1, k2, ex_k1, ex_k2, *rest = messages_to_columns(messages, existing_winners)
    if not rest[-1]:  # canonical flag
        return None
    table_size = scatter_table_for(cell_ids, k1, k2)
    (cell_ids, k1, k2, ex_k1, ex_k2), size = pad_columns([cell_ids, k1, k2, ex_k1, ex_k2], n)
    if table_size is not None:
        metrics.inc("evolu_merge_plan_total", path="scatter")
        xor_mask, upsert_mask = to_host_many(*plan_masks_scatter(
            jnp.asarray(cell_ids), jnp.asarray(k1), jnp.asarray(k2),
            jnp.asarray(ex_k1), jnp.asarray(ex_k2), table_size=table_size,
        ))
    else:
        metrics.inc("evolu_merge_plan_total", path="sort")
        xor_mask, upsert_mask = to_host_many(*plan_merge(
            jnp.asarray(cell_ids), jnp.asarray(k1), jnp.asarray(k2),
            jnp.asarray(ex_k1), jnp.asarray(ex_k2), num_segments=size,
        ))
    return xor_mask[:n].tolist(), select_messages(messages, upsert_mask[:n])


@jax.jit
def _plan_full_kernel(cell_id, k1, k2, ex_k1, ex_k2):
    """Masks + per-minute Merkle XOR deltas in ONE dispatch, all in the
    planner's cell-sorted order (timestamp columns recovered from the
    sorted HLC keys; the single-owner minute segmentation runs with
    owner key 0)."""
    from evolu_tpu.ops.encode import timestamp_hashes, unpack_ts_keys
    from evolu_tpu.ops.merkle_ops import owner_minute_segments

    xor_s, upsert_s, i_s, s1, s2, _ = plan_merge_sorted_flags(cell_id, k1, k2, ex_k1, ex_k2)
    millis_s, counter_s = unpack_ts_keys(s1)
    hashes = jnp.where(xor_s, timestamp_hashes(millis_s, counter_s, s2), jnp.uint32(0))
    zero_owner = jnp.zeros((), jnp.int32)
    _, minute_sorted, seg_end, seg_xor, valid_sorted = owner_minute_segments(
        zero_owner, millis_s, hashes, xor_s
    )
    return xor_s, upsert_s, i_s, minute_sorted, seg_end, seg_xor, valid_sorted


@functools.partial(jax.jit, static_argnames=("table_size",))
def _plan_full_kernel_scatter(cell_id, k1, k2, ex_k1, ex_k2, table_size):
    """Sort-free twin of `_plan_full_kernel` (ops/scatter_merge.py):
    the LWW masks come from the dense scatter-argmax plan in ORIGINAL
    batch order (i_s is the identity — `unpermute_masks` degenerates
    to a copy), and the minute segmentation consumes the original-
    order columns directly (its own tile-local grouping sort is
    order-free — every decoder XOR-merges per key). Same 7-output
    contract; host-level results are bit-identical to the sorted
    kernel wherever the router admits a batch (property-pinned)."""
    from evolu_tpu.ops.encode import timestamp_hashes, unpack_ts_keys
    from evolu_tpu.ops.merkle_ops import owner_minute_segments
    from evolu_tpu.ops.scatter_merge import scatter_plan_masks

    xor_m, upsert_m = scatter_plan_masks(cell_id, k1, k2, ex_k1, ex_k2, table_size)
    i_s = jnp.arange(cell_id.shape[0], dtype=jnp.int32)
    millis, counter = unpack_ts_keys(k1)
    hashes = jnp.where(xor_m, timestamp_hashes(millis, counter, k2), jnp.uint32(0))
    zero_owner = jnp.zeros((), jnp.int32)
    _, minute_sorted, seg_end, seg_xor, valid_sorted = owner_minute_segments(
        zero_owner, millis, hashes, xor_m
    )
    return xor_m, upsert_m, i_s, minute_sorted, seg_end, seg_xor, valid_sorted


def plan_packed_streamed(db, pb, millis, counter, node, cells, touched_ids):
    """Packed plan with winners streamed from SQLite for the touched
    cells — ONE copy of the fetch + scatter + kernel-call sequence,
    shared by the winner cache's streaming mode and the no-cache packed
    route (they must stay identical or the cache-on/off paths diverge).
    `cells` are the touched unique cells; `touched_ids` their indices
    into `pb.cells`. None on a non-canonical stored winner (the caller
    materializes to the object path)."""
    from evolu_tpu.storage.apply import fetch_existing_winners

    winners = fetch_existing_winners(db, cells)
    ex1_t, ex2_t, canonical = winner_key_columns(cells, winners)
    if not canonical:
        return None
    ex1 = np.zeros(len(pb.cells), np.uint64)
    ex2 = np.zeros(len(pb.cells), np.uint64)
    ex1[touched_ids] = ex1_t
    ex2[touched_ids] = ex2_t
    k1 = pack_ts_key_host(millis, counter)
    return plan_packed_device_full(
        pb.cell_id, k1, node, ex1[pb.cell_id], ex2[pb.cell_id], pb.n
    )


def _run_full_plan(cell_ids, k1, k2, ex_k1, ex_k2, n: int):
    """ONE copy of the full-plan dispatch sequence (pad →
    `_plan_full_kernel` → one-wave pull → unpermute → delta decode),
    shared by `plan_batch_device_full` and `plan_packed_device_full` —
    the object and packed routes must produce identical plans, so the
    sequence lives here. → (xor_mask, upsert_mask, deltas), masks in
    batch order, length n. Callers hold the x64 scope and have already
    verified the canonical-case invariant."""
    from evolu_tpu.ops.merkle_ops import decode_owner_minute_deltas
    from evolu_tpu.ops.scatter_merge import scatter_table_for

    # Admission + table sizing in one pre-pad pass (pad rows use the
    # dump slot, never the table).
    table_size = scatter_table_for(cell_ids, k1, k2)
    (cell_ids, k1, k2, ex_k1, ex_k2), size = pad_columns(
        [cell_ids, k1, k2, ex_k1, ex_k2], n
    )
    if table_size is not None:
        metrics.inc("evolu_merge_plan_total", path="scatter")
        outs = _plan_full_kernel_scatter(
            jnp.asarray(cell_ids), jnp.asarray(k1), jnp.asarray(k2),
            jnp.asarray(ex_k1), jnp.asarray(ex_k2), table_size=table_size,
        )
    else:
        metrics.inc("evolu_merge_plan_total", path="sort")
        outs = _plan_full_kernel(
            jnp.asarray(cell_ids), jnp.asarray(k1), jnp.asarray(k2),
            jnp.asarray(ex_k1), jnp.asarray(ex_k2),
        )
    xor_s, upsert_s, i_s, minute_sorted, seg_end, seg_xor, valid = to_host_many(*outs)
    xor_mask, upsert_mask = unpermute_masks(xor_s, upsert_s, i_s)
    deltas = decode_owner_minute_deltas(
        np.zeros(size, np.int32), minute_sorted, seg_end, seg_xor, valid
    ).get(0, {})
    return xor_mask[:n], upsert_mask[:n], deltas


@with_x64
def plan_packed_device_full(cell_ids, k1, k2, ex_k1, ex_k2, n: int):
    """Columns-only twin of `plan_batch_device_full` for the fused
    receive path (PackedReceive): same kernel, but the result is
    `(xor_mask, upsert_mask, deltas)` with positional numpy masks
    only — the packed SQLite apply binds straight from the batch
    buffers, so no `upserts` message list is ever built."""
    with span("kernel:merge", "plan_packed_device_full", n=n):
        return _run_full_plan(cell_ids, k1, k2, ex_k1, ex_k2, n)


@with_x64
def plan_batch_device_full(
    messages: Sequence[CrdtMessage],
    existing_winners: Dict[Tuple[str, str, str], str],
    cols=None,
):
    """Like `plan_batch_device` but ALSO returns the per-minute Merkle
    XOR deltas computed on device — `(xor_mask, upserts, deltas)` — so
    the apply path never hashes timestamps in Python (the reference's
    hot loop #4 eliminated host-side). `cols` optionally reuses a
    caller's `messages_to_columns` result."""
    n = len(messages)
    if n == 0:
        return [], [], {}
    with span("kernel:merge", "plan_batch_device_full", n=n):
        cell_ids, k1, k2, ex_k1, ex_k2, *rest = (
            cols if cols is not None else messages_to_columns(messages, existing_winners)
        )
        if not rest[-1]:  # canonical flag
            return _host_fallback(messages, existing_winners, n, with_deltas=True)
        xor_mask, upsert_mask, deltas = _run_full_plan(
            cell_ids, k1, k2, ex_k1, ex_k2, n
        )
        return PlannedBatch(
            xor_mask.tolist(), select_messages(messages, upsert_mask), deltas, upsert_mask
        )
