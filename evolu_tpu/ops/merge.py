"""Batched LWW merge planner on device.

Replaces the reference's per-message SQL loop (reference
packages/evolu/src/applyMessages.ts:78-124) with one columnar pass:

    stable sort by (cell, batch order)
      → segmented exclusive prefix-max of HLC keys (running winner)
      → xor mask   (message's hash goes into the Merkle tree)
      → segmented total max (final winner per cell)
      → upsert mask (final winner beats the stored winner)

Semantics are *exactly* the sequential loop's, including its quirks:
the Merkle XOR is gated on "running winner != message timestamp", not
on the __message insert actually inserting, so a re-received
non-winning duplicate XORs again (applyMessages.ts:104-122) — the
running winner is the max of the stored winner and all *earlier batch
messages* for the same cell, in batch order.

HLC keys are (k1, k2) uint64 pairs from `encode.pack_ts_keys` — k1 =
millis<<16|counter, k2 = node — compared lexicographically; (0, 0) is
the "no stored winner" sentinel (see encode.pack_ts_keys docstring).

Everything here is shape-static and jit-compiled once per bucket size;
`plan_batch_device` pads to power-of-two buckets to avoid recompiles
(SURVEY.md §7 "dynamic shapes").
"""

from __future__ import annotations

from typing import Dict, List, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from evolu_tpu.core.timestamp import timestamp_from_string
from evolu_tpu.core.types import CrdtMessage
from evolu_tpu.ops import with_x64
from evolu_tpu.ops.encode import node_hex_to_u64, pack_ts_key_host

_PAD_CELL = jnp.int32(0x7FFFFFFF)


def _lex_max(a1, a2, b1, b2):
    """Elementwise max of (a1,a2) vs (b1,b2) under lexicographic order."""
    a_wins = (a1 > b1) | ((a1 == b1) & (a2 >= b2))
    return jnp.where(a_wins, a1, b1), jnp.where(a_wins, a2, b2)


def _segmented_max_scan(flags, k1, k2):
    """Inclusive segmented lexicographic max scan.

    flags[i] marks a segment start. Monoid on (flag, k1, k2): the right
    operand wins outright when it starts a segment.
    """

    def combine(left, right):
        lf, l1, l2 = left
        rf, r1, r2 = right
        m1, m2 = _lex_max(l1, l2, r1, r2)
        return lf | rf, jnp.where(rf, r1, m1), jnp.where(rf, r2, m2)

    _, m1, m2 = jax.lax.associative_scan(combine, (flags, k1, k2))
    return m1, m2


def plan_merge_core(cell_id, k1, k2, ex_k1, ex_k2, num_segments: int):
    """The device LWW planner (traceable core — also called inside
    `shard_map` by `evolu_tpu.parallel.reconcile`, where each shard
    plans its owners' messages independently).

    Args (all shape (N,), padding rows use cell_id=_PAD_CELL, keys 0):
      cell_id: int32 interned (table,row,column) id per message.
      k1, k2: uint64 HLC sort keys per message.
      ex_k1, ex_k2: uint64 stored-winner keys for the message's cell
        ((0,0) = no stored winner).
      num_segments: static upper bound on distinct cells (= N).

    Returns (xor_mask, upsert_mask) bools in original batch order.
    """
    n = cell_id.shape[0]
    idx = jnp.arange(n, dtype=jnp.int32)

    # Stable sort by cell, preserving batch order within a cell.
    order = jnp.lexsort((idx, cell_id))
    c = cell_id[order]
    s1, s2 = k1[order], k2[order]
    e1, e2 = ex_k1[order], ex_k2[order]

    seg_start = jnp.concatenate([jnp.ones((1,), bool), c[1:] != c[:-1]])
    seg_ids = jnp.cumsum(seg_start.astype(jnp.int32)) - 1

    # Running winner BEFORE each message: exclusive segmented max of the
    # batch keys, seeded with the stored winner.
    m1, m2 = _segmented_max_scan(seg_start, s1, s2)
    zero = jnp.zeros((), jnp.uint64)
    p1 = jnp.where(seg_start, zero, jnp.roll(m1, 1))
    p2 = jnp.where(seg_start, zero, jnp.roll(m2, 1))
    r1, r2 = _lex_max(p1, p2, e1, e2)
    xor_sorted = (r1 != s1) | (r2 != s2)

    # Final winner per cell: segment-wide lexicographic max.
    t1 = jax.ops.segment_max(s1, seg_ids, num_segments=num_segments)[seg_ids]
    is_max1 = s1 == t1
    t2 = jax.ops.segment_max(jnp.where(is_max1, s2, zero), seg_ids, num_segments=num_segments)[seg_ids]
    eligible = is_max1 & (s2 == t2)
    # First eligible in batch order: segmented rank via global cumsum
    # minus the segment's base (cumsum-before-segment, which equals the
    # segment-min of the nondecreasing `cume - eligible`).
    cume = jnp.cumsum(eligible.astype(jnp.int32))
    base = jax.ops.segment_min(
        cume - eligible.astype(jnp.int32), seg_ids, num_segments=num_segments
    )[seg_ids]
    first_eligible = eligible & (cume - base == 1)
    # Winner strictly beats the stored winner iff lex_max(t, e) != e.
    beats1, beats2 = _lex_max(t1, t2, e1, e2)
    beats = (beats1 != e1) | (beats2 != e2)
    upsert_sorted = first_eligible & beats & (c != _PAD_CELL)

    xor_mask = jnp.zeros((n,), bool).at[order].set(xor_sorted & (c != _PAD_CELL))
    upsert_mask = jnp.zeros((n,), bool).at[order].set(upsert_sorted)
    return xor_mask, upsert_mask


plan_merge = jax.jit(plan_merge_core, static_argnames=("num_segments",))


def _bucket_size(n: int) -> int:
    size = 64
    while size < n:
        size *= 2
    return size


def messages_to_columns(
    messages: Sequence[CrdtMessage],
    existing_winners: Dict[Tuple[str, str, str], str],
):
    """Host-side columnarization: intern cells, parse timestamps, pack keys.

    Returns numpy arrays (cell_id, k1, k2, ex_k1, ex_k2) plus the parsed
    (millis, counter, node_u64) columns for the Merkle kernel.
    """
    n = len(messages)
    cell_ids = np.empty(n, np.int32)
    millis = np.empty(n, np.int64)
    counter = np.empty(n, np.int32)
    node = np.empty(n, np.uint64)
    ex_k1 = np.zeros(n, np.uint64)
    ex_k2 = np.zeros(n, np.uint64)
    intern: Dict[Tuple[str, str, str], int] = {}
    ex_cache: Dict[int, Tuple[int, int]] = {}
    for i, m in enumerate(messages):
        cell = (m.table, m.row, m.column)
        cid = intern.setdefault(cell, len(intern))
        cell_ids[i] = cid
        t = timestamp_from_string(m.timestamp)
        millis[i], counter[i] = t.millis, t.counter
        node[i] = node_hex_to_u64(t.node)
        if cid not in ex_cache:
            w = existing_winners.get(cell)
            if w is None:
                ex_cache[cid] = (0, 0)
            else:
                wt = timestamp_from_string(w)
                ex_cache[cid] = (pack_ts_key_host(wt.millis, wt.counter), node_hex_to_u64(wt.node))
        ex_k1[i], ex_k2[i] = ex_cache[cid]
    k1 = pack_ts_key_host(millis, counter)
    k2 = node
    return cell_ids, k1, k2, ex_k1, ex_k2, millis, counter, node


def pad_columns(arrays, n: int, pad_cell: bool = True):
    """Pad 1-D columns to the power-of-two bucket ≥ n. First array is
    cell_id (padded with _PAD_CELL); the rest pad with 0."""
    size = _bucket_size(n)
    out = []
    for j, a in enumerate(arrays):
        pad_val = int(_PAD_CELL) if (j == 0 and pad_cell) else 0
        p = np.full(size - n, pad_val, dtype=a.dtype)
        out.append(np.concatenate([a, p]))
    return out, size


@with_x64
def plan_batch_device(
    messages: Sequence[CrdtMessage],
    existing_winners: Dict[Tuple[str, str, str], str],
):
    """Drop-in replacement for the host `storage.apply.plan_batch` with
    the decision masks computed on device. Same return contract:
    (xor_mask: list[bool], upserts: list[CrdtMessage])."""
    n = len(messages)
    if n == 0:
        return [], []
    cell_ids, k1, k2, ex_k1, ex_k2, *_ = messages_to_columns(messages, existing_winners)
    (cell_ids, k1, k2, ex_k1, ex_k2), size = pad_columns([cell_ids, k1, k2, ex_k1, ex_k2], n)
    xor_mask, upsert_mask = plan_merge(
        jnp.asarray(cell_ids), jnp.asarray(k1), jnp.asarray(k2),
        jnp.asarray(ex_k1), jnp.asarray(ex_k2), num_segments=size,
    )
    xor_mask = np.asarray(xor_mask)[:n]
    upsert_mask = np.asarray(upsert_mask)[:n]
    upserts: List[CrdtMessage] = [m for i, m in enumerate(messages) if upsert_mask[i]]
    return list(map(bool, xor_mask)), upserts
