"""Batched Merkle-trie updates on device.

The reference inserts timestamps into the trie one at a time, XORing
the murmur hash into every node on the root→minute path (reference
packages/evolu/src/merkleTree.ts:31-50). XOR is associative and
commutative, so a whole batch reduces to **one XOR delta per distinct
minute**; the host then applies each delta along its ≤16-node path
(`core.merkle.apply_prefix_xors`), touching O(distinct-minutes × 16)
nodes instead of O(batch × 16).

Device pass: hash timestamps (fully on device, `encode.timestamp_hashes`)
→ minute key with JS `|0` int32 truncation (merkleTree.ts:39) → sort by
minute → segmented XOR reduce via the prefix-XOR trick (segment XOR =
prefix[end] ^ prefix[prev_end]).

Hashes are uint32 on device; the host converts to JS signed int32 when
writing trie nodes.
"""

from __future__ import annotations

import functools
from typing import Dict

import jax
import jax.numpy as jnp
import numpy as np

from evolu_tpu.core.merkle import minutes_base3
from evolu_tpu.core.murmur import to_int32
from evolu_tpu.ops import with_x64
from evolu_tpu.ops.encode import timestamp_hashes


def segment_xor_core(keys_i64, hashes_u32, valid):
    """Sorted segmented-XOR reduce (traceable core).

    Sort rows by int64 key; per distinct key, XOR the hashes of its
    rows. Invalid rows must already carry hash 0 and the out-of-range
    sentinel key. Returns (keys_sorted, seg_end, seg_xor, valid_sorted),
    all (N,); rows where seg_end is True give one (key, xor) per
    distinct key.
    """
    n = keys_i64.shape[0]
    order = jnp.argsort(keys_i64)
    m_sorted = keys_i64[order]
    h_sorted = hashes_u32[order]
    valid_sorted = valid[order]

    prefix = jax.lax.associative_scan(jnp.bitwise_xor, h_sorted)
    seg_end = jnp.concatenate([m_sorted[1:] != m_sorted[:-1], jnp.ones((1,), bool)])
    # XOR of a segment = prefix at its end ^ prefix at the previous
    # segment's end. Propagate "index of previous segment end" forward
    # with a running max (-1 = no previous segment).
    idx = jnp.arange(n)
    seg_first = jnp.concatenate([jnp.zeros((1,), bool), seg_end[:-1]])
    prev_end = jax.lax.associative_scan(jnp.maximum, jnp.where(seg_first, idx - 1, -1))
    prev_end_prefix = jnp.where(prev_end >= 0, prefix[jnp.maximum(prev_end, 0)], jnp.uint32(0))
    seg_xor = prefix ^ prev_end_prefix
    return m_sorted, seg_end, seg_xor, valid_sorted


_SENTINEL_KEY = 1 << 62  # Python int: jnp.int64 at import time (outside x64) truncates


def js_minutes(millis):
    """JS `((millis/1000/60) | 0)` — float-divide then truncate to int32.
    millis >= 0 so floor == trunc; int32 cast wraps like `|0`."""
    return (millis // 60000).astype(jnp.int32)


def minute_deltas_core(millis, counter, node, xor_mask):
    """Per-minute XOR deltas for a timestamp batch (traceable core).

    Args (shape (N,)): millis int64, counter int32, node uint64,
      xor_mask bool (False rows contribute nothing — padding or
      messages whose hash the merge planner excluded).

    Masked rows park in a sentinel key outside the int32 range so they
    can never share a segment with a real (wrapped) minute.
    """
    hashes = jnp.where(xor_mask, timestamp_hashes(millis, counter, node), jnp.uint32(0))
    keys = jnp.where(xor_mask, js_minutes(millis).astype(jnp.int64), jnp.int64(_SENTINEL_KEY))
    return segment_xor_core(keys, hashes, xor_mask)


merkle_minute_deltas = with_x64(jax.jit(minute_deltas_core))


def minute_deltas_to_dict(m_sorted, seg_end, seg_xor, valid_sorted) -> Dict[str, int]:
    """Host side: device outputs → {base3-minute-key: signed-int32 delta}
    consumable by `core.merkle.apply_prefix_xors`."""
    m = np.asarray(m_sorted)
    ends = np.asarray(seg_end)
    xs = np.asarray(seg_xor)
    valid = np.asarray(valid_sorted)
    out: Dict[str, int] = {}
    for i in np.nonzero(ends)[0]:
        if not valid[i]:
            continue  # the sentinel segment (masked rows)
        minute = int(m[i])
        out[minutes_base3(minute * 60000)] = to_int32(int(xs[i]))
    return out
