"""Batched Merkle-trie updates on device.

The reference inserts timestamps into the trie one at a time, XORing
the murmur hash into every node on the root→minute path (reference
packages/evolu/src/merkleTree.ts:31-50). XOR is associative and
commutative, so a whole batch reduces to **one XOR delta per distinct
minute**; the host then applies each delta along its ≤16-node path
(`core.merkle.apply_prefix_xors`), touching O(distinct-minutes × 16)
nodes instead of O(batch × 16).

Device pass: hash timestamps (fully on device, `encode.timestamp_hashes`)
→ minute key with JS `|0` int32 truncation (merkleTree.ts:39) → sort by
minute → segmented XOR reduce via the prefix-XOR trick (segment XOR =
prefix[end] ^ prefix[prev_end]).

Hashes are uint32 on device; the host converts to JS signed int32 when
writing trie nodes.
"""

from __future__ import annotations

import functools
from typing import Dict

import jax
import jax.numpy as jnp
import numpy as np

from evolu_tpu.core.merkle import minutes_base3
from evolu_tpu.core.murmur import to_int32
from evolu_tpu.ops import to_host, with_x64
from evolu_tpu.ops.encode import timestamp_hashes


_SENTINEL_HI = 0x7FFFFFFF  # int32 max: masked rows sort after every real key


def segment_xor2_core(hi_i32, lo_i32, hashes_u32, valid=None):
    """Sorted segmented-XOR reduce over an (hi, lo) int32 key pair
    (traceable core).

    Sort rows lexicographically by (hi, lo) — 32-bit keys, so the TPU
    sort never touches emulated 64-bit compares — carrying the hash as
    the only payload (no post-sort gathers). Per distinct key pair,
    XOR the hashes of its rows. Masked rows must carry hash 0 and
    hi = _SENTINEL_HI; validity is recovered from the sorted hi key
    itself rather than riding the sort as a payload. Returns
    (hi_sorted, lo_sorted, seg_end, seg_xor, valid_sorted), all (N,);
    rows where seg_end is True give one (key, xor) per distinct key.
    """
    del valid  # masked rows are identified by the hi sentinel
    n = hi_i32.shape[0]
    hi_s, lo_s, h_sorted = jax.lax.sort((hi_i32, lo_i32, hashes_u32), num_keys=2)
    valid_sorted = hi_s != jnp.int32(_SENTINEL_HI)

    prefix = jax.lax.associative_scan(jnp.bitwise_xor, h_sorted)
    seg_end = jnp.concatenate(
        [(hi_s[1:] != hi_s[:-1]) | (lo_s[1:] != lo_s[:-1]), jnp.ones((1,), bool)]
    )
    # XOR of a segment = prefix at its end ^ prefix at the previous
    # segment's end. Propagate "index of previous segment end" forward
    # with a running max (-1 = no previous segment).
    idx = jnp.arange(n, dtype=jnp.int32)
    seg_first = jnp.concatenate([jnp.zeros((1,), bool), seg_end[:-1]])
    prev_end = jax.lax.associative_scan(jnp.maximum, jnp.where(seg_first, idx - 1, -1))
    prev_end_prefix = jnp.where(prev_end >= 0, prefix[jnp.maximum(prev_end, 0)], jnp.uint32(0))
    seg_xor = prefix ^ prev_end_prefix
    return hi_s, lo_s, seg_end, seg_xor, valid_sorted


def js_minutes(millis):
    """JS `((millis/1000/60) | 0)` — float-divide then truncate to int32.
    millis >= 0 so floor == trunc; int32 cast wraps like `|0`."""
    return (millis // 60000).astype(jnp.int32)


def owner_minute_segments(owner_ix, millis, hashes_u32, valid):
    """Segmented XOR over (owner, minute) as an int32 key pair — owner
    in the hi key (sentinel int32-max for masked rows), JS-wrapped
    minute in the lo key — keeping the sort fully 32-bit. Shared by the
    client reconcile kernel and the server Merkle kernel.

    Returns (owner_sorted, minute_sorted, seg_end, seg_xor, valid_sorted).
    """
    hi = jnp.where(valid, owner_ix.astype(jnp.int32), jnp.int32(_SENTINEL_HI))
    lo = jnp.where(valid, js_minutes(millis), jnp.int32(0))
    return segment_xor2_core(hi, lo, hashes_u32, valid)


def decode_owner_minute_deltas(
    owner_sorted, minute_sorted, seg_end, seg_xor, valid_sorted
) -> Dict[int, Dict[str, int]]:
    """Host side: `owner_minute_segments` outputs → {owner_ix:
    {base3-minute-key: signed-int32 delta}} consumable by
    `core.merkle.apply_prefix_xors`.

    Repeated (owner, minute) keys XOR-combine: the owner-fleet layout
    never splits an owner so keys are unique there, but the hot-owner
    cell sharding produces one partial delta per shard per minute and
    relies on the XOR merge being exact (associative/commutative)."""
    owner_sorted = to_host(owner_sorted)
    minute_sorted = to_host(minute_sorted)
    ends = to_host(seg_end) & to_host(valid_sorted)
    xs = to_host(seg_xor)
    out: Dict[int, Dict[str, int]] = {}
    for i in np.nonzero(ends)[0]:
        o_ix, minute = int(owner_sorted[i]), int(minute_sorted[i])
        key = minutes_base3(minute * 60000)
        d = out.setdefault(o_ix, {})
        d[key] = to_int32(d.get(key, 0) ^ int(xs[i]))
    return out


def minute_deltas_core(millis, counter, node, xor_mask):
    """Per-minute XOR deltas for a timestamp batch (traceable core).

    Args (shape (N,)): millis int64, counter int32, node uint64,
      xor_mask bool (False rows contribute nothing — padding or
      messages whose hash the merge planner excluded).

    Masked rows park under the hi-key sentinel so they sort after (and
    never share a segment with) any real (wrapped) minute.
    """
    hashes = jnp.where(xor_mask, timestamp_hashes(millis, counter, node), jnp.uint32(0))
    hi = jnp.where(xor_mask, jnp.int32(0), jnp.int32(_SENTINEL_HI))
    lo = jnp.where(xor_mask, js_minutes(millis), jnp.int32(0))
    _, lo_s, seg_end, seg_xor, valid_sorted = segment_xor2_core(hi, lo, hashes, xor_mask)
    return lo_s.astype(jnp.int64), seg_end, seg_xor, valid_sorted


merkle_minute_deltas = with_x64(jax.jit(minute_deltas_core))


def minute_deltas_to_dict(m_sorted, seg_end, seg_xor, valid_sorted) -> Dict[str, int]:
    """Host side: device outputs → {base3-minute-key: signed-int32 delta}
    consumable by `core.merkle.apply_prefix_xors`."""
    m = np.asarray(m_sorted)
    ends = np.asarray(seg_end)
    xs = np.asarray(seg_xor)
    valid = np.asarray(valid_sorted)
    out: Dict[str, int] = {}
    for i in np.nonzero(ends)[0]:
        if not valid[i]:
            continue  # the sentinel segment (masked rows)
        minute = int(m[i])
        out[minutes_base3(minute * 60000)] = to_int32(int(xs[i]))
    return out
