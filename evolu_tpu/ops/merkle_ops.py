"""Batched Merkle-trie updates on device.

The reference inserts timestamps into the trie one at a time, XORing
the murmur hash into every node on the root→minute path (reference
packages/evolu/src/merkleTree.ts:31-50). XOR is associative and
commutative, so a whole batch reduces to **one XOR delta per distinct
minute**; the host then applies each delta along its ≤16-node path
(`core.merkle.apply_prefix_xors`), touching O(distinct-minutes × 16)
nodes instead of O(batch × 16).

Device pass: hash timestamps (fully on device, `encode.timestamp_hashes`)
→ minute key with JS `|0` int32 truncation (merkleTree.ts:39) → sort by
minute → ONE inclusive segmented XOR scan (blocked two-level on CPU,
single-pass Pallas on TPU); at each segment's last row the scan value
IS the segment's XOR total, the only positions decoders read.

Hashes are uint32 on device; the host converts to JS signed int32 when
writing trie nodes.
"""

from __future__ import annotations

import functools
from typing import Dict

import jax
import jax.numpy as jnp
import numpy as np

from evolu_tpu.core.merkle import minutes_base3
from evolu_tpu.core.murmur import to_int32
from evolu_tpu.ops import to_host, with_x64
from evolu_tpu.ops.encode import timestamp_hashes


_SENTINEL_HI = 0x7FFFFFFF  # int32 max: masked rows sort after every real key

_XOR_BLOCK = 256


def _seg_xor_combine(left, right):
    """Segmented XOR monoid on (flag, value): the operand nearest the
    scan head wins its prefix outright when flagged."""
    lf, lv = left
    rf, rv = right
    return lf | rf, jnp.where(rf, rv, lv ^ rv)


def segmented_xor_scan_reference(flags, values_u32):
    """Inclusive segmented XOR scan via associative_scan — the
    semantics reference (and the fallback for non-tiling lengths)."""
    _, out = jax.lax.associative_scan(_seg_xor_combine, (flags, values_u32))
    return out


def segmented_xor_scan(flags, values_u32):
    """Inclusive segmented XOR scan, blocked two-level formulation
    (same shape trick as `merge._segmented_max_scan`; the generic
    associative_scan lowering materializes log-depth concat/slice
    passes). On TPU at >=1 pallas tile the single-pass Pallas kernel
    takes over. Bit-identical to the reference (tests/test_ops.py,
    tests/test_pallas.py)."""
    from evolu_tpu.ops.merge import _PALLAS_SCAN_MIN, _use_pallas_scan

    n = flags.shape[0]
    # Pallas first: it pads internally, so it also covers non-tiling
    # lengths that would otherwise fall back to the slow generic
    # associative_scan (merge._segmented_max_scan orders it the same
    # way for the same reason).
    if n >= _PALLAS_SCAN_MIN and _use_pallas_scan():
        from evolu_tpu.ops.pallas_scan import segmented_xor_scan_pallas

        return segmented_xor_scan_pallas(flags, values_u32)
    L = min(_XOR_BLOCK, n)
    if n == 0 or n % L:
        return segmented_xor_scan_reference(flags, values_u32)
    s_f = flags.reshape(-1, L)
    s = values_u32.reshape(-1, L)
    shift = 1
    while shift < L:
        pf = jnp.pad(s_f[:, :-shift], ((0, 0), (shift, 0)), constant_values=False)
        pv = jnp.pad(s[:, :-shift], ((0, 0), (shift, 0)))
        s = jnp.where(s_f, s, pv ^ s)
        s_f = s_f | pf
        shift *= 2
    _, c = jax.lax.associative_scan(_seg_xor_combine, (s_f[:, -1], s[:, -1]))
    e = jnp.concatenate([jnp.zeros((1,), s.dtype), c[:-1]])
    out = jnp.where(s_f, s, e[:, None] ^ s)
    return out.reshape(n)


# Tile width for the block-local grouping sort. Measured on v5e at
# N=1M: full 1M packed-i64 sort 1.33 ms; row-wise sort of a
# (N/8192, 8192) view 0.24 ms (5.5×; 16384 → 0.76, 65536 → 1.02 —
# smaller tiles win, bounded below by per-tile segment inflation).
_GROUP_TILE = 8192


def segment_xor2_core(hi_i32, lo_i32, hashes_u32, valid=None, tile_local=True):
    """Sorted segmented-XOR reduce over an (hi, lo) int32 key pair
    (traceable core).

    Sort rows grouped by (hi, lo) as ONE packed int64 key — REQUIRES
    the x64 context (every production caller is with_x64-wrapped;
    under enable_x64(False) the << 32 would silently corrupt keys) —
    carrying the hash as the only payload (no post-sort gathers). Per distinct key pair,
    XOR the hashes of its rows via ONE segmented XOR scan (the r3
    rewrite: the previous prefix-xor + running-max + 1M-row-gather
    formulation cost ~10 ms/1M — two generic associative_scan
    lowerings plus a gather TPUs serialize). Masked rows must carry
    hash 0 and hi = _SENTINEL_HI; validity is recovered from the
    sorted hi key itself rather than riding the sort as a payload.
    Returns (hi_sorted, lo_sorted, seg_end, seg_xor, valid_sorted),
    all (N,); rows where seg_end & valid give one (key, xor) per
    distinct key — seg_xor is the INCLUSIVE segmented scan, so it
    equals the segment total exactly at those rows (the only positions
    decoders read).

    GROUPING IS TILE-LOCAL when the length tiles (r4): only grouping —
    never order — matters to the decoders, which XOR-merge repeated
    keys exactly (the hot-owner row split already relies on it), so
    the sort runs row-wise over a (N/8192, 8192) view (5.5× the full
    sort on v5e; XLA sorts each row in VMEM). A key spanning tiles
    emits one partial delta per tile; equal keys meeting at a tile
    junction fuse back into one segment (the boundary test below is
    purely key-equality on the flat view). The only cost is more
    seg_end rows for the host decoders — bounded by what N distinct
    minutes could already produce legitimately — and earlier
    compaction-cap overflows in the engine's compact transfer path
    (which falls back to the full pull, engine.deltas_finish).
    `tile_local=False` keeps the r3 global sort — the compact transfer
    kernel needs it, because its cap headroom is budgeted against
    DISTINCT keys, and tile partials would multiply seg_count by up to
    shard_size/8192, flipping realistic workloads into the full-pull
    fallback (seconds over the tunnel)."""
    del valid  # masked rows are identified by the hi sentinel
    # ONE packed int64 key, UNSTABLE: only the GROUPING of equal
    # (hi, lo) pairs matters, so the cheapest total order wins —
    # measured 1.95 (2×i32 keys, stable default) → 1.29 ms/1M on v5e,
    # → 0.24 ms tile-local. The original keys unpack from the sorted
    # key's halves.
    key = (hi_i32.astype(jnp.int64) << jnp.int64(32)) | lo_i32.astype(
        jnp.uint32
    ).astype(jnp.int64)
    n = key.shape[0]
    if tile_local and n >= 2 * _GROUP_TILE and n % _GROUP_TILE == 0:
        k2, h2 = jax.lax.sort(
            (key.reshape(-1, _GROUP_TILE), hashes_u32.reshape(-1, _GROUP_TILE)),
            dimension=1, num_keys=1, is_stable=False,
        )
        k_s, h_sorted = k2.reshape(n), h2.reshape(n)
    else:
        k_s, h_sorted = jax.lax.sort((key, hashes_u32), num_keys=1, is_stable=False)
    hi_s = (k_s >> jnp.int64(32)).astype(jnp.int32)
    lo_s = k_s.astype(jnp.int32)  # low 32 bits, int32 wrap = original lo
    valid_sorted = hi_s != jnp.int32(_SENTINEL_HI)
    key_change = k_s[1:] != k_s[:-1]
    seg_start = jnp.concatenate([jnp.ones((1,), bool), key_change])
    seg_end = jnp.concatenate([key_change, jnp.ones((1,), bool)])
    seg_xor = segmented_xor_scan(seg_start, h_sorted)
    return hi_s, lo_s, seg_end, seg_xor, valid_sorted


def js_minutes(millis):
    """JS `((millis/1000/60) | 0)` — float-divide then truncate to int32.
    millis >= 0 so floor == trunc; int32 cast wraps like `|0`.

    r5: the shared u32 hi/lo divmod chain replaces the emulated 64-bit
    division (0.39 ms/1M measured in-pipeline); out-of-range batches
    (pre-1970 / beyond 2106-02-07) keep the exact i64 path.
    Bit-identical either way (property-pinned incl. the boundary in
    tests/test_ops.py)."""
    from evolu_tpu.ops.encode import millis_range_cond, u32_divmod_hi_lo

    def fast(m):
        minute, _r = u32_divmod_hi_lo(m, 60000)
        return minute.astype(jnp.int32)

    def slow(m):
        return (m // 60000).astype(jnp.int32)

    return millis_range_cond(millis, fast, slow)


def owner_minute_segments(owner_ix, millis, hashes_u32, valid, tile_local=True):
    """Segmented XOR over (owner, minute) — owner in the hi half
    (sentinel int32-max for masked rows), JS-wrapped minute in the lo
    half of one packed int64 sort key (x64 context required; measured
    faster than 2×i32 keys on v5e). Shared by the client reconcile
    kernel and the server Merkle kernel (the latter's compact variant
    passes tile_local=False — see segment_xor2_core).

    Returns (owner_sorted, minute_sorted, seg_end, seg_xor, valid_sorted).
    """
    hi = jnp.where(valid, owner_ix.astype(jnp.int32), jnp.int32(_SENTINEL_HI))
    lo = jnp.where(valid, js_minutes(millis), jnp.int32(0))
    return segment_xor2_core(hi, lo, hashes_u32, valid, tile_local=tile_local)


def decode_owner_minute_deltas(
    owner_sorted, minute_sorted, seg_end, seg_xor, valid_sorted
) -> Dict[int, Dict[str, int]]:
    """Host side: `owner_minute_segments` outputs → {owner_ix:
    {base3-minute-key: signed-int32 delta}} consumable by
    `core.merkle.apply_prefix_xors`.

    Repeated (owner, minute) keys XOR-combine: the owner-fleet layout
    never splits an owner so keys are unique there, but the hot-owner
    cell sharding produces one partial delta per shard per minute and
    relies on the XOR merge being exact (associative/commutative)."""
    owner_sorted = to_host(owner_sorted)
    minute_sorted = to_host(minute_sorted)
    ends = to_host(seg_end) & to_host(valid_sorted)
    xs = to_host(seg_xor)
    out: Dict[int, Dict[str, int]] = {}
    for i in np.nonzero(ends)[0]:
        o_ix, minute = int(owner_sorted[i]), int(minute_sorted[i])
        key = minutes_base3(minute * 60000)
        d = out.setdefault(o_ix, {})
        d[key] = to_int32(d.get(key, 0) ^ int(xs[i]))
    return out


def minute_deltas_core(millis, counter, node, xor_mask):
    """Per-minute XOR deltas for a timestamp batch (traceable core).

    Args (shape (N,)): millis int64, counter int32, node uint64,
      xor_mask bool (False rows contribute nothing — padding or
      messages whose hash the merge planner excluded).

    Masked rows park under the hi-key sentinel so they sort after (and
    never share a segment with) any real (wrapped) minute.
    """
    hashes = jnp.where(xor_mask, timestamp_hashes(millis, counter, node), jnp.uint32(0))
    hi = jnp.where(xor_mask, jnp.int32(0), jnp.int32(_SENTINEL_HI))
    lo = jnp.where(xor_mask, js_minutes(millis), jnp.int32(0))
    _, lo_s, seg_end, seg_xor, valid_sorted = segment_xor2_core(hi, lo, hashes, xor_mask)
    return lo_s.astype(jnp.int64), seg_end, seg_xor, valid_sorted


merkle_minute_deltas = with_x64(jax.jit(minute_deltas_core))


def minute_deltas_to_dict(m_sorted, seg_end, seg_xor, valid_sorted) -> Dict[str, int]:
    """Host side: device outputs → {base3-minute-key: signed-int32 delta}
    consumable by `core.merkle.apply_prefix_xors`. Repeated minute keys
    XOR-combine — tile-local grouping (segment_xor2_core) emits one
    partial per tile for a minute spanning tiles, and the XOR merge is
    exact (same contract as decode_owner_minute_deltas)."""
    m = np.asarray(m_sorted)
    ends = np.asarray(seg_end)
    xs = np.asarray(seg_xor)
    valid = np.asarray(valid_sorted)
    out: Dict[str, int] = {}
    for i in np.nonzero(ends)[0]:
        if not valid[i]:
            continue  # the sentinel segment (masked rows)
        minute = int(m[i])
        key = minutes_base3(minute * 60000)
        out[key] = to_int32(out.get(key, 0) ^ int(xs[i]))
    return out
