"""Pallas TPU kernel for batched timestamp hashing.

Same computation as `encode.timestamp_hashes` (murmur3-32 of the
canonical 46-char timestamp string, timestamp.ts:87-88) but expressed
as an explicit VMEM-blocked Pallas kernel: the XLA path materializes
~46 fused byte columns between HBM round-trips at the fusion
boundaries; here one grid step streams a (8, 128)-tiled block of the
five 32-bit input components into VMEM and emits the 32-bit hash, with
every intermediate staying in registers/VMEM.

Split of work: the two int64 divmods that reduce raw `millis` to
(days, seconds-of-day, millis-of-second) run in plain XLA before the
kernel (Pallas TPU kernels are 32-bit; everything after the split fits
u32/i32 exactly — SURVEY.md §7 bit-exactness notes). The kernel is
bit-exact vs the host oracle and the XLA path (tests/test_pallas.py).

Falls back transparently: `timestamp_hashes_pallas(..., interpret=True)`
runs the same kernel in interpreter mode on CPU (the test env).

Status (re-measured round 3 with the slope method — the r2 "tie" was
~6.9 ms/iter of tunnel RTT masking the real difference): XLA
1.20 ms/1M vs Pallas 1.81 ms/1M on v5e-1 silicon, bit-exact. XLA's
autofusion beats this hand-blocked kernel by ~50% on the
arithmetic-bound hash; `encode.timestamp_hashes` remains the
production path and this kernel stays as the validated-on-silicon
alternative (it would win only if a future pipeline needs the hash
fused with ops XLA refuses to fuse).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from evolu_tpu.core.types import UnknownError
from evolu_tpu.ops import bucket_size, with_x64

try:  # pallas is part of jax, but guard exotic builds
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    PALLAS_AVAILABLE = True
except Exception:  # pragma: no cover
    PALLAS_AVAILABLE = False

_LANES = 128
_SUBLANES = 8
_BLOCK_ROWS = 64  # rows (of 128 lanes) per grid step: 64*128 = 8192 ts/step

_C1 = 0xCC9E2D51
_C2 = 0x1B873593


def _u32(x):
    return jnp.uint32(x)


def _rotl(x, r: int):
    return (x << _u32(r)) | (x >> _u32(32 - r))


def _mix_k(k):
    return _rotl(k * _u32(_C1), 15) * _u32(_C2)


def _civil_from_days_i32(days):
    """Howard Hinnant's civil_from_days in int32 (days < 2^23 for any
    representable date, so every intermediate fits). All constants are
    pinned to int32 — under enable_x64 a bare Python int would promote
    the arithmetic to int64, which Pallas TPU kernels reject."""
    c = jnp.int32
    z = days + c(719468)
    era = z // c(146097)
    doe = z - era * c(146097)
    yoe = (doe - doe // c(1460) + doe // c(36524) - doe // c(146096)) // c(365)
    y = yoe + era * c(400)
    doy = doe - (c(365) * yoe + yoe // c(4) - yoe // c(100))
    mp = (c(5) * doy + c(2)) // c(153)
    d = doy - (c(153) * mp + c(2)) // c(5) + c(1)
    m = mp + jnp.where(mp < c(10), c(3), c(-9))
    y = y + (m <= c(2)).astype(jnp.int32)
    return y, m, d


def _digits(x, n):
    out = []
    for i in range(n - 1, -1, -1):
        out.append((x // _u32(10**i)) % _u32(10) + _u32(ord("0")))
    return out


def _hex_nibble(x, upper):
    return jnp.where(x < 10, x + _u32(ord("0")), x + _u32((ord("A") if upper else ord("a")) - 10))


def _hash_kernel(days_ref, sod_ref, ms_ref, counter_ref, node_hi_ref, node_lo_ref, out_ref):
    """One VMEM block: 5 u32/i32 component planes → u32 murmur3 hashes."""
    days = days_ref[:]
    sod = sod_ref[:].astype(jnp.uint32)
    ms = ms_ref[:]
    counter = counter_ref[:]
    node_hi = node_hi_ref[:]
    node_lo = node_lo_ref[:]

    hh, mm, ss = sod // _u32(3600), (sod // _u32(60)) % _u32(60), sod % _u32(60)
    y, mo, d = _civil_from_days_i32(days)
    y, mo, d = y.astype(jnp.uint32), mo.astype(jnp.uint32), d.astype(jnp.uint32)

    dash, colon = _u32(ord("-")), _u32(ord(":"))
    cols = []
    cols += _digits(y, 4)
    cols.append(jnp.broadcast_to(dash, y.shape))
    cols += _digits(mo, 2)
    cols.append(jnp.broadcast_to(dash, y.shape))
    cols += _digits(d, 2)
    cols.append(jnp.broadcast_to(_u32(ord("T")), y.shape))
    cols += _digits(hh, 2)
    cols.append(jnp.broadcast_to(colon, y.shape))
    cols += _digits(mm, 2)
    cols.append(jnp.broadcast_to(colon, y.shape))
    cols += _digits(ss, 2)
    cols.append(jnp.broadcast_to(_u32(ord(".")), y.shape))
    cols += _digits(ms, 3)
    cols.append(jnp.broadcast_to(_u32(ord("Z")), y.shape))
    cols.append(jnp.broadcast_to(dash, y.shape))
    for shift in (12, 8, 4, 0):
        cols.append(_hex_nibble((counter >> _u32(shift)) & _u32(0xF), upper=True))
    cols.append(jnp.broadcast_to(dash, y.shape))
    for half in (node_hi, node_lo):
        for shift in (28, 24, 20, 16, 12, 8, 4, 0):
            cols.append(_hex_nibble((half >> _u32(shift)) & _u32(0xF), upper=False))

    # murmur3-32 over the 46 bytes (11 words + 2-byte tail).
    h = jnp.zeros_like(cols[0])
    for i in range(11):
        b = i * 4
        k = cols[b] | (cols[b + 1] << _u32(8)) | (cols[b + 2] << _u32(16)) | (cols[b + 3] << _u32(24))
        h = h ^ _mix_k(k)
        h = _rotl(h, 13)
        h = h * _u32(5) + _u32(0xE6546B64)
    k = cols[44] ^ (cols[45] << _u32(8))
    h = h ^ _mix_k(k)
    h = h ^ _u32(46)
    h = h ^ (h >> _u32(16))
    h = h * _u32(0x85EBCA6B)
    h = h ^ (h >> _u32(13))
    h = h * _u32(0xC2B2AE35)
    h = h ^ (h >> _u32(16))
    out_ref[:] = h


@functools.partial(jax.jit, static_argnames=("interpret",))
def _hash_blocks(days, sod, ms, counter, node_hi, node_lo, interpret: bool = False):
    rows = days.shape[0]  # always a multiple of _BLOCK_ROWS (caller pads)
    spec = pl.BlockSpec((_BLOCK_ROWS, _LANES), lambda i: (i, 0), memory_space=pltpu.VMEM)
    return pl.pallas_call(
        _hash_kernel,
        out_shape=jax.ShapeDtypeStruct((rows, _LANES), jnp.uint32),
        grid=(rows // _BLOCK_ROWS,),
        in_specs=[spec] * 6,
        out_specs=spec,
        interpret=interpret,
    )(days, sod, ms, counter, node_hi, node_lo)


@with_x64
def timestamp_hashes_pallas(millis, counter, node, interpret: bool = False):
    """(N,) int64 millis, int32 counter, uint64 node → (N,) uint32
    murmur3 hashes, via the Pallas kernel. Pads N up to a full tile
    grid internally."""
    if not PALLAS_AVAILABLE:
        raise UnknownError("pallas is unavailable in this jax build")
    millis = jnp.asarray(millis, jnp.int64)
    counter = jnp.asarray(counter, jnp.int32)
    node = jnp.asarray(node, jnp.uint64)
    n = millis.shape[0]

    # 64-bit reduction in XLA; everything into the kernel is 32-bit.
    ms = (millis % 1000).astype(jnp.uint32)
    secs = millis // 1000
    days = (secs // 86400).astype(jnp.int32)
    sod = (secs % 86400).astype(jnp.int32)
    c32 = counter.astype(jnp.uint32)
    node_hi = (node >> jnp.uint64(32)).astype(jnp.uint32)
    node_lo = node.astype(jnp.uint32)

    tile = _BLOCK_ROWS * _LANES  # one grid step's worth of elements
    # Power-of-two buckets (>= one grid step): jit compiles once per
    # bucket, not once per distinct batch size (ops.bucket_size policy).
    padded = bucket_size(n, multiple=tile)
    comps = []
    for a in (days, sod, ms, c32, node_hi, node_lo):
        a = jnp.pad(a, (0, padded - n))
        comps.append(a.reshape(padded // _LANES, _LANES))
    # The kernel is pure 32-bit; trace it OUTSIDE the x64 scope so the
    # grid index map emits i32 (an i64 index map fails TPU compilation).
    with jax.enable_x64(False):
        out = _hash_blocks(*comps, interpret=interpret)
    return out.reshape(-1)[:n]
