"""Pallas TPU kernel: single-pass segmented lexicographic max scan.

The LWW planner's wall after the sort is its two segmented scans
(`merge._segmented_max_scan`). The XLA blocked formulation does
log2(256) = 8 shifted elementwise passes over the full arrays — every
pass a round-trip of ~17 bytes/row through HBM. This kernel runs the
scan in ONE pass over HBM: a sequential grid walks the array in
blocks; inside a block everything stays in VMEM (7 lane-shift combines
+ a small cross-row scan), and the running carry crosses grid steps in
SMEM scratch (the TPU grid executes sequentially on a core, so scratch
persists between steps — the canonical Pallas carry pattern).

TPU Pallas has no 64-bit vectors, so the (k1, k2) uint64 HLC keys ride
as four uint32 limb planes with a 4-limb lexicographic compare — the
split/recombine happens in XLA outside the kernel (bit-exact, fused
into the neighbors).

Same monoid and semantics as `merge._segmented_max_scan_reference`:
inclusive segmented lex-max; `flags[i]` marks a segment start (segment
END when reverse=True — the wrapper flips, scans forward, flips back,
exactly like the XLA paths). Bit-identity is property-pinned in
tests/test_pallas.py.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from evolu_tpu.core.types import UnknownError

try:  # pallas is part of jax, but guard exotic builds
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    PALLAS_AVAILABLE = True
except Exception:  # pragma: no cover
    PALLAS_AVAILABLE = False

_LANES = 128
_BLOCK_ROWS = 256  # rows per grid step: 256*128 = 32768 elements


def _lex_ge(a1h, a1l, a2h, a2l, b1h, b1l, b2h, b2l):
    """(a1, a2) >= (b1, b2) lexicographically, on u32 limbs."""
    return (a1h > b1h) | (
        (a1h == b1h)
        & (
            (a1l > b1l)
            | (
                (a1l == b1l)
                & ((a2h > b2h) | ((a2h == b2h) & (a2l >= b2l)))
            )
        )
    )


def _comb(left, right):
    """The segmented lex-max monoid on (flag, 4 key limbs): the operand
    nearest the scan head (right) wins outright when flagged."""
    lf, l1h, l1l, l2h, l2l = left
    rf, r1h, r1l, r2h, r2l = right
    a_wins = _lex_ge(l1h, l1l, l2h, l2l, r1h, r1l, r2h, r2l)

    def pick(lv, rv):
        return jnp.where(rf != 0, rv, jnp.where(a_wins, lv, rv))

    return (lf | rf, pick(l1h, r1h), pick(l1l, r1l), pick(l2h, r2h), pick(l2l, r2l))


def _seg_xor(left, right):
    """Segmented XOR monoid on (flag, value)."""
    lf, lv = left
    rf, rv = right
    return (lf | rf, jnp.where(rf != 0, rv, lv ^ rv))


def _seg_sum(left, right):
    """Segmented u64 SUM monoid on (flag, hi limb, lo limb) — the
    add-monoid twin of `_comb` for the PN-counter fold
    (ops/crdt_merge.py). No 64-bit vectors on TPU Pallas, so the sum
    carries across two u32 limbs: unsigned u32 add wraps, and
    `lo < either operand` detects the wrap (values are non-negative
    pos/neg partial sums, so plain modular limb addition is exact)."""
    lf, lh, ll = left
    rf, rh, rl = right
    lo = ll + rl
    carry = (lo < rl).astype(jnp.uint32)
    hi = lh + rh + carry
    return (lf | rf, jnp.where(rf != 0, rh, hi), jnp.where(rf != 0, rl, lo))


def _make_scan_kernel(n_planes: int, combine):
    """Kernel factory: inclusive segmented scan over `n_planes` u32
    planes (plane 0 is the segment flag) under `combine`, one grid
    step per (R, 128) block in row-major element order, carry across
    the sequential grid in SMEM."""

    def kernel(*refs):
        in_refs = refs[:n_planes]
        out_refs = refs[n_planes : 2 * n_planes]
        carry = refs[2 * n_planes]
        step = pl.program_id(0)

        @pl.when(step == 0)
        def _init():
            for i in range(n_planes):
                carry[i] = jnp.uint32(0)

        vals = tuple(r[:] for r in in_refs)

        # 1) In-row inclusive scan along the 128 lanes: log2(128) = 7
        #    shifted combines; lanes shifted in from the left are
        #    masked to the monoid identity (flag 0, values 0).
        lane = jax.lax.broadcasted_iota(jnp.int32, vals[0].shape, 1)
        shift = 1
        while shift < _LANES:
            shifted = tuple(pltpu.roll(v, shift, 1) for v in vals)
            edge = lane < shift
            shifted = tuple(jnp.where(edge, jnp.uint32(0), v) for v in shifted)
            vals = combine(shifted, vals)
            shift *= 2

        # 2) Cross-row scan over the row totals (lane 127 column).
        totals = tuple(v[:, _LANES - 1 :] for v in vals)
        row = jax.lax.broadcasted_iota(jnp.int32, totals[0].shape, 0)
        shift = 1
        while shift < _BLOCK_ROWS:
            shifted = tuple(pltpu.roll(t, shift, 0) for t in totals)
            edge = row < shift
            shifted = tuple(jnp.where(edge, jnp.uint32(0), t) for t in shifted)
            totals = combine(shifted, totals)
            shift *= 2

        # 3) Exclusive row carry: rows shift down by one; row 0 takes
        #    the block carry from scratch, every other row combines it
        #    in as the left-most operand.
        prev = tuple(pltpu.roll(t, 1, 0) for t in totals)
        prev = tuple(jnp.where(row < 1, jnp.uint32(0), t) for t in prev)
        carry_in = tuple(jnp.full_like(prev[0], carry[i]) for i in range(n_planes))
        row_carry = combine(carry_in, prev)

        # 4) out[r, l] = combine(row_carry[r], in_row_scan[r, l]).
        out = combine(row_carry, vals)
        for o_ref, o in zip(out_refs, out):
            o_ref[:] = o

        # 5) Save the block's inclusive total as the next step's carry.
        for i in range(n_planes):
            carry[i] = out[i][_BLOCK_ROWS - 1, _LANES - 1]

    return kernel


_LEX_KERNEL = _make_scan_kernel(5, _comb)
_XOR_KERNEL = _make_scan_kernel(2, _seg_xor)
_SUM_KERNEL = _make_scan_kernel(3, _seg_sum)


def _scan_call(kernel, n_planes, planes, interpret):
    rows = planes[0].shape[0]  # multiple of _BLOCK_ROWS (caller pads)
    spec = pl.BlockSpec((_BLOCK_ROWS, _LANES), lambda i: (i, 0),
                        memory_space=pltpu.VMEM)
    shape = jax.ShapeDtypeStruct((rows, _LANES), jnp.uint32)
    return pl.pallas_call(
        kernel,
        out_shape=(shape,) * n_planes,
        grid=(rows // _BLOCK_ROWS,),
        in_specs=[spec] * n_planes,
        out_specs=(spec,) * n_planes,
        scratch_shapes=[pltpu.SMEM((n_planes,), jnp.uint32)],
        interpret=interpret,
    )(*planes)


@functools.partial(jax.jit, static_argnames=("interpret",))
def _scan_blocks(f, k1h, k1l, k2h, k2l, interpret: bool = False):
    return _scan_call(_LEX_KERNEL, 5, (f, k1h, k1l, k2h, k2l), interpret)


@functools.partial(jax.jit, static_argnames=("interpret",))
def _xor_scan_blocks(f, v, interpret: bool = False):
    return _scan_call(_XOR_KERNEL, 2, (f, v), interpret)


@functools.partial(jax.jit, static_argnames=("interpret",))
def _sum_scan_blocks(f, hi, lo, interpret: bool = False):
    return _scan_call(_SUM_KERNEL, 3, (f, hi, lo), interpret)


def segmented_xor_scan_pallas(flags, values_u32, interpret: bool = False):
    """(N,) bool flags (segment starts) + (N,) uint32 → inclusive
    segmented XOR scan. At each segment's last row the value is the
    segment's total XOR — the only positions the Merkle decode reads."""
    if not PALLAS_AVAILABLE:
        raise UnknownError("pallas is unavailable in this jax build")
    n = flags.shape[0]
    tile = _BLOCK_ROWS * _LANES
    padded = -(-max(n, 1) // tile) * tile
    pad = padded - n
    f = jnp.pad(flags.astype(jnp.uint32), (0, pad))
    v = jnp.pad(jnp.asarray(values_u32, jnp.uint32), (0, pad))
    planes = [a.reshape(padded // _LANES, _LANES) for a in (f, v)]
    with jax.enable_x64(False):
        _, out = _xor_scan_blocks(*planes, interpret=interpret)
    return out.reshape(-1)[:n]


def segmented_sum_scan_pallas(flags, values_u64, interpret: bool = False):
    """Drop-in for `crdt_merge.segmented_sum_scan`: (N,) bool flags
    (segment starts) + uint64 values → inclusive segmented sum, one HBM
    pass. The u64⇄u32 limb split runs in XLA around the kernel; exact
    for the PN-counter fold's non-negative partial sums (< 2^55)."""
    if not PALLAS_AVAILABLE:
        raise UnknownError("pallas is unavailable in this jax build")
    n = flags.shape[0]
    tile = _BLOCK_ROWS * _LANES
    padded = -(-max(n, 1) // tile) * tile
    pad = padded - n
    f = jnp.pad(flags.astype(jnp.uint32), (0, pad))
    v = jnp.asarray(values_u64, jnp.uint64)
    vh = jnp.pad((v >> jnp.uint64(32)).astype(jnp.uint32), (0, pad))
    vl = jnp.pad(v.astype(jnp.uint32), (0, pad))
    planes = [a.reshape(padded // _LANES, _LANES) for a in (f, vh, vl)]
    with jax.enable_x64(False):
        _, oh, ol = _sum_scan_blocks(*planes, interpret=interpret)
    return (
        oh.reshape(-1)[:n].astype(jnp.uint64) << jnp.uint64(32)
    ) | ol.reshape(-1)[:n].astype(jnp.uint64)


def segmented_max_scan_pallas(flags, k1, k2, reverse: bool = False,
                              interpret: bool = False):
    """Drop-in for `merge._segmented_max_scan`: (N,) bool flags + uint64
    keys → inclusive segmented lex-max (m1, m2) uint64. Traceable; the
    u64⇄u32 limb split and padding run in XLA around the kernel."""
    if not PALLAS_AVAILABLE:
        raise UnknownError("pallas is unavailable in this jax build")
    if reverse:
        o1, o2 = segmented_max_scan_pallas(
            flags[::-1], k1[::-1], k2[::-1], interpret=interpret
        )
        return o1[::-1], o2[::-1]
    n = flags.shape[0]
    tile = _BLOCK_ROWS * _LANES
    padded = -(-max(n, 1) // tile) * tile
    pad = padded - n

    f = jnp.pad(flags.astype(jnp.uint32), (0, pad))
    k1 = jnp.asarray(k1, jnp.uint64)
    k2 = jnp.asarray(k2, jnp.uint64)
    k1h = jnp.pad((k1 >> jnp.uint64(32)).astype(jnp.uint32), (0, pad))
    k1l = jnp.pad(k1.astype(jnp.uint32), (0, pad))
    k2h = jnp.pad((k2 >> jnp.uint64(32)).astype(jnp.uint32), (0, pad))
    k2l = jnp.pad(k2.astype(jnp.uint32), (0, pad))

    planes = [a.reshape(padded // _LANES, _LANES) for a in (f, k1h, k1l, k2h, k2l)]
    # The kernel is pure 32-bit; trace it outside the x64 scope so the
    # grid index map emits i32 (an i64 index map fails TPU compilation).
    with jax.enable_x64(False):
        _, m1h, m1l, m2h, m2l = _scan_blocks(*planes, interpret=interpret)

    def join(hi, lo):
        return (hi.reshape(-1)[:n].astype(jnp.uint64) << jnp.uint64(32)) | lo.reshape(
            -1
        )[:n].astype(jnp.uint64)

    return join(m1h, m1l), join(m2h, m2l)
