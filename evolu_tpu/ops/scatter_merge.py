"""Sort-free scatter-argmax LWW merge plan (ISSUE 4 tentpole).

BENCH_r05's anatomy put 65% of the merge pipeline in one `lax.sort`
(2.30 of 3.53 ms per 1M-message pass on v5e), yet LWW resolution needs
a per-cell MAX, not a total order (reference applyMessages.ts:34-40) —
the commutative per-key reduction Merkle-CRDTs exploit to make merge
order-free (arxiv 2004.00107). This module is the dense formulation:
scatter each message's HLC key into a cell-indexed winner table in
HBM, take the per-cell lexicographic max (two chained u64 scatter-max
passes — the (k1, k2) compare is 128-bit, which no single packed key
can carry), then gather the winners back to label each row.

The reference's xor quirk (applyMessages.ts:104-122) is the part a
per-cell max alone cannot reproduce: the Merkle XOR is gated on
"running winner != message timestamp" where the running winner folds
the stored winner and all EARLIER BATCH rows of the cell — an
inherently order-dependent prefix quantity. The exact algebra (same
derivation as `merge.plan_merge_sorted_flags`, with p = the in-batch
prefix max and e the stored winner, a = e>s, b = e==s):

    xor[i]   = False  ⟺  ¬a ∧ ¬gt_before[i] ∧ (b ∨ eq_before[i])
    upsert[i] =            s_i == t(c) ∧ first-achiever ∧ ¬a ∧ ¬b

`gt_before`/`eq_before` (an earlier batch row of the cell strictly
greater / exactly equal) collapse to scatter-computable quantities
WHEN the batch holds no duplicate (cell, k1, k2) row below the cell
max:

  - eq_before ≡ False for every row (no in-batch duplicates at all is
    the precondition actually enforced — see `batch_has_duplicate_keys`
    — so `(b ∨ eq_before)` reduces to `b`);
  - for b-rows, gt_before ⟺ FB[c] < i where FB[c] is the FIRST batch
    index beating the stored winner (one scatter-min of idx over the
    ¬a∧¬b rows — every row of a cell shares the same e, so "beats e"
    is the row's own flag);
  - dup-free cells have a unique max achiever, so upsert needs no
    first-achiever tie-break.

Duplicate (cell, k1, k2) keys are identical 46-char timestamps in the
same cell — upstream paths (relay PK, in-batch dedup in
engine.start_batch) never produce them, but the planner contract must
hold for arbitrary input, so the ROUTER (`use_scatter_plan`) detects
them host-side with a sorted-hash screen (false positives over-route
to the sort path — safe; false negatives are impossible: equal keys
hash equal) and routes such batches to the sort path. Same pattern as
the wide-id fallback: static host-side routing, two separately
compiled kernels, bit-identical plans wherever both can run
(property-pinned in tests/test_scatter_merge.py).

Cost model notes (why this is config-selectable, not the default):
three scatters + three gathers against table rows vs ONE sort. The
recorded v5e pricing (docs/BENCHMARKS.md r2: 1M-row u64 gathers ~4× a
sort; XLA lowers scatters to serialized updates on TPU, ~100ms+/1M)
predicts a heavy loss on TPU silicon; on the CPU backend (this
environment's production default) the same formulation measures ~13×
FASTER than the 1M single-device sort+scan plan. `merge_plan_path()`
therefore routes "auto" by backend. Numbers: docs/BENCHMARKS.md r6.
"""

from __future__ import annotations

import os
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from evolu_tpu.ops.merge import _PAD_CELL, winner_flags

# Hard table bound: cell ids ride 25 bits in the r5 packed sort key,
# and 2^25 winner slots = 512 MB of u64 pairs — the largest table the
# tentpole brief prices. Batches beyond it keep the sort path.
MAX_TABLE_BITS = 25

# Multiplicative hash constants for the duplicate screen (odd, from
# splitmix64's finalizer family — quality only affects the false
# positive rate, never correctness).
_H1 = np.uint64(0xBF58476D1CE4E5B9)
_H2 = np.uint64(0x94D049BB133111EB)
_H3 = np.uint64(0x9E3779B97F4A7C15)


def table_size_for(cell_max: int) -> int:
    """Power-of-two winner-table size covering cell ids 0..cell_max
    (bucket-stable: the kernel recompiles per table bucket, never per
    batch)."""
    size = 64
    while size <= cell_max:
        size *= 2
    return size


def batch_has_duplicate_keys(cell_id, k1, k2) -> bool:
    """Host-side duplicate screen for the scatter router: True if any
    two REAL rows MAY share (cell, k1, k2) — padding rows (the layout
    sentinels, all identical (PAD, 0, 0)) are excluded, or every
    padded shard layout would self-report as duplicate. Sorted-hash
    check: equal triples hash equal (no false negatives — a missed
    duplicate would silently corrupt the xor mask), unequal triples
    collide with ~N²/2⁶⁴ probability and only over-route to the sort
    path. A dup is the same 46-char timestamp hitting the same cell
    twice in one batch, which every upstream dedup already screens —
    this is the planner-contract backstop, not a hot-path
    expectation."""
    real = cell_id != int(_PAD_CELL)
    if not real.all():
        cell_id, k1, k2 = cell_id[real], k1[real], k2[real]
    n = len(k1)
    if n < 2:
        return False
    with np.errstate(over="ignore"):
        h = (
            k1.astype(np.uint64) * _H1
            ^ k2.astype(np.uint64) * _H2
            ^ cell_id.astype(np.uint64) * _H3
        )
    h.sort()
    return bool((h[1:] == h[:-1]).any())


# -- plan-path selection -------------------------------------------------

_VALID_PATHS = ("auto", "sort", "scatter")
_plan_path = "auto"


def set_plan_path(path: str) -> None:
    """Select the LWW plan formulation: "sort" (the r5 sort+scan
    pipeline), "scatter" (this module), or "auto" (by backend: scatter
    on CPU where it measures ~13× faster, sort on TPU where the
    recorded cost model prices scatters/gathers far above one sort —
    docs/BENCHMARKS.md r6). Wired from `Config.merge_plan` at runtime
    init; the EVOLU_MERGE_PLAN env var overrides either (bench/test
    pinning)."""
    if path not in _VALID_PATHS:
        raise ValueError(f"merge_plan must be one of {_VALID_PATHS}, got {path!r}")
    global _plan_path
    _plan_path = path


def merge_plan_path() -> str:
    """The effective plan path ("sort" | "scatter") after env override
    and "auto" resolution. Reads the default backend lazily — calling
    this must not initialize XLA earlier than the caller's own kernel
    dispatch would."""
    path = os.environ.get("EVOLU_MERGE_PLAN", "") or _plan_path
    if path not in _VALID_PATHS:
        # Loud, like set_plan_path: the env var exists to PIN a kernel
        # for benches/tests — a typo silently resolving to "auto"
        # would record numbers for the wrong kernel.
        raise ValueError(
            f"EVOLU_MERGE_PLAN must be one of {_VALID_PATHS}, got {path!r}"
        )
    if path == "auto":
        return "scatter" if jax.default_backend() == "cpu" else "sort"
    return path


def use_scatter_plan(cell_id, k1, k2, cell_max: Optional[int] = None) -> bool:
    """Full host-side routing decision for one batch: the configured
    path, the table bound, and the duplicate screen. `cell_max` saves
    a pass when the caller already holds the max (shard routing)."""
    if merge_plan_path() != "scatter":
        return False
    if cell_max is None:
        real = cell_id != int(_PAD_CELL)
        cell_max = int(cell_id.max(initial=0, where=real))
    if cell_max >= 1 << MAX_TABLE_BITS:
        return False
    return not batch_has_duplicate_keys(cell_id, k1, k2)


def scatter_table_for(cell_id, k1, k2) -> Optional[int]:
    """Admission AND sizing in one call for the plan entry points: the
    winner-table size when the scatter plan should serve this batch,
    else None. The pad-free cell max is computed ONCE and feeds both
    decisions, so admission and table sizing can never disagree."""
    if merge_plan_path() != "scatter":
        return None
    real = cell_id != int(_PAD_CELL)
    cell_max = int(cell_id.max(initial=0, where=real))
    if cell_max >= 1 << MAX_TABLE_BITS or batch_has_duplicate_keys(cell_id, k1, k2):
        return None
    return table_size_for(cell_max)


# -- the kernel ----------------------------------------------------------


def scatter_plan_masks(cell_id, k1, k2, ex_k1, ex_k2, table_size: int):
    """The dense LWW plan (traceable core): → (xor_mask, upsert_mask)
    bools in ORIGINAL batch order — no sort, no permutation to undo.

    Preconditions (enforced by `use_scatter_plan`, NOT re-checked on
    device): real cell ids < table_size, and no duplicate
    (cell, k1, k2) row. Padding rows carry cell_id=_PAD_CELL and
    scatter to the dump slot `table_size` (mode="drop" on writes; the
    dump-slot gather is masked by `real`).

    TPU notes honored even though the default routing keeps this off
    TPU: comparisons are compare+select only (no maxui), and the
    scatters are plain u64/int32 tables — no 64-bit VECTORS are
    produced by the gathers' consumers beyond what the sort path
    already materializes. Must be traced under enable_x64(True) like
    every planner core (u64 keys)."""
    n = cell_id.shape[0]
    idx = jnp.arange(n, dtype=jnp.int32)
    a, b = winner_flags(k1, k2, ex_k1, ex_k2)
    real = cell_id != _PAD_CELL
    cell = jnp.where(real, cell_id, jnp.int32(table_size))
    # Per-cell lex max over (k1, k2): chained scatter-max passes. The
    # second pass maxes k2 only over rows achieving t1 (losers
    # contribute the u64 zero — the monoid identity, and a legitimate
    # value: max(0, real zeros) is still exact).
    t1 = jnp.zeros(table_size + 1, jnp.uint64).at[cell].max(k1, mode="drop")
    is_t1 = (k1 == t1[cell]) & real
    t2 = (
        jnp.zeros(table_size + 1, jnp.uint64)
        .at[cell]
        .max(jnp.where(is_t1, k2, jnp.uint64(0)), mode="drop")
    )
    is_t = is_t1 & (k2 == t2[cell])
    # FB[c]: first batch index that beats the stored winner — the only
    # prefix quantity the dup-free xor algebra needs (b-rows re-XOR
    # exactly when a beater precedes them).
    beats_e = (~a) & (~b) & real
    fb = (
        jnp.full(table_size + 1, n, jnp.int32)
        .at[cell]
        .min(jnp.where(beats_e, idx, jnp.int32(n)), mode="drop")
    )
    # Dup-free: eq_before ≡ False, so xor=False ⟺ b ∧ ¬gt_before; and
    # the cell max has a unique achiever, so upsert needs no
    # first-achiever tie-break.
    xor_mask = real & (~b | (fb[cell] < idx))
    upsert_mask = is_t & (~a) & (~b)
    return xor_mask, upsert_mask


# Mask-only dispatch for `plan_batch_device` (the plan-masks contract,
# original order — the sort path pays a device RESTORING sort to get
# back to batch order; this path never leaves it).
plan_masks_scatter = jax.jit(scatter_plan_masks, static_argnames=("table_size",))
