"""HBM-resident per-cell winner cache (SURVEY.md §7 hard part 4).

The round-1 design streamed each batch's stored winners out of SQLite
(`storage.apply.fetch_existing_winners`) and shipped them to the device
as `ex_k1/ex_k2` columns. This module is the measured alternative the
round-1 review asked for: the per-cell winner table LIVES in device
memory across batches — the kernel gathers stored winners from HBM,
plans the batch, and scatter-updates the winners in place (donated
buffers, so XLA reuses the allocation) — with SQLite as the durable
write-behind it always was. Per steady-state batch this removes the
SQLite winner read, the winner-string parse, and the 16·N-byte ex
column host→device transfer.

Coherence contract:
- SQLite remains the source of truth. Cache slots are seeded lazily:
  the first time a cell is seen, its winner is read from SQLite (one
  batched read for all new cells). After that the kernel's scatter
  keeps the slot exactly equal to SQLite's `MAX(timestamp)` for the
  cell, because every apply goes through `plan_batch` below.
- The scatter runs at plan time, inside the caller's transaction. If
  the transaction fails the cache is ahead of SQLite, so
  `on_transaction_failed()` (hooked by `storage.apply.apply_messages`)
  drops everything — the next batch re-seeds from SQLite. Cheap and
  always safe.
- Non-canonical hex case (messages or stored winners) cannot be
  ordered by numeric keys (reference semantics are raw-string order);
  such batches fall back to the host oracle planner and every touched
  cell is invalidated, mirroring `merge._host_fallback`.
- TYPED cells (CRDT column types, core/crdt_types.py) keep the slot ==
  MAX(timestamp) invariant unchanged — the xor/Merkle algebra the slot
  feeds is timestamp-only and type-agnostic. What differs is the slot's
  MEANING: for an LWW cell the slot's timestamp is also the app-table
  winner; for a typed cell the app value is merge STATE (__crdt_* fold,
  materialized by storage.apply) and the slot is only the xor gate.
  Invalidation per type: LWW invalidation rules apply verbatim; typed
  merge state never lives in HBM (it lives in SQLite inside the apply
  transaction), so typed state reset/rollback needs no extra cache
  hook — the existing transaction-failure reset already covers the
  shared slots. Contract test: tests/test_crdt_types.py pins slot ==
  MAX(timestamp) while the app value is the fold, per type.
- A SECOND connection writing the same database (SyncLock contemplates
  cross-process workers) would silently strand stale winners; every
  `plan_batch` therefore probes `PRAGMA data_version` — which moves
  iff another connection changed the file — and resets the cache when
  it moved. Same-connection applies never move it.

Memory: 16 bytes/cell (two uint64 keys), power-of-two capacity grown by
doubling — 1M cells = 16 MiB of HBM. Invalidated cells release their
slots to a free list; re-assignment always rewrites the slot (winner or
zeros), so a reused slot cannot leak a previous cell's keys.
"""

from __future__ import annotations

import functools
from typing import Dict, List, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from evolu_tpu.core.types import CrdtMessage
from evolu_tpu.ops import bucket_size, to_host_many, with_x64
from evolu_tpu.ops.encode import pack_ts_key_host, timestamp_hashes, unpack_ts_keys
from evolu_tpu.ops.host_parse import intern_cells, parse_timestamp_strings
from evolu_tpu.ops.merge import (
    _PAD_CELL,
    PlannedBatch,
    plan_merge_sorted_core,
    select_messages,
    unpermute_masks,
)
from evolu_tpu.obs import metrics
from evolu_tpu.ops.merkle_ops import decode_owner_minute_deltas, owner_minute_segments
from evolu_tpu.utils.log import span

Cell = Tuple[str, str, str]


@functools.partial(jax.jit, donate_argnums=(0, 1))
def _cached_plan_kernel(w1, w2, slots, cell_id, k1, k2):
    """Gather stored winners from the HBM cache, plan, scatter the
    updated winners back — one dispatch, cache buffers donated (updated
    in place). Padding rows carry slot 0; their gathered value is dead
    (masked by the pad cell) and their scatter target is the
    out-of-range dump index (dropped)."""
    e1 = w1[slots]
    e2 = w2[slots]
    xor_s, upsert_s, i_s, s1, s2, (slots_s,), (win1, win2, seg_end, real) = (
        plan_merge_sorted_core(
            cell_id, k1, k2, e1, e2, extras=(slots,), return_winners=True
        )
    )
    millis_s, counter_s = unpack_ts_keys(s1)
    hashes = jnp.where(xor_s, timestamp_hashes(millis_s, counter_s, s2), jnp.uint32(0))
    zero_owner = jnp.zeros((), jnp.int32)
    _, minute_sorted, m_seg_end, seg_xor, valid_sorted = owner_minute_segments(
        zero_owner, millis_s, hashes, xor_s
    )
    cap = jnp.int32(w1.shape[0])
    tgt = jnp.where(seg_end & real, slots_s, cap)
    w1 = w1.at[tgt].set(win1, mode="drop")
    w2 = w2.at[tgt].set(win2, mode="drop")
    return w1, w2, xor_s, upsert_s, i_s, minute_sorted, m_seg_end, seg_xor, valid_sorted


@functools.partial(jax.jit, donate_argnums=(0, 1))
def _seed_kernel(w1, w2, idx, v1, v2):
    """Write seed winners into cache slots (padding rows target the
    out-of-range dump index and are dropped)."""
    w1 = w1.at[idx].set(v1, mode="drop")
    w2 = w2.at[idx].set(v2, mode="drop")
    return w1, w2


@functools.partial(jax.jit, donate_argnums=(0,), static_argnames=("new_cap",))
def _grow_kernel(w, new_cap):
    out = jnp.zeros(new_cap, w.dtype)
    return jax.lax.dynamic_update_slice(out, w, (0,))


# -- PR-12 mesh-sharded slot arrays (MeshShardedWinnerCache below) --
#
# Compiled shard_map kernels for the owner-sharded HBM winner store,
# cached per mesh (the mesh object is the jit-cache key, so every
# consumer sharing a MeshContext shares ONE compiled pipeline per
# bucket). Registered for the recompile fence like the engine's
# _JIT_KERNELS (`mesh_jit_cache_size`).

_MESH_JIT_KERNELS: List = []


def mesh_jit_cache_size() -> int:
    """Jit-cache entries across the sharded winner-cache kernels — the
    recompile fence for the sharded pipeline (same `_cache_size`
    degradation contract as `engine.merkle_jit_cache_size`)."""
    return sum(getattr(k, "_cache_size", lambda: 0)() for k in _MESH_JIT_KERNELS)


def _sharded_plan_body(w1, w2, slots, cell_id, k1, k2):
    """Per-device gather/plan/scatter — `_cached_plan_kernel`'s body on
    this device's (1, cap) slot rows and (S,) batch slice. Cells are
    placed per shard (stable hash), so cell segments never span
    devices; minute segments are per-shard partials the host decoder
    XOR-merges exactly (the cross-device delta reduction)."""
    w1r, w2r = w1[0], w2[0]
    e1 = w1r[slots]
    e2 = w2r[slots]
    xor_s, upsert_s, i_s, s1, s2, (slots_s,), (win1, win2, seg_end, real) = (
        plan_merge_sorted_core(
            cell_id, k1, k2, e1, e2, extras=(slots,), return_winners=True
        )
    )
    millis_s, counter_s = unpack_ts_keys(s1)
    hashes = jnp.where(xor_s, timestamp_hashes(millis_s, counter_s, s2), jnp.uint32(0))
    zero_owner = jnp.zeros((), jnp.int32)
    _, minute_sorted, m_seg_end, seg_xor, valid_sorted = owner_minute_segments(
        zero_owner, millis_s, hashes, xor_s
    )
    cap = jnp.int32(w1r.shape[0])
    tgt = jnp.where(seg_end & real, slots_s, cap)
    w1r = w1r.at[tgt].set(win1, mode="drop")
    w2r = w2r.at[tgt].set(win2, mode="drop")
    return (
        w1r[None], w2r[None],
        xor_s, upsert_s, i_s, minute_sorted, m_seg_end, seg_xor, valid_sorted,
    )


@functools.lru_cache(maxsize=None)
def _sharded_plan_kernel(mesh):
    from evolu_tpu.ops import shard_map
    from evolu_tpu.parallel.mesh import OWNERS_AXIS
    from jax.sharding import PartitionSpec as P

    spec2, spec1 = P(OWNERS_AXIS, None), P(OWNERS_AXIS)
    fn = jax.jit(
        shard_map(
            _sharded_plan_body,
            mesh=mesh,
            in_specs=(spec2, spec2, spec1, spec1, spec1, spec1),
            out_specs=(spec2, spec2) + (spec1,) * 7,
            check_vma=False,
        ),
        donate_argnums=(0, 1),
    )
    _MESH_JIT_KERNELS.append(fn)
    return fn


def _sharded_seed_body(w1, w2, idx, v1, v2):
    w1 = w1.at[0, idx[0]].set(v1[0], mode="drop")
    w2 = w2.at[0, idx[0]].set(v2[0], mode="drop")
    return w1, w2


@functools.lru_cache(maxsize=None)
def _sharded_seed_kernel(mesh):
    from evolu_tpu.ops import shard_map
    from evolu_tpu.parallel.mesh import OWNERS_AXIS
    from jax.sharding import PartitionSpec as P

    spec2 = P(OWNERS_AXIS, None)
    fn = jax.jit(
        shard_map(
            _sharded_seed_body,
            mesh=mesh,
            in_specs=(spec2,) * 5,
            out_specs=(spec2, spec2),
            check_vma=False,
        ),
        donate_argnums=(0, 1),
    )
    _MESH_JIT_KERNELS.append(fn)
    return fn


class DeviceWinnerCache:
    """Keeps (k1, k2) winner keys per cell in device memory across
    batches. `plan_batch` matches the planner contract of
    `storage.apply.apply_messages` but advertises
    `fetches_winners = False`: apply skips its SQLite winner read and
    the cache seeds misses itself."""

    fetches_winners = False

    # Adaptive gating (VERDICT r2 #3): when a batch's NEW-cell rate is
    # high, the extra seed dispatch makes the cache LOSE to streaming
    # winners from SQLite (measured: 30.9k cached vs 38.8k streamed
    # msgs/sec under the rotating-cell shape); when the population is
    # steady the cache WINS (~+30%). An EWMA of the per-batch seed
    # rate drives a hysteresis: above `seed_hi` the planner streams
    # (cache dropped, membership tracked host-side only); below
    # `seed_lo` it warms the cache back up. The fresh-decaying EWMA
    # (new weight 0.8) returns to cached mode ~2 clean batches after a
    # churn burst ends (one streamed, one warming); a workload that
    # churns a quarter of its cells every batch holds the EWMA near
    # 0.25 — inside the hysteresis band, so no mode oscillation.
    seed_hi = 0.30
    seed_lo = 0.10
    _EWMA_NEW_WEIGHT = 0.8
    _KNOWN_CAP = 1 << 20  # bound the streaming-mode membership estimator

    def __init__(
        self,
        db,
        capacity: int = 1 << 15,
        adaptive: bool = True,
        max_slots: "int | None" = 1 << 22,
    ):
        self._db = db
        self._slots: Dict[Cell, int] = {}
        self._free: List[int] = []  # invalidated slots, reused first
        self._next_slot = 0
        # HBM bound (VERDICT #3): the cache may never grow past
        # `max_slots` (default 2^22 cells = 64 MiB of winner keys —
        # an unbounded workload writing ever-new cells previously grew
        # it without limit). Overflow evicts by DROP-AND-RESEED:
        # eviction IS invalidation, which the coherence protocol
        # already supports (a dropped slot just re-seeds from SQLite on
        # next touch), so capping can never produce a stale winner.
        self.max_slots = max_slots
        if max_slots is not None:
            capacity = min(capacity, bucket_size(max_slots))
        self.capacity = capacity
        self.adaptive = adaptive  # False = always-cached (static path)
        self._seed_ewma = 0.0
        self._streaming = False
        self._known: set = set()  # membership estimator while streaming
        # The first batch after a reset re-seeds every cell it touches;
        # that 1.0 new-cell rate is recovery, not churn, and must not
        # flip a steady workload into streamed mode (~3 batches of
        # penalty per unrelated rollback otherwise). At most ONE skip
        # per run of resets (_ewma_suppressed): under repeated resets
        # (e.g. a foreign writer touching the DB every batch) the
        # sustained 1.0 rates ARE the workload signal and must reach
        # the EWMA, or the gate starves and never streams.
        self._skip_ewma_once = False
        self._ewma_suppressed = False
        # The cache==MAX(timestamp) invariant assumes this worker's
        # connection observes every apply. SQLite's data_version moves
        # if and only if ANOTHER connection changed the database — the
        # cheap per-batch foreign-write probe. Same-connection writes
        # never move it, so steady-state batches pay one PRAGMA read.
        self._data_version = self._read_data_version()
        self._alloc_slot_arrays()

    # -- overridable array hooks (MeshShardedWinnerCache reshapes the
    # slot store to per-device rows; all coherence/gating logic above
    # these hooks is shared verbatim) --

    def _alloc_slot_arrays(self) -> None:
        with jax.enable_x64(True):
            self._w1 = jnp.zeros(self.capacity, jnp.uint64)
            self._w2 = jnp.zeros(self.capacity, jnp.uint64)

    def _clear_free_slots(self) -> None:
        self._free.clear()
        self._next_slot = 0

    def _gather_slot_values(self, idx: np.ndarray):
        """Device-side gather of the audited slots, pulled in ONE wave
        (never a full-array pull — see verify_against_db)."""
        with jax.enable_x64(True):
            j_idx = jnp.asarray(idx)
            return to_host_many(self._w1[j_idx], self._w2[j_idx])

    def _read_data_version(self):
        try:
            rows = self._db.exec_sql_query("PRAGMA data_version", ())
            return next(iter(rows[0].values())) if rows else None
        except Exception:  # noqa: BLE001 - a backend without PRAGMA
            # support degrades to the documented single-writer contract
            return None

    def _drop_if_foreign_write(self) -> None:
        version = self._read_data_version()
        if version != self._data_version:
            self._data_version = version
            if self._has_slot_state():
                metrics.inc("evolu_winner_cache_foreign_write_drops_total")
                self.reset()

    def _has_slot_state(self) -> bool:
        """Anything live OR freed in the slot store — the foreign-write
        reset gate (a hook: the sharded subclass keeps its free lists
        per shard, and the gate must see them identically)."""
        return bool(self._slots or self._free)

    # -- slot management --

    def _grow_to(self, needed: int) -> None:
        new_cap = self.capacity
        while new_cap < needed:
            new_cap *= 2
        if new_cap != self.capacity:
            with jax.enable_x64(True):
                self._w1 = _grow_kernel(self._w1, new_cap=new_cap)
                self._w2 = _grow_kernel(self._w2, new_cap=new_cap)
            self.capacity = new_cap
            metrics.inc("evolu_winner_cache_grows_total")
            metrics.set_gauge("evolu_winner_cache_capacity_slots", new_cap)

    def _seed_new_cells(self, new_cells: List[Cell]) -> bool:
        """Assign slots to first-seen cells (reusing invalidated slots
        first) and load their winners from SQLite in one batched read.
        Every assigned slot is written — winner keys for cells with
        history, zeros for the rest, so a reused slot can never leak a
        previous cell's stale keys. Returns False when any seed winner
        is non-canonical (the caller must take the host path; the
        non-canonical cells stay unassigned)."""
        from evolu_tpu.ops.merge import winner_key_columns
        from evolu_tpu.storage.apply import fetch_existing_winners

        winners = fetch_existing_winners(self._db, new_cells)
        n = len(new_cells)
        v1, v2, canonical = winner_key_columns(new_cells, winners)
        if not canonical:
            # A stored non-canonical winner cannot live in the
            # numeric cache. Keep every cell of this batch
            # uncached; the caller falls back to the host planner.
            metrics.inc("evolu_winner_cache_noncanonical_seeds_total")
            return False
        metrics.inc("evolu_winner_cache_seeded_cells_total", n)
        self._assign_and_write_seeds(new_cells, v1, v2)
        return True

    def _assign_and_write_seeds(self, new_cells, v1, v2) -> None:
        """Slot assignment + the device seed write (the array-shape-
        specific half of `_seed_new_cells`)."""
        n = len(new_cells)
        reused = min(len(self._free), n)
        self._grow_to(self._next_slot + n - reused)
        idx = np.empty(n, np.int32)
        for j, c in enumerate(new_cells):
            if self._free:
                slot = self._free.pop()
            else:
                slot = self._next_slot
                self._next_slot += 1
            idx[j] = self._slots[c] = slot
        (idx_p, v1_p, v2_p), _ = _pad_seed(idx, v1, v2, self.capacity)
        with jax.enable_x64(True):
            self._w1, self._w2 = _seed_kernel(
                self._w1, self._w2, jnp.asarray(idx_p),
                jnp.asarray(v1_p), jnp.asarray(v2_p),
            )

    def _enforce_capacity(self, cells, new_cells):
        """The `max_slots` cap (VERDICT #3), applied between the gate
        and seeding: if this batch's seeds would push the live slot
        count past the cap, evict by DROPPING the whole cache and
        reseeding just this batch's cells — eviction is exactly the
        invalidation the coherence protocol already supports, so a
        capped cache can never serve a stale winner; the cost is one
        re-seed wave for cells that were live. Returns the (possibly
        replaced) new_cells list, or None when this batch ALONE
        exceeds the cap — the caller plans it with SQLite-streamed
        winners (exact, no cache state) instead of thrashing."""
        if self.max_slots is None or not new_cells:
            return new_cells
        if len(self._slots) + len(new_cells) <= self.max_slots:
            return new_cells
        metrics.inc("evolu_winner_cache_evictions_total")
        self.reset()
        if len(cells) > self.max_slots:
            return None
        return list(cells)

    def invalidate(self, cells) -> None:
        dropped = 0
        for c in cells:
            slot = self._slots.pop(c, None)
            if slot is not None:
                self._free.append(slot)
                dropped += 1
        metrics.inc("evolu_winner_cache_invalidated_cells_total", dropped)

    def reset(self) -> None:
        metrics.inc("evolu_winner_cache_resets_total")
        self._slots.clear()
        self._clear_free_slots()
        # Streaming mode sources winners from SQLite and measures churn
        # against the carried-over _known — no 1.0-rate re-seed
        # artifact is possible there, and skipping a genuine churn
        # sample would only delay the streaming exit by a batch. And
        # never skip twice in a row: consecutive resets mean the resets
        # themselves are the workload (see __init__).
        self._skip_ewma_once = not self._streaming and not self._ewma_suppressed
        self._alloc_slot_arrays()

    def on_transaction_failed(self) -> None:
        """The plan-time scatter already advanced the cache; a rolled
        back transaction leaves SQLite behind it, so drop everything
        and re-seed lazily."""
        self.reset()

    # -- the planner --

    def _adaptive_gate(self, cells):
        """ONE copy of the adaptive seeding gate (EWMA + streaming
        hysteresis) shared by `plan_batch` and `plan_packed` — the two
        flows must keep identical cache behavior, so the state machine
        lives here. Updates the EWMA and mode, returns
        (mode, new_cells): "stream" = plan with SQLite-streamed winners
        (cache dropped on entry); "cached" = seed `new_cells` then plan
        from HBM. Every return routes through `_gate_result` (mode
        gauge + streamed-cell counting); cached-mode hit/miss counting
        lives in `_count_cached`, fired by the callers only after
        seeding succeeds — see both docstrings."""
        if not self.adaptive and self._streaming:
            # The gate was disabled while streaming (tests / ops
            # pinning the static path): leave streaming mode so the
            # cached path reseeds from SQLite — keeping `known = _known`
            # here would skip seeding cells whose slots were dropped at
            # the streaming switch (KeyError).
            self._streaming = False
            self._known = set()
        known = self._known if self._streaming else self._slots
        new_cells = [c for c in cells if c not in known]
        rate = len(new_cells) / len(cells)
        if self._skip_ewma_once:
            self._skip_ewma_once = False
            self._ewma_suppressed = True
        else:
            self._seed_ewma = (
                (1 - self._EWMA_NEW_WEIGHT) * self._seed_ewma
                + self._EWMA_NEW_WEIGHT * rate
            )
            self._ewma_suppressed = False
        if not self.adaptive:
            return self._gate_result("cached", cells, new_cells)
        if self._streaming:
            # Bound the membership estimator: sustained churn (the
            # very workload streaming targets) would otherwise grow
            # it forever. On overflow, restart it from this batch —
            # the one-batch rate spike only reinforces streaming.
            if len(self._known) > self._KNOWN_CAP:
                self._known = set(cells)
            else:
                self._known.update(cells)
            if self._seed_ewma > self.seed_lo:
                return self._gate_result("stream", cells, new_cells)
            # Churn subsided: warm the cache back up this batch
            # (known was _known while streaming; recompute vs slots,
            # and release the estimator — cached mode never reads
            # it, and a later burst rebuilds it from _slots).
            self._streaming = False
            self._known = set()
            metrics.inc("evolu_winner_cache_mode_switches_total", to="cached")
            return self._gate_result(
                "cached", cells, [c for c in cells if c not in self._slots]
            )
        if self._seed_ewma > self.seed_hi:
            # Seeding dominates: drop the cache (it stops being
            # maintained, so it must not survive) and stream until
            # the EWMA decays under seed_lo.
            self._streaming = True
            self._known = set(self._slots)
            self._known.update(cells)
            self.reset()  # arms no EWMA skip: _streaming is set
            metrics.inc("evolu_winner_cache_mode_switches_total", to="stream")
            return self._gate_result("stream", cells, new_cells)
        return self._gate_result("cached", cells, new_cells)

    def _gate_result(self, mode, cells, new_cells):
        """Record the gate's mode decision (gauge only). Cell counting
        is DEFERRED to `_count_cached`/`_count_streamed`, fired by the
        callers only once a route is committed — a batch that bounces
        onward (non-canonical stored winner → host fallback or object
        path, which may re-enter this gate) must not be counted twice
        or on the wrong route."""
        metrics.set_gauge("evolu_winner_cache_streaming", 1 if self._streaming else 0)
        return mode, new_cells

    @staticmethod
    def _count_cached(cells, new_cells):
        """Unique cells served from HBM slots (hits) vs seeded from
        SQLite (misses) — counted at the point of no return on the
        cached route (seeding succeeded, the HBM kernel will plan)."""
        metrics.inc("evolu_winner_cache_hits_total", len(cells) - len(new_cells))
        metrics.inc("evolu_winner_cache_misses_total", len(new_cells))

    @staticmethod
    def _count_streamed(cells):
        """Unique cells planned with SQLite-streamed winners — counted
        only once the streamed plan is actually produced."""
        metrics.inc("evolu_winner_cache_streamed_cells_total", len(cells))

    @with_x64
    def plan_batch(self, messages: Sequence[CrdtMessage], existing_winners=None):
        """Planner with the `plan_batch_device_full` contract
        ((xor_mask, upserts, deltas) + positional upsert mask), winners
        sourced from HBM instead of the `existing_winners` argument
        (which apply passes as {} — `fetches_winners = False`)."""
        n = len(messages)
        if n == 0:
            return PlannedBatch([], [], {}, np.zeros(0, bool))
        self._drop_if_foreign_write()
        with span("kernel:merge", "winner_cache.plan_batch", n=n):
            millis, counter, node, case_ok = parse_timestamp_strings(
                [m.timestamp for m in messages], with_case=True
            )
            cell_ids, cells = intern_cells(
                [m.table for m in messages], [m.row for m in messages],
                [m.column for m in messages],
            )
            if not bool(case_ok.all()):
                return self._host_fallback(messages, cells)

            mode, new_cells = self._adaptive_gate(cells)
            if mode == "cached":
                new_cells = self._enforce_capacity(cells, new_cells)
            if mode == "stream" or new_cells is None:
                return self._plan_streamed(
                    messages, cells, cell_ids, millis, counter, node
                )
            if new_cells and not self._seed_new_cells(new_cells):
                return self._host_fallback(messages, cells)
            self._count_cached(cells, new_cells)

            slot_of = np.fromiter(
                (self._slots[c] for c in cells), np.int32, len(cells)
            )
            slots = slot_of[cell_ids]
            xor_mask, upsert_mask, deltas = self._run_cached_plan(
                cell_ids, slots, millis, counter, node, n
            )
            return PlannedBatch(
                xor_mask.tolist(), select_messages(messages, upsert_mask),
                deltas, upsert_mask,
            )

    def _run_cached_plan(self, cell_ids, slots, millis, counter, node, n):
        """ONE copy of the cached kernel-call sequence (pad → gather/
        plan/scatter dispatch → pull → unpermute → delta decode) shared
        by `plan_batch` and `plan_packed` — the two flows must produce
        identical plans, so the sequence lives here. →
        (xor_mask, upsert_mask, deltas), masks in batch order, length n."""
        k1 = pack_ts_key_host(millis, counter)
        size = bucket_size(n)
        pad = size - n
        cell_p = np.concatenate([cell_ids, np.full(pad, int(_PAD_CELL), np.int32)])
        slots_p = np.concatenate([slots, np.zeros(pad, np.int32)])
        k1_p = np.concatenate([k1, np.zeros(pad, np.uint64)])
        k2_p = np.concatenate([node, np.zeros(pad, np.uint64)])

        self._w1, self._w2, *outs = _cached_plan_kernel(
            self._w1, self._w2, jnp.asarray(slots_p),
            jnp.asarray(cell_p), jnp.asarray(k1_p), jnp.asarray(k2_p),
        )
        xor_s, upsert_s, i_s, minute_sorted, seg_end, seg_xor, valid = (
            to_host_many(*outs)
        )
        xor_mask, upsert_mask = unpermute_masks(xor_s, upsert_s, i_s)
        deltas = decode_owner_minute_deltas(
            np.zeros(size, np.int32), minute_sorted, seg_end, seg_xor, valid
        ).get(0, {})
        return xor_mask[:n], upsert_mask[:n], deltas

    @with_x64
    def plan_packed(self, pb):
        """Packed twin of `plan_batch` for PackedReceive batches (the
        fused receive leg): columns come straight from the C decrypt —
        timestamps parsed once over the 46-wide slab, cells already
        interned — and the result is positional numpy masks
        `(xor_mask, upsert_mask, deltas)` for the packed SQLite apply,
        so no upsert message list is ever built.

        Returns None when the batch must take the object path instead:
        non-canonical hex case in the batch (checked BEFORE any EWMA /
        cache mutation, so the re-route through `plan_batch` keeps
        adaptive-gate parity with a pure-object flow) or a
        non-canonical stored winner seed (the re-route's own
        `_host_fallback` owns invalidation; `_skip_ewma_once` is armed
        before that bounce so the re-entered gate does not sample the
        EWMA a second time for the same batch)."""
        n = pb.n
        if n == 0:
            return np.zeros(0, bool), np.zeros(0, bool), {}
        self._drop_if_foreign_write()
        with span("kernel:merge", "winner_cache.plan_packed", n=n):
            millis, counter, node, case_ok = pb.parse_timestamps()
            if not bool(case_ok.all()):
                return None
            # A slice shares the full batch's interned cell list; only
            # the ids this chunk touches get slots/seeds.
            touched_ids, cells = pb.touched_cells()

            mode, new_cells = self._adaptive_gate(cells)
            if mode == "cached":
                new_cells = self._enforce_capacity(cells, new_cells)
            if mode == "stream" or new_cells is None:
                return self._plan_packed_streamed(
                    pb, cells, touched_ids, millis, counter, node
                )
            if new_cells and not self._seed_new_cells(new_cells):
                # The gate above already took this batch's EWMA sample;
                # the object-path re-route will re-enter the gate (via
                # `plan_batch`) for the SAME batch — arm the one-shot
                # skip so a non-canonical bounce never samples twice.
                self._skip_ewma_once = True
                return None  # non-canonical stored winner → object path
            self._count_cached(cells, new_cells)

            slot_arr = np.zeros(len(pb.cells), np.int32)
            for i in touched_ids:
                slot_arr[int(i)] = self._slots[pb.cells[int(i)]]
            slots = slot_arr[pb.cell_id]
            return self._run_cached_plan(
                pb.cell_id, slots, millis, counter, node, n
            )

    def _plan_packed_streamed(self, pb, cells, touched_ids, millis, counter, node):
        """Streaming-mode packed plan: winners from SQLite, no cache
        state. None on a non-canonical stored winner (object path —
        where the re-entered gate counts the route actually taken, so
        streamed cells count only on a produced plan)."""
        from evolu_tpu.ops.merge import plan_packed_streamed

        plan = plan_packed_streamed(
            self._db, pb, millis, counter, node, cells, touched_ids
        )
        if plan is not None:
            self._count_streamed(cells)
        return plan

    def _plan_streamed(self, messages, cells, cell_ids, millis, counter, node):
        """High-churn mode: winners streamed from SQLite per batch, no
        cache state touched (it was dropped on entry). End state is
        identical to the cached path — both feed the same planner
        kernel; only the winner source differs. The caller's
        already-parsed columns are reused (`cols=`) so the batch is not
        host-parsed a second time — this IS the hot path while churn
        lasts."""
        from evolu_tpu.ops.merge import plan_batch_device_full
        from evolu_tpu.storage.apply import fetch_existing_winners

        from evolu_tpu.ops.merge import winner_key_columns

        winners = fetch_existing_winners(self._db, cells)
        ex1_u, ex2_u, canonical = winner_key_columns(cells, winners)
        if not canonical:
            # Non-canonical stored winner: host oracle (raw-string
            # order / verbatim hashing), same as the cached route.
            return self._host_fallback(messages, cells)
        k1 = pack_ts_key_host(millis, counter)
        self._count_streamed(cells)
        cols = (
            cell_ids, k1, node, ex1_u[cell_ids], ex2_u[cell_ids],
            millis, counter, node, True,
        )
        return plan_batch_device_full(messages, {}, cols=cols)

    # -- the PR-11 invariant audit --

    def verify_against_db(self, sample: "int | None" = None) -> int:
        """Audit the correctness centerpiece of the storage inversion
        (PR-11 / ROADMAP #1, which promotes this cache from cache to
        truth): every LIVE slot's (k1, k2) winner keys must equal
        SQLite's MAX(timestamp) for its cell, read back from the HBM
        slot arrays themselves — not from any host mirror. Streaming
        mode holds no slots (SQLite is the winner source there), so
        the audit is vacuous then by design. → the number of cells
        checked; raises AssertionError naming the first divergent
        cells. `sample` caps the audit to the first N cells (ops
        surface — a full pull of a 2^22-slot cache is ~64 MiB over a
        bandwidth-bound tunnel)."""
        from evolu_tpu.ops.merge import winner_key_columns
        from evolu_tpu.storage.apply import fetch_existing_winners

        cells = list(self._slots)
        if sample is not None:
            cells = cells[: int(sample)]
        if not cells:
            return 0
        winners = fetch_existing_winners(self._db, cells)
        v1, v2, canonical = winner_key_columns(cells, winners)
        if not canonical:
            raise AssertionError(
                "non-canonical stored winner occupies a cache slot "
                "(the host-fallback invalidation contract is broken)"
            )
        # Gather ONLY the audited slots device-side and pull both
        # columns in one wave (CLAUDE.md: never per-array, and a full
        # 2^22-slot pull is the very 64 MiB `sample` exists to avoid).
        idx = np.fromiter((self._slots[c] for c in cells), np.int64, len(cells))
        w1, w2 = self._gather_slot_values(idx)
        bad = []
        for j, c in enumerate(cells):
            if int(w1[j]) != int(v1[j]) or int(w2[j]) != int(v2[j]):
                bad.append((c, int(w1[j]), int(v1[j])))
                if len(bad) >= 5:
                    break
        if bad:
            raise AssertionError(
                f"winner cache != MAX(timestamp) for {len(bad)}+ cells: {bad}"
            )
        return len(cells)

    def _host_fallback(self, messages, cells):
        """Non-canonical hex case: invalidate every touched cell —
        their SQLite winners may now be non-canonical, which the
        numeric cache cannot represent — then delegate to the shared
        host-oracle fallback (raw-string order, verbatim-case hashing;
        one implementation to keep in sync)."""
        from evolu_tpu.ops.merge import _host_fallback
        from evolu_tpu.storage.apply import fetch_existing_winners

        metrics.inc("evolu_winner_cache_host_fallbacks_total")
        self.invalidate(cells)
        existing = fetch_existing_winners(self._db, cells)
        return _host_fallback(messages, existing, len(messages), with_deltas=True)


class MeshShardedWinnerCache(DeviceWinnerCache):
    """PR-12: the winner store SHARDED over the device mesh — slot
    arrays of shape (n_devices, capacity) laid out with a
    `NamedSharding` on the owners axis, cells placed on a STABLE shard
    (crc32 of the cell triple — `parallel.mesh.owner_shard` over the
    interned key, so a cell's slot lives on the same device forever),
    and `plan_batch`/`plan_packed` running ONE shard_map'd
    gather/plan/scatter pass: each device plans the cells it owns from
    its OWN slot rows, and the per-shard (minute, xor) partials are
    XOR-merged by the host decoder exactly (the cross-device reduction
    of per-owner Merkle deltas — decoders merge repeated keys by
    construction).

    Coherence is the base contract, now PER SHARD: every live slot on
    device d equals SQLite's MAX(timestamp) for its cell
    (`verify_against_db` audits through the sharded gather; the
    invalidation/reset/foreign-write hooks are inherited verbatim —
    they operate above the array hooks). Encoded slot ids are
    `local * n_shards + shard`, so growing the per-shard capacity
    (doubling along axis 1) never rewrites an assigned id.
    """

    def __init__(
        self,
        db,
        mesh_ctx=None,
        capacity: int = 1 << 12,
        adaptive: bool = True,
        max_slots: "int | None" = 1 << 22,
    ):
        from evolu_tpu.parallel.mesh import MeshContext

        self.ctx = mesh_ctx if mesh_ctx is not None else MeshContext()
        self.n_shards = self.ctx.n_shards
        self._free_by_shard: List[List[int]] = [[] for _ in range(self.n_shards)]
        self._next_by_shard: List[int] = [0] * self.n_shards
        super().__init__(db, capacity=capacity, adaptive=adaptive,
                         max_slots=max_slots)

    # -- placement --

    def _cell_shard(self, cell: Cell) -> int:
        from evolu_tpu.parallel.mesh import owner_shard

        return owner_shard("\x00".join(cell), self.n_shards)

    def shard_slot_counts(self) -> List[int]:
        """Live slots per device (ops/stats surface; the per-shard
        audit in tests groups its assertions by this placement)."""
        counts = [0] * self.n_shards
        for slot in self._slots.values():
            counts[slot % self.n_shards] += 1
        return counts

    # -- array hooks --

    def _sharding2(self):
        from jax.sharding import NamedSharding, PartitionSpec as P

        from evolu_tpu.parallel.mesh import OWNERS_AXIS

        return NamedSharding(self.ctx.mesh, P(OWNERS_AXIS, None))

    def _sharding1(self):
        from evolu_tpu.parallel.mesh import sharding

        return sharding(self.ctx.mesh)

    def _alloc_slot_arrays(self) -> None:
        shd = self._sharding2()
        with jax.enable_x64(True):
            self._w1 = jax.device_put(
                jnp.zeros((self.n_shards, self.capacity), jnp.uint64), shd
            )
            self._w2 = jax.device_put(
                jnp.zeros((self.n_shards, self.capacity), jnp.uint64), shd
            )

    def _clear_free_slots(self) -> None:
        self._free = []
        self._next_slot = 0
        self._free_by_shard = [[] for _ in range(self.n_shards)]
        self._next_by_shard = [0] * self.n_shards

    def _has_slot_state(self) -> bool:
        return bool(self._slots) or any(self._free_by_shard)

    def _grow_to(self, needed: int) -> None:
        """Grow the PER-SHARD capacity (axis 1); eager lax is fine —
        growth is doubling-rare and never on the steady-state path."""
        new_cap = self.capacity
        while new_cap < needed:
            new_cap *= 2
        if new_cap == self.capacity:
            return
        shd = self._sharding2()
        with jax.enable_x64(True):
            for name in ("_w1", "_w2"):
                grown = jax.lax.dynamic_update_slice(
                    jnp.zeros((self.n_shards, new_cap), jnp.uint64),
                    getattr(self, name), (0, 0),
                )
                setattr(self, name, jax.device_put(grown, shd))
        self.capacity = new_cap
        metrics.inc("evolu_winner_cache_grows_total")
        metrics.set_gauge("evolu_winner_cache_capacity_slots",
                          self.n_shards * new_cap)

    def _gather_slot_values(self, idx: np.ndarray):
        shard = idx % self.n_shards
        local = idx // self.n_shards
        with jax.enable_x64(True):
            return to_host_many(
                self._w1[jnp.asarray(shard), jnp.asarray(local)],
                self._w2[jnp.asarray(shard), jnp.asarray(local)],
            )

    def _assign_and_write_seeds(self, new_cells, v1, v2) -> None:
        ns = self.n_shards
        by_shard: List[List[int]] = [[] for _ in range(ns)]
        for j, c in enumerate(new_cells):
            by_shard[self._cell_shard(c)].append(j)
        need = self.capacity
        for si, js in enumerate(by_shard):
            fresh = max(len(js) - len(self._free_by_shard[si]), 0)
            need = max(need, self._next_by_shard[si] + fresh)
        self._grow_to(need)
        width = bucket_size(max(max(map(len, by_shard)), 1), multiple=16)
        # Pad rows target the out-of-range local index (dropped).
        idx = np.full((ns, width), self.capacity, np.int32)
        v1_p = np.zeros((ns, width), np.uint64)
        v2_p = np.zeros((ns, width), np.uint64)
        for si, js in enumerate(by_shard):
            for k, j in enumerate(js):
                if self._free_by_shard[si]:
                    local = self._free_by_shard[si].pop()
                else:
                    local = self._next_by_shard[si]
                    self._next_by_shard[si] += 1
                self._slots[new_cells[j]] = local * ns + si
                idx[si, k] = local
                v1_p[si, k] = v1[j]
                v2_p[si, k] = v2[j]
        shd = self._sharding2()
        with jax.enable_x64(True):
            self._w1, self._w2 = _sharded_seed_kernel(self.ctx.mesh)(
                self._w1, self._w2,
                jax.device_put(idx, shd),
                jax.device_put(v1_p, shd),
                jax.device_put(v2_p, shd),
            )

    def invalidate(self, cells) -> None:
        dropped = 0
        for c in cells:
            slot = self._slots.pop(c, None)
            if slot is not None:
                self._free_by_shard[slot % self.n_shards].append(
                    slot // self.n_shards
                )
                dropped += 1
        metrics.inc("evolu_winner_cache_invalidated_cells_total", dropped)

    # -- the sharded plan pass --

    def _run_cached_plan(self, cell_ids, slots, millis, counter, node, n):
        """ONE shard_map dispatch: route each row to the device owning
        its cell's slot (stable placement ⇒ same-cell rows co-locate,
        and within a shard the stable routing keeps them in batch
        order — the planner's idx tiebreak contract), pad per-device
        slices to a common power-of-two bucket, plan on-device, then
        unpermute per shard block and map back through the routing.
        Deltas XOR-merge across the per-shard partials in the decoder
        (cross-device reduction). Masks return in batch order, length
        n — identical results to the base single-device pass
        (parity-pinned in tests/test_mesh_engine.py)."""
        k1 = pack_ts_key_host(millis, counter)
        ns = self.n_shards
        shard = (slots % ns).astype(np.int64)
        local = (slots // ns).astype(np.int32)
        counts = np.bincount(shard, minlength=ns)
        size = bucket_size(max(int(counts.max(initial=0)), 1))
        total = ns * size
        cell_p = np.full(total, int(_PAD_CELL), np.int32)
        slots_p = np.zeros(total, np.int32)
        k1_p = np.zeros(total, np.uint64)
        k2_p = np.zeros(total, np.uint64)
        order = np.argsort(shard, kind="stable")
        offs = np.zeros(ns + 1, np.int64)
        offs[1:] = np.cumsum(counts)
        pos_in_shard = np.empty(n, np.int64)
        pos_in_shard[order] = np.arange(n, dtype=np.int64) - offs[shard[order]]
        dest = shard * size + pos_in_shard
        cell_p[dest] = cell_ids
        slots_p[dest] = local
        k1_p[dest] = k1
        k2_p[dest] = node
        self.ctx.record_occupancy(counts.tolist(), size)
        self.ctx.record_xdev_reduce("winner_minute_partials")
        shd1 = self._sharding1()
        self._w1, self._w2, *outs = _sharded_plan_kernel(self.ctx.mesh)(
            self._w1, self._w2,
            jax.device_put(slots_p, shd1), jax.device_put(cell_p, shd1),
            jax.device_put(k1_p, shd1), jax.device_put(k2_p, shd1),
        )
        xor_s, upsert_s, i_s, minute_sorted, seg_end, seg_xor, valid = (
            to_host_many(*outs)
        )
        xor_flat, upsert_flat = unpermute_masks(
            xor_s, upsert_s, i_s, block_size=size
        )
        deltas = decode_owner_minute_deltas(
            np.zeros(total, np.int32), minute_sorted, seg_end, seg_xor, valid
        ).get(0, {})
        return xor_flat[dest], upsert_flat[dest], deltas


def _pad_seed(idx, k1, k2, capacity: int):
    """Pad seed columns to a power-of-two bucket; pad rows target the
    out-of-range dump index (dropped by the scatter)."""
    size = bucket_size(len(idx), multiple=16)
    pad = size - len(idx)
    return (
        np.concatenate([idx, np.full(pad, capacity, np.int32)]),
        np.concatenate([k1, np.zeros(pad, np.uint64)]),
        np.concatenate([k2, np.zeros(pad, np.uint64)]),
    ), size
