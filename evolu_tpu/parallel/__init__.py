"""Device-mesh parallelism: owners sharded over ICI.

The reference's "distribution" is a star topology of independent
replicas (SURVEY.md §1); owners never share state, which makes the
server-side reconcile embarrassingly parallel over owners. This
package maps that onto a TPU pod the jax way (SURVEY.md §2.15):

- owners are assigned to mesh shards (balanced by message count);
- each device plans its owners' LWW merges and Merkle deltas locally
  (`shard_map` over the `owners` axis — no cross-device traffic in
  the hot loop);
- per-owner Merkle roots combine across the mesh with an XOR
  collective (XOR is associative+commutative, so tree digests reduce
  exactly; `xor_allreduce`).
"""

from evolu_tpu.parallel.mesh import (
    MeshContext,
    assign_owners_to_shards,
    create_mesh,
    get_mesh_context,
    owner_shard,
)
from evolu_tpu.parallel.reconcile import (
    reconcile_columns_sharded,
    reconcile_owner_batches,
    xor_allreduce,
)

__all__ = [
    "MeshContext",
    "create_mesh",
    "get_mesh_context",
    "owner_shard",
    "assign_owners_to_shards",
    "reconcile_columns_sharded",
    "reconcile_owner_batches",
    "xor_allreduce",
]
