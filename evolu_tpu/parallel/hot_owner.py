"""Cell-range sharding for one hot owner.

`parallel.reconcile` never splits an owner across shards — right for
fleets of owners, wrong when ONE owner's batch exceeds a single
device. Per-cell LWW merges are independent, so a hot owner's batch
shards by cell id instead (SURVEY.md §5: "within one hot owner, by
cell-id ranges after the radix sort"): each device plans a contiguous
range of interned cell ids, per-minute Merkle XOR deltas are computed per shard and
XOR-combined across shards (XOR is associative/commutative, so
per-shard per-minute partial deltas merge exactly), and the batch
digest is XOR-allreduced over ICI.

Contract matches the single-device planner: masks in original batch
order, {base3-minute-key: delta} dict, uint32 digest.
"""

from __future__ import annotations

from typing import Dict, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from evolu_tpu.ops import shard_map
from jax.sharding import Mesh, PartitionSpec as P

import functools

from evolu_tpu.ops import bucket_size, to_host_many, with_x64
from evolu_tpu.ops.encode import timestamp_hashes, unpack_ts_keys
from evolu_tpu.ops.merge import _PAD_CELL, plan_merge_sorted_core, unpermute_masks
from evolu_tpu.ops.merkle_ops import decode_owner_minute_deltas, owner_minute_segments
from evolu_tpu.parallel.mesh import OWNERS_AXIS, put_sharded, require_single_process, sharding
from evolu_tpu.parallel.reconcile import xor_allreduce
from evolu_tpu.utils.log import span


def _shard_kernel(cell_id, k1, k2, ex_k1, ex_k2):
    xor_s, upsert_s, i_s, s1, s2, _ = plan_merge_sorted_core(cell_id, k1, k2, ex_k1, ex_k2)
    millis_s, counter_s = unpack_ts_keys(s1)
    hashes = jnp.where(xor_s, timestamp_hashes(millis_s, counter_s, s2), jnp.uint32(0))
    # hi key = 0 for every real row (single owner); segments = minutes.
    zero_owner = jnp.zeros((), jnp.int32)
    _, minute_sorted, seg_end, seg_xor, valid_sorted = owner_minute_segments(
        zero_owner, millis_s, hashes, xor_s
    )
    digest = xor_allreduce(jax.lax.reduce(hashes, jnp.uint32(0), jnp.bitwise_xor, (0,)))
    return xor_s, upsert_s, i_s, minute_sorted, seg_end, seg_xor, valid_sorted, digest


@functools.lru_cache(maxsize=None)
def _compiled_kernel(mesh: Mesh):
    spec = P(OWNERS_AXIS)
    return jax.jit(
        shard_map(
            _shard_kernel,
            mesh=mesh,
            in_specs=(spec,) * 5,
            out_specs=(spec,) * 7 + (P(),),
            check_vma=False,
        )
    )


@with_x64
def reconcile_hot_owner(
    mesh: Mesh,
    cell_id: np.ndarray,
    k1: np.ndarray,
    k2: np.ndarray,
    ex_k1: np.ndarray,
    ex_k2: np.ndarray,
    millis: np.ndarray,
    counter: np.ndarray,
    node: np.ndarray,
) -> Tuple[np.ndarray, np.ndarray, Dict[str, int], int]:
    """One owner's columnar batch, cells sharded over the mesh.

    Returns (xor_mask, upsert_mask, minute_deltas, digest) with masks in
    original batch order — identical to running `plan_merge_core` +
    minute deltas on one device (property-tested in tests).
    """
    require_single_process("reconcile_hot_owner")
    n = len(cell_id)
    n_dev = mesh.devices.size
    with span("kernel:reconcile", "reconcile_hot_owner", n=n, devices=n_dev):
        # Assign cells (not rows) to shards so every message of a cell
        # lands on the same device. Interned cell ids are dense
        # (0..num_cells-1), so contiguous ranges balance well.
        num_cells = int(cell_id.max()) + 1 if n else 1
        shard_of = (cell_id.astype(np.int64) * n_dev) // num_cells
        order = np.argsort(shard_of, kind="stable")
        loads = np.bincount(shard_of, minlength=n_dev)
        chunk = bucket_size(int(loads.max()) if n else 1)
        total = n_dev * chunk

        # millis/counter/node are recovered on device from the HLC keys;
        # only the key columns are laid out and transferred.
        cols = {
            "cell_id": np.full(total, int(_PAD_CELL), np.int32),
            "k1": np.zeros(total, np.uint64),
            "k2": np.zeros(total, np.uint64),
            "ex_k1": np.zeros(total, np.uint64),
            "ex_k2": np.zeros(total, np.uint64),
        }
        src = {"cell_id": cell_id, "k1": k1, "k2": k2, "ex_k1": ex_k1, "ex_k2": ex_k2}
        # positions[i] = where original row i lives in the flat layout
        positions = np.empty(n, np.int64)
        start = 0
        for d in range(n_dev):
            rows = order[start : start + loads[d]]
            dst = np.arange(d * chunk, d * chunk + loads[d])
            positions[rows] = dst
            for name, a in src.items():
                cols[name][dst] = a[rows]
            start += loads[d]

        shd = sharding(mesh)
        args = [put_sharded(cols[k], shd) for k in
                ("cell_id", "k1", "k2", "ex_k1", "ex_k2")]
        # ONE transfer wave for all 8 outputs (ops.to_host_many).
        xor_s, upsert_s, i_s, minute_sorted, seg_end, seg_xor, valid, digest = (
            to_host_many(*_compiled_kernel(mesh)(*args))
        )

        xor_flat, upsert_flat = unpermute_masks(xor_s, upsert_s, i_s, block_size=chunk)
        xor_mask = xor_flat[positions]
        upsert_mask = upsert_flat[positions]

        # XOR-combine per-minute deltas across shards (exact: XOR
        # monoid; the shared decoder merges repeated minute keys).
        by_owner = decode_owner_minute_deltas(
            np.zeros_like(minute_sorted), minute_sorted, seg_end, seg_xor, valid
        )
        deltas: Dict[str, int] = by_owner.get(0, {})
        return xor_mask, upsert_mask, deltas, int(digest)
