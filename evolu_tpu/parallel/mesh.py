"""Mesh construction and owner→shard assignment.

Owners are the data-parallel unit (each owner's message log and Merkle
tree are independent by construction — the relay keys everything by
userId, apps/server/src/index.ts:64-75), so the mesh has one axis,
`owners`. Multi-host pods get the same axis laid over all devices; XLA
routes the XOR-combine collectives over ICI.
"""

from __future__ import annotations

from typing import Dict, Hashable, List, Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec

OWNERS_AXIS = "owners"


def create_mesh(n_devices: Optional[int] = None, devices=None) -> Mesh:
    """A 1-D mesh over `n_devices` (default: all available)."""
    if devices is None:
        devices = jax.devices()
    if n_devices is not None:
        devices = devices[:n_devices]
    # Push the platform into the jax-free stage-anatomy plane (ISSUE
    # 16): obs/anatomy.py prices roofline floors from COST_LAWS keyed
    # by platform but must never import jax itself, so the one place
    # that already holds a device tells it. Best-effort — an exotic
    # device object without .platform must not break mesh creation.
    try:
        from evolu_tpu.obs import anatomy

        anatomy.set_platform(devices[0].platform)
    except Exception:  # noqa: BLE001 - telemetry must never gate compute
        pass
    return Mesh(np.array(devices), (OWNERS_AXIS,))


def sharding(mesh: Mesh) -> NamedSharding:
    """Shard a 1-D array's leading axis over the owners axis."""
    return NamedSharding(mesh, PartitionSpec(OWNERS_AXIS))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, PartitionSpec())


def put_sharded(arr: np.ndarray, shd: NamedSharding):
    """Host array → sharded device array, for every sharded dispatch
    site. Single-process: plain device_put. Multi-process (a
    jax.distributed cluster — the DCN topology): each process serves
    its ADDRESSABLE shards from the same host-built global layout via
    make_array_from_callback; jax assembles the global array without
    any process addressing foreign devices."""
    if jax.process_count() > 1:
        return jax.make_array_from_callback(arr.shape, shd, lambda idx: arr[idx])
    return jax.device_put(arr, shd)


def require_single_process(what: str) -> None:
    """Loud guard for fan-ins that index kernel outputs with GLOBAL
    positions: on a multi-process cluster `to_host` returns only the
    ADDRESSABLE shards, so global indexing would be silently wrong.
    The multi-process pattern is `reconcile_columns_sharded` +
    `multihost.local_owners`, each process consuming its own shards
    (see tests/_multihost_worker.py)."""
    if jax.process_count() > 1:
        raise NotImplementedError(
            f"{what} assembles per-owner results from GLOBAL output positions "
            "and runs single-process only; on a jax.distributed cluster use "
            "reconcile_columns_sharded + multihost.local_owners per process"
        )


def owner_shard(owner_id, n_shards: int) -> int:
    """STABLE owner→device placement (crc32, the same family as
    `ShardedRelayStore.shard_index` and `engine.owner_process`): an
    owner's rows land on the same mesh device every batch, which is
    what lets per-owner device-resident state (sharded winner-cache
    slots, write-behind serving trees fed from sharded deltas) survive
    across batches. Pure function of (owner, n_shards) — every
    process/relay sharing a mesh computes the same placement."""
    import zlib

    if not isinstance(owner_id, (bytes, bytearray)):
        owner_id = str(owner_id).encode("utf-8")
    return zlib.crc32(owner_id) % n_shards


class MeshContext:
    """ONE device-mesh context shared by every sharded-engine consumer
    in the process (engine passes, the sharded winner cache, scheduler
    pools serving several relays): the mesh object is the jit-cache key
    for every compiled shard_map kernel, so sharing the context means
    one compiled pipeline per bucket for the whole process — not one
    per relay — and `place`/`assign_stable` give all consumers the same
    stable owner→device placement.

    Per-batch LPT (``assign_owners_to_shards``) balances better but
    re-places owners every batch; the sharded engine trades that for
    placement stability and measures the cost honestly instead
    (`evolu_mesh_shard_rows` occupancy and `evolu_mesh_padding_waste_rows`
    histograms, docs/OBSERVABILITY.md)."""

    def __init__(self, mesh: Optional[Mesh] = None, n_devices: Optional[int] = None):
        self.mesh = mesh if mesh is not None else create_mesh(n_devices)
        self.n_shards = int(self.mesh.devices.size)
        from evolu_tpu.obs import metrics

        metrics.set_gauge("evolu_mesh_devices", self.n_shards)

    def place(self, owner_id) -> int:
        return owner_shard(owner_id, self.n_shards)

    def assign_stable(self, unit_sizes: Dict[Hashable, int]) -> List[List[Hashable]]:
        """Placement-stable layout with the `assign_owners_to_shards`
        return shape. Units are owner ids or (owner, chunk-index)
        tuples (the engine's hot-owner row-split): chunk j of owner o
        lands on shard (place(o) + j) % n — chunk 0 always on the
        owner's home shard, later chunks spilling round-robin so a hot
        owner still uses the whole mesh (safe wherever the decoder
        XOR-merges repeated (owner, minute) partials, which every
        engine delta decoder does)."""
        shards: List[List[Hashable]] = [[] for _ in range(self.n_shards)]
        for u in unit_sizes:
            if isinstance(u, tuple) and len(u) == 2:
                owner, j = u
            else:
                owner, j = u, 0
            shards[(self.place(owner) + int(j)) % self.n_shards].append(u)
        return shards

    def record_occupancy(self, loads: Sequence[int], shard_size: int) -> None:
        """Per-device batch-occupancy / padding-waste telemetry for one
        sharded dispatch (`evolu_mesh_*`, docs/OBSERVABILITY.md)."""
        from evolu_tpu.obs import metrics

        for load in loads:
            metrics.observe("evolu_mesh_shard_rows", load,
                            buckets=metrics.COUNT_BUCKETS)
            metrics.observe("evolu_mesh_padding_waste_rows",
                            max(shard_size - load, 0),
                            buckets=metrics.COUNT_BUCKETS)
        metrics.inc("evolu_mesh_dispatches_total")

    def record_xdev_reduce(self, kind: str) -> None:
        """Count one cross-device reduction (the digest XOR all-reduce
        of a sharded dispatch, or a host XOR-merge of per-owner delta
        partials that spanned devices)."""
        from evolu_tpu.obs import metrics

        metrics.inc("evolu_mesh_xdev_reduce_total", kind=kind)


_process_ctx: Optional[MeshContext] = None


def get_mesh_context(n_devices: Optional[int] = None) -> MeshContext:
    """The process-wide MeshContext singleton (relay/scheduler wiring —
    embedders and tests pass explicit contexts instead). Lazy: calling
    this touches the jax backend, so it must only run on device-side
    paths (the scheduler's first batch), never at relay import.

    FIRST CREATION WINS: placement (`owner_shard` is mod n_shards) must
    be one function per process — two contexts of different sizes would
    place the same owner on different devices for different consumers.
    A later call with a mismatched `n_devices` therefore returns the
    existing context (logged), never a second pool."""
    global _process_ctx
    if _process_ctx is None:
        _process_ctx = MeshContext(n_devices=n_devices)
    elif n_devices is not None and _process_ctx.n_shards != n_devices:
        from evolu_tpu.utils.log import log

        log("server", "mesh context size mismatch ignored (first wins)",
            have=_process_ctx.n_shards, requested=n_devices)
    return _process_ctx


def assign_owners_to_shards(
    owner_sizes: Dict[Hashable, int], n_shards: int
) -> List[List[Hashable]]:
    """Greedy LPT balance: work units (with their message counts) onto
    shards, heaviest first. A unit is never split across shards. Units
    are usually whole owners (keyed by owner id), keeping merge/Merkle
    work device-local — but callers may pre-split a hot owner into
    finer units, e.g. `engine.deltas_from_columns` passes
    (owner, chunk-index) tuples whose partial digests are XOR-merged
    after the pass; this function only balances whatever units it is
    given."""
    shards: List[List[Hashable]] = [[] for _ in range(n_shards)]
    loads = [0] * n_shards
    for owner in sorted(owner_sizes, key=owner_sizes.get, reverse=True):
        i = loads.index(min(loads))
        shards[i].append(owner)
        loads[i] += owner_sizes[owner]
    return shards
