"""Mesh construction and owner→shard assignment.

Owners are the data-parallel unit (each owner's message log and Merkle
tree are independent by construction — the relay keys everything by
userId, apps/server/src/index.ts:64-75), so the mesh has one axis,
`owners`. Multi-host pods get the same axis laid over all devices; XLA
routes the XOR-combine collectives over ICI.
"""

from __future__ import annotations

from typing import Dict, Hashable, List, Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec

OWNERS_AXIS = "owners"


def create_mesh(n_devices: Optional[int] = None, devices=None) -> Mesh:
    """A 1-D mesh over `n_devices` (default: all available)."""
    if devices is None:
        devices = jax.devices()
    if n_devices is not None:
        devices = devices[:n_devices]
    return Mesh(np.array(devices), (OWNERS_AXIS,))


def sharding(mesh: Mesh) -> NamedSharding:
    """Shard a 1-D array's leading axis over the owners axis."""
    return NamedSharding(mesh, PartitionSpec(OWNERS_AXIS))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, PartitionSpec())


def put_sharded(arr: np.ndarray, shd: NamedSharding):
    """Host array → sharded device array, for every sharded dispatch
    site. Single-process: plain device_put. Multi-process (a
    jax.distributed cluster — the DCN topology): each process serves
    its ADDRESSABLE shards from the same host-built global layout via
    make_array_from_callback; jax assembles the global array without
    any process addressing foreign devices."""
    if jax.process_count() > 1:
        return jax.make_array_from_callback(arr.shape, shd, lambda idx: arr[idx])
    return jax.device_put(arr, shd)


def require_single_process(what: str) -> None:
    """Loud guard for fan-ins that index kernel outputs with GLOBAL
    positions: on a multi-process cluster `to_host` returns only the
    ADDRESSABLE shards, so global indexing would be silently wrong.
    The multi-process pattern is `reconcile_columns_sharded` +
    `multihost.local_owners`, each process consuming its own shards
    (see tests/_multihost_worker.py)."""
    if jax.process_count() > 1:
        raise NotImplementedError(
            f"{what} assembles per-owner results from GLOBAL output positions "
            "and runs single-process only; on a jax.distributed cluster use "
            "reconcile_columns_sharded + multihost.local_owners per process"
        )


def assign_owners_to_shards(
    owner_sizes: Dict[Hashable, int], n_shards: int
) -> List[List[Hashable]]:
    """Greedy LPT balance: work units (with their message counts) onto
    shards, heaviest first. A unit is never split across shards. Units
    are usually whole owners (keyed by owner id), keeping merge/Merkle
    work device-local — but callers may pre-split a hot owner into
    finer units, e.g. `engine.deltas_from_columns` passes
    (owner, chunk-index) tuples whose partial digests are XOR-merged
    after the pass; this function only balances whatever units it is
    given."""
    shards: List[List[Hashable]] = [[] for _ in range(n_shards)]
    loads = [0] * n_shards
    for owner in sorted(owner_sizes, key=owner_sizes.get, reverse=True):
        i = loads.index(min(loads))
        shards[i].append(owner)
        loads[i] += owner_sizes[owner]
    return shards
