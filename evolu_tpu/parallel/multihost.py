"""Multi-host initialization — the DCN leg of the distributed design.

Topology (SURVEY.md §5 "distributed communication backend"):

- **Inside a pod (ICI)**: the owner mesh spans every device JAX knows
  about; XLA inserts the collectives (`xor_allreduce` rides ICI).
- **Across hosts (DCN)**: two distinct channels —
  1. the *control/compute* plane: `jax.distributed` (this module) so a
     multi-host mesh sees all processes' devices and collectives cross
     hosts over DCN where the topology requires;
  2. the *sync protocol* plane: the unchanged protobuf-over-HTTP relay
     contract (`evolu_tpu.sync`, `evolu_tpu.server.relay`) — existing
     TypeScript clients interoperate with a pod-backed relay unchanged.

The reference's analog is Worker `postMessage` in-device plus the HTTP
star topology across devices; there is no NCCL/MPI to port — the mesh
+ collectives ARE the backend.
"""

from __future__ import annotations

from typing import Optional

import jax

from evolu_tpu.parallel.mesh import create_mesh


def initialize_multihost(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
):
    """Join this host to the pod's jax.distributed cluster and return
    the global owner mesh over every device in the cluster.

    With no arguments, environment-driven auto-detection is used (TPU
    pods populate it); on a single host this is a no-op join of a
    1-process cluster. Call once, before any jax computation.
    """
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
    )
    return create_mesh()


def is_multihost() -> bool:
    return jax.process_count() > 1


def local_shard_indices(mesh) -> list:
    """Mesh shard slots whose device this PROCESS hosts (hosts feed
    only their addressable devices; jax assembles the global array)."""
    me = jax.process_index()
    return [i for i, d in enumerate(mesh.devices.flat) if d.process_index == me]


def local_owners(mesh, shards) -> list:
    """Owners this process materializes data for, given the ACTUAL
    shard assignment produced by `assign_owners_to_shards` (greedy LPT
    — shard index s maps to mesh.devices.flat[s])."""
    mine = set(local_shard_indices(mesh))
    return [o for i, shard in enumerate(shards) if i in mine for o in shard]
