"""Sharded multi-owner reconcile — the pod-scale merge pass.

Replaces the relay's per-user, per-message loop (reference
apps/server/src/index.ts:148-159) and the client's per-message
applyMessages loop with ONE device dispatch for a whole fleet of
owners: owners are assigned to mesh shards (never split), each device
plans its owners' LWW merges and per-(owner, minute) Merkle XOR
deltas locally, and the only cross-device traffic is the final XOR
all-reduce of the batch digest. XOR is associative and commutative,
so combining per-shard digests over ICI is exact (SURVEY.md §2.15).

Cell ids are interned per owner then offset by a global base, so a
flat shard holds many owners yet `plan_merge_core`'s cell segmentation
keeps them apart. The (owner, minute) segment key is an int32 pair
(owner in the hi key, JS-wrapped minute in the lo key; masked rows
park under the int32-max hi sentinel) so the segmented sort stays
fully 32-bit.
"""

from __future__ import annotations

import functools
from typing import Dict, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P
from evolu_tpu.ops import shard_map

from evolu_tpu.core.types import CrdtMessage
from evolu_tpu.obs import metrics
from evolu_tpu.ops import bucket_size, to_host_many, with_x64
from evolu_tpu.ops.encode import timestamp_hashes, unpack_ts_keys
from evolu_tpu.ops.merge import (
    _PAD_CELL,
    masks_from_sorted_flags,
    messages_to_columns,
    plan_merge_sorted_flags,
    select_messages,
    unpermute_masks,
    winner_flags,
)
from evolu_tpu.ops.merkle_ops import decode_owner_minute_deltas, owner_minute_segments
from evolu_tpu.parallel.mesh import (
    OWNERS_AXIS,
    assign_owners_to_shards,
    put_sharded,
    require_single_process,
    sharding,
)
from evolu_tpu.utils.log import log, span



def xor_allreduce(x, axis_name: str = OWNERS_AXIS):
    """XOR-combine a per-shard value across the mesh axis.

    XLA has no XOR collective; all_gather + local XOR-reduce is one
    ICI round and exact for the associative/commutative XOR monoid.
    """
    gathered = jax.lax.all_gather(x, axis_name)
    return jax.lax.reduce(gathered, jnp.uint32(0), jnp.bitwise_xor, (0,))


# Packed-owner sort key layout (r5): owner(12) | cell(25) | idx(24) |
# flags(2) = 63 bits — the whole per-row identity rides the ONE i64
# sort key, so the merge sort carries only the two u64 HLC keys as
# payloads (the owner i32 payload measured ~0.28 ms/1M on v5e).
# Owner value 4095 is the padding sentinel (sorts last), so real
# owners must be < 4095 and cell ids < 2^25; `shard_kernel_for` routes
# batches exceeding either bound to `_shard_kernel_wide` on HOST data.
_OWNER_BITS, _CELL_BITS = 12, 25
_PAD_OWNER = (1 << _OWNER_BITS) - 1


def pack_owner_cell_key(owner_ix, cell_id, idx, lo_bits: int = 2, lo=None):
    """ONE copy of the packed-owner i64 sort-key layout:
    owner(12) | cell(25) | idx(24) | lo(lo_bits). Shared by the LWW
    shard kernel (lo_bits=2 stored-winner flag bits) and the typed
    CRDT fold kernels (`ops.crdt_merge.counter_shard_sums_core`,
    lo_bits=0 — the sum monoid needs no flags), so the (owner, cell)
    grouping contract can never drift between them. Padding rows
    (cell_id == _PAD_CELL) take the _PAD_OWNER sentinel and sort last.
    Traceable; raises at trace time outside enable_x64(True)."""
    own = jnp.where(
        cell_id == _PAD_CELL, jnp.int64(_PAD_OWNER), owner_ix.astype(jnp.int64)
    )
    key = (
        (own << jnp.int64(_CELL_BITS + 24 + lo_bits))
        | ((cell_id.astype(jnp.int64) & jnp.int64((1 << _CELL_BITS) - 1))
           << jnp.int64(24 + lo_bits))
        | (idx.astype(jnp.int64) << jnp.int64(lo_bits))
    )
    if lo is not None:
        key = key | lo
    if key.dtype != jnp.dtype("int64"):  # x64 disabled: would mis-plan
        raise TypeError(
            "pack_owner_cell_key must be traced under enable_x64(True): "
            f"packed key degraded to {key.dtype}"
        )
    return key


def _shard_kernel(cell_id, k1, k2, ex_k1, ex_k2, owner_ix):
    """Per-shard reconcile: LWW plan + (owner, minute) XOR deltas +
    shard digest. All inputs are this shard's local (S,) slices.

    Packed-owner variant (the production and bench default): the sort
    key is owner<<51 | cell<<26 | idx<<2 | eq<<1 | gt (stored-winner
    flag bits as in `plan_merge_sorted_flags`), segments group by
    (owner, cell) — identical segmentation to cell-grouping because
    cell ids are unique per owner (global interning; every caller's
    layout guarantees it). The sorted HLC keys give back the timestamp
    columns, hashing and the (owner, minute) segmented XOR consume the
    sorted rows directly, and the two bool masks return to the host
    with `i_s` for a vectorized numpy unpermute — no device restoring
    sort. Must be traced under enable_x64(True)."""
    n = cell_id.shape[0]
    if n > 1 << 24:  # idx no longer fits its 24 key bits
        return _shard_kernel_wide(cell_id, k1, k2, ex_k1, ex_k2, owner_ix)
    idx = jnp.arange(n, dtype=jnp.int32)
    a, b = winner_flags(k1, k2, ex_k1, ex_k2)
    key = pack_owner_cell_key(
        owner_ix, cell_id, idx, lo_bits=2,
        lo=(b.astype(jnp.int64) << jnp.int64(1)) | a.astype(jnp.int64),
    )
    key_s, s1, s2 = jax.lax.sort((key, k1, k2), num_keys=1, is_stable=False)
    owner_s = (key_s >> jnp.int64(_CELL_BITS + 26)).astype(jnp.int32)
    i_s = ((key_s >> jnp.int64(2)) & jnp.int64((1 << 24) - 1)).astype(jnp.int32)
    a_s = (key_s & jnp.int64(1)) != 0
    b_s = (key_s & jnp.int64(2)) != 0
    real = owner_s != jnp.int32(_PAD_OWNER)
    # Segment key = key bits above idx/flags = (owner, cell); the mask
    # algebra is the ONE shared copy in ops.merge.
    xor_s, upsert_s = masks_from_sorted_flags(
        key_s >> jnp.int64(26), s1, s2, a_s, b_s, real
    )

    millis_s, counter_s = unpack_ts_keys(s1)
    hashes = jnp.where(
        xor_s, timestamp_hashes(millis_s, counter_s, s2), jnp.uint32(0)
    )
    owner_sorted, minute_sorted, seg_end_m, seg_xor, valid_sorted = owner_minute_segments(
        owner_s, millis_s, hashes, xor_s
    )
    digest = xor_allreduce(jax.lax.reduce(hashes, jnp.uint32(0), jnp.bitwise_xor, (0,)))
    return (
        xor_s, upsert_s, i_s,
        owner_sorted, minute_sorted, seg_end_m, seg_xor, valid_sorted, digest,
    )


def _shard_kernel_wide(cell_id, k1, k2, ex_k1, ex_k2, owner_ix):
    """The wide-id fallback (cell ≥ 2^25 or owner ≥ 4095): owner rides
    as an i32 sort payload and segmentation is by cell alone —
    bit-identical masks/deltas/digest whenever the packed variant's
    preconditions hold (parity-pinned), and the only variant that can
    serve batches beyond them."""
    xor_s, upsert_s, i_s, s1, s2, (owner_s,) = plan_merge_sorted_flags(
        cell_id, k1, k2, ex_k1, ex_k2, extras=(owner_ix.astype(jnp.int32),)
    )
    millis_s, counter_s = unpack_ts_keys(s1)
    hashes = jnp.where(
        xor_s, timestamp_hashes(millis_s, counter_s, s2), jnp.uint32(0)
    )
    owner_sorted, minute_sorted, seg_end, seg_xor, valid_sorted = owner_minute_segments(
        owner_s, millis_s, hashes, xor_s
    )
    digest = xor_allreduce(jax.lax.reduce(hashes, jnp.uint32(0), jnp.bitwise_xor, (0,)))
    return (
        xor_s, upsert_s, i_s,
        owner_sorted, minute_sorted, seg_end, seg_xor, valid_sorted, digest,
    )


def _shard_kernel_scatter(cell_id, k1, k2, ex_k1, ex_k2, owner_ix, table_size):
    """Sort-free per-shard reconcile (ops/scatter_merge.py): the LWW
    masks come from the dense scatter-argmax plan in ORIGINAL shard
    order (i_s is the identity), and the (owner, minute) segmentation
    consumes the original-order columns — its own tile-local grouping
    sort is order-free (decoders XOR-merge per key), so host-level
    plans, deltas, and the digest are bit-identical to the sort
    kernels wherever the router admits a batch. Segmentation-by-cell
    assumption matches `_shard_kernel_wide`'s: cell ids are globally
    interned (unique per owner). Same 9-output contract as
    `_shard_kernel`; must be traced under enable_x64(True)."""
    from evolu_tpu.ops.scatter_merge import scatter_plan_masks

    xor_m, upsert_m = scatter_plan_masks(cell_id, k1, k2, ex_k1, ex_k2, table_size)
    i_s = jnp.arange(cell_id.shape[0], dtype=jnp.int32)
    millis, counter = unpack_ts_keys(k1)
    hashes = jnp.where(xor_m, timestamp_hashes(millis, counter, k2), jnp.uint32(0))
    owner_sorted, minute_sorted, seg_end, seg_xor, valid_sorted = owner_minute_segments(
        owner_ix, millis, hashes, xor_m
    )
    digest = xor_allreduce(jax.lax.reduce(hashes, jnp.uint32(0), jnp.bitwise_xor, (0,)))
    return (
        xor_m, upsert_m, i_s,
        owner_sorted, minute_sorted, seg_end, seg_xor, valid_sorted, digest,
    )


@functools.lru_cache(maxsize=None)
def scatter_shard_kernel(table_size: int):
    """The scatter shard kernel bound to one static table bucket.
    Cached so repeated batches in the same bucket hand `_compiled_kernel`
    the SAME callable (its lru_cache keys on identity — a fresh partial
    per batch would recompile the mesh kernel every call)."""
    kernel = functools.partial(_shard_kernel_scatter, table_size=table_size)
    kernel.__name__ = f"_shard_kernel_scatter_{table_size}"
    return kernel


def shard_kernel_for(cols: Dict[str, np.ndarray]):
    """Static host-side routing between the scatter plan (when
    configured and admissible — ops/scatter_merge.py), the packed-owner
    sort kernel, and the wide fallback: the packed key needs every real
    cell id < 2^25 and every owner index < 4095 (the padding sentinel);
    the scatter plan needs cell ids < 2^25 and a duplicate-free batch.
    `cols` are the HOST numpy columns, so the choice is made before
    tracing — no device cond, separately compiled kernels."""
    from evolu_tpu.ops.scatter_merge import table_size_for, use_scatter_plan

    real = cols["cell_id"] != int(_PAD_CELL)
    cell_max = int(cols["cell_id"].max(initial=0, where=real))
    owner_max = int(cols["owner_ix"].max(initial=0))
    if "k1" in cols and use_scatter_plan(
        cols["cell_id"], cols["k1"], cols["k2"], cell_max=cell_max
    ):
        metrics.inc("evolu_reconcile_kernel_total", variant="scatter")
        return scatter_shard_kernel(table_size_for(cell_max))
    if cell_max < (1 << _CELL_BITS) and owner_max < _PAD_OWNER:
        metrics.inc("evolu_reconcile_kernel_total", variant="packed")
        return _shard_kernel
    metrics.inc("evolu_reconcile_kernel_total", variant="wide")
    return _shard_kernel_wide


@functools.lru_cache(maxsize=None)
def _compiled_kernel(mesh: Mesh, kernel=None):
    spec = P(OWNERS_AXIS)
    mapped = shard_map(
        kernel or _shard_kernel,
        mesh=mesh,
        in_specs=(spec,) * 6,
        out_specs=(spec,) * 8 + (P(),),
        check_vma=False,
    )
    return jax.jit(mapped)


@with_x64
def reconcile_columns_sharded(mesh: Mesh, cols: Dict[str, np.ndarray]):
    """Run the sharded kernel on flat global columns (length D*S, owner
    blocks laid out shard-contiguously). Returns device arrays:
    (xor_sorted, upsert_sorted, i_s, owner_sorted, minute_sorted,
    seg_end, seg_xor, seg_valid, digest) — masks are in per-shard
    cell-sorted order; `unpermute_masks(..., block_size=shard_size)`
    restores batch order on the host. Works on a multi-process
    cluster: every process builds the same global columns, feeds its
    local shards (`put_sharded`), and pulls back only its addressable
    outputs (`to_host` concatenates addressable shards) — the digest
    is replicated by the XOR all-reduce, so every process sees the
    whole-batch digest while owning only its shards' plans."""
    shd = sharding(mesh)
    args = [
        put_sharded(cols[k], shd)
        for k in ("cell_id", "k1", "k2", "ex_k1", "ex_k2", "owner_ix")
    ]
    return _compiled_kernel(mesh, shard_kernel_for(cols))(*args)


def build_owner_columns(
    mesh: Mesh,
    owner_batches: Dict[str, Sequence[CrdtMessage]],
    existing_winners: Dict[str, Dict[Tuple[str, str, str], str]],
    mesh_ctx=None,
):
    """Host-side layout: per-owner columnarization → shard assignment →
    flat padded global columns + bookkeeping to scatter results back.

    Returns (cols, index, host_owners): `host_owners` are owners whose
    batch (or stored winners) contain non-canonical hex case — the
    device's numeric order / canonical-render hash would diverge from
    the reference's raw-string semantics for them, so they are excluded
    from the layout and must be planned on the host. Owners are
    independent, so this quarantine is per owner, not per batch.
    """
    n_shards = mesh.devices.size
    owners = []
    host_owners = []
    per_owner = {}
    cell_base = 0
    for o in owner_batches:
        msgs = owner_batches[o]
        cols = messages_to_columns(msgs, existing_winners.get(o, {}))
        cell_ids, k1, k2, ex_k1, ex_k2, millis, counter, node, canonical = cols
        if not canonical:
            host_owners.append(o)
            continue
        owners.append(o)
        cell_ids = cell_ids + cell_base
        cell_base += len(msgs)  # intern ids are < len(msgs)
        per_owner[o] = (cell_ids, k1, k2, ex_k1, ex_k2, millis, counter, node)
    owner_ix = {o: i for i, o in enumerate(owners)}

    sizes = {o: len(owner_batches[o]) for o in owners}
    if mesh_ctx is not None:
        # PR-12 sharded path: STABLE owner→device placement (an owner
        # lands on the same device every batch — the precondition for
        # device-resident per-owner state such as the mesh-sharded
        # winner cache), occupancy/padding telemetry recorded.
        shards = mesh_ctx.assign_stable(sizes)
    else:
        shards = assign_owners_to_shards(sizes, n_shards)
    # Shard balance telemetry: the assignment's per-shard row loads
    # (host ints already in hand — arXiv:2004.00107's point that
    # anti-entropy behavior is only debuggable with per-round telemetry
    # applies doubly to a load imbalance that serializes the mesh).
    loads = [sum(len(owner_batches[o]) for o in s) for s in shards]
    for load in loads:
        metrics.observe("evolu_reconcile_shard_rows", load,
                        buckets=metrics.COUNT_BUCKETS)
    shard_size = bucket_size(max(max(loads, default=0), 1))
    if mesh_ctx is not None:
        mesh_ctx.record_occupancy(loads, shard_size)
        mesh_ctx.record_xdev_reduce("digest")

    # Timestamp columns are NOT laid out: the kernels recover
    # millis/counter/node from the sorted HLC keys, so transferring
    # them would be dead H2D traffic.
    total = n_shards * shard_size
    out = {
        "cell_id": np.full(total, int(_PAD_CELL), np.int32),
        "k1": np.zeros(total, np.uint64),
        "k2": np.zeros(total, np.uint64),
        "ex_k1": np.zeros(total, np.uint64),
        "ex_k2": np.zeros(total, np.uint64),
        "owner_ix": np.zeros(total, np.int64),
    }
    index: Dict[str, Tuple[np.ndarray, int]] = {}
    for si, shard in enumerate(shards):
        pos = si * shard_size
        for o in shard:
            cell_ids, k1, k2, ex_k1, ex_k2, _millis, _counter, _node = per_owner[o]
            n = len(cell_ids)
            sl = slice(pos, pos + n)
            out["cell_id"][sl] = cell_ids
            out["k1"][sl], out["k2"][sl] = k1, k2
            out["ex_k1"][sl], out["ex_k2"][sl] = ex_k1, ex_k2
            out["owner_ix"][sl] = owner_ix[o]
            index[o] = (np.arange(pos, pos + n), owner_ix[o])
            pos += n
    return out, index, host_owners


def reconcile_owner_batches(
    mesh: Mesh,
    owner_batches: Dict[str, Sequence[CrdtMessage]],
    existing_winners: Dict[str, Dict[Tuple[str, str, str], str]],
    mesh_ctx=None,
):
    """Full multi-owner reconcile: one device dispatch for all owners.

    Returns ({owner: (xor_mask, upserts, minute_deltas)}, digest) with
    the same per-owner contract as the single-owner planner
    (`storage.apply.plan_batch` + the host Merkle delta pass), so the
    caller can apply results to per-owner SQLite stores / trees.
    """
    if not owner_batches:
        return {}, 0
    require_single_process("reconcile_owner_batches")
    n_msgs = sum(len(v) for v in owner_batches.values())
    metrics.observe("evolu_reconcile_batch_rows", n_msgs,
                    buckets=metrics.COUNT_BUCKETS)
    metrics.observe("evolu_reconcile_batch_owners", len(owner_batches),
                    buckets=metrics.COUNT_BUCKETS)
    with span("kernel:reconcile", "reconcile_owner_batches",
              owners=len(owner_batches), n=n_msgs):
        return _reconcile_owner_batches_timed(
            mesh, owner_batches, existing_winners, mesh_ctx
        )


def _reconcile_owner_batches_timed(mesh, owner_batches, existing_winners,
                                   mesh_ctx=None):
    cols, index, host_owners = build_owner_columns(
        mesh, owner_batches, existing_winners, mesh_ctx=mesh_ctx
    )
    results = {}
    digest = 0
    if index:
        # ONE transfer wave for all 9 kernel outputs — per-array pulls
        # pay one tunnel RTT each (see ops.to_host_many).
        xor_s, upsert_s, i_s, owner_sorted, minute_sorted, seg_end, seg_xor, seg_valid, dev_digest = (
            to_host_many(*reconcile_columns_sharded(mesh, cols))
        )
        shard_size = len(cols["cell_id"]) // mesh.devices.size
        xor_mask, upsert_mask = unpermute_masks(xor_s, upsert_s, i_s, block_size=shard_size)
        deltas_by_ix = decode_owner_minute_deltas(
            owner_sorted, minute_sorted, seg_end, seg_xor, seg_valid
        )
        digest = int(dev_digest)
        for owner, (positions, o_ix) in index.items():
            messages = owner_batches[owner]
            o_mask = upsert_mask[positions]
            results[owner] = (
                xor_mask[positions].tolist(),
                select_messages(messages, o_mask),
                deltas_by_ix.get(o_ix, {}),
            )
    metrics.inc("evolu_reconcile_host_owner_fallbacks_total", len(host_owners))
    for owner in host_owners:
        log("kernel:reconcile", "non-canonical hex case: host-planner fallback",
            owner=owner, n=len(owner_batches[owner]))
        plan, owner_digest = _host_owner_plan(
            owner_batches[owner], existing_winners.get(owner, {})
        )
        results[owner] = plan
        digest ^= owner_digest
    return results, digest


def _host_owner_plan(messages, winners):
    """Oracle-exact host plan for one quarantined owner: raw-string LWW
    order + the shared verbatim-case hash fold."""
    from evolu_tpu.core.merkle import minute_deltas_host
    from evolu_tpu.storage.apply import plan_batch

    xor_mask, upserts = plan_batch(messages, winners)
    deltas, digest = minute_deltas_host(
        m.timestamp for flag, m in zip(xor_mask, messages) if flag
    )
    return (xor_mask, upserts, deltas), digest
