"""Client runtime: the DbWorker command engine and the Evolu handle.

Reference: packages/evolu/src/db.worker.ts (single-writer command loop)
and db.ts (main-thread runtime). Here the "worker" is an in-process
thread with an ordered command queue — same single-writer discipline,
no postMessage serialization tax. The sync transport
(`evolu_tpu.sync`) plugs in via a callback, mirroring the
DbWorker ↔ SyncWorker MessageChannel boundary (db.ts:134-136).
"""

from evolu_tpu.runtime.worker import DbWorker
from evolu_tpu.runtime.client import Evolu, create_evolu

__all__ = ["DbWorker", "Evolu", "create_evolu"]
