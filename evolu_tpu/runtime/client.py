"""The Evolu client handle — main-thread runtime analog.

Reference: packages/evolu/src/db.ts. Owns the DbWorker, the reactive
query-rows store (patch application keeps unchanged row identity,
db.ts:96-115), the mutation batch queue (db.ts:302-361), subscription
ref-counting (db.ts:236-266), the error store (error.ts), and owner
lifecycle (db.ts:367-388).

Differences from the browser, by design:
- No microtasks: mutations made inside `with evolu.batching():` flush
  as one `Send` (the reference batches per microtask); a bare
  `mutate()` flushes immediately.
- Sync triggers (`load`/`online`/`focus`, db.ts:390-412) become the
  explicit `sync()` method plus the transport's periodic pull.
"""

from __future__ import annotations

import datetime
import threading
from typing import Callable, Dict, List, Optional, Sequence, Set

from evolu_tpu.api.model import COMMON_COLUMNS, sqlite_value
from evolu_tpu.core.ids import create_id
from evolu_tpu.core.types import NewCrdtMessage, Owner, TableDefinition
from evolu_tpu.runtime import messages as msg
from evolu_tpu.runtime.jsonpatch import apply_patch
from evolu_tpu.runtime.worker import DbWorker
from evolu_tpu.storage.native import open_database
from evolu_tpu.utils.config import Config


def _now_iso() -> str:
    from evolu_tpu.core.timestamp import millis_to_iso
    import time

    return millis_to_iso(int(time.time() * 1000))


class Evolu:
    """One local replica: reactive queries + LWW mutations + sync."""

    def __init__(
        self,
        db_path: str = ":memory:",
        config: Optional[Config] = None,
        mnemonic: Optional[str] = None,
        now_iso: Callable[[], str] = _now_iso,
        backend: str = "auto",
    ):
        self.config = config or Config()
        # "auto" = the C++ SQLite host layer when buildable (SURVEY.md
        # §2.14), else the stdlib backend — identical end state either way.
        self.db = open_database(db_path, backend)
        self._now_iso = now_iso
        self._lock = threading.RLock()
        self._rows_cache: Dict[str, List[dict]] = {}  # queriesRowsCacheRef (db.ts:55)
        self._subscribed: Dict[str, int] = {}  # ref-counted (db.ts:236)
        self._listeners: List[Callable[[], None]] = []
        self._error: Optional[Exception] = None
        self._error_listeners: List[Callable[[Exception], None]] = []
        self._reconnect_listeners: List[Callable[[], None]] = []
        self._disposed = False
        self._on_completes: Dict[str, Callable[[], None]] = {}  # by id (db.ts:70-82)
        # Batching state is thread-local: a batch open on one thread must
        # not capture (or, if aborted, discard) another thread's mutations.
        self._batch = threading.local()
        self._on_reload: Optional[Callable[[], None]] = None
        self._reload_watcher = None  # started by on_reload(cross_process=True)
        self._auto_syncer = None  # started by sync.client.connect
        self._transport = None  # set by attach_transport
        self.worker = DbWorker(
            self.db,
            config=self.config,
            on_output=self._dispatch_output,
            post_sync=self._post_sync,
        )
        self.owner: Owner = self.worker.start(mnemonic)
        self.first_data_loaded = threading.Event()

    # -- schema --

    def update_db_schema(self, schema: Dict[str, Sequence[str]]) -> None:
        """createHooks.ts:26 → updateDbSchema command. `schema` maps table
        name → app columns; `id` and the common columns (createdAt,
        createdBy, updatedAt, isDeleted) are appended here, mirroring
        dbSchemaToTableDefinitions (db.ts:210-221)."""
        tds = tuple(
            TableDefinition.of(
                name,
                tuple(c for c in cols if c != "id")
                + tuple(c for c in COMMON_COLUMNS if c not in cols),
            )
            for name, cols in schema.items()
        )
        self.worker.post(msg.UpdateDbSchema(tds))

    # -- reactive queries --

    @staticmethod
    def _normalize_query(query) -> str:
        """Accept a QueryBuilder, raw SQL, or an already-serialized
        SqlQueryString; always key caches/subscriptions by the
        serialized form (types.ts:115-124)."""
        serialize = getattr(query, "serialize", None)
        if callable(serialize):
            return serialize()
        s = str(query)
        if s.lstrip().startswith("{"):
            return s
        return msg.serialize_query(s)

    def subscribe_query(self, query, listener: Optional[Callable[[], None]] = None):
        """Subscribe a query; returns unsubscribe (db.ts:241-266)."""
        query = self._normalize_query(query)
        with self._lock:
            fresh = query not in self._subscribed
            self._subscribed[query] = self._subscribed.get(query, 0) + 1
            if listener is not None:
                self._listeners.append(listener)
        if fresh:
            self.worker.post(msg.Query((query,)))

        def unsubscribe() -> None:
            with self._lock:
                n = self._subscribed.get(query, 0) - 1
                evict = n <= 0
                if evict:
                    self._subscribed.pop(query, None)
                    self._rows_cache.pop(query, None)
                else:
                    self._subscribed[query] = n
                if listener is not None and listener in self._listeners:
                    self._listeners.remove(listener)
                if evict:
                    # Posted under the lock: a concurrent re-subscribe
                    # cannot enqueue its initial Query ahead of this
                    # eviction (which would then wipe a live cache entry).
                    self.worker.post(msg.EvictQueries((query,)))

        return unsubscribe

    def listen(self, listener: Callable[[], None]):
        """Row-store change notification (db.ts:57-68)."""
        with self._lock:
            self._listeners.append(listener)

        def unlisten() -> None:
            with self._lock:
                if listener in self._listeners:
                    self._listeners.remove(listener)

        return unlisten

    def get_query_rows(self, query) -> List[dict]:
        """Current rows for a subscribed query (db.ts:231-234). Row objects
        are identity-stable across unrelated updates."""
        query = self._normalize_query(query)
        with self._lock:
            return self._rows_cache.get(query, [])

    def query_once(self, query) -> List[dict]:
        """One-shot read-through (no subscription): runs on the worker
        thread to respect the single-writer discipline."""
        unsubscribe = self.subscribe_query(query)
        self.worker.flush()
        try:
            return self.get_query_rows(query)
        finally:
            unsubscribe()

    # -- mutations --

    def _batch_state(self):
        b = self._batch
        if not hasattr(b, "depth"):
            b.depth, b.pending, b.complete_ids = 0, [], []
        return b

    def batching(self):
        """Group several mutate() calls into one Send (db.ts:337-361)."""
        client = self

        class _Batch:
            def __enter__(self):
                client._batch_state().depth += 1
                return client

            def __exit__(self, exc_type, exc, tb):
                b = client._batch_state()
                b.depth -= 1
                if b.depth == 0:
                    if exc_type is None:
                        client._flush_mutations()
                    else:
                        # Aborted batch: drop its mutations outright —
                        # leaving them pending would splice them into the
                        # next unrelated Send.
                        b.pending.clear()
                        with client._lock:
                            for i in b.complete_ids:
                                client._on_completes.pop(i, None)
                        b.complete_ids.clear()
                return False

        return _Batch()

    def mutate(
        self,
        table: str,
        values: Dict[str, object],
        on_complete: Optional[Callable[[], None]] = None,
    ) -> str:
        """Insert or update one row (db.ts:309-365).

        No "id" in `values` ⇒ insert with a fresh nanoid id plus
        createdAt/createdBy; with an id ⇒ update plus updatedAt
        (db.ts:286-290). Values expand to one CrdtMessage per column;
        bools/datetimes cast to their SQLite encodings (db.ts:281-283).
        Returns the row id.
        """
        values = dict(values)
        row_id = values.pop("id", None)
        is_insert = row_id is None
        if is_insert:
            row_id = create_id()
        now = self._now_iso()
        if is_insert:
            values.setdefault("createdAt", now)
            values.setdefault("createdBy", self.owner.id)
        else:
            values.setdefault("updatedAt", now)
        new_messages = [
            NewCrdtMessage(table, row_id, column, sqlite_value(v))
            for column, v in values.items()
        ]
        b = self._batch_state()
        b.pending.extend(new_messages)
        if on_complete is not None:
            complete_id = create_id()
            with self._lock:
                self._on_completes[complete_id] = on_complete
            b.complete_ids.append(complete_id)
        if b.depth == 0:
            self._flush_mutations()
        return row_id

    # -- typed-column mutations (CRDT types beyond LWW, ISSUE 7) --

    def _mutate_raw(self, messages: List[NewCrdtMessage]) -> None:
        """Queue raw op messages through the same batch machinery as
        `mutate` (no common-column side writes — a typed op is ONE
        message on ONE cell)."""
        b = self._batch_state()
        b.pending.extend(messages)
        if b.depth == 0:
            self._flush_mutations()

    def increment(self, table: str, row_id: str, column: str, delta: int) -> None:
        """PN-counter op: add `delta` (may be negative) to a
        `"<column>:counter"` cell. The materialized cell value is the
        sum over all distinct ops across every replica."""
        from evolu_tpu.core.crdt_types import counter_delta

        self._mutate_raw([NewCrdtMessage(table, row_id, column, counter_delta(delta))])

    def set_add(self, table: str, row_id: str, column: str, elem) -> None:
        """AW-set add op for a `"<column>:awset"` cell. The op's own
        timestamp becomes its unique add tag."""
        from evolu_tpu.core.crdt_types import set_add_value

        self._mutate_raw([NewCrdtMessage(table, row_id, column, set_add_value(elem))])

    def set_remove(self, table: str, row_id: str, column: str, elem,
                   observed: Optional[Sequence[str]] = None) -> None:
        """AW-set observed-remove op: kills exactly the add tags this
        replica has APPLIED for (cell, elem). The worker queue is
        drained first so a just-queued same-replica `set_add` is
        covered — without the drain, add-then-remove on one replica
        would read an empty observation and silently remove nothing
        (the add's tag, unobserved, survives by add-wins). A concurrent
        add from ANOTHER replica this one has not synced still survives
        (add wins). Adds queued in a still-open `batching()` block are
        not yet stamped (no tag exists to observe) — close the batch
        first. Pass `observed` explicitly to skip the read."""
        from evolu_tpu.core.crdt_types import observed_tags, set_remove_value

        if observed is None:
            self.worker.flush()
            observed = observed_tags(self.db, table, row_id, column, elem)
        self._mutate_raw([
            NewCrdtMessage(table, row_id, column, set_remove_value(elem, observed))
        ])

    # -- list (RGA sequence) mutations, ISSUE 14 --

    def list_insert(self, table: str, row_id: str, column: str, value,
                    after: Optional[str] = None) -> None:
        """RGA insert op for a `"<column>:list"` cell: place `value`
        AFTER the element tagged `after` (None = head). The op's own
        timestamp becomes the new element's tag — read it back via
        `list_elements` after a flush. A concurrent insert at the same
        anchor orders deterministically on every replica (later
        timestamp lands closer to the anchor)."""
        from evolu_tpu.core.crdt_list import list_insert_value

        self._mutate_raw([
            NewCrdtMessage(table, row_id, column, list_insert_value(value, after))
        ])

    def list_append(self, table: str, row_id: str, column: str, value) -> None:
        """Insert `value` after the cell's LAST alive element. The
        worker queue is drained first so a just-queued same-replica
        insert is observed (the `set_remove` drain lesson — without it,
        two unflushed appends would both anchor on the old tail and
        end up reversed). Appends queued in a still-open `batching()`
        block are not yet stamped — close the batch first."""
        from evolu_tpu.core.crdt_list import list_state

        self.worker.flush()
        elems = list_state(self.db, table, row_id, column)
        self.list_insert(table, row_id, column, value,
                         after=elems[-1][0] if elems else None)

    def list_delete(self, table: str, row_id: str, column: str, tag: str) -> None:
        """Tombstone the element tagged `tag` (from `list_elements`).
        The element keeps its position as an anchor for concurrent
        inserts; a delete racing an unseen insert at the same tag still
        wins on every replica (kill tombstones, like `set_remove`)."""
        from evolu_tpu.core.crdt_list import list_delete_value

        self._mutate_raw([NewCrdtMessage(table, row_id, column,
                                         list_delete_value(tag))])

    def list_elements(self, table: str, row_id: str, column: str):
        """Alive (tag, value) pairs in document order, after draining
        the worker (drain-before-observe) — the read that anchors
        `after=` inserts and tag-addressed deletes."""
        import json as _json

        from evolu_tpu.core.crdt_list import list_state

        self.worker.flush()
        return [(tag, _json.loads(v))
                for tag, v in list_state(self.db, table, row_id, column)]

    # -- tensor (declared-monoid numeric) mutations, ISSUE 20 --

    def tensor_delta(self, table: str, row_id: str, column: str, array,
                     count: int = 1) -> None:
        """Tensor delta op for a `"<column>:tensor:<monoid>:…"` cell:
        contributes `array` (validated against the DECLARED shape and
        dtype) under the column's merge monoid — element-wise sum,
        count-weighted mean (`count` is the mean monoid's weight; other
        monoids reject it), or element-wise max. Commutative: no
        observation needed, so no drain. The worker is flushed only to
        read the declared config (schema reads ride the same
        connection discipline as mutations)."""
        from evolu_tpu.core.crdt_tensor import tensor_config, tensor_delta_value

        self.worker.flush()
        cfg = tensor_config(self.db, table, column)
        self._mutate_raw([
            NewCrdtMessage(table, row_id, column,
                           tensor_delta_value(cfg, array, count))
        ])

    def tensor_set(self, table: str, row_id: str, column: str, array,
                   count: int = 1) -> None:
        """Tensor overwrite (the semidirect LWW fallback): the
        latest-timestamped set resets the fold base; deltas timestamped
        after it reapply on top. Unlike `set_remove`, an overwrite is
        UNCONDITIONAL — it observes nothing, so there is no
        drain-before-observe hazard to manage (the set_remove lesson
        applies to reads, which `tensor_value` performs)."""
        from evolu_tpu.core.crdt_tensor import tensor_config, tensor_set_value

        self.worker.flush()
        cfg = tensor_config(self.db, table, column)
        self._mutate_raw([
            NewCrdtMessage(table, row_id, column,
                           tensor_set_value(cfg, array, count))
        ])

    def tensor_value(self, table: str, row_id: str, column: str):
        """The materialized cell as a shaped numpy array (declared
        dtype), or None if the app row does not exist — after draining
        the worker (drain-before-observe), so a just-queued delta or
        set from this replica is reflected."""
        from evolu_tpu.core.crdt_tensor import tensor_state

        self.worker.flush()
        return tensor_state(self.db, table, row_id, column)

    def create(self, table: str, values: Dict[str, object], on_complete=None) -> str:
        values = dict(values)
        values.pop("id", None)
        return self.mutate(table, values, on_complete)

    def update(self, table: str, row_id: str, values: Dict[str, object], on_complete=None) -> str:
        values = dict(values)
        values["id"] = row_id
        return self.mutate(table, values, on_complete)

    def _flush_mutations(self) -> None:
        b = self._batch_state()
        if not b.pending:
            return
        batch = tuple(b.pending)
        ids = tuple(b.complete_ids)
        b.pending.clear()
        b.complete_ids.clear()
        with self._lock:
            queries = tuple(self._subscribed)
        self.worker.post(msg.Send(batch, ids, queries))

    # -- sync --

    def attach_transport(self, transport) -> None:
        """Wire a sync transport (the SyncWorker analog). The transport
        must expose `request_sync(SyncRequestInput)` and feed responses
        back via `receive()`."""
        self._transport = transport

    def sync(self, refresh_queries: bool = True) -> None:
        """Trigger a pull round (the load/online/focus trigger analog,
        db.ts:390-412)."""
        queries = tuple(self._subscribed) if refresh_queries else ()
        self.worker.post(msg.Sync(queries))

    def receive(
        self, messages: tuple, merkle_tree: str, previous_diff: Optional[int] = None
    ) -> None:
        """Feed a sync response into the engine (db.worker.ts:129-135).
        `messages` is either a CrdtMessage sequence or a PackedReceive
        columnar batch (the fused receive leg) — the worker handles
        both with identical end state."""
        from evolu_tpu.core.packed import PackedReceive

        if not isinstance(messages, PackedReceive):
            messages = tuple(messages)
        self.worker.post(msg.Receive(messages, merkle_tree, previous_diff))

    def _post_sync(self, request: msg.SyncRequestInput) -> None:
        if self._transport is not None:
            self._transport.request_sync(request)

    # -- owner lifecycle (db.ts:367-388) --

    def get_owner(self) -> Owner:
        return self.owner

    def reset_owner(self) -> None:
        self.worker.post(msg.ResetOwner())

    def restore_owner(self, mnemonic: str) -> None:
        from evolu_tpu.core.mnemonic import validate_mnemonic
        from evolu_tpu.core.types import UnknownError

        if not validate_mnemonic(mnemonic):
            raise UnknownError(f"invalid mnemonic")
        self.worker.post(msg.RestoreOwner(mnemonic))

    def on_reload(self, callback: Callable[[], None], cross_process: bool = True) -> None:
        """reloadAllTabs analog (reloadAllTabs.ts:6-14): fires after this
        replica's resetOwner/restoreOwner, and — when `cross_process` and
        the DB is file-backed — when another process sharing the same DB
        file signals one (the localStorage storage-event analog)."""
        self._on_reload = callback
        if cross_process and self._reload_watcher is None and self.db.path != ":memory:":
            from evolu_tpu.utils.reload import ReloadWatcher

            self._reload_watcher = ReloadWatcher(self.db.path, lambda: self._fire_reload())

    def _fire_reload(self) -> None:
        """Another process reset/restored the shared DB file: re-run
        every subscribed query (the worker recomputes against the new
        file state and posts patches, which notify listeners — same
        flow as OnReceive), then the embedder callback. full=True: the
        foreign write never entered this worker's change log, so the
        r9 invalidation gate must not be consulted."""
        with self._lock:
            queries = tuple(self._subscribed)
        if queries:
            self.worker.post(msg.Query(queries, full=True))
        if self._on_reload is not None:
            self._on_reload()

    # -- reconnect (the `online` event analog, db.ts:390-412) --

    def subscribe_reconnect(self, listener: Callable[[], None]):
        """Fires when the sync transport transitions offline → online
        (first successful probe or round after swallowed fetch errors).
        The transport has already scheduled the immediate pull round;
        this is the app-facing hook."""
        with self._lock:
            self._reconnect_listeners.append(listener)

        def unsubscribe() -> None:
            with self._lock:
                if listener in self._reconnect_listeners:
                    self._reconnect_listeners.remove(listener)

        return unsubscribe

    def _fire_reconnect(self) -> None:
        with self._lock:
            listeners = list(self._reconnect_listeners)
        for fn in listeners:
            try:
                fn()
            except Exception:  # noqa: BLE001,S110 - a raising listener
                # must not block the reconnect sync
                pass

    # -- errors (error.ts:8-22) --

    def subscribe_error(self, listener: Callable[[Exception], None]):
        with self._lock:
            self._error_listeners.append(listener)

        def unsubscribe() -> None:
            with self._lock:
                if listener in self._error_listeners:
                    self._error_listeners.remove(listener)

        return unsubscribe

    def get_error(self) -> Optional[Exception]:
        return self._error

    # -- worker output dispatch (db.ts:158-186) --

    def _dispatch_output(self, output: object) -> None:
        if isinstance(output, msg.OnError):
            with self._lock:
                self._error = output.error
                listeners = list(self._error_listeners)
            for fn in listeners:
                fn(output.error)
        elif isinstance(output, msg.OnQuery):
            self._on_query(output)
        elif isinstance(output, msg.OnReceive):
            # Re-run every subscribed query (db.ts:174-176).
            with self._lock:
                queries = tuple(self._subscribed)
            if queries:
                self.worker.post(msg.Query(queries))
        elif isinstance(output, msg.ReloadAllTabs):
            with self._lock:
                self._rows_cache.clear()
                self.owner = self.worker.owner
            # Signal other processes sharing this DB file, then fire the
            # local callback (reloadAllTabs.ts does both: localStorage
            # ping + own location.assign). Our own watcher must skip the
            # nonce — the callback already fires here.
            from evolu_tpu.utils.reload import notify_reload

            nonce = notify_reload(self.db.path)
            if self._reload_watcher is not None:
                self._reload_watcher.ignore(nonce)
            if self._on_reload is not None:
                self._on_reload()
        elif isinstance(output, msg.OnInit):
            self.owner = output.owner

    def _on_query(self, output: msg.OnQuery) -> None:
        with self._lock:
            for query, ops in output.queries_patches:
                self._rows_cache[query] = apply_patch(self._rows_cache.get(query, []), ops)
            listeners = list(self._listeners)
            completes = [
                self._on_completes.pop(i)
                for i in output.on_complete_ids
                if i in self._on_completes
            ]
        self.first_data_loaded.set()
        for fn in listeners:
            fn()
        for fn in completes:
            fn()

    def dispose(self) -> None:
        # Transport stop() bounds its prober join, so a straggler probe
        # can fire on_reconnect after dispose; the connect() wrapper
        # gates on this flag, and clearing the listeners makes the
        # residual instruction-level window benign (a post to the
        # stopped worker's dead queue is a no-op).
        self._disposed = True
        with self._lock:
            self._reconnect_listeners.clear()
        if self._auto_syncer is not None:
            self._auto_syncer.stop()
        self.worker.stop()
        if self._reload_watcher is not None:
            self._reload_watcher.stop()
        if self._transport is not None and hasattr(self._transport, "stop"):
            self._transport.stop()
        self.db.close()


def create_evolu(
    schema: Dict[str, Sequence[str]],
    config: Optional[Config] = None,
    db_path: str = ":memory:",
    mnemonic: Optional[str] = None,
) -> Evolu:
    """The `createHooks` analog (createHooks.ts:20-26): build a client
    and register the app schema."""
    evolu = Evolu(db_path=db_path, config=config, mnemonic=mnemonic)
    evolu.update_db_schema(schema)
    return evolu
