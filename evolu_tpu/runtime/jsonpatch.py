"""Row-array JSON patches for reactive queries.

The reference diffs each subscribed query's fresh rows against a cache
with rfc6902 `createPatch` (query.ts:43-57) and applies patches on the
main thread with `immutableJSONPatch` (db.ts:96-115) so unchanged row
objects keep their identity (React referential equality). This module
is the Python equivalent: `create_patch` emits row-granular RFC-6902
ops, `apply_patch` builds the next rows list reusing unchanged row
objects from the previous one.
"""

from __future__ import annotations

from typing import Any, Dict, List, Sequence


def create_patch(prev: Sequence[Dict[str, Any]], next_: Sequence[Dict[str, Any]]) -> List[dict]:
    """RFC-6902 ops transforming `prev` into `next_` (row granularity).

    Empty list ⇔ no change — the worker posts only non-empty patches
    (query.ts:59-66).
    """
    ops: List[dict] = []
    common = min(len(prev), len(next_))
    for i in range(common):
        # Identity first: the row-granular unpack reuses unchanged row
        # dicts, so most rows shortcut without a key-by-key compare.
        if prev[i] is not next_[i] and prev[i] != next_[i]:
            ops.append({"op": "replace", "path": f"/{i}", "value": next_[i]})
    # Removals are emitted back-to-front so paths stay valid while applying.
    for i in range(len(prev) - 1, common - 1, -1):
        ops.append({"op": "remove", "path": f"/{i}"})
    for i in range(common, len(next_)):
        ops.append({"op": "add", "path": f"/{i}", "value": next_[i]})
    return ops


def apply_patch(prev: Sequence[Dict[str, Any]], ops: Sequence[dict]) -> List[Dict[str, Any]]:
    """Apply `create_patch`-shaped ops, reusing unchanged row objects.

    Like immutableJSONPatch (db.ts:103-113): returns a new list; rows
    not named by any op are the same objects as in `prev`.
    """
    rows: List[Dict[str, Any]] = list(prev)
    for op in ops:
        if op["path"] == "":
            # Root replace: the worker had no cached baseline for this
            # query (first run, or its cache entry was LRU-evicted), so
            # it emits the whole result — correct against ANY client
            # state, unlike index ops diffed from an empty baseline.
            if op["op"] != "replace":  # pragma: no cover - never emitted
                raise ValueError(f"unsupported root op: {op['op']}")
            rows = list(op["value"])
            continue
        idx = int(op["path"].lstrip("/"))
        kind = op["op"]
        if kind == "replace":
            rows[idx] = op["value"]
        elif kind == "remove":
            del rows[idx]
        elif kind == "add":
            rows.insert(idx, op["value"])
        else:  # pragma: no cover - create_patch never emits others
            raise ValueError(f"unsupported op: {kind}")
    return rows
