"""The worker command protocol — DbWorkerInput / DbWorkerOutput.

Reference: packages/evolu/src/types.ts:403-459. The tagged unions
become dataclasses; this protocol is the framework's public runtime
API boundary (SURVEY.md §7 "Boundary preserved") — anything that can
produce these commands can drive the engine, whether it's the Python
client handle, the relay server's reconcile engine, or a test.

Queries travel as `SqlQueryString`: the JSON serialization of
`{"sql": ..., "parameters": [...]}` (types.ts:109-124) so a query is a
hashable cache key.
"""

from __future__ import annotations

import json
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

from evolu_tpu.core.types import CrdtMessage, NewCrdtMessage, Owner


def serialize_query(sql: str, parameters: Sequence = ()) -> str:
    """SqlQueryString (types.ts:115-124)."""
    return json.dumps({"sql": sql, "parameters": list(parameters)}, separators=(",", ":"))


def deserialize_query(query: str) -> Tuple[str, list]:
    q = json.loads(query)
    return q["sql"], q.get("parameters", [])


# --- inputs (types.ts:403-443) ---


@dataclass(frozen=True)
class Init:
    """Handshake; carries config in the reference (types.ts:405-409)."""

    config: object = None


@dataclass(frozen=True)
class UpdateDbSchema:
    table_definitions: tuple  # of TableDefinition


@dataclass(frozen=True)
class Send:
    messages: tuple  # of NewCrdtMessage
    on_complete_ids: tuple = ()
    queries: tuple = ()  # SqlQueryString


@dataclass(frozen=True)
class Query:
    queries: tuple  # SqlQueryString
    # full=True bypasses changed-set gating (ISSUE 9) and re-executes
    # unconditionally — for refreshes whose trigger the worker cannot
    # see in its change log (another process wrote the shared DB file,
    # e.g. the reload watcher). Defaults keep the wire shape.
    full: bool = False


@dataclass(frozen=True)
class EvictQueries:
    """Drop unsubscribed queries from the worker's diff cache (no
    reference analog — the reference's worker cache lives for the
    worker's lifetime; eviction keeps long-lived clients bounded)."""

    queries: tuple  # SqlQueryString


@dataclass(frozen=True)
class Receive:
    messages: tuple  # of CrdtMessage
    merkle_tree: str  # serialized server tree
    previous_diff: Optional[int] = None  # Millis of the previous round's diff


@dataclass(frozen=True)
class Sync:
    queries: tuple = ()  # refresh these before syncing (focus/reshow)


@dataclass(frozen=True)
class ResetOwner:
    pass


@dataclass(frozen=True)
class RestoreOwner:
    mnemonic: str


@dataclass(frozen=True)
class WidenSyncScope:
    """Escalate the partial-replication scope (ISSUE 18,
    sync/scope.py): lower the watermark and/or add tables to the
    filter; `full=True` drops scoping entirely. The worker
    re-materializes every newly-in-scope table from the local
    `__message` log in LWW order and clears its deferred frontier;
    the wider slice's MISSING history then arrives via ordinary
    anti-entropy (the relay's scoped subtree widened with the same
    clause). Narrowing raises — see SyncScope.widen()."""

    watermark_millis: "int | None" = None
    tables: tuple = ()
    full: bool = False


# --- outputs (types.ts:445-459) ---


@dataclass(frozen=True)
class OnError:
    error: Exception


@dataclass(frozen=True)
class OnInit:
    owner: Owner


@dataclass(frozen=True)
class OnQuery:
    queries_patches: tuple  # of (SqlQueryString, ops-list)
    on_complete_ids: tuple = ()


@dataclass(frozen=True)
class OnReceive:
    pass


@dataclass(frozen=True)
class ReloadAllTabs:
    pass


# --- DbWorker → SyncWorker (types.ts:461-473) ---


@dataclass(frozen=True)
class SyncRequestInput:
    """One sync round's input to the sync transport.

    `messages` empty = pull-only round (sync.ts:49-57); non-empty = push
    after a local send (send.ts:63-80).
    """

    messages: tuple  # of CrdtMessage
    clock_timestamp: str
    merkle_tree: str
    owner: Owner
    previous_diff: Optional[int] = None
    # Distributed-trace context of the mutation that produced this
    # round (obs.trace.SpanContext), or None for pull-only rounds /
    # untraced embedders. Carried IN-PROCESS only — on the wire the
    # context rides the HTTP traceparent header, never the body.
    # compare=False: two semantically identical rounds (twin-worker
    # byte-identity oracles) carry different trace ids by design.
    trace: Optional[object] = field(default=None, compare=False)
