"""Sync mutual exclusion — the Web Locks `"evolu_sync"` analog.

Reference: packages/evolu/src/syncLock.ts. In the browser, one lock
per origin makes sync mutually exclusive across tabs; here the analog
is a per-database lock shared by every client in the process plus an
optional OS-level file lock (fcntl) for cross-process exclusion when
the database lives on disk.

`is_pending_or_held` mirrors `syncIsPendingOrHeld` (syncLock.ts:21-29):
the DbWorker uses it to skip redundant sync rounds (receive.ts:186-193,
sync.ts:33-40).
"""

from __future__ import annotations

import os
import threading
from contextlib import contextmanager
from typing import Dict, Optional

try:
    import fcntl
except ImportError:  # pragma: no cover - non-POSIX
    fcntl = None

_registry_guard = threading.Lock()
_registry: Dict[str, "SyncLock"] = {}


class SyncLock:
    """One sync at a time per database, with pending-detection."""

    def __init__(self, db_path: str = ":memory:"):
        self._lock = threading.Lock()
        self._pending = 0
        self._guard = threading.Lock()
        self._file: Optional[int] = None
        if fcntl is not None and db_path not in ("", ":memory:"):
            try:
                self._file = os.open(db_path + ".synclock", os.O_CREAT | os.O_RDWR, 0o644)
            except OSError:
                self._file = None

    @contextmanager
    def hold(self):
        """Run a sync round exclusively (syncLock.ts:8-12)."""
        with self._guard:
            self._pending += 1
        self._lock.acquire()
        if self._file is not None:
            fcntl.flock(self._file, fcntl.LOCK_EX)
        try:
            yield
        finally:
            if self._file is not None:
                fcntl.flock(self._file, fcntl.LOCK_UN)
            self._lock.release()
            with self._guard:
                self._pending -= 1

    def is_pending_or_held(self) -> bool:
        """syncLock.ts:21-29 — True if a sync is running or queued."""
        with self._guard:
            if self._pending > 0:
                return True
        if self._lock.locked():
            return True
        if self._file is not None:
            try:
                fcntl.flock(self._file, fcntl.LOCK_EX | fcntl.LOCK_NB)
            except OSError:
                return True
            fcntl.flock(self._file, fcntl.LOCK_UN)
        return False


def get_sync_lock(db_path: str) -> SyncLock:
    """Process-wide lock registry keyed by db path (one lock per "origin")."""
    with _registry_guard:
        lock = _registry.get(db_path)
        if lock is None or db_path == ":memory:":
            lock = SyncLock(db_path)
            _registry[db_path] = lock
        return lock
