"""DbWorker — the single-writer command engine.

Reference: packages/evolu/src/db.worker.ts. All state-changing work
funnels through one ordered queue processed by one thread; every
command runs inside one SQLite transaction and reports failures as an
`OnError` output instead of raising (db.worker.ts:50-75). Command
semantics live in methods named after the reference's command modules
(send.ts, receive.ts, query.ts, sync.ts, updateDbSchema.ts,
resetOwner.ts, restoreOwner.ts).

TPU-native twist: `Send`/`Receive` batches are applied through a
pluggable merge planner — the host oracle for small batches, the
device kernel (`evolu_tpu.ops.merge.plan_batch_device`) above
`config.min_device_batch` — with identical end state either way
(tests/test_apply.py property-tests the equivalence).
"""

from __future__ import annotations

import queue
import threading
import time
from typing import Callable, Dict, List, Optional, Sequence

from evolu_tpu.core.merkle import diff_merkle_trees, merkle_tree_from_string, merkle_tree_to_string
from evolu_tpu.core.timestamp import (
    receive_timestamps_batch,
    receive_timestamps_batch_packed,
    create_sync_timestamp,
    receive_timestamp,
    send_timestamp,
    timestamp_from_string,
    timestamp_to_string,
)
from evolu_tpu.core.types import CrdtClock, CrdtMessage, Owner, SyncError
from evolu_tpu.obs import flight, metrics, trace
from evolu_tpu.runtime import messages as msg
from evolu_tpu.runtime.jsonpatch import create_patch
from evolu_tpu.runtime.synclock import SyncLock, get_sync_lock
from evolu_tpu.storage.apply import (
    _notify_plan_failure,
    apply_messages,
    apply_messages_chunked,
    plan_batch,
)
from evolu_tpu.storage.changes import ChangedSet
from evolu_tpu.storage.deps import query_dependencies
from evolu_tpu.storage.clock import read_clock, update_clock
from evolu_tpu.storage.schema import delete_all_tables, init_db_model, update_db_schema
from evolu_tpu.storage.sqlite import PySqliteDatabase
from evolu_tpu.sync.protocol import assert_wire_encodable
from evolu_tpu.utils.config import Config
from evolu_tpu.utils.log import logger


def _now_millis() -> int:
    return int(time.time() * 1000)


_MISSING = object()  # pop sentinel: a cached [] must still count


def select_planner(config: Config, db: Optional[PySqliteDatabase] = None) -> Callable:
    """Pick the merge planner per config.backend: the host oracle below
    `min_device_batch`, the device kernel at/above it ("auto"/"tpu"),
    and the cell-range-sharded hot-owner kernel for huge single-owner
    batches on multi-device hosts.

    With `db` and `config.winner_cache`, device-planned batches source
    stored winners from the HBM-resident cache (ops/winner_cache.py —
    measured faster than streaming them from SQLite per batch) — the
    returned planner then owns winner fetching (`fetches_winners =
    False`) and any batch planned OUTSIDE the cache (host oracle,
    hot-owner) invalidates its touched cells, keeping cache == SQLite."""
    if config.backend == "cpu":
        return plan_batch

    from evolu_tpu.ops.merge import plan_batch_device_full
    from evolu_tpu.ops.scatter_merge import set_plan_path

    set_plan_path(config.merge_plan)

    threshold = 0 if config.backend == "tpu" else config.min_device_batch
    hot_min = config.hot_owner_min_batch
    cache = None
    if db is not None and config.winner_cache:
        if config.mesh_engine and _multi_device():
            # PR-12: slot arrays sharded over the device mesh (stable
            # cell→device placement; one shard_map'd gather/plan/
            # scatter pass per batch). Same planner contract and
            # coherence hooks; plans are identical to the single-device
            # cache (parity-pinned in tests/test_mesh_engine.py).
            from evolu_tpu.ops.winner_cache import MeshShardedWinnerCache
            from evolu_tpu.parallel.mesh import get_mesh_context

            cache = MeshShardedWinnerCache(
                db, mesh_ctx=get_mesh_context(config.mesh_devices)
            )
        else:
            from evolu_tpu.ops.winner_cache import DeviceWinnerCache

            cache = DeviceWinnerCache(db)

    def planner(batch, existing):
        hot_route = (
            hot_min is not None and len(batch) >= hot_min and _multi_device()
        )
        touched = None
        if cache is not None:
            if not hot_route and len(batch) >= threshold and not existing:
                # The standard device route: winners live in HBM.
                return cache.plan_batch(batch)
            # A non-cache route plans this batch (hot-owner, host
            # oracle, or a caller handed explicit winners). It needs
            # real stored winners if apply gave us none, and afterwards
            # the cache entries for its cells are stale — the plan
            # bypasses the cache scatter — so invalidate them.
            touched = {(m.table, m.row, m.column) for m in batch}
            if not existing:
                from evolu_tpu.storage.apply import fetch_existing_winners

                existing = fetch_existing_winners(db, touched)
        cols = None
        if hot_route:
            plan, cols = _plan_hot_owner(batch, existing)
            if plan is not None:
                if touched is not None:
                    cache.invalidate(touched)
                return plan
        if touched is not None:
            cache.invalidate(touched)
        if len(batch) >= threshold:
            # `cols` reuses the hot path's columnarization when it
            # declined the batch (non-canonical hex case).
            return plan_batch_device_full(batch, existing, cols=cols)
        return plan_batch(batch, existing)

    def plan_packed(pb):
        """Packed-batch twin of the closure above for PackedReceive
        (the fused receive leg). None = materialize and route the
        object path (which owns invalidation for those shapes)."""
        n = len(pb)
        if n < threshold or (
            hot_min is not None and n >= hot_min and _multi_device()
        ):
            # Small batches take the host oracle; hot-owner batches
            # keep their multi-device shard route — both via objects.
            return None
        if cache is not None:
            return cache.plan_packed(pb)
        if db is None:
            return None
        return _plan_packed_streamed_nocache(db, pb)

    planner.plan_packed = plan_packed
    if cache is not None:
        planner.fetches_winners = False
        planner.on_transaction_failed = cache.on_transaction_failed
        planner.cache = cache
    return planner


def _plan_packed_streamed_nocache(db, pb):
    """Packed plan with winners streamed from SQLite (winner_cache
    off): the PackedReceive analog of `plan_batch_device_full`. None →
    object path (non-canonical batch or stored winner)."""
    from evolu_tpu.ops.merge import plan_packed_streamed

    millis, counter, node, case_ok = pb.parse_timestamps()
    if not bool(case_ok.all()):
        return None
    touched_ids, cells = pb.touched_cells()
    return plan_packed_streamed(db, pb, millis, counter, node, cells, touched_ids)


def _multi_device() -> bool:
    import jax

    return len(jax.devices()) >= 2


def _plan_hot_owner(batch, existing):
    """One client is one owner; a batch above hot_owner_min_batch
    shards by cell-id ranges over every local device (per-cell LWW
    merges are independent — SURVEY.md §5 "within one hot owner, by
    cell-id ranges"). Returns (plan, cols): the standard 3-tuple plan,
    or plan=None when the host should route normally (non-canonical hex
    case — the device order/hash contract doesn't hold there and
    plan_batch_device_full's own fallback takes over); `cols` carries
    the columnarization for reuse either way. Callers gate on
    `_multi_device()`."""
    from evolu_tpu.ops.merge import messages_to_columns
    from evolu_tpu.parallel.hot_owner import reconcile_hot_owner
    from evolu_tpu.parallel.mesh import create_mesh

    cols = messages_to_columns(batch, existing)
    cell_id, k1, k2, ex_k1, ex_k2, millis, counter, node, canonical = cols
    if not canonical:
        return None, cols
    xor_mask, upsert_mask, deltas, _digest = reconcile_hot_owner(
        create_mesh(), cell_id, k1, k2, ex_k1, ex_k2, millis, counter, node
    )
    upserts = [m for i, m in enumerate(batch) if upsert_mask[i]]
    return (list(map(bool, xor_mask)), upserts, deltas), cols


class DbWorker:
    """The engine. Post commands with `post`; outputs arrive on the
    `on_output` callback from the worker thread (or synchronously from
    `start` for `OnInit`)."""

    def __init__(
        self,
        db: PySqliteDatabase,
        config: Optional[Config] = None,
        on_output: Optional[Callable[[object], None]] = None,
        post_sync: Optional[Callable[[msg.SyncRequestInput], None]] = None,
        now: Callable[[], int] = _now_millis,
        sync_lock: Optional[SyncLock] = None,
    ):
        self.db = db
        self.config = config or Config()
        self.on_output = on_output or (lambda _o: None)
        self.post_sync = post_sync or (lambda _r: None)
        self.now = now
        self.sync_lock = sync_lock or get_sync_lock(db.path)
        self.owner: Optional[Owner] = None
        self.queries_rows_cache: Dict[str, List[dict]] = {}
        # (raw packed result bytes, per-row offsets) per query — the
        # change detector for the reactive loop (bytes) plus the r5
        # row-granular alignment key (offsets); lifecycle mirrors
        # queries_rows_cache exactly (staged per command, committed on
        # success, evicted and cleared together — a desynced pair would
        # suppress or duplicate patches).
        self.queries_raw_cache: Dict[str, tuple] = {}
        # r9 incremental invalidation (ISSUE 9). The change log is a
        # short list of (seq, ChangedSet) batches; each tracked query
        # remembers the seq it last executed at (`_query_seen`), so
        # gating = "did anything after my seq touch my read set?"
        # (`storage/deps.py` provides the read set). `_query_lru`
        # orders queries by last use for the Config.query_cache_max
        # bound; an execution with no cached baseline always emits a
        # root-replace (see `_query`), so eviction needs no tombstones.
        self._query_deps: Dict[str, object] = {}
        self._query_seen: Dict[str, int] = {}
        self._query_lru: Dict[str, None] = {}
        self._change_log: List[tuple] = []
        self._change_seq: int = 0
        self._planner = select_planner(self.config, self.db)
        self._staged_effects: List = []
        self._staged_cache: Dict[str, List[dict]] = {}
        self._staged_raw: Dict[str, tuple] = {}
        self._staged_changes: ChangedSet = ChangedSet()
        self._staged_seen: set = set()
        self._queue: "queue.Queue[object]" = queue.Queue()
        self._thread: Optional[threading.Thread] = None
        self._stop = object()

    # -- lifecycle --

    def start(self, mnemonic: Optional[str] = None) -> Owner:
        """Init: bootstrap the db model in one transaction and emit
        OnInit with the owner (db.worker.ts:77-137). Applies the config's
        log setting to the module logger (setConfig, db.worker.ts:103)."""
        logger.configure(self.config.log)
        with self.db.transaction():
            self.owner = init_db_model(self.db, mnemonic)
        self.on_output(msg.OnInit(self.owner))
        self._thread = threading.Thread(target=self._loop, daemon=True, name="evolu-db-worker")
        self._thread.start()
        return self.owner

    def stop(self) -> None:
        self._queue.put(self._stop)
        if self._thread is not None:
            self._thread.join()
            self._thread = None

    def post(self, command: object) -> None:
        """Enqueue a DbWorkerInput (db.worker.ts:47-75)."""
        self._queue.put(command)

    def flush(self) -> None:
        """Block until every queued command has been processed (test/sync aid)."""
        done = threading.Event()
        self._queue.put(done)
        done.wait()

    def _loop(self) -> None:
        while True:
            command = self._queue.get()
            if command is self._stop:
                return
            if isinstance(command, threading.Event):
                command.set()
                continue
            self.handle(command)

    # Side effects (outputs, sync pushes, worker-cache writes) are staged
    # during a command and flushed only after its transaction commits —
    # otherwise a failure later in the command would roll back local
    # state that was already pushed to the relay (the relay's own-node
    # exclusion would then never return those messages: permanent
    # divergence), and the worker's query cache would desync from the
    # committed rows.

    def _emit(self, output: object) -> None:
        self._staged_effects.append(lambda: self.on_output(output))

    def _push(self, request: msg.SyncRequestInput) -> None:
        self._staged_effects.append(lambda: self.post_sync(request))

    def _manages_own_transactions(self, command: object) -> bool:
        """A Receive large enough to chunk commits per chunk (bounded
        transaction memory + resumable clock); every other command gets
        the reference's one-transaction-per-command wrapper. Nested
        transactions JOIN the outer one, so the chunked path must run
        without it or per-chunk commits would silently be no-ops."""
        chunk = self.config.receive_chunk_size
        return (
            isinstance(command, msg.Receive)
            and bool(chunk)
            and len(command.messages) > chunk
        )

    def handle(self, command: object) -> None:
        """Dispatch one command inside one transaction; errors roll back
        and surface as OnError (db.worker.ts:57-73)."""
        t0 = time.perf_counter()
        self._staged_effects = []
        self._staged_cache: Dict[str, List[dict]] = {}
        self._staged_raw: Dict[str, tuple] = {}
        self._staged_changes = ChangedSet()
        self._staged_seen = set()
        metrics.inc("evolu_worker_commands_total", command=type(command).__name__)
        try:
            self._handle_inner(command)
        finally:
            if isinstance(command, (msg.Send, msg.Receive, msg.Query)):
                # The mutation→notify latency surface (ISSUE 9): local
                # mutations notify within their Send; remote ones are a
                # Receive plus the follow-up Query sweep.
                metrics.observe(
                    "evolu_query_notify_latency_ms",
                    (time.perf_counter() - t0) * 1e3,
                    command=type(command).__name__,
                )

    def _handle_inner(self, command: object) -> None:
        try:
            from contextlib import nullcontext

            txn = (
                nullcontext()
                if self._manages_own_transactions(command)
                else self.db.transaction()
            )
            with txn:
                if isinstance(command, msg.Send):
                    self._send(command)
                elif isinstance(command, msg.Receive):
                    self._receive(command)
                elif isinstance(command, msg.Query):
                    # full=True = refresh whose trigger the change log
                    # cannot see (e.g. another process wrote the shared
                    # DB file): bypass gating.
                    self._query(command.queries,
                                gated=not getattr(command, "full", False))
                elif isinstance(command, msg.EvictQueries):
                    for q in command.queries:
                        self._evict_query_entry(q)
                elif isinstance(command, msg.Sync):
                    self._sync(command)
                elif isinstance(command, msg.UpdateDbSchema):
                    update_db_schema(self.db, command.table_definitions)
                    # DDL plus possible pre-declaration typed folds
                    # (crdt_types._fold_predeclaration_ops) touch app
                    # tables in ways no message batch describes: the
                    # "don't know" arm of the invalidation contract.
                    self._staged_changes.mark_unknown()
                elif isinstance(command, msg.ResetOwner):
                    self._reset_owner()
                elif isinstance(command, msg.RestoreOwner):
                    self._restore_owner(command.mnemonic)
                elif isinstance(command, msg.WidenSyncScope):
                    self._widen_scope(command)
                else:
                    raise ValueError(f"unknown command: {command!r}")
        except Exception as e:  # noqa: BLE001 - the Either-left channel
            # The flight recorder's dump rides the exception across the
            # worker boundary: OnError subscribers (and test failures)
            # see the last N structured events, not a bare traceback.
            flight.attach(e)
            metrics.inc("evolu_worker_errors_total",
                        command=type(command).__name__)
            if isinstance(command, (msg.Send, msg.Receive, msg.ResetOwner, msg.RestoreOwner)):
                # A planner-touching command's transaction rolled back,
                # but a stateful planner (the HBM winner cache) may have
                # advanced at plan time INSIDE it — e.g. apply_messages
                # succeeds, then the livelock SyncError aborts the whole
                # receive. Without this resync the cache keeps phantom
                # winners SQLite never committed: redelivered messages
                # get xor=False (their hash never enters the Merkle
                # tree — permanent digest divergence) and beats=False
                # (app rows never upserted). Found by
                # tests/test_model_check.py. Idempotent; the inner
                # apply-level hook may already have fired. Gated to
                # these commands so e.g. a failed Query cannot wipe a
                # warm cache.
                _notify_plan_failure(self._planner)
            # Commit the staged changed-set even on failure: for a
            # rolled-back transaction it is a harmless superset (extra
            # re-execution, never staleness); for a chunked receive it
            # covers the chunks that DID commit. Seen-epoch updates are
            # dropped — queries staged this command re-verify next time.
            self._commit_staged_changes()
            if self._manages_own_transactions(command):
                # Chunked receive: earlier chunks COMMITTED before the
                # failure — their staged effects (OnReceive, so query
                # subscribers re-render the committed rows) must still
                # fire; dropping them would hide committed state until
                # some later command happens to emit.
                self.queries_rows_cache.update(self._staged_cache)
                self.queries_raw_cache.update(self._staged_raw)
                self._flush_staged_effects()
            try:
                self.on_output(msg.OnError(e))
            except Exception:  # noqa: BLE001,S110 - a raising error
                # listener must not kill the worker thread (every later
                # flush would hang on a dead loop)
                pass
            return
        self._commit_staged_changes()
        # Seen-epochs commit with the caches: after _commit_staged_changes
        # the current seq covers this command's own writes, which every
        # query staged this command already observed (the sweep runs
        # after the apply inside _send) or was verified disjoint from.
        for q in self._staged_seen:
            self._query_seen[q] = self._change_seq
        self.queries_rows_cache.update(self._staged_cache)
        self.queries_raw_cache.update(self._staged_raw)
        self._enforce_query_cache_cap()
        if self._staged_seen or isinstance(command, msg.EvictQueries):
            metrics.set_gauge("evolu_query_subscriptions",
                              len(self.queries_rows_cache))
        self._flush_staged_effects()

    # -- incremental-invalidation bookkeeping (ISSUE 9) --

    def _commit_staged_changes(self) -> None:
        if not self._staged_changes:
            return
        self._change_seq += 1
        self._change_log.append((self._change_seq, self._staged_changes))
        self._staged_changes = ChangedSet()
        if len(self._change_log) > 64:
            self._compact_change_log()

    def _compact_change_log(self) -> None:
        """Drop entries every tracked query has seen; if stale one-shot
        seen-epochs still pin history, merge the oldest half into one
        cumulative entry whose seq is the max member seq — still
        greater than any seen value predating any member, so queries
        behind it observe the union (a superset: conservative)."""
        floor = min(self._query_seen.values(), default=self._change_seq)
        log = [(s, e) for s, e in self._change_log if s > floor]
        if len(log) > 64:
            half = len(log) // 2
            merged = ChangedSet()
            for _s, e in log[:half]:
                merged.merge(e)
            log = [(log[half - 1][0], merged)] + log[half:]
        self._change_log = log

    def _staged_changes_or_none(self):
        """The apply-layer recording target — None when invalidation is
        disabled, so the reference-fallback configuration pays zero
        per-message recording cost (record_batch no-ops on None)."""
        return self._staged_changes if self.config.query_invalidation else None

    def _evict_query_entry(self, q: str) -> None:
        """Unsubscribed (EvictQueries): drop every per-query structure."""
        self.queries_rows_cache.pop(q, None)
        self.queries_raw_cache.pop(q, None)
        self._query_deps.pop(q, None)
        self._query_seen.pop(q, None)
        self._query_lru.pop(q, None)

    def _enforce_query_cache_cap(self) -> None:
        """Bound the per-query caches to Config.query_cache_max by
        least-recently-executed eviction, so churned one-shot query
        strings cannot grow the worker without bound. A still-subscribed
        query that loses its entry self-heals on its next execution
        with a root-replace patch (emitted whenever there is no cached
        baseline — including an empty result, so a subscriber holding
        rows from before the eviction can never be left stale)."""
        cap = self.config.query_cache_max
        if not cap:
            return
        evicted = 0
        while len(self.queries_rows_cache) > cap and self._query_lru:
            q = next(iter(self._query_lru))
            del self._query_lru[q]
            had_entry = self.queries_rows_cache.pop(q, _MISSING)
            self.queries_raw_cache.pop(q, None)
            self._query_deps.pop(q, None)
            self._query_seen.pop(q, None)
            if had_entry is not _MISSING:
                evicted += 1  # LRU residue of failed queries don't count
        if evicted:
            metrics.inc("evolu_query_cache_evictions_total", evicted)
        if len(self._query_lru) > 2 * cap:
            # Failed/never-cached queries leave LRU-only residue; sweep
            # it on the rare overflow.
            for q in list(self._query_lru):
                if len(self._query_lru) <= 2 * cap:
                    break
                if q not in self.queries_rows_cache:
                    del self._query_lru[q]
                    self._query_deps.pop(q, None)
                    self._query_seen.pop(q, None)

    def _pending_since(self, seen: int, memo: Dict[int, object]):
        """Shared gate state for every query last verified at epoch
        `seen`: `"clean"` (nothing written since), `"conservative"`
        (an unattributable write — every gated query must re-execute),
        or `(tables, rows)` of the merged pending ChangedSet. Memoized
        per sweep so the change-log merge runs once per distinct
        epoch, not once per query."""
        pend = ChangedSet()
        for s, e in self._change_log:
            if s > seen:
                pend.merge(e)
        if self._staged_changes:
            pend.merge(self._staged_changes)
        if pend.conservative:
            state = "conservative"
        elif not pend.tables:
            state = "clean"
        else:
            state = (pend.tables, pend.rows)
        memo[seen] = state
        return state

    def _flush_staged_effects(self) -> None:
        for effect in self._staged_effects:
            try:
                effect()
            except Exception as e:  # noqa: BLE001 - listener raised: must
                # not kill the worker thread (the command already committed)
                try:
                    self.on_output(msg.OnError(e))
                except Exception:  # noqa: BLE001,S110 - error channel itself broken
                    pass

    # -- commands --

    def _send(self, command: msg.Send) -> None:
        """send.ts:82-122: stamp → apply → persist clock → push → re-query.

        One wall-clock sample per command, like the reference's
        per-command TimeEnv (types.ts:303-309). The mutation mints the
        distributed-trace root span (obs.trace, ISSUE 10): its context
        rides the staged SyncRequestInput into the sync transport and
        from there the HTTP traceparent header — the one id that ties
        client → relay → batch → engine → replica together."""
        # Refuse wire-unencodable values BEFORE they enter the log (the
        # whole command rolls back and surfaces as OnError): a committed
        # value the encoder cannot express (bytes always; float/int64 in
        # strict mode) would wedge every later resend batch permanently.
        # Remote messages are exempt — a replica relays what it received.
        for m in command.messages:
            assert_wire_encodable(m.value, self.config.wire_extensions)
        mspan = trace.start_span(
            "client.mutate", attrs={"messages": len(command.messages)}
        )
        with mspan, trace.use(mspan.context):
            clock = read_clock(self.db)
            t = clock.timestamp
            now = self.now()
            stamped: List[CrdtMessage] = []
            for m in command.messages:
                t = send_timestamp(t, now, self.config.max_drift)
                stamped.append(
                    CrdtMessage(timestamp_to_string(t), m.table, m.row, m.column, m.value)
                )
            tree = apply_messages(self.db, clock.merkle_tree, stamped,
                                  planner=self._planner,
                                  changes=self._staged_changes_or_none())
            next_clock = CrdtClock(t, tree)
            update_clock(self.db, next_clock)
            self._push(
                msg.SyncRequestInput(
                    messages=tuple(stamped),
                    clock_timestamp=timestamp_to_string(t),
                    merkle_tree=merkle_tree_to_string(tree),
                    owner=self.owner,
                    trace=mspan.context,
                )
            )
            self._query(command.queries, command.on_complete_ids)

    def _receive(self, command: msg.Receive) -> None:
        """receive.ts:144-199: merge remote messages, then anti-entropy."""
        clock = read_clock(self.db)
        if len(command.messages):
            # HLC merge folded over every remote timestamp
            # (receive.ts:45-66) — the reduced vectorized fold, with one
            # wall-clock sample per command like the reference's TimeEnv.
            # A parse failure re-runs the fold sequentially so the FIRST
            # failing message defines the surfaced error, exactly like
            # the reference's per-message traversal.
            from evolu_tpu.core.packed import PackedReceive
            from evolu_tpu.core.types import TimestampParseError
            from evolu_tpu.ops.host_parse import parse_timestamp_strings

            now = self.now()
            packed = isinstance(command.messages, PackedReceive)
            try:
                if packed:
                    # Fused receive: the 46-wide slab parses in one
                    # native call; node strings materialize only if a
                    # screen forces the exact sequential fold.
                    pb = command.messages
                    r_millis, r_counter, r_node, _case = pb.parse_timestamps()
                    t = receive_timestamps_batch_packed(
                        clock.timestamp, r_millis, r_counter, r_node,
                        lambda: [s[30:46] for s in pb.timestamp_strings()],
                        now=now, max_drift=self.config.max_drift,
                    )
                else:
                    r_millis, r_counter, _ = parse_timestamp_strings(
                        [m.timestamp for m in command.messages]
                    )
                    t = receive_timestamps_batch(
                        clock.timestamp, r_millis, r_counter,
                        [m.timestamp[30:46] for m in command.messages],
                        now=now, max_drift=self.config.max_drift,
                    )
            except TimestampParseError:
                ts_strings = (
                    command.messages.timestamp_strings() if packed
                    else [m.timestamp for m in command.messages]
                )
                t = clock.timestamp
                for s in ts_strings:
                    t = receive_timestamp(
                        t, timestamp_from_string(s), now, self.config.max_drift
                    )
            messages = command.messages if packed else list(command.messages)
            deferred: List[CrdtMessage] = []
            scope = getattr(self.config, "sync_scope", None)
            if scope is not None and scope.tables:
                # Partial replication (ISSUE 18): only in-scope tables
                # materialize; out-of-scope messages still land in the
                # log and the Merkle tree (log-only apply below) so
                # anti-entropy and the digest never see the difference.
                # The packed slab cannot partition per-table — bounce
                # to the object path BEFORE any side effect (the same
                # stance as the r5 non-canonical bounce).
                if packed:
                    messages = list(messages.to_messages())
                in_scope: List[CrdtMessage] = []
                for m in messages:
                    (in_scope if scope.table_in_scope(m.table)
                     else deferred).append(m)
                messages = in_scope
            chunk = self.config.receive_chunk_size
            if chunk and len(messages) > chunk:
                # Huge history (e.g. initial sync of a restored device):
                # blockwise apply with the clock persisted per chunk —
                # the LWW contraction is associative, so the end state
                # equals one giant batch, but memory stays bounded and a
                # mid-sync failure resumes from the last chunk. The HLC
                # timestamp is already merged over the WHOLE batch above,
                # matching the reference's clock-then-apply order.
                receive_staged = False

                def persist(tree_so_far, _applied):
                    # Stage OnReceive as soon as the FIRST chunk commits:
                    # a mid-stream ChunkedApplyError flushes staged
                    # effects, so subscribers re-render the rows earlier
                    # chunks committed instead of them staying hidden
                    # until some later command emits.
                    nonlocal receive_staged
                    update_clock(self.db, CrdtClock(t, tree_so_far))
                    if not receive_staged:
                        receive_staged = True
                        self._emit(msg.OnReceive())

                tree = apply_messages_chunked(
                    self.db, clock.merkle_tree, messages, chunk_size=chunk,
                    planner=self._planner, on_chunk=persist,
                    changes=self._staged_changes_or_none(),
                )
                # persist() already wrote the final clock with this tree
                # and staged the OnReceive.
                if deferred:
                    tree = self._apply_deferred(tree, deferred)
                    update_clock(self.db, CrdtClock(t, tree))
                clock = CrdtClock(t, tree)
            else:
                tree = apply_messages(
                    self.db, clock.merkle_tree, messages,
                    planner=self._planner, changes=self._staged_changes_or_none(),
                )
                if deferred:
                    tree = self._apply_deferred(tree, deferred)
                clock = CrdtClock(t, tree)
                update_clock(self.db, clock)
                self._emit(msg.OnReceive())

        server_tree = merkle_tree_from_string(command.merkle_tree)
        diff = diff_merkle_trees(server_tree, clock.merkle_tree)
        if diff is None:
            return
        # Livelock guard: the same diff twice in a row means the replicas
        # cannot converge (receive.ts:99-104).
        if command.previous_diff is not None and diff == command.previous_diff:
            raise SyncError()
        if self.sync_lock.is_pending_or_held():
            return
        since = timestamp_to_string(create_sync_timestamp(diff))
        rows = self.db.exec_sql_query(
            'SELECT * FROM "__message" WHERE "timestamp" > ? ORDER BY "timestamp"',
            (since,),
        )
        resend = tuple(
            CrdtMessage(r["timestamp"], r["table"], r["row"], r["column"], r["value"])
            for r in rows
        )
        self._push(
            msg.SyncRequestInput(
                messages=resend,
                clock_timestamp=timestamp_to_string(clock.timestamp),
                merkle_tree=merkle_tree_to_string(clock.merkle_tree),
                owner=self.owner,
                previous_diff=diff,
            )
        )

    # -- partial replication (ISSUE 18, sync/scope.py) --

    _SCOPE_DEFERRED_DDL = (
        'CREATE TABLE IF NOT EXISTS "__scope_deferred" '
        '("table" TEXT PRIMARY KEY, "rows" INTEGER NOT NULL) WITHOUT ROWID'
    )

    def _apply_deferred(self, tree: dict, deferred: List[CrdtMessage]) -> dict:
        """Out-of-scope leg of a scoped receive: log + Merkle tree only
        (`apply_messages_log_only`), no app-table rows, with the skipped
        materialization COUNTED in the `__scope_deferred` frontier so a
        query against one of these tables can answer a typed deferral
        instead of silently-empty rows."""
        from evolu_tpu.storage.apply import apply_messages_log_only

        # Frontier counts must be EXACT against the log: anti-entropy
        # re-serves whole minutes, so a batch can redeliver rows the
        # log already holds — screen them out before counting (the
        # insert below is ON CONFLICT DO NOTHING, so the log agrees).
        seen: set = set()
        stamps = [m.timestamp for m in deferred]
        for i in range(0, len(stamps), 500):
            chunk = stamps[i:i + 500]
            rows = self.db.exec_sql_query(
                'SELECT "timestamp" FROM "__message" WHERE "timestamp" '
                f'IN ({",".join("?" * len(chunk))})',
                tuple(chunk),
            )
            seen.update(r["timestamp"] for r in rows)
        tree = apply_messages_log_only(
            self.db, tree, deferred, changes=self._staged_changes_or_none()
        )
        cache = getattr(self._planner, "cache", None)
        if cache is not None:
            # The log's MAX(timestamp) for these cells just moved via a
            # plan the HBM cache never saw — the cache==SQLite invariant
            # demands invalidation, exactly like the host-oracle route.
            cache.invalidate({(m.table, m.row, m.column) for m in deferred})
        counts: Dict[str, int] = {}
        for m in deferred:
            if m.timestamp in seen:
                continue
            counts[m.table] = counts.get(m.table, 0) + 1
        self.db.exec(self._SCOPE_DEFERRED_DDL)
        for tbl, n in counts.items():
            self.db.run(
                'INSERT INTO "__scope_deferred" ("table", "rows") '
                'VALUES (?, ?) '
                'ON CONFLICT("table") DO UPDATE SET "rows" = "rows" + ?',
                (tbl, n, n),
            )
        n_new = sum(counts.values())
        if n_new:
            metrics.inc("evolu_scope_deferred_total", n_new)
        return tree

    def _deferred_frontier(self) -> Dict[str, int]:
        """table → deferred-message count, {} when nothing is deferred
        (including before the side table first exists)."""
        try:
            rows = self.db.exec_sql_query(
                'SELECT "table", "rows" FROM "__scope_deferred" '
                'WHERE "rows" > 0'
            )
        except Exception:  # noqa: BLE001 - no table yet = empty frontier
            return {}
        return {r["table"]: r["rows"] for r in rows}

    def _widen_scope(self, command: "msg.WidenSyncScope") -> None:
        """Escalation (widenSyncScope): relax the scope, re-materialize
        every newly-in-scope table from the LOCAL log in LWW order, and
        clear its frontier rows. History the relay withheld arrives via
        the next ordinary anti-entropy round — the scoped server
        subtree widens with the same clause, so the tree diff drives
        catch-up with no special protocol."""
        scope = getattr(self.config, "sync_scope", None)
        if scope is None:
            return  # already a full replica; nothing to widen
        if command.full:
            new = None
        else:
            new = scope.widen(command.watermark_millis,
                              tuple(command.tables))
            if new.is_noop:
                new = None
        n_remat = 0
        for tbl in sorted(self._deferred_frontier()):
            if new is None or new.table_in_scope(tbl):
                n_remat += self._rematerialize_table(tbl)
                self.db.run(
                    'DELETE FROM "__scope_deferred" WHERE "table" = ?',
                    (tbl,),
                )
        self.config.sync_scope = new
        if n_remat:
            # Whole tables appeared at once: unattributable to any
            # message batch — the conservative invalidation arm.
            self._staged_changes.mark_unknown()
            metrics.inc("evolu_scope_widen_materialized_total", n_remat)
        self._emit(msg.OnReceive())

    def _rematerialize_table(self, table: str) -> int:
        """Replay one table's app rows from the `__message` log: LWW
        winner per (row, column) upserted (ascending timestamp order,
        last write wins — byte-identical to having applied every batch
        unscoped), typed cells rebuilt via the order-free full-state
        fold. → messages replayed."""
        from evolu_tpu.core.crdt_types import load_schema, rebuild_state
        from evolu_tpu.storage.apply import _upsert_sql

        rows = self.db.exec_sql_query(
            'SELECT "timestamp", "row", "column", "value" FROM "__message" '
            'WHERE "table" = ? ORDER BY "timestamp"',
            (table,),
        )
        if not rows:
            return 0
        schema = load_schema(self.db)
        winners: Dict[tuple, dict] = {}
        has_typed = False
        for r in rows:
            if schema and schema.is_typed(table, r["column"]):
                has_typed = True
                continue
            winners[(r["row"], r["column"])] = r
        for r in winners.values():
            self.db.run(
                _upsert_sql(table, r["column"]),
                (r["row"], r["value"], r["value"]),
            )
        if has_typed:
            # Typed folds were skipped at defer time; the incremental
            # path can't replay them (its dedup screen reads __message,
            # where every one of these ops already lives) — the
            # order-free full rebuild is the exact route.
            rebuild_state(self.db, schema)
        cache = getattr(self._planner, "cache", None)
        if cache is not None:
            cache.invalidate({
                (table, r["row"], r["column"]) for r in rows
            })
        return len(rows)

    def _query(self, queries: Sequence[str], on_complete_ids: Sequence[str] = (),
               gated: bool = True) -> None:
        """query.ts:16-76: run, diff vs cache, post non-empty patches.

        r9 (ISSUE 9) gates the sweep on the changed-set: a query whose
        read tables (storage/deps.py, from SQLite's own compiled
        program) are disjoint from everything written since its last
        run skips WITHOUT a read or a byte compare; a query with a
        static `"id" = ?` constraint additionally skips row-disjoint
        writes. Every "don't know" — unknown deps, unknown rows,
        conservative change, no baseline — falls through to execution,
        so the emitted patch stream is byte-identical to re-running
        everything (bench-gated in benchmarks/query_sub_scaling.py).
        `gated=False` (explicit Sync refresh, Query(full=True))
        re-executes unconditionally.

        With the packed reader (C++ backend), the raw result bytes are
        the change detector for executed queries: a subscribed query
        whose bytes match the cached bytes skips dict materialization
        AND the rfc6902 diff entirely — the dominant cost of the
        reactive re-execution loop (SURVEY hot loop #4; measured r4:
        ~65 ms per 10k-row query on the per-cell path vs ~4 ms raw
        read + compare). Byte equality is EXACT here, not approximate:
        the only value whose deep-equality differs from bit-equality
        is REAL NaN, and SQLite converts NaN to NULL at bind time so
        no queried row can hold one (pinned in test_runtime.py;
        -0.0→0.0 rewrites emit a patch the deep-equal would skip — a
        real write happened, so the extra patch is harmless).

        A query with NO cached baseline (first run, or LRU-evicted
        under Config.query_cache_max) emits a ROOT-REPLACE patch
        (`{"op": "replace", "path": "", "value": rows}`) instead of
        index ops diffed against []: index ops are only correct when
        the subscriber also starts from [], which an evicted-but-live
        subscription does not."""
        patches = []
        # Partial replication (ISSUE 18): a query that reads a table
        # with deferred (log-only) rows must answer a TYPED deferral,
        # never silently-empty rows. One frontier read per sweep; {}
        # when no scope filter is active.
        _scope = getattr(self.config, "sync_scope", None)
        deferred_tables = (
            self._deferred_frontier()
            if _scope is not None and _scope.tables else {}
        )
        deferred_hits: set = set()
        raw_capable = hasattr(self.db, "exec_sql_query_packed_raw")
        if raw_capable:
            from evolu_tpu.storage.native import (
                unpack_changed_rows,
                unpack_packed_rows,
            )
        gate = gated and self.config.query_invalidation
        build_deps = self.config.query_invalidation
        pending_memo: Dict[int, object] = {}
        n_exec = n_clean = n_table = n_rows = n_cons = 0
        # The gate is INLINED in this loop with every dict hoisted to a
        # local: at 10^4 subscriptions per sweep the skip path's cost
        # IS the mutation→notify latency for disjoint writes, and a
        # per-query method call + attribute loads measurably dominate
        # it (profiled: ~2× the set ops). Verdict semantics — sound by
        # construction, any uncertainty re-executes: no baseline /
        # unknown deps ⇒ run; conservative epoch or unknown-table deps
        # ⇒ run (counted conservative); table-disjoint ⇒ skip;
        # table-overlap with a static id-filter disjoint from the
        # changed rows ⇒ skip; anything else ⇒ run.
        lru = self._query_lru
        staged_seen_add = self._staged_seen.add
        query_seen_get = self._query_seen.get
        deps_get = self._query_deps.get
        rows_cache, staged_cache = self.queries_rows_cache, self._staged_cache
        memo_get = pending_memo.get
        for q in queries:
            lru.pop(q, None)
            lru[q] = None
            if gate:
                run = True
                seen = query_seen_get(q)
                if seen is not None and (q in rows_cache or q in staged_cache):
                    state = memo_get(seen)
                    if state is None:
                        state = self._pending_since(seen, pending_memo)
                    if state == "clean":
                        n_clean += 1
                        run = False
                    elif state == "conservative":
                        n_cons += 1
                    else:
                        deps = deps_get(q)
                        read_tables = deps.tables if deps is not None else None
                        if read_tables is None:
                            if deps is not None:
                                n_cons += 1  # EXPLAIN walk gave up
                        else:
                            pend_tables, pend_rows = state
                            if pend_tables.isdisjoint(read_tables):
                                n_table += 1
                                run = False
                            else:
                                row_filters = deps.row_filters
                                for t in read_tables:
                                    if t not in pend_tables:
                                        continue
                                    changed = pend_rows.get(t)
                                    if changed is None:
                                        break  # unknown rows: run
                                    flt = row_filters.get(t)
                                    if flt is None or not changed.isdisjoint(flt):
                                        break  # true overlap: run
                                else:
                                    n_rows += 1
                                    run = False
                if not run:
                    staged_seen_add(q)
                    continue  # skipped: no read, no compare, no patch
            staged_seen_add(q)
            n_exec += 1
            sql, parameters = msg.deserialize_query(q)
            if deferred_tables:
                deps = deps_get(q)
                if deps is None:
                    # Built eagerly for the honesty check even when
                    # invalidation gating is off; never raises (its own
                    # failures degrade to unknown deps).
                    deps = query_dependencies(self.db, sql, parameters)
                    if build_deps:
                        self._query_deps[q] = deps
                read_tables = deps.tables
                if read_tables is not None:
                    hit = [t for t in read_tables if t in deferred_tables]
                else:
                    # EXPLAIN walk gave up: conservative text scan —
                    # over-matching defers a query it needn't (honest,
                    # recoverable by widening); under-matching would
                    # answer rows a full replica wouldn't.
                    import re as _re

                    hit = [
                        t for t in deferred_tables
                        if _re.search(r"\b" + _re.escape(t) + r"\b", sql)
                    ]
                if hit:
                    n_exec -= 1  # deferred, not executed
                    deferred_hits.update(hit)
                    continue
            if build_deps and q not in self._query_deps:
                # First execution builds the dependency index entry;
                # query_dependencies never raises (its own failures
                # degrade to unknown), so the statement's real error
                # surface stays with the execution below.
                self._query_deps[q] = query_dependencies(self.db, sql, parameters)
            entry = None
            cached = q in self._staged_cache or q in self.queries_rows_cache
            if raw_capable:
                raw, offs = self.db.exec_sql_query_packed_raw(
                    sql, parameters, with_offsets=True
                )
                entry = (raw, offs)
                prev_entry = self._staged_raw.get(q, self.queries_raw_cache.get(q))
                if cached and prev_entry is not None and prev_entry[0] == raw:
                    self._staged_raw[q] = prev_entry
                    continue  # unchanged — no parse, no diff, no patch
                prev = self._staged_cache.get(q, self.queries_rows_cache.get(q, []))
                if (
                    prev_entry is not None and prev
                    and offs is not None and prev_entry[1] is not None
                ):
                    # Row-granular: only changed row spans unpack; rows
                    # with unchanged bytes reuse prev's dict objects
                    # (identity-stable — create_patch shortcuts on
                    # `is`, and subscribers keep referential equality).
                    rows = unpack_changed_rows(
                        raw, offs, prev_entry[0], prev_entry[1], prev
                    )
                else:  # no prior entry, or a stale .so gave no offsets
                    rows = unpack_packed_rows(raw)
            else:
                rows = self.db.exec_sql_query(sql, parameters)
                prev = self._staged_cache.get(q, self.queries_rows_cache.get(q, []))
            if cached:
                ops = create_patch(prev, rows)
            else:
                # No cached baseline (first run, or LRU-evicted): emit
                # the whole result — EVEN an empty one. A subscriber
                # may hold non-empty rows from before the eviction,
                # and only a root-replace converges it from any state.
                ops = [{"op": "replace", "path": "", "value": rows}]
            # Stage rows BEFORE raw: an exception between unpack and here
            # leaves both staged caches at their old values — staging raw
            # first would let the OnError commit path pair NEW bytes with
            # OLD rows, suppressing the patch forever (advisor r4).
            self._staged_cache[q] = rows
            if entry is not None:
                self._staged_raw[q] = entry
            if ops:
                patches.append((q, ops))
        # Counters batched per sweep: at 10^4 subscriptions a per-query
        # metrics lock would cost more than the skips save.
        if n_exec:
            metrics.inc("evolu_query_executed_total", n_exec)
        if n_clean:
            metrics.inc("evolu_query_skipped_clean_total", n_clean)
        if n_table:
            metrics.inc("evolu_query_skipped_by_table_total", n_table)
        if n_rows:
            metrics.inc("evolu_query_skipped_by_rows_total", n_rows)
        if n_cons:
            metrics.inc("evolu_query_conservative_total", n_cons)
        if deferred_hits:
            from evolu_tpu.sync.scope import ScopeDeferred

            tables = tuple(sorted(deferred_hits))
            self._emit(msg.OnError(ScopeDeferred(
                tables, sum(deferred_tables[t] for t in tables)
            )))
            metrics.inc("evolu_scope_query_deferred_total",
                        len(deferred_hits))
        if patches or on_complete_ids:
            self._emit(msg.OnQuery(tuple(patches), tuple(on_complete_ids)))

    def _sync(self, command: msg.Sync) -> None:
        """sync.ts:20-69: optional query refresh, then a pull-only round."""
        if command.queries:
            # Ungated: an explicit sync refresh exists to pick up state
            # the worker did not write itself (another process on a
            # shared DB file; the reference's load/online/focus
            # re-runs). The byte compare still suppresses no-op patches.
            self._query(command.queries, gated=False)
        if self.sync_lock.is_pending_or_held():
            return
        clock = read_clock(self.db)
        self._push(
            msg.SyncRequestInput(
                messages=(),
                clock_timestamp=timestamp_to_string(clock.timestamp),
                merkle_tree=merkle_tree_to_string(clock.merkle_tree),
                owner=self.owner,
            )
        )

    def _drop_winner_cache(self) -> None:
        """Tables just got dropped; cached winner keys are meaningless."""
        cache = getattr(self._planner, "cache", None)
        if cache is not None:
            cache.reset()

    def verify_winner_cache(self, sample: "int | None" = None) -> int:
        """Audit the PR-11 "device state is truth" invariant on THIS
        worker's live cache: every HBM slot == SQLite MAX(timestamp)
        for its cell (`DeviceWinnerCache.verify_against_db`). → cells
        checked (0 when no cache is active — cpu backend, winner_cache
        off, or streaming mode). The torture episode and the ops
        surface both call through here so the audit always reads the
        worker's actual planner state, not a reconstructed twin."""
        cache = getattr(self._planner, "cache", None)
        if cache is None:
            return 0
        return cache.verify_against_db(sample=sample)

    def _clear_query_caches(self) -> None:
        self.queries_rows_cache.clear()
        self.queries_raw_cache.clear()
        self._query_deps.clear()
        self._query_seen.clear()
        self._query_lru.clear()
        # The change log only gates queries with a seen-epoch; all were
        # just cleared, so history is dead weight (seq stays monotonic).
        self._change_log.clear()

    def _drop_aead_sessions(self) -> None:
        """Owner identity changed: drop the cached aead-batch-v1
        session keys (sync/aead.py). Sessions are keyed by mnemonic so
        a stale entry could never decrypt wrongly — this is retention
        hygiene (no keys for retired identities) plus a fresh session
        salt for whatever identity syncs next, mirroring the
        winner-cache reset invariant on the same transitions."""
        from evolu_tpu.sync import aead

        aead.reset_sessions()

    def _reset_owner(self) -> None:
        """resetOwner.ts:7-21."""
        self._staged_changes.mark_unknown()  # DDL wipe: unattributable
        delete_all_tables(self.db)
        self._drop_winner_cache()
        self._drop_aead_sessions()
        self._staged_effects.append(self._clear_query_caches)
        self._emit(msg.ReloadAllTabs())

    def _restore_owner(self, mnemonic: str) -> None:
        """restoreOwner.ts:9-23 — wipe, re-seed identity; history returns
        via the first sync against the relay (SURVEY.md §3.5)."""
        self._staged_changes.mark_unknown()  # DDL wipe: unattributable
        delete_all_tables(self.db)
        self._drop_winner_cache()
        self._drop_aead_sessions()
        self._staged_effects.append(self._clear_query_caches)
        self.owner = init_db_model(self.db, mnemonic)
        self._emit(msg.ReloadAllTabs())
