"""Relay server: the E2EE-blind sync endpoint plus the TPU batch
reconcile engine.

Reference: apps/server/src/index.ts — a single `POST /` endpoint
storing (timestamp, userId, ciphertext) rows and per-user Merkle
trees; it never sees plaintext. The TPU-native addition
(`evolu_tpu.server.engine`) reconciles many owners' message batches in
one device pass, sharded over the mesh (SURVEY.md §2.15).
"""

from evolu_tpu.server.relay import RelayStore, RelayServer, serve
from evolu_tpu.server.replicate import ReplicationManager
from evolu_tpu.server.scheduler import SchedulerQueueFull, SyncScheduler

__all__ = [
    "RelayStore", "RelayServer", "serve", "SyncScheduler", "SchedulerQueueFull",
    "ReplicationManager",
]
