"""Event-loop connection tier: idle connections cost file descriptors,
not threads (ISSUE 13 tentpole, ROADMAP #4's enabling refactor).

The threaded tier (`ThreadingHTTPServer`) pins one thread per
connection for the connection's whole life — fine for request/response
traffic, fatal for push: 10^4 parked long-polls would be 10^4 parked
threads. This tier inverts the ownership: ONE loop thread
(selectors-based — the stdlib epoll/kqueue wrapper) owns every socket,
does non-blocking accept / incremental read / HTTP parse / response
write, and only a COMPLETE request ever occupies a thread — dispatched
to a bounded handler pool that drives the UNCHANGED `_Handler` over an
in-memory socket. Byte-identity with the threaded tier is therefore by
construction, not by reimplementation: the same handler code runs the
same serve paths (scheduler admission, fleet routing, replication,
tracing, capability negotiation) and produces the same bytes — the
twin-relay oracle test pins it end to end. Push long-polls
(`GET /push/poll`, server/push.py) never reach the pool at all: the
loop parks the bare connection in the hub and writes the response when
a mutation's changed set wakes it.

Admission layering (all bounded, all flow-control — never an error):
  connections  → file descriptors (the OS bound + `evolu_conn_open`)
  dispatches   → `max_pending` in-flight pool jobs; past it the loop
                 answers 503 + Retry-After itself (the scheduler-
                 backpressure shape) without a thread
  engine work  → the PR-2 scheduler's own bounded queue, unchanged
  subscriptions→ the hub's `max_subscriptions`

Slow-client hardening (satellite): a request must arrive COMPLETELY
within `read_timeout_s` of its first byte (an absolute budget —
sliding deadlines are exactly what slowloris exploits), headers are
capped at `max_header_bytes` (431 past it), bodies at the relay's
MAX_BODY_BYTES (the handler's own 413 answers oversized declarations
without the tier ever buffering them), and a response write that stops
progressing for `write_timeout_s` closes the connection. A poller is
never pinned: every one of these is enforced from the loop.

Config-selectable (`Config.connection_tier` / `EVOLU_CONN_TIER` /
`RelayServer(connection_tier=...)`); the threaded tier stays the
default until parity is proven in a deployment (docs/PUSH.md).
"""

from __future__ import annotations

import io
import selectors
import socket
import threading
import time
from collections import deque
from http.server import BaseHTTPRequestHandler
from typing import Dict, List, Optional, Tuple

from evolu_tpu.obs import metrics
from evolu_tpu.utils.log import log

# Defaults; RelayServer threads the Config knobs through.
MAX_HEADER_BYTES = 16384
READ_TIMEOUT_S = 30.0
WRITE_TIMEOUT_S = 30.0
HANDLER_THREADS = 8
MAX_PENDING = 512

_RECV_CHUNK = 65536


# -- driving the existing handler over an in-memory socket --


class _BufferedSocket:
    """Just enough socket surface for BaseHTTPRequestHandler: rfile
    comes from `makefile("rb")` over the buffered request bytes, wfile
    is socketserver's _SocketWriter calling `sendall` — captured here.
    """

    __slots__ = ("_data", "out")

    def __init__(self, data: bytes):
        self._data = data
        self.out = bytearray()

    def makefile(self, mode: str, *a, **k):
        assert "r" in mode
        return io.BytesIO(self._data)

    def sendall(self, b) -> None:
        self.out += b

    def settimeout(self, *_a) -> None:
        pass

    def setsockopt(self, *_a) -> None:
        pass


class _ServerShim:
    """The `server` argument handler construction wants; nothing in the
    BaseHTTPRequestHandler paths we drive reads it."""

    __slots__ = ()


_SERVER_SHIM = _ServerShim()


def serve_buffered(handler_cls, raw: bytes,
                   client_address: Tuple[str, int]) -> bytes:
    """Run one fully-buffered HTTP request through the relay's real
    handler class → the raw response bytes (status line + headers +
    body, exactly what the threaded tier would put on the wire). Any
    escape from the handler (it answers its own 500s; this is the
    socketserver handle_error analog) degrades to a bare 500 if
    nothing was written yet."""
    fake = _BufferedSocket(raw)
    try:
        handler_cls(fake, client_address, _SERVER_SHIM)
    except Exception as e:  # noqa: BLE001
        log("dev", "conn tier handler escape", error=repr(e))
        metrics.inc("evolu_relay_errors_total")
        if not fake.out:
            body = b"handler failure"
            fake.out += (
                b"HTTP/1.0 500 Internal Server Error\r\n"
                b"Content-Type: text/plain\r\nContent-Length: "
                + str(len(body)).encode() + b"\r\n\r\n" + body
            )
    return bytes(fake.out)


# -- in-loop response framing (push fast paths) --
# Mirrors BaseHTTPRequestHandler's send_response framing (status line,
# Server, Date, then per-call headers) so the two tiers stay
# byte-identical on the endpoints the loop answers itself.


def _date_header() -> str:
    return BaseHTTPRequestHandler.date_time_string(None)  # type: ignore[arg-type]


_SERVER_HEADER = (
    BaseHTTPRequestHandler.server_version + " "
    + BaseHTTPRequestHandler.sys_version
)


def frame_response(code: int, headers: List[Tuple[str, str]],
                   body: bytes = b"") -> bytes:
    from http import HTTPStatus

    try:
        phrase = HTTPStatus(code).phrase
    except ValueError:
        phrase = ""
    lines = [f"HTTP/1.0 {code} {phrase}",
             f"Server: {_SERVER_HEADER}", f"Date: {_date_header()}"]
    lines += [f"{k}: {v}" for k, v in headers]
    return ("\r\n".join(lines) + "\r\n\r\n").encode("latin-1") + body


# -- per-connection state --

_READ, _DISPATCHED, _PARKED, _WRITE = range(4)


class _Conn:
    __slots__ = ("sock", "addr", "buf", "header_end", "content_length",
                 "state", "deadline", "outbuf", "outpos", "scan_from",
                 "postreq")

    def __init__(self, sock, addr, now: float, read_timeout: float):
        self.sock = sock
        self.addr = addr
        self.buf = bytearray()
        self.header_end = -1
        self.content_length = 0
        self.state = _READ
        # ABSOLUTE request deadline — never slid on progress, so a
        # byte-per-second trickle (slowloris) cannot hold a slot past
        # the budget.
        self.deadline = now + read_timeout
        self.outbuf: Optional[memoryview] = None
        self.outpos = 0
        self.scan_from = 0
        self.postreq = 0  # bytes tolerated after a complete request


class EventLoopHTTPServer:
    """Drop-in for `_RelayHTTPServer` in `RelayServer`: same
    `server_address` / `serve_forever` / `shutdown` / `server_close`
    lifecycle, event-loop internals. `handler_cls` is the relay's
    bound handler class — its `push_hub` / `fleet` class attributes are
    read per-request, so `enable_fleet()` after construction works
    exactly as on the threaded tier."""

    def __init__(self, server_address, handler_cls, *,
                 push_hub=None,
                 handler_threads: int = HANDLER_THREADS,
                 max_pending: int = MAX_PENDING,
                 read_timeout_s: float = READ_TIMEOUT_S,
                 write_timeout_s: float = WRITE_TIMEOUT_S,
                 max_header_bytes: int = MAX_HEADER_BYTES):
        self.handler_cls = handler_cls
        self.push_hub = push_hub
        self.handler_threads = int(handler_threads)
        self.max_pending = int(max_pending)
        self.read_timeout_s = float(read_timeout_s)
        self.write_timeout_s = float(write_timeout_s)
        self.max_header_bytes = int(max_header_bytes)

        self._lsock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._lsock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._lsock.bind(server_address)
        self._lsock.listen(1024)
        self._lsock.setblocking(False)
        self.server_address = self._lsock.getsockname()

        self._sel = selectors.DefaultSelector()
        self._sel.register(self._lsock, selectors.EVENT_READ, "accept")
        # Cross-thread wakeups (pool completions, hub wakeups,
        # shutdown): a socketpair the selector always watches.
        self._waker_r, self._waker_w = socket.socketpair()
        self._waker_r.setblocking(False)
        self._sel.register(self._waker_r, selectors.EVENT_READ, "waker")

        self._pool = None  # lazy: no threads until the first dispatch
        self._pool_lock = threading.Lock()
        self._conns: Dict[socket.socket, _Conn] = {}
        # Connections with a LIVE deadline (READ/WRITE states). Parked
        # and dispatched conns leave this set, so the per-tick timeout
        # and sweep scans cost O(active requests), not O(open
        # connections) — at 10^4 parked subscriptions the difference
        # is the whole wake-latency budget (measured: the O(n) scans
        # tripled push p50 before this split).
        self._active: set = set()
        self._done: deque = deque()  # (conn, response_bytes)
        self._done_lock = threading.Lock()
        self._inflight = 0
        self._stopping = threading.Event()
        self._stopped = threading.Event()
        if push_hub is not None:
            push_hub.on_wake = self._on_hub_wake

    # -- lifecycle (socketserver-compatible surface) --

    def serve_forever(self) -> None:
        try:
            while not self._stopping.is_set():
                self._tick()
        finally:
            self._teardown()
            self._stopped.set()

    def shutdown(self) -> None:
        self._stopping.set()
        self._wake()
        self._stopped.wait(timeout=10.0)

    def server_close(self) -> None:
        # serve_forever's teardown closed the sockets; this mops up a
        # never-started server.
        if not self._stopped.is_set():
            self._teardown()

    def _teardown(self) -> None:
        # Flush responses that are already queued (hub.close() ran
        # just before shutdown and resolved every parked poll) with a
        # short bounded grace, then close everything.
        deadline = time.monotonic() + 1.0
        self._drain_done()
        while (time.monotonic() < deadline
               and any(c.state == _WRITE for c in self._conns.values())):
            for c in [c for c in self._conns.values() if c.state == _WRITE]:
                self._try_write(c)
            time.sleep(0.01)
        for conn in list(self._conns.values()):
            self._close(conn, reason="shutdown", quiet=True)
        for s in (self._lsock, self._waker_r, self._waker_w):
            try:
                s.close()
            except OSError:
                pass
        with self._pool_lock:
            pool, self._pool = self._pool, None
        if pool is not None:
            pool.shutdown(wait=False)
        try:
            self._sel.close()
        except Exception:  # noqa: BLE001
            pass

    # -- the loop --

    def _tick(self) -> None:
        timeout = self._next_timeout()
        for key, _mask in self._sel.select(timeout):
            if key.data == "accept":
                self._accept()
            elif key.data == "waker":
                try:
                    while self._waker_r.recv(4096):
                        pass
                except (BlockingIOError, OSError):
                    pass
            else:
                conn: _Conn = key.data
                if conn.state == _WRITE:
                    self._try_write(conn)
                else:
                    self._on_readable(conn)
        self._drain_done()
        self._sweep_deadlines()
        if self.push_hub is not None:
            self.push_hub.expire_due()
            self._drain_done()

    def _next_timeout(self) -> float:
        now = time.monotonic()
        nxt = now + 0.5
        for c in self._active:
            if c.deadline < nxt:
                nxt = c.deadline
        if self.push_hub is not None:
            hd = self.push_hub.next_deadline()
            if hd is not None and hd < nxt:
                nxt = hd
        return max(0.0, nxt - now)

    def _wake(self) -> None:
        try:
            self._waker_w.send(b"\0")
        except OSError:
            pass

    # -- accept / read / parse --

    def _accept(self) -> None:
        for _ in range(64):  # bounded burst per tick
            try:
                sock, addr = self._lsock.accept()
            except (BlockingIOError, OSError):
                return
            sock.setblocking(False)
            conn = _Conn(sock, addr, time.monotonic(), self.read_timeout_s)
            self._conns[sock] = conn
            self._active.add(conn)
            self._sel.register(sock, selectors.EVENT_READ, conn)
            metrics.inc("evolu_conn_accepted_total")
            metrics.set_gauge("evolu_conn_open", len(self._conns))

    def _on_readable(self, conn: _Conn) -> None:
        try:
            data = conn.sock.recv(_RECV_CHUNK)
        except (BlockingIOError, InterruptedError):
            return
        except OSError:
            self._close(conn, reason="error")
            return
        if not data:
            self._close(conn, reason="hup")
            return
        if conn.state != _READ:
            # Bytes past a complete request are DISCARDED, never
            # buffered (review finding: a parked subscriber streaming
            # data used to grow conn.buf without bound — the header
            # cap and read deadline don't apply past _READ). Both
            # tiers speak HTTP/1.0 close-after-response, so tolerate
            # a bounded trickle (a declared body the handler won't
            # read — the threaded tier's kernel buffer analog) and
            # close past it.
            conn.postreq += len(data)
            if conn.postreq > 65536:
                self._close(conn, reason="error")
            return
        conn.buf += data
        self._advance_parse(conn)

    def _advance_parse(self, conn: _Conn) -> None:
        if conn.state != _READ:
            return
        if conn.header_end < 0:
            idx = conn.buf.find(b"\r\n\r\n", conn.scan_from)
            if idx < 0:
                conn.scan_from = max(0, len(conn.buf) - 3)
                if len(conn.buf) > self.max_header_bytes:
                    self._respond_inline(
                        conn, frame_response(431, [("Content-Length", "0")]),
                        counted="header_overflow")
                return
            if idx + 4 > self.max_header_bytes:
                # The budget applies to COMPLETE header sections too —
                # arrival in one segment must not bypass the cap.
                self._respond_inline(
                    conn, frame_response(431, [("Content-Length", "0")]),
                    counted="header_overflow")
                return
            conn.header_end = idx + 4
            conn.content_length = self._parse_content_length(
                bytes(conn.buf[:idx]))
            # Push polls are GETs with no body semantics: intercept on
            # the headers alone, BEFORE any body-size decision — else
            # a poll with an absurd Content-Length would ride the
            # headers-only dispatch below into the bounded pool and
            # PARK a handler thread there (review finding: eight such
            # requests starve the whole pool; "a poller is never
            # pinned" must hold on this path too).
            if self._maybe_push(conn, bytes(conn.buf[:conn.header_end])):
                return
            from evolu_tpu.server.relay import MAX_BODY_BYTES

            if conn.content_length > MAX_BODY_BYTES:
                # Dispatch headers-only NOW: the handler's own length
                # check answers 413 without reading the body — the
                # tier never buffers an oversized declaration.
                self._dispatch(conn, bytes(conn.buf[:conn.header_end]))
                return
        total = conn.header_end + conn.content_length
        if len(conn.buf) < total:
            return
        self._dispatch(conn, bytes(conn.buf[:total]))

    @staticmethod
    def _parse_content_length(header_blob: bytes) -> int:
        """Best-effort Content-Length for FRAMING only (how many body
        bytes to buffer before dispatch). The handler re-parses headers
        itself and owns the 400-on-malformed answer — an unparsable
        value frames as 0 so the request dispatches immediately."""
        for line in header_blob.split(b"\r\n")[1:]:
            if line[:15].lower() == b"content-length:":
                try:
                    n = int(line[15:].strip())
                except ValueError:
                    return 0
                return n if n >= 0 else 0
        return 0

    # -- dispatch to the bounded handler pool --

    def _ensure_pool(self):
        with self._pool_lock:
            if self._pool is None:
                from concurrent.futures import ThreadPoolExecutor

                self._pool = ThreadPoolExecutor(
                    max_workers=self.handler_threads,
                    thread_name_prefix="evolu-conn-handler",
                )
            return self._pool

    def _dispatch(self, conn: _Conn, raw: bytes) -> None:
        if self._inflight >= self.max_pending:
            # The loop's own admission bound: shedding here (the
            # scheduler-backpressure shape) is what keeps a request
            # flood from buffering without bound ahead of the pool.
            metrics.inc("evolu_conn_shed_total")
            self._respond_inline(
                conn,
                frame_response(503, [("Retry-After", "1"),
                                     ("Content-Length", "0")]))
            return
        conn.state = _DISPATCHED
        conn.buf = bytearray()  # the raw copy owns the bytes now
        self._active.discard(conn)
        self._sel.unregister(conn.sock)
        self._inflight += 1
        metrics.set_gauge("evolu_conn_dispatch_pending", self._inflight)
        handler_cls, addr = self.handler_cls, conn.addr

        def job():
            try:
                out = serve_buffered(handler_cls, raw, addr)
            except BaseException as e:  # noqa: BLE001 - never lose a conn
                log("dev", "conn dispatch failed", error=repr(e))
                out = frame_response(500, [("Content-Length", "0")])
            with self._done_lock:
                self._done.append((conn, out))
            self._wake()

        self._ensure_pool().submit(job)

    def _drain_done(self) -> None:
        while True:
            with self._done_lock:
                if not self._done:
                    return
                conn, out = self._done.popleft()
            if conn.sock not in self._conns:
                continue  # closed while handling (client hangup)
            if conn.state == _DISPATCHED:
                self._inflight -= 1
                metrics.set_gauge("evolu_conn_dispatch_pending",
                                  self._inflight)
                self._sel.register(conn.sock, selectors.EVENT_WRITE, conn)
            elif conn.state == _PARKED:
                self._sel.modify(conn.sock, selectors.EVENT_WRITE, conn)
            else:
                continue
            conn.state = _WRITE
            conn.outbuf = memoryview(out)
            conn.outpos = 0
            conn.deadline = time.monotonic() + self.write_timeout_s
            self._active.add(conn)
            self._try_write(conn)

    # -- push long-polls, handled in-loop --

    def _maybe_push(self, conn: _Conn, raw: bytes) -> bool:
        """Park a `GET /push/poll` without a thread. True when this
        request was fully handled (or parked) here. Anything the loop
        can't answer on its own terms — malformed query (400), hub
        disabled (404) — falls through to the pool, where the threaded
        tier's own handler code answers it byte-identically."""
        line_end = raw.find(b"\r\n")
        parts = raw[:line_end].split(b" ")
        if len(parts) != 3 or parts[0] != b"GET":
            return False
        try:
            target = parts[1].decode("latin-1")
        except ValueError:
            return False
        if not target.startswith("/push/poll"):
            return False
        hub = self.push_hub
        if hub is None:
            return False  # pool → handler → 404
        from urllib.parse import urlsplit

        from evolu_tpu.server import push as push_mod

        sp = urlsplit(target)
        try:
            owner, node, cursor, timeout, tags = \
                push_mod.parse_poll_query(sp.query)
        except ValueError:
            return False  # pool → handler → 400, byte-identical
        metrics.inc("evolu_relay_requests_total", endpoint="/push/poll")
        fleet = getattr(self.handler_cls, "fleet", None)
        if fleet is not None:
            resp = _push_fleet_route(fleet, owner, target)
            if resp is not None:
                self._respond_inline(conn, resp)
                return True
        try:
            kind, val = hub.park(owner, node, cursor, timeout, token=conn,
                                 tags=tags)
        except push_mod.HubFull as e:
            # _fmt_retry, not str(): the threaded tier formats through
            # scheduler.format_retry_after ("1", not "1.0") and the
            # tiers must stay byte-identical on this answer too.
            self._respond_inline(conn, frame_response(
                503, [("Retry-After", _fmt_retry(e.retry_after)),
                      ("Content-Length", "0")]))
            return True
        if kind == "now":
            self._respond_inline(conn, _frame_poll(val))
            return True
        conn.state = _PARKED
        conn.buf = bytearray()
        self._active.discard(conn)  # the hub owns the park deadline
        # Stay registered for EVENT_READ: a parked client hanging up
        # (recv → b"") must free the subscription immediately.
        return True

    def _on_hub_wake(self, token, body: bytes) -> None:
        """Installed as PushHub.on_wake: called from ANY thread with a
        parked connection's response."""
        with self._done_lock:
            self._done.append((token, _frame_poll(body)))
        self._wake()

    # -- write / close / sweep --

    def _respond_inline(self, conn: _Conn, out: bytes,
                        counted: Optional[str] = None) -> None:
        if counted:
            metrics.inc("evolu_conn_closed_total", reason=counted)
        conn.state = _WRITE
        conn.outbuf = memoryview(out)
        conn.outpos = 0
        conn.deadline = time.monotonic() + self.write_timeout_s
        self._active.add(conn)
        self._sel.modify(conn.sock, selectors.EVENT_WRITE, conn)
        self._try_write(conn)

    def _try_write(self, conn: _Conn) -> None:
        try:
            while conn.outpos < len(conn.outbuf):
                n = conn.sock.send(conn.outbuf[conn.outpos:])
                if n == 0:
                    break
                conn.outpos += n
                conn.deadline = time.monotonic() + self.write_timeout_s
        except (BlockingIOError, InterruptedError):
            return
        except OSError:
            self._close(conn, reason="hup")
            return
        if conn.outpos >= len(conn.outbuf):
            self._close(conn, reason="done")

    def _close(self, conn: _Conn, reason: str, quiet: bool = False) -> None:
        if conn.sock not in self._conns:
            return
        if conn.state == _PARKED and self.push_hub is not None:
            self.push_hub.cancel(conn)
        del self._conns[conn.sock]
        self._active.discard(conn)
        try:
            self._sel.unregister(conn.sock)
        except (KeyError, ValueError):
            pass
        try:
            conn.sock.close()
        except OSError:
            pass
        if not quiet:
            metrics.inc("evolu_conn_closed_total", reason=reason)
            metrics.set_gauge("evolu_conn_open", len(self._conns))

    def _sweep_deadlines(self) -> None:
        now = time.monotonic()
        overdue = [c for c in self._active if c.deadline <= now]
        for conn in overdue:
            self._close(conn, reason=("read_timeout" if conn.state == _READ
                                      else "write_timeout"))

    # -- observability --

    def stats_payload(self) -> dict:
        return {
            "tier": "eventloop",
            "open_connections": len(self._conns),
            "dispatch_pending": self._inflight,
            "handler_threads": self.handler_threads,
            "accepted_total": metrics.get_counter("evolu_conn_accepted_total"),
            "shed_total": metrics.get_counter("evolu_conn_shed_total"),
            "closed_total": {
                r: metrics.get_counter("evolu_conn_closed_total", reason=r)
                for r in ("done", "hup", "read_timeout", "write_timeout",
                          "header_overflow", "error", "shutdown")
            },
        }


def _frame_poll(body: bytes) -> bytes:
    return frame_response(200, [
        ("Content-Type", "application/json"),
        ("Content-Length", str(len(body))),
    ], body)


def _push_fleet_route(fleet, owner: str, target: str) -> Optional[bytes]:
    """Fleet placement for a push poll: a subscription lives at the
    owner's PLACED relay (where that owner's mutations are served and
    hub-notified). Non-placed polls are 307'd to it — in forward mode
    too: proxying a long-poll would pin a poller on the hop for the
    park's whole duration, exactly what this tier exists to avoid
    (docs/FLEET.md). None → placed locally, park here."""
    from evolu_tpu.server.fleet import FleetNotReady

    try:
        action, peer = fleet.route(owner)
    except FleetNotReady as e:
        return frame_response(503, [
            ("Retry-After", _fmt_retry(e.retry_after)),
            ("Content-Length", "0")])
    if action == "local":
        return None
    metrics.inc("evolu_push_redirects_total")
    return frame_response(307, [("Location", peer + target),
                                ("Content-Length", "0")])


def _fmt_retry(seconds: float) -> str:
    from evolu_tpu.server.scheduler import format_retry_after

    return format_retry_after(seconds)
