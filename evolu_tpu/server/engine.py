"""Batch reconcile engine — many owners' sync rounds in one device pass.

The reference relay handles one user per HTTP request, inserting and
hashing message-by-message (apps/server/src/index.ts:148-159). This
engine takes a whole batch of SyncRequests (config 3: 1M messages
across 1k owners), and:

1. set-diffs incoming timestamps against storage in bulk SQL (the
   INSERT OR IGNORE dedup, batched via a temp-table join);
2. hashes every new timestamp and reduces per-(owner, minute) XOR
   deltas on device (`owner_minute_segments` over int32 owner/minute
   key pairs, sharded over the mesh; an owner bigger than an even
   shard's worth of rows row-splits across shards — safe because the
   decoder XOR-merges repeated (owner, minute) partials exactly);
3. applies the deltas to each owner's sparse tree, persists, and
   answers each request with the standard diff response.

The relay is E2EE-blind, so this touches only timestamps and
ciphertext blobs — the LWW cell merge happens client-side.
"""

from __future__ import annotations

import functools
import os
import time
from contextlib import contextmanager
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P
from evolu_tpu.ops import shard_map

from evolu_tpu.core.merkle import apply_prefix_xors, merkle_tree_to_string
from evolu_tpu.ops import bucket_size, start_host_transfer, to_host_many, with_x64
from evolu_tpu.ops.encode import timestamp_hashes
from evolu_tpu.ops.host_parse import parse_packed_timestamps, parse_timestamp_strings
from evolu_tpu.ops.merkle_ops import decode_owner_minute_deltas, owner_minute_segments
from evolu_tpu.parallel.mesh import (
    OWNERS_AXIS,
    assign_owners_to_shards,
    create_mesh,
    put_sharded,
    require_single_process,
    sharding,
)
from evolu_tpu.obs import anatomy, flight, ledger, metrics
from evolu_tpu.parallel.reconcile import xor_allreduce
from evolu_tpu.server.relay import RelayStore
from evolu_tpu.utils.log import log, span
from evolu_tpu.sync import protocol

# Every compiled Merkle kernel, for the recompile fence: the scheduler
# pins `merkle_jit_cache_size()` flat across varying micro-batch sizes
# (bucket-stable shapes mean jit compiles per BUCKET, never per batch).
_JIT_KERNELS: List = []


def merkle_jit_cache_size() -> int:
    """Total jit-cache entries across the engine's compiled kernels.
    `_cache_size` is a private jax surface (the same one bench.py's
    liveness fence uses); if a jax upgrade drops it, degrade to 0 so
    only the fence test fails loudly, not production callers."""
    return sum(getattr(k, "_cache_size", lambda: 0)() for k in _JIT_KERNELS)


# Recompile sentinel (ISSUE 15 satellite): last-observed cache sizes,
# diffed after each scheduler batch. Single-writer by construction —
# only the scheduler's dispatcher thread calls observe_jit_caches.
_JIT_SENTINEL_SIZES: Dict[str, int] = {}


def observe_jit_caches(batch_rows: int = 0) -> Dict[str, int]:
    """The CLAUDE.md recompile fence, observable in production: export
    `evolu_jit_cache_size{cache}` gauges and grow
    `evolu_jit_recompiles_total{cache}` by the diff of
    `merkle_jit_cache_size()` / `mesh_jit_cache_size()` since the last
    batch. Growth also drops a flight-recorder event naming the batch
    bucket shape that triggered it — the post-mortem answer to "which
    shape broke bucket stability". The FIRST observation is the
    baseline (warm-up compiles between observation 1 and 2 count;
    steady-state traffic within a bucket must then stay flat —
    test-pinned). Returns {cache: size}."""
    from evolu_tpu.ops.winner_cache import mesh_jit_cache_size

    sizes = {"merkle": merkle_jit_cache_size(),
             "mesh": mesh_jit_cache_size()}
    for cache, size in sizes.items():
        metrics.set_gauge("evolu_jit_cache_size", size, cache=cache)
        prev = _JIT_SENTINEL_SIZES.get(cache)
        if prev is not None and size > prev:
            metrics.inc("evolu_jit_recompiles_total", size - prev,
                        cache=cache)
            flight.record(
                "kernel:jit", "jit cache grew", cache=cache,
                new_entries=size - prev, total_entries=size,
                batch_rows=batch_rows,
                bucket_rows=bucket_size(max(1, batch_rows)),
            )
        _JIT_SENTINEL_SIZES[cache] = size
    return sizes


def _merkle_shard_kernel(millis, counter, node, valid, owner_ix):
    """Per-shard (owner, minute) XOR deltas + allreduced batch digest
    (`owner_minute_segments` is shared with the client reconcile
    kernel, parallel/reconcile.py)."""
    hashes = jnp.where(valid, timestamp_hashes(millis, counter, node), jnp.uint32(0))
    out = owner_minute_segments(owner_ix, millis, hashes, valid)
    digest = xor_allreduce(jax.lax.reduce(hashes, jnp.uint32(0), jnp.bitwise_xor, (0,)))
    return (*out, digest)


@functools.lru_cache(maxsize=None)
def _compiled_merkle_kernel(mesh: Mesh):
    spec = P(OWNERS_AXIS)
    fn = jax.jit(
        shard_map(
            _merkle_shard_kernel,
            mesh=mesh,
            in_specs=(spec,) * 5,
            out_specs=(spec, spec, spec, spec, spec, P()),
            check_vma=False,
        )
    )
    _JIT_KERNELS.append(fn)
    return fn


def _compact_segments_tail(owner_ix, millis, counter, node, valid, cap):
    """ONE copy of the correctness-sensitive compaction tail shared by
    both compact kernels (the full-key and delta-encoded uploads must
    stay output-identical): hash → per-(owner, minute) segments with
    tile_local=False (the compaction cap is budgeted against DISTINCT
    keys; tile partials would multiply seg_count by up to
    shard_size/8192 and flip realistic workloads into the full-pull
    fallback — r4 review finding) → stable float-real-entries-to-front
    sort (one more on-chip sort is ~ms while N rows over the tunnel is
    ~seconds) → (packed owner<<32|minute keys[cap], xors[cap],
    seg_count, digest); seg_count > cap signals overflow (caller falls
    back to the full pull)."""
    hashes = jnp.where(valid, timestamp_hashes(millis, counter, node), jnp.uint32(0))
    owner_sorted, minute_sorted, seg_end, seg_xor, valid_sorted = owner_minute_segments(
        owner_ix, millis, hashes, valid, tile_local=False
    )
    is_seg = seg_end & valid_sorted
    packed = (owner_sorted.astype(jnp.uint64) << jnp.uint64(32)) | minute_sorted.astype(
        jnp.uint32
    ).astype(jnp.uint64)
    _, packed_s, xor_s = jax.lax.sort(
        (~is_seg, packed, seg_xor), num_keys=1, is_stable=True
    )
    seg_count = jnp.sum(is_seg.astype(jnp.int32)).reshape(1)
    digest = xor_allreduce(jax.lax.reduce(hashes, jnp.uint32(0), jnp.bitwise_xor, (0,)))
    return packed_s[:cap], xor_s[:cap], seg_count, digest


def _merkle_shard_kernel_compact(k1, node, owner_ix, cap):
    """Transfer-lean variant: 20 bytes/row up (packed HLC key, node,
    int32 owner with -1 marking padding), segments compacted on device
    to `cap` entries via the shared tail above."""
    from evolu_tpu.ops.encode import unpack_ts_keys

    valid = owner_ix >= 0
    millis, counter = unpack_ts_keys(k1)
    return _compact_segments_tail(owner_ix, millis, counter, node, valid, cap)


@functools.lru_cache(maxsize=None)
def _compiled_merkle_kernel_compact(mesh: Mesh, cap: int):
    spec = P(OWNERS_AXIS)
    fn = jax.jit(
        shard_map(
            functools.partial(_merkle_shard_kernel_compact, cap=cap),
            mesh=mesh,
            in_specs=(spec,) * 3,
            out_specs=(spec, spec, spec, P()),
            check_vma=False,
        )
    )
    _JIT_KERNELS.append(fn)
    return fn


# Owner field bits in the delta-compact upload's owner|counter column.
# Owner 0xFFFF is the padding sentinel, so ≤ 65534 distinct owners per
# dispatch ride the 16-byte/row path; bigger batches (or millis spans
# ≥ 2^32 ms ≈ 49.7 days, or pre-1970 rows) keep the 20-byte kernel.
_DELTA_OWNER_BITS = 16
_DELTA_PAD_OWNER = (1 << _DELTA_OWNER_BITS) - 1


def _merkle_shard_kernel_compact_delta(dmillis, ownctr, node, base, cap):
    """The compact kernel with the key column DELTA-ENCODED against the
    batch minimum (VERDICT #9): uploads are 16 bytes/row — u32
    millis-delta, u32 owner<<16|counter (owner 0xFFFF = padding), u64
    node — instead of 20 (u64 packed HLC key + i32 owner). The tunnel
    leg is bandwidth-bound (~12-17 MB/s), so input bytes ARE its cost.
    `base` is the batch-minimum millis, replicated to every shard as a
    (1,) int64; millis reconstruct exactly (host routing guarantees
    every delta fits u32). Outputs identical to
    `_merkle_shard_kernel_compact` — the whole segment/cap/digest tail
    is the ONE shared `_compact_segments_tail`."""
    owner16 = ownctr >> jnp.uint32(16)
    valid = owner16 != jnp.uint32(_DELTA_PAD_OWNER)
    owner_ix = jnp.where(valid, owner16.astype(jnp.int32), jnp.int32(-1))
    millis = base[0] + dmillis.astype(jnp.int64)
    counter = (ownctr & jnp.uint32(0xFFFF)).astype(jnp.int32)
    return _compact_segments_tail(owner_ix, millis, counter, node, valid, cap)


@functools.lru_cache(maxsize=None)
def _compiled_merkle_kernel_compact_delta(mesh: Mesh, cap: int):
    spec = P(OWNERS_AXIS)
    fn = jax.jit(
        shard_map(
            functools.partial(_merkle_shard_kernel_compact_delta, cap=cap),
            mesh=mesh,
            in_specs=(spec, spec, spec, P()),
            out_specs=(spec, spec, spec, P()),
            check_vma=False,
        )
    )
    _JIT_KERNELS.append(fn)
    return fn


@with_x64
def owner_minute_deltas(
    mesh: Mesh, owner_rows: Dict[str, Sequence[str]], ctx=None
) -> Tuple[Dict[str, Dict[str, int]], int]:
    """Device pass: {owner: [timestamp strings]} → per-owner
    {minute-key: xor delta} plus the global batch digest.

    The device hash re-renders the node hex lowercase; the reference
    hashes the parsed node verbatim (timestampToHash of the parsed
    Timestamp, index.ts:155). Owners whose rows carry non-canonical hex
    case are quarantined to the shared host fold (the per-row case flag
    rides out of the batch parse, costing nothing extra); the other
    owners in the batch stay on device — owners are independent."""
    with span("kernel:merkle", "owner_minute_deltas",
              owners=len(owner_rows),
              n=sum(len(v) for v in owner_rows.values())):
        return _owner_minute_deltas_timed(mesh, owner_rows, ctx)


def _owner_minute_deltas_timed(mesh, owner_rows, ctx=None):
    owners = list(owner_rows)
    # ONE vectorized parse for every owner's timestamps (per-owner calls
    # would pay the numpy setup ~owners times); the per-row case flags
    # mark owners that must take the host fold.
    flat = [ts for o in owners for ts in owner_rows[o]]
    all_m, all_c, all_n, case_ok = parse_timestamp_strings(flat, with_case=True)
    owner_index: Dict[str, np.ndarray] = {}
    pos = 0
    for o in owners:
        k = len(owner_rows[o])
        owner_index[o] = np.arange(pos, pos + k)
        pos += k
    return deltas_from_columns(
        mesh, owner_index, all_m, all_c, all_n, case_ok, flat, ctx=ctx
    )


@with_x64
def deltas_from_columns(
    mesh: Mesh,
    owner_index: Dict[str, np.ndarray],
    all_m: np.ndarray,
    all_c: np.ndarray,
    all_n: np.ndarray,
    case_ok: np.ndarray,
    ts_strings: Sequence[str],
    ctx=None,
) -> Tuple[Dict[str, Dict[str, int]], int]:
    """Device Merkle pass over already-parsed columns: `owner_index`
    maps owner → row indices to hash (callers pre-filter to the rows
    that were actually inserted). Owners touching any non-canonical row
    are quarantined to the shared host fold (`ts_strings` provides the
    raw strings for it); everyone else rides one sharded dispatch."""
    return deltas_finish(
        deltas_dispatch(
            mesh, owner_index, all_m, all_c, all_n, case_ok, ts_strings, ctx=ctx
        )
    )


@with_x64
def deltas_dispatch(
    mesh: Mesh,
    owner_index: Dict[str, np.ndarray],
    all_m: np.ndarray,
    all_c: np.ndarray,
    all_n: np.ndarray,
    case_ok: np.ndarray,
    ts_strings: Sequence[str],
    ctx=None,
):
    """First half of `deltas_from_columns` — host packing, device
    dispatch, async transfer START. Returns an opaque state for
    `deltas_finish`. Between the two calls the device computes and the
    tunnel streams outputs back, so a pipelining caller can run batch
    k's SQLite work while batch k+1 is in flight here.

    With a `ctx` (parallel.mesh.MeshContext — the PR-12 sharded-engine
    path), the layout uses STABLE owner→device placement
    (`ctx.assign_stable`) instead of per-batch LPT, and records the
    per-device occupancy / padding-waste / cross-device-reduce
    telemetry. The kernels, decode, and outputs are IDENTICAL — only
    row layout changes, and the delta decoders are layout-agnostic, so
    the sharded path is byte-identical by construction (parity-pinned
    in tests/test_mesh_engine.py anyway)."""
    require_single_process("engine.deltas_from_columns")
    owners = list(owner_index)
    deltas: Dict[str, Dict[str, int]] = {o: {} for o in owners}
    digest = 0
    host_owners = [
        o for o, ix in owner_index.items() if len(ix) and not case_ok[ix].all()
    ]
    if host_owners:
        log("kernel:merkle", "non-canonical hex case: host hashing fallback",
            owners=len(host_owners))
        from evolu_tpu.core.merkle import minute_deltas_host

        for o in host_owners:
            deltas[o], d = minute_deltas_host(ts_strings[i] for i in owner_index[o])
            digest ^= d

    quarantined = set(host_owners)
    sizes = {o: len(owner_index[o]) for o in owners}
    good = [o for o in owners if o not in quarantined and sizes[o]]
    if not good:
        return (deltas, digest, good, None, None)

    owner_ix = {o: i for i, o in enumerate(good)}
    # Hot-owner split: hashing needs no cell locality, and the decoder
    # XOR-merges repeated (owner, minute) keys exactly, so an owner
    # bigger than an even shard's worth of rows splits row-wise across
    # shards instead of capping one shard's load (SURVEY.md §5).
    n_good_rows = sum(sizes[o] for o in good)
    target = max(1, -(-n_good_rows // mesh.devices.size))  # ceil
    units: Dict[Tuple[str, int], np.ndarray] = {}
    for o in good:
        ix = owner_index[o]
        if len(ix) <= target:
            units[(o, 0)] = ix
        else:
            for j, start in enumerate(range(0, len(ix), target)):
                units[(o, j)] = ix[start : start + target]
    unit_sizes = {u: len(ix) for u, ix in units.items()}
    if ctx is not None:
        shards = ctx.assign_stable(unit_sizes)
    else:
        shards = assign_owners_to_shards(unit_sizes, mesh.devices.size)
    loads = [sum(len(units[u]) for u in s) for s in shards]
    shard_size = bucket_size(max(max(loads, default=0), 1))
    total = mesh.devices.size * shard_size
    if ctx is not None:
        ctx.record_occupancy(loads, shard_size)
        # The in-kernel XOR all-reduce of the batch digest is one
        # cross-device reduction per dispatch; owners whose row-split
        # chunks landed on several devices additionally XOR-merge
        # their (owner, minute) partials in the host decode.
        ctx.record_xdev_reduce("digest")
        shard_of = {u: si for si, s in enumerate(shards) for u in s}
        split_owners = {}
        for (o, _j), si in shard_of.items():
            split_owners.setdefault(o, set()).add(si)
        for o, devs in split_owners.items():
            if len(devs) > 1:
                ctx.record_xdev_reduce("owner_delta_partials")

    # Transfer-lean upload: 20 bytes/row — packed HLC key (millis<<16 |
    # counter), node, and int32 owner with -1 marking padding. The
    # tunneled chip is bandwidth-bound, so input bytes ARE the device
    # leg's cost (measured ~12-17 MB/s effective).
    k1 = np.zeros(total, np.uint64)
    node = np.zeros(total, np.uint64)
    oix = np.full(total, -1, np.int32)
    pos_by_shard = [si * shard_size for si in range(len(shards))]
    shard_of_unit = {u: si for si, shard in enumerate(shards) for u in shard}
    for u, ix in units.items():
        n = len(ix)
        si = shard_of_unit[u]
        pos = pos_by_shard[si]
        sl = slice(pos, pos + n)
        k1[sl] = (all_m[ix].astype(np.uint64) << np.uint64(16)) | all_c[ix].astype(
            np.uint64
        )
        node[sl] = all_n[ix]
        oix[sl] = owner_ix[u[0]]
        pos_by_shard[si] = pos + n

    cap = bucket_size(max(shard_size // 8, 64))
    shd = sharding(mesh)
    real = oix >= 0
    millis = (k1 >> np.uint64(16)).astype(np.int64)
    real_millis = millis[real]
    base = int(real_millis.min()) if len(real_millis) else 0
    # `millis_span`, not `span`: this module's `span` is the timing
    # context manager from utils.log.
    millis_span = (int(real_millis.max()) - base) if len(real_millis) else 0
    # Delta-compact admission (host-side, static): batch span under
    # 2^32 ms, owner indexes under the 16-bit padding sentinel, and no
    # wrapped millis. The k1 packing casts signed millis to u64, so a
    # pre-1970 value surfaces HERE as ~2^48 (never negative — a
    # `base >= 0` guard would be dead code); both kernels treat the
    # wrapped value identically, but wrapped batches keep the full-key
    # kernel so admission stays a statement about true timestamps.
    # EVOLU_COMPACT_DELTA=0 pins the 20 B/row kernel (the before/after
    # bytes measurement).
    max_real = base + millis_span
    use_delta = (
        os.environ.get("EVOLU_COMPACT_DELTA", "1") != "0"
        and millis_span < (1 << 32)
        and max_real < (1 << 47)  # wrapped pre-1970 lands near 2^48
        and len(good) < _DELTA_PAD_OWNER
    )
    if use_delta:
        dmillis = np.where(real, millis - base, 0).astype(np.uint32)
        ownctr = np.where(
            real,
            (oix.astype(np.uint32) << np.uint32(16))
            | (k1 & np.uint64(0xFFFF)).astype(np.uint32),
            np.uint32(_DELTA_PAD_OWNER << 16),
        )
        metrics.inc("evolu_engine_compact_upload_bytes_total",
                    16 * total, variant="delta")
        args = [put_sharded(a, shd) for a in (dmillis, ownctr, node)]
        base_arr = jax.device_put(
            np.array([base], np.int64),
            jax.sharding.NamedSharding(mesh, P()),
        )
        outs = start_host_transfer(
            *_compiled_merkle_kernel_compact_delta(mesh, cap)(*args, base_arr)
        )
    else:
        metrics.inc("evolu_engine_compact_upload_bytes_total",
                    20 * total, variant="full")
        args = [put_sharded(a, shd) for a in (k1, node, oix)]
        outs = start_host_transfer(*_compiled_merkle_kernel_compact(mesh, cap)(*args))
    return (deltas, digest, good, outs, (k1, node, oix, mesh, cap))


@with_x64
def deltas_finish(state) -> Tuple[Dict[str, Dict[str, int]], int]:
    """Second half: materialize the (mostly arrived) compact outputs
    and decode the per-(owner, minute) deltas. If any shard produced
    more segments than the compaction cap, re-run the full-width
    kernel and decode every row (rare: means distinct (owner, minute)
    pairs exceed an eighth of the shard's rows)."""
    deltas, digest, good, outs, extra = state
    if outs is None:
        return deltas, digest
    if hasattr(outs, "result"):
        # A background-thread pull started at dispatch time (the
        # tunnel's copy_to_host_async is a no-op; bytes only move
        # during a blocking pull, whose socket wait drops the GIL —
        # so a thread is what actually overlaps transfer with host
        # work).
        packed, xors, counts, dev_digest = outs.result()
    else:
        packed, xors, counts, dev_digest = to_host_many(*outs)
    k1, node, oix, mesh, cap = extra
    counts = np.asarray(counts)
    if (counts > cap).any():
        log("kernel:merkle", "segment compaction overflow: full-width pull",
            cap=cap, max_count=int(counts.max()))
        millis = (k1 >> np.uint64(16)).astype(np.int64)
        counter = (k1 & np.uint64(0xFFFF)).astype(np.int32)
        valid = oix >= 0
        shd = sharding(mesh)
        args = [
            put_sharded(a, shd)
            for a in (millis, counter, node, valid,
                      np.maximum(oix, 0).astype(np.int64))
        ]
        owner_sorted, minute_sorted, seg_end, seg_xor, valid_sorted, dev_digest = (
            to_host_many(*_compiled_merkle_kernel(mesh)(*args))
        )
        by_ix = decode_owner_minute_deltas(
            owner_sorted, minute_sorted, seg_end, seg_xor, valid_sorted
        )
    else:
        from evolu_tpu.core.merkle import minutes_base3
        from evolu_tpu.core.murmur import to_int32

        by_ix: Dict[int, Dict[str, int]] = {}
        key_cache: Dict[int, str] = {}
        packed = np.asarray(packed)
        xors = np.asarray(xors)
        for si in range(len(counts)):
            c = int(counts[si])
            base = si * cap
            for p, x in zip(
                packed[base : base + c].tolist(), xors[base : base + c].tolist()
            ):
                o_ix = p >> 32
                minute = p & 0xFFFFFFFF
                if minute >= 1 << 31:  # undo the uint32 bit carriage of
                    minute -= 1 << 32  # the JS |0-wrapped int32 minute
                key = key_cache.get(minute)
                if key is None:
                    key = key_cache[minute] = minutes_base3(minute * 60000)
                d = by_ix.setdefault(o_ix, {})
                d[key] = to_int32(d.get(key, 0) ^ int(x))
    for o_ix, d in by_ix.items():
        deltas[good[o_ix]] = d
    return deltas, digest ^ int(dev_digest)


def _ledger_count_pass(requests, inserted_by_owner) -> None:
    """Conservation-ledger terminal classification for ONE committed
    engine pass: per owner, `inserted` rows were new (was-new flags /
    set-diff), and everything else the owner submitted this pass —
    including rows the in-batch dedup dropped before they reached a
    shard buffer — terminates at store.duplicate. Call ONLY after the
    shard transactions committed: a poisoned (rolled-back) pass must
    post nothing, or the scheduler's singleton retry would
    double-count."""
    totals: Dict[str, int] = {}
    for r in requests:
        if r.messages:
            totals[r.user_id] = totals.get(r.user_id, 0) + len(r.messages)
    for o, total in totals.items():
        ins = int(inserted_by_owner.get(o, 0))
        ledger.count(ledger.STORE_INSERTED, ins, owner=o)
        ledger.count(ledger.STORE_DUPLICATE, total - ins, owner=o)


def _pack_rows(ts_list, contents):
    """Pack one shard's rows into flat buffers. Per-string width check
    BEFORE packing: a total-length check alone would accept
    ["", "<two stamps concatenated>"] and commit rows with shifted
    timestamp/content pairing (same invariant as
    parse_timestamp_strings)."""
    n = len(ts_list)
    if (np.fromiter(map(len, ts_list), np.int64, count=n) != 46).any():
        raise ValueError("non-canonical timestamp width in batch")
    ts_packed = "".join(ts_list).encode("ascii")
    lens = np.fromiter(map(len, contents), np.int32, count=n)
    return ts_packed, b"".join(contents), lens


class _PackedRows:
    """Lazy timestamp-string accessor over per-shard packed 46-byte
    buffers (used only for the rare non-canonical host fold)."""

    def __init__(self, buffers: List[bytes], offsets: List[int]):
        self._buffers = buffers
        self._offsets = offsets

    def __getitem__(self, i: int) -> str:
        import bisect

        j = bisect.bisect_right(self._offsets, i) - 1
        local = i - self._offsets[j]
        return self._buffers[j][local * 46 : (local + 1) * 46].decode("ascii")


class BatchReconciler:
    """Reconcile a batch of SyncRequests against one RelayStore or a
    ShardedRelayStore (parallel per-shard ingest).

    With a `write_behind` queue attached (PR-11,
    `storage/write_behind.py`), `run_batch_wire` serves from
    device-derived in-memory state instead: the batch's Merkle deltas
    fold onto per-owner authoritative trees held by the queue, the
    packed row buffers are ACKed into the durable log, and SQLite
    materialization happens on the queue's background drain thread —
    off the serving path. Responses that need stored MESSAGES (a
    non-empty tree diff) wait on the owner's drain watermark first, so
    every byte served from SQLite is committed state. The offline
    entry points (`reconcile*`) stay synchronous — deferral is a
    property of the live serving path only."""

    def __init__(
        self, store, mesh: Optional[Mesh] = None, write_behind=None, mesh_ctx=None
    ):
        self.store = store
        # PR-12 sharded-engine path: a parallel.mesh.MeshContext pins
        # the mesh AND switches every device layout this reconciler
        # builds to stable owner→device placement (deltas_dispatch's
        # `ctx=` leg). None = the per-batch LPT layout (the default
        # until the parity gate is green in a deployment —
        # Config.mesh_engine).
        self.mesh_ctx = mesh_ctx
        if mesh_ctx is not None and mesh is None:
            mesh = mesh_ctx.mesh
        self.mesh = mesh or create_mesh()
        self.write_behind = write_behind
        self._executor = None
        self._pull_pool = None

    def _new_messages(
        self, requests: Sequence[protocol.SyncRequest]
    ) -> Dict[str, List[protocol.EncryptedCrdtMessage]]:
        """Bulk dedup: which (timestamp, userId) pairs are not yet stored.
        Batch equivalent of per-row INSERT OR IGNORE changes==1
        (index.ts:153-158). Duplicates inside the batch dedup here too."""
        db = self.store.db
        seen: set = set()
        incoming: List[Tuple[str, str, protocol.EncryptedCrdtMessage]] = []
        for r in requests:
            for m in r.messages:
                k = (m.timestamp, r.user_id)
                if k not in seen:
                    seen.add(k)
                    incoming.append((m.timestamp, r.user_id, m))
        if not incoming:
            return {}
        with db.transaction():
            db.exec('CREATE TEMP TABLE IF NOT EXISTS "__incoming" ("t" TEXT, "u" TEXT)')
            db.run('DELETE FROM "__incoming"')
            db.run_many('INSERT INTO "__incoming" VALUES (?, ?)', [(t, u) for t, u, _ in incoming])
            rows = db.exec_sql_query(
                'SELECT i."t" AS t, i."u" AS u FROM "__incoming" i '
                'JOIN "message" m ON m."timestamp" = i."t" AND m."userId" = i."u"'
            )
            db.run('DELETE FROM "__incoming"')
        existing = {(r["t"], r["u"]) for r in rows}
        out: Dict[str, List[protocol.EncryptedCrdtMessage]] = {}
        for t, u, m in incoming:
            if (t, u) not in existing:
                out.setdefault(u, []).append(m)
        return out

    def reconcile(
        self, requests: Sequence[protocol.SyncRequest]
    ) -> List[protocol.SyncResponse]:
        """One batched pass; responses align with `requests` order.
        End state is identical to running `store.sync` per request."""
        trees, strings = self._ingest(requests)
        return self._respond(requests, trees, strings)

    def _ingest(self, requests):
        """The batched ingest, routed by store shape → (trees, strings).
        ONE copy shared by `reconcile` and `reconcile_wire`."""
        from evolu_tpu.server.relay import ShardedRelayStore

        metrics.inc("evolu_engine_store_passes_total", path="oneshot")
        strings: Dict[str, str] = {}
        db = getattr(self.store, "db", None)
        if isinstance(self.store, ShardedRelayStore):
            if all(hasattr(s.db, "relay_insert_packed") for s in self.store.shards):
                trees = self._ingest_packed(requests, strings)
            else:
                # Sharded python-backend: per-request per-shard path.
                trees = {
                    r.user_id: self.store.add_messages(r.user_id, r.messages)
                    for r in requests
                }
        elif db is not None and hasattr(db, "relay_insert_packed"):
            trees = self._ingest_packed(requests, strings)
        elif db is not None:
            trees = self._ingest_generic(requests, strings)
        else:
            # Generic store (RelayStore surface, no `.db` SQL handle):
            # per-request ingest; the respond side degrades likewise
            # (`_respond_wire`'s object fallback).
            trees = {
                r.user_id: self.store.add_messages(r.user_id, r.messages)
                for r in requests
            }
        return trees, strings

    def _shards(self):
        from evolu_tpu.server.relay import ShardedRelayStore

        if isinstance(self.store, ShardedRelayStore):
            return self.store.shards, self.store.shard_index
        return [self.store], (lambda _u: 0)

    def _pool(self, n: int):
        """One worker per storage shard (sized to the store, not to the
        current batch, so a small first batch can't cap later ones)."""
        if self._executor is None and n > 1:
            from concurrent.futures import ThreadPoolExecutor

            self._executor = ThreadPoolExecutor(max_workers=n, thread_name_prefix="evolu-ingest")
        return self._executor

    def _pull_executor(self):
        if self._pull_pool is None:
            from concurrent.futures import ThreadPoolExecutor

            self._pull_pool = ThreadPoolExecutor(max_workers=1, thread_name_prefix="evolu-pull")
        return self._pull_pool

    def _map_shards(self, fn, live, n_stores):
        """Run fn(si) per live shard — parallel when a pool exists.
        Waits for EVERY worker before raising: a rollback while a
        worker is still running would let its insert land in autocommit
        mode — committed rows outside any tree."""
        pool = self._pool(n_stores)
        if pool is not None and len(live) > 1:
            futures = [pool.submit(fn, si) for si in live]
            results, first_err = [], None
            for f in futures:
                try:
                    results.append(f.result())
                except BaseException as e:  # noqa: BLE001
                    first_err = first_err or e
            if first_err is not None:
                raise first_err
            return results
        return [fn(si) for si in live]

    @contextmanager
    def _shard_transactions(self, stores, live):
        """One open transaction per live shard, rolled back together on
        error, committed together on exit (first commit error wins).
        Short-lock begin/commit (not the lock-holding context manager)
        so worker threads can execute inside them; each shard has
        exactly one logical writer (its worker)."""
        begun: List[int] = []
        try:
            for si in live:
                stores[si].db.begin()
                begun.append(si)
            yield
        except BaseException:
            for si in begun:
                stores[si].db.rollback()
            raise
        commit_err: Optional[Exception] = None
        for si in begun:
            try:
                stores[si].db.commit()
            except Exception as e:  # noqa: BLE001
                commit_err = commit_err or e
        if commit_err is not None:
            raise commit_err

    def close(self) -> None:
        if self._executor is not None:
            self._executor.shutdown(wait=True)
            self._executor = None
        if self._pull_pool is not None:
            self._pull_pool.shutdown(wait=True)
            self._pull_pool = None

    def _ingest_packed(self, requests, tree_strings=None) -> Dict[str, dict]:
        """The packed columnar ingest. Per storage shard: pack the
        shard's timestamps and ciphertexts into flat buffers and INSERT
        OR IGNORE them in ONE native call (the PK dedups, including
        in-batch duplicates, with per-row was-new flags —
        index.ts:153-158 semantics), then parse the packed buffer
        natively. Shards ingest in parallel threads (the C calls drop
        the GIL). The new rows of every shard ride ONE device dispatch
        for the per-(owner, minute) hashes, and each shard's inserts +
        tree updates commit in one transaction, so rows can never
        outrun their tree. A failure anywhere rolls every uncommitted
        shard back."""
        stores, shard_index = self._shards()
        per_shard: List[List[protocol.SyncRequest]] = [[] for _ in stores]
        for r in requests:
            per_shard[shard_index(r.user_id)].append(r)
        live = [si for si, reqs in enumerate(per_shard) if any(len(r.messages) for r in reqs)]
        trees: Dict[str, dict] = {}
        if not live:
            return trees
        n_total = sum(len(r.messages) for r in requests)

        def ingest_shard(si: int):
            db = stores[si].db
            reqs = per_shard[si]
            gu = [r.user_id for r in reqs]
            gc = [len(r.messages) for r in reqs]
            n = sum(gc)
            # One flat pass over the shard's messages; everything below
            # is C-speed (map/join/fromiter) — per-message Python
            # generators here cost ~2.5s/1M (profiled).
            ts_list = [m.timestamp for r in reqs for m in r.messages]
            contents = [m.content for r in reqs for m in r.messages]
            ts_packed, content_packed, lens = _pack_rows(ts_list, contents)
            was_new = db.relay_insert_packed(gu, gc, ts_packed, content_packed, lens)
            cols = parse_packed_timestamps(ts_packed, n, with_case=True)
            return gu, gc, ts_packed, was_new, cols

        def ingest_all():
            results = self._map_shards(ingest_shard, live, len(stores))

            # Merge shard results into one flat column space.
            owner_index: Dict[str, List[np.ndarray]] = {}
            buffers, offsets = [], []
            col_parts = ([], [], [], [])
            off = 0
            for (gu, gc, ts_packed, was_new, cols) in results:
                pos = 0
                for u, k in zip(gu, gc):
                    ix = np.nonzero(was_new[pos : pos + k])[0] + (pos + off)
                    if len(ix):
                        owner_index.setdefault(u, []).append(ix)
                    pos += k
                buffers.append(ts_packed)
                offsets.append(off)
                for part, c in zip(col_parts, cols):
                    part.append(c)
                off += len(was_new)
            merged = {
                u: (v[0] if len(v) == 1 else np.concatenate(v))
                for u, v in owner_index.items()
            }
            inserted_by_owner.update((u, len(v)) for u, v in merged.items())
            all_m, all_c, all_n, case_ok = (
                (p[0] if len(p) == 1 else np.concatenate(p)) for p in col_parts
            )
            deltas_by_owner, _digest = deltas_from_columns(
                self.mesh, merged, all_m, all_c, all_n, case_ok,
                _PackedRows(buffers, offsets), ctx=self.mesh_ctx,
            )
            tree_rows: List[List[Tuple[str, str]]] = [[] for _ in stores]
            for o, deltas in deltas_by_owner.items():
                if not deltas:
                    continue
                si = shard_index(o)
                tree = apply_prefix_xors(stores[si].get_merkle_tree(o), deltas)
                trees[o] = tree
                s = merkle_tree_to_string(tree)
                if tree_strings is not None:
                    tree_strings[o] = s  # respond reuses the upsert's dump
                tree_rows[si].append((o, s))
            for si in live:
                if tree_rows[si]:
                    stores[si].db.run_many(
                        'INSERT OR REPLACE INTO "merkleTree" ("userId", "merkleTree") '
                        "VALUES (?, ?)",
                        tree_rows[si],
                    )

        inserted_by_owner: Dict[str, int] = {}
        with span("kernel:merkle", "reconcile_ingest",
                  owners=len({r.user_id for r in requests}), n=n_total,
                  shards=len(live)):
            # Transactions held across the device dispatch so inserts +
            # trees commit atomically.
            with self._shard_transactions(stores, live):
                ingest_all()
        _ledger_count_pass(requests, inserted_by_owner)
        return trees

    # -- pipelined streaming reconcile (VERDICT r2 #1) --
    #
    # `reconcile` holds each shard transaction open ACROSS the device
    # dispatch, so host and device strictly alternate. The streaming
    # path breaks the dependency: the device hashes the WHOLE batch
    # optimistically (newness is unknown until the insert), and owners
    # that turn out to contain duplicate rows get their deltas
    # recomputed host-side from the new rows only — bit-identical to
    # the fold the one-shot path does. Since the device leg then needs
    # nothing from the database, batch k+1's transfer + compute ride
    # the tunnel while batch k's C inserts/trees/commit run on the
    # host (the C calls drop the GIL).

    def start_batch(self, requests: Sequence[protocol.SyncRequest]):
        """Stage batch k+1: pack per-shard buffers, parse natively,
        dispatch the device hash of ALL rows, START the async output
        transfer. No database access happens here. The whole seam is
        one `device_dispatch` stage record (obs.anatomy): fixed tunnel
        RTT separates from the per-row slope in the stage fit, and a
        dispatch above FLOOR_FACTOR× its priced pipeline floor flags
        evolu_stage_over_floor_total."""
        t0_dispatch = time.perf_counter()
        stores, shard_index = self._shards()
        per_shard: List[List[protocol.SyncRequest]] = [[] for _ in stores]
        for r in requests:
            per_shard[shard_index(r.user_id)].append(r)

        seen: set = set()
        shard_data: Dict[int, tuple] = {}
        buffers: List[bytes] = []
        offsets: List[int] = []
        col_parts = ([], [], [], [])
        owner_rows: Dict[str, List[np.ndarray]] = {}
        live: List[int] = []
        off = 0
        for si, reqs in enumerate(per_shard):
            gu: List[str] = []
            gc: List[int] = []
            ts_list: List[str] = []
            contents: List[bytes] = []
            for r in reqs:
                # In-batch dedup up front (the one-shot path leaves it
                # to the PK): correction logic needs was_new==False to
                # mean exactly "already in the store". Same-user rows
                # stay in request order, so the kept occurrence matches
                # the row the PK would have kept.
                kept = [
                    m for m in r.messages
                    if (m.timestamp, r.user_id) not in seen
                    and not seen.add((m.timestamp, r.user_id))
                ]
                if kept:
                    gu.append(r.user_id)
                    gc.append(len(kept))
                    ts_list.extend(m.timestamp for m in kept)
                    contents.extend(m.content for m in kept)
            n = len(ts_list)
            if n == 0:
                continue
            live.append(si)
            ts_packed, content_packed, lens = _pack_rows(ts_list, contents)
            cols = parse_packed_timestamps(ts_packed, n, with_case=True)
            pos = 0
            for u, k in zip(gu, gc):
                if k:
                    owner_rows.setdefault(u, []).append(np.arange(pos, pos + k) + off)
                pos += k
            buffers.append(ts_packed)
            offsets.append(off)
            for part, c in zip(col_parts, cols):
                part.append(c)
            shard_data[si] = (gu, gc, ts_packed, content_packed, lens)
            off += n

        packed = _PackedRows(buffers, offsets)
        shard_offsets = dict(zip(live, offsets))
        dev_state = None
        if owner_rows:
            merged = {
                u: (v[0] if len(v) == 1 else np.concatenate(v))
                for u, v in owner_rows.items()
            }
            all_m, all_c, all_n, case_ok = (
                (p[0] if len(p) == 1 else np.concatenate(p)) for p in col_parts
            )
            dev_state = deltas_dispatch(
                self.mesh, merged, all_m, all_c, all_n, case_ok, packed,
                ctx=self.mesh_ctx,
            )
            if dev_state[3] is not None:
                # Start the blocking pull NOW on the pull thread: under
                # the tunnel nothing moves until a blocking pull, and
                # its socket wait releases the GIL — this is the actual
                # device/host overlap for the pipelined path.
                fut = self._pull_executor().submit(to_host_many, *dev_state[3])
                dev_state = (*dev_state[:3], fut, dev_state[4])
        anatomy.record_stage("device_dispatch",
                             time.perf_counter() - t0_dispatch, rows=off)
        return {
            "requests": requests, "live": live, "shard_data": shard_data,
            "dev": dev_state, "packed": packed, "n_total": off,
            "shard_offsets": shard_offsets,
        }

    def finish_batch(self, st, wire: bool = False) -> List:
        """Land batch k: per-shard C inserts (parallel, GIL-free),
        duplicate-owner delta recompute, tree updates, one atomic
        commit per shard — while batch k+1 flies on the device.
        `wire=True` answers in BYTES mode (`_respond_wire`) for
        consumers that only forward protobuf — the live scheduler path,
        byte-identical to encoding the object responses (test-pinned
        via `_respond_wire`'s own fence)."""
        stores, shard_index = self._shards()
        metrics.inc("evolu_engine_store_passes_total", path="stream")
        respond = self._respond_wire if wire else self._respond
        live, shard_data = st["live"], st["shard_data"]
        trees: Dict[str, dict] = {}
        strings: Dict[str, str] = {}
        if not live:
            return respond(st["requests"], trees, strings)

        def ingest_shard(si: int):
            gu, gc, ts_packed, content_packed, lens = shard_data[si]
            return si, stores[si].db.relay_insert_packed(
                gu, gc, ts_packed, content_packed, lens
            )

        # host_apply stage record (obs.anatomy): the C inserts + delta
        # decode + tree folds + commit block. The pull itself records
        # under pull_wave from to_host_many (possibly on the pull
        # thread) — shares are over summed stage walls, and the two
        # legs can overlap (documented in docs/OBSERVABILITY.md).
        t0_apply = time.perf_counter()
        with span("kernel:merkle", "reconcile_stream_finish",
                  owners=len({r.user_id for r in st["requests"]}),
                  n=st["n_total"], shards=len(live)):
            with self._shard_transactions(stores, live):
                was_new_by_shard = dict(
                    self._map_shards(ingest_shard, live, len(stores))
                )
                deltas_by_owner, _digest = deltas_finish(st["dev"])
                self._recompute_duplicate_owners(
                    st, was_new_by_shard, deltas_by_owner
                )

                tree_rows: List[List[Tuple[str, str]]] = [[] for _ in stores]
                for o, deltas in deltas_by_owner.items():
                    if not deltas:
                        continue
                    si = shard_index(o)
                    tree = apply_prefix_xors(stores[si].get_merkle_tree(o), deltas)
                    trees[o] = tree
                    s = merkle_tree_to_string(tree)
                    strings[o] = s
                    tree_rows[si].append((o, s))
                for si in live:
                    if tree_rows[si]:
                        stores[si].db.run_many(
                            'INSERT OR REPLACE INTO "merkleTree" ("userId", "merkleTree") '
                            "VALUES (?, ?)",
                            tree_rows[si],
                        )
        anatomy.record_stage("host_apply", time.perf_counter() - t0_apply,
                             rows=st["n_total"])
        # Ledger terminals AFTER the per-shard commits: per-owner
        # was-new sums classify inserted; the per-owner request totals
        # in _ledger_count_pass fold the in-batch-deduped rows into
        # store.duplicate automatically.
        ins_by_owner: Dict[str, int] = {}
        for si in live:
            gu, gc, _tsp, _cp, _lens = shard_data[si]
            was_new = was_new_by_shard[si]
            pos = 0
            for u, k in zip(gu, gc):
                ins_by_owner[u] = ins_by_owner.get(u, 0) + int(
                    np.asarray(was_new[pos : pos + k]).sum()
                )
                pos += k
        _ledger_count_pass(st["requests"], ins_by_owner)
        return respond(st["requests"], trees, strings)

    def _recompute_duplicate_owners(self, st, was_new_by_shard, deltas_by_owner) -> None:
        """The device hashed every row; owners where some rows were
        already stored get their delta dict recomputed from the NEW
        rows only — the same fold the one-shot path runs, so minute-key
        presence semantics (a minute whose new hashes XOR to zero stays
        present; a minute with only duplicate rows disappears) are
        bit-identical. Steady state has no duplicates and skips this
        entirely; a full-replay batch has no new rows and recomputes
        empty dicts — both ends are cheap."""
        from evolu_tpu.core.merkle import minute_deltas_host

        packed = st["packed"]
        offsets = st["shard_offsets"]
        # Pass 1 (steady state exits here): which owners have ANY
        # duplicate row? One cheap .all() per group, no allocations.
        affected: set = set()
        for si in st["live"]:
            gu, gc, _tsp, _cp, _lens = st["shard_data"][si]
            was_new = was_new_by_shard[si]
            pos = 0
            for u, k in zip(gu, gc):
                if not was_new[pos : pos + k].all():
                    affected.add(u)
                pos += k
        if not affected:
            return
        # Pass 2: an affected owner needs ALL its new rows (it may span
        # several request groups) — collect, then recompute once.
        new_rows: Dict[str, List[np.ndarray]] = {}
        for si in st["live"]:
            gu, gc, _tsp, _cp, _lens = st["shard_data"][si]
            was_new = was_new_by_shard[si]
            base = offsets[si]
            pos = 0
            for u, k in zip(gu, gc):
                if u in affected:
                    new_rows.setdefault(u, []).append(
                        np.nonzero(was_new[pos : pos + k])[0] + (pos + base)
                    )
                pos += k
        for u in affected:
            ix = np.concatenate(new_rows[u])
            deltas_by_owner[u], _d = minute_deltas_host(packed[i] for i in ix)

    def reconcile_stream(
        self, batches: Sequence[Sequence[protocol.SyncRequest]]
    ) -> List[List[protocol.SyncResponse]]:
        """Software-pipelined reconcile over a stream of request
        batches: batch k+1's device leg (upload, hash, output transfer)
        overlaps batch k's host leg (C inserts, trees, commit). End
        state is identical to sequential `reconcile` calls. Requires a
        packed-capable store; falls back to sequential otherwise."""
        stores, _ = self._shards()
        if not all(hasattr(s.db, "relay_insert_packed") for s in stores):
            return [self.reconcile(b) for b in batches]
        out: List[List[protocol.SyncResponse]] = []
        prev = None
        for reqs in batches:
            try:
                st = self.start_batch(reqs)
            except BaseException:
                # A bad batch k+1 must not drop the already-dispatched
                # batch k — sequential reconcile would have committed it
                # before raising; match that contract.
                if prev is not None:
                    out.append(self.finish_batch(prev))
                    prev = None
                raise
            if prev is not None:
                out.append(self.finish_batch(prev))
            prev = st
        if prev is not None:
            out.append(self.finish_batch(prev))
        return out

    def _ingest_generic(self, requests, tree_strings=None) -> Dict[str, dict]:
        """Python-backend fallback: temp-table set-diff + bulk SQL."""
        new_by_owner = self._new_messages(requests)

        # Device: per-(owner, minute) XOR deltas for all new timestamps.
        deltas_by_owner, _digest = (
            owner_minute_deltas(
                self.mesh,
                {o: [m.timestamp for m in ms] for o, ms in new_by_owner.items()},
                ctx=self.mesh_ctx,
            )
            if new_by_owner
            else ({}, 0)
        )

        # Host: bulk insert + tree updates in one transaction.
        db = self.store.db
        trees: Dict[str, dict] = {}
        with db.transaction():
            rows = [
                (m.timestamp, o, m.content)
                for o, ms in new_by_owner.items()
                for m in ms
            ]
            if rows:
                db.run_many(
                    'INSERT OR IGNORE INTO "message" ("timestamp", "userId", "content") '
                    "VALUES (?, ?, ?)",
                    rows,
                )
            for o, deltas in deltas_by_owner.items():
                tree = apply_prefix_xors(self.store.get_merkle_tree(o), deltas)
                trees[o] = tree
                s = merkle_tree_to_string(tree)
                if tree_strings is not None:
                    tree_strings[o] = s
                db.run(
                    'INSERT OR REPLACE INTO "merkleTree" ("userId", "merkleTree") VALUES (?, ?)',
                    (o, s),
                )
        _ledger_count_pass(
            requests, {o: len(ms) for o, ms in new_by_owner.items()}
        )
        return trees

    def _resolve_tree(self, user_id: str, trees, tree_strings):
        """Tree + serialized string for one owner, reusing the ingest's
        caches; owners not in `trees` (no new rows this batch — the
        cold-sync shape) read the STORED string verbatim and parse it
        once for the diff, never re-dumping (the parse→re-dump
        round-trip, ~1.25 ms per realistic owner tree, was the measured
        respond wall at 1k divergent owners — docs/BENCHMARKS.md r4).
        Mutates both caches; ONE copy shared by `_respond` and
        `_respond_wire`."""
        from evolu_tpu.core.merkle import merkle_tree_from_string

        tree = trees.get(user_id)
        if tree is None:
            if hasattr(self.store, "get_merkle_tree_string"):
                raw = self.store.get_merkle_tree_string(user_id)
                tree = merkle_tree_from_string(raw)
            else:
                tree = self.store.get_merkle_tree(user_id)
                raw = merkle_tree_to_string(tree)
            trees[user_id] = tree
            tree_strings.setdefault(user_id, raw)
        raw = tree_strings.get(user_id)
        if raw is None:
            raw = tree_strings[user_id] = merkle_tree_to_string(tree)
        return tree, raw

    def _respond(
        self, requests, trees: Dict[str, dict],
        tree_strings: Optional[Dict[str, str]] = None,
    ) -> List[protocol.SyncResponse]:
        """Standard diff per request against the updated trees."""
        from evolu_tpu.core.merkle import merkle_tree_from_string

        responses = []
        tree_strings = dict(tree_strings or {})
        for r in requests:
            if r.scope is not None:
                # Scoped request: the batch ingest above already landed
                # its rows in the FULL tree (scoping never touches
                # ingest); only the respond is answered from the
                # derived scoped subtree (server/scope.py).
                from evolu_tpu.server import scope as scope_mod

                responses.append(scope_mod.scoped_response(self.store, r))
                continue
            tree, ts = self._resolve_tree(r.user_id, trees, tree_strings)
            client_tree = merkle_tree_from_string(r.merkle_tree)
            messages = self.store.get_messages(r.user_id, r.node_id, tree, client_tree)
            responses.append(protocol.SyncResponse(messages, ts))
        return responses

    def reconcile_wire(
        self, requests: Sequence[protocol.SyncRequest]
    ) -> List[bytes]:
        """`reconcile` with BYTES-mode responses: each entry is the
        fully encoded SyncResponse, the messages stream emitted
        straight from C (`eh_get_messages_wire`) — for consumers that
        only forward protobuf (the HTTP/pod serve paths), where the
        per-message SyncResponse objects of `_respond` were pure
        retention cost (docs/BENCHMARKS.md r4: the divergent respond
        leg was ~196k msgs/s object-bound while the relay's identical
        C leg served 1.39M). Byte-identical to
        `encode_sync_response(reconcile(...)[i])` (test-pinned);
        per-request fallback to the object path + encoder where the C
        entry is missing or a stored row is non-canonical."""
        trees, strings = self._ingest(requests)
        return self._respond_wire(requests, trees, strings)

    def run_batch_wire(self, requests: Sequence[protocol.SyncRequest]) -> List[bytes]:
        """ONE engine/store pass for a live micro-batch → wire bytes per
        request (the scheduler's entry point). With a write-behind
        queue attached, the pass defers SQLite entirely
        (`_finish_batch_deferred`): serve from in-memory trees, ACK
        into the durable log, answer — a `WriteBehindFull` raised
        before the ACK leaves no state anywhere (the scheduler maps it
        to 503 + Retry-After). Otherwise packed-capable stores take
        `start_batch`/`finish_batch` (in-batch dedup in request order,
        optimistic device hash, atomic per-shard insert+tree commit);
        anything else routes through `reconcile_wire`, whose `_ingest`
        picks the store-appropriate batched path. Either way a failure
        rolls every shard transaction back before raising — the
        scheduler's singleton retry depends on that."""
        stores, _ = self._shards()
        if self.write_behind is not None and hasattr(
            self.store, "get_merkle_tree_string"
        ):
            return self._finish_batch_deferred(self.start_batch(requests))
        if all(
            hasattr(getattr(s, "db", None), "relay_insert_packed") for s in stores
        ):
            return self.finish_batch(self.start_batch(requests), wire=True)
        return self.reconcile_wire(requests)

    # -- write-behind serving (PR-11: device state is the truth) --

    def _finish_batch_deferred(self, st) -> List[bytes]:
        """Land batch k WITHOUT touching the btree: fold the device
        deltas onto the queue's authoritative per-owner trees
        (optimistically — every in-batch-deduped row XORs; rows that
        turn out to be already stored are corrected EXACTLY at drain
        time, see storage/write_behind.py), append the packed row
        buffers + tree strings to the durable log (the ACK point), and
        respond from the in-memory trees. Nothing is installed if the
        append raises (backpressure or log failure) — the serving
        state stays consistent for the retry."""
        from evolu_tpu.core.merkle import merkle_tree_from_string
        from evolu_tpu.storage.write_behind import IngestRecord

        wb = self.write_behind
        requests = st["requests"]
        live, shard_data = st["live"], st["shard_data"]
        trees: Dict[str, dict] = {}
        strings: Dict[str, str] = {}
        metrics.inc("evolu_engine_store_passes_total", path="write_behind")
        if not live:
            return self._respond_deferred(requests, trees, strings)
        with span("kernel:merkle", "reconcile_deferred",
                  owners=len({r.user_id for r in requests}),
                  n=st["n_total"], shards=len(live)):
            deltas_by_owner, _digest = deltas_finish(st["dev"])
            for o, deltas in deltas_by_owner.items():
                if not deltas:
                    continue
                cached = wb.serving_tree(o)
                if cached is not None:
                    base_tree = cached[0]
                else:
                    # Per-owner read: only the owner's SHARD lock — a
                    # sibling shard's drain keeps running underneath.
                    with wb.owner_lock(o):
                        raw = self.store.get_merkle_tree_string(o)
                    base_tree = merkle_tree_from_string(raw)
                tree = apply_prefix_xors(base_tree, deltas)
                trees[o] = tree
                strings[o] = merkle_tree_to_string(tree)
            records = []
            for si in live:
                gu, gc, ts_packed, content_packed, lens = shard_data[si]
                seen_o: set = set()
                tree_rows = []
                for o in gu:
                    if o in strings and o not in seen_o:
                        seen_o.add(o)
                        tree_rows.append((o, strings[o]))
                records.append(IngestRecord(
                    gu, gc, ts_packed, content_packed, lens, tree_rows
                ))
            wb.append_batch(
                records, {o: (trees[o], strings[o]) for o in strings}
            )
            # Ledger: append_batch counted wb.queued for every row that
            # entered the log (the ACK); rows the in-batch dedup
            # dropped never reach the queue and terminate HERE as
            # store.duplicate. The queued rows' inserted/duplicate
            # split is classified exactly at drain time, per shard
            # (write_behind._materialize_shard) — nothing is posted if the
            # append raised (backpressure = no state anywhere).
            kept: Dict[str, int] = {}
            for si in live:
                gu, gc, _tsp, _cp, _lens = shard_data[si]
                for u, k in zip(gu, gc):
                    kept[u] = kept.get(u, 0) + k
            totals: Dict[str, int] = {}
            for r in requests:
                if r.messages:
                    totals[r.user_id] = totals.get(r.user_id, 0) + len(r.messages)
            for o, total in totals.items():
                ledger.count(ledger.STORE_DUPLICATE, total - kept.get(o, 0),
                             owner=o)
        return self._respond_deferred(requests, trees, strings)

    def _resolve_tree_deferred(self, user_id: str, trees, tree_strings):
        """`_resolve_tree` against the write-behind truth: this batch's
        freshly folded tree, else the queue's serving cache (the owner
        has undrained history), else the stored string (SQLite is
        current for fully drained owners)."""
        from evolu_tpu.core.merkle import merkle_tree_from_string

        tree = trees.get(user_id)
        if tree is not None:
            return tree, tree_strings[user_id]
        cached = self.write_behind.serving_tree(user_id)
        if cached is not None:
            tree, raw = cached
        else:
            with self.write_behind.owner_lock(user_id):
                raw = self.store.get_merkle_tree_string(user_id)
            tree = merkle_tree_from_string(raw)
        trees[user_id] = tree
        tree_strings[user_id] = raw
        return tree, raw

    def _respond_deferred(self, requests, trees, strings) -> List[bytes]:
        """Bytes-mode respond for the deferred path. The hot shape —
        trees agree after the push — answers tree-only from memory
        (ZERO SQLite). A non-empty diff needs stored messages: wait on
        the owner's drain watermark, re-read the owner's EXACT
        committed tree, and run the SAME `fetch_response_stream`
        composition (the one byte-format-coupled copy, shared with
        `sync_wire` and `_respond_wire`) under the drain lock.

        The exact re-read matters beyond precision: a duplicate-
        carrying push folds an already-stored row's hash onto a base
        that contains it (XOR-cancel), so the OPTIMISTIC tree claims
        the row is missing. Serving that tree would make the client
        re-send the row every round — each redelivery re-cancelling it
        — a permanent retry livelock. Post-flush SQLite carries the
        drain-corrected truth, so the served tree converges instead
        (review finding, pinned by
        test_write_behind.py::test_duplicate_retry_response_tree_is_exact).
        Shards that cannot C-serve degrade to the batched object
        respond, also post-flush."""
        from evolu_tpu.core.merkle import diff_merkle_trees, merkle_tree_from_string
        from evolu_tpu.core.types import NonCanonicalStoreError
        from evolu_tpu.server.relay import fetch_response_stream

        wb = self.write_behind
        shards, shard_ix = self._shards()
        out: List[Optional[bytes]] = []
        fallback: List[Tuple[int, protocol.SyncRequest]] = []
        for i, r in enumerate(requests):
            if r.scope is not None:
                # A scoped respond reads stored rows + lanes: SQLite
                # must be current for this owner first, and the serve
                # runs under the drain lock against committed truth.
                from evolu_tpu.server import scope as scope_mod

                wb.flush_owner(r.user_id)
                with wb.owner_lock(r.user_id):
                    out.append(protocol.encode_sync_response(
                        scope_mod.scoped_response(self.store, r)))
                continue
            tree, raw = self._resolve_tree_deferred(r.user_id, trees, strings)
            client_tree = merkle_tree_from_string(r.merkle_tree)
            if diff_merkle_trees(tree, client_tree) is None:
                out.append(protocol._string(2, raw))
                continue
            # The response needs stored rows: SQLite must be current
            # for this owner first (the per-owner drain watermark —
            # ONLY the owner's shard; a backlogged sibling shard
            # cannot stall this serve), and from here on the EXACT
            # committed tree serves under the owner's shard lock.
            wb.flush_owner(r.user_id)
            with wb.owner_lock(r.user_id):
                raw = self.store.get_merkle_tree_string(r.user_id)
            tree = merkle_tree_from_string(raw)
            trees[r.user_id] = tree
            strings[r.user_id] = raw
            if diff_merkle_trees(tree, client_tree) is None:
                # The optimistic divergence was the duplicate-cancel
                # artifact; the committed trees actually agree.
                out.append(protocol._string(2, raw))
                continue
            db = getattr(shards[shard_ix(r.user_id)], "db", None)
            if db is None or not hasattr(db, "fetch_relay_messages_wire"):
                fallback.append((i, r))
                out.append(None)
                continue
            try:
                with wb.owner_lock(r.user_id):
                    stream = fetch_response_stream(
                        db, r.user_id, r.node_id, tree, client_tree
                    )
            except NonCanonicalStoreError:
                fallback.append((i, r))
                out.append(None)
                continue
            out.append(stream + protocol._string(2, raw))
        if fallback:
            # Mixed-owner object-path respond: the one deferred-mode
            # site that still needs the whole-store composite lock.
            with wb.db_lock:
                resps = self._respond([r for _i, r in fallback], trees, strings)
            for (i, _r), resp in zip(fallback, resps):
                out[i] = protocol.encode_sync_response(resp)
        return out

    def _respond_wire(
        self, requests, trees: Dict[str, dict],
        tree_strings: Optional[Dict[str, str]] = None,
    ) -> List[bytes]:
        """Bytes-mode twin of `_respond`. The response composition is
        `relay.fetch_response_stream` (ONE copy shared with
        `RelayStore.sync_wire`) plus the field-2 tree string — the SAME
        serialized tree `_respond` would carry, so encodings are
        byte-identical. Requests a shard cannot C-serve (python
        backend, malformed stored row) degrade to ONE batched
        object-path respond at their original positions."""
        from evolu_tpu.core.merkle import merkle_tree_from_string
        from evolu_tpu.core.types import NonCanonicalStoreError
        from evolu_tpu.server.relay import fetch_response_stream

        shards, shard_ix = self._shards()
        tree_strings = dict(tree_strings or {})
        out: List[Optional[bytes]] = []
        fallback: List[Tuple[int, protocol.SyncRequest]] = []
        for i, r in enumerate(requests):
            if r.scope is not None:
                # Scoped responds never ride the fused C stream —
                # per-row lane filtering can't; object path + encode
                # (server/scope.py), ingest already done by the batch.
                from evolu_tpu.server import scope as scope_mod

                out.append(protocol.encode_sync_response(
                    scope_mod.scoped_response(self.store, r)))
                continue
            tree, raw = self._resolve_tree(r.user_id, trees, tree_strings)
            # A generic store (no `.db` attribute at all) must degrade
            # to the object-respond fallback, not AttributeError.
            db = getattr(shards[shard_ix(r.user_id)], "db", None)
            if db is None or not hasattr(db, "fetch_relay_messages_wire"):
                fallback.append((i, r))
                out.append(None)
                continue
            client_tree = merkle_tree_from_string(r.merkle_tree)
            try:
                stream = fetch_response_stream(
                    db, r.user_id, r.node_id, tree, client_tree
                )
            except NonCanonicalStoreError:
                # A malformed stored width degrades this request to the
                # object path (generic SQL), like sync_wire.
                fallback.append((i, r))
                out.append(None)
                continue
            out.append(stream + protocol._string(2, raw))
        if fallback:
            resps = self._respond([r for _i, r in fallback], trees, tree_strings)
            for (i, _r), resp in zip(fallback, resps):
                out[i] = protocol.encode_sync_response(resp)
        return out


# -- pod-scale multi-process reconcile (VERDICT r3 #3) --
#
# The reference deploys one relay process (apps/server/src/index.ts:
# 224-248); the BASELINE "one pod pass" north star describes the same
# server at pod scale. `reconcile_pod` runs the WHOLE server across a
# jax.distributed cluster: storage is partitioned by a stable owner →
# process hash (an owner's history always lives on one process's
# shards), while the device Merkle leg is ONE SPMD dispatch over the
# GLOBAL mesh — every process participates, feeds only its addressable
# shards, and the XOR digest all-reduce makes the whole-batch digest
# visible pod-wide. Owners are only ever laid out on their OWNING
# process's addressable shards, so each process decodes exactly the
# deltas its stores need — no cross-process delta traffic (the DCN
# carries collectives, not rows).


def owner_process(user_id: str, nproc: int) -> int:
    """Stable owner → process assignment (crc32, like
    ShardedRelayStore.shard_index): storage ownership must survive
    across batches, so it cannot depend on per-batch load."""
    import zlib

    return zlib.crc32(user_id.encode("utf-8")) % nproc


@with_x64
def reconcile_pod(
    mesh: Mesh, store, requests: Sequence[protocol.SyncRequest],
    wire: bool = False,
) -> Tuple[List, int]:
    """One pod pass. Call on EVERY process of the cluster with
    identical `requests` (the ingest fabric broadcasts a batch; each
    process answers for the owners it stores). Returns (responses,
    device_digest): `responses` aligns with `requests`, None for
    requests owned by another process; the digest is the pod-wide XOR
    over every device-hashed row (pre-dedup — the device hashes
    optimistically like `reconcile_stream`), replicated to all
    processes by the all-reduce, so agreement across processes is an
    end-to-end integrity check of the global dispatch.

    With `wire=True`, owned requests get the BYTES-mode response
    (`_respond_wire`: the encoded SyncResponse with its messages stream
    straight from C) — the pod serve path only forwards protobuf, so
    the object layer is skipped; byte-identical to encoding the
    object-mode response (test-pinned).

    Storage semantics per owner are identical to the single-process
    `BatchReconciler.reconcile`: in-batch dedup in request order, PK
    dedup against the store via per-row was-new flags, owners with any
    duplicate row re-folded host-side from their new rows only, one
    atomic insert+tree transaction per storage shard. Single-process
    clusters degenerate to the plain engine semantics exactly (the
    parity test runs both)."""
    from evolu_tpu.core.merkle import minute_deltas_host

    nproc = jax.process_count()
    pid = jax.process_index()
    n_dev = mesh.devices.size

    # 0) In-batch dedup, request order (deterministic on all processes).
    seen: set = set()
    kept: Dict[str, List[protocol.EncryptedCrdtMessage]] = {}
    for r in requests:
        for m in r.messages:
            k = (m.timestamp, r.user_id)
            if k not in seen:
                seen.add(k)
                kept.setdefault(r.user_id, []).append(m)
    owners = list(kept)  # first-appearance order — identical everywhere

    # 1) One vectorized parse; owners with any non-canonical row take
    # the host fold on their owning process (device hash re-renders
    # canonical case — same quarantine rule as deltas_dispatch).
    flat_ts = [m.timestamp for o in owners for m in kept[o]]
    spans: Dict[str, slice] = {}
    pos = 0
    for o in owners:
        spans[o] = slice(pos, pos + len(kept[o]))
        pos += len(kept[o])
    if flat_ts:
        all_m, all_c, all_n, case_ok = parse_timestamp_strings(flat_ts, with_case=True)
    else:
        all_m = all_c = all_n = case_ok = np.zeros(0, np.int64)
    good = [o for o in owners if bool(case_ok[spans[o]].all())]
    good_set = set(good)
    host_only = [o for o in owners if o not in good_set]

    # 2) Global device layout: each owner lands on a shard of its
    # OWNING process (per-process LPT over that process's addressable
    # shard slots) — every process computes the full layout
    # deterministically, then feeds only its addressable slices.
    proc_of = {o: owner_process(o, nproc) for o in good}
    proc_shards: Dict[int, List[int]] = {}
    for i, d in enumerate(mesh.devices.flat):
        proc_shards.setdefault(d.process_index, []).append(i)
    shards_global: List[List[str]] = [[] for _ in range(n_dev)]
    for p, slots in proc_shards.items():
        mine = {o: len(kept[o]) for o in good if proc_of[o] == p}
        for j, owner_list in enumerate(assign_owners_to_shards(mine, len(slots))):
            shards_global[slots[j]] = owner_list
    shard_len = max((sum(len(kept[o]) for o in s) for s in shards_global), default=0)
    shard_size = bucket_size(max(shard_len, 1))
    total = n_dev * shard_size

    good_ix = {o: i for i, o in enumerate(good)}
    millis = np.zeros(total, np.int64)
    counter = np.zeros(total, np.int32)
    node = np.zeros(total, np.uint64)
    valid = np.zeros(total, bool)
    oix = np.zeros(total, np.int64)
    for si, shard in enumerate(shards_global):
        p0 = si * shard_size
        for o in shard:
            sl_src = spans[o]
            n = sl_src.stop - sl_src.start
            sl = slice(p0, p0 + n)
            millis[sl] = all_m[sl_src]
            counter[sl] = all_c[sl_src]
            node[sl] = all_n[sl_src]
            valid[sl] = True
            oix[sl] = good_ix[o]
            p0 += n

    # 3) ONE SPMD dispatch over the global mesh (uniform: `good` is
    # identical on every process, so either all dispatch or none do).
    digest = 0
    by_ix: Dict[int, Dict[str, int]] = {}
    if good:
        shd = sharding(mesh)
        args = [put_sharded(a, shd) for a in (millis, counter, node, valid, oix)]
        owner_sorted, minute_sorted, seg_end, seg_xor, valid_sorted, dev_digest = (
            to_host_many(*_compiled_merkle_kernel(mesh)(*args))
        )
        by_ix = decode_owner_minute_deltas(
            owner_sorted, minute_sorted, seg_end, seg_xor, valid_sorted
        )
        digest = int(dev_digest)

    # 4) Storage leg — my owners only. Inserts run one worker per
    # storage shard like `_ingest_packed` (the C calls drop the GIL);
    # tree math + upserts follow per shard inside the same atomic
    # transaction window.
    local = [o for o in good if proc_of[o] == pid]
    local += [o for o in host_only if owner_process(o, nproc) == pid]
    eng = BatchReconciler(store, mesh)  # storage/respond helpers only
    stores, shard_index = eng._shards()
    per_shard: Dict[int, List[str]] = {}
    for o in local:
        per_shard.setdefault(shard_index(o), []).append(o)
    live = sorted(per_shard)
    trees: Dict[str, dict] = {}
    tree_strings: Dict[str, str] = {}
    packed_capable = all(hasattr(stores[si].db, "relay_insert_packed") for si in live)

    def insert_shard(si: int):
        sh_owners = per_shard[si]
        gu, gc = sh_owners, [len(kept[o]) for o in sh_owners]
        if packed_capable:
            ts_list = [m.timestamp for o in sh_owners for m in kept[o]]
            contents = [m.content for o in sh_owners for m in kept[o]]
            ts_packed, content_packed, lens = _pack_rows(ts_list, contents)
            was_new = stores[si].db.relay_insert_packed(
                gu, gc, ts_packed, content_packed, lens
            )
        else:  # stdlib backend: per-row changes==1 flags
            was_new = np.array([
                stores[si].db.run(
                    'INSERT OR IGNORE INTO "message" '
                    '("timestamp", "userId", "content") VALUES (?, ?, ?)',
                    (m.timestamp, o, m.content),
                ) == 1
                for o in sh_owners
                for m in kept[o]
            ], bool)
        return si, gu, gc, was_new

    pod_ins: Dict[str, int] = {}
    with span("kernel:merkle", "reconcile_pod",
              owners=len(owners), local_owners=len(local),
              n=len(flat_ts), nproc=nproc):
        with eng._shard_transactions(stores, live):
            for si, gu, gc, was_new in eng._map_shards(
                insert_shard, live, len(stores)
            ):
                pos = 0
                for o, k in zip(gu, gc):
                    flags = was_new[pos : pos + k]
                    pos += k
                    pod_ins[o] = pod_ins.get(o, 0) + int(np.asarray(flags).sum())
                    if o in good_ix and bool(flags.all()):
                        deltas = by_ix.get(good_ix[o], {})
                    else:
                        # Duplicates or non-canonical: the exact host
                        # fold over this owner's NEW rows only.
                        deltas, _d = minute_deltas_host(
                            m.timestamp
                            for m, f in zip(kept[o], flags)
                            if bool(f)
                        )
                    if not deltas:
                        continue
                    tree = apply_prefix_xors(stores[si].get_merkle_tree(o), deltas)
                    trees[o] = tree
                    s = merkle_tree_to_string(tree)
                    tree_strings[o] = s
                    stores[si].db.run(
                        'INSERT OR REPLACE INTO "merkleTree" ("userId", "merkleTree") '
                        "VALUES (?, ?)",
                        (o, s),
                    )
    eng.close()
    # Ledger, per process: the broadcast batch ingresses HERE only for
    # rows this process stores (my owners); terminals classify from
    # the was-new flags, in-batch-dedup dropped rows fold into
    # store.duplicate via the request totals.
    local_requests = [
        r for r in requests if owner_process(r.user_id, nproc) == pid
    ]
    ledger.count(
        ledger.INGRESS_SYNC,
        sum(len(r.messages) for r in local_requests),
    )
    _ledger_count_pass(local_requests, pod_ins)

    # 5) Respond for MY requests (message-less cold-sync requests route
    # by the same stable owner hash).
    respond = eng._respond_wire if wire else eng._respond
    responses: List = []
    for r in requests:
        if owner_process(r.user_id, nproc) == pid:
            responses.append(respond([r], trees, tree_strings)[0])
        else:
            responses.append(None)
    return responses, digest
