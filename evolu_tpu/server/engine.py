"""Batch reconcile engine — many owners' sync rounds in one device pass.

The reference relay handles one user per HTTP request, inserting and
hashing message-by-message (apps/server/src/index.ts:148-159). This
engine takes a whole batch of SyncRequests (config 3: 1M messages
across 1k owners), and:

1. set-diffs incoming timestamps against storage in bulk SQL (the
   INSERT OR IGNORE dedup, batched via a temp-table join);
2. hashes every new timestamp and reduces per-(owner, minute) XOR
   deltas on device (`owner_minute_segments` over int32 owner/minute
   key pairs, sharded over the mesh — owners never split);
3. applies the deltas to each owner's sparse tree, persists, and
   answers each request with the standard diff response.

The relay is E2EE-blind, so this touches only timestamps and
ciphertext blobs — the LWW cell merge happens client-side.
"""

from __future__ import annotations

import functools
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P
from jax import shard_map

from evolu_tpu.core.merkle import apply_prefix_xors, merkle_tree_to_string
from evolu_tpu.ops import bucket_size, with_x64
from evolu_tpu.ops.encode import timestamp_hashes
from evolu_tpu.ops.host_parse import parse_timestamp_strings
from evolu_tpu.ops.merkle_ops import decode_owner_minute_deltas, owner_minute_segments
from evolu_tpu.parallel.mesh import OWNERS_AXIS, assign_owners_to_shards, create_mesh, sharding
from evolu_tpu.parallel.reconcile import xor_allreduce
from evolu_tpu.server.relay import RelayStore
from evolu_tpu.utils.log import log, span
from evolu_tpu.sync import protocol


def _merkle_shard_kernel(millis, counter, node, valid, owner_ix):
    """Per-shard (owner, minute) XOR deltas + allreduced batch digest
    (`owner_minute_segments` is shared with the client reconcile
    kernel, parallel/reconcile.py)."""
    hashes = jnp.where(valid, timestamp_hashes(millis, counter, node), jnp.uint32(0))
    out = owner_minute_segments(owner_ix, millis, hashes, valid)
    digest = xor_allreduce(jax.lax.reduce(hashes, jnp.uint32(0), jnp.bitwise_xor, (0,)))
    return (*out, digest)


@functools.lru_cache(maxsize=None)
def _compiled_merkle_kernel(mesh: Mesh):
    spec = P(OWNERS_AXIS)
    return jax.jit(
        shard_map(
            _merkle_shard_kernel,
            mesh=mesh,
            in_specs=(spec,) * 5,
            out_specs=(spec, spec, spec, spec, spec, P()),
            check_vma=False,
        )
    )


@with_x64
def owner_minute_deltas(
    mesh: Mesh, owner_rows: Dict[str, Sequence[str]]
) -> Tuple[Dict[str, Dict[str, int]], int]:
    """Device pass: {owner: [timestamp strings]} → per-owner
    {minute-key: xor delta} plus the global batch digest.

    The device hash re-renders the node hex lowercase; the reference
    hashes the parsed node verbatim (timestampToHash of the parsed
    Timestamp, index.ts:155). Owners whose rows carry non-canonical hex
    case are quarantined to the shared host fold (the per-row case flag
    rides out of the batch parse, costing nothing extra); the other
    owners in the batch stay on device — owners are independent."""
    with span("kernel:merkle", "owner_minute_deltas",
              owners=len(owner_rows),
              n=sum(len(v) for v in owner_rows.values())):
        return _owner_minute_deltas_timed(mesh, owner_rows)


def _owner_minute_deltas_host(
    owner_rows: Dict[str, Sequence[str]]
) -> Tuple[Dict[str, Dict[str, int]], int]:
    """Oracle-exact host fallback: the shared verbatim-case fold."""
    from evolu_tpu.core.merkle import minute_deltas_host

    deltas: Dict[str, Dict[str, int]] = {}
    digest = 0
    for o, rows in owner_rows.items():
        deltas[o], d = minute_deltas_host(rows)
        digest ^= d
    return deltas, digest


def _owner_minute_deltas_timed(mesh, owner_rows):
    owners = list(owner_rows)
    # ONE vectorized parse for every owner's timestamps (per-owner calls
    # would pay the numpy setup ~owners times); the per-row case flags
    # mark owners that must take the host fold.
    flat = [ts for o in owners for ts in owner_rows[o]]
    all_m, all_c, all_n, case_ok = parse_timestamp_strings(flat, with_case=True)
    bounds: Dict[str, slice] = {}
    host_owners: List[str] = []
    pos = 0
    for o in owners:
        k = len(owner_rows[o])
        bounds[o] = slice(pos, pos + k)
        if k and not case_ok[bounds[o]].all():
            host_owners.append(o)
        pos += k

    deltas: Dict[str, Dict[str, int]] = {o: {} for o in owners}
    digest = 0
    if host_owners:
        log("kernel:merkle", "non-canonical hex case: host hashing fallback",
            owners=len(host_owners))
        host_deltas, host_digest = _owner_minute_deltas_host(
            {o: owner_rows[o] for o in host_owners}
        )
        deltas.update(host_deltas)
        digest ^= host_digest

    quarantined = set(host_owners)
    good = [o for o in owners if o not in quarantined]
    if not any(len(owner_rows[o]) for o in good):
        return deltas, digest

    owner_ix = {o: i for i, o in enumerate(good)}
    shards = assign_owners_to_shards({o: len(owner_rows[o]) for o in good}, mesh.devices.size)
    shard_len = max((sum(len(owner_rows[o]) for o in s) for s in shards), default=0)
    shard_size = bucket_size(max(shard_len, 1))
    total = mesh.devices.size * shard_size

    millis = np.zeros(total, np.int64)
    counter = np.zeros(total, np.int32)
    node = np.zeros(total, np.uint64)
    valid = np.zeros(total, bool)
    oix = np.zeros(total, np.int64)
    pos_by_shard = [si * shard_size for si in range(len(shards))]
    shard_of_owner = {o: si for si, shard in enumerate(shards) for o in shard}
    for o in good:
        src = bounds[o]
        n = src.stop - src.start
        if not n:
            continue
        si = shard_of_owner[o]
        pos = pos_by_shard[si]
        sl = slice(pos, pos + n)
        millis[sl] = all_m[src]
        counter[sl] = all_c[src]
        node[sl] = all_n[src]
        valid[sl] = True
        oix[sl] = owner_ix[o]
        pos_by_shard[si] = pos + n

    shd = sharding(mesh)
    args = [jax.device_put(a, shd) for a in (millis, counter, node, valid, oix)]
    owner_sorted, minute_sorted, seg_end, seg_xor, valid_sorted, dev_digest = (
        _compiled_merkle_kernel(mesh)(*args)
    )

    by_ix = decode_owner_minute_deltas(owner_sorted, minute_sorted, seg_end, seg_xor, valid_sorted)
    for o_ix, d in by_ix.items():
        deltas[good[o_ix]] = d
    return deltas, digest ^ int(dev_digest)


class BatchReconciler:
    """Reconcile a batch of SyncRequests against one RelayStore."""

    def __init__(self, store: RelayStore, mesh: Optional[Mesh] = None):
        self.store = store
        self.mesh = mesh or create_mesh()

    def _new_messages(
        self, requests: Sequence[protocol.SyncRequest]
    ) -> Dict[str, List[protocol.EncryptedCrdtMessage]]:
        """Bulk dedup: which (timestamp, userId) pairs are not yet stored.
        Batch equivalent of per-row INSERT OR IGNORE changes==1
        (index.ts:153-158). Duplicates inside the batch dedup here too."""
        db = self.store.db
        seen: set = set()
        incoming: List[Tuple[str, str, protocol.EncryptedCrdtMessage]] = []
        for r in requests:
            for m in r.messages:
                k = (m.timestamp, r.user_id)
                if k not in seen:
                    seen.add(k)
                    incoming.append((m.timestamp, r.user_id, m))
        if not incoming:
            return {}
        with db.transaction():
            db.exec('CREATE TEMP TABLE IF NOT EXISTS "__incoming" ("t" TEXT, "u" TEXT)')
            db.run('DELETE FROM "__incoming"')
            db.run_many('INSERT INTO "__incoming" VALUES (?, ?)', [(t, u) for t, u, _ in incoming])
            rows = db.exec_sql_query(
                'SELECT i."t" AS t, i."u" AS u FROM "__incoming" i '
                'JOIN "message" m ON m."timestamp" = i."t" AND m."userId" = i."u"'
            )
            db.run('DELETE FROM "__incoming"')
        existing = {(r["t"], r["u"]) for r in rows}
        out: Dict[str, List[protocol.EncryptedCrdtMessage]] = {}
        for t, u, m in incoming:
            if (t, u) not in existing:
                out.setdefault(u, []).append(m)
        return out

    def reconcile(
        self, requests: Sequence[protocol.SyncRequest]
    ) -> List[protocol.SyncResponse]:
        """One batched pass; responses align with `requests` order.
        End state is identical to running `store.sync` per request."""
        new_by_owner = self._new_messages(requests)

        # Device: per-(owner, minute) XOR deltas for all new timestamps.
        deltas_by_owner, _digest = (
            owner_minute_deltas(self.mesh, {o: [m.timestamp for m in ms] for o, ms in new_by_owner.items()})
            if new_by_owner
            else ({}, 0)
        )

        # Host: bulk insert + tree updates in one transaction.
        db = self.store.db
        with db.transaction():
            rows = [
                (m.timestamp, o, m.content)
                for o, ms in new_by_owner.items()
                for m in ms
            ]
            if rows:
                db.run_many(
                    'INSERT OR IGNORE INTO "message" ("timestamp", "userId", "content") '
                    "VALUES (?, ?, ?)",
                    rows,
                )
            trees: Dict[str, dict] = {}
            for o, deltas in deltas_by_owner.items():
                tree = apply_prefix_xors(self.store.get_merkle_tree(o), deltas)
                trees[o] = tree
                db.run(
                    'INSERT OR REPLACE INTO "merkleTree" ("userId", "merkleTree") VALUES (?, ?)',
                    (o, merkle_tree_to_string(tree)),
                )

        # Responses: standard diff per request against the updated trees.
        from evolu_tpu.core.merkle import merkle_tree_from_string

        responses = []
        for r in requests:
            tree = trees.get(r.user_id)
            if tree is None:
                tree = self.store.get_merkle_tree(r.user_id)
                trees[r.user_id] = tree
            client_tree = merkle_tree_from_string(r.merkle_tree)
            messages = self.store.get_messages(r.user_id, r.node_id, tree, client_tree)
            responses.append(protocol.SyncResponse(messages, merkle_tree_to_string(tree)))
        return responses
