"""Owner-sharded relay fleet: placement ring, routing, rebalancing.

No reference equivalent — the reference relay (apps/server, 258 LoC)
is a single node. PRs 1-5 built every piece of a multi-relay tier
(metrics, batching scheduler, Merkle anti-entropy, snapshot
bootstrap), but the replication topology was still FULL: every relay
gossiped every owner to every peer, O(fleet) traffic and O(fleet)
storage per relay. This module composes the pieces into a fleet that
*partitions* owners across relays:

* **Placement ring** — a deterministic hash ring over owner ids with
  virtual nodes (`HashRing`): every relay holding the same
  `FleetConfig` (utils/config.py — relays, replication factor R,
  vnodes, seed) computes the same owner→[primary, replica, ...]
  placement with no coordination. Merkle-CRDTs (arXiv:2004.00107)
  makes per-owner tree summaries exactly the unit that keeps
  placement-scoped anti-entropy sound; replication-factor-bounded
  propagation is the standard escape from O(fleet) gossip
  (arXiv:2310.18220 §replication).

* **Request routing** — a sync POST landing on a non-placed relay is
  answered with `307 + Location: <authoritative relay>` (the client
  follows once and caches the owner→relay route, sync/client.py) or
  proxy-forwarded through `POST /fleet/forward` (`FleetConfig.
  forward=True`; the envelope's hop guard means a forwarded request is
  NEVER forwarded again — ring disagreement during a reload degrades
  to local service + gossip heal, not a cycle). A down primary fails
  over to the next ring replica, gated on a readiness probe
  (`GET /health`, cached briefly).

* **Scoped replication** — `ReplicationManager` with a fleet attached
  sends each peer only the owners placed on that peer (the summary
  carries our own URL so the peer scopes its answer the same way) and
  pulls only owners placed on itself: gossip drops from O(fleet) to
  O(R), and stray owners (written to the wrong relay mid-reload)
  drain to their placement instead of replicating everywhere.

* **Snapshot-driven rebalancing** — a ring change (join/leave via
  `POST /fleet/reload`, a static config push) makes the gaining relay
  bootstrap the moved owners from the losing relay's PR-5 snapshot:
  manifest → crc-checked chunks → owner-filtered install through the
  store's own changes==1 XOR gate → per-owner cutover at the Merkle
  watermark (the manifest's root-hash + tree-crc digests). An owner
  being installed answers 503 + Retry-After ("not ready") and only
  starts being served once its recomputed tree matches the watermark;
  writes ACKed by the loser after capture heal through scoped gossip
  (the loser keeps its copy and remains a summary source). Failure
  anywhere degrades to incremental anti-entropy — never data loss.

The relay stays E2EE-blind throughout; placement hashes opaque owner
ids. That blindness is also what makes the `aead-batch-v1` wire
(docs/WIRE_V2.md) fleet-safe with NO code here: negotiation binds a
(client, relay) pair per hop, relays never re-encrypt, and every fleet
surface — hop-guarded forwards, scoped peer pulls, snapshot chunks,
rebalance installs — carries stored ciphertext verbatim, so v1 and v2
records cross the fleet identically. The one hop that matters is
client→serving-relay: on a forward the SERVING relay computes the
capability echo (it decodes the forwarded body, `relay._do_fleet_
forward` → `_serve_request`), so a client talking through a
forwarding front-end negotiates with the relay that actually stores
its rows; on failover the client re-encodes v2 rounds as v1 itself
(sync/client.py::retarget — a relay that didn't advertise never
receives v2). Observability: the `evolu_fleet_*` families
(docs/OBSERVABILITY.md) + a `fleet` section under `GET /stats`; the
ingest wire-format mix shows up per serving relay as
`evolu_crypto_v{1,2}_relay_messages_total`.

`python -m evolu_tpu.server.fleet` runs one fleet relay process (the
unit `benchmarks/fleet_scaling.py` multiplies into N-process fleets).
"""

from __future__ import annotations

import bisect
import hashlib
import threading
import time
from typing import Dict, List, Optional, Sequence, Tuple

from evolu_tpu.obs import ledger, metrics
from evolu_tpu.sync import protocol
from evolu_tpu.utils.config import FleetConfig
from evolu_tpu.utils.log import log

# How long one readiness probe result is trusted. Short: failover
# freshness beats probe savings (a probe is one local-network GET);
# long enough that a burst of requests for one owner pays one probe.
PROBE_TTL_S = 1.0
# What a "busy" (owner mid-install / no ready replica) answer tells
# the client to wait before retrying — the same Retry-After contract
# as the scheduler's backpressure 503.
NOT_READY_RETRY_S = 0.25


def _h64(data: str, seed: int) -> int:
    """Stable 64-bit ring coordinate. blake2b, not crc32: placement
    quality is balance, and 32-bit crc collisions across vnode points
    are not rare at fleet scale. Seeded so disjoint fleets sharing a
    wire never agree on placement by accident."""
    return int.from_bytes(
        hashlib.blake2b(
            f"{seed}|{data}".encode("utf-8"), digest_size=8
        ).digest(),
        "big",
    )


class HashRing:
    """Consistent-hash placement: owner id → an ordered tuple of R
    distinct relay URLs (primary first). Pure function of the
    FleetConfig — every member computes identical placement, and a
    membership change moves only the owners whose arc changed
    (~moved_fraction ≈ joined/total, the consistent-hashing property
    the rebalance bench leans on)."""

    def __init__(self, config: FleetConfig):
        self.config = config
        relays: List[str] = []
        for u in config.relays:
            if u not in relays:  # dedupe, order-preserving
                relays.append(u)
        self.relays = tuple(relays)
        points: List[Tuple[int, str]] = []
        for url in self.relays:
            for v in range(max(1, config.virtual_nodes)):
                points.append((_h64(f"relay|{url}#{v}", config.seed), url))
        points.sort()
        self._points = [p for p, _u in points]
        self._urls = [u for _p, u in points]
        self._r = max(1, min(config.replication_factor, len(self.relays)))

    def placement(self, owner_id: str) -> Tuple[str, ...]:
        """The R distinct relays for `owner_id`, primary first —
        clockwise walk from the owner's ring coordinate."""
        if not self._points:
            return ()
        h = _h64(f"owner|{owner_id}", self.config.seed)
        i = bisect.bisect_right(self._points, h)
        out: List[str] = []
        n = len(self._points)
        for k in range(n):
            url = self._urls[(i + k) % n]
            if url not in out:
                out.append(url)
                if len(out) == self._r:
                    break
        return tuple(out)

    def primary(self, owner_id: str) -> str:
        return self.placement(owner_id)[0]


class FleetNotReady(Exception):
    """The owner is placed here but mid-install (or no placed relay is
    ready): the relay answers 503 + Retry-After — flow control, like
    the scheduler's backpressure, never an error count."""

    def __init__(self, retry_after: float = NOT_READY_RETRY_S):
        super().__init__(f"owner not ready; retry after {retry_after}s")
        self.retry_after = retry_after


class FleetManager:
    """One relay's view of the fleet: the ring, its own URL, the
    owner-readiness set, the rebalance machinery, and the health
    probe cache. Attach to a RelayServer with `enable_fleet` — the
    handler consults `route()` per sync POST; the ReplicationManager
    reads `placed_on()` to scope gossip."""

    def __init__(self, store, config: FleetConfig, self_url: str,
                 replication=None, http_post=None, http_get=None,
                 probe_ttl_s: float = PROBE_TTL_S, write_behind=None):
        import functools

        from evolu_tpu.sync.client import _http_post

        self.store = store
        self.self_url = self_url.rstrip("/")
        self.replication = replication
        # PR-11: the rebalance installer is a direct store writer; on a
        # write-behind relay each owner move runs behind the queue's
        # drain barrier (drained + drain-locked across EVERY shard
        # worker since PR-19 — coarse, but owner moves are operator
        # events, and the moved owners are FleetNotReady during the
        # install so no serving-path state races them). Backlog-driven readiness lives in the relay's
        # /health handler: a saturated backlog answers 503, so peer
        # failover and the rebalance readiness probe route around it.
        self.write_behind = write_behind
        self._post = http_post or functools.partial(_http_post, retries=0)
        self._get = http_get or _http_get_status
        self._probe_ttl_s = float(probe_ttl_s)
        self._lock = threading.RLock()
        self._installing: set = set()  # owners mid-rebalance (not served)
        self._probe_cache: Dict[str, Tuple[float, bool]] = {}
        self._rebalance_serial = threading.Lock()  # one rebalance at a time
        self._threads: List[threading.Thread] = []
        self._stopping = False
        self._manifest_owners: Optional[Tuple] = None  # last install's watermarks
        self.config: Optional[FleetConfig] = None
        self.ring: Optional[HashRing] = None
        self.apply_config(config, rebalance=False)

    # -- placement queries --

    def placement(self, owner_id: str) -> Tuple[str, ...]:
        return self.ring.placement(owner_id)

    def placed_on(self, owner_id: str, url: str) -> bool:
        return url.rstrip("/") in self.ring.placement(owner_id)

    def is_primary(self, owner_id: str) -> bool:
        return self.ring.primary(owner_id) == self.self_url

    # -- request routing --

    def route(self, owner_id: str) -> Tuple[str, Optional[str]]:
        """→ ("local", None) | ("redirect"|"forward", peer_url).
        Raises FleetNotReady for an owner placed here but mid-install
        (serve-after-cutover is the zero-lost-writes gate) or placed
        nowhere ready. Non-placed requests go to the first placed
        relay whose readiness probe passes — a down primary fails over
        to the next ring replica; if NO probe passes, the primary is
        still named (the client's own retry/backoff may outlive a
        probe-window blip)."""
        placement = self.ring.placement(owner_id)
        if self.self_url in placement:
            with self._lock:
                if owner_id in self._installing:
                    metrics.inc("evolu_fleet_not_ready_total")
                    raise FleetNotReady()
            return ("local", None)
        mode = "forward" if self.config.forward else "redirect"
        for url in placement:
            if self._peer_serving(url):
                if url != placement[0]:
                    metrics.inc("evolu_fleet_failovers_total")
                return (mode, url)
        if not placement:
            return ("local", None)
        if mode == "redirect":
            # Name the primary anyway: the CLIENT pays the retry, and
            # its own backoff may outlive a probe-window blip.
            return (mode, placement[0])
        # Forward mode would make THIS relay synchronously POST to a
        # known-down peer — each request would pin a handler thread
        # through the transport timeouts. Shed instead; the next
        # route() re-probes.
        metrics.inc("evolu_fleet_not_ready_total")
        raise FleetNotReady()

    def _peer_serving(self, url: str) -> bool:
        now = time.monotonic()
        with self._lock:
            hit = self._probe_cache.get(url)
            if hit is not None and hit[0] > now:
                return hit[1]
        try:
            serving = self._get(url + "/health") == 200
        except Exception:  # noqa: BLE001 - unreachable peer = not serving
            serving = False
        with self._lock:
            self._probe_cache[url] = (now + self._probe_ttl_s, serving)
        return serving

    # -- health / observability --

    def installing_owners(self) -> int:
        with self._lock:
            return len(self._installing)

    def health_payload(self) -> Tuple[bool, dict]:
        """→ (serving, detail). NOT serving while a PR-5 whole-store
        snapshot install is pending (its phase marker persists across
        crashes) or any owner is mid-rebalance — fleet failover and
        the bench must never route to a relay mid-install."""
        from evolu_tpu.server.snapshot import install_phase

        phase = install_phase(self.store)
        n_inst = self.installing_owners()
        serving = phase is None and n_inst == 0
        return serving, {
            "status": "serving" if serving else "installing",
            "install_phase": phase,
            "installing_owners": n_inst,
            "ring_version": self.config.version,
            "members": len(self.ring.relays),
        }

    def stats_payload(self) -> dict:
        owners = self.store.user_ids()
        placed = [u for u in owners if self.placed_on(u, self.self_url)]
        primary = [u for u in placed if self.is_primary(u)]
        metrics.set_gauge("evolu_fleet_owners", len(placed))
        metrics.set_gauge("evolu_fleet_primary_owners", len(primary))
        return {
            "self_url": self.self_url,
            "ring_version": self.config.version,
            "members": list(self.ring.relays),
            "replication_factor": self.ring._r,
            "owners_stored": len(owners),
            "owners_placed": len(placed),
            "owners_primary": len(primary),
            "installing_owners": self.installing_owners(),
            "redirects": metrics.get_counter("evolu_fleet_redirects_total"),
            "forwards": metrics.get_counter("evolu_fleet_forwards_total"),
            "forwarded_served": metrics.get_counter(
                "evolu_fleet_forwarded_served_total"
            ),
            "reloads": metrics.get_counter("evolu_fleet_reloads_total"),
            "rebalanced_owners": metrics.get_counter(
                "evolu_fleet_rebalanced_owners_total"
            ),
            "rebalanced_messages": metrics.get_counter(
                "evolu_fleet_rebalanced_messages_total"
            ),
            "cutovers_verified": metrics.get_counter(
                "evolu_fleet_cutover_verified_total"
            ),
            "cutovers_superset": metrics.get_counter(
                "evolu_fleet_cutover_superset_total"
            ),
            "failovers": metrics.get_counter("evolu_fleet_failovers_total"),
            "rebalance_failures": metrics.get_counter(
                "evolu_fleet_rebalance_failures_total"
            ),
        }

    # -- config reload + rebalance --

    def apply_config(self, config: FleetConfig, rebalance: bool = True) -> bool:
        """Install a new fleet config (the `/fleet/reload` body). A
        stale generation (version < current) raises ValueError — the
        caller answers 400, so a racing old push cannot roll the ring
        back. Re-pushing the CURRENT config is "reconcile": no ring
        change, but the rebalance sweep still runs (idempotent — one
        scoped summary per peer when nothing moved), which is how a
        joining relay pulls its owners once the REST of the fleet has
        reloaded (peers scope summaries by THEIR ring, so a sweep
        before they reload sees nothing). Returns True when a
        rebalance was started."""
        with self._lock:
            changed = True
            if self.config is not None:
                if config.version < self.config.version:
                    raise ValueError(
                        f"stale fleet config version {config.version} "
                        f"< current {self.config.version}"
                    )
                if config == self.config:
                    changed = False
                elif config.version == self.config.version:
                    # Two DIFFERENT configs at one version would
                    # split-brain the ring (members install whichever
                    # push landed last). Content changes require a
                    # strictly newer generation; same-version re-push
                    # of the identical config (reconcile) is the only
                    # equal-version accept.
                    raise ValueError(
                        f"conflicting fleet config at version "
                        f"{config.version}: content changes need a "
                        "strictly newer version"
                    )
                else:
                    metrics.inc("evolu_fleet_reloads_total")
            if changed:
                self.config = config
                self.ring = HashRing(config)
                self._probe_cache.clear()
                metrics.set_gauge("evolu_fleet_ring_version", config.version)
                metrics.set_gauge("evolu_fleet_members", len(self.ring.relays))
        # New members become gossip peers (add_peer is idempotent
        # under the manager's own lock and gossips new ones
        # immediately); departed members' scoped summaries go empty on
        # their own, so stale peers are harmless.
        if changed and self.replication is not None:
            for url in self.ring.relays:
                if url != self.self_url:
                    self.replication.add_peer(url)
        if not rebalance:
            return False
        t = threading.Thread(
            target=self._rebalance, name="evolu-fleet-rebalance", daemon=True
        )
        with self._lock:
            if self._stopping:
                return False
            self._threads = [x for x in self._threads if x.is_alive()]
            self._threads.append(t)
        t.start()
        return True

    def stop(self) -> None:
        with self._lock:
            self._stopping = True
            threads = list(self._threads)
        for t in threads:
            t.join(timeout=35.0)

    # -- snapshot-driven owner moves --

    def rebalance_once(self) -> int:
        """Run one synchronous rebalance sweep on the calling thread
        (the unit-test / bench / operator surface — `run_once`'s
        analog). Serialized with any background reload-triggered sweep
        — two concurrent sweeps would share `_manifest_owners` and
        could unmark each other's mid-install owners. Returns the
        number of owners installed."""
        with self._rebalance_serial:
            return self._sweep()

    def _rebalance(self) -> None:
        with self._rebalance_serial:  # serialize racing reloads
            try:
                self._sweep()
            except Exception as e:  # noqa: BLE001 - a failed rebalance
                # degrades to incremental anti-entropy, never a crash.
                metrics.inc("evolu_fleet_rebalance_failures_total")
                log("server", "fleet rebalance failed", error=repr(e))

    def _sweep(self) -> int:
        """For each peer: ask for the owners it stores that are placed
        on US (the scoped summary), and snapshot-install the ones we
        lack entirely. Owners we already store heal through normal
        scoped gossip — the snapshot path is for whole-owner moves."""
        moved_total = 0
        for peer_url in list(self.ring.relays):
            if peer_url == self.self_url or self._stopping:
                continue
            try:
                moved_total += self._pull_moved_owners(peer_url)
            except Exception as e:  # noqa: BLE001 - per-peer isolation:
                # one unreachable loser must not block gains from the
                # others; its owners stay with it until it comes back.
                metrics.inc("evolu_fleet_rebalance_failures_total")
                log("server", "fleet rebalance peer failed",
                    peer=peer_url, error=repr(e))
        if self.replication is not None and moved_total:
            # Post-capture donor writes heal at debounce latency.
            self.replication.hint()
        return moved_total

    def _pull_moved_owners(self, peer_url: str) -> int:
        # 1. What does the peer store that belongs to me? An EMPTY
        # summary with our URL: the peer's scoped answer enumerates
        # exactly the owners placed on us — no full-store enumeration.
        body = protocol.encode_replica_summary(
            protocol.ReplicaSummary((), self._replica_id(), self.self_url)
        )
        resp = protocol.decode_replica_summary(
            self._post(peer_url + "/replicate/summary", body)
        )
        local = set(self.store.user_ids())
        gained = sorted(
            uid for uid, _tree in resp.trees
            if uid not in local and self.placed_on(uid, self.self_url)
        )
        if not gained:
            return 0
        with self._lock:
            if self._stopping:
                return 0
            self._installing.update(gained)
        t0 = time.perf_counter()
        try:
            from contextlib import nullcontext

            barrier = (
                self.write_behind.drain_barrier()
                if self.write_behind is not None else nullcontext()
            )
            with barrier:
                installed_msgs, shipped_trees = self._install_from_snapshot(
                    peer_url, set(gained)
                )
        except BaseException:
            # Nothing (or a prefix) landed — all of it through the
            # idempotent XOR gate, so partial installs are safe state.
            # Unmark: route() serves what we have; scoped gossip pulls
            # the rest incrementally.
            with self._lock:
                self._installing.difference_update(gained)
            raise
        # 2. Cutover at the per-owner Merkle watermark: an owner only
        # starts being served once its recomputed tree is byte-equal
        # to the donor's capture-time watermark. A concurrent gossip
        # ingest can only ADD rows (INSERT OR IGNORE), so a mismatch
        # here means a SUPERSET of the snapshot — safe to serve, but
        # counted separately (the bench asserts clean cutovers).
        by_owner = {uid: (root, crc) for uid, root, crc in
                    self._manifest_owners or []}
        import zlib as _z

        for uid in gained:
            shipped = shipped_trees.get(uid, "")
            now_tree = self.store.get_merkle_tree_string(uid)
            root_crc = by_owner.get(uid)
            exact = (
                shipped and now_tree == shipped and root_crc is not None
                and _z.crc32(shipped.encode("utf-8")) == root_crc[1]
            )
            metrics.inc(
                "evolu_fleet_cutover_verified_total" if exact
                else "evolu_fleet_cutover_superset_total"
            )
            with self._lock:
                self._installing.discard(uid)
        metrics.inc("evolu_fleet_rebalanced_owners_total", len(gained))
        metrics.inc("evolu_fleet_rebalanced_messages_total", installed_msgs)
        metrics.observe(
            "evolu_fleet_rebalance_ms", (time.perf_counter() - t0) * 1e3
        )
        log("server", "fleet rebalance installed owners", peer=peer_url,
            owners=len(gained), messages=installed_msgs)
        return len(gained)

    def _install_from_snapshot(self, peer_url: str, wanted: set):
        """Owner-scoped manifest → chunk fetches → owner-filtered
        ingest through `store.add_messages` (the changes==1 XOR gate —
        trees stay exact digests of the installed rows, and
        re-installs are idempotent). The request names the moved
        owners so the donor ships O(moved owners), not its whole
        store; the record filter below still applies — a pre-fleet
        donor ignores the owner field and ships everything. →
        (message_count, {owner: shipped tree text})."""
        from evolu_tpu.server import snapshot as snap

        manifest = protocol.decode_snapshot_manifest(
            self._post(
                peer_url + "/replicate/snapshot",
                protocol.encode_snapshot_request(
                    protocol.SnapshotRequest(
                        self._replica_id(), 0, tuple(sorted(wanted))
                    )
                ),
            )
        )
        self._manifest_owners = manifest.owners
        shipped_trees: Dict[str, str] = {}
        installed = 0
        for i in range(len(manifest.chunk_sizes)):
            if self._stopping:
                raise RuntimeError("fleet manager stopping mid-rebalance")
            raw = self._post(
                peer_url + "/replicate/snapshot/chunk",
                protocol.encode_snapshot_chunk_request(
                    protocol.SnapshotChunkRequest(
                        manifest.snapshot_id, i, self._replica_id()
                    )
                ),
            )
            chunk = protocol.decode_snapshot_chunk(raw)
            if (chunk.snapshot_id != manifest.snapshot_id
                    or chunk.index != i
                    or len(chunk.payload) != manifest.chunk_sizes[i]
                    or chunk.crc != manifest.chunk_crcs[i]):
                raise snap.SnapshotInstallError(
                    f"fleet rebalance chunk {i}: response does not match "
                    "the manifest (id/index/size/crc)"
                )
            by_owner: Dict[str, List[protocol.EncryptedCrdtMessage]] = {}
            for rec in snap.iter_records(chunk.payload):
                if rec[0] == "M" and rec[2] in wanted:
                    by_owner.setdefault(rec[2], []).append(
                        protocol.EncryptedCrdtMessage(rec[1], rec[3])
                    )
                elif rec[0] == "T" and rec[1] in wanted:
                    shipped_trees[rec[1]] = rec[2]
            for uid, msgs in by_owner.items():
                self.store.add_messages(uid, msgs)
                # Ledger ingress: rebalance-installed rows arrive as
                # snapshot chunks; add_messages above posted their
                # store terminals through its changes==1 gate.
                ledger.count(ledger.INGRESS_SNAPSHOT, len(msgs), owner=uid)
                installed += len(msgs)
        return installed, shipped_trees

    def _replica_id(self) -> str:
        if self.replication is not None:
            return self.replication.replica_id
        return f"fleet:{self.self_url}"


def _http_get_status(url: str, timeout: float = 2.0) -> int:
    """One readiness probe GET → the HTTP status (an ANSWERED non-200
    — e.g. 503 mid-install — is 'not serving', not 'unreachable')."""
    import urllib.error
    import urllib.request

    try:
        with urllib.request.urlopen(url, timeout=timeout) as resp:
            return resp.status
    except urllib.error.HTTPError as e:
        return e.code


# -- one fleet relay process (the benchmarks/fleet_scaling.py unit) --


def _worker_main(argv: Optional[Sequence[str]] = None) -> None:
    """Run ONE fleet relay as its own process: store + RelayServer +
    scoped replication + FleetManager. The bench spawns N of these —
    plain subprocesses like MultiprocessRelay's workers (no fork of
    jax/tunnel state, no multiprocessing-spawn re-import of
    __main__)."""
    import argparse
    import json
    import signal

    from evolu_tpu.server.relay import RelayServer, RelayStore

    ap = argparse.ArgumentParser(description="one evolu fleet relay process")
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int, required=True)
    ap.add_argument("--path", default=":memory:")
    ap.add_argument("--self-url", required=True)
    ap.add_argument("--config-json", required=True,
                    help="FleetConfig.to_json() of the shared fleet config")
    ap.add_argument("--backend", default="auto")
    ap.add_argument("--replication-interval-s", type=float, default=1.0)
    ap.add_argument("--batching", action="store_true")
    args = ap.parse_args(argv)

    cfg = FleetConfig.from_json(json.loads(args.config_json))
    store = RelayStore(args.path, args.backend)
    peers = [u for u in cfg.relays if u != args.self_url.rstrip("/")]
    server = RelayServer(
        store, host=args.host, port=args.port, batching=args.batching,
        peers=peers, replication_interval_s=args.replication_interval_s,
    )
    # Fleet BEFORE start(): the replication loop's first round fires
    # immediately on start, and it must already be placement-scoped —
    # an unscoped first round against a big donor would pull owners
    # this member is not placed for.
    server.enable_fleet(cfg, self_url=args.self_url)
    server.start()
    stop = threading.Event()
    signal.signal(signal.SIGTERM, lambda *_a: stop.set())
    print("READY", flush=True)  # the parent waits for listen()
    try:
        stop.wait()
    except KeyboardInterrupt:
        pass
    server.stop()


if __name__ == "__main__":  # pragma: no cover - subprocess entry
    _worker_main()
